"""Subscriber-lifecycle storm suite — the traffic shapes that break
real BNGs at the ISP edge.

The scripted scenarios (chaos/scenarios.py) prove recovery from FAULTS:
kills, corruption, skew. This module proves graceful degradation under
LOAD SHAPES — the storms that take down production BNGs with no fault
injected at all:

    flash_crowd_reconnect   an access-network outage heals and >=100k
                            subscribers re-DORA at once; admission must
                            shed DHCP-correctly (DISCOVERs first, never
                            a REQUEST whose OFFER was sent) while the
                            fleet autoscaler grows under the load
    lease_expiry_avalanche  a mass bring-up scheduled a synchronized
                            lease cliff; the bounded expiry sweep must
                            amortize the reap over ticks (service
                            continues mid-cliff) and the lease-time
                            jitter must prevent the next cliff
    cgnat_port_exhaustion   EIM churn drives the CGNAT allocator to
                            block and port exhaustion; every refused
                            verdict is COUNTED (never silent), the
                            block accounting stays exact, and expiry
                            makes the blocks reusable
    coa_policy_flap         RADIUS CoA bursts rewrite QoS device rows
                            mid-traffic; after the flap the host and
                            device QoS mirrors must agree bit-exact on
                            every config word
    dual_stack_bringup      interleaved DORA + SOLICIT/REQUEST + RS/RA
                            per subscriber; the v4 and v6 lease books
                            must both agree with their pool bitmaps
    production_day          one compressed production day on a single
                            engine: diurnal IPoE/PPPoE/dual-stack/CGNAT
                            churn with CoA waves, intercept taps armed
                            mid-storm, an ISP uplink flap re-steered as
                            bounded route deltas, and a spoofed-source
                            DDoS burst the antispoof stage counts; the
                            edge audit closes the day

The Jepsen split (PAPERS.md): the GENERATORS here are dumb — they build
frames (loadtest.harness.StormFrameFactory) and retry like clients do.
All the intelligence lives in the CHECKERS: a cross-authority
invariant-audit epilogue (chaos/invariants.py — extended with v6/PPPoE
lease-vs-pool and NAT block-accounting checks for this suite) and a
per-stage telemetry budget that FAILS the scenario when the
stage_breakdown blows past its envelope (Dapper's lesson: the
unbudgeted stage is where the regression hides).

Determinism: everything runs on SimClock logical time and seeded
schedules; reports carry no wallclock, so `bng chaos run --seed S` is
byte-identical across runs — storms included. The telemetry budget is
the one wall-clock observer: only its BOOLEAN verdict (and the names of
breached stages) lands in the report, and the envelopes are sized one
to two orders above the observed means (PERF_NOTES §10) so a passing
run cannot flap.

Every scenario takes `(seed, scale=1.0)`: scale=1.0 is the published
storm (flash crowd at 100k subscribers); `make verify-storm` and the
tier-1 tests run reduced scales of the SAME code.
"""

from __future__ import annotations

from bng_tpu.chaos.faults import FaultPlan, FaultSpec, SimClock, SKEW, armed
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import (SERVER_IP, SERVER_MAC, _mac, _reply,
                                     _build_server_stack)
from bng_tpu.control import dhcp_codec
from bng_tpu.loadtest.harness import StormFrameFactory
from bng_tpu.telemetry import spans as tele
from bng_tpu.utils.net import ip_to_u32


# ---------------------------------------------------------------------------
# the stage budget: re-homed onto the SLO engine (telemetry/slo.py) so
# storm budgets and production SLOs share one vocabulary and one
# evaluator. Re-exported here because storms ARE the budget's main
# author; verdict semantics are byte-identical to the PR-8 originals
# (the verify-chaos bit-determinism gate pins that).
# ---------------------------------------------------------------------------

from bng_tpu.telemetry.slo import BudgetLine, check_budget  # noqa: E402,F401


class _traced:
    """Arm a fresh Tracer for the scenario body, disarm on exit. Storm
    scenarios run standalone (bng chaos run) — a leaked tracer would
    poison the next scenario's budget."""

    def __enter__(self):
        self.prev = tele.tracer()
        return tele.arm(tele.Tracer())

    def __exit__(self, *exc):
        tele.disarm()
        if self.prev is not None:
            tele.arm(self.prev)


# ---------------------------------------------------------------------------
# shared builders
# ---------------------------------------------------------------------------

def _build_storm_fleet(workers: int, clock, *, prefix_len: int,
                       sub_nbuckets: int, slice_size: int,
                       inbox: int, fallback=None):
    """Inline fleet on a pool big enough for the storm's subscriber
    count (the scenarios.build_fleet geometry tops out at a /20)."""
    from bng_tpu.control.admission import AdmissionConfig
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.runtime.tables import FastPathTables

    fastpath = FastPathTables(sub_nbuckets=sub_nbuckets, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=prefix_len, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    spec = FleetSpec.from_pool_manager(
        SERVER_MAC, SERVER_IP, pools, slice_size=slice_size,
        low_watermark=max(1, slice_size // 4))
    fleet = SlowPathFleet(spec, workers, pools, mode="inline",
                          table_sink=fastpath, clock=clock,
                          admission=AdmissionConfig(inbox_capacity=inbox),
                          fallback=fallback)
    return fleet, pools, fastpath


# ---------------------------------------------------------------------------
# 1. flash-crowd mass-reconnect
# ---------------------------------------------------------------------------

def flash_crowd_reconnect(seed: int, scale: float = 1.0) -> dict:
    """An outage heals and every subscriber re-DORAs at once. The
    admission controller must shed the overload DHCP-correctly: only
    DISCOVERs shed (clients retransmit those by design), never a
    REQUEST whose OFFER was sent, and never a half-allocation. The
    fleet autoscaler grows on the shed signal, and after the surge a
    calm round proves admission recovered to steady state."""
    n_subs = max(1_000, int(round(100_000 * scale)))
    workers = 4
    chunk = max(512, n_subs // 6)
    inbox = max(32, chunk // (8 * workers))
    rounds_max = 5

    with _traced() as tracer:
        clock = SimClock()
        fleet, pools, fastpath = _build_storm_fleet(
            workers, clock, prefix_len=15, sub_nbuckets=1 << 15,
            slice_size=max(256, inbox * 4), inbox=inbox)

        from bng_tpu.control.opsctl import AutoscaleConfig, FleetAutoscaler

        # watermark autoscaler on the shed signal alone: busy_hi is
        # unreachable and busy_lo impossible, so every decision is a
        # deterministic function of the (seeded) shed counters — the
        # wall-clock busy fraction can never flip a report bit
        scaler = FleetAutoscaler(fleet, AutoscaleConfig(
            min_workers=workers, max_workers=workers + 2,
            busy_hi=1e18, busy_lo=-1.0, cooldown_s=0.0), clock=clock)
        scaler.target(clock())  # baseline look

        fac = StormFrameFactory(SERVER_IP)
        base = (seed % 89) * 1_000_000
        macs = [_mac(base + i) for i in range(n_subs)]
        offers: dict[bytes, int] = {}
        leased: dict[bytes, int] = {}
        req_after_offer_shed = 0
        xid = 1
        rounds = []
        for rnd in range(rounds_max):
            pend = [m for m in macs if m not in leased]
            if not pend:
                break
            shed_before = fleet.admission.shed_total()
            for ci in range(0, len(pend), chunk):
                batch, batch_macs = [], []
                for k, m in enumerate(pend[ci:ci + chunk]):
                    if m in offers:
                        batch.append((k, fac.request(m, offers[m], xid + k)))
                    else:
                        batch.append((k, fac.discover(m, xid + k)))
                    batch_macs.append(m)
                xid += len(batch)
                out = fleet.handle_batch(batch, now=clock())
                for (_lane, rep), m in zip(out, batch_macs):
                    if rep is None:
                        if m in offers:
                            # the invariant this storm exists to prove:
                            # an OFFERed client's REQUEST never sheds
                            req_after_offer_shed += 1
                        continue
                    p = _reply(rep)
                    if p.msg_type == dhcp_codec.OFFER:
                        offers[m] = p.yiaddr
                    elif p.msg_type == dhcp_codec.ACK:
                        leased[m] = p.yiaddr
                        offers.pop(m, None)
                    elif p.msg_type == dhcp_codec.NAK:
                        offers.pop(m, None)
            clock.advance(5.0)
            target = scaler.target(clock())
            if target is not None and target != fleet.n:
                fleet.resize(target)
            rounds.append({
                "round": rnd,
                "pending": len(pend),
                "leased": len(leased),
                "offers_open": len(offers),
                "shed_delta": fleet.admission.shed_total() - shed_before,
                "workers": fleet.n,
            })

        # the surge is over: a calm round must shed NOTHING and every
        # renewal must ACK — admission recovered to steady state
        calm = sorted(leased)[:min(256, len(leased))]
        shed_before = fleet.admission.shed_total()
        out = fleet.handle_batch(
            [(k, fac.renew(m, leased[m], 0x70000 + k))
             for k, m in enumerate(calm)], now=clock.advance(30.0))
        calm_acks = sum(
            1 for (_l, rep), m in zip(out, calm)
            if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
            and _reply(rep).yiaddr == leased[m])
        calm_shed = fleet.admission.shed_total() - shed_before

        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath)
        budget = check_budget(tracer, (
            # per-frame envelopes (per=chunk amortizes the batch laps);
            # observed means are ~2-15us/frame isolated but 60-110us
            # late in a full tier-1 process (heap/GC pressure), and the
            # admit mean covers only a couple of laps — the envelope
            # must sit an order above the WORST healthy observation or
            # one GC pause flakes the bit-determinism gate
            BudgetLine("admit", limit_us=500.0, per=chunk),
            BudgetLine("fleet", limit_us=2_000.0, per=chunk),
            # per-frame worker handler latency (its histogram is
            # already per-frame): observed ~40-90us
            BudgetLine("worker", limit_us=5_000.0),
        ))

    out_rep = {
        "name": "flash_crowd_reconnect", "seed": seed,
        "subscribers": n_subs,
        "rounds": rounds,
        "leased": len(leased),
        "unique_ips": len(set(leased.values())),
        "req_after_offer_shed": req_after_offer_shed,
        "shed": dict(sorted(fleet.admission.stats.shed.items())),
        "workers_final": fleet.n,
        "calm_acks": calm_acks,
        "calm_expected": len(calm),
        "calm_shed": calm_shed,
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
        "budget": budget,
    }
    out_rep["ok"] = (
        req_after_offer_shed == 0
        and out_rep["unique_ips"] == out_rep["leased"]
        and out_rep["leased"] > 0
        and sum(out_rep["shed"].values()) > 0  # the storm actually shed
        and out_rep["workers_final"] > workers  # autoscaler grew
        and calm_acks == len(calm) and calm_shed == 0
        and audit.ok and budget["ok"])
    return out_rep


# ---------------------------------------------------------------------------
# 2. lease-expiry avalanche
# ---------------------------------------------------------------------------

def lease_expiry_avalanche(seed: int, scale: float = 1.0) -> dict:
    """A jitterless mass bring-up schedules one synchronized lease
    cliff. The bounded sweep (cleanup_expired max_reaps) must amortize
    the cliff across ticks — with service continuing between sweeps —
    under dhcp.expire clock skew in both directions; then the same
    bring-up WITH lease-time jitter proves the next cliff never forms.
    A NAT session cliff rides the same clock through nat.expire."""
    n = max(400, int(round(20_000 * scale)))
    reap_budget = max(64, n // 8)

    with _traced() as tracer:
        clock = SimClock()
        # the shared /20 stack (scenarios._build_server_stack) tops out
        # around 4k subscribers; the avalanche needs room for n
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.nat import NATManager
        from bng_tpu.control.pool import Pool, PoolManager
        from bng_tpu.runtime.tables import FastPathTables

        fastpath = FastPathTables(sub_nbuckets=1 << 15, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(SERVER_MAC, SERVER_IP)
        pools = PoolManager(fastpath)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=15, gateway=SERVER_IP,
                            dns_primary=ip_to_u32("1.1.1.1"),
                            lease_time=600))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         ports_per_subscriber=64,
                         sessions_nbuckets=256, sub_nat_nbuckets=256)
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            fastpath_tables=fastpath, clock=clock)
        fac = StormFrameFactory(SERVER_IP)
        base = (seed % 83) * 1_000_000
        macs = [_mac(base + i) for i in range(n)]

        def dora_all(ms, xbase):
            for i, m in enumerate(ms):
                off = server.handle_frame(fac.discover(m, xbase + i))
                ip = _reply(off).yiaddr
                server.handle_frame(fac.request(m, ip, xbase + n + i))

        t0 = tele.t()
        dora_all(macs, 0x1000)
        tele.lap(tele.SLOW, t0)
        out = {"name": "lease_expiry_avalanche", "seed": seed,
               "subscribers": n, "reap_budget": reap_budget}
        exps = {l.expiry for l in server.leases.values()}
        out["cliff_expiries"] = len(exps)  # jitterless: ONE cliff

        # backward skew first: the cliff is in the future AND the clock
        # stepped back — nothing may expire
        with armed(FaultPlan(seed, [
                FaultSpec("dhcp.expire", SKEW, at_hit=1, arg=-7200.0)]),
                log=False):
            out["reaped_backward_skew"] = server.cleanup_expired(
                int(clock()), max_reaps=reap_budget)

        # past the cliff: every lease is expired at once. Sweep with the
        # budget; between sweeps a FRESH subscriber must still be served
        # (the tick the bounded reap protects)
        clock.advance(600.0 + 1200.0)
        sweeps = []
        mid_service_ok = 0
        guard = 0
        # the mid-cliff fresh DORAs below add UNexpired leases, so the
        # loop ends on reap progress, not on an empty book
        while sum(sweeps) < n and guard < (n // reap_budget) + 4:
            guard += 1
            t0 = tele.t()
            reaped = server.cleanup_expired(int(clock()),
                                            max_reaps=reap_budget)
            tele.lap(tele.OPS, t0)  # the sweep IS an ops stall
            sweeps.append(reaped)
            fresh = _mac(base + 500_000 + guard)
            off = server.handle_frame(fac.discover(fresh, 0x90000 + guard))
            ack = (server.handle_frame(fac.request(
                fresh, _reply(off).yiaddr, 0x91000 + guard))
                if off is not None else None)
            if ack is not None and _reply(ack).msg_type == dhcp_codec.ACK:
                mid_service_ok += 1
            clock.advance(1.0)
        out["sweeps"] = sweeps
        out["mid_cliff_doras"] = mid_service_ok
        audit_mid = audit_invariants(pools=pools, dhcp=server,
                                     fastpath=fastpath,
                                     check_roundtrip=False)
        out["audit_after_cliff_ok"] = audit_mid.ok

        # jittered re-bring-up: the SAME generator cannot form a cliff
        from bng_tpu.utils.net import mac_to_u64

        server.lease_jitter_frac = 0.5
        jmacs = macs[: max(200, n // 4)]
        dora_all(jmacs, 0x200000)
        jexps = {server.leases[mac_to_u64(m)].expiry for m in jmacs
                 if mac_to_u64(m) in server.leases}
        out["jitter_expiries"] = len(jexps)
        out["jitter_buckets_min"] = server.LEASE_JITTER_BUCKETS // 2

        # NAT cliff under nat.expire skew, same discipline
        from bng_tpu.ops.parse import PROTO_UDP

        subs = [ip_to_u32("10.1.0.10") + i for i in range(32)]
        for s in subs:
            nat.allocate_nat(s, int(clock()))
            nat.handle_new_flow(s, ip_to_u32("1.1.1.1"), 5000, 53,
                                PROTO_UDP, 64, int(clock()))
        with armed(FaultPlan(seed, [
                FaultSpec("nat.expire", SKEW, at_hit=1, arg=-7200.0)]),
                log=False):
            out["nat_expired_backward"] = nat.expire_sessions(int(clock()))
        with armed(FaultPlan(seed, [
                FaultSpec("nat.expire", SKEW, at_hit=1, arg=7200.0)]),
                log=False):
            out["nat_expired_forward"] = nat.expire_sessions(int(clock()))

        audit = audit_invariants(pools=pools, dhcp=server,
                                 fastpath=fastpath, nat=nat,
                                 check_roundtrip=(scale <= 0.2))
        budget = check_budget(tracer, (
            # per-reap teardown envelope: observed ~20-60us/reap on CPU
            BudgetLine("ops", limit_us=2_000.0, per=reap_budget),
            # DORA generator laps amortized per subscriber (~100-250us
            # observed through the full slow path)
            BudgetLine("slow_path", limit_us=10_000.0, per=n),
        ))

    out["audit_ok"] = audit.ok
    out["violations"] = audit.violations_by_kind()
    out["budget"] = budget
    out["ok"] = (
        out["cliff_expiries"] == 1
        and out["reaped_backward_skew"] == 0
        and all(s <= reap_budget for s in sweeps)
        and len(sweeps) >= (n + reap_budget - 1) // reap_budget
        and sum(sweeps) == n
        and mid_service_ok == len(sweeps)  # service survived the cliff
        and out["audit_after_cliff_ok"]
        and out["jitter_expiries"] >= out["jitter_buckets_min"]
        and out["nat_expired_backward"] == 0
        and out["nat_expired_forward"] == len(subs)
        and audit.ok and budget["ok"])
    return out


# ---------------------------------------------------------------------------
# 3. CGNAT port-block exhaustion
# ---------------------------------------------------------------------------

def cgnat_port_exhaustion(seed: int, scale: float = 1.0) -> dict:
    """EIM churn until the CGNAT allocator exhausts: first the port
    space inside each subscriber's block, then the block space itself.
    Every refusal is a COUNTED degraded verdict (nat.exhausted +
    rate-limited ErrorLog — never silent), the block accounting stays
    exact (the auditor's nat-block-accounting check proves exhaustion
    is real, not a leak), and expiry + release make the blocks
    reusable."""
    from bng_tpu.control.nat import NATManager
    from bng_tpu.ops.parse import PROTO_UDP

    span = 64
    blocks_per_ip = 8
    n_subs = 20  # 16 get blocks, 4 are refused
    churn_rounds = max(1, int(round(2 * scale)))

    with _traced() as tracer:
        clock = SimClock()
        nat = NATManager(
            public_ips=[ip_to_u32("203.0.113.1"), ip_to_u32("203.0.113.2")],
            ports_per_subscriber=span,
            port_range=(1024, 1024 + span * blocks_per_ip - 1),
            sessions_nbuckets=1 << 11, sub_nat_nbuckets=256)
        subs = [ip_to_u32("10.9.0.10") + i for i in range(n_subs)]
        out = {"name": "cgnat_port_exhaustion", "seed": seed,
               "churn_rounds": churn_rounds}

        t0 = tele.t()
        granted = [s for s in subs if nat.allocate_nat(s, int(clock()))]
        refused_block = [s for s in subs if s not in granted]
        out["blocks_granted"] = len(granted)
        out["blocks_refused"] = len(refused_block)
        out["counted_block"] = int(nat.exhausted["block"])

        # port churn: each granted subscriber opens more distinct
        # endpoints than its block holds — EIM reuse keeps shared
        # endpoints cheap, the overflow must be refused AND counted
        flows_ok = flows_refused = 0
        dst = ip_to_u32("93.184.216.34")
        for s in granted:
            for p in range(span + 16):
                got = nat.handle_new_flow(s, dst, 2000 + p, 80,
                                          PROTO_UDP, 64, int(clock()))
                if got is None:
                    flows_refused += 1
                else:
                    flows_ok += 1
        out["flows_ok"] = flows_ok
        out["flows_refused"] = flows_refused
        out["counted_port"] = int(nat.exhausted["port"])
        tele.lap(tele.OPS, t0)
        audit_full = audit_invariants(nat=nat, check_roundtrip=False)
        out["audit_exhausted_ok"] = audit_full.ok

        # heal: expire the sessions, release a few blocks, and the
        # previously refused subscribers must now be served
        reuse_ok = 0
        for _ in range(churn_rounds):
            clock.advance(7200.0)
            nat.expire_sessions(int(clock()))
            for s in granted[:len(refused_block)]:
                nat.release_nat(s, int(clock()))
            for s in refused_block:
                if nat.allocate_nat(s, int(clock())) is not None:
                    reuse_ok += 1
            # swap roles for the next round so release/alloc churns
            granted, refused_block = (
                refused_block + granted[len(refused_block):],
                granted[:len(refused_block)])
        out["reused_after_release"] = reuse_ok

        audit = audit_invariants(nat=nat, check_roundtrip=False)
        budget = check_budget(tracer, (
            # whole churn phase (one lap): ~1300 flow punts, observed
            # low single-digit ms total on CPU
            BudgetLine("ops", limit_us=5_000_000.0),
        ))

    out["audit_ok"] = audit.ok
    out["violations"] = audit.violations_by_kind()
    out["budget"] = budget
    expect_granted = 2 * blocks_per_ip
    out["ok"] = (
        out["blocks_granted"] == expect_granted
        and out["blocks_refused"] == n_subs - expect_granted
        and out["counted_block"] == out["blocks_refused"]
        and out["flows_ok"] == expect_granted * span
        and out["flows_refused"] == expect_granted * 16
        and out["counted_port"] == out["flows_refused"]
        and out["audit_exhausted_ok"]
        and out["reused_after_release"]
        == churn_rounds * (n_subs - expect_granted)
        and audit.ok and budget["ok"])
    return out


# ---------------------------------------------------------------------------
# 4. CoA policy-flap storm
# ---------------------------------------------------------------------------

def coa_policy_flap(seed: int, scale: float = 1.0) -> dict:
    """RADIUS CoA bursts rewrite QoS device rows while renewals ride
    the device fast path. The flap storm interleaves authenticated
    CoA-Requests (policy flip via Filter-Id), NAK'd lookups for unknown
    sessions, bad-authenticator drops and a Disconnect teardown with
    live engine batches — then proves the host and device QoS mirrors
    agree bit-exact on every config word (the new qos-mirror audit)."""
    from bng_tpu.control.radius import packet as rp
    from bng_tpu.control.radius.coa import CoAProcessor, CoAServer
    from bng_tpu.control.radius.packet import RadiusPacket
    from bng_tpu.control.radius.policy import PolicyManager, QoSPolicy
    from bng_tpu.runtime.engine import Engine, QoSTables
    from bng_tpu.utils.net import u32_to_ip

    n_subs = 12
    flap_rounds = max(4, int(round(24 * scale)))
    secret = b"storm-secret"

    # warm-up runs UNtraced: the first engine.process pays the jit
    # compile, and a budget that averaged a compile into the dispatch
    # stage would measure XLA, not the storm
    clock = SimClock()
    server, pools, fastpath, nat = _build_server_stack(clock)
    qos = QoSTables()
    policies = PolicyManager([
        QoSPolicy("gold", download_bps=400_000_000,
                  upload_bps=200_000_000),
        QoSPolicy("bronze", download_bps=50_000_000,
                  upload_bps=10_000_000),
    ])

    def qos_hook(ip, policy_name):
        p = policies.get(policy_name or "bronze")
        if p is not None:
            qos.set_subscriber(ip, p.download_bps, p.upload_bps)
        return True

    server.qos_hook = qos_hook
    # geometry matches engine_swap_crash_rollback so a suite run
    # compiles the fused pipeline exactly once
    eng = Engine(fastpath, nat, qos=qos, batch_size=32,
                 slow_path=server.handle_frame, clock=clock)
    fac = StormFrameFactory(SERVER_IP)
    base = (seed % 71) * 1_000_000
    macs = [_mac(base + i) for i in range(n_subs)]
    leased: dict[bytes, int] = {}
    for i, m in enumerate(macs):
        res = eng.process([fac.discover(m, 0x800 + i)])
        off = (res["slow"] or res["tx"])[0][1]
        ip = _reply(off).yiaddr
        eng.process([fac.request(m, ip, 0x900 + i)])
        leased[m] = ip

    def find_by_ip(ip):
        for mk, lease in server.leases.items():
            if lease.ip == ip:
                return lease
        return None

    def disconnect(lease):
        # the cli's CoA teardown idiom: force-expire so the client
        # re-DORAs, and drop the QoS rows both sides
        lease.expiry = 0
        server.cleanup_expired(1)
        qos.remove_subscriber(lease.ip)
        return True

    proc = CoAProcessor(find_by_ip=find_by_ip, qos_update=qos_hook,
                        disconnect=disconnect,
                        policy_manager=policies)
    coa = CoAServer(secret, proc)

    def coa_raw(code, ip, policy=None, bad_secret=False):
        req = RadiusPacket(code, (ip + code) & 0xFF)
        req.add(rp.FRAMED_IP_ADDRESS, ip)
        if policy is not None:
            req.add(rp.FILTER_ID, policy)
        return req.encode(b"wrong" if bad_secret else secret)

    with _traced() as tracer:
        # the flap storm: every round flips a deterministic subset's
        # policy between gold and bronze, mid-traffic
        renew_ok = 0
        renew_total = 0
        unknown_ip = ip_to_u32("172.31.0.1")
        for rnd in range(flap_rounds):
            policy = ("gold", "bronze")[rnd % 2]
            for i, m in enumerate(macs):
                if (i + rnd) % 3 == 0:
                    coa.handle_raw(coa_raw(rp.COA_REQUEST, leased[m],
                                           policy))
            # interleaved renewals must stay on the device fast path
            batch = [(fac.renew(m, leased[m], 0xA000 + rnd * 64 + i))
                     for i, m in enumerate(macs)]
            res = eng.process(batch, now=clock.advance(30.0))
            renew_total += len(batch)
            renew_ok += sum(
                1 for _l, f in res["tx"]
                if f is not None
                and _reply(f).msg_type == dhcp_codec.ACK)
            # storm noise: unknown session -> NAK; bad auth -> dropped
            coa.handle_raw(coa_raw(rp.COA_REQUEST, unknown_ip, "gold"))
            coa.handle_raw(coa_raw(rp.COA_REQUEST, leased[macs[0]],
                                   "gold", bad_secret=True))

        out = {"name": "coa_policy_flap", "seed": seed,
               "flap_rounds": flap_rounds,
               "coa_ack": proc.stats["coa_ack"],
               "coa_nak": proc.stats["coa_nak"],
               "bad_auth": coa.stats["bad_auth"],
               "renew_ok": renew_ok, "renew_total": renew_total}

        # disconnect storm tail: tear one session down over CoA
        victim = macs[-1]
        coa.handle_raw(coa_raw(rp.DISCONNECT_REQUEST, leased[victim]))
        out["disc_ack"] = proc.stats["disc_ack"]
        out["victim_gone"] = find_by_ip(leased[victim]) is None

        # the LAST flap that touched macs[0] decides its policy — the
        # host QoS row must hold exactly that round's rate
        from bng_tpu.ops.qtable import QW_RATE_HI, QW_RATE_LO

        last_flip = max(r for r in range(flap_rounds) if r % 3 == 0)
        expect_policy = "gold" if last_flip % 2 == 0 else "bronze"
        probe_ip = leased[macs[0]]
        slot = qos.up._find(probe_ip)
        rate = (int(qos.up.rows[slot][QW_RATE_LO])
                | (int(qos.up.rows[slot][QW_RATE_HI]) << 32))
        out["probe_rate_matches"] = (
            rate == policies.get(expect_policy).upload_bps)
        out["probe_ip"] = u32_to_ip(probe_ip)

        audit = audit_invariants(engine=eng, pools=pools, dhcp=server,
                                 nat=nat)
        budget = check_budget(tracer, (
            # warm-path envelopes (~0.5-10ms observed per stage on CPU)
            BudgetLine("dispatch", limit_us=500_000.0),
            BudgetLine("device_wait", limit_us=2_000_000.0),
            BudgetLine("reply", limit_us=200_000.0),
            BudgetLine("total", limit_us=5_000_000.0),
        ))

    out["audit_ok"] = audit.ok
    out["violations"] = audit.violations_by_kind()
    out["budget"] = budget
    expected_acks = sum(
        sum(1 for i in range(n_subs) if (i + rnd) % 3 == 0)
        for rnd in range(flap_rounds))
    out["ok"] = (
        out["coa_ack"] == expected_acks
        and out["coa_nak"] == flap_rounds  # one unknown-session NAK/round
        and out["bad_auth"] == flap_rounds
        and renew_ok == renew_total  # flaps never knocked renewals off
        and out["disc_ack"] == 1 and out["victim_gone"]
        and out["probe_rate_matches"]
        and audit.ok and budget["ok"])
    return out


# ---------------------------------------------------------------------------
# 5. dual-stack bring-up storm
# ---------------------------------------------------------------------------

def _solicit6(mac: bytes, xid: int, duid: bytes) -> bytes:
    from bng_tpu.control.dhcpv6 import protocol as p6
    from bng_tpu.control.dhcpv6.protocol import DHCPv6Message, IANA, IAPD
    from bng_tpu.control.packets import udp6_packet
    from bng_tpu.control.slaac import link_local

    m = DHCPv6Message(p6.SOLICIT, xid & 0xFFFFFF)
    m.add(p6.OPT_CLIENTID, duid)
    m.add_ia_na(IANA(1))
    m.add_ia_pd(IAPD(1))
    return udp6_packet(mac, bytes.fromhex("333300010002"), link_local(mac),
                       bytes.fromhex("ff02000000000000"
                                     "0000000000010002"),
                       546, 547, m.encode())


def _request6(mac: bytes, xid: int, duid: bytes, server_duid: bytes,
              adv) -> bytes:
    from bng_tpu.control.dhcpv6 import protocol as p6
    from bng_tpu.control.dhcpv6.protocol import DHCPv6Message, IANA, IAPD
    from bng_tpu.control.packets import udp6_packet
    from bng_tpu.control.slaac import link_local

    m = DHCPv6Message(p6.REQUEST, xid & 0xFFFFFF)
    m.add(p6.OPT_CLIENTID, duid)
    m.add(p6.OPT_SERVERID, server_duid)
    m.add_ia_na(IANA(1))
    m.add_ia_pd(IAPD(1))
    return udp6_packet(mac, bytes.fromhex("333300010002"), link_local(mac),
                       bytes.fromhex("ff02000000000000"
                                     "0000000000010002"),
                       546, 547, m.encode())


def _rs_frame(mac: bytes) -> bytes:
    import struct as _s

    from bng_tpu.control.slaac import link_local

    icmp = _s.pack(">BBHI", 133, 0, 0, 0)
    ip6 = _s.pack(">IHBB", 0x60000000, len(icmp), 58, 255) \
        + link_local(mac) \
        + bytes.fromhex("ff020000000000000000000000000002")
    return bytes.fromhex("333300000002") + mac + b"\x86\xdd" + ip6 + icmp


def dual_stack_bringup(seed: int, scale: float = 1.0) -> dict:
    """Every subscriber brings up v4 and v6 at once: DORA through the
    fleet, SOLICIT/REQUEST (IA_NA + IA_PD) and RS/RA through the
    parent demux fallback, interleaved in the same batches — the
    mixed-protocol slow queue a real dual-stack BNG sees after an
    access-node reboot. The checker proves BOTH books agree with their
    pool bitmaps (v4 cross-authority audit + the new v6 lease-vs-pool
    audit) and every subscriber ends fully dual-stacked."""
    from bng_tpu.control.dhcpv6 import protocol as p6
    from bng_tpu.control.dhcpv6.protocol import (DHCPv6Message,
                                                 generate_duid_ll)
    from bng_tpu.control.dhcpv6.server import (AddressPool6, DHCPv6Server,
                                               DHCPv6ServerConfig,
                                               PrefixPool6)
    from bng_tpu.control.slaac import (PrefixConfig, SLAACConfig,
                                       SLAACServer)
    from bng_tpu.control.slowpath import SlowPathDemux

    n_subs = max(250, int(round(4_000 * scale)))
    workers = 3
    chunk = 512

    with _traced() as tracer:
        clock = SimClock()
        v6 = DHCPv6Server(
            DHCPv6ServerConfig(server_mac=SERVER_MAC, rapid_commit=False),
            address_pool=AddressPool6("2001:db8:100::/64"),
            prefix_pool=PrefixPool6("2001:db8:f000::/40",
                                    delegated_len=56),
            clock=clock)
        slaac = SLAACServer(SLAACConfig(
            server_mac=SERVER_MAC,
            prefixes=[PrefixConfig(
                prefix=bytes.fromhex("20010db8010000000000000000000000"))],
            managed=True))
        demux = SlowPathDemux(dhcpv6=v6, slaac=slaac, clock=clock)
        fleet, pools, fastpath = _build_storm_fleet(
            workers, clock, prefix_len=18, sub_nbuckets=1 << 13,
            slice_size=512, inbox=1 << 16, fallback=demux)

        fac = StormFrameFactory(SERVER_IP)
        server_duid = v6.duid.encode()
        base = (seed % 67) * 1_000_000
        macs = [_mac(base + i) for i in range(n_subs)]
        duids = {m: generate_duid_ll(m).encode() for m in macs}
        leased4: dict[bytes, int] = {}
        leased6_na: dict[bytes, bytes] = {}
        leased6_pd: dict[bytes, bytes] = {}
        ra_seen = 0
        xid = 1
        for ci in range(0, n_subs, chunk):
            cm = macs[ci:ci + chunk]
            # wave 1: DISCOVER + SOLICIT + RS interleaved per subscriber
            batch = []
            for m in cm:
                batch.append((len(batch), fac.discover(m, xid)))
                batch.append((len(batch), _solicit6(m, xid + 1, duids[m])))
                batch.append((len(batch), _rs_frame(m)))
                xid += 2
            out1 = fleet.handle_batch(batch, now=clock())
            offers: dict[bytes, int] = {}
            for (lane, rep) in out1:
                if rep is None:
                    continue
                m = cm[lane // 3]
                kind = lane % 3
                if kind == 0:
                    offers[m] = _reply(rep).yiaddr
                elif kind == 1:
                    adv = DHCPv6Message.decode(rep[62:])
                    assert adv.msg_type == p6.ADVERTISE
                elif kind == 2:
                    ra_seen += 1
            # wave 2: REQUEST (v4) + REQUEST (v6) interleaved
            batch = []
            for m in cm:
                batch.append((len(batch), fac.request(m, offers[m], xid)))
                batch.append((len(batch), _request6(m, xid + 1, duids[m],
                                                    server_duid, None)))
                xid += 2
            out2 = fleet.handle_batch(batch, now=clock())
            for (lane, rep) in out2:
                if rep is None:
                    continue
                m = cm[lane // 2]
                if lane % 2 == 0:
                    p = _reply(rep)
                    if p.msg_type == dhcp_codec.ACK:
                        leased4[m] = p.yiaddr
                else:
                    rep6 = DHCPv6Message.decode(rep[62:])
                    ias = rep6.ia_nas()
                    if ias and ias[0].addresses:
                        leased6_na[m] = ias[0].addresses[0].address
                    pds = rep6.ia_pds()
                    if pds and pds[0].prefixes:
                        leased6_pd[m] = pds[0].prefixes[0].prefix
            clock.advance(1.0)

        # cross-book checks: the same subscriber set, fully dual-stacked
        dual = sum(1 for m in macs
                   if m in leased4 and m in leased6_na and m in leased6_pd)
        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath, dhcpv6=v6,
                                 check_roundtrip=(scale <= 0.2))
        budget = check_budget(tracer, (
            # 500us/frame: the flash-crowd rationale — the dual-stack
            # admit mean covers TWO laps, so one full-suite GC pause
            # inside either lap flakes a tighter envelope
            BudgetLine("admit", limit_us=500.0, per=chunk),
            BudgetLine("fleet", limit_us=5_000.0, per=chunk),
            BudgetLine("worker", limit_us=5_000.0),
        ))

    pool = pools.pools[1]
    out_rep = {
        "name": "dual_stack_bringup", "seed": seed,
        "subscribers": n_subs,
        "leased_v4": len(leased4),
        "leased_v6_na": len(leased6_na),
        "leased_v6_pd": len(leased6_pd),
        "dual_stacked": dual,
        "ra_seen": ra_seen,
        "rs_answered": slaac.stats.rs_received,
        "v4_pool_fleet_owned": sum(
            1 for owner in pool._allocated.values()
            if owner.startswith("fleet:")),
        "v6_allocated_na": len(v6.addr_pool._allocated),
        "v6_allocated_pd": len(v6.prefix_pool._allocated),
        "demux": dict(sorted(demux.stats.items())),
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
        "budget": budget,
    }
    out_rep["ok"] = (
        dual == n_subs
        and len(leased4) == n_subs
        and ra_seen == n_subs and slaac.stats.rs_received == n_subs
        # the v6 books agree with the v6 pool bitmaps EXACTLY
        and out_rep["v6_allocated_na"] == n_subs
        and out_rep["v6_allocated_pd"] == n_subs
        and audit.ok and budget["ok"])
    return out_rep


# ---------------------------------------------------------------------------
# 6. cluster-scale storm: 4M+ subscribers across a cluster of BNGs
# ---------------------------------------------------------------------------

def cluster_scale_storm(seed: int, scale: float = 1.0) -> dict:
    """4M+ subscribers steered across a 4-instance cluster
    (bng_tpu/cluster). The full population is steered VECTORIZED
    (`steer_macs_u48` — one numpy pass over every MAC) and pinned
    bit-exact against the scalar `instance_for_mac` on a seeded sample;
    a sampled per-instance DORA wave then runs FULL FRAMES through the
    cluster front door, each wave under its own tracer so every
    instance gets its OWN SLO verdict (one overloaded member cannot
    hide behind the cluster mean). Mid-storm one member dies: the
    standby promotes and the victim's whole wave renews sticky. The
    `_audit_cluster` epilogue proves no IP is owned by two instances
    and every lease sits inside its owner's carve."""
    import random

    import numpy as np

    from bng_tpu.cluster import ClusterCoordinator, instance_for_mac
    from bng_tpu.cluster.plan import steer_macs_u48

    n_members = 4
    n_steered = max(40_000, int(round(4_200_000 * scale)))
    per_inst = max(250, int(round(6_000 * scale)))
    chunk = max(256, per_inst // 4)

    clock = SimClock()
    # a /9 space carves into 4 x /11 blocks: 8.4M addresses, so the 4M+
    # steered population fits the plan with room for growth blocks
    coord = ClusterCoordinator(
        clock=clock, space_network=ip_to_u32("10.0.0.0"),
        space_prefix_len=9, nat_base=ip_to_u32("100.64.0.0"),
        nat_total=1 << 14, sub_nbuckets=1 << 13, slice_size=256,
        inbox_capacity=1 << 15)
    coord.add_instances(["bng-%02d" % i for i in range(n_members)])
    ids = coord.member_ids()

    # ---- steer the WHOLE population in one vectorized pass ----------
    base = (seed % 89) * 8_000_000
    mac_u48 = ((np.uint64(0x02C5) << np.uint64(32)) + np.uint64(base)
               + np.arange(n_steered, dtype=np.uint64))
    steer = steer_macs_u48(mac_u48, len(ids))
    counts = np.bincount(steer, minlength=len(ids))
    steered = {ids[k]: int(counts[k]) for k in range(len(ids))}
    rng = random.Random(seed)
    sample = rng.sample(range(n_steered), min(512, n_steered))
    steer_identity = all(
        ids[int(steer[j])] == instance_for_mac(
            int(mac_u48[j]).to_bytes(6, "big"), ids)
        for j in sample)
    # FNV-1a32 over a contiguous MAC range lands near-uniform; a
    # member starving below 80% of its fair share means the steering
    # family regressed
    fair = n_steered / len(ids)
    spread_ok = all(int(c) >= int(0.8 * fair) for c in counts)

    # ---- sampled per-instance full-frame DORA waves -----------------
    fac = StormFrameFactory(SERVER_IP)
    waves: dict[str, list] = {}
    leases: dict[str, dict] = {}
    slo: dict[str, dict] = {}
    for k, iid in enumerate(ids):
        idx = np.flatnonzero(steer == k)[:per_inst]
        wave = [int(mac_u48[j]).to_bytes(6, "big") for j in idx]
        waves[iid] = wave
        got: dict[bytes, int] = {}
        xid = 1
        with _traced() as tracer:
            for ci in range(0, len(wave), chunk):
                cmacs = wave[ci:ci + chunk]
                out = coord.handle_batch(
                    [(i, fac.discover(m, xid + i))
                     for i, m in enumerate(cmacs)], now=clock())
                offers: dict[bytes, int] = {}
                for (_l, rep), m in zip(out, cmacs):
                    if rep is not None:
                        p = _reply(rep)
                        if p.msg_type == dhcp_codec.OFFER:
                            offers[m] = p.yiaddr
                req_macs = [m for m in cmacs if m in offers]
                out = coord.handle_batch(
                    [(i, fac.request(m, offers[m], 0x100000 + xid + i))
                     for i, m in enumerate(req_macs)], now=clock())
                for (_l, rep), m in zip(out, req_macs):
                    if rep is not None:
                        p = _reply(rep)
                        if p.msg_type == dhcp_codec.ACK:
                            got[m] = p.yiaddr
                xid += len(cmacs)
                clock.advance(1.0)
            # each instance gets its OWN verdict — envelopes match
            # flash_crowd_reconnect (same stages, same per-frame cost)
            slo[iid] = check_budget(tracer, (
                BudgetLine("admit", limit_us=500.0, per=chunk),
                BudgetLine("fleet", limit_us=2_000.0, per=chunk),
                BudgetLine("worker", limit_us=5_000.0),
            ))
        leases[iid] = got

    # carve containment, end to end: every ACKed address must sit in
    # the plan blocks of the instance that served it
    carve_ok = all(
        coord.plan.owner_of(ip) == iid
        for iid, got in leases.items() for ip in got.values())
    all_ips = [ip for got in leases.values() for ip in got.values()]
    unique_ok = len(all_ips) == len(set(all_ips))

    # ---- storm-scale failover: kill a member mid-service ------------
    victim = ids[seed % len(ids)]
    coord.kill_instance(victim)
    ticks = 0
    while coord.members[victim].role != "promoted" and ticks < 64:
        clock.advance(1.0)
        coord.tick()
        ticks += 1
    promoted = coord.members[victim].role == "promoted"

    # the victim's WHOLE wave renews through the promoted standby and
    # must come back with the addresses the dead active handed out
    vwave = [m for m in waves[victim] if m in leases[victim]]
    sticky = 0
    for ci in range(0, len(vwave), chunk):
        cmacs = vwave[ci:ci + chunk]
        out = coord.handle_batch(
            [(i, fac.renew(m, leases[victim][m], 0x200000 + ci + i))
             for i, m in enumerate(cmacs)], now=clock())
        sticky += sum(
            1 for (_l, rep), m in zip(out, cmacs)
            if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
            and _reply(rep).yiaddr == leases[victim][m])

    audit = audit_invariants(bng_cluster=coord)
    out_rep = {
        "name": "cluster_scale_storm", "seed": seed,
        "instances": len(ids),
        "subscribers": n_steered,
        "plan_addresses": coord.plan.total_addresses(),
        "steered": steered,
        "steer_identity": steer_identity,
        "spread_ok": spread_ok,
        "wave_per_instance": per_inst,
        "leased": {iid: len(got) for iid, got in sorted(leases.items())},
        "unique_ips": len(set(all_ips)),
        "carve_ok": carve_ok,
        "slo": {iid: slo[iid] for iid in sorted(slo)},
        "victim": victim,
        "promoted": promoted,
        "failovers": coord.failovers,
        "sticky_acks": sticky,
        "sticky_expected": len(vwave),
        "shed_frames": coord.shed_frames,
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
    }
    coord.close()
    out_rep["ok"] = (
        len(ids) >= 4
        and out_rep["plan_addresses"] >= n_steered
        and steer_identity and spread_ok
        and all(len(leases[i]) == len(waves[i]) for i in ids)
        and unique_ok and carve_ok
        and all(v["ok"] for v in slo.values())
        and promoted and coord.failovers == 1
        and sticky == len(vwave) and sticky > 0
        and audit.ok)
    return out_rep


# ---------------------------------------------------------------------------
# 7. production day: the composite edge-protection storm
# ---------------------------------------------------------------------------

def production_day(seed: int, scale: float = 1.0) -> dict:
    """One compressed production day on a single engine proves the edge
    subsystem under composite churn. Morning: IPoE DORA plus dual-stack
    SOLICIT/REQUEST and PPPoE discovery share one slow queue while every
    lease carves a CGNAT block and binds a next-hop route (ECMP by
    subscriber class). Midday: CoA policy waves rewrite QoS rows with
    renewals riding the device path. Afternoon: two intercept warrants
    arm mid-storm — matching flows mirror to RecordCC, non-matching
    flows are filtered ON DEVICE. Evening: an ISP uplink dies and the
    route table re-steers as bounded dirty-slot deltas (never a
    resync); a spoofed-source DDoS burst is dropped and counted by the
    antispoof stage. Night: the short warrant expires, the bounded reap
    removes its tap rows, and the edge audit plus per-stage SLO budget
    close the day."""
    from bng_tpu.control import packets
    from bng_tpu.control.dhcpv6.server import (AddressPool6, DHCPv6Server,
                                               DHCPv6ServerConfig,
                                               PrefixPool6)
    from bng_tpu.control.intercept import InterceptManager, Warrant
    from bng_tpu.control.pppoe import codec as pcodec
    from bng_tpu.control.pppoe.auth import LocalVerifier
    from bng_tpu.control.pppoe.server import PPPoEServer, PPPoEServerConfig
    from bng_tpu.control.radius import packet as rp
    from bng_tpu.control.radius.coa import CoAProcessor, CoAServer
    from bng_tpu.control.radius.packet import RadiusPacket
    from bng_tpu.control.radius.policy import PolicyManager, QoSPolicy
    from bng_tpu.control.routing import (RoutingManager, StubPlatform,
                                         Upstream)
    from bng_tpu.control.slaac import PrefixConfig, SLAACConfig, SLAACServer
    from bng_tpu.control.slowpath import SlowPathDemux
    from bng_tpu.edge import (EdgeTables, InterceptTapProgram, MirrorPump,
                              RouteProgram)
    from bng_tpu.edge.ops import EST_ROUTE_REWRITES, EST_TAP_FILTERED
    from bng_tpu.ops.antispoof import (AST_DROPPED, AST_V4_VIOL,
                                       MODE_DISABLED, MODE_STRICT)
    from bng_tpu.runtime.engine import (AntispoofTables, Engine, QoSTables)
    from bng_tpu.utils.net import u32_to_ip

    import numpy as np

    n_subs = max(6, int(round(12 * scale)))
    n_v6 = max(2, int(round(4 * scale)))
    n_ppp = max(2, int(round(4 * scale)))
    coa_waves = max(2, int(round(6 * scale)))
    ddos = max(8, int(round(24 * scale)))
    secret = b"day-secret"

    # ---- build the whole stack UNtraced: the first process() pays the
    # fused-pipeline compile and must not land in a budget stage -------
    clock = SimClock()
    server, pools, fastpath, nat = _build_server_stack(clock)
    qos = QoSTables()
    spoof = AntispoofTables(nbuckets=256)
    # per-binding STRICT, default DISABLED: control planes (v6 SOLICIT,
    # PPPoE discovery) come from not-yet-bound MACs and must reach the
    # slow path; only a BOUND subscriber spoofing a foreign source is a
    # violation — exactly the reference's per-subscriber mode column
    spoof.set_config(MODE_DISABLED, True)
    edge = EdgeTables(nbuckets=256)
    policies = PolicyManager([
        QoSPolicy("gold", download_bps=400_000_000,
                  upload_bps=200_000_000),
        QoSPolicy("bronze", download_bps=50_000_000,
                  upload_bps=10_000_000),
    ])

    def qos_hook(ip, policy_name):
        p = policies.get(policy_name or "bronze")
        if p is not None:
            qos.set_subscriber(ip, p.download_bps, p.upload_bps)
        return True

    server.qos_hook = qos_hook

    im = InterceptManager(clock=clock)
    platform = StubPlatform()
    rman = RoutingManager(None, platform)
    rman.add_upstream(Upstream(name="ispA", interface="eth1",
                               gateway="192.0.2.1", table=100,
                               health_target="192.0.2.1", weight=1))
    rman.add_upstream(Upstream(name="ispB", interface="eth2",
                               gateway="192.0.2.2", table=101,
                               health_target="192.0.2.2", weight=1))
    platform.reachable["192.0.2.1"] = 0.001
    platform.reachable["192.0.2.2"] = 0.001
    for _ in range(3):
        rman.check_health()
    mac_a = bytes.fromhex("02dd0000000a")
    mac_b = bytes.fromhex("02dd0000000b")
    tap_prog = InterceptTapProgram(edge, im, clock=clock)
    route_prog = RouteProgram(edge, rman)
    route_prog.attach()
    route_prog.set_neighbor("192.0.2.1", mac_a)
    route_prog.set_neighbor("192.0.2.2", mac_b)
    pump = MirrorPump(tap_prog, manager=im)

    v6 = DHCPv6Server(
        DHCPv6ServerConfig(server_mac=SERVER_MAC, rapid_commit=False),
        address_pool=AddressPool6("2001:db8:100::/64"),
        prefix_pool=PrefixPool6("2001:db8:f000::/40", delegated_len=56),
        clock=clock)
    slaac = SLAACServer(SLAACConfig(
        server_mac=SERVER_MAC,
        prefixes=[PrefixConfig(
            prefix=bytes.fromhex("20010db8010000000000000000000000"))],
        managed=True))
    ppp = PPPoEServer(
        PPPoEServerConfig(our_ip=ip_to_u32("10.64.0.1"),
                          dns_primary=ip_to_u32("1.1.1.1"),
                          echo_interval_s=30.0),
        LocalVerifier({"alice": b"secret123"}),
        lambda username, mac: ip_to_u32("10.64.0.100"),
        magic_source=lambda: 0xDEADBEEF,
        challenge_source=lambda: b"C" * 16)
    demux = SlowPathDemux(dhcp=server, dhcpv6=v6, slaac=slaac, pppoe=ppp,
                          clock=clock)
    eng = Engine(fastpath, nat, qos=qos, antispoof=spoof, edge=edge,
                 mirror_sink=pump, batch_size=32, slow_path=demux,
                 clock=clock)

    fac = StormFrameFactory(SERVER_IP)
    base = (seed % 61) * 1_000_000
    macs = [_mac(base + i) for i in range(n_subs)]
    ppp_macs = [_mac(base + 0x10000 + i) for i in range(n_ppp)]
    v6_macs = [_mac(base + 0x20000 + i) for i in range(n_v6)]
    ext_ip = ip_to_u32("198.51.100.9")

    def data(mac, src_ip, dport, sport=40000):
        return packets.udp_packet(mac, SERVER_MAC, src_ip, ext_ip,
                                  sport, dport, b"production-day")

    # warm-up: ONE lease pays the jit compile outside the tracer
    leased: dict[bytes, int] = {}

    def dora(m, i):
        res = eng.process([fac.discover(m, 0x800 + i)])
        off = (res["slow"] or res["tx"])[0][1]
        ip = _reply(off).yiaddr
        eng.process([fac.request(m, ip, 0x900 + i)])
        leased[m] = ip

    dora(macs[0], 0)

    with _traced() as tracer:
        # ---- morning: bring-up wave — IPoE + dual-stack + PPPoE ------
        for i, m in enumerate(macs[1:], start=1):
            dora(m, i)
        for m in macs:
            spoof.add_binding(m, leased[m], MODE_STRICT)
            route_prog.bind_subscriber(
                leased[m], "business" if leased[m] % 2 else "residential")

        from bng_tpu.control.dhcpv6.protocol import (DHCPv6Message,
                                                     generate_duid_ll)
        from bng_tpu.control.dhcpv6 import protocol as p6

        server_duid = v6.duid.encode()
        v6_leased = 0
        ra_seen = 0
        for i, m in enumerate(v6_macs):
            duid = generate_duid_ll(m).encode()
            res = eng.process([_solicit6(m, 0x600 + i, duid),
                               _rs_frame(m)])
            replies = [f for _l, f in res["slow"] if f is not None]
            ra_seen += sum(1 for f in replies if f[12:14] == b"\x86\xdd"
                           and f[20] != 17)
            res = eng.process([_request6(m, 0x700 + i, duid,
                                         server_duid, None)])
            for _l, f in res["slow"]:
                if f is None or f[20] != 17:
                    continue
                msg = DHCPv6Message.decode(f[62:])
                if msg.msg_type == p6.REPLY:
                    ias = msg.ia_nas()
                    if ias and ias[0].addresses:
                        v6_leased += 1

        ppp_sessions = 0
        for i, m in enumerate(ppp_macs):
            padi = pcodec.PPPoEPacket(pcodec.CODE_PADI, 0,
                                      pcodec.serialize_tags(
                [pcodec.Tag(pcodec.TAG_SERVICE_NAME, b""),
                 pcodec.Tag(pcodec.TAG_HOST_UNIQ, b"HU%02d" % i)]))
            res = eng.process([pcodec.eth_frame(
                b"\xff" * 6, m, pcodec.ETH_PPPOE_DISCOVERY, padi.encode())])
            pado = next((f for _l, f in res["slow"] if f is not None), None)
            if pado is None:
                continue
            _d, src, _e, payload = pcodec.parse_eth(pado)
            tags = pcodec.parse_tags(pcodec.PPPoEPacket.decode(payload).payload)
            cookie = pcodec.find_tag(tags, pcodec.TAG_AC_COOKIE)
            out_tags = [pcodec.Tag(pcodec.TAG_SERVICE_NAME, b"")]
            if cookie is not None:
                out_tags.append(cookie)
            padr = pcodec.PPPoEPacket(pcodec.CODE_PADR, 0,
                                      pcodec.serialize_tags(out_tags))
            res = eng.process([pcodec.eth_frame(
                src, m, pcodec.ETH_PPPOE_DISCOVERY, padr.encode())])
            for _l, f in res["slow"]:
                if f is None:
                    continue
                pads = pcodec.PPPoEPacket.decode(pcodec.parse_eth(f)[3])
                if pads.code == pcodec.CODE_PADS and pads.session_id:
                    ppp_sessions += 1
            demux.drain_pending()  # LCP conf-reqs beyond the ring contract

        # every lease carved a CGNAT block at DORA time (nat_hook); a
        # first flow per subscriber proves the blocks actually translate
        nat_flows = sum(
            1 for i, m in enumerate(macs)
            if nat.handle_new_flow(leased[m], ext_ip, 40000 + i, 80, 17,
                                   100, int(clock())) is not None)

        def forward_wave(dport, sport=41000):
            """One upstream data frame per subscriber; returns (fwd
            count, dst MACs of the forwarded frames)."""
            res = eng.process([data(m, leased[m], dport,
                                    sport=sport + i)
                               for i, m in enumerate(macs)],
                              now=clock.advance(1.0))
            out_macs = [bytes(f[:6]) for _l, f in res["fwd"]]
            return len(res["fwd"]), out_macs

        fwd_morning, wave_macs = forward_wave(8080)
        on_isps = sum(1 for mm in wave_macs if mm in (mac_a, mac_b))
        classes_split = len(set(wave_macs)) == 2  # ECMP split by class

        # ---- midday: CoA policy waves with renewals on the device ----
        def find_by_ip(ip):
            for _mk, lease in server.leases.items():
                if lease.ip == ip:
                    return lease
            return None

        proc = CoAProcessor(find_by_ip=find_by_ip, qos_update=qos_hook,
                            policy_manager=policies)
        coa = CoAServer(secret, proc)
        renew_ok = renew_total = 0
        for rnd in range(coa_waves):
            policy = ("gold", "bronze")[rnd % 2]
            for i, m in enumerate(macs):
                if (i + rnd) % 3 == 0:
                    req = RadiusPacket(rp.COA_REQUEST,
                                       (leased[m] + rnd) & 0xFF)
                    req.add(rp.FRAMED_IP_ADDRESS, leased[m])
                    req.add(rp.FILTER_ID, policy)
                    coa.handle_raw(req.encode(secret))
            batch = [fac.renew(m, leased[m], 0xA000 + rnd * 64 + i)
                     for i, m in enumerate(macs)]
            res = eng.process(batch, now=clock.advance(30.0))
            renew_total += len(batch)
            renew_ok += sum(1 for _l, f in res["tx"]
                            if f is not None
                            and _reply(f).msg_type == dhcp_codec.ACK)

        # ---- afternoon: taps armed MID-storm -------------------------
        now = clock()
        im.add_warrant(Warrant(id="W-DAY-1", liid="LIID-D1",
                               target_ipv4=u32_to_ip(leased[macs[0]]),
                               valid_from=now - 1.0,
                               valid_until=now + 100_000.0,
                               filter_dest_ports=[443]))
        im.add_warrant(Warrant(id="W-DAY-2", liid="LIID-D2",
                               target_ipv4=u32_to_ip(leased[macs[1]]),
                               valid_from=now - 1.0,
                               valid_until=now + 600.0))
        sync_rep = tap_prog.sync()
        filtered_before = int(np.asarray(eng.stats.edge)[EST_TAP_FILTERED])
        # matching flow mirrors; non-matching is filtered ON DEVICE
        eng.process([data(macs[0], leased[macs[0]], 443, sport=42000),
                     data(macs[0], leased[macs[0]], 9999, sport=42001),
                     data(macs[1], leased[macs[1]], 8080, sport=42002),
                     data(macs[2], leased[macs[2]], 443, sport=42003)],
                    now=clock.advance(1.0))
        filtered_on_device = (int(np.asarray(eng.stats.edge)[EST_TAP_FILTERED])
                              - filtered_before)
        mirrored_day = pump.stats["mirrored"]
        cc_records = im.stats()["cc_records"]

        # ---- evening rush: uplink dies + DDoS burst ------------------
        del platform.reachable["192.0.2.1"]
        for _ in range(rman.config.failure_threshold):
            rman.check_health()
        dirty_after_flap = edge.dirty_count()
        deltas = route_prog.stats["deltas"]
        fwd_evening, wave_macs = forward_wave(8081, sport=43000)
        on_survivor = sum(1 for mm in wave_macs if mm == mac_b)

        viol_before = np.asarray(eng.stats.spoof)[
            [AST_DROPPED, AST_V4_VIOL]].astype(np.int64)
        burst = [data(macs[i % n_subs],
                      ip_to_u32("172.16.9.9") + i,  # NOT the binding
                      53, sport=44000 + i)
                 for i in range(ddos)]
        eng.process(burst, now=clock.advance(1.0))
        viol_delta = (np.asarray(eng.stats.spoof)[
            [AST_DROPPED, AST_V4_VIOL]].astype(np.int64) - viol_before)

        # ---- night: the short warrant expires; bounded reap ----------
        clock.advance(700.0)
        expired = im.expire_warrants(max_reaps=4)
        reap_rep = tap_prog.sync()
        mirrored_before_night = pump.stats["mirrored"]
        eng.process([data(macs[1], leased[macs[1]], 8080, sport=45000)],
                    now=clock())
        mirrored_at_night = pump.stats["mirrored"] - mirrored_before_night

        audit = audit_invariants(engine=eng, pools=pools, dhcp=server,
                                 nat=nat, dhcpv6=v6,
                                 tap_program=tap_prog,
                                 route_program=route_prog)
        budget = check_budget(tracer, (
            # the coa_policy_flap envelopes: same engine, same stages
            BudgetLine("dispatch", limit_us=500_000.0),
            BudgetLine("device_wait", limit_us=2_000_000.0),
            BudgetLine("reply", limit_us=200_000.0),
            BudgetLine("total", limit_us=5_000_000.0),
        ))

    out = {
        "name": "production_day", "seed": seed,
        "subscribers": n_subs,
        "leased": len(leased),
        "v6_leased": v6_leased,
        "ra_seen": ra_seen,
        "ppp_sessions": ppp_sessions,
        "nat_flows": nat_flows,
        "routes_bound": route_prog.stats["bound"],
        "fwd_morning": fwd_morning,
        "ecmp_on_isps": on_isps,
        "ecmp_split": classes_split,
        "coa_ack": proc.stats["coa_ack"],
        "renew_ok": renew_ok, "renew_total": renew_total,
        "taps_armed": sync_rep["armed"],
        "mirrored": mirrored_day,
        "cc_records": cc_records,
        "filtered_on_device": filtered_on_device,
        "route_flaps": route_prog.stats["flaps"],
        "route_deltas": deltas,
        "dirty_after_flap": dirty_after_flap,
        "fwd_evening": fwd_evening,
        "on_survivor": on_survivor,
        "spoof_dropped": int(viol_delta[0]),
        "spoof_v4_viol": int(viol_delta[1]),
        "warrants_expired": expired,
        "taps_reaped": reap_rep["reaped"],
        "tap_rows_after_reap": reap_rep["rows"],
        "mirrored_after_expiry": mirrored_at_night,
        "edge_rewrites": int(np.asarray(eng.stats.edge)[EST_ROUTE_REWRITES]),
        "demux": dict(sorted(demux.stats.items())),
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
        "budget": budget,
    }
    out["ok"] = (
        len(leased) == n_subs
        and v6_leased == n_v6 and ra_seen == n_v6
        and ppp_sessions == n_ppp
        and nat_flows == n_subs
        and out["routes_bound"] == n_subs
        and fwd_morning == n_subs and on_isps == n_subs
        and classes_split
        and renew_ok == renew_total
        and out["taps_armed"] == 2
        # W-DAY-1 matched once (443), W-DAY-2 has no filters (any flow);
        # the 9999 flow died on the DEVICE filter predicate, and the
        # untargeted macs[2] flow never mirrors
        and mirrored_day == 2 and cc_records == 2
        and filtered_on_device >= 1
        and out["route_flaps"] == 1 and deltas >= 1
        and 0 < dirty_after_flap <= 2 * n_subs
        and fwd_evening == n_subs and on_survivor == n_subs
        and out["spoof_dropped"] == ddos
        and out["spoof_v4_viol"] == ddos
        and expired == 1
        and out["taps_reaped"] == 1 and out["tap_rows_after_reap"] == 1
        and mirrored_at_night == 0
        and audit.ok and budget["ok"])
    return out


# ---------------------------------------------------------------------------
# registry (merged into the runner's catalog next to SCENARIOS)
# ---------------------------------------------------------------------------

STORMS = {
    "flash_crowd_reconnect": flash_crowd_reconnect,
    "lease_expiry_avalanche": lease_expiry_avalanche,
    "cgnat_port_exhaustion": cgnat_port_exhaustion,
    "coa_policy_flap": coa_policy_flap,
    "dual_stack_bringup": dual_stack_bringup,
    "cluster_scale_storm": cluster_scale_storm,
    "production_day": production_day,
}
