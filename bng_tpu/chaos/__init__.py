"""Chaos/fault-injection + cross-authority invariant auditing.

The product of an ISP-edge BNG is correctness under partial failure:
worker death mid-DORA, corrupt snapshots, peer timeouts, clock skew,
pool exhaustion. This package is the correctness backstop every perf PR
runs against:

- `faults`     — a seeded, deterministic `FaultPlan` and the
                 near-zero-overhead `fault_point()` hook API wired into
                 the fleet pipe protocol, admission controller,
                 checkpoint writer/reader, engine dispatch/drain, the
                 HA syncer and the NAT/lease expiry clocks.
- `invariants` — the cross-authority auditor: proves the five state
                 authorities (lease books, pool bitmap, fleet slices,
                 host tables, device mirrors) never disagree, at the
                 existing quiesce barrier.
- `scenarios`  — scripted failure scenarios (DORA under worker crash,
                 corrupt-restore-then-cold-start, fleet reshard under
                 kill, NAT expiry under skew, HA delta loss).
- `runner`     — the scenario/soak driver behind `bng chaos run` and
                 `make verify-chaos`, emitting a bit-deterministic JSON
                 report.

Only `faults` is imported here: the instrumented runtime/control
modules import `fault_point` from it, and a package __init__ that
pulled in scenarios would create import cycles (scenarios import the
modules that import us).
"""

from bng_tpu.chaos.faults import (FaultInjector, FaultPlan,  # noqa: F401
                                  FaultSpec, armed, fault_point,
                                  mutate_point)
