"""Seeded, deterministic fault injection — the `fault_point()` hook API.

Design constraints, in order:

1. **Disarmed cost ~ zero.** Every instrumented call site pays one
   function call, one module-global load and one `is None` compare when
   no injector is armed (PERF_NOTES §7 measures it as unmeasurable
   against run-to-run noise on the hot path). No locks, no dict lookups,
   no allocation.
2. **Bit-deterministic.** A `FaultPlan` is either written out explicitly
   (a list of `FaultSpec`s) or generated from a seed via
   `random.Random` — two runs with the same seed produce the identical
   fault schedule, and the injector's record of what fired is part of
   the scenario report, so reports diff clean.
3. **Faults are *requests*, not actions.** `fault_point("name")` returns
   the matching `FaultSpec` (or None); the call site interprets the
   kinds it understands and ignores the rest. The injector never
   reaches into subsystems — the subsystems stay the single writers of
   their own state, which is the invariant the auditor proves.

Instrumented points and the kinds each site honors:

    fleet.scatter     kill | drop_batch | dup_batch | reorder
                      (per-worker batch dispatch, control/fleet.py)
    admission.admit   force_shed        (control/admission.py)
    ckpt.write        truncate | bitflip | io_error
                      (statestore.CheckpointStore.save — corrupts the
                      bytes that land on disk)
    ckpt.read         truncate | bitflip | io_error
                      (statestore.CheckpointStore.load — corrupts the
                      bytes handed to the decoder)
    engine.dispatch   fail | delay      (runtime/engine.py device step)
    engine.slow_drain fail              (slow-lane batch drain)
    devloop.dispatch  fail              (devloop/host.py megakernel ring
                                        dispatch: the staged slots re-
                                        dispatch per-batch, loudly)
    ha.push           drop_delta        (control/ha.py ActiveSyncer)
    ha.connect        fail              (StandbySyncer peer timeout)
    nat.expire        skew              (NATManager.expire_sessions now)
    dhcp.expire       skew              (DHCPServer.cleanup_expired now)
    pool.allocate     exhaust           (control/pool.py Pool.allocate)
    fleet.resize      kill | fail       (SlowPathFleet.resize transfer
                                        loop: kill a worker mid-resize,
                                        or abort the transition before
                                        any state has moved)
    fleet.restart     kill | fail       (SlowPathFleet.rolling_restart:
                                        kill the shard being replaced,
                                        or abort the remaining rotation)
    ops.swap          fail              (blue/green engine swap, fired
                                        at the flip barrier — standby
                                        discarded, active keeps serving;
                                        runtime/ops.py)
    ops.snapshot      io_error          (in-memory checkpoint encode the
                                        swap hydrates from;
                                        runtime/checkpoint.py
                                        roundtrip_checkpoint)

Chaos events log through the existing rate-limited structlog path
(utils.structlog.RateLimiter) — a fault storm must be visible without
becoming a log firehose — and feed the bng_chaos_* metric families when
the injector is built with a `metrics` sink.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from bng_tpu.utils.structlog import RateLimiter, get_logger

# fault kinds (call sites honor the subset that makes sense for them)
KILL = "kill"
DROP_BATCH = "drop_batch"
DUP_BATCH = "dup_batch"
REORDER = "reorder"
FORCE_SHED = "force_shed"
TRUNCATE = "truncate"
BITFLIP = "bitflip"
IO_ERROR = "io_error"
FAIL = "fail"
DELAY = "delay"
DROP_DELTA = "drop_delta"
SKEW = "skew"
EXHAUST = "exhaust"

# point -> kinds the soak generator may draw for it (the full registry;
# explicit plans can use any (point, kind) pair their call site honors)
POINT_KINDS: dict[str, tuple[str, ...]] = {
    "fleet.scatter": (KILL, DROP_BATCH, DUP_BATCH, REORDER),
    "admission.admit": (FORCE_SHED,),
    "ckpt.write": (TRUNCATE, BITFLIP, IO_ERROR),
    "ckpt.read": (TRUNCATE, BITFLIP, IO_ERROR),
    "engine.dispatch": (FAIL, DELAY),
    "engine.slow_drain": (FAIL,),
    "devloop.dispatch": (FAIL,),
    "ha.push": (DROP_DELTA,),
    "ha.connect": (FAIL,),
    "nat.expire": (SKEW,),
    "dhcp.expire": (SKEW,),
    "pool.allocate": (EXHAUST,),
    "fleet.resize": (KILL, FAIL),
    "fleet.restart": (KILL, FAIL),
    "ops.swap": (FAIL,),
    "ops.snapshot": (IO_ERROR,),
}


class FaultInjectedError(RuntimeError):
    """Raised by call sites that honor `fail`/`io_error` kinds — the
    scenario driver catches it and counts the work unit as lost (the
    client-retransmit failure mode)."""


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire at the `at_hit`-th visit (1-based) of
    `point`, for `count` consecutive visits. `arg` is kind-specific:
    truncate = bytes to cut, bitflip = byte offset, delay = seconds,
    skew = signed seconds added to the expiry clock."""

    point: str
    kind: str
    at_hit: int = 1
    count: int = 1
    arg: float = 0.0

    def to_dict(self) -> dict:
        return {"point": self.point, "kind": self.kind,
                "at_hit": self.at_hit, "count": self.count,
                "arg": self.arg}


@dataclass
class FaultPlan:
    """A deterministic schedule of faults. Either hand-written
    (scenarios pin exact faults) or generated from a seed (the soak
    driver's randomized-but-reproducible sweep)."""

    seed: int = 0
    specs: list[FaultSpec] = field(default_factory=list)

    @staticmethod
    def generate(seed: int, points: tuple[str, ...] | None = None,
                 n_faults: int = 8, max_hit: int = 32) -> "FaultPlan":
        """Seeded plan over `points` (default: every registered point).
        random.Random is stable across platforms and Python versions for
        the methods used here, so the schedule is bit-reproducible."""
        rng = random.Random(seed)
        points = tuple(points if points is not None else sorted(POINT_KINDS))
        specs = []
        for _ in range(n_faults):
            point = rng.choice(points)
            kind = rng.choice(POINT_KINDS[point])
            arg = 0.0
            if kind == TRUNCATE:
                arg = float(rng.randrange(1, 64))
            elif kind == BITFLIP:
                arg = float(rng.randrange(0, 1 << 16))
            elif kind == DELAY:
                arg = rng.randrange(1, 10) / 1000.0
            elif kind == SKEW:
                arg = float(rng.choice((-7200, -3600, 3600, 7200)))
            specs.append(FaultSpec(point=point, kind=kind,
                                   at_hit=rng.randrange(1, max_hit + 1),
                                   arg=arg))
        return FaultPlan(seed=seed, specs=specs)

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "specs": [s.to_dict() for s in self.specs]}


class FaultInjector:
    """Armed runtime of one FaultPlan: counts visits per point, decides
    which visits fire, and records the schedule that actually executed
    (the deterministic half of the scenario report)."""

    def __init__(self, plan: FaultPlan, metrics=None, log: bool = True):
        self.plan = plan
        self.metrics = metrics
        self.hits: dict[str, int] = {}
        self.injected: list[tuple[str, str, int]] = []  # (point, kind, hit)
        self._by_point: dict[str, list[FaultSpec]] = {}
        for spec in plan.specs:
            self._by_point.setdefault(spec.point, []).append(spec)
        self._log = get_logger("chaos") if log else None
        self._log_limit = RateLimiter(rate=2.0, burst=5)

    # -- the decision ----------------------------------------------------

    def check(self, point: str) -> FaultSpec | None:
        h = self.hits.get(point, 0) + 1
        self.hits[point] = h
        for spec in self._by_point.get(point, ()):
            if spec.at_hit <= h < spec.at_hit + spec.count:
                self._record(spec, h)
                return spec
        return None

    def mutate(self, point: str, data: bytes) -> bytes:
        """Byte-corrupting points (the checkpoint writer/reader). The
        returned bytes replace `data`; io_error raises instead."""
        spec = self.check(point)
        if spec is None:
            return data
        if spec.kind == IO_ERROR:
            raise OSError(f"chaos: injected I/O error at {point}")
        if spec.kind == TRUNCATE:
            cut = int(spec.arg) or max(1, len(data) // 4)
            return data[: max(0, len(data) - cut)]
        if spec.kind == BITFLIP:
            if not data:
                return data
            pos = int(spec.arg) % len(data)
            bit = 1 << (int(spec.arg) % 8)
            return data[:pos] + bytes([data[pos] ^ bit]) + data[pos + 1:]
        return data

    def _record(self, spec: FaultSpec, hit: int) -> None:
        self.injected.append((spec.point, spec.kind, hit))
        if self.metrics is not None:
            try:
                self.metrics.chaos_faults.inc(point=spec.point,
                                              kind=spec.kind)
            except Exception:  # noqa: BLE001 — metrics must never fault
                pass
        if self._log is not None:
            ok, suppressed = self._log_limit.allow()
            if ok:
                self._log.warning("fault injected", point=spec.point,
                                  kind=spec.kind, hit=hit, arg=spec.arg,
                                  suppressed=suppressed)

    def stats_snapshot(self) -> dict:
        by_kind: dict[str, int] = {}
        for _p, kind, _h in self.injected:
            by_kind[kind] = by_kind.get(kind, 0) + 1
        return {"hits": dict(sorted(self.hits.items())),
                "injected": [list(t) for t in self.injected],
                "by_kind": dict(sorted(by_kind.items()))}


# ---------------------------------------------------------------------------
# the hot-path hook (module-level no-op when disarmed)
# ---------------------------------------------------------------------------

_ACTIVE: FaultInjector | None = None


def any_armed() -> bool:
    """Is ANY fault plan armed? The vectorized host paths (ISSUE 14)
    check this once per batch and fall back to their per-frame scalar
    twins when chaos is live: fault plans count per-call hits, so a
    batched path that skipped N-1 of N fault_point() visits would
    silently shift every later hit in the plan. Disarmed (production):
    one global load + None compare, same contract as fault_point."""
    return _ACTIVE is not None


def fault_point(name: str) -> FaultSpec | None:
    """The instrumentation hook. Disarmed (the production state) this is
    a global load + None compare — nothing else. Armed, it asks the
    injector whether this visit fires and returns the FaultSpec for the
    call site to interpret."""
    if _ACTIVE is None:
        return None
    return _ACTIVE.check(name)


def mutate_point(name: str, data: bytes) -> bytes:
    """Byte-corrupting variant for the checkpoint writer/reader: returns
    `data` untouched when disarmed."""
    if _ACTIVE is None:
        return data
    return _ACTIVE.mutate(name, data)


def arm(injector: FaultInjector) -> FaultInjector:
    global _ACTIVE
    _ACTIVE = injector
    return injector


def disarm() -> None:
    global _ACTIVE
    _ACTIVE = None


class armed:
    """Context manager: arm a plan (or a prebuilt injector) for the
    block, disarm on exit — exceptions included, so a failed scenario
    can never leak an armed injector into the next one."""

    def __init__(self, plan: FaultPlan | FaultInjector, metrics=None,
                 log: bool = True):
        self.injector = (plan if isinstance(plan, FaultInjector)
                         else FaultInjector(plan, metrics=metrics, log=log))

    def __enter__(self) -> FaultInjector:
        return arm(self.injector)

    def __exit__(self, *exc) -> None:
        disarm()


class SimClock:
    """Deterministic logical clock for scenarios. Reports built on it
    contain no wallclock, so two runs with one seed emit identical
    JSON. The epoch is arbitrary but fixed."""

    def __init__(self, start: float = 1_700_000_000.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t
