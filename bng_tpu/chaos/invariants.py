"""Cross-authority invariant auditor.

Five authorities hold overlapping views of subscriber/session state:

  1. the parent pool bitmaps   (control/pool.py  Pool._allocated)
  2. the fleet lease slices    (control/fleet.py SlicePool per worker)
  3. the lease books           (DHCPServer.leases, parent + per worker)
  4. the host fast-path tables (runtime/tables.py FastPathTables)
  5. the device mirrors        (Engine.tables — the HBM copies)

plus the NAT manager's allocator/EIM/table triple. Every one of them is
updated by a different code path (slow path, fleet relay, checkpoint
restore, expiry sweeps), and a bug in any path shows up as two
authorities disagreeing — the precondition for double-allocating an
address or DNATing traffic to the wrong subscriber.

`audit_invariants` proves, at the existing quiesce barrier (the same
one checkpoints snapshot behind):

  - no IP is owned by two of {parent pool bitmap, fleet worker slices,
    lease books} — carve-leak, double-grant, double-lease;
  - every leased IP is marked allocated in its owning authority;
  - host FastPathTables rows match the device mirrors bit-exact after a
    drain (krows/stash/vals per table, plus the dense pool/server
    config), and no fast-path row outlives its lease;
  - the NAT allocator, EIM map, _ext_ports index, session and reverse
    tables are mutually consistent (block geometry, port ranges,
    refcounts, reverse-row pairing);
  - a checkpoint save -> decode round trip is state-identical
    (meta + every array + re-encoded bytes).

Violations come back as structured `Finding`s (bounded per kind),
feed the bng_invariant_* metric families, and `AuditReport.to_dict()`
is deterministic (sorted) so scenario reports diff clean.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

# per-kind cap: a systematically broken table would otherwise produce
# one finding per row; the count still lands in violations_by_kind
MAX_FINDINGS_PER_KIND = 16


@dataclass(frozen=True)
class Finding:
    kind: str  # stable slug, the bng_invariant_violations_total label
    subject: str  # the ip/mac/slot/table the violation is about
    detail: str

    def to_dict(self) -> dict:
        return {"kind": self.kind, "subject": self.subject,
                "detail": self.detail}


@dataclass
class AuditReport:
    findings: list[Finding] = field(default_factory=list)
    checks: dict[str, int] = field(default_factory=dict)  # coverage counts
    suppressed: dict[str, int] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.suppressed

    def violations_by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.kind] = out.get(f.kind, 0) + 1
        for kind, extra in self.suppressed.items():
            out[kind] = out.get(kind, 0) + extra
        return dict(sorted(out.items()))

    def add(self, kind: str, subject: str, detail: str) -> None:
        if sum(1 for f in self.findings if f.kind == kind) \
                >= MAX_FINDINGS_PER_KIND:
            self.suppressed[kind] = self.suppressed.get(kind, 0) + 1
            return
        self.findings.append(Finding(kind, subject, detail))

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "checks": dict(sorted(self.checks.items())),
            "violations_by_kind": self.violations_by_kind(),
            "findings": [f.to_dict() for f in sorted(
                self.findings, key=lambda f: (f.kind, f.subject))],
        }


# ---------------------------------------------------------------------------
# lease book collection
# ---------------------------------------------------------------------------

def _fleet_worker_books(fleet) -> list[tuple[int, dict]] | None:
    """[(worker_id, {mac_u64: Lease})] — direct object access in inline
    mode, via the pipe protocol in process mode. None when a dead worker
    makes the books unknowable (its carved slices stay allocated in the
    parent, so consistency is preserved; coverage just shrinks)."""
    if fleet is None:
        return []
    if fleet.mode == "inline":
        return [(w, dict(worker.server.leases))
                for w, worker in enumerate(fleet._inline)]
    from bng_tpu.control.dhcp_server import DHCPServer

    try:
        state = fleet.export_state()
    except (OSError, EOFError):
        return None
    out = []
    for idx, wstate in enumerate(state["workers"]):
        from bng_tpu.utils.net import mac_to_u64

        _seq, leases = DHCPServer.parse_lease_state(wstate)
        # export skips dead workers, so the list index is NOT the worker
        # id — the entry carries its real id (older snapshots without it
        # fall back to position)
        w = int(wstate.get("worker_id", idx))
        out.append((w, {mac_to_u64(l.mac): l for l in leases}))
    return out


def _audit_ownership(report: AuditReport, pools, dhcp, fleet,
                     books) -> None:
    """Authorities 1-3: pool bitmap vs fleet slices vs lease books.
    `books` is the one fleet-book snapshot shared with the fastpath-row
    check (one export round-trip, one consistent cut)."""
    if pools is None:
        return
    # slice carve invariants (fleet workers' granted sets vs the parent)
    granted_by: dict[int, list[int]] = {}  # ip -> [worker]
    workers = fleet._inline if (fleet is not None
                                and fleet.mode == "inline") else []
    n_granted = 0
    for w, worker in enumerate(workers):
        owner_tag = f"fleet:w{w}"
        for pid, sp in worker.pools.pools.items():
            parent = pools.pools.get(pid)
            for ip in sp._granted:
                n_granted += 1
                granted_by.setdefault(ip, []).append(w)
                if parent is None:
                    report.add("carve-leak", _ip(ip),
                               f"worker {w} slice references unknown "
                               f"pool {pid}")
                elif parent._allocated.get(ip) != owner_tag:
                    report.add(
                        "carve-leak", _ip(ip),
                        f"granted to worker {w} but parent pool {pid} "
                        f"owner is {parent._allocated.get(ip)!r} "
                        f"(expected {owner_tag!r})")
            for ip in sp._allocated:
                if ip not in sp._granted:
                    report.add("slice-alloc-outside-grant", _ip(ip),
                               f"worker {w} allocated an address outside "
                               f"its granted slice of pool {pid}")
    for ip, ws in granted_by.items():
        if len(ws) > 1:
            report.add("double-grant", _ip(ip),
                       f"address granted to workers {sorted(ws)}")
    report.checks["slice_granted"] = n_granted

    # lease books: parent server + every fleet worker
    entries: list[tuple[str, int, object]] = []  # (source, mac_u64, lease)
    if dhcp is not None:
        entries += [("parent", mk, l) for mk, l in dhcp.leases.items()]
    if books is None:
        report.checks["fleet_books_unreadable"] = 1
        books = []
    for w, book in books:
        entries += [(f"w{w}", mk, l) for mk, l in book.items()]
    report.checks["leases"] = len(entries)

    by_ip: dict[int, list[tuple[str, int]]] = {}
    by_mac: dict[int, list[str]] = {}
    for src, mk, lease in entries:
        by_ip.setdefault(lease.ip, []).append((src, mk))
        by_mac.setdefault(mk, []).append(src)
        # every leased IP must be marked allocated in its owning
        # authority: the worker's slice for fleet leases (inline mode —
        # process-mode slices live in the child), the parent pool for
        # parent leases
        if src.startswith("w") and fleet is not None \
                and fleet.mode == "inline":
            w = int(src[1:])
            sp = workers[w].pools.pool_for_ip(lease.ip)
            if sp is None or lease.ip not in sp._allocated:
                report.add("lease-not-allocated", _ip(lease.ip),
                           f"worker {w} lease (mac {lease.mac.hex()}) "
                           f"not allocated in its slice")
        pool = pools.pool_for_ip(lease.ip)
        if pool is None:
            report.add("lease-outside-pools", _ip(lease.ip),
                       f"{src} lease (mac {lease.mac.hex()}) is outside "
                       f"every configured pool")
        elif src == "parent" and lease.ip not in pool._allocated:
            report.add("lease-not-allocated", _ip(lease.ip),
                       f"parent lease (mac {lease.mac.hex()}) not "
                       f"allocated in pool {pool.pool_id}")
        elif pool is not None and lease.ip == pool.gateway:
            report.add("gateway-leased", _ip(lease.ip),
                       f"{src} leased the pool {pool.pool_id} gateway")
    for ip, owners in by_ip.items():
        if len(owners) > 1:
            macs = sorted({f"{s}:{mk:012x}" for s, mk in owners})
            report.add("double-lease", _ip(ip),
                       f"leased by {len(owners)} owners: {macs}")
    for mk, srcs in by_mac.items():
        if len(srcs) > 1:
            report.add("mac-double-lease", f"{mk:012x}",
                       f"one MAC holds leases in {sorted(srcs)}")


# ---------------------------------------------------------------------------
# fast-path tables: rows vs leases, host vs device
# ---------------------------------------------------------------------------

def _collect_lease_index(dhcp, fleet, books) -> dict[int, int] | None:
    """mac_u64 -> ip over every lease book (the shared `books` snapshot),
    or None when books are unknowably partial."""
    idx: dict[int, int] = {}
    if dhcp is not None:
        for mk, lease in dhcp.leases.items():
            idx[mk] = lease.ip
    if fleet is not None and fleet.mode == "process" and fleet._dead:
        # a dead process's book is gone but its subscribers still hold
        # their leases — rows for them are NOT stale, just unprovable
        return None
    if books is None:
        return None
    for _w, book in books:
        for mk, lease in book.items():
            idx[mk] = lease.ip
    return idx


def _audit_fastpath_rows(report: AuditReport, fastpath, dhcp, fleet,
                         books) -> None:
    """Authority 4 vs 3: no subscriber row outlives (or contradicts) its
    lease. One-directional by design — a lease WITHOUT a row is only a
    fast-path miss (the slow path re-answers; restores that hydrate
    books but not tables are legal), but a row without a lease would
    device-ACK an address nobody holds."""
    if fastpath is None or (dhcp is None and fleet is None):
        # without any lease book there is nothing to cross-check rows
        # against (bench-style bulk installs are legal book-less rows)
        return
    idx = _collect_lease_index(dhcp, fleet, books)
    if idx is None:
        return
    sub = fastpath.sub
    occupied = np.nonzero(sub.used)[0]
    report.checks["fastpath_rows"] = len(occupied)
    from bng_tpu.ops.dhcp import AV_IP

    for s in occupied:
        hi, lo = int(sub.keys[s][0]), int(sub.keys[s][1])
        mk = (hi << 32) | lo
        row_ip = int(sub.vals[s][AV_IP])
        got = idx.get(mk)
        if got is None:
            report.add("fastpath-stale-row", f"{mk:012x}",
                       f"subscriber row (ip {_ip(row_ip)}) has no live "
                       f"lease in any book")
        elif got != row_ip:
            report.add("fastpath-ip-mismatch", f"{mk:012x}",
                       f"row ip {_ip(row_ip)} != leased ip {_ip(got)}")


def _table_mirror_findings(report: AuditReport, host, dev_state,
                           label: str) -> None:
    """One HostTable vs its device TableState, bit-exact. Caller must
    have drained (dirty_count()==0) — pending deltas are legal lag, not
    divergence."""
    exp_krows = host._pack_bucket_rows(np.arange(host.nbuckets))
    exp_stash = host._pack_stash_rows(np.arange(host.stash))
    got_krows = np.asarray(dev_state.krows)
    got_stash = np.asarray(dev_state.stash_rows)
    got_vals = np.asarray(dev_state.vals)
    report.checks[f"mirror_buckets.{label}"] = host.nbuckets
    if exp_krows.shape != got_krows.shape:
        report.add("mirror-mismatch", label,
                   f"krows shape {got_krows.shape} != host "
                   f"{exp_krows.shape}")
        return
    bad = np.nonzero((exp_krows != got_krows).any(axis=1))[0]
    for b in bad[:4]:
        report.add("mirror-mismatch", f"{label}/bucket{int(b)}",
                   "device probe row differs from host mirror")
    if len(bad) > 4:
        report.add("mirror-mismatch", label,
                   f"{len(bad)} buckets diverge in total")
    if not np.array_equal(exp_stash, got_stash):
        report.add("mirror-mismatch", f"{label}/stash",
                   "device stash rows differ from host mirror")
    if host.vals.shape != got_vals.shape \
            or not np.array_equal(host.vals, got_vals):
        bad_v = (np.nonzero((host.vals != got_vals).any(axis=1))[0]
                 if host.vals.shape == got_vals.shape else [])
        for s in bad_v[:4]:
            report.add("mirror-mismatch", f"{label}/slot{int(s)}",
                       "device value row differs from host mirror")
        if len(bad_v) > 4 or host.vals.shape != got_vals.shape:
            report.add("mirror-mismatch", f"{label}/vals",
                       "device value array differs from host mirror")


def _audit_device_mirror(report: AuditReport, engine,
                         max_drain_steps: int = 64) -> None:
    """Authority 5 vs 4: after draining every pending delta, the HBM
    DHCP tables must equal the host mirrors bit-exact, and the QoS way
    rows must match on every host-authoritative word. NAT session
    values and the QoS token/last-us words are device-WRITTEN
    (fold_device_authoritative owns those), so they are masked out."""
    if engine is None:
        return
    fastpath = engine.fastpath
    steps = 0
    while engine.pending_dirty() > 0 and steps < max_drain_steps:
        # an empty batch still runs the bounded update drain (and a
        # bulk-build resync if one is pending) — the cheapest way to
        # ship the remaining deltas without inventing a second drain
        # path. pending_dirty covers EVERY drained mirror (dhcp, nat,
        # qos, antispoof, ...), not just the fastpath tables: the QoS
        # mirror check below needs its deltas shipped too.
        engine.process([])
        steps += 1
    if engine.pending_dirty() > 0:
        report.add("mirror-undrained", "fastpath",
                   f"{engine.pending_dirty()} dirty slots after "
                   f"{steps} drain steps")
        return
    engine.quiesce()
    report.checks["mirror_drain_steps"] = steps
    for t in ("sub", "vlan", "cid"):
        _table_mirror_findings(report, getattr(fastpath, t),
                               getattr(engine.tables.dhcp, t),
                               f"fastpath.{t}")
    if not np.array_equal(fastpath.pools,
                          np.asarray(engine.tables.dhcp.pools)):
        report.add("mirror-mismatch", "fastpath.pools",
                   "device pool config differs from host")
    if not np.array_equal(fastpath.server,
                          np.asarray(engine.tables.dhcp.server)):
        report.add("mirror-mismatch", "fastpath.server",
                   "device server config differs from host")
    _audit_qos_mirror(report, engine)
    edge = getattr(engine, "edge", None)
    if edge is not None and engine.tables.tap is not None:
        _table_mirror_findings(report, edge.tap, engine.tables.tap,
                               "edge.tap")
        _table_mirror_findings(report, edge.route, engine.tables.route,
                               "edge.route")
        if not np.array_equal(edge.tap_filters,
                              np.asarray(engine.tables.tap_filters)):
            report.add("mirror-mismatch", "edge.tap_filters",
                       "device filter rows differ from host")
        if not np.array_equal(edge.tap_config,
                              np.asarray(engine.tables.tap_config)):
            report.add("mirror-mismatch", "edge.tap_config",
                       "device armed predicate differs from host")


def _audit_qos_mirror(report: AuditReport, engine) -> None:
    """QoS host way rows vs device rows, masking the device-written
    token-bucket words (tokens + last_us) — a CoA policy flap rewrites
    key/flags/rate/burst/priority through the bounded drain, and after
    the drain the config words must agree bit-exact on every slot.
    Caller has drained (pending_dirty()==0) and quiesced."""
    from bng_tpu.ops.qtable import QW_LAST_US, QW_TOKENS

    for label, host, dev_rows in (
            ("qos.up", engine.qos.up, engine.tables.qos_up.rows),
            ("qos.down", engine.qos.down, engine.tables.qos_down.rows)):
        got = np.asarray(dev_rows)
        report.checks[f"mirror_slots.{label}"] = host.S
        if host.rows.shape != got.shape:
            report.add("qos-mirror-mismatch", label,
                       f"device rows shape {got.shape} != host "
                       f"{host.rows.shape}")
            continue
        mask = np.ones(host.rows.shape[1], dtype=bool)
        mask[[QW_TOKENS, QW_LAST_US]] = False
        bad = np.nonzero(
            (host.rows[:, mask] != got[:, mask]).any(axis=1))[0]
        for s in bad[:4]:
            report.add("qos-mirror-mismatch", f"{label}/slot{int(s)}",
                       "device config words differ from host way row")
        if len(bad) > 4:
            report.add("qos-mirror-mismatch", label,
                       f"{len(bad)} slots diverge in total")


# ---------------------------------------------------------------------------
# edge protection: tap rows vs warrants, route rows vs the routing program
# ---------------------------------------------------------------------------

def _audit_edge(report: AuditReport, edge, tap_program=None,
                route_program=None) -> None:
    """Edge-protection cross-authority clauses (ISSUE 17). The tap table
    and the warrant store are separate writers (device rows via
    EdgeTables, warrant lifecycle via control/intercept.py), so the
    auditor proves both directions:

    - every device tap row is backed by an ACTIVE in-window warrant — a
      row without one mirrors subscriber traffic with no legal basis,
      the worst finding this auditor can make;
    - every target the compiler armed is resident on the device — a
      missing row silently under-collects a live intercept;
    - every route row equals what the routing program would compile
      RIGHT NOW from the ISP tables + link health — a divergent row
      forwards to a next hop the tables no longer name;
    - each EdgeTables' armed predicate equals its live tap row count —
      a stale zero disables matching with warrants armed, a stale
      nonzero pays the tap probe with none.

    `edge` is anything with tap_rows()/route_rows(): an EdgeTables or a
    ShardedCluster's merged owner-routed surface.
    """
    if edge is None:
        return
    from bng_tpu.edge.compile import _active_in_window
    from bng_tpu.edge.ops import (RW_CLASS, RW_MAC_HI, RW_MAC_LO,
                                  RW_TABLE, TC_ARMED, TW_WID)

    taps = edge.tap_rows()
    routes = edge.route_rows()
    report.checks["edge_tap_rows"] = len(taps)
    report.checks["edge_route_rows"] = len(routes)

    if tap_program is not None:
        now = tap_program._clock()
        resident = {}
        for ip, row in taps:
            wid = int(row[TW_WID])
            resident[ip] = wid
            wid_id = tap_program.warrant_for(wid)
            try:
                w = (tap_program.manager.get_warrant(wid_id)
                     if wid_id is not None else None)
            except KeyError:  # warrant deleted out from under the row
                w = None
            if w is None:
                report.add("edge-tap-orphan", _ip(ip),
                           f"tap row carries wid {wid} with no known "
                           f"warrant — mirroring without legal basis")
            elif not _active_in_window(w, now):
                report.add("edge-tap-orphan", _ip(ip),
                           f"tap row for warrant {w.id} outside its "
                           f"ACTIVE validity window — must be reaped")
        for wid, ips in sorted(tap_program._ips_by_wid.items()):
            for ip in sorted(ips):
                if resident.get(ip) != wid:
                    report.add("edge-tap-missing", _ip(ip),
                               f"warrant wid {wid} armed this target but "
                               f"no device row carries it — the intercept "
                               f"silently under-collects")

    if route_program is not None:
        for ip, row in routes:
            want = route_program.expected_row(ip)
            got = (int(row[RW_MAC_HI]), int(row[RW_MAC_LO]),
                   int(row[RW_TABLE]), int(row[RW_CLASS]))
            if want is None:
                report.add("edge-route-orphan", _ip(ip),
                           "route row for a subscriber the routing "
                           "program would not route (unbound, or no "
                           "eligible upstream for its class)")
            elif got != tuple(int(x) for x in want):
                report.add("edge-route-divergence", _ip(ip),
                           f"device row {got} != compiled {want} — "
                           f"forwarding to a next hop the ISP tables "
                           f"no longer select")

    # armed predicate == live tap row count, per EdgeTables instance
    # (a ShardedCluster exposes its per-shard authorities as .edge)
    tables = ([edge] if hasattr(edge, "tap_config")
              else list(getattr(edge, "edge", None) or ()))
    for j, e in enumerate(tables):
        n_rows = len(e.tap_rows())
        cfg = int(e.tap_config[TC_ARMED])
        if cfg != n_rows:
            report.add("edge-armed-count", f"edge{j}",
                       f"armed predicate {cfg} != {n_rows} live tap rows")


# ---------------------------------------------------------------------------
# NAT: allocator / EIM / tables
# ---------------------------------------------------------------------------

def _audit_nat(report: AuditReport, nat) -> None:
    if nat is None:
        return
    from bng_tpu.ops.nat44 import (BV_PORT_END, BV_PORT_START, BV_PUBLIC_IP,
                                   FLAG_EIM, SV_NAT_IP, SV_NAT_PORT,
                                   SV_ORIG_IP, SV_ORIG_PORT, SV_PROTO)
    from bng_tpu.ops.parse import PROTO_ICMP

    report.checks["nat_blocks"] = len(nat.blocks)
    # blocks <-> sub_nat rows
    for priv_ip, blk in nat.blocks.items():
        row = nat.sub_nat.lookup([priv_ip])
        if row is None:
            report.add("nat-block-row-missing", _ip(priv_ip),
                       "allocator block has no subscriber_nat row")
            continue
        if (int(row[BV_PUBLIC_IP]) != blk["public_ip"]
                or int(row[BV_PORT_START]) != blk["port_start"]
                or int(row[BV_PORT_END]) != blk["port_end"]):
            report.add("nat-block-row-mismatch", _ip(priv_ip),
                       f"row ({_ip(int(row[BV_PUBLIC_IP]))} "
                       f"{int(row[BV_PORT_START])}-{int(row[BV_PORT_END])}) "
                       f"!= block ({_ip(blk['public_ip'])} "
                       f"{blk['port_start']}-{blk['port_end']})")
    n_rows = int(np.count_nonzero(nat.sub_nat.used))
    if n_rows != len(nat.blocks):
        report.add("nat-subnat-count", "subscriber_nat",
                   f"{n_rows} rows != {len(nat.blocks)} allocator blocks")

    # block carving: per public IP the allocated+free block starts must
    # be disjoint, uniform-size and behind the cursor
    by_pub: dict[int, list[tuple[int, int, str]]] = {}
    span = nat.ports_per_subscriber
    for priv_ip, blk in nat.blocks.items():
        by_pub.setdefault(blk["public_ip"], []).append(
            (blk["port_start"], blk["port_end"], _ip(priv_ip)))
        if blk["port_end"] - blk["port_start"] + 1 != span:
            report.add("nat-block-geometry", _ip(priv_ip),
                       f"block span {blk['port_end'] - blk['port_start'] + 1}"
                       f" != ports_per_subscriber {span}")
    for pub_ip, starts in nat._free_blocks.items():
        if len(starts) != len(set(starts)):
            report.add("nat-free-duplicate", _ip(pub_ip),
                       "free-block list holds duplicate starts")
        allocated = {s for s, _e, _p in by_pub.get(pub_ip, [])}
        for s in starts:
            if s in allocated:
                report.add("nat-free-allocated-overlap", _ip(pub_ip),
                           f"block start {s} is both free and allocated")
            if s + span - 1 >= nat._next_block.get(pub_ip, 0) + span:
                report.add("nat-free-past-cursor", _ip(pub_ip),
                           f"free block {s} lies beyond the carve cursor")
    for pub_ip, ranges in by_pub.items():
        cursor = nat._next_block.get(pub_ip)
        prev_end, prev_sub = -1, ""
        for start, end, sub in sorted(ranges):
            if start <= prev_end:
                report.add("nat-block-overlap", _ip(pub_ip),
                           f"blocks of {prev_sub} and {sub} overlap "
                           f"at port {start}")
            prev_end, prev_sub = end, sub
            if cursor is not None and start >= cursor:
                report.add("nat-cursor-behind", _ip(pub_ip),
                           f"block {start}-{end} ({sub}) sits at/past the "
                           f"carve cursor {cursor} — a future carve would "
                           f"re-issue it")

    # block-exhaustion accounting: every block the cursor has ever
    # carved is either allocated to a subscriber or on the free list —
    # carved != allocated + free means blocks leaked (exhaustion that
    # never heals) or double-booked. Checked per public IP so an
    # exhausted IP proves it is exhausted for a REASON.
    for pub_ip in nat.public_ips:
        cursor = nat._next_block.get(pub_ip, nat.port_range[0])
        carved = (cursor - nat.port_range[0]) // span
        n_alloc = len(by_pub.get(pub_ip, ()))
        n_free = len(nat._free_blocks.get(pub_ip, ()))
        if carved != n_alloc + n_free:
            report.add("nat-block-accounting", _ip(pub_ip),
                       f"{carved} blocks carved but {n_alloc} allocated "
                       f"+ {n_free} free — blocks leaked or double-booked")
        if cursor > nat.port_range[1] + 1:
            report.add("nat-block-accounting", _ip(pub_ip),
                       f"carve cursor {cursor} ran past the port range "
                       f"end {nat.port_range[1]}")
    report.checks["nat_exhausted_block"] = int(nat.exhausted["block"])
    report.checks["nat_exhausted_port"] = int(nat.exhausted["port"])

    # EIM <-> _ext_ports bijection, mappings inside the owner's block
    report.checks["nat_eim"] = len(nat.eim)
    for key, m in nat.eim.items():
        int_ip, _int_port, proto = key
        ext = (m[0], m[1], proto)
        if nat._ext_ports.get(ext) != key:
            report.add("nat-eim-extports-mismatch", _ip(int_ip),
                       f"eim {key} -> {ext} not indexed back")
        if m[2] <= 0:
            report.add("nat-eim-refcount", _ip(int_ip),
                       f"eim {key} refcount {m[2]} <= 0 but still mapped")
        blk = nat.blocks.get(int_ip)
        if blk is None:
            report.add("nat-eim-orphan", _ip(int_ip),
                       f"eim {key} has no allocator block")
        elif (m[0] != blk["public_ip"]
              or not blk["port_start"] <= m[1] <= blk["port_end"]):
            report.add("nat-eim-outside-block", _ip(int_ip),
                       f"mapping {_ip(m[0])}:{m[1]} outside block "
                       f"{blk['port_start']}-{blk['port_end']}")
    for ext, key in nat._ext_ports.items():
        if key not in nat.eim:
            report.add("nat-eim-extports-mismatch", _ip(ext[0]),
                       f"ext port {ext} indexes a vanished eim {key}")

    # sessions <-> reverse pairing + per-endpoint refcounts
    occupied = np.nonzero(nat.sessions.used)[0]
    report.checks["nat_sessions"] = len(occupied)
    ep_counts: dict[tuple[int, int, int], int] = {}
    for s in occupied:
        key = nat.sessions.keys[s]
        v = nat.sessions.vals[s]
        src_ip, dst_ip = int(key[0]), int(key[1])
        ports, proto = int(key[2]), int(key[3])
        src_port, dst_port = ports >> 16, ports & 0xFFFF
        nat_ip, nat_port = int(v[SV_NAT_IP]), int(v[SV_NAT_PORT])
        blk = nat.blocks.get(src_ip)
        if blk is None:
            report.add("nat-session-orphan", _ip(src_ip),
                       f"session slot {int(s)} has no allocator block")
        elif (nat_ip != blk["public_ip"]
              or not blk["port_start"] <= nat_port <= blk["port_end"]):
            report.add("nat-session-outside-block", _ip(src_ip),
                       f"session maps to {_ip(nat_ip)}:{nat_port} outside "
                       f"block {blk['port_start']}-{blk['port_end']}")
        r_src = 0 if proto == PROTO_ICMP else dst_port
        rkey = nat._key(dst_ip, nat_ip, r_src, nat_port, proto)
        rv = nat.reverse.lookup(rkey)
        # reverse rows are the 4 session-key words padded to the 8-word
        # gather-fast shape — only the key words carry meaning
        if rv is None or not np.array_equal(
                np.asarray(rv, dtype=np.uint32)[:4],
                np.asarray(key, dtype=np.uint32)):
            report.add("nat-missing-reverse", _ip(src_ip),
                       f"session slot {int(s)} has no matching reverse row")
        ep = (int(v[SV_ORIG_IP]), int(v[SV_ORIG_PORT]), int(v[SV_PROTO]))
        ep_counts[ep] = ep_counts.get(ep, 0) + 1
    n_rev = int(np.count_nonzero(nat.reverse.used))
    if n_rev != len(occupied):
        report.add("nat-reverse-count", "nat_reverse",
                   f"{n_rev} reverse rows != {len(occupied)} sessions "
                   f"(orphan reverse rows DNAT dead flows)")
    if nat.flags & FLAG_EIM:
        for ep, n in ep_counts.items():
            m = nat.eim.get(ep)
            if m is not None and m[2] != n:
                report.add("nat-eim-refcount", _ip(ep[0]),
                           f"eim {ep} refcount {m[2]} != {n} live sessions")


# ---------------------------------------------------------------------------
# DHCPv6 / PPPoE: lease books vs their pools
# ---------------------------------------------------------------------------

def _audit_dhcpv6(report: AuditReport, dhcpv6) -> None:
    """v6 lease book vs pool bitmaps, both directions: every IA_NA/IA_PD
    binding must be allocated in its pool (a binding outside the bitmap
    can be re-granted -> v6 double-lease), and every allocated address
    must have a binding (an orphan allocation is an address leak the
    pool can never hand out again). Advertise-only allocations release
    before the server returns, so the book and the bitmaps agree exactly
    at every quiesce point."""
    if dhcpv6 is None:
        return
    leased_na: dict[bytes, list] = {}
    leased_pd: dict[bytes, list] = {}
    for (duid, iaid, is_pd), lease in dhcpv6.leases.items():
        (leased_pd if is_pd else leased_na).setdefault(
            lease.address, []).append((duid.hex(), iaid))
    report.checks["v6_leases_na"] = len(leased_na)
    report.checks["v6_leases_pd"] = len(leased_pd)
    for addr, owners in leased_na.items():
        if len(owners) > 1:
            report.add("v6-double-lease", _ip6(addr),
                       f"IA_NA address bound to {len(owners)} clients")
    for addr, owners in leased_pd.items():
        if len(owners) > 1:
            report.add("v6-double-lease", _ip6(addr),
                       f"IA_PD prefix delegated to {len(owners)} clients")
    for pool, book, kind in ((dhcpv6.addr_pool, leased_na, "IA_NA"),
                             (dhcpv6.prefix_pool, leased_pd, "IA_PD")):
        if pool is None:
            continue
        allocated = set(pool._allocated)
        for addr in book:
            if addr not in allocated:
                report.add("v6-lease-not-allocated", _ip6(addr),
                           f"{kind} binding not marked allocated in its "
                           f"pool — re-grantable while bound")
        for addr in allocated - set(book):
            report.add("v6-alloc-orphan", _ip6(addr),
                       f"{kind} pool allocation with no binding — the "
                       f"address leaked out of circulation")
        # free-list hygiene: a free offset that is also allocated would
        # double-grant on the next allocate()
        alloc_offs = set(pool._allocated.values())
        for off in pool._free:
            if off in alloc_offs:
                report.add("v6-free-allocated-overlap", f"{kind}+{off}",
                           "pool offset is both free and allocated")


def _audit_pppoe(report: AuditReport, pppoe, pools) -> None:
    """PPPoE session store vs the v4 pools: every established session's
    assigned IP must be allocated in a configured pool, and no address
    may back two live sessions (the IPCP grant and the pool bitmap are
    separate writers — exactly the two-authority shape this auditor
    exists for)."""
    if pppoe is None:
        return
    by_ip: dict[int, list[int]] = {}
    n = 0
    for sess in pppoe.sessions.all():
        if not sess.assigned_ip:
            continue
        n += 1
        by_ip.setdefault(sess.assigned_ip, []).append(sess.session_id)
        if pools is not None:
            pool = pools.pool_for_ip(sess.assigned_ip)
            if pool is None:
                report.add("pppoe-lease-outside-pools",
                           _ip(sess.assigned_ip),
                           f"session {sess.session_id} assigned an IP "
                           f"outside every configured pool")
            elif sess.assigned_ip not in pool._allocated:
                report.add("pppoe-lease-not-allocated",
                           _ip(sess.assigned_ip),
                           f"session {sess.session_id} IP not marked "
                           f"allocated in pool {pool.pool_id}")
    for ip, sids in by_ip.items():
        if len(sids) > 1:
            report.add("pppoe-double-lease", _ip(ip),
                       f"IP assigned to sessions {sorted(sids)}")
    report.checks["pppoe_sessions"] = n


def _ip6(addr: bytes) -> str:
    import ipaddress

    try:
        return str(ipaddress.IPv6Address(int.from_bytes(addr, "big")))
    except Exception:  # noqa: BLE001 — a bad value is still a subject
        return addr.hex()


# ---------------------------------------------------------------------------
# checkpoint round trip
# ---------------------------------------------------------------------------

def _audit_checkpoint_roundtrip(report: AuditReport, *, fastpath=None,
                                nat=None, dhcp=None, fleet=None,
                                ha=None) -> None:
    """save -> encode -> decode must be state-identical: same meta, same
    arrays, and a re-encode of the decode is byte-identical. Runs with
    engine=None — the caller already quiesced; this must not re-enter
    the barrier."""
    from bng_tpu.runtime.checkpoint import (build_checkpoint,
                                            decode_checkpoint,
                                            encode_checkpoint)

    if fastpath is None and nat is None and dhcp is None and fleet is None:
        return
    c1 = build_checkpoint(0, 0.0, fastpath=fastpath, nat=nat, dhcp=dhcp,
                          fleet=fleet, ha=ha, node_id="audit")
    e1 = encode_checkpoint(c1)
    report.checks["ckpt_bytes"] = len(e1)
    try:
        d = decode_checkpoint(e1)
    except Exception as e:  # noqa: BLE001 — a reject IS the finding
        report.add("ckpt-roundtrip-reject", "checkpoint",
                   f"fresh snapshot failed to decode: {e}")
        return
    if json.dumps(c1.meta, sort_keys=True) != json.dumps(d.meta,
                                                         sort_keys=True):
        report.add("ckpt-roundtrip-mismatch", "meta",
                   "decoded meta differs from the snapshot")
    if sorted(c1.arrays) != sorted(d.arrays):
        report.add("ckpt-roundtrip-mismatch", "arrays",
                   f"array manifest differs: {sorted(c1.arrays)[:4]}... vs "
                   f"{sorted(d.arrays)[:4]}...")
        return
    for name in sorted(c1.arrays):
        if not np.array_equal(np.asarray(c1.arrays[name]),
                              d.arrays[name]):
            report.add("ckpt-roundtrip-mismatch", name,
                       "decoded array differs from the snapshot")
    if encode_checkpoint(d) != e1:
        report.add("ckpt-roundtrip-mismatch", "bytes",
                   "re-encoding the decode is not byte-identical")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _ip(ip: int) -> str:
    from bng_tpu.utils.net import u32_to_ip

    try:
        return u32_to_ip(int(ip))
    except Exception:  # noqa: BLE001 — a bad value is still a subject
        return str(ip)


def _audit_sharded(report: AuditReport, cluster, dhcp=None,
                   max_drain_steps: int = 64) -> None:
    """The ICI-sharded dataplane's cross-authority clause (ISSUE 12,
    FATE+DESTINI one level down): shard-local tables must PARTITION the
    global authority —

    * every DHCP row lives on exactly the shard its key hashes to, and
      no key is resident on two shards (the fleet's "no IP reachable
      from two workers" clause at the chip level);
    * chip-local state (QoS rows, antispoof bindings, garden
      membership, NAT port blocks) lives on the subscriber's affinity
      shard and nowhere else — the ring steers traffic there, so a
      misplaced row is state the dataplane can never reach;
    * NAT public-IP ownership is exclusive across shards (downstream
      steering is by-IP: shared ownership is unroutable);
    * the union of shard-resident subscriber rows covers the lease
      book: every lease's row on its owner shard (sums to the global
      authority, no row orphaned by a re-shard);
    * after draining pending deltas, every shard's device slice equals
      its host mirror bit-exact (the single-engine mirror proof, per
      shard).
    """
    if cluster is None:
        return
    from bng_tpu.ops.qtable import QW_FLAGS as _QF, QW_KEY as _QK
    from bng_tpu.ops.table import TableState, shard_owner

    n = cluster.n
    report.checks["shards"] = n

    # -- partition: dhcp rows on their owner shard, no double-residency
    for t in ("sub", "vlan", "cid"):
        seen: dict[bytes, int] = {}
        total = 0
        for i in range(n):
            tbl = getattr(cluster.fastpath[i], t)
            used = np.nonzero(tbl.used)[0]
            total += len(used)
            if not len(used):
                continue
            keys = tbl.keys[used]
            owners = np.asarray(shard_owner(
                [keys[:, k] for k in range(keys.shape[1])], n))
            for r in np.nonzero(owners != i)[0]:
                report.add("shard-misplaced-row",
                           f"fastpath.{t}/shard{i}",
                           f"key {keys[int(r)].tolist()} hashes to shard "
                           f"{int(owners[int(r)])} but is resident on "
                           f"shard {i}: the device lookup routes probes "
                           f"to the owner, so this row is unreachable")
            for r in range(len(keys)):
                kb = keys[r].tobytes()
                prev = seen.get(kb)
                if prev is not None and prev != i:
                    report.add("shard-double-owner", f"fastpath.{t}",
                               f"key {keys[r].tolist()} resident on "
                               f"shards {prev} AND {i}: two shards "
                               f"claim one subscriber row")
                else:
                    seen[kb] = i
        report.checks[f"shard_rows.{t}"] = total

    # -- chip-local state on the affinity shard
    for i in range(n):
        for side in ("up", "down"):
            host = getattr(cluster.qos[i], side)
            for s in np.nonzero((host.rows[:, _QF] & 1) != 0)[0]:
                ip = int(host.rows[int(s), _QK])
                o = cluster.affinity_shard_ip(ip)
                if o != i:
                    report.add("shard-misplaced-affinity",
                               f"qos.{side}/shard{i}",
                               f"{_ip(ip)} affinity shard is {o}; the "
                               f"ring never steers its traffic here")
        sp = cluster.spoof[i].bindings
        from bng_tpu.ops.antispoof import AB_IPV4, AB_VALIDS, VALID_V4

        for s in np.nonzero(sp.used)[0]:
            if not (int(sp.vals[int(s)][AB_VALIDS]) & VALID_V4):
                continue  # v6-only binding: no v4 affinity key
            ip = int(sp.vals[int(s)][AB_IPV4])
            o = cluster.affinity_shard_ip(ip)
            if o != i:
                report.add("shard-misplaced-affinity",
                           f"antispoof/shard{i}",
                           f"binding for {_ip(ip)} belongs on shard {o}")
        if cluster.garden is not None:
            gd = cluster.garden[i].subscribers
            for s in np.nonzero(gd.used)[0]:
                ip = int(gd.keys[int(s)][0])
                o = cluster.affinity_shard_ip(ip)
                if o != i:
                    report.add("shard-misplaced-affinity",
                               f"garden/shard{i}",
                               f"membership for {_ip(ip)} belongs on "
                               f"shard {o}")
        for priv in cluster.nat[i].blocks:
            o = cluster.affinity_shard_ip(int(priv))
            if o != i:
                report.add("shard-misplaced-affinity",
                           f"nat/shard{i}",
                           f"port block for {_ip(int(priv))} belongs on "
                           f"shard {o}")
        if cluster.edge is not None:
            for t in ("tap", "route"):
                for ip, _row in getattr(cluster.edge[i], f"{t}_rows")():
                    o = cluster.affinity_shard_ip(int(ip))
                    if o != i:
                        report.add("shard-misplaced-affinity",
                                   f"edge.{t}/shard{i}",
                                   f"{t} row for {_ip(int(ip))} belongs "
                                   f"on shard {o}; the ring never "
                                   f"steers its traffic here")

    # -- NAT public-IP exclusivity (downstream steering is by-IP)
    try:
        report.checks["shard_pub_ips"] = len(cluster.pub_ip_map())
    except ValueError as e:
        report.add("shard-pub-ip-conflict", "nat", str(e))

    # -- shard rows sum to the global lease authority
    if dhcp is not None:
        report.checks["shard_leases"] = len(dhcp.leases)
        for mac_u64 in dhcp.leases:
            o = cluster.dhcp_sub_shard(int(mac_u64))
            if cluster.fastpath[o].get_subscriber(int(mac_u64)) is None:
                lease = dhcp.leases[mac_u64]
                report.add("shard-lease-unbacked", f"shard{o}",
                           f"lease {lease.mac.hex()} -> {_ip(lease.ip)} "
                           f"has no subscriber row on its owner shard")

    # -- per-shard host == device mirror (after draining pending deltas)
    if cluster.tables is None:
        return
    B = cluster.n * cluster.b
    # pkt slot must cover the DHCP canon region even for all-idle lanes
    # (the program's shapes are static)
    zero_pkt = np.zeros((B, 512), dtype=np.uint8)
    zero_len = np.zeros((B,), dtype=np.uint32)
    zero_fa = np.zeros((B,), dtype=bool)
    steps = 0
    while cluster.pending_dirty() > 0 and steps < max_drain_steps:
        # an empty sharded step still runs the bounded update drain
        # (deterministic at now=0: zero-length lanes are not real, so
        # no verdict/stat depends on the clock)
        cluster.step(zero_pkt, zero_len, zero_fa, 0, 0)
        steps += 1
    if cluster.pending_dirty() > 0:
        report.add("mirror-undrained", "sharded",
                   f"{cluster.pending_dirty()} dirty slots after "
                   f"{steps} drain steps")
        return
    cluster.quiesce()
    report.checks["shard_mirror_drain_steps"] = steps
    dev = cluster.tables
    for i in range(n):
        for t in ("sub", "vlan", "cid"):
            dt = getattr(dev.dhcp, t)
            _table_mirror_findings(
                report, getattr(cluster.fastpath[i], t),
                TableState(krows=np.asarray(dt.krows)[i],
                           stash_rows=np.asarray(dt.stash_rows)[i],
                           vals=np.asarray(dt.vals)[i]),
                f"shard{i}.fastpath.{t}")
        if not np.array_equal(cluster.fastpath[i].pools,
                              np.asarray(dev.dhcp.pools)[i]):
            report.add("mirror-mismatch", f"shard{i}.fastpath.pools",
                       "device pool config differs from host")
        if cluster.edge is not None and dev.tap is not None:
            for t, dt in (("tap", dev.tap), ("route", dev.route)):
                _table_mirror_findings(
                    report, getattr(cluster.edge[i], t),
                    TableState(krows=np.asarray(dt.krows)[i],
                               stash_rows=np.asarray(dt.stash_rows)[i],
                               vals=np.asarray(dt.vals)[i]),
                    f"shard{i}.edge.{t}")
            if not np.array_equal(cluster.edge[i].tap_filters,
                                  np.asarray(dev.tap_filters)[i]):
                report.add("mirror-mismatch",
                           f"shard{i}.edge.tap_filters",
                           "device filter rows differ from host")
            if not np.array_equal(cluster.edge[i].tap_config,
                                  np.asarray(dev.tap_config)[i]):
                report.add("mirror-mismatch",
                           f"shard{i}.edge.tap_config",
                           "device armed predicate differs from host")


def audit_invariants(*, engine=None, scheduler=None, fastpath=None,
                     pools=None, dhcp=None, fleet=None, nat=None,
                     dhcpv6=None, pppoe=None, edge=None, tap_program=None,
                     route_program=None, cluster=None,
                     bng_cluster=None,
                     ha_pair=None, quiesce=True, check_roundtrip=True,
                     metrics=None, epoch=None) -> AuditReport:
    """Run every applicable invariant over the components given.

    With an `engine`, runs at the same drain barrier checkpoints use
    (scheduler.quiesce() when a scheduler owns the loop, else
    engine.quiesce()) and includes the host-vs-device mirror proof;
    fastpath/nat default from the engine. `ha_pair=(active, standby)`
    adds the replication-divergence check. `metrics` (BNGMetrics) gets
    the bng_invariant_* families recorded; `epoch` stamps
    bng_invariant_last_audit_epoch (defaults to the audit counter).
    """
    report = AuditReport()
    if engine is not None:
        if quiesce:
            if scheduler is not None:
                scheduler.quiesce()
            else:
                engine.quiesce()
        fastpath = fastpath if fastpath is not None else engine.fastpath
        nat = nat if nat is not None else engine.nat
    if cluster is not None:
        if quiesce:
            cluster.quiesce()
        _audit_sharded(report, cluster, dhcp=dhcp)
        # each shard's NAT authority must be internally consistent too
        # (allocator/EIM/session/reverse mutual consistency, per shard)
        if nat is None:
            for _i in range(cluster.n):
                _audit_nat(report, cluster.nat[_i])

    # ONE fleet-book snapshot (one export IPC round-trip in process
    # mode) shared by the ownership and fastpath-row checks, so both
    # reason about the same consistent cut
    books = _fleet_worker_books(fleet)
    _audit_ownership(report, pools, dhcp, fleet, books)
    _audit_fastpath_rows(report, fastpath, dhcp, fleet, books)
    _audit_device_mirror(report, engine)
    _audit_nat(report, nat)
    _audit_dhcpv6(report, dhcpv6)
    _audit_pppoe(report, pppoe, pools)
    if edge is None and engine is not None:
        edge = getattr(engine, "edge", None)
    if edge is None and cluster is not None \
            and getattr(cluster, "edge", None) is not None:
        # the merged owner-routed surface IS the cluster audit surface
        edge = cluster
    _audit_edge(report, edge, tap_program, route_program)
    if check_roundtrip:
        active = None
        if ha_pair is not None:
            active = ha_pair[0]
        _audit_checkpoint_roundtrip(report, fastpath=fastpath, nat=nat,
                                    dhcp=dhcp, fleet=fleet, ha=active)
    if ha_pair is not None:
        _audit_ha_pair(report, *ha_pair)
    if bng_cluster is not None:
        _audit_cluster(report, bng_cluster)

    if metrics is not None:
        metrics.record_audit(report, epoch=epoch)
    if not report.ok:
        # flight-recorder anomaly hook (telemetry/recorder.py): an
        # invariant violation must leave the last-N batch evidence on
        # disk the moment it is proven, not at run end. Disarmed: one
        # global load + None compare.
        from bng_tpu.telemetry import spans as _tele

        _tele.trigger("invariant_violation",
                      str(report.violations_by_kind()))
    return report


def _audit_ha_pair(report: AuditReport, active, standby) -> None:
    """A CONNECTED standby must mirror the active's session store
    exactly (a disconnected one is allowed to lag — reconnect heals via
    replay_since/full_sync)."""
    if active is None or standby is None or not getattr(
            standby, "connected", False):
        return
    a = {s.session_id: s.to_dict() for s in active.store.all()}
    b = {s.session_id: s.to_dict() for s in standby.store.all()}
    report.checks["ha_sessions"] = len(a)
    for sid in sorted(set(a) | set(b)):
        if sid not in a:
            report.add("ha-store-divergence", sid,
                       "standby holds a session the active deleted")
        elif sid not in b:
            report.add("ha-store-divergence", sid,
                       "connected standby is missing an active session")
        elif a[sid] != b[sid]:
            report.add("ha-store-divergence", sid,
                       "session state differs between active and standby")


def _audit_cluster(report: AuditReport, coord) -> None:
    """Cluster-of-BNGs cross-authority clauses (the DESTINI "no IP owned
    by two" one level up from the fleet's worker audit):

    - the carve PLAN partitions the space: every block assigned to
      exactly one member or free, geometry matching the split;
    - every built instance's pools match its planned carve exactly
      (carve ⊆ plan, block-for-block);
    - no lease IP outside its owner's carve, and no IP (or subscriber
      MAC) held by two instances at once;
    - every held lease's MAC steers to the instance holding it — the
      front door and the books agree on placement;
    - each member's HA pair mirrors exactly while connected (the
      existing divergence clause, per member).

    Lease-book checks need inline instances (process members export
    through their own checkpoints); the plan checks always run.
    """
    from bng_tpu.cluster.plan import instance_for_mac

    plan = coord.plan
    if plan is None:
        if coord.members:
            report.add("cluster-no-plan", "plan",
                       f"{len(coord.members)} member(s) but no carve plan")
        return
    report.checks["cluster_members"] = len(plan.members)

    # -- plan partitions the space ----------------------------------------
    block_size = 1 << (32 - plan.block_prefix_len)
    seen_idx: dict[int, str] = {}
    for owner, blocks in ([(iid, p.blocks)
                           for iid, p in sorted(plan.members.items())]
                          + [("<free>", plan.free)]):
        for b in blocks:
            if b.index in seen_idx:
                report.add("cluster-plan-overlap", f"block{b.index}",
                           f"assigned to both {seen_idx[b.index]} "
                           f"and {owner}")
            seen_idx[b.index] = owner
            want_net = plan.space_network + b.index * block_size
            if (b.prefix_len != plan.block_prefix_len
                    or b.network != want_net):
                report.add("cluster-plan-alien-block",
                           f"{owner}/block{b.index}",
                           f"{_ip(b.network)}/{b.prefix_len} is not "
                           f"slice {b.index} of the cluster space")
    for idx in range(plan.n_blocks):
        if idx not in seen_idx:
            report.add("cluster-plan-overlap", f"block{idx}",
                       "slice of the cluster space is unaccounted for")
    if plan.nat_total > 0:
        per = plan.nat_total // plan.n_blocks
        for iid, p in sorted(plan.members.items()):
            for b in p.blocks:
                start, count = plan.nat_range(b)
                if count != per or start != plan.nat_base + b.index * per:
                    report.add("cluster-plan-alien-block",
                               f"{iid}/nat{b.index}",
                               "NAT slice does not ride its block index")

    # -- carve ⊆ plan + cross-instance ownership --------------------------
    ids = plan.serving_ids()
    ip_owner: dict[int, str] = {}
    mac_owner: dict[bytes, str] = {}
    n_leases = 0
    for iid, m in sorted(coord.members.items()):
        inst = m.instance
        if inst is None or not hasattr(inst, "fleet"):
            continue
        iplan = plan.members.get(iid)
        if iplan is None:
            report.add("cluster-carve-mismatch", iid,
                       "instance built but absent from the plan")
            continue
        want = sorted((b.network, b.prefix_len) for b in iplan.blocks)
        got = sorted((p.network, p.prefix_len)
                     for p in inst.pools.pools.values())
        if want != got:
            report.add("cluster-carve-mismatch", iid,
                       f"pools {got} differ from planned carve {want}")
        for _w, book in _fleet_worker_books(inst.fleet):
            for lease in book.values():
                n_leases += 1
                if not iplan.contains(lease.ip):
                    report.add("cluster-foreign-ip",
                               f"{iid}/{_ip(lease.ip)}",
                               "lease outside the instance's carve")
                prev = ip_owner.get(lease.ip)
                if prev is not None and prev != iid:
                    report.add("cluster-double-ownership", _ip(lease.ip),
                               f"held by both {prev} and {iid}")
                ip_owner[lease.ip] = iid
                prevm = mac_owner.get(lease.mac)
                if prevm is not None and prevm != iid:
                    report.add("cluster-double-ownership",
                               lease.mac.hex(),
                               f"subscriber leased on both {prevm} "
                               f"and {iid}")
                mac_owner[lease.mac] = iid
                steer = instance_for_mac(lease.mac, ids)
                if steer != iid:
                    report.add("cluster-missteer",
                               f"{iid}/{lease.mac.hex()}",
                               f"front door steers this MAC to {steer}")
    report.checks["cluster_leases"] = n_leases

    # -- HA pair equality per member --------------------------------------
    for _iid, m in sorted(coord.members.items()):
        if m.syncer is not None and m.standby is not None:
            _audit_ha_pair(report, m.syncer, m.standby)


def audit_app(app, metrics=None, epoch=None) -> AuditReport:
    """Audit a composed BNGApp (the `bng chaos audit` /
    `bng checkpoint restore --audit` entry): pulls the live components
    out of the composition root and runs the full invariant set."""
    c = app.components
    return audit_invariants(
        engine=c.get("engine"), scheduler=c.get("scheduler"),
        fastpath=c.get("fastpath"), pools=c.get("pools"),
        dhcp=c.get("dhcp"), fleet=c.get("fleet"), nat=c.get("nat"),
        dhcpv6=c.get("dhcpv6"), pppoe=c.get("pppoe"),
        cluster=c.get("cluster"),
        metrics=metrics if metrics is not None else c.get("metrics"),
        epoch=epoch)
