"""Scripted chaos scenarios — deterministic, device-free, audited.

Every scenario is a pure function `(seed) -> dict`: it builds its own
small component stack (inline fleet / parent server / NAT manager / HA
pair) on a `SimClock`, arms a pinned `FaultPlan`, drives real protocol
traffic through the real code paths, and finishes with a cross-authority
invariant audit. The contract the suite enforces:

    faults may degrade SERVICE (lost DORAs, shed frames, late replies)
    but never CONSISTENCY (the closing audit must be clean).

Reports contain no wallclock, no filesystem paths and no object ids —
two runs with the same seed emit byte-identical JSON (the
`bng chaos run --seed S` acceptance gate).

Scenario list:

    dora_worker_crash         kill a fleet worker at every scatter hit
                              (plus a fault-free control sweep)
    corrupt_restore_cold_start truncation/bit-flip/io-error on the
                              checkpoint write+read paths: reject, fall
                              back to the previous good file, cold-start
                              semantics, then a clean restore
    fleet_reshard_under_kill  kill a worker mid-traffic, checkpoint the
                              books, restore onto a smaller fleet
    nat_expiry_under_skew     forward/backward clock skew over the NAT
                              expiry sweep; EIM/reverse/block bookkeeping
                              must survive both directions
    ha_delta_drop_reconnect   replication stream dies mid-delta + peer
                              timeout on reconnect; replay_since heals
    fleet_resize_under_kill   LIVE resize (shrink + grow) with a worker
                              killed at every transfer hit; in-flight
                              DORAs (un-ACKed OFFERs) must complete on
                              the new owners, zero drops
    rolling_restart_under_kill rolling worker replacement with a kill at
                              every rotation hit; books+offers+slices
                              move verbatim, the dead shard heals
    engine_swap_crash_rollback blue/green engine swap: clean flip serves
                              renewals on-device from the hydrated
                              standby; crash-mid-swap and snapshot
                              io_error roll back with the active
                              untouched
    intercept_tap_live        warrant-compiled taps mirror on the live
                              sharded serving path, filter at the
                              device, and provably reap on expiry
    route_flap_rewrite        next-hop rewrite rides a link flap as
                              bounded dirty-slot deltas; traffic
                              re-forwards via the survivor
    devloop_storm             express OFFER storm through the device-
                              resident serving loop against a saturated
                              bulk lane, with a mid-storm injected
                              megakernel dispatch failure; reply bytes
                              must match a fault-free control sweep and
                              the ring cursor audit must close clean
    cluster_partial_partition sever exactly the a<->b fabric link while
                              both still reach c (NEAT): mutual
                              suspicion but no accusation quorum, so no
                              demotion, no failover, no double-carve
    cluster_gray_member       a member beats perfectly but its serving
                              word stalls: GRAY verdict off its own
                              signed beats, standby promotes, the
                              flash crowd re-DORAs sticky
"""

from __future__ import annotations

import random

import numpy as np

from bng_tpu.chaos.faults import (BITFLIP, DROP_DELTA, FAIL, IO_ERROR, KILL,
                                  SKEW, TRUNCATE, FaultPlan, FaultSpec,
                                  SimClock, armed)
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.pool import Pool, PoolManager
from bng_tpu.utils.net import ip_to_u32

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")


# ---------------------------------------------------------------------------
# shared builders (geometry matches tests/test_fleet.py so a test session
# never compiles anything extra for chaos)
# ---------------------------------------------------------------------------

def _mac(i: int) -> bytes:
    return (0x02C5 << 32 | i).to_bytes(6, "big")


def _discover(mac: bytes, xid: int) -> bytes:
    p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def _request(mac: bytes, ip: int, xid: int) -> bytes:
    p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid,
                                 requested_ip=ip, server_id=SERVER_IP)
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def _renew(mac: bytes, ip: int, xid: int) -> bytes:
    p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid, ciaddr=ip)
    return packets.udp_packet(mac, b"\xff" * 6, ip, SERVER_IP, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def _release(mac: bytes, ip: int, xid: int) -> bytes:
    p = dhcp_codec.build_request(mac, dhcp_codec.RELEASE, xid=xid, ciaddr=ip)
    return packets.udp_packet(mac, b"\xff" * 6, ip, SERVER_IP, 68, 67,
                              p.encode().ljust(300, b"\x00"))


def _reply(frame: bytes) -> dhcp_codec.DHCPPacket:
    return dhcp_codec.decode(packets.decode(frame).payload)


def _make_fastpath():
    from bng_tpu.runtime.tables import FastPathTables

    fp = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64, cid_nbuckets=64,
                        max_pools=16)
    fp.set_server_config(SERVER_MAC, SERVER_IP)
    return fp


def _make_pools(fastpath=None, cidr_net: str = "10.0.0.0",
                prefix_len: int = 20):
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32(cidr_net),
                        prefix_len=prefix_len, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    return pools


def build_fleet(n_workers: int, clock, slice_size: int = 64):
    """Inline fleet + parent pools + host fast-path tables — the
    deterministic stack every fleet scenario runs on."""
    from bng_tpu.control.fleet import FleetSpec, SlowPathFleet

    fastpath = _make_fastpath()
    pools = _make_pools(fastpath)
    spec = FleetSpec.from_pool_manager(SERVER_MAC, SERVER_IP, pools,
                                       slice_size=slice_size,
                                       low_watermark=max(1, slice_size // 4))
    fleet = SlowPathFleet(spec, n_workers, pools, mode="inline",
                          table_sink=fastpath, clock=clock)
    return fleet, pools, fastpath


def dora_with_retries(fleet, macs, clock, rounds: int = 6) -> dict:
    """Drive each MAC through DORA, retransmitting lost exchanges once
    per round (the client-retry behavior every fault scenario leans on).
    Returns {mac: leased_ip}."""
    offers: dict[bytes, int] = {}
    leased: dict[bytes, int] = {}
    xid = 1
    for _ in range(rounds):
        batch, batch_macs = [], []
        for m in macs:
            if m in leased:
                continue
            if m in offers:
                batch.append((len(batch), _request(m, offers[m], xid)))
            else:
                batch.append((len(batch), _discover(m, xid)))
            batch_macs.append(m)
            xid += 1
        if not batch:
            break
        out = fleet.handle_batch(batch, now=clock())
        for (_lane, rep), m in zip(out, batch_macs):
            if rep is None:
                continue
            p = _reply(rep)
            if p.msg_type == dhcp_codec.OFFER:
                offers[m] = p.yiaddr
            elif p.msg_type == dhcp_codec.ACK:
                leased[m] = p.yiaddr
            elif p.msg_type == dhcp_codec.NAK:
                offers.pop(m, None)
        clock.advance(1.0)
    return leased


# ---------------------------------------------------------------------------
# 1. DORA under worker crash, killed at every fault-point hit
# ---------------------------------------------------------------------------

def dora_worker_crash(seed: int) -> dict:
    """Sweep the kill fault across scatter hits 0 (control: no fault)
    through 6. Each killed shard loses service — clients retransmit,
    survivors complete — but every sweep must audit clean."""
    n_macs, workers = 12, 3
    macs = [_mac((seed % 97) * 100 + i) for i in range(n_macs)]
    sweeps = []
    for hit in range(0, 7):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(workers, clock)
        specs = ([] if hit == 0
                 else [FaultSpec("fleet.scatter", KILL, at_hit=hit)])
        with armed(FaultPlan(seed=seed, specs=specs), log=False) as inj:
            leased = dora_with_retries(fleet, macs, clock)
        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath)
        sweeps.append({
            "kill_at_hit": hit,
            "leased": len(leased),
            "unique_ips": len(set(leased.values())),
            "faults": len(inj.injected),
            "worker_failures": fleet.worker_failures,
            "audit_ok": audit.ok,
            "violations": audit.violations_by_kind(),
        })
    control = sweeps[0]
    ok = (all(s["audit_ok"] for s in sweeps)
          and control["leased"] == n_macs
          and all(s["unique_ips"] == s["leased"] for s in sweeps)
          and any(s["faults"] for s in sweeps[1:]))
    return {"name": "dora_worker_crash", "seed": seed, "ok": ok,
            "sweeps": sweeps}


# ---------------------------------------------------------------------------
# 2. corrupt restore -> reject -> fall back / cold start -> clean restore
# ---------------------------------------------------------------------------

def _build_server_stack(clock):
    """Parent-only stack (no fleet): DHCP server + pools + fast path +
    NAT, the single-worker authority set."""
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.nat import NATManager

    fastpath = _make_fastpath()
    pools = _make_pools(fastpath)
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     ports_per_subscriber=64,
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                        fastpath_tables=fastpath,
                        nat_hook=lambda ip, now: nat.allocate_nat(ip,
                                                                  int(now)),
                        clock=clock)
    return server, pools, fastpath, nat


def _dora_server(server, macs) -> dict:
    leased = {}
    for i, m in enumerate(macs):
        off = server.handle_frame(_discover(m, 1000 + i))
        ip = _reply(off).yiaddr
        ack = server.handle_frame(_request(m, ip, 2000 + i))
        assert _reply(ack).msg_type == dhcp_codec.ACK
        leased[m] = ip
    return leased


def corrupt_restore_cold_start(seed: int) -> dict:
    """A corrupt snapshot must never silently serve traffic: write-side
    truncation lands a bad file that load_latest skips in favor of the
    previous good one; read-side bit-flips reject at decode; io_error
    surfaces; and the good checkpoint restores state-identical into a
    fresh (cold-started) stack that audits clean."""
    import tempfile

    from bng_tpu.control.statestore import CheckpointStore
    from bng_tpu.runtime.checkpoint import (CheckpointError,
                                            build_checkpoint,
                                            restore_checkpoint)

    clock = SimClock()
    server, pools, fastpath, nat = _build_server_stack(clock)
    macs = [_mac((seed % 89) * 100 + i) for i in range(8)]
    leased = _dora_server(server, macs)

    out = {"name": "corrupt_restore_cold_start", "seed": seed}
    with tempfile.TemporaryDirectory() as td:
        store = CheckpointStore(td)
        good = store.save(build_checkpoint(
            store.next_seq(), clock(), fastpath=fastpath, nat=nat,
            dhcp=server, node_id="chaos"))

        # 1. write-side truncation: the NEWER file on disk is corrupt
        plan = FaultPlan(seed, [
            FaultSpec("ckpt.write", TRUNCATE, at_hit=1, arg=97.0)])
        with armed(plan, log=False):
            bad = store.save(build_checkpoint(
                store.next_seq(), clock.advance(10.0), fastpath=fastpath,
                nat=nat, dhcp=server, node_id="chaos"))
        try:
            store.load(bad)
            out["truncated_rejected"] = False
        except CheckpointError:
            out["truncated_rejected"] = True
        ckpt, path = store.load_latest()
        out["fallback_to_good"] = (str(path) == str(good)
                                   and ckpt.seq == 1)

        # 2. read-side bit flip: a good file corrupted in transit rejects
        plan = FaultPlan(seed, [
            FaultSpec("ckpt.read", BITFLIP, at_hit=1,
                      arg=float(101 + seed % 997))])
        with armed(plan, log=False):
            try:
                store.load(good)
                out["bitflip_rejected"] = False
            except CheckpointError:
                out["bitflip_rejected"] = True

        # 3. io_error on save surfaces (the PeriodicCheckpointer failure
        # counter path) instead of landing a half-written file
        plan = FaultPlan(seed, [FaultSpec("ckpt.write", IO_ERROR)])
        with armed(plan, log=False):
            try:
                store.save(build_checkpoint(
                    store.next_seq(), clock(), dhcp=server,
                    node_id="chaos"))
                out["io_error_surfaced"] = False
            except OSError:
                out["io_error_surfaced"] = True
        out["files_on_disk"] = len(store.list())

        # 4. the good checkpoint restores into a FRESH stack (the warm
        # path a clean restart takes; a rejected one cold-starts empty)
        clock2 = SimClock()
        server2, pools2, fastpath2, nat2 = _build_server_stack(clock2)
        rows = restore_checkpoint(ckpt, fastpath=fastpath2, nat=nat2,
                                  dhcp=server2)
        out["restored_leases"] = rows.get("dhcp.leases", 0)
        renew_ok = 0
        for i, m in enumerate(macs):
            ack = server2.handle_frame(_renew(m, leased[m], 3000 + i))
            if ack is not None and _reply(ack).msg_type == dhcp_codec.ACK \
                    and _reply(ack).yiaddr == leased[m]:
                renew_ok += 1
        out["renewed_after_restore"] = renew_ok
        audit = audit_invariants(pools=pools2, dhcp=server2,
                                 fastpath=fastpath2, nat=nat2)
        out["audit_ok"] = audit.ok
        out["violations"] = audit.violations_by_kind()

    out["ok"] = (out["truncated_rejected"] and out["fallback_to_good"]
                 and out["bitflip_rejected"] and out["io_error_surfaced"]
                 and out["restored_leases"] == len(macs)
                 and out["renewed_after_restore"] == len(macs)
                 and out["audit_ok"])
    return out


# ---------------------------------------------------------------------------
# 3. fleet reshard under kill
# ---------------------------------------------------------------------------

def fleet_reshard_under_kill(seed: int) -> dict:
    """Kill a worker mid-traffic, checkpoint every lease book (the dead
    worker's included), restore onto a SMALLER fleet: the MAC hash
    re-shards every subscriber onto its new owner and renewals ACK the
    original addresses."""
    clock = SimClock()
    fleet, pools, fastpath = build_fleet(4, clock)
    macs = [_mac((seed % 83) * 100 + i) for i in range(24)]
    leased = dora_with_retries(fleet, macs, clock)
    out = {"name": "fleet_reshard_under_kill", "seed": seed,
           "leased_before": len(leased)}

    plan = FaultPlan(seed, [FaultSpec("fleet.scatter", KILL, at_hit=1)])
    with armed(plan, log=False) as inj:
        # renewal round under the kill: the dead shard's lanes are lost
        batch = [(i, _renew(m, leased[m], 5000 + i))
                 for i, m in enumerate(macs)]
        replies = fleet.handle_batch(batch, now=clock.advance(30.0))
    out["renew_lost_to_kill"] = sum(1 for _l, r in replies if r is None)
    out["faults"] = len(inj.injected)
    audit1 = audit_invariants(pools=pools, fleet=fleet, fastpath=fastpath)
    out["audit_after_kill_ok"] = audit1.ok

    state = fleet.export_state()  # inline books: dead worker's included
    clock2 = SimClock(clock())
    fleet2, pools2, fastpath2 = build_fleet(3, clock2)
    restored = fleet2.restore_state(state)
    out["restored"] = restored

    renew_ok = 0
    out2 = fleet2.handle_batch(
        [(i, _renew(m, leased[m], 6000 + i)) for i, m in enumerate(macs)],
        now=clock2.advance(30.0))
    for (_lane, rep), m in zip(out2, macs):
        if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK \
                and _reply(rep).yiaddr == leased[m]:
            renew_ok += 1
    out["renewed_after_reshard"] = renew_ok
    audit2 = audit_invariants(pools=pools2, fleet=fleet2,
                              fastpath=fastpath2)
    out["audit_ok"] = audit2.ok
    out["violations"] = audit2.violations_by_kind()
    out["ok"] = (out["leased_before"] == len(macs)
                 and out["faults"] >= 1
                 and out["renew_lost_to_kill"] >= 1
                 and out["audit_after_kill_ok"]
                 and restored == len(macs)
                 and renew_ok == len(macs)
                 and audit2.ok)
    return out


# ---------------------------------------------------------------------------
# 4. NAT expiry under clock skew
# ---------------------------------------------------------------------------

def nat_expiry_under_skew(seed: int) -> dict:
    """Forward skew mass-expires sessions; backward skew must expire
    nothing; both directions must leave the allocator/EIM/session/
    reverse bookkeeping mutually consistent and the port blocks
    reusable."""
    from bng_tpu.control.nat import NATManager
    from bng_tpu.ops.parse import PROTO_UDP

    clock = SimClock()
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1"),
                                 ip_to_u32("203.0.113.2")],
                     ports_per_subscriber=64,
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    rng = random.Random(seed)
    subs = [ip_to_u32("10.1.0.10") + i for i in range(8)]
    for s in subs:
        nat.allocate_nat(s, int(clock()))

    def make_flows(tag: int) -> int:
        n = 0
        for s in subs:
            base_port = 5000 + (tag * 16) + rng.randrange(0, 4)
            dsts = [ip_to_u32("93.184.216.34"), ip_to_u32("1.1.1.1")]
            # two flows share one internal endpoint (EIM refcount 2),
            # a third uses its own port
            for dst, dport in ((dsts[0], 80), (dsts[1], 443)):
                if nat.handle_new_flow(s, dst, base_port, dport,
                                       PROTO_UDP, 128, int(clock())):
                    n += 1
            if nat.handle_new_flow(s, dsts[0], base_port + 1000 + tag, 80,
                                   PROTO_UDP, 128, int(clock())):
                n += 1
        return n

    out = {"name": "nat_expiry_under_skew", "seed": seed}
    out["flows_created"] = make_flows(0)
    out["audit_fresh_ok"] = audit_invariants(nat=nat,
                                             check_roundtrip=False).ok

    # forward skew: every UDP session is idle far past its timeout
    with armed(FaultPlan(seed, [
            FaultSpec("nat.expire", SKEW, at_hit=1, arg=7200.0)]),
            log=False):
        out["expired_forward"] = nat.expire_sessions(int(clock()))
    audit_f = audit_invariants(nat=nat, check_roundtrip=False)
    out["audit_forward_ok"] = audit_f.ok
    out["sessions_after_forward"] = int(np.count_nonzero(nat.sessions.used))

    # recreate on the freed ports — the blocks must be reusable
    out["flows_recreated"] = make_flows(1)
    # backward skew: (now - last_seen) goes negative, nothing may expire
    with armed(FaultPlan(seed, [
            FaultSpec("nat.expire", SKEW, at_hit=1, arg=-7200.0)]),
            log=False):
        out["expired_backward"] = nat.expire_sessions(
            int(clock.advance(30.0)))
    audit_b = audit_invariants(nat=nat, check_roundtrip=False)
    out["audit_ok"] = audit_b.ok
    out["violations"] = audit_b.violations_by_kind()

    out["ok"] = (out["flows_created"] == 24
                 and out["audit_fresh_ok"]
                 and out["expired_forward"] == 24
                 and out["sessions_after_forward"] == 0
                 and out["audit_forward_ok"]
                 and out["flows_recreated"] == 24
                 and out["expired_backward"] == 0
                 and out["audit_ok"])
    return out


# ---------------------------------------------------------------------------
# 5. HA replication: stream death mid-delta + peer timeout on reconnect
# ---------------------------------------------------------------------------

def ha_delta_drop_reconnect(seed: int) -> dict:
    """The replication stream dies mid-delta (drop_delta kills every
    subscriber callback, exactly like an SSE connection breaking), then
    the first reconnect attempt times out (ha.connect fail -> backoff).
    The second reconnect heals via replay_since with zero full syncs —
    and the stores must end identical."""
    from bng_tpu.control.ha import (ActiveSyncer, InMemorySessionStore,
                                    SessionState, StandbySyncer)

    clock = SimClock()
    active = ActiveSyncer(InMemorySessionStore(), replay_buffer=64)
    standby = StandbySyncer(InMemorySessionStore(),
                            transport=lambda: active,
                            backoff_initial_s=1.0)
    standby.tick(clock())
    out = {"name": "ha_delta_drop_reconnect", "seed": seed,
           "connected_initially": standby.connected}

    def push(i: int) -> None:
        active.push_change(SessionState(
            session_id=f"s-{i:04d}", mac=_mac(i).hex(),
            ip=ip_to_u32("10.2.0.1") + i, lease_expiry=clock() + 3600,
            updated_at=clock()))

    for i in range(6):
        push(i)
    out["delivered_before_fault"] = standby.last_seq

    plan = FaultPlan(seed, [
        # hits count from arming: the 2nd armed push (session seq 8)
        # dies mid-delivery; seq 7 lands, 8-12 reach only the replay log
        FaultSpec("ha.push", DROP_DELTA, at_hit=2),
        # the standby's FIRST reconnect attempt times out
        FaultSpec("ha.connect", FAIL, at_hit=1)])
    with armed(plan, log=False) as inj:
        for i in range(6, 12):
            push(i)
        out["standby_seq_after_drop"] = standby.last_seq
        # the broken stream is observed (no subscriber left on the
        # active — the on_stream_end role) and the standby reconnects
        out["stream_died"] = not active._subscribers
        if out["stream_died"]:
            standby.disconnect()
        standby.tick(clock.advance(1.0))  # injected peer timeout
        out["first_reconnect_failed"] = not standby.connected
        standby.tick(clock.advance(5.0))  # backoff elapsed: heals
    out["faults"] = len(inj.injected)
    out["healed"] = (standby.connected
                     and standby.last_seq == active._seq)
    out["full_syncs_during_heal"] = standby.stats["full_syncs"] - 1
    audit = audit_invariants(ha_pair=(active, standby),
                             check_roundtrip=False)
    out["audit_ok"] = audit.ok
    out["violations"] = audit.violations_by_kind()
    out["ok"] = (out["connected_initially"]
                 and out["delivered_before_fault"] == 6
                 and out["stream_died"]
                 and out["standby_seq_after_drop"] == 7
                 and out["first_reconnect_failed"]
                 and out["healed"]
                 and out["full_syncs_during_heal"] == 0
                 and out["audit_ok"])
    return out


# ---------------------------------------------------------------------------
# 6. LIVE fleet resize under kill — the zero-downtime elasticity proof
# ---------------------------------------------------------------------------

def _start_inflight(fleet, clock, macs) -> dict:
    """Open an in-flight DORA per MAC (DISCOVER only) -> {mac: offered
    ip}. These are the exchanges a transition must NOT drop."""
    out = fleet.handle_batch(
        [(i, _discover(m, 0x5000 + i)) for i, m in enumerate(macs)],
        now=clock())
    offers = {}
    for (_lane, rep), m in zip(out, macs):
        if rep is not None and _reply(rep).msg_type == dhcp_codec.OFFER:
            offers[m] = _reply(rep).yiaddr
    return offers


def _complete_inflight(fleet, clock, offers) -> int:
    """REQUEST each outstanding OFFER; count ACKs of the OFFERED ip."""
    macs = sorted(offers)
    out = fleet.handle_batch(
        [(i, _request(m, offers[m], 0x6000 + i))
         for i, m in enumerate(macs)], now=clock())
    done = 0
    for (_lane, rep), m in zip(out, macs):
        if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK \
                and _reply(rep).yiaddr == offers[m]:
            done += 1
    return done


def _renew_all(fleet, clock, leased) -> int:
    macs = sorted(leased)
    out = fleet.handle_batch(
        [(i, _renew(m, leased[m], 0x7000 + i))
         for i, m in enumerate(macs)], now=clock.advance(30.0))
    return sum(1 for (_l, rep), m in zip(out, macs)
               if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
               and _reply(rep).yiaddr == leased[m])


def fleet_resize_under_kill(seed: int) -> dict:
    """Sweep the kill fault across fleet.resize transfer hits 0 (control)
    through 4 on a 4->2 shrink, then grow 2->5 clean. The acceptance
    bar: ZERO dropped in-flight DORAs (every un-ACKed OFFER completes on
    its new owner), every lease renews its original address, and every
    audit is clean — kill included, because an inline worker's book
    survives its death and the transfer HEALS the shard."""
    n_macs, workers = 16, 4
    sweeps = []
    for hit in range(0, 5):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(workers, clock)
        macs = [_mac((seed % 79) * 100 + i) for i in range(n_macs)]
        leased = dora_with_retries(fleet, macs, clock)
        inflight = [_mac((seed % 79) * 100 + 500 + i) for i in range(4)]
        offers = _start_inflight(fleet, clock, inflight)
        specs = ([] if hit == 0
                 else [FaultSpec("fleet.resize", KILL, at_hit=hit)])
        with armed(FaultPlan(seed=seed, specs=specs), log=False) as inj:
            rep = fleet.resize(2)
        sweep = {
            "kill_at_hit": hit,
            "resize_outcome": rep["outcome"],
            "leases_moved": rep.get("leases_moved", 0),
            "offers_moved": rep.get("offers_moved", 0),
            "faults": len(inj.injected),
            "inflight_completed": _complete_inflight(fleet, clock, offers),
            "renewed": _renew_all(fleet, clock, leased),
        }
        # grow back past the original count — elasticity both ways
        rep2 = fleet.resize(5)
        sweep["grow_outcome"] = rep2["outcome"]
        sweep["renewed_after_grow"] = _renew_all(fleet, clock, leased)
        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath)
        sweep["audit_ok"] = audit.ok
        sweep["violations"] = audit.violations_by_kind()
        sweeps.append(sweep)
    ok = (all(s["audit_ok"] for s in sweeps)
          and all(s["resize_outcome"] == "ok"
                  and s["grow_outcome"] == "ok" for s in sweeps)
          and all(s["renewed"] == n_macs for s in sweeps)
          and all(s["renewed_after_grow"] == n_macs for s in sweeps)
          and all(s["inflight_completed"] == 4 for s in sweeps)
          and all(s["offers_moved"] == 4 for s in sweeps)
          and any(s["faults"] for s in sweeps[1:]))
    return {"name": "fleet_resize_under_kill", "seed": seed, "ok": ok,
            "sweeps": sweeps}


# ---------------------------------------------------------------------------
# 7. rolling worker restart under kill
# ---------------------------------------------------------------------------

def rolling_restart_under_kill(seed: int) -> dict:
    """Replace every worker one shard at a time with a kill injected at
    each rotation hit in turn. Books, un-ACKed OFFERs and granted slices
    move verbatim into the replacement (no re-shard: same slot, same
    MAC owner), so renewals and in-flight DORAs survive every sweep —
    and a killed shard comes back HEALED (its book was still knowable
    inline), which the report pins via the `healed` list."""
    n_macs, workers = 18, 3
    sweeps = []
    for hit in range(0, 4):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(workers, clock)
        macs = [_mac((seed % 71) * 100 + i) for i in range(n_macs)]
        leased = dora_with_retries(fleet, macs, clock)
        inflight = [_mac((seed % 71) * 100 + 600 + i) for i in range(3)]
        offers = _start_inflight(fleet, clock, inflight)
        specs = ([] if hit == 0
                 else [FaultSpec("fleet.restart", KILL, at_hit=hit)])
        with armed(FaultPlan(seed=seed, specs=specs), log=False) as inj:
            rep = fleet.rolling_restart()
        audit = audit_invariants(pools=pools, fleet=fleet,
                                 fastpath=fastpath)
        sweeps.append({
            "kill_at_hit": hit,
            "outcome": rep["outcome"],
            "replaced": len(rep.get("replaced", ())),
            "healed": len(rep.get("healed", ())),
            "lost": len(rep.get("lost", ())),
            "faults": len(inj.injected),
            "inflight_completed": _complete_inflight(fleet, clock, offers),
            "renewed": _renew_all(fleet, clock, leased),
            "audit_ok": audit.ok,
            "violations": audit.violations_by_kind(),
        })
    ok = (all(s["audit_ok"] for s in sweeps)
          and all(s["outcome"] == "ok" for s in sweeps)
          and all(s["renewed"] == n_macs for s in sweeps)
          and all(s["inflight_completed"] == 3 for s in sweeps)
          and all(s["lost"] == 0 for s in sweeps)
          and all(s["healed"] == 1 for s in sweeps[1:])
          and any(s["faults"] for s in sweeps[1:]))
    return {"name": "rolling_restart_under_kill", "seed": seed, "ok": ok,
            "sweeps": sweeps}


# ---------------------------------------------------------------------------
# 8. blue/green engine swap: clean flip + crash rollback + snapshot fault
# ---------------------------------------------------------------------------

def engine_swap_crash_rollback(seed: int) -> dict:
    """Three swaps on one live engine stack: (a) clean — the standby
    hydrates from the in-memory snapshot, audits clean, flips, and
    serves renewals ON DEVICE from the hydrated chain; (b) crash at the
    flip barrier (ops.swap fail) — rolled back, active untouched; (c)
    snapshot encode io_error — failed before a standby ever existed.
    After every failure the ACTIVE engine must still serve and audit
    clean (the rollback re-sync heals any consumed delta)."""
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.ops import blue_green_swap

    clock = SimClock()
    server, pools, fastpath, nat = _build_server_stack(clock)
    eng = Engine(fastpath, nat, batch_size=32,
                 slow_path=server.handle_frame, clock=clock)
    macs = [_mac((seed % 61) * 100 + i) for i in range(6)]
    leased = {}
    for i, m in enumerate(macs):
        out = eng.process([_discover(m, 0x800 + i)])
        off = (out["slow"] or out["tx"])[0][1]
        ip = _reply(off).yiaddr
        out = eng.process([_request(m, ip, 0x900 + i)])
        leased[m] = ip
    components = {"engine": eng, "pools": pools, "dhcp": server}
    out_rep: dict = {"name": "engine_swap_crash_rollback", "seed": seed,
                     "leased": len(leased)}

    def _renew_one(i: int) -> tuple[bool, str]:
        m = macs[i % len(macs)]
        res = components["engine"].process(
            [_renew(m, leased[m], 0xA00 + i)],
            now=clock.advance(30.0))
        path = "tx" if res["tx"] else "slow"
        rep = (res["tx"] or res["slow"])[0][1]
        ok = (rep is not None
              and _reply(rep).msg_type == dhcp_codec.ACK
              and _reply(rep).yiaddr == leased[m])
        return ok, path

    # (a) clean swap
    rep = blue_green_swap(components)
    out_rep["swap_outcome"] = rep["outcome"]
    out_rep["swap_audit_ok"] = rep.get("audit_ok", False)
    out_rep["swapped_engine"] = components["engine"] is not eng
    ok_renew, path = _renew_one(0)
    out_rep["renew_after_swap"] = ok_renew
    # the standby's device chain came from the snapshot: a renewal must
    # hit the device fast path, proving the hydration actually carried
    # the subscriber rows (a slow-path ACK would mask an empty chain)
    out_rep["renew_path_after_swap"] = path

    # (b) crash mid-swap -> rollback
    active = components["engine"]
    plan = FaultPlan(seed, [FaultSpec("ops.swap", FAIL, at_hit=1)])
    with armed(plan, log=False):
        rep_b = blue_green_swap(components)
    out_rep["crash_outcome"] = rep_b["outcome"]
    out_rep["crash_kept_active"] = components["engine"] is active
    out_rep["renew_after_crash"] = _renew_one(1)[0]

    # (c) io_error on the in-memory snapshot encode
    plan = FaultPlan(seed, [FaultSpec("ops.snapshot", IO_ERROR, at_hit=1)])
    with armed(plan, log=False):
        rep_c = blue_green_swap(components)
    out_rep["snapshot_fault_outcome"] = rep_c["outcome"]
    out_rep["renew_after_snapshot_fault"] = _renew_one(2)[0]

    audit = audit_invariants(engine=components["engine"], pools=pools,
                             dhcp=server, nat=nat)
    out_rep["audit_ok"] = audit.ok
    out_rep["violations"] = audit.violations_by_kind()
    out_rep["ok"] = (out_rep["swap_outcome"] == "ok"
                     and out_rep["swap_audit_ok"]
                     and out_rep["swapped_engine"]
                     and out_rep["renew_after_swap"]
                     and out_rep["renew_path_after_swap"] == "tx"
                     and out_rep["crash_outcome"] == "rolled_back"
                     and out_rep["crash_kept_active"]
                     and out_rep["renew_after_crash"]
                     and out_rep["snapshot_fault_outcome"] == "failed"
                     and out_rep["renew_after_snapshot_fault"]
                     and out_rep["audit_ok"])
    return out_rep


def sharded_swap_crash_rollback(seed: int) -> dict:
    """The ICI-sharded serving path's swap discipline (ISSUE 12): DORA
    through a 2-shard cluster's STEERED ring (ring-classified control
    batches on the sharded DHCP fast lane, slow-path misses answered by
    the host server writing rows to their OWNER shards), then (a) a
    clean sharded blue/green swap — standby hydrated from the in-memory
    sharded snapshot, partition-audited BEFORE the flip, renewals served
    ON DEVICE by the standby with zero missteers; (b) a chaos crash at
    the flip barrier (ops.swap fail) — the active cluster keeps serving,
    untouched; (c) an io_error on the snapshot encode — failed before a
    standby ever existed. Final cross-authority sharded audit clean."""
    import numpy as np

    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.parallel.sharded import ShardedCluster, ShardedFastPathSink
    from bng_tpu.runtime.ops import sharded_blue_green_swap
    from bng_tpu.utils.net import ip_to_u32, parse_mac

    clock = SimClock()
    server_mac = parse_mac("02:aa:bb:cc:dd:01")
    server_ip = ip_to_u32("10.0.0.1")
    cl = ShardedCluster(2, batch_per_shard=8, sub_nbuckets=64,
                        vlan_nbuckets=64, cid_nbuckets=64,
                        nat_sessions_nbuckets=64, qos_nbuckets=64,
                        spoof_nbuckets=64, garden_enabled=False)
    # resolver: post-swap DORA writes must land on the SERVING cluster
    cl_ref = {"cluster": cl}
    sink = ShardedFastPathSink(lambda: cl_ref["cluster"])
    sink.set_server_config(server_mac, server_ip)
    pools = _make_pools(sink)
    server = DHCPServer(server_mac, server_ip, pools,
                        fastpath_tables=sink, clock=clock)
    ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)

    def _drive(frame: bytes) -> bytes | None:
        """One frame through the steered ring; returns the reply frame
        (device TX or slow-path inject), if any."""
        assert ring.rx_push(frame, from_access=True)
        cl_ref["cluster"].process_ring(ring, int(clock()), 0,
                                       pkt_slot=2048,
                                       slow_path=server.handle_frame)
        got = ring.tx_pop()
        return got[0] if got is not None else None

    macs = [_mac((seed % 61) * 100 + i) for i in range(6)]
    cl_ref.update(pools=pools, dhcp=server)
    # DORA: DISCOVER punts to the host server (OFFER via TX inject),
    # REQUEST binds the lease; the sink lands each row on its owner
    for i, m in enumerate(macs):
        offer = _drive(_discover(m, 0x800 + i))
        assert offer is not None, "DORA discover went unanswered"
        ack = _drive(_request(m, _reply(offer).yiaddr, 0x900 + i))
        assert ack is not None and _reply(ack).msg_type == dhcp_codec.ACK

    def _renew_on_device(i: int) -> bool:
        """A cached DISCOVER must be answered BY THE MESH (verdict TX on
        the sharded DHCP fast lane), proving the serving cluster's
        device chain carries the subscriber rows."""
        m = macs[i % len(macs)]
        clock.advance(5.0)
        tx_before = cl_ref["cluster"].telemetry.verdicts[:, 2].sum()
        assert ring.rx_push(_discover(m, 0xA00 + i), from_access=True)
        cl_ref["cluster"].process_ring(ring, int(clock()), 0,
                                      pkt_slot=2048,
                                      slow_path=server.handle_frame)
        reply = ring.tx_pop()
        on_dev = (cl_ref["cluster"].telemetry.verdicts[:, 2].sum()
                  > tx_before)
        return bool(reply is not None and on_dev)

    out_rep: dict = {"name": "sharded_swap_crash_rollback", "seed": seed,
                     "leased": len(server.leases),
                     "renew_before_swap": _renew_on_device(0)}

    # (a) clean swap
    active = cl_ref["cluster"]
    rep = sharded_blue_green_swap(cl_ref, clock=clock)
    out_rep["swap_outcome"] = rep["outcome"]
    out_rep["swap_audit_ok"] = rep.get("audit_ok", False)
    out_rep["swapped_cluster"] = cl_ref["cluster"] is not active
    out_rep["renew_after_swap"] = _renew_on_device(1)

    # (b) crash at the flip barrier -> active keeps serving
    active = cl_ref["cluster"]
    plan = FaultPlan(seed, [FaultSpec("ops.swap", FAIL, at_hit=1)])
    with armed(plan, log=False):
        rep_b = sharded_blue_green_swap(cl_ref, clock=clock)
    out_rep["crash_outcome"] = rep_b["outcome"]
    out_rep["crash_kept_active"] = cl_ref["cluster"] is active
    out_rep["renew_after_crash"] = _renew_on_device(2)

    # (c) io_error on the snapshot encode
    plan = FaultPlan(seed, [FaultSpec("ops.snapshot", IO_ERROR, at_hit=1)])
    with armed(plan, log=False):
        rep_c = sharded_blue_green_swap(cl_ref, clock=clock)
    out_rep["snapshot_fault_outcome"] = rep_c["outcome"]
    out_rep["renew_after_snapshot_fault"] = _renew_on_device(3)

    audit = audit_invariants(cluster=cl_ref["cluster"], pools=pools,
                             dhcp=server, check_roundtrip=False)
    snap = cl_ref["cluster"].telemetry.snapshot()
    out_rep["missteers"] = int(snap["missteer_total"])
    out_rep["audit_ok"] = audit.ok
    out_rep["violations"] = audit.violations_by_kind()
    out_rep["ok"] = (out_rep["renew_before_swap"]
                     and out_rep["swap_outcome"] == "ok"
                     and out_rep["swap_audit_ok"]
                     and out_rep["swapped_cluster"]
                     and out_rep["renew_after_swap"]
                     and out_rep["crash_outcome"] == "failed"
                     and out_rep["crash_kept_active"]
                     and out_rep["renew_after_crash"]
                     and out_rep["snapshot_fault_outcome"] == "failed"
                     and out_rep["renew_after_snapshot_fault"]
                     and out_rep["missteers"] == 0
                     and out_rep["audit_ok"])
    return out_rep


# ---------------------------------------------------------------------------
# 10. edge protection on the sharded serving path (ISSUE 17)
# ---------------------------------------------------------------------------

def _build_edge_cluster(clock):
    """2-shard edge-enabled cluster + steered ring + host DHCP server —
    the shared stack for the two edge scenarios (identical geometry so
    one jit compile serves both)."""
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.parallel.sharded import ShardedCluster, ShardedFastPathSink

    cl = ShardedCluster(2, batch_per_shard=8, sub_nbuckets=64,
                        vlan_nbuckets=64, cid_nbuckets=64,
                        nat_sessions_nbuckets=64, qos_nbuckets=64,
                        spoof_nbuckets=64, garden_enabled=False,
                        edge_enabled=True, edge_nbuckets=64)
    sink = ShardedFastPathSink(lambda: cl)
    sink.set_server_config(SERVER_MAC, SERVER_IP)
    pools = _make_pools(sink)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                        fastpath_tables=sink, clock=clock)
    ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)

    def drive(frame: bytes, from_access: bool = True) -> bytes | None:
        assert ring.rx_push(frame, from_access=from_access)
        cl.process_ring(ring, int(clock()), 0, pkt_slot=2048,
                        slow_path=server.handle_frame)
        got = ring.tx_pop()
        return got[0] if got is not None else None

    def dora(macs) -> dict:
        leased = {}
        for i, m in enumerate(macs):
            offer = drive(_discover(m, 0x800 + i))
            assert offer is not None, "DORA discover went unanswered"
            ip = _reply(offer).yiaddr
            ack = drive(_request(m, ip, 0x900 + i))
            assert ack is not None \
                and _reply(ack).msg_type == dhcp_codec.ACK
            leased[m] = ip
        return leased

    return cl, pools, server, ring, drive, dora


def _data(mac: bytes, src_ip: int, dst_ip: int, sport: int,
          dport: int) -> bytes:
    return packets.udp_packet(mac, SERVER_MAC, src_ip, dst_ip, sport,
                              dport, b"edge-scenario-payload")


def intercept_tap_live(seed: int) -> dict:
    """Warrant-compiled taps mirror on the live sharded serving path,
    filter at the device, and reap on expiry. A warrant with a port
    filter arms mid-service against a leased subscriber: matching
    upstream frames MIRROR to RecordCC through the ring retire,
    non-matching and non-target frames do not, the warrant's expiry
    (bounded `expire_warrants(max_reaps=)` sweep + `sync()`) provably
    removes the device row, and the `_audit_edge` warrant<->row clause
    plus the missteer counter close the loop."""
    from bng_tpu.control.intercept import InterceptManager, Warrant
    from bng_tpu.edge import InterceptTapProgram, MirrorPump
    from bng_tpu.utils.net import u32_to_ip

    clock = SimClock()
    cl, pools, server, ring, drive, dora = _build_edge_cluster(clock)
    macs = [_mac((seed % 53) * 100 + i) for i in range(6)]
    leased = dora(macs)

    target_mac = macs[seed % len(macs)]
    bystander = macs[(seed + 1) % len(macs)]
    target_ip = leased[target_mac]

    im = InterceptManager(clock=clock)
    im.add_warrant(Warrant(
        id="W-STORM-1", liid="LIID-17", target_ipv4=u32_to_ip(target_ip),
        valid_from=clock() - 1.0, valid_until=clock() + 600.0,
        filter_dest_ports=[443]))
    program = InterceptTapProgram(cl, im, clock=clock)
    pump = MirrorPump(program)
    cl.mirror_sink = pump
    sync0 = program.sync()

    peer = ip_to_u32("198.51.100.7")
    # matching flow (dst port 443) from the target: must mirror
    drive(_data(target_mac, target_ip, peer, 40001, 443))
    mirrored_match = pump.stats["mirrored"]
    # non-matching port from the target: device filter rejects the lane
    drive(_data(target_mac, target_ip, peer, 40002, 9999))
    # a bystander's matching flow: no tap row, never mirrored
    drive(_data(bystander, leased[bystander], peer, 40003, 443))
    mirrored_total = pump.stats["mirrored"]
    edge_stats = np.asarray(cl.stats.get("edge", np.zeros(4)))

    audit_live = audit_invariants(cluster=cl, pools=pools, dhcp=server,
                                  tap_program=program,
                                  check_roundtrip=False)

    # expiry: bounded sweep flips the warrant, sync reaps the row, and
    # the audit would have flagged the stale row had it survived
    clock.advance(700.0)
    expired = im.expire_warrants(max_reaps=4)
    sync1 = program.sync()
    drive(_data(target_mac, target_ip, peer, 40004, 443))
    mirrored_after = pump.stats["mirrored"]

    audit = audit_invariants(cluster=cl, pools=pools, dhcp=server,
                             tap_program=program, check_roundtrip=False)
    snap = cl.telemetry.snapshot()
    out_rep = {
        "name": "intercept_tap_live", "seed": seed,
        "leased": len(leased),
        "armed": sync0["armed"],
        "mirrored_match": mirrored_match,
        "mirrored_total": mirrored_total,
        "tap_filtered": int(edge_stats[1]),
        "cc_records": im.stats()["cc_records"],
        "expired": expired,
        "reaped": sync1["reaped"],
        "rows_after_reap": len(cl.tap_rows()),
        "mirrored_after_expiry": mirrored_after - mirrored_total,
        "missteers": int(snap["missteer_total"]),
        "audit_live_ok": audit_live.ok,
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
    }
    out_rep["ok"] = (out_rep["armed"] == 1
                     and out_rep["mirrored_match"] == 1
                     and out_rep["mirrored_total"] == 1
                     and out_rep["tap_filtered"] >= 1
                     and out_rep["cc_records"] == 1
                     and out_rep["expired"] == 1
                     and out_rep["reaped"] == 1
                     and out_rep["rows_after_reap"] == 0
                     and out_rep["mirrored_after_expiry"] == 0
                     and out_rep["missteers"] == 0
                     and out_rep["audit_live_ok"]
                     and out_rep["audit_ok"])
    return out_rep


def route_flap_rewrite(seed: int) -> dict:
    """Next-hop rewrite rides a link flap on the live sharded serving
    path as bounded dirty-slot deltas — never a resync. Subscribers
    bind to per-class ECMP next hops compiled into chip-local device
    rows; data frames forward (verdict FWD) with the gateway MAC
    stamped; killing an upstream's health target recompiles ONLY the
    rows whose selection changed (dirty slots bounded by the bound
    count), traffic re-forwards via the survivor, recovery flaps back,
    and `_audit_edge` proves every row equals the routing program's
    compiled expectation."""
    from bng_tpu.control.routing import (RoutingManager, StubPlatform,
                                         Upstream)
    from bng_tpu.edge import RouteProgram
    from bng_tpu.edge.ops import RW_MAC_HI, RW_MAC_LO

    clock = SimClock()
    cl, pools, server, ring, drive, dora = _build_edge_cluster(clock)
    macs = [_mac((seed % 47) * 100 + i) for i in range(8)]
    leased = dora(macs)

    platform = StubPlatform()
    manager = RoutingManager(None, platform)
    mac_a, mac_b = bytes.fromhex("02dd0000000a"), bytes.fromhex(
        "02dd0000000b")
    manager.add_upstream(Upstream(name="ispA", interface="eth1",
                                  gateway="192.0.2.1", table=100,
                                  health_target="192.0.2.1"))
    manager.add_upstream(Upstream(name="ispB", interface="eth2",
                                  gateway="192.0.2.2", table=101,
                                  health_target="192.0.2.2"))
    platform.reachable["192.0.2.1"] = 0.001
    platform.reachable["192.0.2.2"] = 0.001
    manager.check_health()

    program = RouteProgram(cl, manager)
    program.attach()
    program.set_neighbor("192.0.2.1", mac_a)
    program.set_neighbor("192.0.2.2", mac_b)
    classes = ("residential", "business")
    for i, m in enumerate(macs):
        assert program.bind_subscriber(leased[m], classes[i % 2])

    def _forward_all(xid: int) -> int:
        fwd0 = int(cl.telemetry.verdicts[:, 3].sum())
        for i, m in enumerate(macs):
            drive(_data(m, leased[m], ip_to_u32("203.0.113.9"),
                        41000 + xid + i, 443))
        return int(cl.telemetry.verdicts[:, 3].sum()) - fwd0

    fwd_before = _forward_all(0)
    rewrites_before = int(np.asarray(cl.stats["edge"])[2])
    audit_live = audit_invariants(cluster=cl, pools=pools, dhcp=server,
                                  route_program=program,
                                  check_roundtrip=False)

    # flap: ispA's health target dies; threshold failures mark it DOWN
    # and the manager hook recompiles ONLY the rows that moved
    deltas_before = program.stats["deltas"]
    del platform.reachable["192.0.2.1"]
    for _ in range(manager.config.failure_threshold):
        manager.check_health()
    dirty_after_flap = cl.pending_dirty()
    moved = program.stats["deltas"] - deltas_before
    on_b = sum(1 for m in macs
               if (r := cl.get_route(leased[m])) is not None
               and (int(r[RW_MAC_HI]), int(r[RW_MAC_LO]))
               == (int.from_bytes(mac_b[:2], "big"),
                   int.from_bytes(mac_b[2:6], "big")))
    fwd_during = _forward_all(100)

    # recovery: the target answers again, selection heals (bounded)
    platform.reachable["192.0.2.1"] = 0.001
    manager.check_health()
    fwd_after = _forward_all(200)

    audit = audit_invariants(cluster=cl, pools=pools, dhcp=server,
                             route_program=program, check_roundtrip=False)
    snap = cl.telemetry.snapshot()
    out_rep = {
        "name": "route_flap_rewrite", "seed": seed,
        "leased": len(leased),
        "bound": len(macs),
        "fwd_before": fwd_before,
        "rewrites_before": rewrites_before,
        "flaps": program.stats["flaps"],
        "moved_rows": moved,
        "dirty_after_flap": dirty_after_flap,
        "on_survivor": on_b,
        "fwd_during_flap": fwd_during,
        "fwd_after_recovery": fwd_after,
        "unroutable": program.stats["unroutable"],
        "missteers": int(snap["missteer_total"]),
        "audit_live_ok": audit_live.ok,
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
    }
    n = len(macs)
    out_rep["ok"] = (out_rep["fwd_before"] == n
                     and out_rep["rewrites_before"] >= n
                     and out_rep["flaps"] == 2
                     and 0 < out_rep["moved_rows"] <= n
                     and 0 < out_rep["dirty_after_flap"] <= 2 * n
                     and out_rep["on_survivor"] == n
                     and out_rep["fwd_during_flap"] == n
                     and out_rep["fwd_after_recovery"] == n
                     and out_rep["unroutable"] == 0
                     and out_rep["missteers"] == 0
                     and out_rep["audit_live_ok"]
                     and out_rep["audit_ok"])
    return out_rep


# ---------------------------------------------------------------------------
# 11. cluster failover: flash-crowd re-DORA lands on the promoted standby
# ---------------------------------------------------------------------------

def cluster_failover_redora(seed: int) -> dict:
    """Cluster-of-BNGs failover (bng_tpu/cluster): DORA a town through
    the cluster front door, kill one member mid-service, let the
    health-monitor/failover machinery promote its standby, and land the
    flash-crowd re-DORA on the promoted instance. Renewals must ACK
    with the ORIGINAL addresses (the replicated session books make
    stickiness through failover real), fresh subscribers must keep
    leasing cluster-wide, and `_audit_cluster` must stay clean — plus
    the carve's never-half-allocate discipline: removing a member with
    live leases is refused, and a joiner with no free blocks waits."""
    from bng_tpu.cluster import ClusterCoordinator, instance_for_mac

    n_macs = 48
    clock = SimClock()
    coord = ClusterCoordinator(
        clock=clock, sub_nbuckets=512, slice_size=64,
        space_network=ip_to_u32("10.64.0.0"), space_prefix_len=16)
    coord.add_instances(["bng-a", "bng-b", "bng-c"])
    macs = [_mac((seed % 89) * 100 + i) for i in range(n_macs)]
    leased = dora_with_retries(coord, macs, clock)
    audit_before = audit_invariants(bng_cluster=coord)

    ids = coord.member_ids()
    victim = ids[seed % len(ids)]
    victim_macs = [m for m in macs if instance_for_mac(m, ids) == victim]
    coord.kill_instance(victim)
    # outage window: the dead member's subscribers shed (clients
    # retransmit), everyone else keeps serving
    out = coord.handle_batch(
        [(k, _renew(m, leased[m], 0x30000 + k))
         for k, m in enumerate(victim_macs)], now=clock())
    outage_shed = sum(1 for _l, rep in out if rep is None)
    ticks = 0
    while coord.members[victim].role != "promoted" and ticks < 64:
        clock.advance(1.0)
        coord.tick()
        ticks += 1
    promoted = coord.members[victim].role == "promoted"

    # the flash crowd reconnects: renewals land on the promoted standby
    # and must come back with the addresses the dead active handed out
    out = coord.handle_batch(
        [(k, _renew(m, leased[m], 0x40000 + k))
         for k, m in enumerate(victim_macs)], now=clock())
    sticky = sum(
        1 for (_l, rep), m in zip(out, victim_macs)
        if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
        and _reply(rep).yiaddr == leased[m])

    fresh = [_mac((seed % 89) * 100 + 10_000 + i) for i in range(24)]
    fresh_leased = dora_with_retries(coord, fresh, clock)

    # never-half-allocate, exercised live: a member holding leases may
    # not leave (its blocks would move half-drained), and a joiner with
    # nothing on the free list stays pending instead of stealing
    survivor = next(i for i in ids if i != victim)
    refused = not coord.remove_instance(survivor)
    coord.add_instance("bng-x")
    joiner_pending = coord.members["bng-x"].pending
    coord.remove_instance("bng-x")  # empty member: clean leave

    audit_after = audit_invariants(bng_cluster=coord)
    out_rep = {
        "name": "cluster_failover_redora", "seed": seed,
        "instances": len(ids),
        "victim": victim,
        "leased": len(leased),
        "victim_subs": len(victim_macs),
        "outage_shed": outage_shed,
        "promoted": promoted,
        "failovers": coord.failovers,
        "sticky_acks": sticky,
        "fresh_leased": len(fresh_leased),
        "fresh_unique": len(set(fresh_leased.values())),
        "remove_refused": refused,
        "joiner_pending": joiner_pending,
        "recarves": coord.recarves,
        "audit_before_ok": audit_before.ok,
        "audit_ok": audit_after.ok,
        "violations": audit_after.violations_by_kind(),
    }
    coord.close()
    out_rep["ok"] = (
        out_rep["leased"] == n_macs
        and out_rep["victim_subs"] > 0
        and out_rep["outage_shed"] == out_rep["victim_subs"]
        and promoted and coord.failovers == 1
        and sticky == out_rep["victim_subs"]
        and out_rep["fresh_leased"] == len(fresh)
        and out_rep["fresh_unique"] == len(fresh)
        and refused and joiner_pending
        and audit_before.ok and audit_after.ok)
    return out_rep


def _build_devloop_stack(clock, devloop_k: int):
    """Tiered scheduler with the devloop express lane armed + 32
    pre-provisioned subscribers — geometry pinned to tests/test_express
    (sub 256 / vlan 64 / cid 64, engine B=32, express B=8) so a test
    session reuses every compiled program."""
    from bng_tpu.control.nat import NATManager
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
    from bng_tpu.runtime.tables import FastPathTables

    base = int(clock())
    fp = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64,
                        cid_nbuckets=64, max_pools=8)
    fp.set_server_config(SERVER_MAC, SERVER_IP)
    fp.add_pool(1, ip_to_u32("10.0.0.0"), 24, SERVER_IP,
                ip_to_u32("8.8.8.8"), ip_to_u32("8.8.4.4"), 3600)
    subs = []
    for i in range(32):
        mac = _mac(0xD00 + i)
        ip = ip_to_u32("10.0.0.0") + 10 + i
        fp.add_subscriber(mac, 1, ip, base + 600)
        subs.append((mac, ip))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=64, sub_nat_nbuckets=64)
    eng = Engine(fp, nat, batch_size=32, pkt_slot=512, clock=clock)
    sched = TieredScheduler(eng, SchedulerConfig(
        express_batch=8, bulk_batch=32, express_aot=True,
        express_loop="devloop", devloop_k=devloop_k), clock=clock)
    return sched, subs


def _devloop_sweep(seed: int, rounds: int, devloop_k: int,
                   plan: FaultPlan | None) -> dict:
    """One storm sweep on a FRESH stack: each round submits a full
    ring's worth of express DHCP (k slots x express batch) interleaved
    with a saturated bulk batch, all through `process()` (which
    flushes, so partial rings never carry across rounds). Returns the
    deterministic digest the scenario diffs: reply byte hash, verdict
    counts, loop/fallback counters, cursor audit."""
    import hashlib

    clock = SimClock()
    sched, subs = _build_devloop_stack(clock, devloop_k)
    per_round = devloop_k * sched.express.cfg.batch
    tx_sha = hashlib.sha256()
    counts = {"tx": 0, "slow": 0, "fwd": 0, "dropped": 0}
    peer = ip_to_u32("198.51.100.9")

    def storm() -> None:
        for r in range(rounds):
            frames, kinds = [], []
            for j in range(per_round):
                mac, ip = subs[(seed + r * 7 + j) % len(subs)]
                xid = 0xD0000 + r * 256 + j
                if (r + j) % 3 == 2:  # renew REQUESTs ride the storm too
                    frames.append(_renew(mac, ip, xid))
                else:
                    frames.append(_discover(mac, xid))
                kinds.append(True)
            for j in range(sched.bulk.cfg.batch):  # saturate the bulk lane
                mac, ip = subs[(seed + j) % len(subs)]
                frames.append(packets.udp_packet(
                    mac, SERVER_MAC, ip, peer, 40000 + j, 443,
                    b"devloop-storm-bulk"))
                kinds.append(True)
            out = sched.process(frames, now=clock())
            for verdict in ("tx", "fwd"):
                for i, frame in out[verdict]:
                    tx_sha.update(i.to_bytes(4, "big"))
                    tx_sha.update(frame)
                counts[verdict] += len(out[verdict])
            counts["slow"] += len(out["slow"])
            counts["dropped"] += len(out["dropped"])
            clock.advance(0.01)

    if plan is not None:
        with armed(plan, log=False) as inj:
            storm()
        injected = [list(t) for t in inj.injected]
    else:
        storm()
        injected = []

    sched.quiesce(now=clock())
    pump = sched._devloop
    audit = pump.audit() if pump is not None else {"consistent": False}
    stats = pump.stats() if pump is not None else {}
    return {
        "loop": sched.express_loop,
        "counts": counts,
        "reply_sha": tx_sha.hexdigest(),
        "ring_dispatches": stats.get("dispatches", 0),
        "ring_batches": stats.get("batches", 0),
        "fallback_slots": stats.get("fallback_slots", 0),
        "fallbacks": dict(sorted(sched.express_fallbacks.items())),
        "injected": injected,
        "cursor_seq": audit.get("seq", -1),
        "audit_consistent": bool(audit.get("consistent", False)),
    }


def devloop_storm(seed: int) -> dict:
    """Express OFFER storm through the device-resident serving loop
    (devloop/) against a saturated bulk lane, with a mid-storm injected
    ``devloop.dispatch`` failure. The control sweep serves every round
    through full descriptor rings; the faulted sweep loses its second
    ring dispatch to the injected fault, which must degrade LOUDLY
    (fallback counter + per-batch re-dispatch of every staged slot) and
    never silently: reply bytes must be byte-identical to the control
    sweep, the express frames all still answer, and the quiesce-time
    cursor audit must close consistent in both sweeps — faults degrade
    service, never consistency."""
    rounds, devloop_k = 6, 4
    fault_round = 2 + seed % 3  # mid-storm: ring dispatch 2, 3 or 4
    control = _devloop_sweep(seed, rounds, devloop_k, None)
    faulted = _devloop_sweep(
        seed, rounds, devloop_k,
        FaultPlan(seed, [FaultSpec("devloop.dispatch", FAIL,
                                   at_hit=fault_round)]))

    out_rep = {
        "name": "devloop_storm", "seed": seed,
        "rounds": rounds, "devloop_k": devloop_k,
        "fault_round": fault_round,
        "control": control, "faulted": faulted,
        "replies_identical": control["reply_sha"] == faulted["reply_sha"],
    }
    out_rep["ok"] = (
        control["loop"] == "devloop" and faulted["loop"] == "devloop"
        and out_rep["replies_identical"]
        and control["counts"]["tx"] > 0
        and control["counts"] == faulted["counts"]
        and control["fallback_slots"] == 0 and not control["fallbacks"]
        and faulted["fallback_slots"] == devloop_k
        and faulted["fallbacks"].get("devloop_miss", 0) == 1
        and faulted["injected"] == [["devloop.dispatch", "fail",
                                     fault_round]]
        and faulted["ring_dispatches"] == control["ring_dispatches"] - 1
        and faulted["cursor_seq"] == control["cursor_seq"] - devloop_k
        and control["audit_consistent"]
        and faulted["audit_consistent"])
    return out_rep


# ---------------------------------------------------------------------------
# 13. cluster partial partition: no quorum, no demotion, no double-carve
# ---------------------------------------------------------------------------

def cluster_partial_partition(seed: int) -> dict:
    """The NEAT shape (Alquraan OSDI'18) on the cluster control fabric:
    three members beat over a SimTransport mesh, then the a<->b link is
    severed while BOTH still reach c. a and b accuse each other, but c
    accuses neither — no quorum forms on either side, so nobody is
    demoted to down, the coordinator fails nothing over, and the carve
    plan keeps one owner per block (no double-carve). Service continues
    through the split (renewals ACK cluster-wide), and when the link
    heals both suspicion episodes close as observed partitions."""
    from bng_tpu.cluster import ClusterCoordinator
    from bng_tpu.cluster.fabric import FailureDetector, SimTransport

    n_macs = 36
    clock = SimClock()
    ids = ["bng-a", "bng-b", "bng-c"]
    hub = SimTransport(clock, seed=seed)
    dets: dict = {}
    for nid in ids:
        ep = hub.endpoint(nid)
        for peer in ids:
            if peer != nid:
                ep.add_peer(peer)
        # mesh quorum: observers of X are the 2 others -> majority 2
        dets[nid] = FailureDetector(nid, ep, clock=clock,
                                    beat_interval_s=0.5,
                                    suspicion_threshold=3,
                                    startup_grace_s=0.0)
    for nid in ids:
        for peer in ids:
            if peer != nid:
                dets[nid].watch(peer, now=clock())

    # the data plane the fabric protects: an inline cluster serving
    # leases under the same member names
    coord = ClusterCoordinator(
        clock=clock, sub_nbuckets=512, slice_size=64,
        space_network=ip_to_u32("10.80.0.0"), space_prefix_len=16)
    coord.add_instances(ids)
    macs = [_mac((seed % 89) * 100 + i) for i in range(n_macs)]
    leased = dora_with_retries(coord, macs, clock)
    epoch_before = coord.plan.epoch

    counters = {nid: 0 for nid in ids}

    def fabric_round(rounds: int) -> None:
        for _ in range(rounds):
            for nid in ids:
                counters[nid] += 1
                dets[nid].beat(served=counters[nid], work=counters[nid])
            for nid in ids:
                dets[nid].tick(clock())
            clock.advance(0.5)

    fabric_round(4)  # warm: everyone sees everyone up
    warm_ok = all(v.state == "up"
                  for d in dets.values() for v in d.views.values())

    hub.partition("bng-a", "bng-b")
    fabric_round(8)  # 4s of split: 3-beat suspicion windows expire

    # the quorum ledger mid-split, per observer
    states_during = {nid: {p: v.state
                           for p, v in sorted(dets[nid].views.items())}
                     for nid in ids}
    accusers_at_c = {p: sorted(v.accused_by)
                     for p, v in sorted(dets["bng-c"].views.items())}
    down_verdicts = sum(d.verdicts["down"] for d in dets.values())
    # a coordinator acting on the fabric would only carve out members
    # the detector demoted to DOWN; none were, so nothing is killed
    for nid in ids:
        for peer, v in dets[nid].views.items():
            if v.state == "down":
                coord.kill_instance(peer)
    for _ in range(4):
        clock.advance(1.0)
        coord.tick()

    # service through the split: every subscriber renews, cluster-wide
    out = coord.handle_batch(
        [(k, _renew(m, leased[m], 0x50000 + k))
         for k, m in enumerate(macs)], now=clock())
    renew_acks = sum(
        1 for (_l, rep), m in zip(out, macs)
        if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
        and _reply(rep).yiaddr == leased[m])

    hub.heal_all()
    fabric_round(6)
    healed_ok = all(v.state == "up"
                    for d in dets.values() for v in d.views.values())
    partitions_observed = sum(
        v.partitions_observed
        for d in dets.values() for v in d.views.values())

    audit = audit_invariants(bng_cluster=coord)
    out_rep = {
        "name": "cluster_partial_partition", "seed": seed,
        "instances": len(ids),
        "leased": len(leased),
        "warm_ok": warm_ok,
        "states_during": states_during,
        "accusers_at_c": accusers_at_c,
        "down_verdicts": down_verdicts,
        "failovers": coord.failovers,
        "epoch_before": epoch_before,
        "epoch_after": coord.plan.epoch,
        "renew_acks": renew_acks,
        "healed_ok": healed_ok,
        "partitions_observed": partitions_observed,
        "link_cut_datagrams": hub.stats["cut"],
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
    }
    coord.close()
    out_rep["ok"] = (
        out_rep["leased"] == n_macs and warm_ok
        # each split side suspects the other; the common neighbour
        # keeps both up — the quorum evidence that blocks demotion
        and states_during["bng-a"]["bng-b"] == "suspect"
        and states_during["bng-b"]["bng-a"] == "suspect"
        and states_during["bng-c"] == {"bng-a": "up", "bng-b": "up"}
        and accusers_at_c == {"bng-a": ["bng-b"], "bng-b": ["bng-a"]}
        and down_verdicts == 0
        and out_rep["failovers"] == 0
        and out_rep["epoch_after"] == epoch_before
        and renew_acks == n_macs
        and healed_ok and partitions_observed >= 2
        and out_rep["link_cut_datagrams"] > 0
        and audit.ok)
    return out_rep


# ---------------------------------------------------------------------------
# 14. cluster gray member: beating but not serving -> demoted, sticky re-DORA
# ---------------------------------------------------------------------------

def cluster_gray_member(seed: int) -> dict:
    """Gray failure (Huang HotOS'17) through the fabric detector: a
    member keeps beating — its heartbeats are perfect — but its
    serving-health word stalls (work accepted keeps climbing, replies
    produced does not). The detector reads the stall off the member's
    own signed beats, issues a GRAY verdict with no quorum needed, the
    HA probe goes false, and the standby promotes exactly as if the
    member had died. The wedged member's subscribers re-DORA sticky
    onto the promoted standby (original addresses), and the healthy
    member never flaps."""
    from bng_tpu.cluster import ClusterCoordinator, instance_for_mac
    from bng_tpu.cluster.fabric import SimTransport

    n_macs = 32
    clock = SimClock()
    hub = SimTransport(clock, seed=seed)
    ids = ["bng-a", "bng-b"]
    coord = ClusterCoordinator(
        clock=clock, sub_nbuckets=512, slice_size=64,
        space_network=ip_to_u32("10.96.0.0"), space_prefix_len=16,
        fabric_endpoint=hub.endpoint("coordinator"),
        fabric_beat_interval_s=0.5, fabric_gray_beats=4,
        fabric_startup_grace_s=2.0,
        ha_probe_interval_s=0.5, ha_failure_threshold=2,
        ha_failover_delay_s=1.0)
    coord.add_instances(ids)
    # inline members do not beat on their own (the flag oracle serves
    # them); this scenario IS the fabric lane, so watch them and speak
    # their beats from the sim endpoints
    eps = {}
    for iid in ids:
        coord.fabric_detector.watch(iid, now=clock())
        eps[iid] = hub.endpoint(iid)
        eps[iid].add_peer("coordinator")

    macs = [_mac((seed % 89) * 100 + i) for i in range(n_macs)]
    leased = dora_with_retries(coord, macs, clock)
    victim = ids[seed % len(ids)]
    healthy = next(i for i in ids if i != victim)
    victim_macs = [m for m in macs if instance_for_mac(m, ids) == victim]

    served = {iid: 0 for iid in ids}
    work = {iid: 0 for iid in ids}

    def beat_round(wedged: str = "") -> None:
        for iid in ids:
            work[iid] += 8  # batches keep arriving either way
            if iid != wedged:
                served[iid] += 8  # ...but only healthy members reply
            eps[iid].send("coordinator", "beat",
                          {"served": served[iid], "work": work[iid],
                           "accuse": []})
        coord.tick(clock())
        clock.advance(0.5)

    for _ in range(4):
        beat_round()
    warm_states = {p: v["state"] for p, v in
                   coord.fabric_detector.status()["peers"].items()}

    # wedge: the victim's replies stop while its intake keeps climbing
    rounds = 0
    while coord.members[victim].role != "promoted" and rounds < 40:
        beat_round(wedged=victim)
        rounds += 1
    promoted = coord.members[victim].role == "promoted"
    gray_events = [e for e in coord.fabric_events if e == (victim, "gray")]

    # the promoted slot is fresh (detector view was reset): beats
    # resume with a healthy serving word and it must read up again
    for _ in range(4):
        beat_round()
    post_state = coord.fabric_detector.views[victim].state

    # the wedged member's flash crowd lands on the promoted standby
    # and must keep its addresses (replicated books = sticky re-DORA)
    out = coord.handle_batch(
        [(k, _renew(m, leased[m], 0x60000 + k))
         for k, m in enumerate(victim_macs)], now=clock())
    sticky = sum(
        1 for (_l, rep), m in zip(out, victim_macs)
        if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
        and _reply(rep).yiaddr == leased[m])

    audit = audit_invariants(bng_cluster=coord)
    out_rep = {
        "name": "cluster_gray_member", "seed": seed,
        "victim": victim,
        "leased": len(leased),
        "victim_subs": len(victim_macs),
        "warm_states": warm_states,
        "promoted": promoted,
        "gray_verdicts": coord.fabric_detector.verdicts["gray"],
        "gray_events": [list(e) for e in gray_events],
        "failovers": coord.failovers,
        "healthy_role": coord.members[healthy].role,
        "healthy_state": coord.fabric_detector.views[healthy].state,
        "post_promote_state": post_state,
        "sticky_acks": sticky,
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
    }
    coord.close()
    out_rep["ok"] = (
        out_rep["leased"] == n_macs
        and out_rep["victim_subs"] > 0
        and warm_states == {"bng-a": "up", "bng-b": "up"}
        and promoted and out_rep["failovers"] == 1
        and out_rep["gray_verdicts"] >= 1
        and len(gray_events) >= 1
        and out_rep["healthy_role"] == "active"
        and out_rep["healthy_state"] == "up"
        and post_state == "up"
        and sticky == out_rep["victim_subs"]
        and audit.ok)
    return out_rep


def cluster_host_loss(seed: int) -> dict:
    """Multi-box host loss (ISSUE 20): two remote members `--join` a
    coordinator over the SimTransport fabric, hydrate their carved
    blocks through the chunked handoff stream, and serve steered DORAs
    from their own stacks (missteers must be 0 — the placement law
    re-checked on the remote box). Then the whole remote HOST vanishes
    (every beta link cut at once — the box died, not a process): the
    detector downs both members by accusation quorum, `_check_host_loss`
    promotes their surviving-host HA halves AS A GROUP, the accounting
    spool the lost box left behind replays exactly once, the flash
    crowd's renewals ACK their ORIGINAL addresses, and the audit stays
    clean. FATE+DESTINI one level up: the per-process kill lane can
    never see a box disappearing with both of its HA halves' state."""
    import tempfile

    from bng_tpu.cluster import (ClusterCoordinator, MemberRuntime,
                                 instance_for_mac)
    from bng_tpu.cluster.fabric import SimTransport
    from bng_tpu.control.radius import packet as rp
    from bng_tpu.control.radius.accounting import AccountingManager
    from bng_tpu.control.radius.client import (RadiusClient,
                                               RadiusServerConfig)
    from bng_tpu.control.radius.packet import RadiusPacket

    n_macs = 32
    clock = SimClock()
    hub = SimTransport(clock, seed=seed)
    coord = ClusterCoordinator(
        clock=clock, sub_nbuckets=512, slice_size=64,
        space_network=ip_to_u32("10.112.0.0"), space_prefix_len=16,
        fabric_endpoint=hub.endpoint("coordinator"),
        fabric_beat_interval_s=0.5, fabric_suspicion_threshold=3,
        fabric_startup_grace_s=2.0,
        ha_probe_interval_s=0.5, ha_failure_threshold=2,
        ha_failover_delay_s=1.0)
    # the founding carve declares the remote slots (blocks interleave
    # on the host axis NOW; the boxes join into them)
    coord.add_instances(["bng-a"], host="alpha",
                        remotes={"bng-r1": "beta", "bng-r2": "beta"})
    remote_ids = ("bng-r1", "bng-r2")
    members = {iid: MemberRuntime(hub.endpoint(iid), iid, "beta",
                                  clock=clock)
               for iid in remote_ids}
    # single-threaded determinism: the coordinator's reply wait chains
    # the members' own ticks (the two "boxes" run in lockstep)
    coord.remote_waiter = lambda: [m.tick(clock())
                                   for m in members.values()]
    join_ticks = 0
    while not all(m.state == "serving" for m in members.values()) \
            and join_ticks < 200:
        clock.advance(0.25)
        for m in members.values():
            m.tick(clock())
        coord.tick()
        join_ticks += 1

    macs = [_mac((seed % 89) * 100 + i) for i in range(n_macs)]
    leased = dora_with_retries(coord, macs, clock)
    ids = coord.member_ids()
    remote_macs = [m for m in macs
                   if instance_for_mac(m, ids) in remote_ids]
    missteers = sum(m.missteers for m in members.values())
    handoff_rx = sum(m.handoff.stats()["completed"]
                     for m in members.values())

    # the lost box's accounting story: its RADIUS lane was already dark,
    # so session stops SPOOLED instead of sending — the spool is the
    # state a dead host leaves behind for the survivor to replay
    class _AcctServer:
        def __init__(self):
            self.stops = 0

        def __call__(self, data, host, port, timeout):
            req = RadiusPacket.decode(data)
            if req.get_int(rp.ACCT_STATUS_TYPE) == rp.ACCT_STOP:
                self.stops += 1
            return RadiusPacket(rp.ACCOUNTING_RESPONSE, req.id).encode(
                b"chaos-secret", request_auth=req.authenticator)

    live = _AcctServer()
    spool = tempfile.mktemp(prefix="bng-chaos-hostloss-", suffix=".spool")

    def _client(transport):
        return RadiusClient(
            [RadiusServerConfig("10.0.0.5", secret=b"chaos-secret",
                                timeout_s=0.05, retries=1)],
            transport=transport, clock=clock)

    lost_acct = AccountingManager(_client(lambda *a: None),
                                  interim_interval_s=60,
                                  spool_path=spool, clock=clock)
    for i, m in enumerate(remote_macs[:4]):
        sid = f"s-{m.hex()}"
        lost_acct.start(sid, f"sub-{i}", leased[m])
        lost_acct.update_counters(sid, 1000 + i, 2000 + i)
        lost_acct.stop(sid)  # dark RADIUS: spools
    # the dark transport spooled BOTH halves of each session's story
    # (start + stop) — all 8 records must replay exactly once
    spooled = len(lost_acct.pending)

    replay_rounds: list = []

    def _on_host_loss(host, _ids):
        # the surviving host recovers the dead box's spool: exactly-once
        # replay through a fresh manager on the SAME spool path
        survivor = AccountingManager(_client(live), interim_interval_s=60,
                                     spool_path=spool, clock=clock)
        replay_rounds.append(survivor.retry_tick())
        replay_rounds.append(survivor.retry_tick())

    coord.on_host_loss = _on_host_loss

    # the whole beta host vanishes: every link cut in the same instant
    for iid in remote_ids:
        hub.partition("coordinator", iid)
    coord.remote_waiter = None  # nothing left to chain — the box is gone
    loss_ticks = 0
    while coord.host_losses == 0 and loss_ticks < 120:
        clock.advance(0.5)
        coord.tick()
        loss_ticks += 1

    roles = {iid: coord.members[iid].role for iid in remote_ids}
    # flash crowd: the lost host's subscribers renew against the
    # promoted surviving-host halves and must keep their addresses
    out = coord.handle_batch(
        [(k, _renew(m, leased[m], 0x70000 + k))
         for k, m in enumerate(remote_macs)], now=clock())
    sticky = sum(
        1 for (_l, rep), m in zip(out, remote_macs)
        if rep is not None and _reply(rep).msg_type == dhcp_codec.ACK
        and _reply(rep).yiaddr == leased[m])
    fresh = [_mac((seed % 89) * 100 + 20_000 + i) for i in range(12)]
    fresh_leased = dora_with_retries(coord, fresh, clock)

    audit = audit_invariants(bng_cluster=coord)
    out_rep = {
        "name": "cluster_host_loss", "seed": seed,
        "join_ticks": join_ticks,
        "handoff_completed": handoff_rx,
        "leased": len(leased),
        "remote_subs": len(remote_macs),
        "missteers": missteers,
        "host_losses": coord.host_losses,
        "lost_hosts": sorted(coord._lost_hosts),
        "loss_ticks": loss_ticks,
        "roles": roles,
        "failovers": coord.failovers,
        "spooled": spooled,
        "replay_rounds": replay_rounds,
        "acct_stops": live.stops,
        "sticky_acks": sticky,
        "fresh_leased": len(fresh_leased),
        "audit_ok": audit.ok,
        "violations": audit.violations_by_kind(),
    }
    coord.close()
    out_rep["ok"] = (
        out_rep["handoff_completed"] == len(remote_ids)
        and out_rep["leased"] == n_macs
        and out_rep["remote_subs"] > 0
        and missteers == 0
        and out_rep["host_losses"] == 1
        and out_rep["lost_hosts"] == ["beta"]
        and roles == {"bng-r1": "promoted", "bng-r2": "promoted"}
        and out_rep["failovers"] == len(remote_ids)
        and spooled == 8
        and replay_rounds == [8, 0]
        and live.stops == 4
        and sticky == out_rep["remote_subs"]
        and out_rep["fresh_leased"] == len(fresh)
        and audit.ok)
    return out_rep


SCENARIOS = {
    "dora_worker_crash": dora_worker_crash,
    "corrupt_restore_cold_start": corrupt_restore_cold_start,
    "fleet_reshard_under_kill": fleet_reshard_under_kill,
    "nat_expiry_under_skew": nat_expiry_under_skew,
    "ha_delta_drop_reconnect": ha_delta_drop_reconnect,
    "fleet_resize_under_kill": fleet_resize_under_kill,
    "rolling_restart_under_kill": rolling_restart_under_kill,
    "engine_swap_crash_rollback": engine_swap_crash_rollback,
    "sharded_swap_crash_rollback": sharded_swap_crash_rollback,
    "intercept_tap_live": intercept_tap_live,
    "route_flap_rewrite": route_flap_rewrite,
    "cluster_failover_redora": cluster_failover_redora,
    "devloop_storm": devloop_storm,
    "cluster_partial_partition": cluster_partial_partition,
    "cluster_gray_member": cluster_gray_member,
    "cluster_host_loss": cluster_host_loss,
}
