"""Scenario/soak driver — the engine behind `bng chaos run` and
`make verify-chaos`.

Two entry points:

- `run_scenarios(seed)` — every scripted scenario (chaos/scenarios.py),
  each with a seed derived deterministically from the top-level one.
- `soak(seed, epochs)` — interleaves DORA/renew/release traffic through
  an inline fleet with a seed-GENERATED FaultPlan over the instrumented
  points, and runs the cross-authority audit every epoch (the
  "traffic + faults + audit every epoch" loop the ROADMAP's
  as-many-scenarios-as-you-can-imagine goal needs as a harness, not a
  hand-written list).

Both produce JSON-safe dicts with no wallclock, paths or object ids;
`canonical_json()` is the byte-deterministic serialization the
acceptance gate compares across runs (`bng chaos run --seed S` twice ->
identical bytes).
"""

from __future__ import annotations

import json
import random

from bng_tpu.chaos.faults import FaultPlan, SimClock, armed
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import (SCENARIOS, _mac, _release, _renew,
                                     build_fleet, dora_with_retries)

REPORT_SCHEMA = 1

# the soak generator draws faults only over points its stack actually
# visits — scheduling a fault on a point that never fires would make
# "faults injected" quietly read lower than the plan promises
SOAK_POINTS = ("fleet.scatter", "admission.admit", "dhcp.expire",
               "pool.allocate")


def _sub_seed(seed: int, idx: int) -> int:
    """Stable per-scenario seed derivation (documented so reports can be
    reproduced scenario-by-scenario with `--scenario NAME`)."""
    return seed * 1000 + idx


def run_scenarios(seed: int = 1, names: list[str] | None = None,
                  metrics=None) -> dict:
    """Run the scripted scenarios; a scenario that *raises* is reported
    as failed (ok=False) rather than aborting the sweep — chaos tooling
    that dies on the failure it was hunting is useless."""
    picked = sorted(names) if names else sorted(SCENARIOS)
    unknown = [n for n in picked if n not in SCENARIOS]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; "
                         f"have {sorted(SCENARIOS)}")
    out: dict = {"schema": REPORT_SCHEMA, "seed": seed, "scenarios": {}}
    for idx, name in enumerate(sorted(SCENARIOS)):
        if name not in picked:
            continue
        sub = _sub_seed(seed, idx)
        try:
            result = SCENARIOS[name](sub)
        except Exception as e:  # noqa: BLE001 — the failure IS the result
            result = {"name": name, "seed": sub, "ok": False,
                      "error": f"{type(e).__name__}: {e}"[:200]}
        out["scenarios"][name] = result
        if metrics is not None:
            metrics.chaos_scenarios.inc(
                result="ok" if result.get("ok") else "failed")
    out["ok"] = all(r.get("ok", False) for r in out["scenarios"].values())
    return out


def soak(seed: int = 1, epochs: int = 4, n_macs: int = 24,
         workers: int = 3, n_faults: int = 6, metrics=None) -> dict:
    """Seeded fault soak: churn DHCP traffic through an inline fleet
    under a generated FaultPlan, audit every epoch. Faults may cost
    service (lost shards, shed frames, skew-expired leases — all of
    which the next epoch's retransmits re-acquire where a worker still
    owns the shard); every epoch's audit must be clean."""
    clock = SimClock()
    fleet, pools, fastpath = build_fleet(workers, clock)
    plan = FaultPlan.generate(seed, points=SOAK_POINTS, n_faults=n_faults,
                              max_hit=epochs * workers * 2)
    rng = random.Random(seed ^ 0x5A5A)
    macs = [_mac(7000 + i) for i in range(n_macs)]
    epochs_out = []
    with armed(plan, metrics=metrics, log=False) as inj:
        for ep in range(epochs):
            leased = dora_with_retries(fleet, macs, clock, rounds=4)
            # churn: renew a deterministic subset, release another
            items, kind = [], {}
            for i, m in enumerate(macs):
                if m not in leased:
                    continue
                r = rng.random()
                if r < 0.25:
                    items.append((len(items), _release(m, leased[m],
                                                       9000 + i)))
                    kind[m] = "release"
                elif r < 0.75:
                    items.append((len(items), _renew(m, leased[m],
                                                     8000 + i)))
            if items:
                fleet.handle_batch(items, now=clock())
            clock.advance(30.0)
            fleet.expire(int(clock()))  # visits dhcp.expire per worker
            audit = audit_invariants(
                pools=pools, fleet=fleet, fastpath=fastpath,
                check_roundtrip=(ep == epochs - 1),
                metrics=metrics, epoch=ep)
            epochs_out.append({
                "epoch": ep,
                "leased": len(leased),
                "released": sum(1 for k in kind.values()
                                if k == "release"),
                "faults_so_far": len(inj.injected),
                "worker_failures": fleet.worker_failures,
                "shed": dict(sorted(
                    fleet.admission.stats.shed.items())),
                "audit_ok": audit.ok,
                "violations": audit.violations_by_kind(),
            })
    return {
        "schema": REPORT_SCHEMA, "seed": seed,
        "plan": plan.to_dict(),
        "injected": inj.stats_snapshot(),
        "epochs": epochs_out,
        "ok": all(e["audit_ok"] for e in epochs_out),
    }


def run_report(seed: int = 1, names: list[str] | None = None,
               soak_epochs: int = 0, metrics=None) -> dict:
    """The `bng chaos run` payload: scenarios (+ optional soak)."""
    report = run_scenarios(seed, names=names, metrics=metrics)
    if soak_epochs > 0:
        report["soak"] = soak(seed, epochs=soak_epochs, metrics=metrics)
        report["ok"] = report["ok"] and report["soak"]["ok"]
    return report


def canonical_json(report: dict) -> str:
    """Byte-deterministic serialization (sorted keys, fixed separators)
    — the string two same-seed runs are compared on."""
    return json.dumps(report, sort_keys=True, indent=2,
                      separators=(",", ": "))
