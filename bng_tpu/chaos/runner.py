"""Scenario/soak driver — the engine behind `bng chaos run` and
`make verify-chaos`.

Two entry points:

- `run_scenarios(seed)` — every scripted scenario (chaos/scenarios.py),
  each with a seed derived deterministically from the top-level one.
- `soak(seed, epochs)` — interleaves DORA/renew/release traffic through
  an inline fleet with a seed-GENERATED FaultPlan over the instrumented
  points, and runs the cross-authority audit every epoch (the
  "traffic + faults + audit every epoch" loop the ROADMAP's
  as-many-scenarios-as-you-can-imagine goal needs as a harness, not a
  hand-written list).

Both produce JSON-safe dicts with no wallclock, paths or object ids;
`canonical_json()` is the byte-deterministic serialization the
acceptance gate compares across runs (`bng chaos run --seed S` twice ->
identical bytes).
"""

from __future__ import annotations

import json
import random

from bng_tpu.chaos.faults import FaultPlan, SimClock, armed
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import (SCENARIOS, _mac, _release, _renew,
                                     build_fleet, dora_with_retries)
from bng_tpu.chaos.storms import STORMS

REPORT_SCHEMA = 1

# the full catalog: scripted fault scenarios + the storm suite. Storm
# callables take (seed, scale); everything else takes (seed).
ALL_SCENARIOS = {**SCENARIOS, **STORMS}


def scenario_catalog() -> list[tuple[str, str]]:
    """[(name, one-line description)] — the `bng chaos run --list`
    payload, sourced from each scenario's docstring so the catalog can
    never drift from the code."""
    out = []
    for name in sorted(ALL_SCENARIOS):
        doc = (ALL_SCENARIOS[name].__doc__ or "").strip()
        first = " ".join(doc.split(".")[0].split()) if doc else ""
        out.append((name, first[:120]))
    return out

# the soak generator draws faults only over points its stack actually
# visits — scheduling a fault on a point that never fires would make
# "faults injected" quietly read lower than the plan promises
SOAK_POINTS = ("fleet.scatter", "admission.admit", "dhcp.expire",
               "pool.allocate")


def _sub_seed(seed: int, idx: int) -> int:
    """Stable per-scenario seed derivation (documented so reports can be
    reproduced scenario-by-scenario with `--scenario NAME`)."""
    return seed * 1000 + idx


def run_scenarios(seed: int = 1, names: list[str] | None = None,
                  metrics=None, storm_scale: float = 1.0) -> dict:
    """Run the scripted scenarios + storm suite; a scenario that
    *raises* is reported as failed (ok=False) rather than aborting the
    sweep — chaos tooling that dies on the failure it was hunting is
    useless. `storm_scale` scales the storm scenarios' subscriber
    counts (1.0 = the published storms, flash crowd at 100k)."""
    picked = sorted(names) if names else sorted(ALL_SCENARIOS)
    unknown = [n for n in picked if n not in ALL_SCENARIOS]
    if unknown:
        raise ValueError(f"unknown scenario(s) {unknown}; "
                         f"have {sorted(ALL_SCENARIOS)}")
    out: dict = {"schema": REPORT_SCHEMA, "seed": seed, "scenarios": {}}
    if storm_scale != 1.0 and any(n in STORMS for n in picked):
        # the scale changes storm subscriber counts, hence the report
        # bytes — stamp it so two reports only ever compare like-for-like
        out["storm_scale"] = storm_scale
    for idx, name in enumerate(sorted(ALL_SCENARIOS)):
        if name not in picked:
            continue
        sub = _sub_seed(seed, idx)
        try:
            if name in STORMS:
                result = STORMS[name](sub, scale=storm_scale)
            else:
                result = ALL_SCENARIOS[name](sub)
        except Exception as e:  # noqa: BLE001 — the failure IS the result
            result = {"name": name, "seed": sub, "ok": False,
                      "error": f"{type(e).__name__}: {e}"[:200]}
        out["scenarios"][name] = result
        if metrics is not None:
            metrics.chaos_scenarios.inc(
                result="ok" if result.get("ok") else "failed")
    out["ok"] = all(r.get("ok", False) for r in out["scenarios"].values())
    return out


def soak(seed: int = 1, epochs: int = 4, n_macs: int = 24,
         workers: int = 3, n_faults: int = 6, metrics=None) -> dict:
    """Seeded fault soak: churn DHCP traffic through an inline fleet
    under a generated FaultPlan, audit every epoch. Faults may cost
    service (lost shards, shed frames, skew-expired leases — all of
    which the next epoch's retransmits re-acquire where a worker still
    owns the shard); every epoch's audit must be clean."""
    clock = SimClock()
    fleet, pools, fastpath = build_fleet(workers, clock)
    plan = FaultPlan.generate(seed, points=SOAK_POINTS, n_faults=n_faults,
                              max_hit=epochs * workers * 2)
    rng = random.Random(seed ^ 0x5A5A)
    macs = [_mac(7000 + i) for i in range(n_macs)]
    epochs_out = []
    with armed(plan, metrics=metrics, log=False) as inj:
        for ep in range(epochs):
            leased = dora_with_retries(fleet, macs, clock, rounds=4)
            # churn: renew a deterministic subset, release another
            items, kind = [], {}
            for i, m in enumerate(macs):
                if m not in leased:
                    continue
                r = rng.random()
                if r < 0.25:
                    items.append((len(items), _release(m, leased[m],
                                                       9000 + i)))
                    kind[m] = "release"
                elif r < 0.75:
                    items.append((len(items), _renew(m, leased[m],
                                                     8000 + i)))
            if items:
                fleet.handle_batch(items, now=clock())
            clock.advance(30.0)
            fleet.expire(int(clock()))  # visits dhcp.expire per worker
            audit = audit_invariants(
                pools=pools, fleet=fleet, fastpath=fastpath,
                check_roundtrip=(ep == epochs - 1),
                metrics=metrics, epoch=ep)
            epochs_out.append({
                "epoch": ep,
                "leased": len(leased),
                "released": sum(1 for k in kind.values()
                                if k == "release"),
                "faults_so_far": len(inj.injected),
                "worker_failures": fleet.worker_failures,
                "shed": dict(sorted(
                    fleet.admission.stats.shed.items())),
                "audit_ok": audit.ok,
                "violations": audit.violations_by_kind(),
            })
    return {
        "schema": REPORT_SCHEMA, "seed": seed,
        "plan": plan.to_dict(),
        "injected": inj.stats_snapshot(),
        "epochs": epochs_out,
        "ok": all(e["audit_ok"] for e in epochs_out),
    }


def run_report(seed: int = 1, names: list[str] | None = None,
               soak_epochs: int = 0, metrics=None,
               storm_scale: float = 1.0) -> dict:
    """The `bng chaos run` payload: scenarios + storms (+ optional
    soak)."""
    report = run_scenarios(seed, names=names, metrics=metrics,
                           storm_scale=storm_scale)
    if soak_epochs > 0:
        report["soak"] = soak(seed, epochs=soak_epochs, metrics=metrics)
        report["ok"] = report["ok"] and report["soak"]["ok"]
    return report


def bench_lines(report: dict) -> list[dict]:
    """One diffable bench_runs.jsonl line per scenario: the
    scenario/shed/degraded triple the loadtest BenchmarkResult also
    carries, so storm runs and load runs diff with the same tooling.
    (Wallclock stamps are the appender's job — these lines stay
    deterministic.)"""
    lines = []
    for name, r in sorted(report.get("scenarios", {}).items()):
        degraded = {}
        for key, label in (("counted_block", "nat_block"),
                           ("counted_port", "nat_port"),
                           ("blocks_refused", "nat_block_refused")):
            if r.get(key):
                degraded[label] = r[key]
        line = {
            "metric": "storm", "scenario": name,
            "ok": bool(r.get("ok", False)),
            "seed": r.get("seed"),
            "shed": dict(r.get("shed", {})),
            "degraded": degraded,
            "violations": dict(r.get("violations", {})),
        }
        # the SLO verdict (telemetry/slo.py check_budget) rides every
        # storm bench line so the perf gate's consumers see WHICH stage
        # blew its envelope, not just a boolean
        if isinstance(r.get("budget"), dict):
            line["slo"] = {"ok": bool(r["budget"].get("ok", False)),
                           "breaches": list(r["budget"].get("breaches", ()))}
        lines.append(line)
    return lines


def canonical_json(report: dict) -> str:
    """Byte-deterministic serialization (sorted keys, fixed separators)
    — the string two same-seed runs are compared on."""
    return json.dumps(report, sort_keys=True, indent=2,
                      separators=(",", ": "))
