"""Composition root + CLI: run / demo / stats / version.

Parity: cmd/bng — cobra run/demo/stats/version (main.go:48-62,421-439),
flag surface + YAML overlay where CLI wins (main.go:195-419, loadConfigFile
main.go:1420-1457), secret-file resolution keeping secrets out of ps
(resolveSecret main.go:1567), runBNG construction order
loader->antispoof->walledgarden->pools->deviceauth->DHCP->Nexus->peer-pool
->HA->BGP/BFD->RADIUS->policy->QoS->NAT(+logger)->PPPoE->DHCPv6->SLAAC->
resilience->metrics with LIFO cleanup (main.go:441-1380), demo mode's
eBPF-free full-lifecycle simulation (demo.go:46-120).

The TPU twist: where runBNG loads XDP programs, run() builds the device
Engine (fused Pallas/jnp pipeline + HBM tables) and drives it from a
packet source; everything else stays host-side control plane. As of
round 5 the full construction order is wired: deviceauth (4a), Nexus
HTTPAllocator + resilience FSM (4b), peer pool (4c), RADIUS accounting
(7b), PPPoE with the device data path (10c), the CoA/Disconnect
listener (10d), TLS/mTLS on the cluster wire, and App.tick as the 1 Hz
maintenance heartbeat for every periodic goroutine of the reference.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

__version__ = "0.1.0"


@dataclasses.dataclass
class BNGConfig:
    """Flattened flag surface (main.go:195-419 subset, grouped)."""

    # dataplane
    server_ip: str = "10.0.0.1"
    server_mac: str = "02:aa:bb:cc:dd:01"
    batch_size: int = 256
    # ICI-sharded serving path (parallel/sharded.py, ISSUE 12): >1 makes
    # `bng run` drive an N-shard ShardedCluster instead of the single-
    # device Engine — tables hash-sharded over the mesh, the ring
    # classifier steering popped batches to owner shards, checkpoints/
    # blue-green swap/chaos audit all sharded-aware. On a machine with
    # no accelerator the mesh is CPU-virtual (forced host device count,
    # the tier-1 posture); set JAX_PLATFORMS=tpu to use real chips.
    # batch_size is the AGGREGATE batch (split evenly across shards).
    shards: int = 1
    # per-shard table geometry for the sharded path (buckets per cuckoo
    # table; sized for the per-shard subscriber slice)
    shard_nbuckets: int = 1 << 10
    # latency-tiered scheduler (runtime/scheduler.py): express DHCP lane +
    # depth-pipelined bulk lane instead of the monolithic pipelined loop
    scheduler_enabled: bool = False
    sched_express_batch: int = 64
    sched_express_max_wait_us: float = 200.0
    # AOT express OFFER path (ISSUE 13): minimal-program lane compiled
    # ahead of time for the express batch geometry, replies patched
    # into preassembled wire templates host-side; a geometry miss falls
    # back to the jit full-program path loudly
    # (bng_express_aot_miss_total + flight-recorder note). Also
    # disabled via BNG_EXPRESS_AOT=0.
    sched_express_aot: bool = True
    sched_bulk_depth: int = 2
    sched_drain_every: int = 1
    # slow-path fleet (control/fleet.py + control/admission.py): N
    # shared-nothing workers sharded by the ring's MAC hash, with
    # admission control in front. workers=1 keeps the single in-process
    # slow path (every integration supported); >1 fans DHCPv4 out.
    slowpath_workers: int = 1
    slowpath_worker_mode: str = "process"  # process | inline
    slowpath_inbox: int = 512  # per-worker admission inbox bound
    slowpath_deadline_ms: float = 50.0  # stale-DISCOVER shed deadline
    slowpath_slice: int = 1024  # per-worker lease-slice target size
    # watermark-driven live fleet elasticity (control/opsctl.py
    # FleetAutoscaler -> SlowPathFleet.resize at the tick boundary)
    slowpath_autoscale: bool = False
    slowpath_min_workers: int = 1
    slowpath_max_workers: int = 8
    # runtime ops control listener (`bng ctl` wire, control/opsctl.py):
    # fleet resize / rolling restart / engine swap on the LIVE process.
    # OPT-IN ("" = disabled, the default): the endpoint is unauthenticated
    # and mutates subscriber-serving state, so even loopback exposure —
    # any local process could resize/swap a production dataplane — is a
    # deployment decision. Enable with --ctl-listen 127.0.0.1:9092.
    ctl_listen: str = ""
    # pools (single primary pool via flags; more via YAML `pools:`)
    pool_cidr: str = "10.0.0.0/16"
    pool_gateway: str = ""
    dns_primary: str = "1.1.1.1"
    dns_secondary: str = "8.8.8.8"
    lease_time: int = 3600
    # per-MAC deterministic lease-time spread in [lt, lt*(1+frac)] —
    # de-synchronizes the expiry cliff a mass bring-up would otherwise
    # schedule (storm suite: lease_expiry_avalanche; PERF_NOTES §10)
    lease_jitter_frac: float = 0.0
    # per-sweep lease-reap bound (DHCPServer.cleanup_expired max_reaps;
    # per WORKER when a fleet runs): one synchronized expiry cliff costs
    # ceil(cliff/batch) ticks instead of starving one dataplane tick.
    # 0 = unbounded (the pre-storm-suite behavior)
    expire_batch: int = 8192
    pools: list = dataclasses.field(default_factory=list)
    # RADIUS
    radius_server: str = ""
    radius_secret: str = ""
    radius_secret_file: str = ""
    # RADIUS accounting (pkg/radius/accounting.go role); active whenever a
    # radius server is configured. Spool path "" = in-memory only.
    acct_interim_interval: int = 300
    acct_spool_path: str = ""
    # CoA/Disconnect listener (RFC 5176; pkg/radius/coa.go role) — on by
    # default when a radius server is configured, like the reference
    coa_enabled: bool = True
    coa_listen: str = "0.0.0.0:3799"
    # PPPoE (pkg/pppoe; wired like main.go:1063-1180)
    pppoe_enabled: bool = False
    pppoe_ac_name: str = "bng-tpu"
    pppoe_service_name: str = ""
    pppoe_auth: str = "chap"  # chap | pap | none
    # local credentials (YAML `pppoe-users: [{username, password}]`);
    # ignored when RADIUS is configured (RADIUS wins, reference behavior)
    pppoe_users: list = dataclasses.field(default_factory=list)
    # NAT
    nat_enabled: bool = True
    nat_public_ips: list = dataclasses.field(default_factory=lambda: ["203.0.113.1"])
    nat_ports_per_subscriber: int = 1024
    nat_log_path: str = ""
    nat_log_format: str = "json"
    nat_bulk_logging: bool = False
    # QoS
    qos_enabled: bool = True
    default_policy: str = "residential-100mbps"
    # walled garden
    walled_garden_enabled: bool = True
    portal_ip: str = "10.255.255.1"
    portal_port: int = 8080
    # DNS wire (control/dns_wire.py): UDP listener serving the resolver,
    # forwarding cache misses upstream with failover
    dns_enabled: bool = False
    dns_listen: str = "0.0.0.0:53"
    dns_upstreams: list = dataclasses.field(
        default_factory=lambda: ["8.8.8.8:53", "1.1.1.1:53"])
    # central Nexus allocator (pkg/nexus HTTPAllocator; main.go:628-756):
    # DHCP allocation tries Nexus first, local pools as fallback; also
    # the health signal the resilience partition FSM watches
    nexus_url: str = ""
    # peer-to-peer shared pool (pkg/pool, Demo G): the agreed range plus
    # node-id -> cluster-URL map (YAML `peer-pool-nodes:
    # [{node: n1, url: "http://..."}]`); "" cidr = peer pool off
    peer_pool_cidr: str = ""
    peer_pool_nodes: list = dataclasses.field(default_factory=list)
    # device->Nexus identity (pkg/deviceauth): none | psk | mtls
    device_auth_method: str = "none"
    device_auth_psk: str = ""
    device_auth_psk_file: str = ""
    device_auth_cert: str = ""
    device_auth_key: str = ""
    # HA
    ha_role: str = ""  # "", "active", "standby"
    ha_peer: str = ""  # active's cluster URL (http://host:port) for standbys
    # clustering (control/cluster_http.py wire)
    cluster_listen: str = ""  # "host:port" ("" = no listener; port 0 = any)
    # cluster-wire TLS (pkg/ha/sync.go:151-185 role). Listener side:
    # cert+key -> the cluster listener speaks TLS; client-ca -> demands
    # verified client certs (mTLS). Client side (ha_peer/store_peers over
    # https): ca/pins verify the peer, client cert+key is our identity.
    cluster_tls_cert: str = ""
    cluster_tls_key: str = ""
    cluster_tls_client_ca: str = ""
    cluster_tls_ca: str = ""
    cluster_tls_pins: list = dataclasses.field(default_factory=list)
    cluster_tls_server_name: str = ""
    cluster_tls_client_cert: str = ""
    cluster_tls_client_key: str = ""
    store_mode: str = "memory"  # memory | read | write (control/crdt.py)
    store_peers: list = dataclasses.field(default_factory=list)  # peer URLs
    # BGP
    bgp_enabled: bool = False
    bgp_local_as: int = 65000
    bgp_router_id: str = ""
    # FRR wiring: when true, BGP commands run through real `vtysh -c`
    # subprocesses (main.go:884-940, bgp.go:554-578); default keeps the
    # inert executor so `run` works without FRR installed
    bgp_vtysh: bool = False
    bgp_vtysh_path: str = "vtysh"
    # routing platform: "stub" (in-memory) | "linux" (iproute2/netlink —
    # real kernel routes/rules; needs CAP_NET_ADMIN)
    routing_platform: str = "stub"
    # checkpoint/warm-restart (runtime/checkpoint.py +
    # control/statestore.py): dir set -> restore-at-start (cold-start
    # fallback on reject) + SIGTERM snapshot; interval > 0 adds the
    # background cadence off the 1 Hz tick
    checkpoint_dir: str = ""
    checkpoint_interval_s: float = 0.0
    checkpoint_keep: int = 3
    # telemetry (bng_tpu/telemetry): span tracing + per-batch flight
    # recorder. Off by default (disarmed hooks cost one global load per
    # call site); BNG_TELEMETRY=1 arms it too (the env is how fleet
    # worker processes inherit the setting).
    telemetry_enabled: bool = False
    trace_dir: str = ""  # "" -> $BNG_TRACE_DIR or <tmp>/bng-flightrec
    trace_budget_us: float = 0.0  # latency-excursion dump trigger; 0=off
    # SLO engine (bng_tpu/telemetry/slo.py): live burn-rate evaluation
    # of per-stage latency budgets over the armed tracer's histograms.
    # Active only when telemetry is armed (no tracer -> nothing to
    # evaluate); breach -> slo_breach flight dump + bng_slo_* families.
    slo_enabled: bool = True
    slo_window_s: float = 30.0  # burn-rate window length
    slo_burn_windows: int = 2  # consecutive bad windows before a breach
    # per-stage budget overrides, "stage:limit_us[:per]" (default:
    # telemetry/slo.py DEFAULT_SLOS — envelopes 1-2 orders above the
    # CPU-dev means, the paper's 50us target on the fenced device stage)
    slo_budgets: list = dataclasses.field(default_factory=list)
    # metrics
    metrics_port: int = 9090
    metrics_enabled: bool = True
    # dhcpv6 / slaac
    dhcpv6_enabled: bool = True
    dhcpv6_prefix: str = "2001:db8:1::/64"
    # reply-source for framed DHCPv6 ("" = EUI-64 link-local of
    # server_mac); set a global address when clients reach us via a relay
    dhcpv6_server_ip: str = ""
    slaac_enabled: bool = True
    # wire (AF_XDP attach ladder; runtime/xsk.py)
    wire_if: str = ""  # NIC to bind AF_XDP on ("" = in-memory ring only)
    wire_queue: int = 0
    # wire pump implementation (runtime/xsk.py WirePump): "" resolves
    # BNG_WIRE_PUMP (default scalar); "vector" runs the batch-native
    # pump over the native batch verbs (ISSUE 15)
    wire_pump: str = ""
    synthetic_subs: int = 0  # >0: generate DISCOVER/data traffic (smoke)
    # logging (main.go:1398-1418 zap production config role)
    log_level: str = "info"
    log_format: str = "json"  # json | console
    # misc
    node_id: str = "bng0"


def pppoe_sid(sess) -> str:
    """One Acct-Session-Id format for a PPPoE session — shared by
    accounting start/stop, the CoA locator, and HA replication keys
    (drifting copies would strand sessions in the standby store)."""
    return f"pppoe-{sess.session_id:04x}-{sess.client_mac.hex()}"


def resolve_secret(value: str, file_path: str) -> str:
    """main.go:1567: prefer --*-file so secrets stay out of ps."""
    if file_path:
        with open(file_path) as f:
            return f.read().strip()
    return value


def load_config_file(path: str, cli_set: set[str],
                     base: BNGConfig) -> BNGConfig:
    """YAML overlay applied only to fields NOT set on the CLI
    (main.go:1420-1457: CLI wins)."""
    import yaml
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    for key, value in data.items():
        key = key.replace("-", "_")
        if key in cli_set or not hasattr(base, key):
            continue
        setattr(base, key, value)
    return base


from bng_tpu.analysis.sanitize import ctx_enter as _sanitize_ctx_enter
from bng_tpu.analysis.sanitize import owned_by as _owned_by


@_owned_by("loop", guard="_ctl")
class BNGApp:
    """Everything `bng run` constructs, with LIFO cleanup
    (main.go:441-1380).

    Ownership (BNG_SANITIZE): app state belongs to the loop context;
    any other context (ctl handler, scrape, HA sync) must hold `_ctl`
    to mutate — the @owned_by stamp makes a dropped `with self._ctl`
    an OwnershipViolation in sanitizer runs instead of a silent race."""

    def __init__(self, config: BNGConfig, clock=time.time):
        self.config = config
        self.clock = clock
        self._cleanup = []
        self._last_sync = 0.0
        self._last_expire = 0.0
        self._last_garden = 0.0
        self._last_acct_sync = 0.0
        self._last_acct_retry = 0.0
        # serializes CoA-listener-thread actions against the main loop's
        # slow path + maintenance sweeps (lease dict, QoS tables, demux
        # pending queue) — the goroutine-with-mutex role of the reference
        import threading as _threading

        self._ctl = _threading.Lock()
        self._syn_i = 0
        # sharded serving: the per-beat slow-path handler (demux or the
        # DHCP server) — the cluster takes it per call, unlike the
        # engine which owns a reference
        self._slow_path = None
        self.components: dict[str, object] = {}
        try:
            self._build()
        except BaseException:
            # a half-built app leaks live resources (listener threads,
            # bound sockets, AF_XDP attachments): run the LIFO cleanup for
            # whatever was already wired before re-raising
            self.close()
            raise

    def _on_close(self, fn) -> None:
        self._cleanup.append(fn)

    def _build(self) -> None:
        import ipaddress

        from bng_tpu.utils import structlog

        structlog.setup(self.config.log_level, self.config.log_format)
        self.log = structlog.get_logger("app", node_id=self.config.node_id)

        # 0. telemetry — armed FIRST so every later construction step
        # (fleet spawn exports BNG_TELEMETRY to workers; engine/scheduler
        # spans) sees the armed tracer. Metrics attach at step 13.
        import os as _os

        if self.config.telemetry_enabled or _os.environ.get(
                "BNG_TELEMETRY") == "1":
            from bng_tpu.telemetry import (FlightRecorder, RecorderConfig,
                                           spans as tele_spans)

            recorder = FlightRecorder(RecorderConfig(
                latency_budget_us=self.config.trace_budget_us,
                out_dir=self.config.trace_dir))
            tracer = self.components["telemetry"] = tele_spans.arm(
                tele_spans.Tracer(recorder=recorder))
            self._on_close(tele_spans.disarm)
            self.log.info("telemetry armed",
                          trace_dir=recorder.cfg.out_dir or "(default)",
                          budget_us=self.config.trace_budget_us)
            if self.config.slo_enabled:
                # the SLO engine rides the armed tracer: rolling
                # burn-rate windows over the stage histograms, ticked
                # by the 1 Hz heartbeat; breach -> slo_breach flight
                # dump + bng_slo_* (collect_slo at step 13)
                from bng_tpu.telemetry import slo as slo_mod

                budgets = (slo_mod.parse_budgets(
                    list(self.config.slo_budgets))
                    if self.config.slo_budgets else slo_mod.DEFAULT_SLOS)
                self.components["slo"] = slo_mod.SLOMonitor(
                    tracer, slos=budgets,
                    window_s=self.config.slo_window_s,
                    burn_windows=self.config.slo_burn_windows)
                self.log.info("slo monitor armed",
                              window_s=self.config.slo_window_s,
                              burn_windows=self.config.slo_burn_windows,
                              budgets=len(budgets))

        from bng_tpu.control import walledgarden as wg
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.metrics import BNGMetrics, MetricsCollector
        from bng_tpu.control.nat import NATManager
        from bng_tpu.control.nat_logging import (NATComplianceLogger,
                                                 NATLoggerConfig)
        from bng_tpu.control.nexus import NexusClient
        from bng_tpu.control.pool import Pool, PoolManager
        from bng_tpu.control.radius.policy import PolicyManager
        from bng_tpu.control.subscriber import SubscriberManager
        from bng_tpu.runtime.engine import AntispoofTables, Engine, QoSTables
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32, parse_mac

        cfg = self.config
        c = self.components

        # 1. device tables (the Loader.Load role, main.go:498-506).
        # --shards N promotes the ICI-sharded dataplane to the serving
        # path (ISSUE 12): an N-shard ShardedCluster replaces the
        # single-device Engine, and every fast-path write routes to its
        # owner shard through the ShardedFastPathSink facade. Features
        # whose wiring is engine-specific degrade with a warning
        # (tracked in sharded_blockers, exported like fleet_blockers).
        self.sharded_blockers: list[str] = []
        if cfg.shards > 1:
            import os as _sh_os

            if "tpu" not in _sh_os.environ.get("JAX_PLATFORMS", "").lower():
                # CPU tier-1 posture: force the host-device mesh BEFORE
                # any backend init (XLA_FLAGS
                # --xla_force_host_platform_device_count)
                from bng_tpu.utils.jaxenv import force_cpu

                force_cpu(cfg.shards)
            from bng_tpu.parallel.sharded import (ShardedCluster,
                                                  ShardedFastPathSink)

            self.sharded_blockers = [name for flag, name in (
                (cfg.scheduler_enabled, "scheduler"),
                (cfg.pppoe_enabled, "pppoe"),
                (cfg.wire_if, "wire"),
                (cfg.slowpath_workers > 1, "slowpath-fleet")) if flag]
            if self.sharded_blockers:
                # same posture as the fleet blockers: the sharded path
                # serves, the engine-specific feature degrades LOUDLY
                self.log.warning(
                    "sharded serving: engine-specific features disabled",
                    blockers=self.sharded_blockers, shards=cfg.shards)
            pub_ips = [ip_to_u32(ip) for ip in cfg.nat_public_ips]
            while len(pub_ips) < cfg.shards:
                # each shard must own its public IPs exclusively
                # (downstream ring steering is by-IP): extend the
                # configured block consecutively
                pub_ips.append((pub_ips[-1] + 1) if pub_ips
                               else ip_to_u32("203.0.113.1") + len(pub_ips))
            cluster = c["cluster"] = ShardedCluster(
                cfg.shards,
                batch_per_shard=max(8, cfg.batch_size // cfg.shards),
                sub_nbuckets=cfg.shard_nbuckets,
                vlan_nbuckets=max(64, cfg.shard_nbuckets // 4),
                cid_nbuckets=max(64, cfg.shard_nbuckets // 4),
                nat_sessions_nbuckets=cfg.shard_nbuckets,
                qos_nbuckets=cfg.shard_nbuckets,
                spoof_nbuckets=cfg.shard_nbuckets,
                public_ips=pub_ips,
                garden_enabled=cfg.walled_garden_enabled,
                server_mac=parse_mac(cfg.server_mac))
            # resolver, NOT the object: a blue/green swap replaces
            # c["cluster"] and every later DHCP/pool write must follow
            # the flip to the serving cluster
            fastpath = c["fastpath_sink"] = ShardedFastPathSink(
                lambda: c["cluster"])
            self.log.info("sharded cluster built", shards=cfg.shards,
                          batch_per_shard=cluster.b,
                          nbuckets=cfg.shard_nbuckets)
        else:
            fastpath = c["fastpath"] = FastPathTables()
        fastpath.set_server_config(
            parse_mac(cfg.server_mac),
            ip_to_u32(cfg.server_ip))

        # 2. antispoof + walled garden (main.go:509-564)
        c["antispoof"] = AntispoofTables()
        if cfg.walled_garden_enabled:
            garden = c["walledgarden"] = wg.WalledGardenManager(
                wg.WalledGardenConfig(portal_ip=cfg.portal_ip,
                                      portal_port=cfg.portal_port),
                clock=self.clock)
            self._on_close(lambda: garden.check_expired())

        # 2b. DNS wire (pkg/dns role, now with a real socket): UDP listener
        # serving the resolver; walled-garden subscribers get the portal
        # for every name, everyone else forwards upstream with failover
        if cfg.dns_enabled:
            from bng_tpu.control.dns import DNSConfig, Resolver
            from bng_tpu.control.dns_wire import DNSServer, UDPForwarder

            dns_cfg = DNSConfig(upstreams=list(cfg.dns_upstreams),
                                walled_garden_redirect_ip=cfg.portal_ip)
            resolver = c["dns_resolver"] = Resolver(
                dns_cfg, forwarder=UDPForwarder(dns_cfg.upstreams,
                                                timeout=dns_cfg.timeout))
            host, _, port = cfg.dns_listen.partition(":")
            dns_srv = c["dns_server"] = DNSServer(
                resolver, host=host or "0.0.0.0", port=int(port or 53))
            dns_srv.start()
            self._on_close(dns_srv.stop)
            self.log.info("dns listener", addr=f"{dns_srv.addr[0]}:"
                                               f"{dns_srv.addr[1]}")

        # 3. pools (main.go:567-594)
        pool_mgr = c["pools"] = PoolManager(fastpath_tables=fastpath)
        pool_specs = cfg.pools or [{
            "cidr": cfg.pool_cidr, "gateway": cfg.pool_gateway,
            "lease_time": cfg.lease_time}]
        for i, spec in enumerate(pool_specs, start=1):
            if isinstance(spec, str):  # --pools 10.1.0.0/24 (CLI shorthand)
                spec = {"cidr": spec}
            net = ipaddress.ip_network(spec["cidr"])
            gw = spec.get("gateway") or str(net.network_address + 1)
            pool_mgr.add_pool(Pool(
                pool_id=i, network=int(net.network_address),
                prefix_len=net.prefixlen, gateway=ip_to_u32(gw),
                dns_primary=ip_to_u32(spec.get("dns_primary", cfg.dns_primary)),
                dns_secondary=ip_to_u32(spec.get("dns_secondary",
                                                 cfg.dns_secondary)),
                lease_time=int(spec.get("lease_time", cfg.lease_time)),
                client_class=int(spec.get("client_class", 0))))

        # 4. Nexus + subscriber orchestration (main.go:628-756 role)
        c["nexus"] = NexusClient(node_id=cfg.node_id, clock=self.clock)
        c["subscribers"] = SubscriberManager(clock=self.clock)

        # 4a. device identity for the Nexus wire (pkg/deviceauth;
        # main.go's deviceauth construction slot)
        if cfg.device_auth_method != "none":
            from bng_tpu.control import deviceauth as da

            if cfg.device_auth_method == "psk":
                c["deviceauth"] = da.PSKAuthenticator(
                    psk=cfg.device_auth_psk,
                    psk_file=cfg.device_auth_psk_file)
            elif cfg.device_auth_method == "mtls":
                c["deviceauth"] = da.MTLSAuthenticator(
                    cert_file=cfg.device_auth_cert,
                    key_file=cfg.device_auth_key)
            else:
                raise ValueError(
                    f"device_auth_method={cfg.device_auth_method!r}: "
                    f"expected 'none', 'psk' or 'mtls'")

        # 4b. central allocator client + partition resilience. The
        # adapter narrows HTTPAllocator's ip-string API to the DHCP
        # server's int contract, and goes straight to the local pool
        # while partitioned (one timeout per DISCOVER would melt the
        # slow path — the resilience FSM owns retry cadence instead).
        nexus_alloc = None
        resilience = None
        if cfg.nexus_url:
            from bng_tpu.control.cluster_http import http_nexus_transport
            from bng_tpu.control.nexus import HTTPAllocator
            from bng_tpu.control.resilience import ResilienceManager

            nexus_tls = (self._cluster_client_tls()
                         if cfg.nexus_url.startswith("https") else None)
            nexus_http = c["nexus_allocator"] = HTTPAllocator(
                cfg.nexus_url,
                http_nexus_transport(cfg.nexus_url, tls=nexus_tls),
                node_id=cfg.node_id)
            resilience = c["resilience"] = ResilienceManager(
                nexus_healthy=nexus_http.health_check)

            class _NexusAlloc:
                def allocate(self, owner):
                    if resilience.partitioned:
                        return None  # local-pool fallback, no timeout
                    try:
                        ip = nexus_http.allocate(owner)
                    except Exception:
                        return None
                    return ip_to_u32(ip) if ip else None

                def release(self, owner):
                    if resilience.partitioned:
                        return  # no 3s timeout per expired lease during
                        # an outage; heal-time reconciliation covers it
                    try:
                        nexus_http.release(owner)
                    except Exception:
                        pass

            nexus_alloc = _NexusAlloc()

        # 4c. peer-to-peer shared pool (pkg/pool/peer.go; Demo G):
        # HRW owner-or-forward over the cluster HTTP wire
        if cfg.peer_pool_cidr and cfg.peer_pool_nodes:
            from bng_tpu.control.cluster_http import HTTPPeerProxy
            from bng_tpu.control.peerpool import PeerPool, PoolRange

            net = ipaddress.ip_network(cfg.peer_pool_cidr)
            node_urls = {str(n["node"]): str(n["url"])
                         for n in cfg.peer_pool_nodes}
            if cfg.node_id not in node_urls:
                raise ValueError(
                    f"peer_pool_nodes must include this node "
                    f"({cfg.node_id!r}): peers agree on one member list")

            # proxies built ONCE per node: each would otherwise rebuild
            # its TLS context (cert/CA file reads) per forwarded request
            peer_proxies: dict[str, object] = {}

            def _peer_transport(node, _urls=node_urls):
                proxy = peer_proxies.get(node)
                if proxy is None:
                    url = _urls.get(node)
                    if url is None:
                        raise ConnectionError(f"unknown peer {node}")
                    proxy = peer_proxies[node] = HTTPPeerProxy(
                        url, tls=(self._cluster_client_tls()
                                  if url.startswith("https") else None))
                return proxy

            # PeerPool allocates network+1+idx (it skips the network
            # address itself): pass the RAW base, usable = hosts only
            c["peerpool"] = PeerPool(
                cfg.node_id, sorted(node_urls),
                PoolRange(network=int(net.network_address),
                          size=max(net.num_addresses - 2, 1)),
                transport=_peer_transport)
            self.log.info("peer pool", nodes=sorted(node_urls),
                          cidr=cfg.peer_pool_cidr)

        # 5. RADIUS (main.go:946-973)
        authenticator = None
        radius_server_cfgs: list = []  # picklable, reused by the fleet
        if cfg.radius_server:
            from bng_tpu.control.radius.client import (RadiusClient,
                                                       RadiusServerConfig)
            secret = resolve_secret(cfg.radius_secret, cfg.radius_secret_file)
            host, _, port = cfg.radius_server.partition(":")
            radius_server_cfgs = [RadiusServerConfig(
                host=host, auth_port=int(port or 1812),
                secret=secret.encode())]
            radius = c["radius"] = RadiusClient(servers=radius_server_cfgs)

            def authenticator(username="", password="", mac=b"",
                              circuit_id=b"", **kw):
                res = radius.authenticate(username, password, mac=mac,
                                          circuit_id=circuit_id)
                key = username or mac.hex()
                if res is None:
                    # every server timed out: degraded auth from the
                    # cached profile (radius_handler.go:134-489 role) —
                    # an outage must not evict paying subscribers
                    if resilience is not None:
                        cached = resilience.radius_handler.degraded_auth(
                            key, self.clock())
                        if cached is not None:
                            return {"qos_policy": cached.policy_name,
                                    "framed_ip": cached.framed_ip}
                    return None
                if not res.success:
                    return None  # a real REJECT is never served from cache
                if resilience is not None:
                    from bng_tpu.control.resilience import CachedProfile

                    resilience.radius_handler.cache_profile(CachedProfile(
                        username=key, policy_name=res.policy_name,
                        framed_ip=res.framed_ip, cached_at=self.clock()))
                # keys DHCPServer._request actually consumes: qos_policy
                # (Filter-Id -> policy, server.go:774-794 role) and
                # lease_time (Session-Timeout caps the lease)
                profile = {"qos_policy": res.policy_name,
                           "framed_ip": res.framed_ip,
                           **res.attributes}
                if res.session_timeout:
                    profile["lease_time"] = res.session_timeout
                return profile

        # 6. QoS (main.go:977-995)
        qos = None if cfg.shards > 1 else QoSTables()
        if qos is not None:
            c["qos"] = qos
        policies = c["policies"] = PolicyManager()
        qos_hook = None
        if cfg.qos_enabled:
            if cfg.shards > 1:
                # owner-shard routing: the policy row lands on the
                # subscriber's affinity shard (the only shard the ring
                # ever steers its traffic to)
                def qos_hook(ip, policy_name):
                    p = policies.get(policy_name or cfg.default_policy)
                    if p is not None:
                        c["cluster"].set_qos(
                            ip, down_bps=p.download_bps,
                            up_bps=p.upload_bps, priority=p.priority)
            else:
                def qos_hook(ip, policy_name):
                    p = policies.get(policy_name or cfg.default_policy)
                    if p is not None:
                        qos.set_subscriber(ip, p.download_bps, p.upload_bps,
                                           priority=p.priority)

        # 7. NAT + compliance logger (main.go:1000-1060). Sharded: NAT
        # state is chip-local per shard inside the cluster (subscriber-
        # affinity placement); the hook routes allocations to the owner.
        # The per-event compliance logger is engine-wiring and degrades
        # (documented in README "Sharded serving").
        nat = None
        nat_hook = None
        if cfg.shards > 1:
            if cfg.nat_enabled:
                def nat_hook(ip, now):
                    c["cluster"].allocate_nat(ip, int(now))
        elif cfg.nat_enabled:
            nat_logger = c["nat_logger"] = NATComplianceLogger(
                NATLoggerConfig(file_path=cfg.nat_log_path,
                                fmt=cfg.nat_log_format,
                                bulk_logging=cfg.nat_bulk_logging),
                clock=self.clock)
            self._on_close(nat_logger.close)
            nat = c["nat"] = NATManager(
                public_ips=[ip_to_u32(ip) for ip in cfg.nat_public_ips],
                ports_per_subscriber=cfg.nat_ports_per_subscriber,
                log_sink=nat_logger.log_device_event)
            def nat_hook(ip, now):
                nat.allocate_nat(ip, int(now))
        else:
            nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                             sessions_nbuckets=256, sub_nat_nbuckets=64)

        # 7b. RADIUS accounting (accounting.go:410-497 role): start/stop
        # ride the DHCP lease lifecycle; interim/retry fire from App.tick.
        # Installed BEFORE the garden wiring so its hook chain (9b)
        # preserves accounting.
        acct = None
        if "radius" in c:
            from bng_tpu.control.radius.accounting import AccountingManager
            acct = c["accounting"] = AccountingManager(
                c["radius"],
                interim_interval_s=cfg.acct_interim_interval,
                spool_path=cfg.acct_spool_path or None,
                clock=self.clock)

        # 8. DHCP server, wired like main.go:642 + SetXxx hooks
        dhcp = c["dhcp"] = DHCPServer(
            server_mac=parse_mac(cfg.server_mac),
            server_ip=ip_to_u32(cfg.server_ip),
            pool_manager=pool_mgr, fastpath_tables=fastpath,
            allocator=nexus_alloc,
            authenticator=authenticator, qos_hook=qos_hook,
            nat_hook=nat_hook, clock=self.clock,
            lease_jitter_frac=cfg.lease_jitter_frac)
        if resilience is not None:
            # heal-time reconciliation (manager.go:342-528): the central
            # store answers who owns each partition-allocated IP, and the
            # loser of a conflict gets force-renumbered (its lease is
            # expired so the client re-DORAs onto a fresh address)
            from bng_tpu.utils.net import mac_to_u64, u32_to_ip

            def _central_lookup(ip_u32, _nx=c["nexus_allocator"]):
                try:
                    return _nx.lookup_by_ip(u32_to_ip(ip_u32))
                except Exception:
                    return None  # unreachable mid-heal: no verdict

            def _renumber(subscriber_id, _dhcp=dhcp):
                try:
                    mac = bytes.fromhex(subscriber_id)
                except ValueError:
                    return False
                lease = _dhcp.leases.get(mac_to_u64(mac))
                if lease is None:
                    return False
                lease.expiry = 0
                _dhcp.cleanup_expired(1)  # reaps only the forced lease
                return True

            resilience.central_lookup = _central_lookup
            resilience.renumber = _renumber
            # partition-time allocations feed the conflict detector so
            # heal-time reconciliation can renumber losers
            prev_res_acct = dhcp.accounting_hook

            def _res_lease(event, lease, sid, _res=resilience):
                if prev_res_acct is not None:
                    prev_res_acct(event, lease, sid)
                if event == "start":
                    _res.record_allocation(lease.mac.hex(), lease.ip,
                                           self.clock())

            dhcp.accounting_hook = _res_lease
        if acct is not None:
            from bng_tpu.utils.net import u32_to_ip as _u32ip

            prev_acct_hook = dhcp.accounting_hook  # chain (resilience 8a)

            def _acct_lease(event, lease, sid, _acct=acct):
                if prev_acct_hook is not None:
                    prev_acct_hook(event, lease, sid)
                if event == "start":
                    _acct.start(sid, username=lease.username
                                or _u32ip(lease.ip), framed_ip=lease.ip,
                                mac="-".join(f"{b:02X}" for b in lease.mac))
                elif event == "stop":
                    _acct.stop(sid)  # renew extends, it never stops

            dhcp.accounting_hook = _acct_lease

        # 9. engine: the TPU dataplane replacing the XDP attach. The
        # device-side garden gate compiles in only when the walled garden
        # is enabled (a disabled feature must cost zero per batch).
        garden_tables = None
        if cfg.walled_garden_enabled and cfg.shards <= 1:
            from bng_tpu.runtime.engine import GardenTables

            garden_tables = GardenTables()
        pppoe_tables = None
        if cfg.pppoe_enabled and cfg.shards <= 1:
            from bng_tpu.runtime.tables import PPPoEFastPathTables

            pppoe_tables = c["pppoe_tables"] = PPPoEFastPathTables(
                server_mac=parse_mac(cfg.server_mac))
        if cfg.shards > 1:
            # the cluster IS the dataplane: drive_once feeds its steered
            # ring loop; the slow path is attached per beat (10b)
            self._slow_path = dhcp.handle_frame
        else:
            c["engine"] = Engine(
                fastpath=fastpath, nat=nat, qos=qos,
                antispoof=c["antispoof"],
                garden=garden_tables, pppoe=pppoe_tables,
                batch_size=cfg.batch_size, slow_path=dhcp.handle_frame,
                clock=self.clock)
            self.log.info("engine built", batch_size=cfg.batch_size,
                          nat=cfg.nat_enabled, qos=cfg.qos_enabled)
        if "telemetry" in c:
            import jax as _jax

            # flight records must name the backend that actually served
            # them — the gray-failure flag (a CPU fallback must never
            # read as a TPU run)
            c["telemetry"].recorder.set_backend(_jax.default_backend())

        # 9a. latency-tiered scheduler over the engine's two programs
        # (express DHCP / depth-pipelined bulk) — opt-in; drive_once then
        # feeds it frame-wise instead of the monolithic pipelined step
        if cfg.scheduler_enabled and cfg.shards <= 1:
            from bng_tpu.runtime.scheduler import (SchedulerConfig,
                                                   TieredScheduler)

            c["scheduler"] = TieredScheduler(c["engine"], SchedulerConfig(
                express_batch=cfg.sched_express_batch,
                express_max_wait_us=cfg.sched_express_max_wait_us,
                express_aot=cfg.sched_express_aot,
                bulk_batch=cfg.batch_size,
                bulk_depth=cfg.sched_bulk_depth,
                drain_every=cfg.sched_drain_every), clock=self.clock)
            self._on_close(c["scheduler"].close)
            self.log.info("scheduler built",
                          express_batch=cfg.sched_express_batch,
                          express_aot=cfg.sched_express_aot,
                          bulk_depth=cfg.sched_bulk_depth)

        # 9b. walled-garden enforcement sync. One MAC-state feed drives
        # BOTH enforcement points: the DEVICE gate (engine.garden — a
        # pre-auth subscriber's data traffic drops on-chip; beyond the
        # reference, whose garden maps reach no bpf program) and, when
        # enabled, the DNS resolver's per-client portal answers
        # (resolver.go:150-157 role). A MAC's garden state maps to its
        # lease IP at each garden transition AND each lease event (grant
        # applies the current state — covers garden-before-DHCP; stop
        # scrubs the IP so a reassigned address inherits nothing).
        if cfg.walled_garden_enabled:
            from bng_tpu.control.walledgarden import SubscriberState
            from bng_tpu.utils.net import u32_to_ip

            garden = c["walledgarden"]
            if cfg.shards > 1:
                # owner-shard routing facade: membership lands on the
                # subscriber's affinity shard, allowed destinations are
                # policy (replicated to every shard). Resolves the live
                # cluster per call so garden writes follow a swap.
                class _ShardedGardenGate:
                    def __init__(self, resolve):
                        self._resolve = resolve

                    def set_gardened(self, ip, gardened):
                        self._resolve().set_gardened(ip, gardened)

                    def allow_destination(self, ip, port=0, proto=0):
                        self._resolve().allow_garden_destination(
                            ip, port, proto)

                gt = _ShardedGardenGate(lambda: c["cluster"])
            else:
                gt = c["engine"].garden
            resolver = c.get("dns_resolver")
            # allowed destinations (manager.go:95-103): the portal on ANY
            # TCP port (the DNS-redirect flow lands on the original URL's
            # port 80/443, not just the portal's own listener) and every
            # DNS server a gardened client could plausibly query — the
            # addresses DHCP actually advertises (global + per-pool) plus
            # the garden config's allowlist; a gardened client whose
            # resolver the gate drops could never even reach the portal.
            gt.allow_destination(ip_to_u32(cfg.portal_ip), 0, 6)
            dns_ips = {cfg.dns_primary, cfg.dns_secondary,
                       *garden.config.allowed_dns}
            for spec in pool_specs:
                if isinstance(spec, dict):
                    dns_ips |= {spec.get("dns_primary", ""),
                                spec.get("dns_secondary", "")}
            for d in sorted(d for d in dns_ips if d):
                gt.allow_destination(ip_to_u32(d), 53, 0)

            def _apply_garden_ip(state, ip_u32, _resolver=resolver, _gt=gt):
                # DEVICE gate: only EXPLICIT garden membership drops
                # on-chip — UNKNOWN (never registered) stays unenforced,
                # or a default-on garden would drop every data packet of
                # subscribers the portal flow never touched.
                # DNS resolver: keeps the manager's own stricter contract
                # (everything non-PROVISIONED is gardened, UNKNOWN
                # included) — portal answers are harmless-if-wrong in the
                # way a device drop is not, and the reference's resolver
                # behaves this way (resolver.go:150-157).
                _gt.set_gardened(ip_u32, state in (
                    SubscriberState.WALLED_GARDEN, SubscriberState.BLOCKED))
                if _resolver is not None:
                    ip = u32_to_ip(ip_u32)
                    if state == SubscriberState.PROVISIONED:
                        _resolver.remove_walled_garden_client(ip)
                    else:
                        _resolver.add_walled_garden_client(ip)

            def _garden_sync(mac_u64, state, _dhcp=dhcp):
                lease = _dhcp.leases.get(mac_u64)
                if lease is not None:
                    _apply_garden_ip(state, lease.ip)

            garden.on_state_change(_garden_sync)

            prev_acct = dhcp.accounting_hook

            def _lease_sync(event, lease, sid, _garden=garden,
                            _resolver=resolver, _gt=gt):
                if prev_acct is not None:
                    prev_acct(event, lease, sid)
                if event in ("start", "renew"):
                    _apply_garden_ip(_garden.get_subscriber_state(lease.mac),
                                     lease.ip)
                elif event == "stop":
                    _gt.set_gardened(lease.ip, False)
                    if _resolver is not None:
                        _resolver.remove_walled_garden_client(
                            u32_to_ip(lease.ip))

            dhcp.accounting_hook = _lease_sync

        # 10. DHCPv6 + SLAAC (main.go:1063-1180)
        if cfg.dhcpv6_enabled:
            from bng_tpu.control.dhcpv6.server import (AddressPool6,
                                                       DHCPv6Server,
                                                       DHCPv6ServerConfig)
            server_ip6 = b""
            if cfg.dhcpv6_server_ip:
                server_ip6 = ipaddress.IPv6Address(
                    cfg.dhcpv6_server_ip).packed
            c["dhcpv6"] = DHCPv6Server(
                DHCPv6ServerConfig(server_mac=parse_mac(cfg.server_mac),
                                   server_ip6=server_ip6),
                address_pool=AddressPool6(cfg.dhcpv6_prefix,
                                          cfg.lease_time, cfg.lease_time * 2),
                clock=self.clock)
        if cfg.slaac_enabled:
            from bng_tpu.control.slaac import SLAACConfig, SLAACServer
            c["slaac"] = SLAACServer(SLAACConfig())

        # 10c. PPPoE server (pkg/pppoe; main.go:1063-1180 construction
        # role). Negotiation is host-side via PASS lanes; OPEN sessions
        # publish to the device tables (10's pppoe_tables) so DATA frames
        # decap/encap in the fused pipeline.
        if cfg.pppoe_enabled:
            from bng_tpu.control.pppoe.auth import (LocalVerifier,
                                                    RadiusVerifier)
            from bng_tpu.control.pppoe.codec import PROTO_CHAP, PROTO_PAP
            from bng_tpu.control.pppoe.server import (PPPoEServer,
                                                      PPPoEServerConfig)

            if "radius" in c:
                verifier = RadiusVerifier(c["radius"])
            else:
                creds = {}
                for u in cfg.pppoe_users:
                    if isinstance(u, dict):
                        creds[str(u["username"])] = str(u["password"]).encode()
                verifier = LocalVerifier(creds)
            auth_proto = {"chap": PROTO_CHAP, "pap": PROTO_PAP,
                          "none": 0}.get(cfg.pppoe_auth)
            if auth_proto is None:
                raise ValueError(f"pppoe_auth={cfg.pppoe_auth!r}: "
                                 f"expected 'chap', 'pap' or 'none'")

            def _pppoe_alloc(username, mac, _pools=pool_mgr):
                pool = _pools.classify(0)
                if pool is None:
                    return None
                try:
                    return pool.allocate(f"pppoe:{mac.hex()}")
                except Exception:
                    return None  # exhaustion -> Service-Unavailable PADT

            def _pppoe_release(ip, mac, _pools=pool_mgr):
                pool = _pools.pool_for_ip(ip)
                if pool is not None:
                    pool.release(ip)

            def _pppoe_open(sess, _acct=acct):
                # a RADIUS Framed-IP-Address bypasses _pppoe_alloc
                # (server.py _start_network prefers it); reserve it in the
                # owning pool or DHCP could hand the same address out.
                # allocate_specific is idempotent for the same owner, so
                # pool-allocated sessions cost one no-op re-claim.
                pool = pool_mgr.pool_for_ip(sess.assigned_ip)
                if pool is not None:
                    pool.allocate_specific(sess.assigned_ip,
                                           f"pppoe:{sess.client_mac.hex()}")
                pppoe_tables.session_up(sess)
                if cfg.qos_enabled:
                    qos_hook(sess.assigned_ip,
                             sess.radius_attributes.get("qos_policy"))
                if cfg.nat_enabled:
                    nat.allocate_nat(sess.assigned_ip, int(self.clock()))
                if _acct is not None:
                    _acct.start(pppoe_sid(sess), username=sess.username,
                                framed_ip=sess.assigned_ip,
                                mac="-".join(f"{b:02X}"
                                             for b in sess.client_mac))

            def _pppoe_close(event, _acct=acct):
                sess = event.session
                pppoe_tables.session_down(event)
                if cfg.qos_enabled and sess.assigned_ip:
                    qos.remove_subscriber(sess.assigned_ip)
                if cfg.nat_enabled and sess.assigned_ip:
                    nat.release_nat(sess.assigned_ip, int(self.clock()))
                if _acct is not None:
                    _acct.stop(pppoe_sid(sess))

            c["pppoe"] = PPPoEServer(
                PPPoEServerConfig(
                    ac_name=cfg.pppoe_ac_name,
                    service_name=cfg.pppoe_service_name,
                    server_mac=parse_mac(cfg.server_mac),
                    our_ip=ip_to_u32(cfg.server_ip),
                    dns_primary=ip_to_u32(cfg.dns_primary),
                    dns_secondary=ip_to_u32(cfg.dns_secondary),
                    auth_proto=auth_proto),
                verifier, _pppoe_alloc, release_ip=_pppoe_release,
                on_open=_pppoe_open, on_close=_pppoe_close)
            self.log.info("pppoe server", ac_name=cfg.pppoe_ac_name,
                          auth=cfg.pppoe_auth,
                          backend="radius" if "radius" in c else "local")

        # 10b. slow-path demux: the reference runs one socket+goroutine
        # per protocol server; here every PASSed frame lands on the ring's
        # one slow queue, so the engine's slow_path becomes a dispatcher
        # over whatever servers are enabled (v4 handled even alone)
        if cfg.dhcpv6_enabled or cfg.slaac_enabled or cfg.pppoe_enabled:
            from bng_tpu.control.slowpath import SlowPathDemux

            demux = c["slowpath"] = SlowPathDemux(
                dhcp=dhcp, dhcpv6=c.get("dhcpv6"), slaac=c.get("slaac"),
                pppoe=c.get("pppoe"), clock=self.clock)
            if cfg.shards > 1:
                self._slow_path = demux
            else:
                c["engine"].slow_path = demux

        # 10b2. slow-path fleet: shard DHCPv4 across N shared-nothing
        # workers (control/fleet.py). Workers own per-worker lease
        # slices carved from the parent pools and relay table writes
        # back through the single-writer drain; non-DHCPv4 slow frames
        # (v6/SLAAC/PPPoE) stay on the parent demux via the fallback.
        # Integrations that live on the parent's per-lease state
        # (PPPoE) are not yet fleet-aware: with any of them configured
        # the fleet is skipped so no integration silently degrades.
        # Fleet-aware and OFF the blocker list: `ha` (worker lease
        # events relay through the active's syncer push), `radius`
        # (per-worker RadiusClient on the MAC steering hash — ISSUE 19,
        # accounting start/stop riding the same lease-event relay, CoA
        # routed to the owning shard), `peer-pool` (parent-side only:
        # it mounts on the cluster HTTP server and health-checks in
        # tick — it never sits in the DHCP allocation path), and
        # `nexus` (ISSUE 20: each shard allocates against the shared
        # store through its own HTTPAllocator + partition FSM — lease
        # authority is per-MAC, and MAC steering makes that per-shard).
        self.fleet_blockers: list[str] = []
        if cfg.slowpath_workers > 1:
            blockers = [name for flag, name in (
                (cfg.pppoe_enabled, "pppoe"),
                (cfg.shards > 1, "sharded")) if flag]
            if blockers:
                # more than a log line: the degradation is exported as
                # bng_slowpath_fleet_blocked (step 13), surfaced in the
                # `bng run` startup status and stats() — a capacity
                # config that silently collapsed to 1 worker is how
                # overload pages happen (blockers documented in README)
                self.fleet_blockers = blockers
                self.log.warning(
                    "slowpath fleet disabled: per-lease integrations "
                    "not yet fleet-aware", blockers=blockers,
                    workers=cfg.slowpath_workers)
            else:
                from bng_tpu.control.admission import AdmissionConfig
                from bng_tpu.control.fleet import FleetSpec, SlowPathFleet
                from bng_tpu.control.ha import SessionState as _HAState

                def _fleet_ha_lease(event, lease, sid, _c=c, _acct=acct):
                    # late-bound: HA (step 11) builds AFTER the fleet,
                    # so the hook reads c["ha"] at event time. Worker
                    # lease events ride the drained TableEventLog into
                    # this single-writer seam — push_change here is the
                    # fleet-side twin of the parent _ha_lease closure,
                    # and accounting start/stop the _acct_lease twin
                    # (octets stay device-authoritative: the tick bridge
                    # folds NAT counters by framed_ip, which is disjoint
                    # per shard, so per-shard folding is exact).
                    if _acct is not None:
                        from bng_tpu.utils.net import u32_to_ip as _uip
                        if event == "start":
                            _acct.start(
                                sid, username=lease.get("username")
                                or _uip(lease["ip"]),
                                framed_ip=lease["ip"],
                                mac="-".join(
                                    f"{b:02X}" for b in
                                    bytes.fromhex(lease["mac"])))
                        elif event == "stop":
                            _acct.stop(sid)
                    ha_sync = _c.get("ha")
                    if ha_sync is None or not hasattr(ha_sync,
                                                      "push_change"):
                        return
                    if event == "stop":
                        ha_sync.push_change(None, session_id=sid)
                    else:  # start / renew both RE-push (expiry tracks)
                        ha_sync.push_change(_HAState(
                            session_id=sid, mac=lease["mac"],
                            ip=lease["ip"], pool_id=lease["pool_id"],
                            username=lease.get("username") or "",
                            lease_expiry=float(lease["expiry"]),
                            qos_policy=lease.get("qos_policy") or "",
                            session_kind="ipoe",
                            updated_at=self.clock()))

                fallback = c.get("slowpath") or dhcp.handle_frame
                fspec = FleetSpec.from_pool_manager(
                    parse_mac(cfg.server_mac), ip_to_u32(cfg.server_ip),
                    pool_mgr, slice_size=cfg.slowpath_slice,
                    low_watermark=max(1, cfg.slowpath_slice // 4))
                if radius_server_cfgs:
                    # per-worker RADIUS sockets on the MAC steering
                    # hash (ISSUE 19): auth affinity = DHCP affinity
                    fspec.radius_servers = list(radius_server_cfgs)
                    fspec.radius_nas_id = cfg.node_id or "bng-tpu"
                    fspec.radius_nas_ip = ip_to_u32(cfg.server_ip)
                if cfg.nexus_url:
                    # per-worker Nexus allocators (ISSUE 20): lease
                    # authority through the shared store, one client +
                    # partition FSM per shard
                    fspec.nexus_url = cfg.nexus_url
                    fspec.nexus_node_id = cfg.node_id or "bng-tpu"
                    if cfg.nexus_url.startswith("https"):
                        fspec.nexus_tls = self._cluster_client_tls()
                fleet = c["fleet"] = SlowPathFleet(
                    fspec,
                    n_workers=cfg.slowpath_workers, pools=pool_mgr,
                    mode=cfg.slowpath_worker_mode,
                    admission=AdmissionConfig(
                        inbox_capacity=cfg.slowpath_inbox,
                        deadline_ms=cfg.slowpath_deadline_ms),
                    table_sink=fastpath, qos_hook=qos_hook,
                    nat_hook=nat_hook, lease_hook=_fleet_ha_lease,
                    fallback=fallback, clock=self.clock)
                c["engine"].slow_path_batch = fleet.handle_batch
                self._on_close(fleet.close)
                self.log.info("slowpath fleet up",
                              workers=cfg.slowpath_workers,
                              mode=cfg.slowpath_worker_mode,
                              inbox=cfg.slowpath_inbox)

        # 10d. CoA/Disconnect listener (RFC 5176; coa.go:119-240 +
        # coa_handler.go:175-460): dynamic authorization reaches BOTH
        # session kinds — DHCP leases (policy -> device QoS; disconnect
        # force-expires the lease) and PPPoE sessions (disconnect runs
        # the LCP/PADT teardown, frames ride the demux pending queue to
        # the wire).
        if cfg.radius_server and cfg.coa_enabled:
            from bng_tpu.control.radius.coa import CoAProcessor, CoAServer
            from bng_tpu.utils.net import mac_to_u64

            pppoe_srv = c.get("pppoe")
            # fleet-aware CoA (ISSUE 19): DHCPv4 leases live in the
            # workers when the fleet serves — the locators probe the
            # parent books first (PPPoE and non-fleet leases), then
            # route to the owning shard on the same MAC steering hash
            # (relay counted by the fleet when missteered)
            fleet_coa = c.get("fleet")

            def _find_by_ip(ip):
                for lease in dhcp.leases.values():
                    if lease.ip == ip:
                        return ("dhcp", lease)
                if pppoe_srv is not None:
                    for s in pppoe_srv.sessions.all():
                        if s.assigned_ip == ip:
                            return ("pppoe", s)
                if fleet_coa is not None:
                    r = fleet_coa.handle_coa("locate", ip=ip)
                    if r["found"]:
                        return ("fleet", r)
                return None

            def _find_by_sid(sid):
                for lease in dhcp.leases.values():
                    if lease.session_id == sid:
                        return ("dhcp", lease)
                if pppoe_srv is not None and sid.startswith("pppoe-"):
                    # inverse of pppoe_sid() — keep in lockstep
                    try:
                        num = int(sid.split("-")[1], 16)
                    except (IndexError, ValueError):
                        return None
                    s = pppoe_srv.sessions.get(num)
                    if s is not None:
                        return ("pppoe", s)
                if fleet_coa is not None and not sid.startswith("pppoe-"):
                    r = fleet_coa.handle_coa("locate", session_id=sid)
                    if r["found"]:
                        return ("fleet", r)
                return None

            def _find_by_mac(mac_str):
                try:
                    mac = bytes.fromhex(mac_str.replace("-", "")
                                        .replace(":", ""))
                except ValueError:
                    return None
                lease = dhcp.leases.get(mac_to_u64(mac))
                if lease is not None:
                    return ("dhcp", lease)
                if pppoe_srv is not None:
                    for s in pppoe_srv.sessions.all():
                        if s.client_mac == mac:
                            return ("pppoe", s)
                if fleet_coa is not None:
                    r = fleet_coa.handle_coa("locate", mac=mac)
                    if r["found"]:
                        return ("fleet", r)
                return None

            def _coa_qos(ip, policy_name):
                if qos_hook is None:
                    return False  # QoS disabled: a CoA rate change NAKs
                qos_hook(ip, policy_name)  # processor pre-validates name
                # record the new plan on the lease and re-push through
                # the hook chain so HA replication (and any other
                # lease-state consumer) sees the change — else failover
                # restores the PRE-CoA policy
                lease = next((l for l in dhcp.leases.values()
                              if l.ip == ip), None)
                if lease is not None:
                    lease.qos_policy = policy_name
                    if dhcp.accounting_hook is not None:
                        dhcp.accounting_hook("renew", lease,
                                             lease.session_id)
                elif fleet_coa is not None:
                    # the owning shard mutates its own lease; the renew
                    # event rides the drained relay into HA/accounting
                    fleet_coa.handle_coa("qos", ip=ip,
                                         policy_name=policy_name)
                return True

            def _coa_disconnect(handle):
                kind, obj = handle
                if kind == "dhcp":
                    obj.expiry = 0
                    dhcp.cleanup_expired(1)  # reaps only the forced lease
                    return True
                if kind == "fleet":
                    r = fleet_coa.handle_coa("disconnect", ip=obj["ip"])
                    return bool(r["found"])
                from bng_tpu.control.pppoe.session import TerminateCause

                frames = pppoe_srv.terminate(
                    obj.session_id, TerminateCause.ADMIN_RESET,
                    now=self.clock())
                if "slowpath" in c:
                    # PADT/LCP teardown frames ride the demux pending
                    # queue; drive_once injects them on the TX ring
                    c["slowpath"].requeue(frames)
                return True

            class _CoASession:  # adapt (kind, obj) to processor's .ip read
                pass

            def _wrap(found):
                if found is None:
                    return None
                kind, obj = found
                h = _CoASession()
                h.kind, h.obj = kind, obj
                if kind == "dhcp":
                    h.ip = obj.ip
                elif kind == "fleet":
                    h.ip = obj["ip"]
                else:
                    h.ip = obj.assigned_ip
                return h

            def _locked(fn):
                def run(*a):
                    with self._ctl:
                        return fn(*a)
                return run

            proc = CoAProcessor(
                find_by_session_id=_locked(lambda sid: _wrap(_find_by_sid(sid))),
                find_by_ip=_locked(lambda ip: _wrap(_find_by_ip(ip))),
                find_by_mac=_locked(lambda m: _wrap(_find_by_mac(m))),
                qos_update=_locked(_coa_qos),
                disconnect=_locked(
                    lambda h: _coa_disconnect((h.kind, h.obj))),
                policy_manager=policies)
            host, _, port = cfg.coa_listen.rpartition(":")
            coa = c["coa"] = CoAServer(
                resolve_secret(cfg.radius_secret,
                               cfg.radius_secret_file).encode(),
                proc, bind=(host or "0.0.0.0", int(port or 3799)))
            coa.start()
            self._on_close(coa.stop)
            self.log.info("coa listener", addr=f"{coa.addr[0]}:{coa.addr[1]}")

        # 11. HA pair (main.go:759-881)
        if cfg.ha_role:
            from bng_tpu.control.ha import (ActiveSyncer, InMemorySessionStore,
                                            Role, SessionState, StandbySyncer)
            store = c["ha_store"] = InMemorySessionStore()
            if cfg.ha_role == "active":
                ha_sync = c["ha"] = ActiveSyncer(store)
                self.log.info("ha role active")
                c["ha_role"] = Role.ACTIVE

                # feed the syncer from BOTH session lifecycles (the
                # reference integrates HASyncer with its servers —
                # sync.go:456 PushChange callers): without this the pair
                # replicates an always-empty store.
                def _nat_fields(ip):
                    blk = nat.blocks.get(ip) if cfg.nat_enabled else None
                    if blk is None:
                        return {}
                    return {"nat_public_ip": blk["public_ip"],
                            "nat_port_start": blk["port_start"],
                            "nat_port_end": blk["port_end"]}

                prev_ha_hook = dhcp.accounting_hook

                def _ha_lease(event, lease, sid, _ha=ha_sync):
                    if prev_ha_hook is not None:
                        prev_ha_hook(event, lease, sid)
                    if event in ("start", "renew"):
                        # renewals RE-push: the standby's lease_expiry
                        # must track extensions or failover treats a
                        # live subscriber as long-expired
                        _ha.push_change(SessionState(
                            session_id=sid, mac=lease.mac.hex(),
                            ip=lease.ip, pool_id=lease.pool_id,
                            circuit_id=lease.circuit_id.hex(),
                            username=lease.username,
                            lease_expiry=float(lease.expiry),
                            s_tag=lease.s_tag, c_tag=lease.c_tag,
                            qos_policy=lease.qos_policy,
                            session_kind="ipoe",
                            updated_at=self.clock(),
                            **_nat_fields(lease.ip)))
                    elif event == "stop":
                        _ha.push_change(None, session_id=sid)

                dhcp.accounting_hook = _ha_lease

                if "pppoe" in c:
                    pppoe_srv2 = c["pppoe"]
                    prev_po, prev_pc = pppoe_srv2.on_open, pppoe_srv2.on_close

                    def _ha_pppoe_open(sess, _ha=ha_sync):
                        if prev_po is not None:
                            prev_po(sess)
                        _ha.push_change(SessionState(
                            session_id=pppoe_sid(sess),
                            mac=sess.client_mac.hex(),
                            ip=sess.assigned_ip,
                            username=sess.username,
                            session_kind="pppoe",
                            updated_at=self.clock(),
                            **_nat_fields(sess.assigned_ip)))

                    def _ha_pppoe_close(event, _ha=ha_sync):
                        if prev_pc is not None:
                            prev_pc(event)
                        _ha.push_change(None,
                                        session_id=pppoe_sid(event.session))

                    pppoe_srv2.on_open = _ha_pppoe_open
                    pppoe_srv2.on_close = _ha_pppoe_close
            else:
                if cfg.ha_peer.startswith("http"):
                    # real wire: full sync + SSE deltas from the active's
                    # cluster listener (control/cluster_http.py)
                    from bng_tpu.control.cluster_http import HTTPActiveProxy

                    def _peer():
                        return HTTPActiveProxy(
                            cfg.ha_peer,
                            on_stream_end=lambda: c["ha"].disconnect(),
                            tls=self._cluster_client_tls())
                else:
                    def _peer():
                        raise ConnectionError(
                            f"HA peer unreachable: {cfg.ha_peer}")
                c["ha"] = StandbySyncer(store, transport=_peer)
                self.log.info("ha role standby", peer=cfg.ha_peer)
                c["ha_role"] = Role.STANDBY

        # 11b. replicated store + cluster listener (pkg/nexus CLSet modes)
        if cfg.store_mode != "memory" or cfg.store_peers:
            from bng_tpu.control.crdt import DistributedStore
            from bng_tpu.control.cluster_http import HTTPStorePeer

            cstore = c["cluster_store"] = DistributedStore(
                cfg.node_id, mode=cfg.store_mode, clock=self.clock)
            for url in cfg.store_peers:
                cstore.add_peer(HTTPStorePeer(
                    url, tls=(self._cluster_client_tls()
                              if url.startswith("https") else None)))
        if cfg.cluster_listen:
            from bng_tpu.control.cluster_http import ClusterServer

            server_tls = None
            if cfg.cluster_tls_cert or cfg.cluster_tls_key:
                from bng_tpu.control.ztp_tls import ServerTLSConfig

                server_tls = ServerTLSConfig(
                    cert_file=cfg.cluster_tls_cert,
                    key_file=cfg.cluster_tls_key,
                    client_ca_file=cfg.cluster_tls_client_ca)
            host, _, port = cfg.cluster_listen.rpartition(":")
            srv = ClusterServer(host or "127.0.0.1", int(port or 0),
                                tls=server_tls)
            if cfg.ha_role == "active":
                srv.mount_ha(c["ha"])
            if "cluster_store" in c:
                srv.mount_store(c["cluster_store"])
            if "peerpool" in c:
                srv.mount_pool(c["peerpool"])
            c["cluster_server"] = srv.start()
            self.log.info("cluster listener up", url=srv.url,
                          ha=bool(srv.ha), store=srv.store is not None)
            self._on_close(srv.close)

        # 11c. the wire: packet ring + AF_XDP attach ladder (the XDP-attach
        # role, loader.go:294-315). Always build the ring when a wire or
        # synthetic source is requested; the attach mode is whatever rung
        # the environment supports (zerocopy -> copy -> in-memory).
        if cfg.shards > 1 and (cfg.wire_if or cfg.synthetic_subs):
            # sharded serving ring: built BY the cluster so the steering
            # tables (NAT public-IP ownership, owner-shard hash) are
            # registered — shard i's batch region holds shard i's
            # subscribers and the common case never punts. AF_XDP attach
            # is an engine-path feature for now (sharded_blockers).
            ring = c["ring"] = c["cluster"].make_ring(frame_size=2048)
            self._on_close(ring.close)
            self._on_close(lambda: c["cluster"].flush_pipeline(
                self._slow_path))
        elif cfg.wire_if or cfg.synthetic_subs:
            from bng_tpu.runtime import xsk as xsk_mod
            from bng_tpu.runtime.ring import make_ring

            # the tiered scheduler consumes frames via rx_pop (two lanes
            # retire out of dispatch order — the native ring's FIFO
            # assemble..complete contract can't express that), so prefer
            # the Python ring when the scheduler owns the loop. A real
            # wire attach needs the native UMEM, which wins: forcing a
            # PyRing would silently downgrade the NIC to in-memory mode,
            # so with wire_if set the ring stays native and drive_once
            # falls back to the pipelined engine loop (warned there).
            if cfg.wire_if and "scheduler" in c:
                self.log.warning(
                    "scheduler enabled with a wire interface: native ring "
                    "required for AF_XDP, scheduler will be bypassed in "
                    "the drive loop")
            ring = c["ring"] = make_ring(
                frame_size=2048,
                prefer_native=bool(cfg.wire_if) or "scheduler" not in c)
            att = xsk_mod.open_wire(ring, ifname=cfg.wire_if,
                                    queue=cfg.wire_queue,
                                    pump_path=cfg.wire_pump or None)
            c["wire_attachment"] = att
            self.log.info("wire attach", mode=att.mode,
                          interface=cfg.wire_if or "(none)",
                          detail=att.detail)
            if cfg.wire_if and att.mode == xsk_mod.MODE_MEMORY:
                # a REQUESTED NIC landed on the memory rung: the ring
                # keeps serving, so every counter looks healthy while
                # zero packets touch the wire — dump the flight ring
                # (TRIG_WIRE_FALLBACK) and say it loudly; the
                # bng_wire_rung gauge pins it for dashboards
                from bng_tpu.telemetry import recorder as rec_mod
                from bng_tpu.telemetry import spans as tele_sp

                self.log.warning(
                    "wire attach FELL BACK to the memory rung — this is "
                    "NOT wire serving", interface=cfg.wire_if,
                    detail=att.detail)
                tele_sp.trigger(rec_mod.TRIG_WIRE_FALLBACK,
                                f"requested {cfg.wire_if!r} landed on the "
                                f"memory rung: {att.detail}")
            if att.xsk is not None:
                # an AF_XDP socket only RECEIVES via an xskmap redirect
                # program; load ours through the kernel verifier. TX works
                # without it, so a missing CAP_BPF degrades (logged), it
                # does not abort the attach ladder.
                from bng_tpu.runtime import xdp_redirect

                try:
                    c["xdp_redirect"] = xdp_redirect.XdpRedirect(
                        cfg.wire_if, {cfg.wire_queue: att.xsk.fd})
                    self.log.info("xdp redirect loaded",
                                  interface=cfg.wire_if,
                                  queue=cfg.wire_queue)
                except OSError as e:
                    self.log.warning("xdp redirect unavailable (RX via "
                                     "kernel needs CAP_BPF)", error=str(e))
            # LIFO shutdown: flush the pipelined batch (needs the ring),
            # then detach the socket + redirect, then free the ring/UMEM
            self._on_close(ring.close)
            if att.xsk is not None:
                self._on_close(att.xsk.close)
            if "xdp_redirect" in c:
                self._on_close(c["xdp_redirect"].close)
            self._on_close(lambda: c["engine"].flush_pipeline())

        # 12. routing + BGP (main.go:884-940). The platform and the FRR
        # executor are both flag-gated: stub/inert by default (run works
        # with no FRR and no CAP_NET_ADMIN), real when asked for.
        if cfg.routing_platform == "linux":
            from bng_tpu.control.routing import (IPRoute2Platform,
                                                 RoutingManager)
            c["routing"] = RoutingManager(platform=IPRoute2Platform())
            self.log.info("routing platform", kind="linux-iproute2")
        elif cfg.routing_platform == "stub":
            from bng_tpu.control.routing import RoutingManager, StubPlatform
            c["routing"] = RoutingManager(platform=StubPlatform())
        else:  # a typo must not silently disable multi-ISP routing
            raise ValueError(
                f"routing_platform={cfg.routing_platform!r}: "
                f"expected 'stub' or 'linux'")
        if cfg.bgp_enabled:
            from bng_tpu.control.routing import (BGPConfig, BGPController,
                                                 vtysh_executor)
            if cfg.bgp_vtysh:
                executor = vtysh_executor(cfg.bgp_vtysh_path)
                self.log.info("bgp executor", kind="vtysh",
                              binary=cfg.bgp_vtysh_path)
            else:
                executor = lambda cmd: ""  # noqa: E731 — inert by default
            c["bgp"] = BGPController(
                BGPConfig(local_as=cfg.bgp_local_as,
                          router_id=cfg.bgp_router_id),
                executor=executor)

        # 13. metrics (main.go:1214-1241)
        if cfg.metrics_enabled:
            metrics = c["metrics"] = BNGMetrics()
            collector = c["collector"] = MetricsCollector(metrics)
            # engine/cluster sources read c[...] at scrape time, never a
            # captured reference: a blue/green swap replaces the object
            # mid-run and the dashboard must follow the flip
            if cfg.shards > 1:
                collector.add_source(
                    lambda: metrics.collect_sharded(c["cluster"]))
            else:
                collector.add_source(
                    lambda: metrics.collect_engine(c["engine"].stats))
            collector.add_source(lambda: metrics.collect_dhcp_server(dhcp.stats))
            if self.fleet_blockers:
                metrics.record_fleet_blocked(self.fleet_blockers)
            if cfg.walled_garden_enabled and cfg.shards <= 1:
                collector.add_source(
                    lambda: metrics.collect_garden(c["engine"].stats))
            if "scheduler" in c:
                sched = c["scheduler"]
                # histograms are fed live at dispatch/retire; the gauges
                # come from the 5s scrape like every other source
                sched.metrics = metrics
                collector.add_source(
                    lambda: metrics.collect_scheduler(sched))
            if "fleet" in c:
                fleet_c = c["fleet"]
                collector.add_source(
                    lambda: metrics.collect_fleet(fleet_c))
            if "wire_attachment" in c:
                # rung identity + pump accounting; reads c[...] at
                # scrape time so a re-attach follows the flip
                collector.add_source(
                    lambda: metrics.collect_wire(
                        c.get("wire_attachment")))
            if "telemetry" in c:
                tele_tr = c["telemetry"]
                # bng_stage_latency_us renders live from the tracer's
                # histograms at scrape; the counters ride the 5s loop
                metrics.attach_telemetry(tele_tr)
                collector.add_source(
                    lambda: metrics.collect_telemetry(tele_tr))
            if "slo" in c:
                slo_mon = c["slo"]
                # burn-rate verdicts + configured budgets per stage:
                # collect_slo reads one locked monitor snapshot
                collector.add_source(
                    lambda: metrics.collect_slo(slo_mon))
            if cfg.dns_enabled:
                collector.add_source(lambda: metrics.collect_dns(
                    dns_srv.stats, resolver.stats()))
            collector.add_source(lambda: metrics.collect_pools(
                {str(pid): st for pid, st in pool_mgr.stats().items()}))
            # exhaustion counters read c[...] at scrape time (nil-safe):
            # a fleet resize or engine swap must not strand a captured ref
            collector.add_source(lambda: metrics.collect_exhaustion(
                dhcpv6=c.get("dhcpv6"), nat=c.get("nat"),
                fleet=c.get("fleet")))
            self._on_close(collector.stop)

        # 14. checkpoint/warm-restart (runtime/checkpoint.py +
        # control/statestore.py). Restore-at-start hydrates the host
        # mirrors + lease book + HA store and re-uploads via the bulk
        # path (zero slow-path DHCP exchanges); a corrupt or mismatched
        # checkpoint is REJECTED and the process cold-starts, logged. A
        # standby bootstraps its session store + last_seq from the
        # checkpoint, then catches up via replay_since on first connect.
        if cfg.checkpoint_dir:
            from bng_tpu.control.statestore import (CheckpointStore,
                                                    PeriodicCheckpointer)
            from bng_tpu.runtime import checkpoint as ckpt_mod

            store = c["checkpoint_store"] = CheckpointStore(
                cfg.checkpoint_dir)
            ha_sync = c.get("ha")
            if store.has_checkpoints():
                try:
                    snap, path = store.load_latest()
                    if cfg.shards > 1:
                        # sharded restore: slot-exact at matching
                        # topology, owner-routed re-shard on N->M (the
                        # fleet lease-book discipline); a single-engine
                        # snapshot rejects to cold start
                        rows = ckpt_mod.restore_sharded_checkpoint(
                            snap, c["cluster"], dhcp=dhcp, ha=ha_sync,
                            fleet=c.get("fleet"),
                            now=int(self.clock()))
                    else:
                        rows = ckpt_mod.restore_checkpoint(
                            snap, engine=c["engine"], dhcp=dhcp,
                            ha=ha_sync, fleet=c.get("fleet"))
                    c["checkpoint_restored"] = rows
                    self.log.info("warm restart from checkpoint",
                                  path=str(path), seq=snap.seq,
                                  rows={k: v for k, v in rows.items() if v})
                    if "metrics" in c:
                        c["metrics"].record_restore(rows)
                except ckpt_mod.CheckpointError as e:
                    c["checkpoint_error"] = str(e)
                    self.log.warning(
                        "checkpoint restore rejected; cold start",
                        error=str(e))
                    if "metrics" in c:
                        c["metrics"].record_restore({}, outcome="rejected")

            def _snapshot(seq, now, _dhcp=dhcp, _ha=ha_sync):
                # c["engine"]/c["cluster"] read at snapshot time: after
                # a blue/green swap the checkpoint must fold device
                # words from the SERVING chain, not the retired one's
                if cfg.shards > 1:
                    return ckpt_mod.build_sharded_checkpoint(
                        c["cluster"], seq, now, dhcp=_dhcp, ha=_ha,
                        fleet=c.get("fleet"), node_id=cfg.node_id)
                return ckpt_mod.build_checkpoint(
                    seq, now, engine=c["engine"],
                    scheduler=c.get("scheduler"), dhcp=_dhcp, ha=_ha,
                    fleet=c.get("fleet"), node_id=cfg.node_id)

            ckptr = c["checkpointer"] = PeriodicCheckpointer(
                store, _snapshot, interval_s=cfg.checkpoint_interval_s,
                keep=cfg.checkpoint_keep, metrics=c.get("metrics"),
                clock=self.clock)
            if "collector" in c:
                c["collector"].add_source(
                    lambda: c["metrics"].collect_checkpoint(ckptr))

        # 15. zero-downtime ops (control/opsctl.py): the transition
        # queue the run loop drains at batch boundaries (`bng ctl`
        # submits into it over the --ctl-listen wire, started by the
        # serve loop like the metrics endpoint) and, when asked, the
        # watermark autoscaler driving live fleet elasticity from tick.
        from bng_tpu.control.opsctl import (AutoscaleConfig, FleetAutoscaler,
                                            OpsController)

        c["ops"] = OpsController(self)
        if cfg.slowpath_autoscale and "fleet" in c:
            c["autoscaler"] = FleetAutoscaler(
                c["fleet"],
                AutoscaleConfig(min_workers=max(1, cfg.slowpath_min_workers),
                                max_workers=max(1, cfg.slowpath_max_workers)),
                clock=self.clock)
            self.log.info("fleet autoscaler armed",
                          min=cfg.slowpath_min_workers,
                          max=cfg.slowpath_max_workers)

    # -- zero-downtime transitions (ops verbs; serialized on _ctl) -------

    def fleet_resize(self, n: int) -> dict:
        """Live fleet elasticity: grow/shrink the slow-path fleet to `n`
        workers at a batch boundary — no restart, no dropped in-flight
        DORAs (control/fleet.py resize)."""
        with self._ctl:
            return self._fleet_resize_locked(int(n))

    def _fleet_resize_locked(self, n: int) -> dict:
        fleet = self.components.get("fleet")
        if fleet is None:
            why = (f"blocked by {self.fleet_blockers}"
                   if self.fleet_blockers else
                   "not configured (--slowpath-workers <= 1)")
            return {"op": "fleet_resize", "outcome": "rejected",
                    "error": f"no slow-path fleet: {why}"}
        report = fleet.resize(n)
        if "metrics" in self.components:
            self.components["metrics"].record_transition(report)
            self.components["metrics"].slowpath_workers.set(fleet.n)
        self.log.info("fleet resize", **{k: report.get(k) for k in
                                         ("from", "to", "outcome",
                                          "leases_moved", "offers_moved")})
        return report

    def fleet_rolling_restart(self) -> dict:
        """Replace fleet workers one shard at a time (drain-then-transfer
        per shard; heals chaos-killed inline workers) — the live-deploy
        verb (control/fleet.py rolling_restart)."""
        with self._ctl:
            fleet = self.components.get("fleet")
            if fleet is None:
                return {"op": "fleet_rolling_restart",
                        "outcome": "rejected",
                        "error": "no slow-path fleet configured"}
            report = fleet.rolling_restart()
            if "metrics" in self.components:
                self.components["metrics"].record_transition(report)
            self.log.info("fleet rolling restart",
                          outcome=report.get("outcome"),
                          replaced=report.get("replaced"),
                          lost=report.get("lost"))
            return report

    def engine_swap(self) -> dict:
        """Blue/green engine swap: hydrate a standby from an in-memory
        snapshot, replay the delta, audit, flip atomically — rollback on
        any failure with the active untouched (runtime/ops.py). On the
        sharded serving path the standby is a ShardedCluster hydrated
        from a sharded snapshot, partition-audited before the flip."""
        from bng_tpu.runtime.ops import blue_green_swap, sharded_blue_green_swap

        with self._ctl:
            if "cluster" in self.components:
                report = sharded_blue_green_swap(
                    self.components,
                    metrics=self.components.get("metrics"),
                    node_id=self.config.node_id, clock=self.clock)
            else:
                report = blue_green_swap(
                    self.components,
                    metrics=self.components.get("metrics"),
                    node_id=self.config.node_id)
            self.log.info("engine swap", outcome=report.get("outcome"),
                          delta_rows=report.get("delta_rows"),
                          error=report.get("error"))
            return report

    def ops_status(self) -> dict:
        """GET /ops/status payload: what a transition would act on.
        Runs on the HTTP handler thread — takes _ctl so it never reads
        fleet state mid-mutation (stats_snapshot iterates sets/lists the
        loop thread's transitions rebind)."""
        with self._ctl:
            c = self.components
            out: dict = {"node_id": self.config.node_id,
                         "fleet_blocked": self.fleet_blockers,
                         "ops": c["ops"].stats_snapshot()
                         if "ops" in c else None}
            fleet = c.get("fleet")
            if fleet is not None:
                fs = fleet.stats_snapshot()
                out["fleet"] = {k: fs[k] for k in (
                    "workers", "mode", "resizes", "rolling_restarts",
                    "dead_workers")}
            auto = c.get("autoscaler")
            if auto is not None:
                out["autoscaler"] = {"decisions": auto.decisions,
                                     "min": auto.cfg.min_workers,
                                     "max": auto.cfg.max_workers}
            return out

    def _cluster_client_tls(self):
        """Client-side TLSConfig for https cluster peers, or None when no
        TLS material is configured (plaintext peers keep working)."""
        cfg = self.config
        if not (cfg.cluster_tls_ca or cfg.cluster_tls_pins
                or cfg.cluster_tls_client_cert):
            return None
        from bng_tpu.control.ztp_tls import TLSConfig

        return TLSConfig(
            ca_cert_file=cfg.cluster_tls_ca,
            pinned_certs=list(cfg.cluster_tls_pins),
            server_name=cfg.cluster_tls_server_name,
            # pins without a CA: self-signed cluster certs (the common
            # operator deployment) — pinning carries the trust. With no
            # pins the chain check must stay on (CA file or system roots)
            # or the config would authenticate nobody.
            require_valid_chain=not cfg.cluster_tls_pins
            or bool(cfg.cluster_tls_ca),
            client_cert_file=cfg.cluster_tls_client_cert,
            client_key_file=cfg.cluster_tls_client_key)

    def close(self) -> None:
        """LIFO cleanup (main.go:1301-1379)."""
        for fn in reversed(self._cleanup):
            try:
                fn()
            except Exception:
                pass
        self._cleanup.clear()

    def drive_once(self) -> int:
        """One dataplane beat: pump the AF_XDP socket (kernel RX -> ring,
        ring TX verdicts -> kernel) when a real rung is attached, feed the
        synthetic source (if configured), and run a double-buffered engine
        step over the ring. Returns frames moved (the run loop sleeps
        when this stays 0)."""
        ring = self.components.get("ring")
        if ring is None:
            return 0
        att = self.components.get("wire_attachment")
        pumped = 0
        if att is not None and att.xsk is not None:
            pumped = att.xsk.pump()  # kernel -> ring before the step
        if self.config.synthetic_subs:
            self._push_synthetic(ring)
        cluster = self.components.get("cluster")
        sched = self.components.get("scheduler")
        if cluster is not None:
            # the promoted serving path: double-buffered sharded ring
            # loop — ring-steered owner-shard batches, depth-2 windows
            # in flight, slow-path punts handled lane-aligned
            now = self.clock()
            with self._ctl:
                moved = self.components["cluster"].process_ring_pipelined(
                    ring, int(now), int(now * 1e6) & 0xFFFFFFFF,
                    slow_path=self._slow_path)
        elif sched is not None and hasattr(ring, "rx_pop"):
            with self._ctl:
                moved = self._drive_scheduler(ring, sched)
        else:
            # scheduler off, or a native ring (batch assemble..complete is
            # its contract; the two-lane out-of-order retire needs the
            # frame-wise rx_pop only PyRing provides)
            if sched is not None and not self._warned_no_rx_pop:
                self._warned_no_rx_pop = True
                self.log.warning("scheduler enabled but ring has no rx_pop; "
                                 "using pipelined engine loop")
            with self._ctl:
                moved = self.components["engine"].process_ring_pipelined(ring)
        # PPPoE negotiation extras beyond the one-inline-reply slow
        # contract (CHAP-Success + IPCP Conf-Req in one beat), plus the
        # fleet workers' pending frames relayed by the parent. A full
        # TX ring re-queues the remainder for the next beat (the FSM
        # retransmit would recover anyway, but without the drop).
        # Under _ctl: a CoA disconnect may extend the queue
        # concurrently, and drain's swap must not lose its frames.
        for src in (self.components.get("slowpath"),
                    self.components.get("fleet")):
            if src is None:
                continue
            with self._ctl:
                pending = src.drain_pending()
                for i, frame in enumerate(pending):
                    if ring.tx_inject(frame, from_access=True):
                        moved += 1
                    else:
                        # re-queue the WHOLE un-injected remainder,
                        # order-preserving, via the public API
                        src.requeue(pending[i:], front=True)
                        break
        if att is not None and att.xsk is not None:
            pumped += att.xsk.pump()  # verdicts -> kernel after the step
        return moved + pumped

    _warned_no_rx_pop = False

    def _drive_scheduler(self, ring, sched) -> int:
        """One scheduler beat over the ring: RX frames into the lanes,
        poll (express first, bulk ring-managed), completions back out.
        TX/FWD device output and slow-path replies are injected on the TX
        ring; PASS frames were already handled inside the scheduler's
        retire (slow path runs there), so nothing touches the slow ring.
        """
        from bng_tpu.runtime.ring import FLAG_DHCP_CTRL, FLAG_FROM_ACCESS
        from bng_tpu.runtime.scheduler import LANE_BULK, LANE_EXPRESS
        from bng_tpu.telemetry import spans as tele

        moved = 0
        t0 = tele.t()
        budget = sched.bulk.cfg.batch * sched.bulk.cfg.depth
        for _ in range(budget):
            got = ring.rx_pop()
            if got is None:
                break
            frame, fl = got
            fa = (fl & FLAG_FROM_ACCESS) != 0
            # the ring already classified at rx_push (FLAG_DHCP_CTRL) —
            # pass the lane so submit() skips a second header parse
            lane = (LANE_EXPRESS if fa and (fl & FLAG_DHCP_CTRL)
                    else LANE_BULK)
            sched.submit(frame, from_access=fa, lane=lane)
            # ingested frames count as movement even before their lane
            # closes — otherwise the run loop's moved==0 idle sleep (1ms)
            # would stretch a sub-ms express deadline close
            moved += 1
        if moved:
            tele.lap(tele.RING, t0)
        moved += sched.poll()
        if moved == 0 and (len(sched.express) or len(sched.bulk)):
            # frames are waiting on a deadline close: keep the run loop
            # hot (no idle sleep) so the close fires at max_wait_us, not
            # at sleep granularity
            moved = 1
        for c in sched.drain_completions():
            if c.frame is None:
                continue
            if c.verdict in ("tx", "fwd", "slow"):
                # slow completions carry the handler's reply frame; a full
                # TX ring drops it (the client's retransmit recovers, the
                # reference's socket-write failure mode)
                ring.tx_inject(c.frame, from_access=c.from_access)
        return moved

    def _push_synthetic(self, ring, per_beat: int = 16) -> None:
        """Rotating-MAC DISCOVER source (the loadtest generator's role,
        here for `bng-tpu run --synthetic-subs N` smoke runs)."""
        from bng_tpu.control import dhcp_codec, packets

        n_subs = self.config.synthetic_subs
        for _ in range(per_beat):
            i = self._syn_i % n_subs
            self._syn_i += 1
            mac = (0x02B70000 << 16 | i).to_bytes(6, "big")
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER,
                                         xid=self._syn_i & 0xFFFFFFFF)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                              bytes([1, 3, 6, 51, 54])))
            f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                   p.encode().ljust(320, b"\x00"))
            if not ring.rx_push(f, from_access=True):
                break  # ring full: back off until the engine drains

    # maintenance cadences (seconds): how often each slow sweep runs when
    # tick() is called every second. Mirrors the reference's goroutine
    # intervals: lease cleanup 60s (pkg/dhcp/server.go:1100), NAT expiry
    # 60s (the bpf timeout sweep role), garden 30s, accounting interim
    # honors its own interval so tick just has to fire it regularly.
    EXPIRE_EVERY_S = 60.0
    GARDEN_EVERY_S = 30.0
    ACCT_SYNC_EVERY_S = 60.0
    ACCT_RETRY_EVERY_S = 30.0

    def tick(self, now: float | None = None) -> None:
        """The run loop's 1 Hz maintenance heartbeat — every periodic
        goroutine of the reference's runBNG collapsed into one driver:

        - HA standby reconnect (backoff) + CRDT anti-entropy
        - DHCP lease cleanup (server.go:1100-1163) and NAT session expiry
          against device-authoritative last-seen (nat44.c:49-53 timeouts)
        - RADIUS accounting interim + spool retry (accounting.go:410-497)
        - walled-garden expiry checker (walledgarden/manager.go role)
        - PPPoE keepalive/timeout sweep + SLAAC unsolicited RAs, whose
          generated frames TX-inject on the ring (socket-write role)
        """
        now = now if now is not None else self.clock()
        with self._ctl:
            self._tick_locked(now)

    def _tick_locked(self, now: float) -> None:
        c = self.components
        ha = c.get("ha")
        if ha is not None and hasattr(ha, "tick"):  # StandbySyncer only
            ha.tick(now)
        cstore = c.get("cluster_store")
        if cstore is not None and now - self._last_sync >= cstore.sync_interval:
            self._last_sync = now
            cstore.tick()

        ring = c.get("ring")

        # protocol-server ticks that EMIT frames: PPPoE echo/teardown,
        # SLAAC periodic RAs. Without a ring (pure control-plane app, or
        # tests poking tick directly) the frames are dropped — there is
        # no wire to write to.
        pppoe = c.get("pppoe")
        if pppoe is not None:
            for frame in pppoe.tick(now):
                if ring is not None:
                    ring.tx_inject(frame, from_access=True)
        slaac = c.get("slaac")
        if slaac is not None:
            for frame in slaac.tick(now):
                if ring is not None:
                    ring.tx_inject(frame, from_access=True)

        # slow sweeps on their own cadence; the reap bound keeps one
        # synchronized lease cliff from starving this tick (leftovers
        # are reaped by the next sweeps — see cleanup_expired)
        if now - self._last_expire >= self.EXPIRE_EVERY_S:
            self._last_expire = now
            budget = self.config.expire_batch or None
            c["dhcp"].cleanup_expired(int(now), max_reaps=budget)
            if c.get("dhcpv6") is not None:
                c["dhcpv6"].cleanup_expired(now, max_reaps=budget)
            if "cluster" in c:
                c["cluster"].expire(int(now))
            else:
                c["engine"].expire(int(now))
            fleet = c.get("fleet")
            if fleet is not None:
                # fleet workers own their lease books; the sweep fans
                # out and the release table-events replay here
                fleet.expire(int(now), max_reaps=budget)
        garden = c.get("walledgarden")
        if garden is not None and now - self._last_garden >= self.GARDEN_EVERY_S:
            self._last_garden = now
            garden.check_expired()

        # partition FSM (resilience/manager.go:221-341) + peer health
        # (pool/peer.go:541-631); both rate-limit internally
        res = c.get("resilience")
        if res is not None:
            acct_mgr = c.get("accounting")
            res.tick(now, acct_send=(
                (lambda rec: acct_mgr.client.send_accounting(**rec))
                if acct_mgr is not None else None))
        pool = c.get("peerpool")
        if pool is not None:
            pool.health_check(now)

        # background checkpoint cadence (never raises; failures count +
        # rate-limited log inside PeriodicCheckpointer.tick)
        ckptr = c.get("checkpointer")
        if ckptr is not None:
            ckptr.tick(now)

        # live SLO burn-rate window (telemetry/slo.py): evaluates only
        # when a window elapsed; a breach fires the slo_breach flight
        # dump and is logged here so the operator sees WHICH stage
        slo_mon = c.get("slo")
        if slo_mon is not None:
            breached = slo_mon.tick(now)
            if breached:
                self.log.warning("slo breach", stages=sorted(breached),
                                 window_s=slo_mon.window_s)

        # watermark-driven fleet elasticity: the autoscaler recommends,
        # the SAME resize verb the operator uses executes (already under
        # _ctl here — tick() took it)
        auto = c.get("autoscaler")
        if auto is not None and "fleet" in c:
            target = auto.target(now)
            if target is not None and target != c["fleet"].n:
                if "metrics" in c:
                    c["metrics"].ops_autoscaler_target.set(target)
                try:
                    self._fleet_resize_locked(target)
                except Exception as e:  # noqa: BLE001
                    # an autoscaler-triggered resize failure must not
                    # take the dataplane loop (and the whole process)
                    # down — that is the outage this layer exists to
                    # prevent; cooldown paces the retry
                    self.log.error("autoscaler resize failed",
                                   target=target,
                                   error=f"{type(e).__name__}: {e}")

        acct = c.get("accounting")
        if acct is not None:
            # bridge device-authoritative NAT octet counters into the
            # accounting sessions before interims fire, else every interim
            # and stop reports zero usage (the reference reads its
            # per-subscriber counters the same way before each interim)
            if acct.sessions and now - self._last_acct_sync >= self.ACCT_SYNC_EVERY_S:
                self._last_acct_sync = now
                if "cluster" in c:
                    # sharded: fold every shard's device-authoritative
                    # session words (a subscriber's NAT state lives on
                    # exactly its affinity shard, so the per-shard dicts
                    # are disjoint)
                    cl = c["cluster"]
                    octets = {}
                    if cl.tables is not None:
                        for i in range(cl.n):
                            octets.update(cl.nat[i].subscriber_octets(
                                cl.fetch_session_vals(i)))
                else:
                    octets = c["engine"].nat.subscriber_octets(
                        c["engine"].fetch_session_vals())
                for s in list(acct.sessions.values()):
                    got = octets.get(s.framed_ip)
                    if got is not None:
                        acct.update_counters(s.session_id, got[0], got[1],
                                             got[2], got[3])
            # interims and spool retries are blocking sends (timeout x
            # retries per record/session): run both on their own cadence,
            # not 1 Hz, or a dead accounting server stalls the whole
            # heartbeat every second (interim_tick re-blocks for every
            # still-due session until the server answers)
            if now - self._last_acct_retry >= self.ACCT_RETRY_EVERY_S:
                self._last_acct_retry = now
                acct.interim_tick(now)
                acct.retry_tick()

    def stats(self) -> dict:
        out = {"version": __version__, "node_id": self.config.node_id}
        eng = self.components.get("engine")
        if eng is not None:
            out["engine"] = {
                "batches": eng.stats.batches, "tx": eng.stats.tx,
                "passed": eng.stats.passed, "dropped": eng.stats.dropped}
        cluster = self.components.get("cluster")
        if cluster is not None:
            out["sharded"] = cluster.stats_summary()
            if self.sharded_blockers:
                out["sharded_blockers"] = list(self.sharded_blockers)
        dhcp = self.components.get("dhcp")
        if dhcp is not None:
            out["dhcp"] = {k: getattr(dhcp.stats, k) for k in
                           ("discover", "offer", "request", "ack", "nak",
                            "release") if hasattr(dhcp.stats, k)}
        pools = self.components.get("pools")
        if pools is not None:
            out["pools"] = pools.stats()
        pppoe = self.components.get("pppoe")
        if pppoe is not None and eng is not None:
            out["pppoe"] = {
                "sessions": len(pppoe.sessions),  # atomic vs CoA thread
                "opened": pppoe.stats.sessions_opened,
                "closed": pppoe.stats.sessions_closed,
                "auth_failures": pppoe.stats.auth_failure,
                "device": {"decap": int(eng.stats.pppoe[0]),
                           "encap": int(eng.stats.pppoe[1])}}
        nat = self.components.get("nat")
        if nat is not None:  # registered only when nat_enabled
            out["nat"] = {"sessions": nat.sessions.count,
                          "blocks": len(nat.blocks)}
        fleet = self.components.get("fleet")
        if fleet is not None:
            out["slowpath_fleet"] = fleet.stats_snapshot()
        if self.fleet_blockers:
            # the configured-but-degraded state must be visible wherever
            # an operator looks first (stats, metrics, startup banner)
            out["slowpath_fleet_blocked"] = list(self.fleet_blockers)
        res = self.components.get("resilience")
        if res is not None:
            out["resilience"] = {"state": res.state.value,
                                 "degraded_auth": res.degraded_auth_active}
        coa = self.components.get("coa")
        if coa is not None:
            out["coa"] = {**coa.stats, **coa.processor.stats}
        return out


# ---------------------------------------------------------------------------
# demo mode (demo.go:46-120): full lifecycle, no device required
# ---------------------------------------------------------------------------

def run_demo(subscriber_count: int = 3, out=None, clock=time.time) -> dict:
    """ONT discovery -> walled garden -> activation -> session, with stub
    auth/allocator — 'No eBPF required' (demo.go:47-58); here: no TPU
    required either (pure host path)."""
    from bng_tpu.control.nexus import (NexusClient, NTEEntity,
                                       SubscriberEntity, VLANAllocator)
    from bng_tpu.control.pon import DiscoveryEvent, PONConfig, PONManager
    from bng_tpu.control.direct import DirectAuthenticator
    from bng_tpu.control.subscriber import SessionKind, SubscriberManager
    from bng_tpu.control.walledgarden import WalledGardenManager

    def log(msg):
        print(msg, file=out if out is not None else sys.stdout)

    nexus = NexusClient(clock=clock)
    vlans = VLANAllocator()
    pon = PONManager(PONConfig(), nexus, vlans, clock=clock)
    garden = WalledGardenManager(clock=clock)
    auth = DirectAuthenticator(nexus=nexus, clock=clock)

    class DemoAllocator:
        def __init__(self):
            self.next = 10
        def allocate(self, sid):
            ip = f"10.1.0.{self.next}"
            self.next += 1
            return ip
        def release(self, sid):
            return True

    class GardenBridge:
        def add(self, session):
            garden.add_to_walled_garden(session.mac or "02:00:00:00:00:00")
        def remove(self, session):
            garden.release_from_walled_garden(session.mac or "02:00:00:00:00:00")

    subs = SubscriberManager(authenticator=auth, allocator=DemoAllocator(),
                             walled_garden=GardenBridge(), clock=clock)

    results = {"provisioned": 0, "active": 0, "walled": 0}
    for i in range(1, subscriber_count + 1):
        serial = f"DEMO-ONT-{i:03d}"
        mac = f"02:de:e0:00:00:{i:02x}"
        log(f"--- subscriber {i}: ONT {serial} ---")

        # 1. ONT appears; operator pre-approved it in Nexus
        nexus.ntes.put(serial, NTEEntity(id=serial, serial=serial,
                                         approved=True))
        r = pon.handle_discovery(DiscoveryEvent(serial=serial))
        log(f"  provisioned: s_tag={r.s_tag} c_tag={r.c_tag}")
        results["provisioned"] += 1

        # 2. subscriber record exists for odd ONTs; evens hit the garden
        if i % 2:
            nexus.subscribers.put(f"sub-{i}", SubscriberEntity(
                id=f"sub-{i}", mac=mac, nte_id=serial,
                circuit_id=f"olt1/1/{i}", qos_policy="residential-100mbps"))

        s = subs.create_session(SessionKind.IPOE, mac=mac,
                                circuit_id=f"olt1/1/{i}")
        if subs.authenticate(s.id):
            ip = subs.assign_address(s.id)
            subs.activate(s.id)
            log(f"  ACTIVE: {s.subscriber_id} ip={ip}")
            results["active"] += 1
        else:
            log("  WALLED GARDEN: unknown subscriber, portal redirect on")
            results["walled"] += 1

    log(f"demo complete: {results}")
    return results


def run_loadtest(args) -> int:
    """Build a self-contained engine + slow-path stack and load-test it
    (the dhcp-loadtest CLI role; validation gating per main.go:90-93)."""
    import ipaddress

    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.nat import NATManager
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.loadtest import BenchmarkConfig, DHCPBenchmark
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32, parse_mac

    net = ipaddress.ip_network(args.pool_cidr)
    server_ip = int(net.network_address + 1)
    server_mac = parse_mac("02:aa:bb:cc:dd:01")
    # size the subscriber table for the MAC working set at <50% load
    sub_nb = 1 << max(10, (args.macs // 2).bit_length())
    # update_slots must cover a full warmup batch of inserts per step or
    # the device cache lags the host table and renewals miss spuriously
    fastpath = FastPathTables(sub_nbuckets=sub_nb, vlan_nbuckets=1 << 10,
                              cid_nbuckets=1 << 10, max_pools=16, stash=256,
                              update_slots=max(256, 2 * args.batch_size))
    fastpath.set_server_config(server_mac, server_ip)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=int(net.network_address),
                        prefix_len=net.prefixlen, gateway=server_ip,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=86400))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    server = DHCPServer(server_mac, server_ip, pools, fastpath_tables=fastpath)
    engine = Engine(fastpath, nat, batch_size=args.batch_size,
                    slow_path=server.handle_frame)
    tracer = None
    if getattr(args, "trace", False):
        # --trace: arm the telemetry tracer BEFORE the fleet spawns — a
        # process-mode fleet exports BNG_TELEMETRY to its children at
        # construction, which is how worker processes know to build the
        # per-frame histograms the `worker` stage merges. The report
        # gains the per-stage latency breakdown.
        from bng_tpu.telemetry import spans as tele_spans

        tracer = tele_spans.arm(tele_spans.Tracer())
    fleet = None
    workers = getattr(args, "workers", 1) or 1
    if workers > 1:
        # slow-path fleet: DHCPv4 slow lanes fan out to N worker
        # processes; the parent DHCPServer above is bypassed (workers
        # own the lease books) but stays as the engine's per-frame
        # fallback for anything the fleet doesn't shard
        from bng_tpu.control.admission import AdmissionConfig
        from bng_tpu.control.fleet import FleetSpec, SlowPathFleet

        fleet = SlowPathFleet(
            FleetSpec.from_pool_manager(server_mac, server_ip, pools),
            n_workers=workers, pools=pools,
            mode=getattr(args, "fleet_mode", "process"),
            # inbox sized past the harness batch: the loadtest measures
            # throughput, the dedicated overload tests measure shedding
            admission=AdmissionConfig(
                inbox_capacity=max(512, 2 * args.batch_size)),
            table_sink=fastpath)
        engine.slow_path_batch = fleet.handle_batch
    target = engine
    if getattr(args, "scheduler", False):
        from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler

        target = TieredScheduler(engine, SchedulerConfig(
            bulk_batch=args.batch_size))

    # --wire: drive the batches through the full wire loop (inject at
    # the far end -> kernel rings -> WirePump -> UMEM ring -> engine ->
    # WirePump -> far end) instead of the engine's batch interface
    # (ISSUE 15). `--wire` alone runs the memory-rung SimKernelRings
    # loopback (no privileges needed); `--wire IFNAME` walks the real
    # attach ladder and needs --wire-peer to see replies.
    wire = getattr(args, "wire", None)
    wire_cleanup: list = []
    wire_pump = None
    wire_mode = ""
    if wire is not None:
        if getattr(args, "scheduler", False):
            print("loadtest: --wire and --scheduler are incompatible "
                  "(the native ring's batch assemble..complete contract "
                  "has no rx_pop)", file=sys.stderr)
            return 2
        from bng_tpu.loadtest import WireLoopTarget
        from bng_tpu.runtime import xsk as xsk_mod
        from bng_tpu.runtime.ring import NativeRing

        nframes = 1 << max(12, (4 * args.batch_size - 1).bit_length())
        depth = 1 << max(10, (2 * args.batch_size - 1).bit_length())
        try:
            wire_ring = NativeRing(nframes=nframes, frame_size=2048,
                                   depth=depth)
        except RuntimeError as e:
            print(f"loadtest: --wire needs the native ring: {e}",
                  file=sys.stderr)
            return 2
        wire_cleanup.append(wire_ring.close)
        pump_path = getattr(args, "wire_pump", "") or None
        att = (xsk_mod.open_wire(wire_ring, ifname=wire,
                                 pump_path=pump_path)
               if wire != "mem" else None)
        if att is not None and att.xsk is not None:
            peer = getattr(args, "wire_peer", "")
            if not peer:
                print("loadtest: --wire on a live rung needs --wire-peer "
                      "IFNAME (the far end to inject/collect on)",
                      file=sys.stderr)
                return 2
            import socket as so

            from bng_tpu.runtime import xdp_redirect

            wire_cleanup.append(att.xsk.close)
            try:
                redir = xdp_redirect.XdpRedirect(wire, {0: att.xsk.fd})
                wire_cleanup.append(redir.close)
            except OSError as e:
                print(f"loadtest: xdp redirect failed (CAP_BPF): {e}",
                      file=sys.stderr)
                return 2
            txs = so.socket(so.AF_PACKET, so.SOCK_RAW)
            txs.bind((peer, 0))
            rxs = so.socket(so.AF_PACKET, so.SOCK_RAW, so.htons(0x0003))
            rxs.bind((peer, 0))
            rxs.setblocking(False)
            wire_cleanup.extend((txs.close, rxs.close))

            def _inject(frames, _s=txs):
                for f in frames:
                    _s.send(f)

            def _collect(_s=rxs):
                out = []
                while True:
                    try:
                        out.append(_s.recv(4096))
                    except (BlockingIOError, OSError):
                        break
                return out

            wire_pump = att.xsk.wire_pump
            wire_mode = att.mode
            target = WireLoopTarget(engine, wire_ring, wire_pump,
                                    _inject, _collect)
        else:
            if att is not None:
                # a REQUESTED NIC fell back: say it loudly, then serve
                # the memory rung anyway (the loadtest still measures
                # the pump loop; bng_wire_rung would pin it in `run`)
                print(f"loadtest: wire attach fell back to the memory "
                      f"rung: {att.detail}", file=sys.stderr)
            kern = xsk_mod.SimKernelRings(wire_ring, headroom=256,
                                          ring_size=depth)
            wire_pump = xsk_mod.WirePump(wire_ring, kern, path=pump_path)
            wire_mode = "memory"
            target = WireLoopTarget(engine, wire_ring, wire_pump,
                                    kern.inject_many, kern.drain_egress,
                                    tick=kern.deliver)

    cfg = BenchmarkConfig(
        batch_size=args.batch_size, duration_s=args.duration,
        warmup_s=args.warmup, unique_macs=args.macs,
        enable_renewals=args.renewals, renewal_ratio=args.renewal_ratio,
        rps_limit=args.rps)
    bench = DHCPBenchmark(target, cfg, log=lambda s: print(s, file=sys.stderr))
    try:
        res = bench.run()
        # counted degradations ride the result (storm-suite hygiene):
        # shed-by-reason from admission, exhaustion verdicts by resource
        if fleet is not None:
            res.shed = dict(fleet.admission.stats.shed)
        degraded = {}
        if server.stats.pool_exhausted:
            degraded["dhcp_pool"] = server.stats.pool_exhausted
        if fleet is not None:
            slice_exhausted = fleet.pool_exhausted_total()
            if slice_exhausted:
                degraded["fleet_slice"] = slice_exhausted
        for resource, count in nat.exhausted.items():
            if count:
                degraded[f"nat_{resource}"] = count
        res.degraded = degraded
    finally:
        if tracer is not None:
            from bng_tpu.telemetry import spans as tele_spans

            tele_spans.disarm()
        if fleet is not None:
            fleet_snap = fleet.stats_snapshot()
            fleet.close()
        for fn in reversed(wire_cleanup):
            try:
                fn()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    stage_breakdown = tracer.breakdown() if tracer is not None else {}
    if tracer is not None:
        # SLO verdict over the per-stage breakdown (telemetry/slo.py):
        # the same vocabulary the storm budgets and `bng run`'s live
        # monitor gate on, persisted so the lines are gate-consumable
        from bng_tpu.telemetry import slo as slo_mod

        res.slo = slo_mod.evaluate(stage_breakdown)
    if getattr(args, "bench_log", ""):
        # schema'd ledger line (telemetry/ledger.py): stage_breakdown +
        # SLO verdict + env fingerprint ride every loadtest run so
        # `bng perf gate` can trend it like a bench line
        from bng_tpu.telemetry import ledger as ledger_mod

        try:
            ledger_mod.append(args.bench_log, {
                "metric": "loadtest req/s",
                "value": round(res.rps, 1),
                "unit": "req/s",
                "scenario": res.scenario,
                "batch": args.batch_size,
                "subscribers": args.macs,
                "workers": workers,
                "program": res.program,
                "latency_p99_us": round(res.latency_p99_us, 1),
                "request_p99_us": res.request_p99_us,
                "shed": res.shed,
                "degraded": res.degraded,
                # only present on traced runs: an empty dict would read
                # as "instrumentation on, every stage vanished"
                **({"slo": res.slo, "stage_breakdown": stage_breakdown}
                   if tracer is not None else {}),
                "env": ledger_mod.environment_fingerprint(),
            })
        except OSError as e:
            print(f"loadtest: bench-log append failed: {e}",
                  file=sys.stderr)
    if args.json_out:
        out = res.to_dict()
        if fleet is not None:
            out["fleet"] = fleet_snap
        if tracer is not None:
            out["stage_breakdown"] = stage_breakdown
        if wire_pump is not None:
            out["wire"] = {"mode": wire_mode, "pump_path": wire_pump.path,
                           "pump_stats": dict(wire_pump.pump_stats),
                           "unmatched": target.unmatched}
        print(json.dumps(out, indent=2))
    else:
        print(res.summary())
        if wire_pump is not None:
            st = wire_pump.pump_stats
            print(f"Wire:              rung={wire_mode} "
                  f"pump={wire_pump.path} rx={st['rx']} tx={st['tx']} "
                  f"submit_fail={st['rx_submit_fail']} "
                  f"tx_overflow={st['tx_overflow']}")
        if fleet is not None:
            adm = fleet_snap["admission"]
            print(f"Fleet:             {fleet_snap['workers']} workers, "
                  f"{adm['admitted']} admitted, "
                  f"{sum(adm['shed'].values())} shed")
        if tracer is not None:
            print("Stage breakdown (us):")
            for stage, s in stage_breakdown.items():
                print(f"  {stage:<12} p50 {s['p50_us']:>9.1f}   "
                      f"p99 {s['p99_us']:>9.1f}   n {s['count']}")
            if not res.slo["ok"]:
                print(f"SLO BREACHED: {', '.join(res.slo['breaches'])}")
    if args.validate:
        failures = res.meets_targets(cfg)
        for f in failures:
            print(f"TARGET FAILED: {f}", file=sys.stderr)
        return 1 if failures else 0
    return 0


def _trace_dora(args):
    """Build a self-contained engine (+scheduler/+inline fleet) stack,
    arm a span-event-keeping tracer, and drive a full DORA exchange for
    `--macs` subscribers plus a renewal round that hits the device fast
    path — the canonical traced workload `bng trace dump/export` ships.
    Returns (tracer, recorder) with the tracer DISARMED again."""
    import ipaddress

    from bng_tpu.control import dhcp_codec, packets
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.nat import NATManager
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.telemetry import FlightRecorder, RecorderConfig
    from bng_tpu.telemetry import spans as tele
    from bng_tpu.utils.net import ip_to_u32, parse_mac

    net = ipaddress.ip_network(args.pool_cidr)
    server_ip = int(net.network_address + 1)
    server_mac = parse_mac("02:aa:bb:cc:dd:01")
    fastpath = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=4,
                              update_slots=max(256, 2 * args.batch_size))
    fastpath.set_server_config(server_mac, server_ip)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=int(net.network_address),
                        prefix_len=net.prefixlen, gateway=server_ip,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    server = DHCPServer(server_mac, server_ip, pools,
                        fastpath_tables=fastpath)
    engine = Engine(fastpath, nat, batch_size=args.batch_size,
                    slow_path=server.handle_frame)
    fleet = None
    if args.workers > 1:
        from bng_tpu.control.admission import AdmissionConfig
        from bng_tpu.control.fleet import FleetSpec, SlowPathFleet

        # inline workers: deterministic, and the worker-stage histogram
        # still exercises the cross-worker merge path. A generous
        # deadline keeps compile-cold first batches from being shed.
        fleet = SlowPathFleet(
            FleetSpec.from_pool_manager(server_mac, server_ip, pools),
            n_workers=args.workers, pools=pools, mode="inline",
            admission=AdmissionConfig(
                inbox_capacity=max(512, 2 * args.batch_size),
                deadline_ms=60_000.0),
            table_sink=fastpath)
        engine.slow_path_batch = fleet.handle_batch
    target = engine
    if args.scheduler:
        from bng_tpu.runtime.scheduler import (SchedulerConfig,
                                               TieredScheduler)

        target = TieredScheduler(engine, SchedulerConfig(
            bulk_batch=args.batch_size))

    recorder = FlightRecorder(RecorderConfig(out_dir=args.trace_dir))
    import jax

    recorder.set_backend(jax.default_backend())
    tracer = tele.Tracer(recorder=recorder, keep_events=1 << 14)

    def discover(mac, xid):
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def request(mac, offer_frame, xid):
        od = packets.decode(offer_frame)
        off = dhcp_codec.decode(od.payload)
        p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid,
                                     requested_ip=off.yiaddr,
                                     server_id=od.src_ip)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    macs = [(0x02C0 << 32 | i).to_bytes(6, "big") for i in range(args.macs)]
    with tele.armed(tracer):
        for base in range(0, len(macs), args.batch_size):
            chunk = macs[base : base + args.batch_size]
            res = target.process([discover(m, 0x1000 + base + k)
                                  for k, m in enumerate(chunk)])
            offers = {i: f for i, f in res["slow"] if f is not None}
            offers.update({i: f for i, f in res.get("tx", [])})
            reqs = [request(m, offers[k], 0x2000 + base + k)
                    for k, m in enumerate(chunk) if k in offers]
            if reqs:
                target.process(reqs)
        # renewal round: cached DISCOVERs answered on device (the
        # trace shows the fast path next to the slow one)
        target.process([discover(m, 0x3000 + k)
                        for k, m in enumerate(macs[: args.batch_size])])
        if hasattr(target, "flush"):
            target.flush()
    if fleet is not None:
        fleet.close()
    return tracer, recorder


def run_trace(args) -> int:
    """`bng trace status|dump|export` — operator verbs over the
    telemetry subsystem. `status` lists flight dumps in the trace dir;
    `dump` runs a traced DORA exchange and writes a flight-recorder
    dump; `export --format chrome` emits Chrome-trace/Perfetto JSON of
    the exchange's spans."""
    import os

    from bng_tpu.telemetry import chrome_trace, default_trace_dir

    if args.trace_cmd == "status":
        out_dir = args.trace_dir or default_trace_dir()
        dumps = []
        if os.path.isdir(out_dir):
            for name in sorted(os.listdir(out_dir)):
                if not name.startswith("flight-") or not name.endswith(".json"):
                    continue
                path = os.path.join(out_dir, name)
                entry = {"file": name, "bytes": os.path.getsize(path)}
                try:
                    with open(path) as f:
                        d = json.load(f)
                    entry.update(reason=d.get("reason"),
                                 backend=d.get("meta", {}).get("backend"),
                                 records=len(d.get("records", ())))
                except (OSError, ValueError):
                    entry["error"] = "unreadable"
                dumps.append(entry)
        print(json.dumps({
            "trace_dir": out_dir,
            "armed_env": os.environ.get("BNG_TELEMETRY") == "1",
            "dumps": dumps,
        }, indent=2))
        return 0

    tracer, recorder = _trace_dora(args)
    if args.trace_cmd == "dump":
        path = recorder.dump("cli", "bng trace dump DORA exchange",
                             path=args.out or None)
        if path is None:
            print("trace dump: write failed", file=sys.stderr)
            return 1
        print(json.dumps({"dump": path,
                          "records": int(tracer.seq),
                          "stage_breakdown": tracer.breakdown()}, indent=2))
        return 0
    # export
    if args.format != "chrome":
        print(f"trace export: unknown format {args.format!r} "
              f"(supported: chrome)", file=sys.stderr)
        return 2
    trace = chrome_trace(tracer, label="bng-tpu DORA")
    out_path = args.out or os.path.join(
        args.trace_dir or default_trace_dir(), "dora-trace.json")
    if os.path.dirname(out_path):
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    n_x = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    print(json.dumps({"trace": out_path, "events": n_x,
                      "stages": sorted({e["name"] for e in
                                        trace["traceEvents"]
                                        if e.get("ph") == "X"})}, indent=2))
    return 0


def run_ctl(args) -> int:
    """`bng ctl` — runtime control of a LIVE `bng run` process over its
    --ctl-listen wire (control/opsctl.py): `fleet resize N`,
    `fleet rolling-restart`, `engine swap`, `status`. Prints the
    transition report; rc=0 on ok/noop, 1 on a rejected/failed/rolled-
    back transition, 2 when the process is unreachable."""
    from bng_tpu.control.opsctl import ctl_request

    if args.ctl_cmd == "status":
        op, body = "status", None
    elif args.ctl_cmd == "fleet":
        if args.fleet_cmd == "resize":
            op, body = "fleet/resize", {"n": args.n}
        else:
            op, body = "fleet/rolling-restart", {}
    else:  # engine swap
        op, body = "engine/swap", {}
    try:
        _code, doc = ctl_request(args.ctl_addr, op, body)
    except OSError as e:  # URLError subclasses OSError
        print(f"ctl: cannot reach {args.ctl_addr}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(doc, indent=2, sort_keys=True))
    if op == "status":
        return 0
    return 0 if doc.get("outcome") in ("ok", "noop") else 1


def run_checkpoint(args) -> int:
    """`bng checkpoint save|restore|info` — operator verbs over the
    warm-restart store. save/restore build the full app from the same
    flag surface as `run` (the snapshot must see the same table
    geometry the running process uses); info only reads headers."""
    from bng_tpu.control.statestore import CheckpointStore

    cfg = _config_from_args(args)
    if not cfg.checkpoint_dir:
        print("checkpoint: --checkpoint-dir is required", file=sys.stderr)
        return 2
    if args.ckpt_cmd == "info":
        infos = [i._asdict() for i in CheckpointStore(cfg.checkpoint_dir).list()]
        print(json.dumps(infos, indent=2))
        return 0

    app = BNGApp(cfg)
    try:
        if args.ckpt_cmd == "save":
            # snapshot of THIS freshly-built process (warm-restored from
            # the dir's newest checkpoint when one exists) — it cannot
            # see a separately-running daemon's live state; a running
            # `bng run` snapshots via SIGTERM or its own cadence
            print("checkpoint save: snapshotting a freshly built app "
                  "(not any running daemon — use SIGTERM or "
                  "--checkpoint-interval-s for that)", file=sys.stderr)
            ckptr = app.components["checkpointer"]
            path = ckptr.save_now(reason="cli")
            s = ckptr.stats
            print(json.dumps({
                "path": str(path), "seq": s["last_seq"],
                "bytes": s["last_bytes"],
                "duration_s": round(s["last_duration_s"], 3)}))
            return 0
        # restore: _build already hydrated (or rejected) — report it
        err = app.components.get("checkpoint_error")
        if err:
            print(f"checkpoint restore REJECTED: {err}", file=sys.stderr)
            return 1
        rows = app.components.get("checkpoint_restored")
        if rows is None:
            print(f"checkpoint restore: no checkpoint in "
                  f"{cfg.checkpoint_dir}", file=sys.stderr)
            return 1
        out = {"restored_rows": rows}
        if getattr(args, "audit", False):
            # --audit: prove the hydrated authorities agree BEFORE the
            # snapshot is trusted to serve traffic. rc=2 on ANY
            # violation — a bad checkpoint must never silently serve.
            from bng_tpu.chaos.invariants import audit_app

            report = audit_app(app)
            out["audit"] = report.to_dict()
            print(json.dumps(out, indent=2))
            if not report.ok:
                print("checkpoint restore --audit: invariant "
                      f"violations {report.violations_by_kind()} — "
                      "refusing this snapshot", file=sys.stderr)
                return 2
            return 0
        print(json.dumps(out, indent=2))
        return 0
    finally:
        app.close()


def run_chaos(args) -> int:
    """`bng chaos run|audit` — the fault-injection harness
    (bng_tpu/chaos): `run` executes the scripted scenario suite (plus an
    optional fault soak) and prints a bit-deterministic JSON report —
    two runs with one --seed emit identical bytes; `audit` builds the
    app from the normal run flags and proves the cross-authority
    invariants hold (rc=2 on any violation)."""
    if args.chaos_cmd == "audit":
        from bng_tpu.chaos.invariants import audit_app

        app = BNGApp(_config_from_args(args))
        try:
            report = audit_app(app)
            print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
            return 0 if report.ok else 2
        finally:
            app.close()

    # the scenario suite is CPU-deterministic by contract (two runs of
    # one --seed must emit identical bytes) and the sharded swap
    # scenario needs a multi-device mesh: pin the hermetic CPU backend
    # with 8 virtual devices BEFORE anything initializes a backend —
    # the same guard the test conftest and dryrun_multichip use
    from bng_tpu.utils.jaxenv import force_cpu

    force_cpu(8)
    from bng_tpu.chaos.runner import (bench_lines, canonical_json,
                                      run_report, scenario_catalog)

    if getattr(args, "list", False):
        for name, desc in scenario_catalog():
            print(f"{name:<28} {desc}")
        return 0
    # metrics=None: the one-shot CLI run has no scrape endpoint to serve
    # the bng_chaos_* families from — the report IS the output. A live
    # `bng run` process soaking via the runner passes its own BNGMetrics.
    names = [args.scenario] if args.scenario else None
    try:
        report = run_report(args.seed, names=names,
                            soak_epochs=args.soak_epochs,
                            storm_scale=args.storm_scale)
    except ValueError as e:
        print(f"chaos run: {e}", file=sys.stderr)
        print("scenario catalog:", file=sys.stderr)
        for name, desc in scenario_catalog():
            print(f"  {name:<28} {desc}", file=sys.stderr)
        return 2
    text = canonical_json(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.bench_log:
        # diffable per-scenario lines next to bench.py's results; the
        # wallclock/run_id/schema stamp lives only in the appender
        # (telemetry/ledger.py), never in the compared report bytes
        from bng_tpu.telemetry import ledger as ledger_mod

        try:
            for line in bench_lines(report):
                ledger_mod.append(args.bench_log, line)
        except OSError as e:
            print(f"chaos run: bench-log append failed: {e}",
                  file=sys.stderr)
    print(text)
    return 0 if report["ok"] else 1


def _cluster_wave(coord, n_subs: int, chunk: int = 512) -> dict:
    """Drive a synthetic DORA wave through the cluster front door —
    the `bng cluster run --subscribers N` smoke traffic. Returns the
    wave verdict (leased / unique / shed) for the status output."""
    from bng_tpu.control import dhcp_codec, packets
    from bng_tpu.loadtest.harness import StormFrameFactory

    fac = StormFrameFactory(coord.server_ip)
    macs = [(0x02D6 << 32 | i).to_bytes(6, "big") for i in range(n_subs)]
    leased: dict[bytes, int] = {}
    now = coord.clock()
    for ci in range(0, n_subs, chunk):
        cmacs = macs[ci:ci + chunk]
        out = coord.handle_batch(
            [(i, fac.discover(m, ci + i + 1)) for i, m in enumerate(cmacs)],
            now=now)
        offers: dict[bytes, int] = {}
        for (_l, rep), m in zip(out, cmacs):
            if rep is not None:
                p = dhcp_codec.decode(packets.decode(rep).payload)
                if p.msg_type == dhcp_codec.OFFER:
                    offers[m] = p.yiaddr
        req = [m for m in cmacs if m in offers]
        out = coord.handle_batch(
            [(i, fac.request(m, offers[m], 0x100000 + ci + i))
             for i, m in enumerate(req)], now=now)
        for (_l, rep), m in zip(out, req):
            if rep is not None:
                p = dhcp_codec.decode(packets.decode(rep).payload)
                if p.msg_type == dhcp_codec.ACK:
                    leased[m] = p.yiaddr
    return {"subscribers": n_subs, "leased": len(leased),
            "unique_ips": len(set(leased.values())),
            "shed": coord.shed_frames,
            "ok": (len(leased) == n_subs
                   and len(set(leased.values())) == n_subs)}


def _plan_summary(plan) -> dict:
    from bng_tpu.utils.net import u32_to_ip

    return {
        "space": f"{u32_to_ip(plan.space_network)}/{plan.space_prefix_len}",
        "block_prefix_len": plan.block_prefix_len,
        "blocks": plan.n_blocks,
        "epoch": plan.epoch,
        "addresses": plan.total_addresses(),
        "members": {
            iid: {"blocks": [f"{u32_to_ip(b.network)}/{b.prefix_len}"
                             for b in p.blocks],
                  "addresses": p.addresses(),
                  "nat": [list(plan.nat_range(b)) for b in p.blocks]}
            for iid, p in sorted(plan.members.items())},
        "free_blocks": [f"{u32_to_ip(b.network)}/{b.prefix_len}"
                        for b in plan.free],
    }


def run_cluster(args) -> int:
    """`bng cluster run|status` — the cluster-of-BNGs front door
    (bng_tpu/cluster). `run` composes N instances behind one FNV-1a32
    steering door (inline in this process, or one child process per
    instance), optionally drives a synthetic DORA wave, and prints or
    serves the coordinator status + bng_cluster_* metrics; `status`
    reads the carve plan back out of a checkpoint (or a status file a
    `run` wrote) without building anything."""
    from bng_tpu.utils.net import ip_to_u32

    if args.cluster_cmd == "status":
        if args.from_checkpoint:
            from bng_tpu.cluster import ClusterPlan
            from bng_tpu.runtime.checkpoint import (CheckpointError,
                                                    decode_checkpoint)

            try:
                with open(args.from_checkpoint, "rb") as f:
                    ckpt = decode_checkpoint(f.read())
            except (OSError, CheckpointError) as e:
                print(f"cluster status: {e}", file=sys.stderr)
                return 2
            comp = ckpt.meta.get("components", {}).get("cluster_plan")
            if not comp:
                print("cluster status: checkpoint carries no "
                      "cluster_plan component", file=sys.stderr)
                return 1
            try:
                plan = ClusterPlan.from_dict(comp)
            except (KeyError, TypeError, ValueError) as e:
                print(f"cluster status: corrupt carve plan: {e!r}",
                      file=sys.stderr)
                return 2
            print(json.dumps(_plan_summary(plan), indent=2,
                             sort_keys=True))
            return 0
        if args.status_file:
            try:
                with open(args.status_file) as f:
                    print(f.read().rstrip())
            except OSError as e:
                print(f"cluster status: {e}", file=sys.stderr)
                return 2
            return 0
        print("cluster status: --from-checkpoint or --status-file "
              "required (a live `cluster run` writes the latter)",
              file=sys.stderr)
        return 2

    # -- cluster join ------------------------------------------------
    # run this box as a FULL SERVING MEMBER of a remote coordinator's
    # carve (ISSUE 20): announce with capped-backoff retries, hydrate
    # the carved blocks from the coordinator's handoff stream, bring up
    # a local fleet+engine stack, serve steered batches over the
    # fabric, and ship lease/HA deltas back on every reply
    if args.join:
        import socket as _socket

        from bng_tpu.cluster.coordinator import DEFAULT_FABRIC_PSK
        from bng_tpu.cluster.fabric import UDPTransport
        from bng_tpu.cluster.member import MemberRuntime
        from bng_tpu.control.deviceauth import PSKAuthenticator
        from bng_tpu.control.metrics import BNGMetrics

        host_s, _, port_s = args.join.rpartition(":")
        try:
            hub = (host_s or "127.0.0.1", int(port_s))
        except ValueError:
            print(f"cluster run: bad --join {args.join!r} "
                  f"(want HOST:PORT)", file=sys.stderr)
            return 2
        hostname = _socket.gethostname()
        node_id = args.node_id or f"bng-{hostname}"
        ep = UDPTransport(node_id, PSKAuthenticator(
            psk=args.fabric_psk or DEFAULT_FABRIC_PSK))
        ep.add_peer("coordinator", hub)
        member = MemberRuntime(
            ep, node_id, hostname,
            join_deadline_s=args.join_deadline,
            log=lambda m: print(m, file=sys.stderr))
        metrics = BNGMetrics()
        print(f"cluster join: {node_id} (host {hostname}) -> "
              f"{hub[0]}:{hub[1]}", file=sys.stderr)
        last_state = member.state
        ticks = 0
        try:
            while True:
                member.tick()
                st = member.status()
                metrics.record_member(st)
                if member.state != last_state:
                    print(f"cluster join: {last_state} -> "
                          f"{member.state} (epoch {member.epoch}, "
                          f"{member.join_retries} retries)",
                          file=sys.stderr)
                    last_state = member.state
                if member.state == "gave_up":
                    return 1
                ticks += 1
                if args.once and (member.state == "serving"
                                  or ticks >= 3):
                    print(json.dumps(st, indent=2, sort_keys=True,
                                     default=str))
                    return 0 if member.state == "serving" else 1
                if args.status_file and ticks % 10 == 0:
                    with open(args.status_file, "w") as f:
                        f.write(json.dumps(st, indent=2, sort_keys=True,
                                           default=str) + "\n")
                time.sleep(0.05)
        except KeyboardInterrupt:
            return 0
        finally:
            member.close()

    # -- cluster run -------------------------------------------------
    from bng_tpu.cluster import ClusterCoordinator
    from bng_tpu.control.metrics import BNGMetrics

    net_s, _, plen_s = args.space.partition("/")
    try:
        space_net, space_plen = ip_to_u32(net_s), int(plen_s or "10")
    except (OSError, ValueError) as e:
        print(f"cluster run: bad --space {args.space!r}: {e}",
              file=sys.stderr)
        return 2
    fabric_bind: tuple = ("127.0.0.1", 0)
    if args.listen:
        lh, _, lp = args.listen.rpartition(":")
        try:
            fabric_bind = (lh or "127.0.0.1", int(lp))
        except ValueError:
            print(f"cluster run: bad --listen {args.listen!r} "
                  f"(want HOST:PORT)", file=sys.stderr)
            return 2
    # the fabric lane rides --listen or process mode (process members
    # beat over UDP; inline members stay on the in-process oracle
    # unless a hub address asks for remote joiners)
    use_fabric = bool(args.listen) or args.mode == "process"
    coord = ClusterCoordinator(
        mode=args.mode, space_network=space_net,
        space_prefix_len=space_plen,
        nat_base=ip_to_u32(args.nat_base) if args.nat_base else 0,
        nat_total=args.nat_total, n_workers=args.workers,
        sub_nbuckets=args.sub_nbuckets,
        fabric=use_fabric, fabric_psk=args.fabric_psk,
        fabric_bind=fabric_bind)
    if use_fabric and coord.fabric_transport is not None:
        fa = coord.fabric_transport.addr
        print(f"cluster fabric: listening on {fa[0]}:{fa[1]}",
              file=sys.stderr)
    metrics = BNGMetrics()
    expected_remotes: dict = {}
    for spec_s in (args.expect_remote or ()):
        iid, _, rhost = spec_s.partition("=")
        if not iid:
            print(f"cluster run: bad --expect-remote {spec_s!r} "
                  f"(want ID=HOST)", file=sys.stderr)
            return 2
        expected_remotes[iid] = rhost or iid
    try:
        coord.add_instances([f"bng-{i:02d}" for i in range(args.instances)],
                            remotes=expected_remotes)
        out: dict = {}
        if args.subscribers:
            out["wave"] = _cluster_wave(coord, args.subscribers)
        status = coord.status()
        metrics.record_cluster(status)
        out["status"] = status
        if args.checkpoint_out:
            from bng_tpu.runtime.checkpoint import (build_checkpoint,
                                                    encode_checkpoint)

            ckpt = build_checkpoint(1, time.time(), cluster_plan=coord)
            with open(args.checkpoint_out, "wb") as f:
                f.write(encode_checkpoint(ckpt))
            out["checkpoint"] = args.checkpoint_out
        text = json.dumps(out, indent=2, sort_keys=True, default=str)
        if args.status_file:
            with open(args.status_file, "w") as f:
                f.write(text + "\n")
        print(text)
        if args.once:
            wave = out.get("wave")
            return 0 if (wave is None or wave["ok"]) else 1
        # serve: the HA/membership machinery ticks at 1 Hz (the same
        # cadence App.tick gives a single instance) until interrupted
        print(f"cluster serving: {args.instances} instances "
              f"({args.mode}); ^C to stop", file=sys.stderr)
        # with a fabric the tick must outpace the membership beats and
        # the handoff retransmit timer; without one, 1 Hz (App.tick's
        # cadence for a single instance) is plenty
        tick_s = 0.1 if use_fabric else 1.0
        try:
            last_status = 0.0
            while True:
                time.sleep(tick_s)
                coord.tick()
                if time.time() - last_status >= 1.0:
                    last_status = time.time()
                    status = coord.status()
                    metrics.record_cluster(status)
                    if args.status_file:
                        with open(args.status_file, "w") as f:
                            f.write(json.dumps(status, indent=2,
                                               sort_keys=True,
                                               default=str) + "\n")
        except KeyboardInterrupt:
            pass
        return 0
    finally:
        coord.close()


def run_perf(args) -> int:
    """`bng perf gate|import` — the perf-regression ledger verbs
    (telemetry/ledger.py; no jax import, runs cold in milliseconds).

    gate: robust per-stage trend regression detection for the newest
    ledger line against its last-K COMPARABLE predecessors (same
    metric + backend class + device kind + batch geometry — a
    CPU-fallback run is never scored against a TPU cohort). rc contract:
    0 clean / 1 regression (stderr names the stage) / 2 internal /
    3 incomparable cohort.

    import: one-shot normalizer migrating pre-schema bench_runs.jsonl
    lines to the current schema (schema_version 0 tag, stable legacy
    run_ids, best-effort env fingerprint from the `device` field)."""
    from bng_tpu.telemetry import ledger as ledger_mod

    path = args.ledger or ledger_mod.default_ledger_path()
    if args.perf_cmd == "import":
        try:
            lines = ledger_mod.read(path)
        except OSError as e:
            print(f"perf import: cannot read {path}: {e}", file=sys.stderr)
            return 2
        migrated = ledger_mod.import_legacy(lines)
        n_legacy = sum(1 for ln in migrated
                       if ln.get("schema_version") == 0)
        out_path = args.out
        if args.in_place:
            out_path = path
            backup = path + ".bak"
            import shutil

            shutil.copyfile(path, backup)
            print(f"perf import: backup at {backup}", file=sys.stderr)
        if not out_path:
            for ln in migrated:
                print(json.dumps(ln))
        else:
            with open(out_path, "w") as f:
                for ln in migrated:
                    f.write(json.dumps(ln) + "\n")
        print(f"perf import: {len(migrated)} lines "
              f"({n_legacy} tagged schema_version 0)"
              + (f" -> {out_path}" if out_path else " -> stdout"),
              file=sys.stderr)
        return 0

    # gate
    rep = ledger_mod.gate_file(
        path, last_k=args.last_k, min_cohort=args.min_cohort,
        include_legacy=not args.no_legacy, metric=args.metric)
    if args.json_out:
        print(json.dumps(rep.to_dict(), indent=2, sort_keys=True))
    else:
        print(rep.format_text())
    if rep.regressions:
        names = ", ".join(r["key"] for r in rep.regressions)
        print(f"perf gate: REGRESSION in {names}", file=sys.stderr)
    return rep.rc


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _add_run_flags(p: argparse.ArgumentParser) -> None:
    defaults = BNGConfig()
    for f in dataclasses.fields(BNGConfig):
        flag = "--" + f.name.replace("_", "-")
        default = getattr(defaults, f.name)
        if isinstance(default, bool):
            p.add_argument(flag, dest=f.name, default=None,
                           action=argparse.BooleanOptionalAction)
        elif isinstance(default, list):
            p.add_argument(flag, dest=f.name, default=None, nargs="*")
        else:
            p.add_argument(flag, dest=f.name, default=None,
                           type=type(default))
    p.add_argument("--config", dest="config_file", default="")


def _config_from_args(args) -> BNGConfig:
    cfg = BNGConfig()
    cli_set = set()
    for f in dataclasses.fields(BNGConfig):
        v = getattr(args, f.name, None)
        if v is not None:
            setattr(cfg, f.name, v)
            cli_set.add(f.name)
    if args.config_file:
        cfg = load_config_file(args.config_file, cli_set, cfg)
    return cfg


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bng-tpu", description="TPU-native BNG dataplane")
    sub = parser.add_subparsers(dest="command")

    runp = sub.add_parser("run", help="run the BNG (full stack)")
    _add_run_flags(runp)
    runp.add_argument("--once", action="store_true",
                      help="build everything, print stats, exit (smoke mode)")

    demop = sub.add_parser("demo", help="device-free lifecycle demo")
    demop.add_argument("--subscribers", type=int, default=3)

    statsp = sub.add_parser("stats", help="print stats for a built app")
    _add_run_flags(statsp)

    # dhcp-loadtest parity (test/load/cmd/dhcp-loadtest/main.go:27-40)
    loadp = sub.add_parser("loadtest", help="DHCP load test against the "
                           "device pipeline + slow path")
    loadp.add_argument("--duration", type=float, default=10.0,
                       help="measured duration, seconds")
    loadp.add_argument("--warmup", type=float, default=1.0,
                       help="warmup duration, seconds (excluded)")
    loadp.add_argument("--batch-size", type=int, default=256,
                       help="lanes per device batch (the concurrency knob)")
    loadp.add_argument("--macs", type=int, default=10_000,
                       help="unique MAC cardinality (steers fast/slow split)")
    loadp.add_argument("--rps", type=int, default=0,
                       help="target requests/sec (0 = unlimited)")
    loadp.add_argument("--renewals", default=True,
                       action=argparse.BooleanOptionalAction)
    loadp.add_argument("--renewal-ratio", type=float, default=0.8)
    loadp.add_argument("--pool-cidr", default="10.0.0.0/16")
    loadp.add_argument("--json", action="store_true", dest="json_out")
    loadp.add_argument("--validate", action="store_true",
                       help="exit non-zero if performance targets not met")
    loadp.add_argument("--scheduler", action="store_true",
                       help="drive the latency-tiered scheduler instead of "
                            "the engine's batch interface")
    loadp.add_argument("--workers", type=int, default=1,
                       help="slow-path fleet worker count (>1 fans DHCPv4 "
                            "slow lanes out to worker processes)")
    loadp.add_argument("--fleet-mode", default="process",
                       choices=("process", "inline"),
                       help="fleet execution mode (inline = deterministic, "
                            "no child processes)")
    loadp.add_argument("--trace", action="store_true",
                       help="arm the telemetry tracer for the run and "
                            "report the per-stage latency breakdown")
    loadp.add_argument("--bench-log", default="",
                       help="append a schema'd perf-ledger line (stage "
                            "breakdown + SLO verdict + env fingerprint) "
                            "to this jsonl file — gate with `bng perf "
                            "gate --ledger FILE`")
    loadp.add_argument("--wire", nargs="?", const="mem", default=None,
                       metavar="IFNAME",
                       help="drive batches through the full wire loop "
                            "(kernel rings -> WirePump -> UMEM ring -> "
                            "engine -> wire) instead of the engine batch "
                            "interface; bare --wire runs the memory-rung "
                            "SimKernel loopback, --wire IFNAME walks the "
                            "real AF_XDP attach ladder")
    loadp.add_argument("--wire-pump", default="",
                       choices=("", "scalar", "vector"),
                       help="wire pump implementation (default: "
                            "BNG_WIRE_PUMP, scalar)")
    loadp.add_argument("--wire-peer", default="",
                       help="far-end interface for a live --wire rung "
                            "(veth peer to inject/collect on)")

    # telemetry subsystem (bng_tpu/telemetry)
    tracep = sub.add_parser("trace", help="telemetry: flight-recorder "
                            "status/dumps and Chrome-trace export of a "
                            "traced DORA exchange")
    trace_sub = tracep.add_subparsers(dest="trace_cmd", required=True)
    for verb, hlp in (("status", "list flight-recorder dumps in the "
                                 "trace dir"),
                      ("dump", "run a traced DORA exchange and write a "
                               "flight-recorder dump"),
                      ("export", "run a traced DORA exchange and export "
                                 "its spans (--format chrome loads in "
                                 "Perfetto / chrome://tracing)")):
        vp = trace_sub.add_parser(verb, help=hlp)
        vp.add_argument("--trace-dir", default="",
                        help="flight-dump dir (default $BNG_TRACE_DIR "
                             "or <tmp>/bng-flightrec)")
        if verb == "status":
            continue
        vp.add_argument("--out", default="", help="output file path")
        vp.add_argument("--format", default="chrome",
                        help="export format (chrome)")
        vp.add_argument("--macs", type=int, default=32,
                        help="subscribers to DORA through the trace")
        vp.add_argument("--batch-size", type=int, default=64)
        vp.add_argument("--pool-cidr", default="10.0.0.0/16")
        vp.add_argument("--scheduler", action="store_true",
                        help="drive the tiered scheduler (express/bulk "
                             "lanes appear as trace threads)")
        vp.add_argument("--workers", type=int, default=1,
                        help="inline fleet workers (>1 adds the worker "
                             "stage + scatter/gather spans)")

    # warm-restart snapshots (runtime/checkpoint.py + statestore.py)
    ckptp = sub.add_parser("checkpoint",
                           help="save/restore/inspect warm-restart "
                                "snapshots of the device tables")
    ckpt_sub = ckptp.add_subparsers(dest="ckpt_cmd", required=True)
    for verb, hlp in (("save", "build a fresh app (warm-restored from "
                               "the dir if possible) and snapshot IT — "
                               "a running daemon snapshots via SIGTERM "
                               "or --checkpoint-interval-s"),
                      ("restore", "build the app, hydrate from the "
                                  "latest checkpoint, report row counts"),
                      ("info", "list checkpoints in --checkpoint-dir "
                               "(header-only; flags corrupt files)")):
        vp = ckpt_sub.add_parser(verb, help=hlp)
        _add_run_flags(vp)
        if verb == "restore":
            vp.add_argument("--audit", action="store_true",
                            help="run the cross-authority invariant "
                                 "auditor after hydration; exit rc=2 on "
                                 "any violation (a bad snapshot must "
                                 "never silently serve traffic)")

    # chaos harness + invariant auditor (bng_tpu/chaos)
    chaosp = sub.add_parser("chaos", help="fault-injection scenarios and "
                                          "cross-authority invariant audits")
    chaos_sub = chaosp.add_subparsers(dest="chaos_cmd", required=True)
    crun = chaos_sub.add_parser(
        "run", help="run the scripted chaos scenarios (+ optional fault "
                    "soak); deterministic JSON report, rc=1 on failure")
    crun.add_argument("--seed", type=int, default=1,
                      help="fault-schedule seed; same seed -> identical "
                           "schedules and byte-identical report")
    crun.add_argument("--scenario", default="",
                      help="run one scenario by name (default: all)")
    crun.add_argument("--soak-epochs", type=int, default=0,
                      help="also run the seeded fault soak for N epochs "
                           "(traffic + generated faults + audit/epoch)")
    crun.add_argument("--out", default="",
                      help="also write the report JSON to this file")
    crun.add_argument("--list", action="store_true",
                      help="print the scenario catalog (one line each) "
                           "and exit")
    crun.add_argument("--storm-scale", type=float, default=1.0,
                      help="scale factor for the storm scenarios' "
                           "subscriber counts (1.0 = the published "
                           "storms: flash crowd at 100k)")
    crun.add_argument("--bench-log", default="",
                      help="append one diffable line per scenario "
                           "(scenario/shed/degraded) to this jsonl file "
                           "(bench_runs.jsonl convention)")
    caud = chaos_sub.add_parser(
        "audit", help="build the app from run flags and audit the state "
                      "authorities; rc=2 on any violation")
    _add_run_flags(caud)

    # cluster-of-BNGs front door (bng_tpu/cluster)
    clup = sub.add_parser(
        "cluster", help="compose N BNG instances into one cluster: "
                        "disjoint pool carve, HA standbys, FNV-1a32 "
                        "MAC steering (bng_tpu/cluster)")
    clu_sub = clup.add_subparsers(dest="cluster_cmd", required=True)
    clrun = clu_sub.add_parser(
        "run", help="carve the space, build the instances and serve "
                    "(or --once: print status and exit)")
    clrun.add_argument("--instances", type=int, default=4,
                       help="founding member count (default 4)")
    clrun.add_argument("--mode", choices=("inline", "process"),
                       default="inline",
                       help="inline = all instances in this process "
                            "(deterministic); process = one child per "
                            "instance")
    clrun.add_argument("--space", default="10.0.0.0/10",
                       help="cluster address space CIDR to carve "
                            "(default 10.0.0.0/10)")
    clrun.add_argument("--nat-base", default="",
                       help="first NAT public IP (block index maps to "
                            "NAT slice; default: no NAT ranges)")
    clrun.add_argument("--nat-total", type=int, default=0,
                       help="NAT public IP count across the space")
    clrun.add_argument("--workers", type=int, default=1,
                       help="slow-path workers per instance")
    clrun.add_argument("--sub-nbuckets", type=int, default=0,
                       help="per-instance fast-path subscriber buckets "
                            "(0 = slow-path only)")
    clrun.add_argument("--subscribers", type=int, default=0,
                       help="drive a synthetic DORA wave of N "
                            "subscribers through the front door")
    clrun.add_argument("--once", action="store_true",
                       help="print status (+ wave verdict) and exit "
                            "instead of serving")
    clrun.add_argument("--status-file", default="",
                       help="write status JSON here (refreshed each "
                            "tick while serving)")
    clrun.add_argument("--checkpoint-out", default="",
                       help="write a checkpoint carrying the carve "
                            "plan to this file")
    # ISSUE 19: the cluster control fabric (UDP membership lane)
    clrun.add_argument("--listen", default="",
                       help="HOST:PORT for the fabric hub: process "
                            "members beat here over authenticated UDP "
                            "and remote `--join`ers announce themselves "
                            "(process mode; port 0 = ephemeral)")
    clrun.add_argument("--join", default="",
                       help="HOST:PORT of a running coordinator's "
                            "--listen: join its carve as a full remote "
                            "serving member — hydrate the carved blocks "
                            "over the fabric handoff stream and serve "
                            "them from this box")
    clrun.add_argument("--join-deadline", type=float, default=60.0,
                       help="give up the join (capped-backoff retries) "
                            "after this many seconds (default 60)")
    clrun.add_argument("--expect-remote", action="append", default=[],
                       metavar="ID=HOST",
                       help="declare a remote member slot in the "
                            "founding carve (repeatable): blocks deal "
                            "to it on the host axis now, and the slot "
                            "comes alive when that box --join's")
    clrun.add_argument("--fabric-psk", default="",
                       help="pre-shared key authenticating fabric "
                            "datagrams (>=16 chars; default: the dev "
                            "PSK — set your own off-box)")
    clrun.add_argument("--node-id", default="",
                       help="member id to announce when --join'ing "
                            "(default bng-<hostname>)")
    clstat = clu_sub.add_parser(
        "status", help="print cluster status: the carve plan from a "
                       "checkpoint, or a status file a run wrote")
    clstat.add_argument("--from-checkpoint", default="",
                        help="read the carve plan out of this "
                             "checkpoint file")
    clstat.add_argument("--status-file", default="",
                        help="print the status JSON a `cluster run "
                             "--status-file` wrote")

    # runtime ops control (control/opsctl.py wire)
    ctlp = sub.add_parser(
        "ctl", help="zero-downtime ops on a LIVE `bng run` process "
                    "(fleet resize / rolling restart / engine swap)")
    ctlp.add_argument("--ctl-addr", default="127.0.0.1:9092",
                      help="the live process's --ctl-listen address")
    ctl_sub = ctlp.add_subparsers(dest="ctl_cmd", required=True)
    ctl_sub.add_parser("status", help="what a transition would act on")
    cfp = ctl_sub.add_parser("fleet", help="slow-path fleet transitions")
    cf_sub = cfp.add_subparsers(dest="fleet_cmd", required=True)
    rzp = cf_sub.add_parser(
        "resize", help="grow/shrink the fleet live — re-carves lease "
                       "slices and re-shards books without dropping "
                       "in-flight DORAs")
    rzp.add_argument("n", type=int, help="target worker count")
    cf_sub.add_parser(
        "rolling-restart", help="replace workers one shard at a time "
                                "(drain-then-transfer per shard)")
    cep = ctl_sub.add_parser("engine", help="engine transitions")
    ce_sub = cep.add_subparsers(dest="engine_cmd", required=True)
    ce_sub.add_parser(
        "swap", help="blue/green engine swap: snapshot-hydrated standby "
                     "+ delta replay + audited atomic flip (rollback on "
                     "failure)")

    # perf ledger + regression gate (telemetry/ledger.py)
    perfp = sub.add_parser(
        "perf", help="perf-regression ledger over bench_runs.jsonl: "
                     "schema import + per-stage trend gate")
    perf_sub = perfp.add_subparsers(dest="perf_cmd", required=True)
    pgate = perf_sub.add_parser(
        "gate", help="gate the newest ledger line against its last-K "
                     "comparable runs (median/MAD per stage); rc: 0 "
                     "clean / 1 regression / 2 internal / 3 "
                     "incomparable-cohort")
    pgate.add_argument("--ledger", default="",
                       help="ledger path (default $BNG_BENCH_LOG or the "
                            "repo's bench_runs.jsonl)")
    pgate.add_argument("--metric", default="",
                       help="gate the newest line of this metric only")
    pgate.add_argument("--last-k", type=int, default=8,
                       help="cohort depth: compare against the last K "
                            "comparable runs")
    pgate.add_argument("--min-cohort", type=int, default=3,
                       help="minimum comparable history before the "
                            "trend gate claims anything")
    pgate.add_argument("--no-legacy", action="store_true",
                       help="exclude schema_version<1 (pre-schema) "
                            "lines from cohorts")
    pgate.add_argument("--json", action="store_true", dest="json_out")
    pimp = perf_sub.add_parser(
        "import", help="one-shot normalizer: migrate pre-schema ledger "
                       "lines to the current schema (schema_version 0 "
                       "tag, legacy run_ids, env from `device`)")
    pimp.add_argument("--ledger", default="",
                      help="ledger path (default $BNG_BENCH_LOG or the "
                           "repo's bench_runs.jsonl)")
    pimp.add_argument("--out", default="",
                      help="write migrated lines here (default stdout)")
    pimp.add_argument("--in-place", action="store_true",
                      help="rewrite the ledger in place (backup at "
                           "<ledger>.bak)")

    checkp = sub.add_parser(
        "check", help="bngcheck: dataplane-invariant static analyzer "
                      "(rc=1 on any non-baselined finding)")
    from bng_tpu.analysis.cli import add_check_args, run_check
    add_check_args(checkp)

    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)

    if args.command == "version":
        print(f"bng-tpu {__version__}")
        return 0
    if args.command == "check":
        return run_check(args)
    if args.command == "demo":
        run_demo(args.subscribers)
        return 0
    if args.command == "loadtest":
        return run_loadtest(args)
    if args.command == "checkpoint":
        return run_checkpoint(args)
    if args.command == "chaos":
        return run_chaos(args)
    if args.command == "cluster":
        return run_cluster(args)
    if args.command == "ctl":
        return run_ctl(args)
    if args.command == "trace":
        return run_trace(args)
    if args.command == "perf":
        return run_perf(args)
    if args.command in ("run", "stats"):
        app = BNGApp(_config_from_args(args))
        try:
            if args.command == "stats" or args.once:
                print(json.dumps(app.stats(), indent=2, default=str))
                return 0
            # Serve until interrupted: metrics + collector loops live in
            # threads; the engine is driven by the packet source the
            # operator attaches (synthetic source in tests/bench).
            collector = app.components.get("collector")
            if collector is not None:
                collector.start()
                port = collector.serve_http(app.config.metrics_port)
                print(f"metrics on :{port}/metrics", file=sys.stderr)
            srv = app.components.get("cluster_server")
            if srv is not None:
                print(f"cluster on {srv.url}", file=sys.stderr)
            if app.fleet_blockers:
                # startup status must say it, not just a log line: the
                # configured worker count is NOT what is running
                print(f"slowpath fleet BLOCKED (single-worker): "
                      f"{','.join(app.fleet_blockers)} not yet "
                      f"fleet-aware — see README 'Slow-path fleet'",
                      file=sys.stderr)
            if getattr(app, "sharded_blockers", None):
                print(f"sharded serving: "
                      f"{','.join(app.sharded_blockers)} disabled "
                      f"(engine-path features) — see README "
                      f"'Sharded serving'", file=sys.stderr)
            if app.config.shards > 1:
                print(f"sharded dataplane: {app.config.shards} shards "
                      f"(ring-steered owner batches)", file=sys.stderr)
            ops = app.components.get("ops")
            if ops is not None and app.config.ctl_listen:
                from bng_tpu.control.opsctl import OpsServer

                chost, _, cport = app.config.ctl_listen.rpartition(":")
                try:
                    osrv = app.components["ops_server"] = OpsServer(
                        ops, chost or "127.0.0.1", int(cport or 0)).start()
                    app._on_close(osrv.close)
                    print(f"ctl on {osrv.addr[0]}:{osrv.addr[1]} "
                          f"(bng ctl --ctl-addr "
                          f"{osrv.addr[0]}:{osrv.addr[1]} ...)",
                          file=sys.stderr)
                except OSError as e:
                    print(f"ctl listener unavailable ({e}); "
                          f"runtime ops disabled", file=sys.stderr)
            # SIGTERM -> final checkpoint then clean exit. The handler
            # only sets a flag: the save runs on the loop thread below,
            # never from signal context (the drive loop may hold _ctl —
            # a snapshot from the handler would deadlock on it).
            ckptr = app.components.get("checkpointer")
            if ckptr is not None:
                import signal

                stop_flag = {"sigterm": False}
                signal.signal(signal.SIGTERM,
                              lambda *_: stop_flag.update(sigterm=True))
            # main loop: busy-drive the ring when one exists, 1 Hz
            # cluster maintenance either way
            has_ring = app.components.get("ring") is not None
            last_tick = 0.0
            _sanitize_ctx_enter("loop")  # sanitizer ownership context
            while True:
                if ckptr is not None and stop_flag["sigterm"]:
                    with app._ctl:
                        ckptr.save_now(reason="sigterm")
                    return 0
                moved = app.drive_once()
                if ops is not None:
                    # operator transitions run HERE — at the batch
                    # boundary, on the loop thread — never on the HTTP
                    # handler thread that requested them
                    moved += ops.run_pending()
                now_t = time.time()
                if now_t - last_tick >= 1.0:
                    last_tick = now_t
                    app.tick(now_t)
                if moved == 0:
                    time.sleep(0.001 if has_ring else 1.0)
        except KeyboardInterrupt:
            return 0
        finally:
            app.close()
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
