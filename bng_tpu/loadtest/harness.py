"""DHCP load/benchmark harness — the test/load framework re-hosted.

Parity with the reference's load framework (SURVEY.md §4.5;
test/load/dhcp_benchmark.go): configurable unique-MAC cardinality to
steer the fast/slow path split, warmup phase excluded from measurement,
renewal ratio after warmup, P50/P95/P99/min/max latency, achieved RPS,
and target validation with the published thresholds (50k+ RPS, P99
<10ms slow path, >95% cache hit after warmup — README.md Performance
table; targets restated in test/load/dhcp_benchmark.go:1-9).

TPU twist: instead of blasting UDP sockets at a server process, the
harness drives the Engine's batch interface directly — the measured
quantity is the device pipeline + slow-path control plane, which is the
system under test. Cache-hit rate here is exact (device ST_HIT/ST_MISS
counters), not the reference's latency-threshold estimate
(dhcp_benchmark.go:114-121) — the estimate is still computed for
output parity.
"""

from __future__ import annotations

import dataclasses
import json
import struct
import time
from typing import Callable

import numpy as np

from bng_tpu.control import dhcp_codec, packets


@dataclasses.dataclass
class BenchmarkConfig:
    """BenchmarkConfig parity (dhcp_benchmark.go:25-54)."""

    batch_size: int = 256
    duration_s: float = 10.0
    warmup_s: float = 1.0
    unique_macs: int = 10_000
    enable_renewals: bool = True
    renewal_ratio: float = 0.8  # DefaultConfig: 80% renewals after warmup
    rps_limit: int = 0  # 0 = unlimited
    seed: int = 42

    # validation targets (README.md Performance table)
    target_rps: float = 50_000.0
    target_p99_ms: float = 10.0
    target_cache_hit: float = 0.95
    target_fastpath_p99_us: float = 100.0

    # run the pre-classified DHCP stream through the engine's DHCP-only
    # device program (reference parity: dhcp_fastpath.c is its own XDP
    # program and replies never traverse the TC chain). False = the fused
    # full-pipeline step.
    dhcp_only_program: bool = True

    # label for the traffic shape that drove the run ("" = the default
    # steady DORA/renewal mix); storm scenarios stamp their name here so
    # bench_runs.jsonl lines are diffable per scenario
    scenario: str = ""


@dataclasses.dataclass
class BenchmarkResult:
    """BenchmarkResult parity (dhcp_benchmark.go:71-121)."""

    duration_s: float = 0.0
    requests: int = 0
    responses: int = 0
    errors: int = 0
    rps: float = 0.0
    latency_p50_us: float = 0.0
    latency_p95_us: float = 0.0
    latency_p99_us: float = 0.0
    latency_p999_us: float = 0.0
    latency_min_us: float = 0.0
    latency_max_us: float = 0.0
    # per-request latency percentiles from the telemetry histogram
    # (telemetry/hist.py — log-bucketed, mergeable): batch wall time
    # amortized over the batch, which is what each client in the batch
    # actually waited. A p999 exists here because the histogram keeps
    # the whole distribution, not three pre-picked quantiles.
    request_p50_us: float = 0.0
    request_p99_us: float = 0.0
    request_p999_us: float = 0.0
    request_mean_us: float = 0.0
    fastpath_hits: int = 0  # exact device counter
    slowpath_hits: int = 0
    cache_hit_rate: float = 0.0
    # per-request (batch-amortized) latency estimate for reference parity
    # (<1ms == fast path, dhcp_benchmark.go:114-121)
    est_fastpath_hits: int = 0
    est_cache_hit_rate: float = 0.0
    # p99 over per-request latency of batches with NO slow lanes — the
    # fast-path-only latency the <100us target gates
    fastpath_p99_us: float = 0.0
    batches: int = 0
    # which device program served the run: "dhcp_fastpath" (DHCP-only fast
    # lane) or "fused_pipeline" — numbers are not comparable across the two
    program: str = ""
    # traffic shape that drove the run (BenchmarkConfig.scenario) — storm
    # runs stamp their name so bench_runs.jsonl lines diff per scenario
    scenario: str = ""
    # admission shed counts by reason (inbox_full / deadline /
    # request_overflow / chaos) — every shed is a COUNTED degradation
    shed: dict = dataclasses.field(default_factory=dict)
    # degraded-but-not-failed verdicts by resource (dhcp_pool /
    # nat_block / nat_port ... exhaustion): the server stayed up and
    # answered what it could; these count what it could NOT
    degraded: dict = dataclasses.field(default_factory=dict)
    # per-stage SLO verdict (telemetry/slo.py evaluate over the armed
    # tracer's breakdown): {"ok": bool, "breaches": [stage...]} — empty
    # when the run was untraced. Rides to_dict so loadtest JSON and
    # --bench-log ledger lines are perf-gate-consumable.
    slo: dict = dataclasses.field(default_factory=dict)

    def meets_targets(self, cfg: BenchmarkConfig) -> list[str]:
        """Returns failed-target descriptions (empty == pass), the
        MeetsTargets role (dhcp_benchmark.go:578-596)."""
        failures = []
        if self.rps < cfg.target_rps:
            failures.append(f"RPS {self.rps:.0f} < {cfg.target_rps:.0f}")
        if self.latency_p99_us > cfg.target_p99_ms * 1000:
            failures.append(
                f"P99 {self.latency_p99_us / 1000:.2f}ms > {cfg.target_p99_ms}ms")
        if self.cache_hit_rate < cfg.target_cache_hit:
            failures.append(
                f"cache hit {self.cache_hit_rate:.1%} < {cfg.target_cache_hit:.0%}")
        if self.fastpath_p99_us and self.fastpath_p99_us > cfg.target_fastpath_p99_us:
            failures.append(
                f"fast-path P99 {self.fastpath_p99_us:.0f}us > "
                f"{cfg.target_fastpath_p99_us:.0f}us")
        return failures

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        lines = [
            "--- DHCP Load Test Results ---",
            f"Duration:          {self.duration_s:.2f}s",
            f"Requests:          {self.requests}",
            f"Responses:         {self.responses}",
            f"Errors:            {self.errors}",
            f"Requests/sec:      {self.rps:,.0f}",
            f"Latency P50:       {self.latency_p50_us:.0f}us",
            f"Latency P95:       {self.latency_p95_us:.0f}us",
            f"Latency P99:       {self.latency_p99_us:.0f}us",
            f"Latency P999:      {self.latency_p999_us:.0f}us",
            f"Per-request P50/P99/P999: {self.request_p50_us:.0f}/"
            f"{self.request_p99_us:.0f}/{self.request_p999_us:.0f}us",
            f"Latency Min/Max:   {self.latency_min_us:.0f}us / {self.latency_max_us:.0f}us",
            f"Fast Path (dev):   {self.fastpath_hits} "
            f"({self.cache_hit_rate:.2%})",
            f"Slow Path:         {self.slowpath_hits}",
            f"Cache Hit Rate:    {self.cache_hit_rate:.2%}",
        ]
        if self.scenario:
            lines.insert(1, f"Scenario:          {self.scenario}")
        if self.shed:
            lines.append("Shed:              " + ", ".join(
                f"{k}={v}" for k, v in sorted(self.shed.items()) if v))
        if self.degraded:
            lines.append("Degraded:          " + ", ".join(
                f"{k}={v}" for k, v in sorted(self.degraded.items()) if v))
        return "\n".join(lines)


class DHCPBenchmark:
    """Drives an Engine with synthetic DHCP traffic and measures.

    The MAC working set cycles through `unique_macs` addresses; during
    warmup DORA establishes leases (populating the device cache via the
    slow path, exactly the reference's warmup role), then the measured
    phase sends DISCOVER/renewal REQUEST mixes whose fast/slow split
    follows cache coverage.
    """

    def __init__(self, engine, cfg: BenchmarkConfig | None = None,
                 clock: Callable[[], float] = time.perf_counter,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Callable[[str], None] | None = None):
        self.engine = engine
        self.cfg = cfg or BenchmarkConfig()
        self.clock = clock
        self.sleep = sleep  # injected with clock so RPS pacing stays consistent
        self.log = log or (lambda s: None)
        self._rng = np.random.default_rng(self.cfg.seed)
        self._macs = [
            (0x02B0 << 32 | i).to_bytes(6, "big")
            for i in range(self.cfg.unique_macs)
        ]
        self._leased: dict[bytes, int] = {}  # mac -> yiaddr

    def _program(self) -> str:
        """Which device program _process will use (recorded in the result —
        a fused-step fallback must be visible, not silent)."""
        if getattr(self.engine, "is_scheduler", False):
            # the tiered scheduler classifies per frame: pure-DHCP load
            # all rides its express lane (the DHCP-only program)
            return "tiered_scheduler"
        if self.cfg.dhcp_only_program and hasattr(self.engine, "process_dhcp"):
            return "dhcp_fastpath"
        return "fused_pipeline"

    def _process(self, frames: list[bytes]) -> dict:
        """Route the batch to the configured device program."""
        program = self._program()
        if program == "tiered_scheduler":
            return self.engine.process(frames)
        if program == "dhcp_fastpath":
            return self.engine.process_dhcp(frames, batch=self.cfg.batch_size)
        return self.engine.process(frames)

    # -- frame builders --
    def _discover(self, mac: bytes, xid: int) -> bytes:
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
        p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def _renew_request(self, mac: bytes, ip: int, server_ip: int, xid: int) -> bytes:
        # RENEW: unicast REQUEST with ciaddr set (RFC 2131 §4.3.2)
        p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid, ciaddr=ip)
        p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
        return packets.udp_packet(mac, b"\xff" * 6, ip, server_ip, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def _full_request(self, mac: bytes, offer_frame: bytes, xid: int) -> bytes:
        od = packets.decode(offer_frame)
        offer = dhcp_codec.decode(od.payload)
        p = dhcp_codec.build_request(mac, dhcp_codec.REQUEST, xid=xid,
                                     requested_ip=offer.yiaddr, server_id=od.src_ip)
        p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    # -- phases --
    def warmup(self, deadline_s: float | None = None) -> int:
        """DORA every MAC through the slow path until the cache holds the
        working set (or the warmup budget runs out). Returns # leased."""
        cfg = self.cfg
        t_end = self.clock() + (deadline_s if deadline_s is not None else cfg.warmup_s)
        B = cfg.batch_size
        xid = 1
        i = 0
        while i < len(self._macs) and self.clock() < t_end:
            chunk = self._macs[i : i + B]
            frames = [self._discover(m, xid + k) for k, m in enumerate(chunk)]
            res = self._process(frames)
            offers = {lane: f for lane, f in res["slow"] if f is not None}
            offers.update({lane: f for lane, f in res["tx"]})
            req_frames, req_macs = [], []
            for k, m in enumerate(chunk):
                if k in offers:
                    req_frames.append(self._full_request(m, offers[k], xid + k))
                    req_macs.append(m)
            if req_frames:
                # a lease only counts once the server ACKs it — NAK'd or
                # dropped REQUESTs must not become renewal targets
                res2 = self._process(req_frames)
                acks = {lane: f for lane, f in res2["slow"] if f is not None}
                acks.update({lane: f for lane, f in res2["tx"]})
                for lane, m in enumerate(req_macs):
                    f = acks.get(lane)
                    if f is None:
                        continue
                    rep = dhcp_codec.decode(packets.decode(f).payload)
                    if rep.msg_type == dhcp_codec.ACK:
                        self._leased[m] = rep.yiaddr
            xid += 2 * B
            i += B
        return len(self._leased)

    def run(self) -> BenchmarkResult:
        cfg = self.cfg
        self.log(f"warmup {cfg.warmup_s}s over {cfg.unique_macs} MACs...")
        leased = self.warmup()
        self.log(f"warmup done: {leased} leases cached; measuring {cfg.duration_s}s...")

        # measurement deltas start from here (warmup excluded)
        start_dhcp = self.engine.stats.dhcp.copy()
        start_slow_errors = self.engine.stats.slow_errors
        from bng_tpu.telemetry.hist import LatencyHist

        res = BenchmarkResult(program=self._program(), scenario=cfg.scenario)
        lat_us: list[float] = []  # whole-batch wall time
        fast_lat_us: list[float] = []  # per-request, pure-fastpath batches
        req_hist = LatencyHist()  # per-request (batch-amortized) latency
        B = cfg.batch_size
        xid = 1 << 20
        from bng_tpu.ops.dhcp import SC_IP

        server_ip = int(self.engine.fastpath.server[SC_IP])
        t0 = self.clock()
        t_end = t0 + cfg.duration_s
        macs = self._macs
        leased_macs = list(self._leased.items())
        while self.clock() < t_end:
            frames = []
            for k in range(B):
                renew = (cfg.enable_renewals and leased_macs
                         and self._rng.random() < cfg.renewal_ratio)
                if renew:
                    mac, ip = leased_macs[int(self._rng.integers(len(leased_macs)))]
                    # RFC 2131 §4.3.2 renewal: unicast REQUEST w/ ciaddr,
                    # answered on device (fast path handles REQUEST too)
                    frames.append(self._renew_request(mac, ip, server_ip, xid + k))
                else:
                    mac = macs[int(self._rng.integers(len(macs)))]
                    frames.append(self._discover(mac, xid + k))
            t1 = self.clock()
            out = self._process(frames)
            dt_us = (self.clock() - t1) * 1e6
            lat_us.append(dt_us)
            # one histogram sample per REQUEST at its amortized share of
            # the batch wall time (all requests in a batch wait the same
            # wall clock; B samples weight the distribution by traffic)
            req_hist.record_many(np.full(len(frames), dt_us / len(frames)))
            if not out["slow"]:
                fast_lat_us.append(dt_us / B)
            res.batches += 1
            res.requests += len(frames)
            res.responses += len(out["tx"]) + sum(
                1 for _, f in out["slow"] if f is not None)
            xid += B
            if cfg.rps_limit:
                # pace to the target rate (token-bucket-ish sleep)
                expected = res.requests / cfg.rps_limit
                ahead = expected - (self.clock() - t0)
                if ahead > 0:
                    self.sleep(min(ahead, 0.1))

        res.duration_s = self.clock() - t0
        res.rps = res.requests / res.duration_s if res.duration_s else 0.0
        if lat_us:
            arr = np.asarray(lat_us)
            # latency percentiles report the full batch wall time — the
            # worst-case client-observed response time; the reference's
            # per-request <1ms fast/slow estimate is applied to the
            # batch-amortized per-request latency
            res.latency_p50_us = float(np.percentile(arr, 50))
            res.latency_p95_us = float(np.percentile(arr, 95))
            res.latency_p99_us = float(np.percentile(arr, 99))
            res.latency_p999_us = float(np.percentile(arr, 99.9))
            res.latency_min_us = float(arr.min())
            res.latency_max_us = float(arr.max())
        if req_hist.n:
            res.request_p50_us = round(req_hist.percentile(50), 1)
            res.request_p99_us = round(req_hist.percentile(99), 1)
            res.request_p999_us = round(req_hist.percentile(99.9), 1)
            res.request_mean_us = round(req_hist.mean_us, 1)
            per_req = arr / B
            res.est_fastpath_hits = int((per_req < 1000).sum()) * B
            res.est_cache_hit_rate = float((per_req < 1000).mean())
        if fast_lat_us:
            res.fastpath_p99_us = float(np.percentile(np.asarray(fast_lat_us), 99))
        from bng_tpu.ops.dhcp import ST_HIT, ST_MISS

        d = self.engine.stats.dhcp - start_dhcp
        res.fastpath_hits = int(d[ST_HIT])
        res.slowpath_hits = int(d[ST_MISS])
        total = res.fastpath_hits + res.slowpath_hits
        res.cache_hit_rate = res.fastpath_hits / total if total else 0.0
        # errors: requests that never got a reply (pool exhaustion and
        # other swallowed slow-path failures) + handler exceptions
        res.errors = (res.requests - res.responses
                      + int(self.engine.stats.slow_errors - start_slow_errors))
        return res


class WireLoopTarget:
    """Adapts the full wire loop to the DHCPBenchmark `process()`
    contract — `bng loadtest --wire` (ISSUE 15).

    Instead of calling the engine's batch interface, every benchmark
    batch is injected at the far end of the wire and collected back
    there: inject -> kernel rings -> WirePump -> UMEM ring ->
    Engine.process_ring_pipelined -> verdicts -> WirePump -> kernel TX
    -> far end. Replies are matched to request lanes by BOOTP xid (the
    wire gives back frames, not lane indexes), and everything that left
    the wire reports as the "tx" lane — on the wire a slow-path OFFER
    and a device OFFER are indistinguishable by design; the exact
    fast/slow split still comes from the device counters like every
    other loadtest.

    `inject(frames)` / `collect() -> list[bytes]` / `tick()` abstract
    the far end: SimKernelRings loopback on the memory rung (works in
    any container), AF_PACKET peer sockets on a real veth/NIC rung.
    """

    is_scheduler = False

    def __init__(self, engine, ring, pump, inject: Callable,
                 collect: Callable, tick: Callable | None = None,
                 deadline_s: float = 2.0, idle_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.ring = ring
        self.pump = pump
        self._inject = inject
        self._collect = collect
        self._tick = tick
        self.deadline_s = deadline_s
        # give up on missing lanes after this much continuous no-progress
        # (frames shed at admission never produce a reply: without the
        # idle exit an overloaded run spins out the FULL deadline per
        # batch and the benchmark measures the timeout constant)
        self.idle_s = idle_s
        self.clock = clock
        self.unmatched = 0  # egress frames with no requesting lane

    # DHCPBenchmark reads these off its target
    @property
    def stats(self):
        return self.engine.stats

    @property
    def fastpath(self):
        return self.engine.fastpath

    @staticmethod
    def _xid(frame: bytes, reply: bool) -> int | None:
        """BOOTP xid of a DHCP frame (request op=1 / reply op=2), or
        None. Tolerates 0-2 VLAN tags like the ring classifier."""
        off = 12
        if len(frame) < off + 2:
            return None
        et = (frame[off] << 8) | frame[off + 1]
        for _ in range(2):
            if et not in (0x8100, 0x88A8):
                break
            off += 4
            if len(frame) < off + 2:
                return None
            et = (frame[off] << 8) | frame[off + 1]
        off += 2
        if et != 0x0800 or len(frame) < off + 20:
            return None
        ihl = (frame[off] & 0x0F) * 4
        bootp = off + ihl + 8
        if len(frame) < bootp + 8 or frame[bootp] != (2 if reply else 1):
            return None
        return int.from_bytes(frame[bootp + 4 : bootp + 8], "big")

    def process(self, frames: list[bytes]) -> dict:
        lanes: dict[int, int] = {}
        for i, f in enumerate(frames):
            xid = self._xid(f, reply=False)
            if xid is not None:
                lanes[xid] = i
        self._inject(frames)
        got: dict[int, bytes] = {}
        budget = max(64, len(frames))
        now = self.clock()
        deadline = now + self.deadline_s
        last_progress = now
        while True:
            moved = self.pump.pump(budget=budget)
            if self._tick is not None:
                self._tick()
            self.engine.process_ring_pipelined(self.ring)
            self.engine.flush_pipeline()
            moved += self.pump.pump(budget=budget)
            matched = 0
            for fr in self._collect():
                xid = self._xid(fr, reply=True)
                lane = lanes.get(xid) if xid is not None else None
                if lane is None or lane in got:
                    self.unmatched += 1
                    continue
                got[lane] = fr
                matched += 1
            now = self.clock()
            if moved or matched:
                last_progress = now
            if len(got) >= len(lanes) or now >= deadline \
                    or now - last_progress > self.idle_s:
                break
        return {"tx": sorted(got.items()), "slow": []}


def result_json(res: BenchmarkResult) -> str:
    return json.dumps(res.to_dict(), indent=2)


# ---------------------------------------------------------------------------
# storm traffic generation (the DUMB half of the Jepsen split: generators
# know how to build traffic shapes, checkers — chaos/storms.py — carry
# all the intelligence about what must still be true afterwards)
# ---------------------------------------------------------------------------

class StormFrameFactory:
    """Preassembled client-frame prototypes with per-subscriber patch-in.

    The flash-crowd storm builds >=100k DISCOVER frames per retry round;
    at codec speed (~25us/frame: packet object, option encode, ljust,
    header pack) the GENERATOR would dominate the scenario's wall time.
    This is dhcp_codec.ReplyTemplate's idea pointed the other way: build
    one frame per (kind, geometry) through the real codec, then patch
    only the per-subscriber words. Patching is exact, not approximate —
    `tests/test_storms.py` pins byte-identity against codec-built frames
    for every kind.

    Checksum safety: v4 client frames carry UDP checksum 0 (legal in
    IPv4, and what packets.udp_packet emits), and the IPv4 header
    checksum covers no patched field except the renew frame's source
    address — renew() refolds the header checksum the same way
    udp_packet does.
    """

    # untagged Eth(14) + IPv4(20) + UDP(8)
    _BOOTP = 42

    def __init__(self, server_ip: int, pad: int = 300):
        self.server_ip = server_ip
        self.pad = pad
        self._proto: dict[str, bytes] = {}

    # -- prototype construction (once per kind, through the real codec) --

    def _build(self, kind: str) -> bytes:
        mac0 = b"\x00" * 6
        if kind == "discover":
            p = dhcp_codec.build_request(mac0, dhcp_codec.DISCOVER, xid=0)
            return packets.udp_packet(mac0, b"\xff" * 6, 0, 0xFFFFFFFF,
                                      68, 67, p.encode().ljust(self.pad,
                                                               b"\x00"))
        if kind == "request":
            p = dhcp_codec.build_request(mac0, dhcp_codec.REQUEST, xid=0,
                                         requested_ip=1,
                                         server_id=self.server_ip)
            return packets.udp_packet(mac0, b"\xff" * 6, 0, 0xFFFFFFFF,
                                      68, 67, p.encode().ljust(self.pad,
                                                               b"\x00"))
        if kind == "renew":
            p = dhcp_codec.build_request(mac0, dhcp_codec.REQUEST, xid=0,
                                         ciaddr=1)
            return packets.udp_packet(mac0, b"\xff" * 6, 1, self.server_ip,
                                      68, 67, p.encode().ljust(self.pad,
                                                               b"\x00"))
        raise ValueError(kind)

    def _template(self, kind: str) -> bytearray:
        proto = self._proto.get(kind)
        if proto is None:
            proto = self._proto[kind] = self._build(kind)
        return bytearray(proto)

    # -- per-subscriber renders ------------------------------------------

    def discover(self, mac: bytes, xid: int) -> bytes:
        f = self._template("discover")
        f[6:12] = mac
        b = self._BOOTP
        f[b + 4: b + 8] = struct.pack("!I", xid & 0xFFFFFFFF)
        f[b + 28: b + 34] = mac
        return bytes(f)

    def request(self, mac: bytes, ip: int, xid: int) -> bytes:
        f = self._template("request")
        f[6:12] = mac
        b = self._BOOTP
        f[b + 4: b + 8] = struct.pack("!I", xid & 0xFFFFFFFF)
        f[b + 28: b + 34] = mac
        # options: magic(236..240) | (53,1,t) | (50,4,ip) | (54,4,sid)
        # — build_request's layout; the requested-ip VALUE sits at +245
        f[b + 245: b + 249] = struct.pack("!I", ip)
        return bytes(f)

    def renew(self, mac: bytes, ip: int, xid: int) -> bytes:
        f = self._template("renew")
        f[6:12] = mac
        b = self._BOOTP
        f[b + 4: b + 8] = struct.pack("!I", xid & 0xFFFFFFFF)
        f[b + 12: b + 16] = struct.pack("!I", ip)  # ciaddr
        f[b + 28: b + 34] = mac
        f[26:30] = struct.pack("!I", ip)  # IP src — checksum input
        # refold the IPv4 header checksum from the actual header bytes
        # (udp_packet's arithmetic fold would desync silently if its
        # header fields ever change)
        f[24:26] = b"\x00\x00"
        f[24:26] = struct.pack("!H", packets.checksum16(bytes(f[14:34])))
        return bytes(f)
