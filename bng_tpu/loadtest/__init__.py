from bng_tpu.loadtest.harness import (  # noqa: F401
    BenchmarkConfig,
    BenchmarkResult,
    DHCPBenchmark,
    WireLoopTarget,
    result_json,
)
