"""Near-zero-overhead span tracing — the telemetry hook API.

Mold: chaos.faults.fault_point (PERF_NOTES §7). Design constraints, in
order:

1. **Disarmed cost ~ zero.** Every instrumented call site pays one
   function call, one module-global load and one `is None` compare when
   no tracer is armed (`python bench.py --telemetry-overhead` measures
   the ns/call; PERF_NOTES §8 publishes it). No locks, no dict lookups,
   no allocation on the disarmed path.
2. **Stages, not free-form names.** The packet lifecycle is a fixed
   stage vocabulary (small-int indexes into preallocated arrays), so an
   armed stamp costs array stores, not string hashing:

       ring        ring pop / assemble into the staging batch
       admit       admission verdicts (control/admission.py)
       lane_wait   scheduler lane enqueue -> dispatch (oldest frame)
       dispatch    host-side jitted dispatch (update drain + enqueue)
       device      device execution, PROFILER-FENCED (fed by bench via
                   utils/profiling.profile_step_durations +
                   jax.block_until_ready fencing — never conflated with
                   host wall time, the gray-failure class of VERDICT r5)
       device_wait host blocked forcing device outputs (includes tunnel
                   sync artifacts — report next to `device`, never as it)
       fleet       slow-path fleet scatter/gather (control/fleet.py)
       worker      per-frame worker handler time (merged from worker
                   processes' own histograms)
       slow_path   slow-path drain total (engine._handle_slow_lanes)
       reply       verdict demux + reply encode/inject
       wire_rx     wire pump ingress: kernel fill-ring feed + kernel RX
                   drain -> ring submit (runtime/xsk.py WirePump; the
                   kernel<->UMEM hop Dapper-named so wire cost is never
                   invisible to the SLO gate)
       wire_tx     wire pump egress: ring verdict descriptors -> kernel
                   TX ring + completion reap -> fill pool
       total       batch begin -> end (the client-visible wall time)

3. **Tracing is observation.** A span never mutates subsystem state;
   arming swaps one module global; telemetry failures never fault the
   dataplane (the recorder swallows its own I/O errors).

Two granularities:

- `t()` / `lap(stage, t0)` — the hot-path pair: `t()` returns None when
  disarmed, `lap` no-ops on a None origin. Two hook calls per
  instrumented region.
- `span(stage)` — context-manager sugar for coarse paths (CLI, tests).

Per-batch flight records: `begin_batch(lane, n)` opens a record slot
(preallocated pool — allocation-free), `stamp`/`lap`/`add` fill it, and
`end_batch(tok)` finalizes it into the FlightRecorder ring where the
anomaly triggers live (recorder.py).
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from bng_tpu.telemetry.hist import LatencyHist

# stage ids — array indexes; keep STAGE_NAMES in lockstep (and TOTAL
# LAST — the recorder indexes it as NSTAGES-1). `ops` is the
# zero-downtime-transition stage (fleet resize / rolling restart /
# blue/green engine swap phases — runtime/ops.py, control/fleet.py):
# each transition phase records one lap, so the histogram answers "how
# long do operational state moves stall the dataplane". The loop_*
# stages attribute the devloop ring pump (devloop/host.py): fill = rows
# into the ring slot, wait = slot staged -> ring dispatch (the latency
# the k-amortization trades away), retire = ring force + per-slot demux.
(RING, ADMIT, LANE_WAIT, DISPATCH, LOOP_FILL, LOOP_WAIT, LOOP_RETIRE,
 DEVICE, DEVICE_WAIT, FLEET, WORKER, SLOW, REPLY, OPS, WIRE_RX, WIRE_TX,
 TOTAL) = range(17)
STAGE_NAMES = ("ring", "admit", "lane_wait", "dispatch", "loop_fill",
               "loop_wait", "loop_retire", "device", "device_wait",
               "fleet", "worker", "slow_path", "reply", "ops", "wire_rx",
               "wire_tx", "total")
NSTAGES = len(STAGE_NAMES)

# lane ids for batch records
LANE_ENGINE, LANE_EXPRESS_L, LANE_BULK_L, LANE_RING_L, LANE_BENCH = range(5)
LANE_NAMES = ("engine", "express", "bulk", "ring", "bench")


class Tracer:
    """Armed runtime: per-stage histograms + open-batch record slots +
    (optionally) a bounded span-event log for Chrome-trace export."""

    OPEN_SLOTS = 16  # > max in-flight batches (sched depth + pipelined)

    def __init__(self, recorder=None, keep_events: int = 0,
                 clock=time.perf_counter_ns):
        self.recorder = recorder
        self.clock = clock
        self.hists = [LatencyHist() for _ in range(NSTAGES)]
        k = self.OPEN_SLOTS
        self._open_dur = np.zeros((k, NSTAGES), dtype=np.float64)  # us
        self._open_stamp = np.zeros((k, NSTAGES), dtype=np.int64)  # ns rel t0
        self._open_meta = np.zeros((k, 4), dtype=np.int64)  # lane,n,shed,punt
        self._open_t0 = np.zeros(k, dtype=np.int64)
        self._free = list(range(k))
        self._cur: int | None = None
        self.seq = 0
        self.records_dropped = 0
        # (stage, lane, t0_ns, dur_ns) span events for trace export
        self.events: deque | None = (deque(maxlen=keep_events)
                                     if keep_events else None)

    # -- batch records ----------------------------------------------------

    def begin(self, lane: int, size: int) -> int | None:
        if not self._free:
            self.records_dropped += 1
            return None
        tok = self._free.pop()
        self._open_dur[tok] = 0.0
        self._open_stamp[tok] = 0
        self._open_meta[tok] = (lane, size, 0, 0)
        self._open_t0[tok] = self.clock()
        self._cur = tok
        return tok

    def end(self, tok: int, punt: int = 0, shed: int = 0) -> None:
        now = self.clock()
        total_us = (now - self._open_t0[tok]) / 1000.0
        self._open_dur[tok, TOTAL] = total_us
        self.hists[TOTAL].record(total_us)
        if punt:
            self._open_meta[tok, 3] += punt
        if shed:
            self._open_meta[tok, 2] += shed
        if self.events is not None:
            self.events.append((TOTAL, int(self._open_meta[tok, 0]),
                                int(self._open_t0[tok]),
                                now - int(self._open_t0[tok])))
        if self.recorder is not None:
            lane, n, rshed, rpunt = (int(x) for x in self._open_meta[tok])
            self.recorder.push(lane, n, rshed, rpunt, self.seq,
                               self._open_dur[tok], self._open_stamp[tok])
        self.seq += 1
        self._free.append(tok)
        if self._cur == tok:
            self._cur = None

    def cancel(self, tok: int) -> None:
        """Release an open slot without recording (dispatch crashed)."""
        if tok not in self._free:
            self._free.append(tok)
        if self._cur == tok:
            self._cur = None

    def focus(self, tok) -> None:
        """Make `tok` the target of token-less laps (the retire path of a
        pipelined batch, where helpers don't thread the token)."""
        if tok is not None and tok not in self._free:
            self._cur = tok

    # -- span primitives --------------------------------------------------

    def lap(self, stage: int, t0: int, tok: int | None = None) -> None:
        now = self.clock()
        dur_us = (now - t0) / 1000.0
        self.hists[stage].record(dur_us)
        tok = tok if tok is not None else self._cur
        if tok is not None:
            self._open_dur[tok, stage] += dur_us
        if self.events is not None:
            lane = int(self._open_meta[tok, 0]) if tok is not None else 0
            self.events.append((stage, lane, t0, now - t0))

    def stamp(self, stage: int, tok: int | None = None) -> None:
        """Point event: ns offset of reaching `stage` within the open
        batch record (flight records carry stage timestamps AND stage
        durations)."""
        tok = tok if tok is not None else self._cur
        if tok is None:
            return
        self._open_stamp[tok, stage] = self.clock() - self._open_t0[tok]

    def observe(self, stage: int, dur_us: float,
                tok: int | None = None) -> None:
        """Feed an externally measured duration (lane wait computed from
        enqueue timestamps, profiler-fenced device time)."""
        self.hists[stage].record(dur_us)
        tok = tok if tok is not None else self._cur
        if tok is not None:
            self._open_dur[tok, stage] += dur_us
        if self.events is not None:
            lane = int(self._open_meta[tok, 0]) if tok is not None else 0
            now = self.clock()
            self.events.append((stage, lane, now - int(dur_us * 1000),
                                int(dur_us * 1000)))

    def observe_many(self, stage: int, us_values) -> None:
        """Bulk histogram feed (bench's profiler distributions)."""
        self.hists[stage].record_many(us_values)

    def add(self, tok: int | None = None, shed: int = 0,
            punt: int = 0) -> None:
        """Count sheds/punts against the open record; shed counts with no
        open record still reach the recorder's burst detector."""
        tok = tok if tok is not None else self._cur
        if tok is not None:
            self._open_meta[tok, 2] += shed
            self._open_meta[tok, 3] += punt
        elif shed and self.recorder is not None:
            self.recorder.note_shed(shed)

    # -- queries ----------------------------------------------------------

    def merge_stage(self, stage: int, hist_dict: dict) -> None:
        """Fold a serialized worker/shard histogram into a stage (the
        cross-process merge — control/fleet.py ships these in worker
        stats payloads)."""
        self.hists[stage].merge(LatencyHist.from_dict(hist_dict))

    def breakdown(self) -> dict:
        """{stage: {count, p50_us, p99_us, p999_us, mean_us, max_us}} for
        every stage with samples — the BENCH JSON `stage_breakdown`."""
        return {STAGE_NAMES[i]: h.summary()
                for i, h in enumerate(self.hists) if h.n}

    def snapshot(self) -> dict:
        return {
            "records": self.seq,
            "records_dropped": self.records_dropped,
            "stages": self.breakdown(),
            "recorder": (self.recorder.snapshot_meta()
                         if self.recorder is not None else None),
        }


# ---------------------------------------------------------------------------
# the hot-path hooks (module-level no-ops when disarmed)
# ---------------------------------------------------------------------------

_ACTIVE: Tracer | None = None


def enabled() -> bool:
    return _ACTIVE is not None


def tracer() -> Tracer | None:
    return _ACTIVE


def t() -> int | None:
    """Span origin. Disarmed (the production state) this is a global
    load + None compare — nothing else."""
    if _ACTIVE is None:
        return None
    return _ACTIVE.clock()


def lap(stage: int, t0: int | None, tok: int | None = None) -> None:
    """Close a span opened with t(). No-ops when disarmed at open time
    (t0 None) or now."""
    if _ACTIVE is None or t0 is None:
        return
    _ACTIVE.lap(stage, t0, tok)


def stamp(stage: int, tok: int | None = None) -> None:
    if _ACTIVE is None:
        return
    _ACTIVE.stamp(stage, tok)


def observe(stage: int, dur_us: float, tok: int | None = None) -> None:
    if _ACTIVE is None:
        return
    _ACTIVE.observe(stage, dur_us, tok)


def begin_batch(lane: int, size: int) -> int | None:
    if _ACTIVE is None:
        return None
    return _ACTIVE.begin(lane, size)


def end_batch(tok: int | None, punt: int = 0, shed: int = 0) -> None:
    if _ACTIVE is None or tok is None:
        return
    _ACTIVE.end(tok, punt=punt, shed=shed)


def cancel_batch(tok: int | None) -> None:
    if _ACTIVE is None or tok is None:
        return
    _ACTIVE.cancel(tok)


def focus(tok: int | None) -> None:
    if _ACTIVE is None:
        return
    _ACTIVE.focus(tok)


def add(tok: int | None = None, shed: int = 0, punt: int = 0) -> None:
    if _ACTIVE is None:
        return
    _ACTIVE.add(tok, shed=shed, punt=punt)


def trigger(reason: str, detail: str = "") -> str | None:
    """Anomaly hook: asks the armed recorder to dump the flight ring.
    Disarmed: global load + None compare (instrumented at worker death,
    invariant violations, backend fallback)."""
    if _ACTIVE is None or _ACTIVE.recorder is None:
        return None
    return _ACTIVE.recorder.trigger(reason, detail)


def set_meta(key: str, value) -> None:
    """Stamp a fact into the flight-record ring metadata — the
    backend-identity discipline (recorder.set_backend) generalized: the
    serving identity is per-process/per-path state, not per-batch, so
    it rides `meta` and lands in every dump. Used by the scheduler to
    record which express program (aot-express vs jit-full) served the
    last dispatch, so a fallback storm is diagnosable from one dump.
    Disarmed: global load + None compare."""
    if _ACTIVE is None or _ACTIVE.recorder is None:
        return
    _ACTIVE.recorder.meta[key] = value


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("stage", "tok", "t0")

    def __init__(self, stage: int, tok: int | None):
        self.stage = stage
        self.tok = tok

    def __enter__(self):
        self.t0 = _ACTIVE.clock() if _ACTIVE is not None else None
        return self

    def __exit__(self, *exc):
        lap(self.stage, self.t0, self.tok)
        return False


def span(stage: int, tok: int | None = None):
    """Context-manager span for coarse paths. Disarmed: returns a shared
    no-op singleton (global load + compare + attribute-free enter/exit)."""
    if _ACTIVE is None:
        return _NOOP
    return _Span(stage, tok)


def arm(tr: Tracer) -> Tracer:
    global _ACTIVE
    _ACTIVE = tr
    return tr


def disarm() -> None:
    global _ACTIVE
    _ACTIVE = None


class armed:
    """Context manager: arm a tracer for the block, disarm on exit —
    exceptions included (a failed bench can never leak an armed tracer
    into the next test)."""

    def __init__(self, tr: Tracer | None = None, recorder=None,
                 keep_events: int = 0):
        self.tracer = tr if tr is not None else Tracer(
            recorder=recorder, keep_events=keep_events)

    def __enter__(self) -> Tracer:
        return arm(self.tracer)

    def __exit__(self, *exc) -> None:
        disarm()
