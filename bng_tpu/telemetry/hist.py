"""Mergeable log-bucketed latency histograms (HDR-histogram shape).

Why not the Prometheus Histogram in control/metrics.py: its fixed bucket
tuple cannot recover a p999 at microsecond resolution, and merging two of
them across fleet worker processes loses everything between bucket
bounds. This is the standard HDR answer (log2 octaves subdivided
linearly): bounded relative error, O(1) record, and merge is plain
counter addition — associative and commutative by construction, so
per-worker and per-shard histograms fold into one fleet-wide
distribution in any order.

Geometry: values are recorded in integer nanoseconds. The first 8
buckets are exact (0..7 ns); above that each octave [2^e, 2^(e+1)) is
split into 8 linear sub-buckets, so every bucket's width is 1/8 of its
magnitude — relative quantization error <= 12.5%, percentiles reported
at the bucket midpoint. 488 int64 buckets cover 1 ns .. ~4.6e18 ns
(146 years) in ~4 KB.
"""

from __future__ import annotations

import numpy as np

_SUB = 8  # linear sub-buckets per octave (3 mantissa bits)
_SUB_BITS = 3
# exact buckets 0..7, then octaves e=3..62 (int64 range) x 8 sub-buckets
NBUCKETS = _SUB + (63 - _SUB_BITS) * _SUB


def _bucket_of(v_ns: int) -> int:
    """Bucket index for a non-negative integer nanosecond value."""
    if v_ns < _SUB:
        return v_ns if v_ns > 0 else 0
    e = v_ns.bit_length() - 1  # >= 3
    return (e - _SUB_BITS) * _SUB + ((v_ns >> (e - _SUB_BITS)) & (_SUB - 1)) + _SUB


def _bucket_bounds(idx: int) -> tuple[float, float]:
    """[lo, hi) in ns for bucket idx."""
    if idx < _SUB:
        return float(idx), float(idx + 1)
    b = idx - _SUB
    e = b // _SUB + _SUB_BITS
    m = b % _SUB
    width = 1 << (e - _SUB_BITS)
    lo = (_SUB + m) * width
    return float(lo), float(lo + width)


def counts_percentile(counts: np.ndarray, q: float) -> float:
    """q-th percentile (us, bucket-midpoint, <=12.5% rel. error) from a
    raw bucket-count vector — the ONE rank/cumsum/midpoint core.
    LatencyHist.percentile wraps it (adding the observed min/max
    clamp); the SLO monitor's windowed p99 calls it directly on
    bucket-count DELTAS, so the two can never drift apart."""
    n = int(counts.sum())
    if n == 0:
        return 0.0
    rank = q / 100.0 * (n - 1)
    target = int(np.floor(rank)) + 1  # 1-based sample index
    cum = np.cumsum(counts)
    idx = int(np.searchsorted(cum, target))
    lo, hi = _bucket_bounds(idx)
    return (lo + hi) / 2.0 / 1000.0


class LatencyHist:
    """One mergeable latency distribution. The public unit is
    MICROSECONDS (the stage-latency quantity); storage is ns buckets."""

    __slots__ = ("counts", "n", "sum_us", "min_us", "max_us")

    def __init__(self):
        self.counts = np.zeros(NBUCKETS, dtype=np.int64)
        self.n = 0
        self.sum_us = 0.0
        self.min_us = float("inf")
        self.max_us = 0.0

    # -- recording --------------------------------------------------------

    def record(self, us: float) -> None:
        if us < 0.0:
            us = 0.0
        self.counts[_bucket_of(int(us * 1000.0))] += 1
        self.n += 1
        self.sum_us += us
        if us < self.min_us:
            self.min_us = us
        if us > self.max_us:
            self.max_us = us

    def record_many(self, us_values) -> None:
        """Vectorized bulk record (bench feeds profiler distributions)."""
        us = np.asarray(us_values, dtype=np.float64)
        if us.size == 0:
            return
        us = np.maximum(us, 0.0)
        v = np.maximum((us * 1000.0).astype(np.int64), 0)
        # exponent via frexp (exact for ints < 2^53: v = m * 2^ex, m in
        # [0.5, 1) -> e = ex - 1); small values take the exact buckets
        _m, ex = np.frexp(np.maximum(v, 1).astype(np.float64))
        e = (ex - 1).astype(np.int64)
        shift = np.maximum(e - _SUB_BITS, 0)
        sub = (v >> shift) & (_SUB - 1)
        idx = np.where(v < _SUB, v,
                       (e - _SUB_BITS) * _SUB + sub + _SUB)
        np.add.at(self.counts, idx, 1)
        self.n += int(us.size)
        self.sum_us += float(us.sum())
        self.min_us = min(self.min_us, float(us.min()))
        self.max_us = max(self.max_us, float(us.max()))

    # -- queries ----------------------------------------------------------

    def percentile(self, q: float) -> float:
        """q-th percentile in us (bucket-midpoint; <=12.5% rel. error)."""
        if self.n == 0:
            return 0.0
        mid_us = counts_percentile(self.counts, q)
        # clamp into the observed range: midpoints can overshoot max
        return float(min(max(mid_us, self.min_us), self.max_us))

    def cumulative_le(self, us: float) -> int:
        """Samples <= us (bucket-granular: counts every bucket whose
        lower bound is <= the threshold — the Prometheus export bound)."""
        v_ns = int(us * 1000.0)
        idx = _bucket_of(v_ns)
        return int(self.counts[: idx + 1].sum())

    @property
    def mean_us(self) -> float:
        return self.sum_us / self.n if self.n else 0.0

    # -- merge (associative + commutative: plain counter addition) --------

    def merge(self, other: "LatencyHist") -> "LatencyHist":
        self.counts += other.counts
        self.n += other.n
        self.sum_us += other.sum_us
        self.min_us = min(self.min_us, other.min_us)
        self.max_us = max(self.max_us, other.max_us)
        return self

    def copy(self) -> "LatencyHist":
        h = LatencyHist()
        h.counts = self.counts.copy()
        h.n, h.sum_us = self.n, self.sum_us
        h.min_us, h.max_us = self.min_us, self.max_us
        return h

    # -- wire format (fleet workers ship these over the result pipe) ------

    def to_dict(self) -> dict:
        nz = np.nonzero(self.counts)[0]
        return {
            "n": self.n,
            "sum_us": self.sum_us,
            "min_us": self.min_us if self.n else 0.0,
            "max_us": self.max_us,
            "counts": {int(i): int(self.counts[i]) for i in nz},
        }

    @staticmethod
    def from_dict(d: dict) -> "LatencyHist":
        h = LatencyHist()
        h.n = int(d.get("n", 0))
        h.sum_us = float(d.get("sum_us", 0.0))
        h.min_us = float(d.get("min_us", 0.0)) if h.n else float("inf")
        h.max_us = float(d.get("max_us", 0.0))
        for i, c in d.get("counts", {}).items():
            i = int(i)
            if 0 <= i < NBUCKETS:
                h.counts[i] = int(c)
        return h

    def summary(self) -> dict:
        """{count, p50/p99/p999, mean, max} in us — the report shape."""
        return {
            "count": self.n,
            "p50_us": round(self.percentile(50), 2),
            "p99_us": round(self.percentile(99), 2),
            "p999_us": round(self.percentile(99.9), 2),
            "mean_us": round(self.mean_us, 2),
            "max_us": round(self.max_us, 2) if self.n else 0.0,
        }
