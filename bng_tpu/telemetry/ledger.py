"""Perf-regression ledger — bench_runs.jsonl promoted from a pile of
schema-less lines to a schema'd, append-only, machine-gated artifact.

Three bench rounds shipped CPU numbers as TPU headlines before the
`backend_fallback` fencing caught it (VERDICT r5), and the cure has two
halves: record WHAT actually served the run (backend identity,
environment fingerprint) on every line, and refuse to compare lines
across that identity. This module is both halves plus the trend gate:

- **Schema** (``append``): every new line carries ``schema_version``,
  a ``run_id``, a wallclock ``ts`` and — from the emitters — an ``env``
  fingerprint (device kind, jaxlib version, hostname) next to the
  existing geometry keys (batch/subscribers/flows). Legacy lines are
  normalized on read (``normalize_legacy``) and tagged
  ``schema_version: 0`` so the gate can include or exclude them
  explicitly (`--no-legacy`).
- **Cohorts** (``cohort_key``): two runs are comparable only when
  metric, backend class, device kind and batch geometry all match. A
  CPU-fallback run therefore has NO TPU cohort — asking the gate to
  score one against the other is the rc=3 refusal class, never a
  silent comparison (the Gray Failure lesson: record what served the
  request BEFORE comparing anything).
- **Gate** (``gate``): robust trend regression detection for the
  newest line against its last-K comparable predecessors — median/MAD
  per gated quantity, covering EVERY stage in ``stage_breakdown`` (p99
  per stage — Dapper: the ungated stage is where the regression
  hides), the headline ``value`` (direction inferred from the unit)
  and ``offer_device_only_p99_us``. The regression threshold is
  ``median + clamp(max(K_MAD * 1.4826 * MAD, REL_FLOOR * median),
  <= HARD_CAP * median)``: the MAD term absorbs run-to-run noise, the
  relative floor keeps a near-zero-MAD cohort from flagging jitter,
  and the hard cap guarantees a 2x regression can NEVER hide inside a
  noisy cohort (PERF_NOTES §12). A stage every cohort line carries but
  the candidate dropped is a coverage regression, flagged by name.

rc contract (`bng perf gate` / `bench.py --gate`):
  0 clean (or vacuous: cohort smaller than --min-cohort)
  1 regression — stderr names the regressed stage(s)/key(s)
  2 internal error (unreadable ledger, error-line candidate)
  3 incomparable cohort — history exists for this metric+geometry but
    only on a different backend class

Stdlib-only on the gate path (no jax import): `bng perf gate` runs in
tens of milliseconds, cold, anywhere — the same discipline as bngcheck.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import time
import uuid
from dataclasses import dataclass, field

SCHEMA_VERSION = 1

GATE_OK = 0
GATE_REGRESSION = 1
GATE_INTERNAL = 2
GATE_INCOMPARABLE = 3

# robust-threshold constants (PERF_NOTES §12). MAD is scaled by 1.4826
# (consistent sigma estimate under normality); the hard cap bounds the
# tolerated excess at 90% of the median so a 2x regression always trips
# regardless of cohort noise.
K_MAD = 4.0
REL_FLOOR = 0.35
HARD_CAP = 0.9
HARD_CAP_VALUE = 0.45  # higher-is-better keys: a 2x slowdown halves value

# geometry keys that define a cohort (present-only: legacy lines missing
# a key match other lines missing it). `depth` is the autotune sweep's
# pipeline-depth knob — two points differing only in depth are different
# operating points, not a trend (a depth-2 point gated against depth-8
# history would read as a fabricated 2-4x regression).
GEOMETRY_KEYS = ("batch", "subscribers", "flows", "depth")

# headline keys gated besides per-stage p99s; direction by unit/name
LOWER_BETTER_KEYS = ("offer_device_only_p99_us",)


def environment_fingerprint() -> dict:
    """Host/toolchain identity for a bench line. NEVER imports jax —
    config-1 (pure-host) runs call this before any backend probe, and
    an import here would race the guarded backend init. If jax is
    already up in this process, the device identity rides along."""
    env: dict = {"host": socket.gethostname()}
    try:
        from importlib import metadata

        env["jaxlib"] = metadata.version("jaxlib")
    except Exception:  # noqa: BLE001 — fingerprint is best-effort
        pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            env["jax"] = jax.__version__
            dev = jax.devices()[0]
            env["platform"] = dev.platform
            env["device_kind"] = (getattr(dev, "device_kind", "")
                                  or str(dev))
        except Exception:  # noqa: BLE001 — backend may be half-up
            pass
    # table-probe impl (xla | pallas): rides the fingerprint so Pallas
    # and XLA runs are never silently compared (cohort_key keys on it).
    # sys.modules only — importing ops.table here would drag jax in.
    tbl = sys.modules.get("bng_tpu.ops.table")
    if tbl is not None:
        try:
            env["table_impl"] = tbl.current_impl_label()
        except Exception:  # noqa: BLE001 — fingerprint is best-effort
            pass
    # host serving path (scalar | vector, ISSUE 14): same discipline —
    # a vectorized-host run must never trend against scalar history
    hp = sys.modules.get("bng_tpu.runtime.hostpath")
    if hp is not None:
        try:
            env["host_path"] = hp.current_host_path_label()
        except Exception:  # noqa: BLE001 — fingerprint is best-effort
            pass
    # wire pump (scalar | vector, ISSUE 15): the kernel<->UMEM mover's
    # identity — a batch-pump run must never trend against per-frame
    # pump history
    wp = sys.modules.get("bng_tpu.runtime.xsk")
    if wp is not None:
        try:
            env["wire_pump"] = wp.current_wire_pump_label()
        except Exception:  # noqa: BLE001 — fingerprint is best-effort
            pass
    return env


# ---------------------------------------------------------------------------
# line identity
# ---------------------------------------------------------------------------

def _device_str(line: dict) -> str:
    env = line.get("env") or {}
    return str(line.get("device") or env.get("device_kind") or "")


def backend_class(line: dict) -> str:
    """cpu | tpu | gpu | host — what actually served the run. The
    explicit fallback flag wins (a fallback line IS a cpu line even if
    other fields look healthy), then the env platform, then the device
    string; lines with no device at all (config-1 pure-host runs) are
    their own `host` class."""
    if line.get("backend_fallback"):
        return "cpu"
    env = line.get("env") or {}
    plat = env.get("platform")
    if plat:
        return str(plat)
    dev = _device_str(line)
    low = dev.lower()
    if "tpu" in low:
        return "tpu"
    if "cpu" in low:
        return "cpu"
    if "gpu" in low or "cuda" in low or "rocm" in low:
        return "gpu"
    return "host"


def device_kind(line: dict) -> str:
    """Device identity minus the ordinal (TFRT_CPU_0 -> TFRT_CPU): two
    chips of one kind are comparable, a v5e and a v4 are not. The
    `device` string is preferred over env.device_kind: both legacy and
    new bench lines carry it in the same format, while the jax
    Device.device_kind spelling differs ('cpu' vs the legacy-derived
    'TFRT_CPU') — keying on env first would silently split new runs
    from their legacy cohort and void the trend gate until new-schema
    history accumulates."""
    dev = str(line.get("device") or "")
    if dev:
        return dev.rstrip("0123456789").rstrip("_:")
    env = line.get("env") or {}
    return str(env.get("device_kind") or "")


def geometry(line: dict) -> tuple:
    return tuple((k, line[k]) for k in GEOMETRY_KEYS
                 if line.get(k) is not None)


def table_impl(line: dict) -> str:
    """Which table-probe implementation served the run (ISSUE 11): the
    top-level stamp wins (bench records the resolved choice on every
    line), then the env fingerprint. Legacy/unstamped lines predate the
    Pallas kernel and are, by construction, `xla` — defaulting keeps
    them one cohort instead of voiding all existing history.

    Host-class lines (config-1 pure control-plane runs, no device) never
    probe a device table, so their stamp is identity noise: a
    BNG_TABLE_IMPL=pallas config-1 run must keep gating against its
    host history, not void it behind an rc=3 refusal for a knob that
    cannot affect the metric."""
    if backend_class(line) == "host":
        return "xla"
    env = line.get("env") or {}
    return str(line.get("table_impl") or env.get("table_impl") or "xla")


def express_path(line: dict) -> str:
    """Which express-lane architecture served the run (ISSUE 13):
    `aot-express` (minimal AOT program + host template patch-in) vs
    `jit-full` (the full `_dhcp_jit` device program). Unstamped lines
    predate the AOT path and measured the full program — defaulting to
    `jit-full` keeps existing scheduler/OFFER history one cohort
    instead of voiding it. The two architectures are different
    programs: the gate must never trend one against the other (rc=3
    refusal, same discipline as table_impl)."""
    v = line.get("express_path")
    return str(v) if v else "jit-full"


def express_loop(line: dict) -> str:
    """Which express SERVING LOOP drove the dispatches (ISSUE 18):
    `per-batch` (one device touch per admission batch — both the
    jit-full and aot-express architectures) vs `devloop` (the k-slot
    descriptor-ring megakernel, one device touch per k batches).
    Unstamped lines predate the ring and dispatched per batch —
    defaulting to `per-batch` keeps ALL existing express history
    (jit-full and aot-express cohorts alike) one loop cohort. The loop
    changes what a "dispatch" stage lap even measures (one batch vs an
    amortized ring share): a trend across loops is an architecture
    comparison, not a regression signal (rc=3 refusal, the express_path
    discipline)."""
    v = line.get("express_loop")
    return str(v) if v else "per-batch"


def host_path(line: dict) -> str:
    """Which HOST serving path staged the run (ISSUE 14): `scalar` (the
    original per-frame ring/admission/pack loops) vs `vector` (the
    batch-native SoA path behind BNG_HOST_PATH). The top-level stamp
    wins (`bench.py --host-ab` records it per cohort), then the env
    fingerprint. Unstamped lines predate the vector path and ran the
    per-frame loops — defaulting to `scalar` keeps existing history one
    cohort. The two paths do the same work with different host
    machinery: a host-stage trend across them is an architecture
    comparison, not a regression signal (rc=3 refusal, the table_impl
    discipline)."""
    v = line.get("host_path")
    if v:
        return str(v)
    env = line.get("env") or {}
    return str(env.get("host_path") or "scalar")


def wire_pump(line: dict) -> str:
    """Which wire-pump implementation moved the run's frames (ISSUE
    15): `scalar` (the per-frame ctypes loop) vs `vector` (the batch
    verbs behind BNG_WIRE_PUMP). The top-level stamp wins (`bench.py
    --wire-ab` records it per cohort), then the env fingerprint.
    Unstamped lines predate the vector pump (or never touched a wire
    loop) and ran — if anything — the per-frame pump: defaulting to
    `scalar` keeps existing history one cohort. A wire-stage trend
    across the two pumps is an architecture comparison, not a
    regression signal (rc=3 refusal, the host_path discipline)."""
    v = line.get("wire_pump")
    if v:
        return str(v)
    env = line.get("env") or {}
    return str(env.get("wire_pump") or "scalar")


def n_shards(line: dict) -> int:
    """How many dataplane shards served the run (ISSUE 12): the
    top-level stamp wins (`bench.py --shards` records it on every
    line), then the legacy spelling `devices` (the config-5 sharded
    bench always recorded its mesh width there), then the env
    fingerprint. Unstamped lines are single-device by construction —
    defaulting to 1 keeps existing history one cohort. An aggregate
    8-shard Mpps line must never trend against single-device history:
    the cohort keys on this."""
    v = line.get("n_shards")
    if v is None:
        v = line.get("devices")
    if v is None:
        v = (line.get("env") or {}).get("n_shards")
    try:
        return int(v) if v is not None else 1
    except (TypeError, ValueError):
        return 1


def n_instances(line: dict) -> int:
    """How many cluster instances served the run (ISSUE 16): the
    top-level stamp wins (`bng cluster` benches record it per line),
    then the env fingerprint. Unstamped lines are single-instance by
    construction — defaulting to 1 keeps existing history one cohort.
    An aggregate 4-instance cluster number must never trend against
    single-process history: the cohort keys on this."""
    v = line.get("n_instances")
    if v is None:
        v = (line.get("env") or {}).get("n_instances")
    try:
        return int(v) if v is not None else 1
    except (TypeError, ValueError):
        return 1


def n_hosts(line: dict) -> int:
    """How many HOSTS the cluster's carve spanned (ISSUE 19): the plan
    host axis interleaves blocks across hosts, and a multi-host run's
    numbers carry cross-host fabric overhead a single-host run never
    pays. Same accessor discipline as n_instances — top-level stamp,
    then env fingerprint, legacy default 1 (every pre-fabric line ran
    on one host by construction)."""
    v = line.get("n_hosts")
    if v is None:
        v = (line.get("env") or {}).get("n_hosts")
    try:
        return int(v) if v is not None else 1
    except (TypeError, ValueError):
        return 1


def cohort_key(line: dict) -> tuple:
    return (line.get("metric"), backend_class(line), device_kind(line),
            table_impl(line), n_shards(line), n_instances(line),
            n_hosts(line), express_path(line), express_loop(line),
            host_path(line), wire_pump(line), geometry(line))


def _gateable(line: dict) -> bool:
    """Error lines and schema-less non-bench lines never gate (and never
    serve as cohort history): a failed run is not a trend point."""
    return (isinstance(line, dict) and "metric" in line
            and "error" not in line)


def newest_gateable_index(lines: list[dict]) -> int | None:
    """Index of the line gate() would pick as candidate — callers that
    must tie a verdict to a specific run (bench.py --gate) compare this
    against the pre-run line count, so a run that appended nothing (or
    only an error line) can never get a CLEAN verdict about stale
    history."""
    for i in range(len(lines) - 1, -1, -1):
        if _gateable(lines[i]):
            return i
    return None


# ---------------------------------------------------------------------------
# schema append / read / legacy import
# ---------------------------------------------------------------------------

def append(path: str, line: dict, run_id: str | None = None,
           ts: str | None = None) -> dict:
    """Append one schema'd line. The stamp (ts/schema_version/run_id)
    happens HERE, in the appender — deterministic producers (chaos
    reports, storm bench lines) stay byte-comparable because their
    compared payloads never contain the stamp."""
    stamped = {
        "ts": ts or time.strftime("%Y-%m-%dT%H:%M:%S"),
        "schema_version": line.get("schema_version", SCHEMA_VERSION),
        "run_id": run_id or line.get("run_id") or uuid.uuid4().hex[:12],
        **{k: v for k, v in line.items()
           if k not in ("ts", "schema_version", "run_id")},
    }
    with open(path, "a") as f:
        f.write(json.dumps(stamped) + "\n")
    return stamped


def read(path: str) -> list[dict]:
    """All parseable lines, in file order. A corrupt line is skipped
    (recorded under the `_corrupt` count on the returned list's last
    resort — callers that care use gate(), which reports it)."""
    out = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                out.append(json.loads(raw))
            except ValueError:
                out.append({"_corrupt": raw[:80]})
    return out


def normalize_legacy(line: dict, idx: int = 0) -> dict:
    """Best-effort migration of a pre-schema line: schema_version 0 tag
    (the gate's include-or-exclude handle), a stable legacy run_id, and
    an env fingerprint recovered from the fields the old emitters did
    write (`device`). Idempotent: an already-schema'd line is returned
    unchanged."""
    if "schema_version" in line:
        return line
    env = {}
    dev = line.get("device")
    if dev:
        env["device_kind"] = str(dev).rstrip("0123456789").rstrip("_:")
    out = {
        "ts": line.get("ts", ""),
        "schema_version": 0,
        "run_id": f"legacy-{idx:03d}",
        **{k: v for k, v in line.items() if k != "ts"},
    }
    if env:
        out["env"] = env
    return out


def import_legacy(lines: list[dict]) -> list[dict]:
    """`bng perf import`: the one-shot normalizer over a whole ledger."""
    return [normalize_legacy(ln, i) for i, ln in enumerate(lines)
            if "_corrupt" not in ln]


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

@dataclass
class GateReport:
    rc: int = GATE_OK
    candidate: dict = field(default_factory=dict)
    cohort_n: int = 0
    checked: list = field(default_factory=list)
    regressions: list = field(default_factory=list)
    notes: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.rc == GATE_OK

    def to_dict(self) -> dict:
        return {
            "rc": self.rc, "ok": self.ok,
            "candidate": self.candidate, "cohort_n": self.cohort_n,
            "checked": list(self.checked),
            "regressions": list(self.regressions),
            "notes": list(self.notes),
        }

    def format_text(self) -> str:
        lines = []
        cand = self.candidate
        head = (f"perf gate: {cand.get('metric', '?')} "
                f"[{cand.get('run_id', cand.get('ts', '?'))}] "
                f"vs cohort n={self.cohort_n}")
        lines.append(head)
        for note in self.notes:
            lines.append(f"  note: {note}")
        for r in self.regressions:
            lines.append(
                f"  REGRESSION {r['key']}: {r['candidate']} vs "
                f"median {r['median']} (threshold {r['threshold']}, "
                f"MAD {r['mad']})" if "median" in r
                else f"  REGRESSION {r['key']}: {r['detail']}")
        lines.append({GATE_OK: "verdict: CLEAN (rc=0)",
                      GATE_REGRESSION: "verdict: REGRESSION (rc=1)",
                      GATE_INTERNAL: "verdict: INTERNAL ERROR (rc=2)",
                      GATE_INCOMPARABLE:
                      "verdict: INCOMPARABLE COHORT (rc=3)"}[self.rc])
        return "\n".join(lines)


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _mad(vals: list[float], med: float) -> float:
    return _median([abs(v - med) for v in vals])


def _check_lower(key, cand, vals, regressions, checked):
    """Lower-is-better quantity (latencies): flag candidate above the
    robust threshold. The hard cap bounds tolerated excess at
    HARD_CAP * median — a 2x regression trips it in ANY cohort.
    `checked` records only quantities that actually evaluated (a
    zero-median cohort cannot be trended — claiming it was checked
    would overstate the report's coverage)."""
    med = _median(vals)
    if med <= 0:
        return
    checked.append(key)
    madn = _mad(vals, med) * 1.4826
    excess = min(max(K_MAD * madn, REL_FLOOR * med), HARD_CAP * med)
    threshold = med + excess
    if cand > threshold:
        regressions.append({
            "key": key, "candidate": round(cand, 2),
            "median": round(med, 2), "mad": round(madn, 2),
            "threshold": round(threshold, 2), "direction": "lower-better",
        })


def _check_higher(key, cand, vals, regressions, checked):
    """Higher-is-better quantity (Mpps, req/s): flag candidate below
    the robust floor; cap at HARD_CAP_VALUE so a halved value (= 2x
    slowdown) always trips."""
    med = _median(vals)
    if med <= 0:
        return
    checked.append(key)
    madn = _mad(vals, med) * 1.4826
    deficit = min(max(K_MAD * madn, REL_FLOOR * med), HARD_CAP_VALUE * med)
    threshold = med - deficit
    if cand < threshold:
        regressions.append({
            "key": key, "candidate": round(cand, 2),
            "median": round(med, 2), "mad": round(madn, 2),
            "threshold": round(threshold, 2), "direction": "higher-better",
        })


def _stage_p99(line: dict, stage: str) -> float | None:
    sb = line.get("stage_breakdown")
    if not isinstance(sb, dict):
        return None
    s = sb.get(stage)
    if not isinstance(s, dict):
        return None
    v = s.get("p99_us")
    return float(v) if isinstance(v, (int, float)) else None


def gate(lines: list[dict], last_k: int = 8, min_cohort: int = 3,
         include_legacy: bool = True, metric: str = "") -> GateReport:
    """Gate the newest gateable line against its comparable history.

    ``metric`` narrows candidacy to one metric's newest line; the
    default gates whatever run landed last (the `bench.py --gate`
    posture: you just appended a line, is it a regression?)."""
    rep = GateReport()
    corrupt = sum(1 for ln in lines if "_corrupt" in ln)
    if corrupt:
        rep.notes.append(f"{corrupt} corrupt ledger line(s) skipped")
    pool = [ln for ln in lines if _gateable(ln)]
    if metric:
        pool = [ln for ln in pool if ln.get("metric") == metric]
    if not include_legacy:
        pool = [ln for ln in pool
                if ln.get("schema_version", 0) >= SCHEMA_VERSION]
    if not pool:
        rep.notes.append("nothing to gate (no gateable lines)")
        return rep
    # legacy lines normalize in-memory so cohort identity is uniform
    pool = [normalize_legacy(ln, i) for i, ln in enumerate(pool)]
    cand = pool[-1]
    rep.candidate = {k: cand.get(k) for k in
                     ("metric", "run_id", "ts", "schema_version")}
    rep.candidate["backend"] = backend_class(cand)
    key = cohort_key(cand)
    history = pool[:-1]
    cohort = [ln for ln in history if cohort_key(ln) == key][-last_k:]
    rep.cohort_n = len(cohort)
    if len(cohort) < min_cohort:
        # ZERO same-cohort history while same-metric/geometry history
        # exists on a DIFFERENT backend class or table impl is the
        # cross-identity refusal class (a CPU-fallback run must never
        # score against TPU runs; a Pallas run must never score against
        # XLA history — the kernels are different programs). A merely
        # YOUNG same-identity cohort (1..min_cohort-1 lines) is not:
        # after a backend/impl migration the trend gate passes
        # vacuously while its new history accumulates.
        relaxed = [ln for ln in history
                   if ln.get("metric") == cand.get("metric")
                   and geometry(ln) == geometry(cand)
                   and (backend_class(ln) != backend_class(cand)
                        or table_impl(ln) != table_impl(cand)
                        or n_shards(ln) != n_shards(cand)
                        or n_instances(ln) != n_instances(cand)
                        or n_hosts(ln) != n_hosts(cand)
                        or express_path(ln) != express_path(cand)
                        or express_loop(ln) != express_loop(cand)
                        or host_path(ln) != host_path(cand)
                        or wire_pump(ln) != wire_pump(cand))]
        if not cohort and len(relaxed) >= min_cohort:
            others = sorted({
                f"{backend_class(ln)}/{table_impl(ln)}"
                f"/shards={n_shards(ln)}"
                f"/instances={n_instances(ln)}"
                f"/hosts={n_hosts(ln)}"
                f"/express={express_path(ln)}"
                f"/loop={express_loop(ln)}"
                f"/host={host_path(ln)}/wire={wire_pump(ln)}"
                for ln in relaxed})
            rep.rc = GATE_INCOMPARABLE
            rep.notes.append(
                f"candidate ran as {backend_class(cand)!r}/"
                f"{table_impl(cand)!r}/shards={n_shards(cand)}"
                f"/instances={n_instances(cand)}"
                f"/hosts={n_hosts(cand)}"
                f"/express={express_path(cand)!r}"
                f"/loop={express_loop(cand)!r}"
                f"/host={host_path(cand)!r}"
                f"/wire={wire_pump(cand)!r} (device "
                f"{device_kind(cand) or 'none'!r}) with no same-identity "
                f"history for this metric+geometry — the existing history "
                f"is on {others}: refusing the cross-identity comparison "
                f"(an aggregate sharded number never trends against a "
                f"different shard count's cohort, the AOT express "
                f"architecture never trends against the jit full-program "
                f"path, the devloop ring never trends against per-batch "
                f"dispatch, the vectorized host path never trends against "
                f"the scalar per-frame path, and the vector wire pump "
                f"never trends against the scalar pump)")
            return rep
        rep.notes.append(
            f"cohort too small (n={len(cohort)} < {min_cohort}): trend "
            f"gate passes vacuously")
        return rep

    # headline value, direction by unit
    unit = str(cand.get("unit", ""))
    vals = [float(ln["value"]) for ln in cohort
            if isinstance(ln.get("value"), (int, float))]
    if isinstance(cand.get("value"), (int, float)) and len(vals) >= min_cohort:
        if unit in ("us", "ms", "s"):
            _check_lower("value", float(cand["value"]), vals,
                         rep.regressions, rep.checked)
        else:
            _check_higher("value", float(cand["value"]), vals,
                          rep.regressions, rep.checked)

    # explicit lower-better headline keys (the paper-target quantity)
    for k in LOWER_BETTER_KEYS:
        cv = cand.get(k)
        vals = [float(ln[k]) for ln in cohort
                if isinstance(ln.get(k), (int, float)) and float(ln[k]) > 0]
        if isinstance(cv, (int, float)) and cv > 0 and len(vals) >= min_cohort:
            _check_lower(k, float(cv), vals, rep.regressions, rep.checked)

    # EVERY stage, not the headline: per-stage p99 trend
    cand_sb = cand.get("stage_breakdown") or {}
    cohort_stages: dict[str, list[float]] = {}
    for ln in cohort:
        sb = ln.get("stage_breakdown")
        if not isinstance(sb, dict):
            continue
        for stage in sb:
            v = _stage_p99(ln, stage)
            if v is not None and v > 0:
                cohort_stages.setdefault(stage, []).append(v)
    if not cand_sb and cohort_stages:
        # an entirely untraced candidate (loadtest without --trace)
        # cannot be trended per stage — note the coverage gap loudly
        # instead of fabricating a per-stage regression for every
        # stage the traced cohort carries
        rep.notes.append(
            "candidate carries no stage_breakdown: per-stage trend "
            "not evaluated (cohort has "
            f"{sorted(cohort_stages)})")
        cohort_stages = {}
    for stage in sorted(set(cand_sb) | set(cohort_stages)):
        vals = cohort_stages.get(stage, [])
        cv = _stage_p99(cand, stage)
        if cv is None:
            # coverage regression: a stage EVERY cohort line carries
            # vanished from the candidate — the Dapper failure mode
            # (the uninstrumented stage is where the regression hides)
            sb_lines = sum(1 for ln in cohort
                           if isinstance(ln.get("stage_breakdown"), dict))
            if sb_lines >= min_cohort and len(vals) == sb_lines:
                rep.regressions.append({
                    "key": f"stage:{stage}",
                    "detail": f"stage {stage!r} present in all "
                              f"{sb_lines} cohort lines but missing "
                              f"from the candidate (coverage hole)"})
            continue
        if len(vals) >= min_cohort:
            _check_lower(f"stage:{stage}", cv, vals,
                         rep.regressions, rep.checked)

    if not rep.checked and not rep.regressions:
        rep.notes.append("no gateable quantities shared with the cohort")
    if rep.regressions:
        rep.rc = GATE_REGRESSION
    return rep


def gate_file(path: str, **kw) -> GateReport:
    """gate() over a ledger file; rc=2 on an unreadable file."""
    rep = GateReport()
    try:
        lines = read(path)
    except OSError as e:
        rep.rc = GATE_INTERNAL
        rep.notes.append(f"cannot read ledger {path}: {e}")
        return rep
    try:
        return gate(lines, **kw)
    except Exception as e:  # noqa: BLE001 — rc=2 is the contract
        rep.rc = GATE_INTERNAL
        rep.notes.append(f"gate internal error: {type(e).__name__}: {e}")
        return rep


def default_ledger_path() -> str:
    """$BNG_BENCH_LOG, or bench_runs.jsonl at the repo root (next to
    bench.py). The ONE resolution rule — bench._persist, `bench.py
    --gate` and `bng perf` all call this, so they can never gate a
    different file than the run appended to."""
    envp = os.environ.get("BNG_BENCH_LOG")
    if envp:
        return envp
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "bench_runs.jsonl")
