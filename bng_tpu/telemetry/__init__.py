"""Telemetry subsystem: span tracing, flight recorder, mergeable
stage-latency histograms (see spans.py / recorder.py / hist.py).

Import surface: `from bng_tpu.telemetry import spans` at instrumented
call sites (module-level hooks, fault_point-style disarmed cost);
Tracer/FlightRecorder/LatencyHist here for composition roots. The SLO
engine (slo.py) and the perf ledger/gate (ledger.py) are imported as
submodules by their consumers — ledger stays jax-free by design.
"""

from bng_tpu.telemetry.hist import LatencyHist, NBUCKETS
from bng_tpu.telemetry.recorder import (FlightRecorder, RecorderConfig,
                                        chrome_trace, default_trace_dir)
from bng_tpu.telemetry.slo import (DEFAULT_SLOS, HEADLINE_TARGETS,
                                   BudgetLine, SLOMonitor, SLOSpec,
                                   check_budget)
from bng_tpu.telemetry.spans import (NSTAGES, STAGE_NAMES, Tracer, arm,
                                     armed, disarm)

__all__ = [
    "LatencyHist", "NBUCKETS", "FlightRecorder", "RecorderConfig",
    "chrome_trace", "default_trace_dir", "NSTAGES", "STAGE_NAMES",
    "Tracer", "arm", "armed", "disarm", "SLOSpec", "SLOMonitor",
    "DEFAULT_SLOS", "HEADLINE_TARGETS", "BudgetLine", "check_budget",
]
