"""Telemetry subsystem: span tracing, flight recorder, mergeable
stage-latency histograms (see spans.py / recorder.py / hist.py).

Import surface: `from bng_tpu.telemetry import spans` at instrumented
call sites (module-level hooks, fault_point-style disarmed cost);
Tracer/FlightRecorder/LatencyHist here for composition roots.
"""

from bng_tpu.telemetry.hist import LatencyHist, NBUCKETS
from bng_tpu.telemetry.recorder import (FlightRecorder, RecorderConfig,
                                        chrome_trace, default_trace_dir)
from bng_tpu.telemetry.spans import (NSTAGES, STAGE_NAMES, Tracer, arm,
                                     armed, disarm)

__all__ = [
    "LatencyHist", "NBUCKETS", "FlightRecorder", "RecorderConfig",
    "chrome_trace", "default_trace_dir", "NSTAGES", "STAGE_NAMES",
    "Tracer", "arm", "armed", "disarm",
]
