"""Per-stage SLO engine — declarative latency budgets over the fixed
stage vocabulary, evaluated everywhere a stage histogram exists.

The paper's headline targets (>=100 Mpps NAT44+DHCP aggregate, p99
OFFER device time < 50us) were instrumented by PR 5 but enforced
nowhere: storm budgets lived as ad-hoc tuples inside chaos/storms.py,
`bng run` evaluated nothing live, and bench_runs.jsonl was a pile of
schema-less lines nobody read. This module is the ONE registry those
consumers now share:

- ``SLOSpec`` — a per-stage p99 budget (stage name validated against
  spans.STAGE_NAMES at construction: an SLO on a stage that does not
  exist is a configuration bug, not a silent no-op — Dapper's lesson
  that the unbudgeted stage is where the regression hides).
- ``DEFAULT_SLOS`` / ``HEADLINE_TARGETS`` — the shipped registry: one
  envelope per stage of the packet lifecycle plus the paper's headline
  numbers (telemetry/ledger.py's trend gate reports against the same
  constants).
- ``evaluate(breakdown)`` — one-shot p99 verdict over a
  Tracer.breakdown() dict (loadtest reports, bench artifacts).
- ``SLOMonitor`` — the live half for `bng run`: rolling burn-rate
  windows over the armed tracer's stage histograms (windowed p99 from
  bucket-count deltas — the mergeable-histogram property pointed at
  time instead of workers), breach -> ``slo_breach`` flight-recorder
  trigger + the bng_slo_* metric families (control/metrics.py).
- ``BudgetLine`` / ``check_budget`` — the storm-suite budget checker,
  re-homed here from chaos/storms.py so storm budgets and production
  SLOs are one vocabulary. Verdict semantics are byte-identical to the
  PR-8 originals (mean-based, `per` amortization, required stages with
  zero samples FAIL as coverage holes) — the verify-chaos
  bit-determinism gate depends on that.

Thread model: SLOMonitor.tick runs on the `bng run` loop (under the
app's _ctl, like every other 1 Hz sweep); snapshot() is called from the
metrics scrape thread — both serialize on the monitor's own lock so the
concurrency pass (BNG060/062) can prove the discipline.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry.hist import counts_percentile
from bng_tpu.telemetry.spans import STAGE_NAMES

# the paper's headline targets (BASELINE.md / PAPER.md): the trend gate
# (telemetry/ledger.py) annotates every gated run against these, and
# bench.py's vs_baseline columns are derived from the same constants.
HEADLINE_TARGETS = {
    # <50us p99 for the device-only OFFER program @1M subscribers
    "offer_device_only_p99_us": 50.0,
    # >=100 Mpps aggregate on a v5e-8 = 12.5 Mpps per chip
    "mpps_per_chip_floor": 12.5,
}


def _valid_stage(stage: str) -> None:
    if stage not in STAGE_NAMES:
        raise ValueError(
            f"unknown stage {stage!r}: SLOs bind to the fixed span "
            f"vocabulary {STAGE_NAMES}")


@dataclass(frozen=True)
class SLOSpec:
    """One per-stage p99 latency budget.

    ``per`` amortizes batch-scoped laps over the units of work one lap
    covers (frames per batch), mirroring BudgetLine. ``required=False``
    stages are skipped when they recorded nothing: in `bng run` most
    device-side stages only exist under bench instrumentation, and a
    live monitor must not page on absent traffic.
    """

    stage: str
    p99_limit_us: float
    per: float = 1.0
    required: bool = False
    description: str = ""

    def __post_init__(self):
        _valid_stage(self.stage)
        if self.p99_limit_us <= 0 or self.per <= 0:
            raise ValueError(
                f"SLOSpec({self.stage}): limit and per must be positive")


# The shipped per-stage registry. Envelopes sit one to two orders above
# the CPU-dev observed means (PERF_NOTES §10/§12) so a healthy run can
# never flap, while a genuine order-of-magnitude excursion pages within
# burn_windows windows. `device` carries the paper target itself: it is
# only ever fed profiler-fenced device time (spans.py), so the 50us
# budget gates exactly the quantity the target constrains.
DEFAULT_SLOS: tuple[SLOSpec, ...] = (
    SLOSpec("ring", 5_000.0, description="ring pop + staging, per batch"),
    SLOSpec("admit", 2_000.0, description="admission verdicts, per batch"),
    SLOSpec("lane_wait", 50_000.0,
            description="scheduler enqueue -> dispatch (oldest frame)"),
    SLOSpec("dispatch", 50_000.0, description="host-side jitted dispatch"),
    SLOSpec("loop_fill", 2_000.0,
            description="devloop: descriptor rows -> ring slot, per batch"),
    SLOSpec("loop_wait", 100_000.0,
            description="devloop: slot staged -> ring dispatch (bounded "
                        "by the ring deadline)"),
    SLOSpec("loop_retire", 50_000.0,
            description="devloop: ring force + per-slot demux, amortized "
                        "per batch"),
    SLOSpec("device", HEADLINE_TARGETS["offer_device_only_p99_us"],
            description="profiler-fenced device execution (paper target)"),
    SLOSpec("device_wait", 200_000.0,
            description="host blocked forcing device outputs"),
    SLOSpec("fleet", 100_000.0, description="slow-path scatter/gather"),
    SLOSpec("worker", 20_000.0, description="per-frame worker handler"),
    SLOSpec("slow_path", 200_000.0, description="slow-path drain total"),
    SLOSpec("reply", 20_000.0, description="verdict demux + reply encode"),
    SLOSpec("ops", 2_000_000.0,
            description="zero-downtime transition phases"),
    SLOSpec("wire_rx", 5_000.0,
            description="wire pump ingress: kernel fill+RX -> ring "
                        "submit, per pump round"),
    SLOSpec("wire_tx", 5_000.0,
            description="wire pump egress: ring verdicts -> kernel TX "
                        "+ completion reap, per pump round"),
    SLOSpec("total", 500_000.0, description="batch begin -> end"),
)


def parse_budgets(specs: list[str]) -> tuple[SLOSpec, ...]:
    """Parse `stage:limit_us[:per]` strings into SLOSpecs — the
    `bng run --slo-budgets` / config-file `slo_budgets:` override
    surface. Unknown stages raise loudly."""
    out = []
    for s in specs:
        parts = s.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(
                f"bad SLO budget {s!r}: want stage:limit_us[:per]")
        stage, limit = parts[0], float(parts[1])
        per = float(parts[2]) if len(parts) == 3 else 1.0
        out.append(SLOSpec(stage, limit, per=per))
    return tuple(out)


def evaluate(breakdown: dict, slos: tuple[SLOSpec, ...] = DEFAULT_SLOS) -> dict:
    """One-shot p99 verdict over a Tracer.breakdown() dict.

    Same report shape as check_budget (ok + sorted breach names, with
    `stage:missing` for required stages that recorded nothing) so
    loadtest JSON, bench artifacts and storm reports stay diffable with
    one vocabulary."""
    breaches = []
    for spec in slos:
        s = breakdown.get(spec.stage)
        if s is None:
            if spec.required:
                breaches.append(f"{spec.stage}:missing")
            continue
        if s["p99_us"] / spec.per > spec.p99_limit_us:
            breaches.append(spec.stage)
    return {"ok": not breaches, "breaches": sorted(breaches)}


# ---------------------------------------------------------------------------
# storm budgets (re-homed from chaos/storms.py — PR 8) — the mean-based
# envelope checker the deterministic storm reports embed. Kept verbatim:
# the verify-chaos gate compares report bytes across runs and across the
# re-home, so the verdict dict must not change by a byte.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BudgetLine:
    """One stage envelope: the stage's mean lap, divided by `per` (the
    units of work one lap covers — frames per batch for batch-scoped
    stages), must stay under `limit_us`. `required` stages must have
    samples at all: a storm whose instrumented stage recorded NOTHING
    is a coverage hole, not a pass."""

    stage: str
    limit_us: float
    per: float = 1.0
    required: bool = True

    def __post_init__(self):
        _valid_stage(self.stage)


def check_budget(tracer, lines: tuple[BudgetLine, ...]) -> dict:
    """Evaluate the envelope. Only deterministic facts reach the report:
    the verdict and WHICH stages breached — measured values go to the
    flight recorder / PERF_NOTES, never into the bit-compared bytes."""
    bd = tracer.breakdown() if tracer is not None else {}
    breaches = []
    for ln in lines:
        s = bd.get(ln.stage)
        if s is None:
            if ln.required:
                breaches.append(f"{ln.stage}:missing")
            continue
        if s["mean_us"] / ln.per > ln.limit_us:
            breaches.append(ln.stage)
    if breaches:
        tele.trigger("slo_breach",
                     f"storm budget breached: {sorted(breaches)}")
    return {"ok": not breaches, "breaches": sorted(breaches)}


# ---------------------------------------------------------------------------
# live burn-rate monitor (`bng run`)
# ---------------------------------------------------------------------------

# windowed percentiles evaluate hist.py's shared rank/cumsum/midpoint
# core directly on bucket-count DELTAS (counts_now - window_start) —
# one implementation, so the monitor's p99 can never drift from every
# other p99 in the system
_counts_percentile = counts_percentile


class SLOMonitor:
    """Rolling burn-rate evaluation of per-stage SLOs over the armed
    tracer's histograms.

    Every `window_s` seconds the monitor diffs each budgeted stage's
    bucket counts against the previous window boundary (mergeable
    histograms subtract as cleanly as they add) and computes the
    WINDOWED p99 — not the since-boot p99, which dilutes a fresh
    regression under hours of healthy history. A stage whose windowed
    p99 exceeds its budget for `burn_windows` consecutive windows is a
    breach: the `slo_breach` flight-recorder trigger fires (the last-N
    batch records around the breach are the evidence) and the breach
    counter increments (bng_slo_breaches_total). Windows with fewer
    than `min_samples` laps are skipped — no traffic is not a breach.
    """

    min_samples = 16

    def __init__(self, tracer, slos: tuple[SLOSpec, ...] = DEFAULT_SLOS,
                 window_s: float = 30.0, burn_windows: int = 2,
                 clock=time.monotonic):
        self.tracer = tracer
        self.slos = tuple(slos)
        self.window_s = float(window_s)
        self.burn_windows = max(1, int(burn_windows))
        self.clock = clock
        self._lock = threading.Lock()
        self._win_start: float | None = None
        self._snap: dict[int, np.ndarray] = {}
        self._burning: dict[str, int] = {s.stage: 0 for s in self.slos}
        self._window_p99: dict[str, float] = {}
        self.breaches: dict[str, int] = {s.stage: 0 for s in self.slos}
        self.windows_evaluated = 0

    def _stage_idx(self, stage: str) -> int:
        return STAGE_NAMES.index(stage)

    def tick(self, now: float | None = None) -> list[str]:
        """Evaluate the window if it elapsed; returns the stages that
        breached this tick (empty most of the time). Called from the
        run loop's 1 Hz heartbeat."""
        now = now if now is not None else self.clock()
        with self._lock:
            breached = self._tick_locked(now)
        if breached:
            tele.trigger("slo_breach",
                         f"burn-rate breach ({self.burn_windows} windows "
                         f"x {self.window_s:.0f}s): {sorted(breached)}")
        return breached

    def _tick_locked(self, now: float) -> list[str]:
        if self._win_start is None:
            self._win_start = now
            for spec in self.slos:
                i = self._stage_idx(spec.stage)
                self._snap[i] = self.tracer.hists[i].counts.copy()
            return []
        if now - self._win_start < self.window_s:
            return []
        self._win_start = now
        self.windows_evaluated += 1
        breached = []
        for spec in self.slos:
            i = self._stage_idx(spec.stage)
            counts = self.tracer.hists[i].counts
            prev = self._snap.get(i)
            delta = counts - prev if prev is not None else counts.copy()
            self._snap[i] = counts.copy()
            n = int(delta.sum())
            if n < self.min_samples:
                self._burning[spec.stage] = 0
                self._window_p99.pop(spec.stage, None)
                continue
            p99 = _counts_percentile(delta, 99.0)
            self._window_p99[spec.stage] = p99
            if p99 / spec.per > spec.p99_limit_us:
                self._burning[spec.stage] += 1
            else:
                self._burning[spec.stage] = 0
            if self._burning[spec.stage] >= self.burn_windows:
                self.breaches[spec.stage] += 1
                self._burning[spec.stage] = 0  # re-arm for the next burn
                breached.append(spec.stage)
        return breached

    def snapshot(self) -> dict:
        """Scrape-thread view (control/metrics.py collect_slo)."""
        with self._lock:
            return {
                "windows": self.windows_evaluated,
                "window_s": self.window_s,
                "burn_windows": self.burn_windows,
                "budgets_us": {s.stage: s.p99_limit_us for s in self.slos},
                "window_p99_us": dict(self._window_p99),
                "burning": dict(self._burning),
                "breaches": dict(self.breaches),
                "ok": not any(self._burning.values()),
            }
