"""Per-batch flight recorder: last-N ring + anomaly-triggered dumps.

The gray-failure cure (Huang et al., HotOS'17; PAPERS.md): when every
aggregate metric looks healthy but the system is quietly degraded — the
three bench rounds that published CPU-fallback numbers as TPU headlines —
the evidence that tells you *what the last milliseconds actually looked
like* must already have been recorded. So:

- a FIXED-SIZE, allocation-free ring of the last N batch records (stage
  timestamps + stage durations, lane, batch size, shed/punt counts;
  backend identity rides the ring metadata — it is per-process, not
  per-batch), written by Tracer.end on every finalized batch;
- ANOMALY TRIGGERS that dump the ring to a bounded JSON file the moment
  something crosses a line, not at the end of a run:
    latency_excursion    batch total over the configured budget
    shed_burst           admission shed count over the burst threshold
    worker_death         a fleet worker's IPC died (control/fleet.py)
    invariant_violation  the cross-authority auditor found one (chaos/)
    backend_fallback     the bench ran on CPU when a TPU was expected
                         (bench.py — the VERDICT "What's weak" §1 class)
- dump volume is bounded twice: a min interval between dumps and a hard
  per-process dump cap, so a flapping trigger can't fill a disk.

Telemetry never faults the dataplane: every filesystem error is
swallowed and counted.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from dataclasses import dataclass

import numpy as np

from bng_tpu.telemetry.spans import LANE_NAMES, NSTAGES, STAGE_NAMES

TRIG_LATENCY = "latency_excursion"
TRIG_SHED = "shed_burst"
TRIG_WORKER = "worker_death"
TRIG_INVARIANT = "invariant_violation"
TRIG_BACKEND = "backend_fallback"
# an SLO burn-rate window (telemetry/slo.py SLOMonitor) or a storm
# budget (slo.check_budget) crossed its per-stage latency budget
TRIG_SLO = "slo_breach"
# an express dispatch found no AOT-compiled program for its batch
# geometry and fell back to the jit full-program path (ISSUE 13): the
# gray-failure class where a fallback storm serves every OFFER through
# the slow architecture while the aggregate counters look healthy
TRIG_EXPRESS_AOT_MISS = "express_aot_miss"
# a requested NIC attach (bng run --wire-if) landed on the memory rung
# (ISSUE 15): the in-memory ring keeps serving, so every aggregate
# counter looks healthy while zero packets touch the wire — the silent
# fallback must dump the flight ring and flip bng_wire_rung, never
# masquerade as wire serving
TRIG_WIRE_FALLBACK = "wire_rung_fallback"
# the express lane fell back a rung (ISSUE 18 gray-failure hardening):
# a devloop megakernel miss / compile failure re-dispatching per-batch,
# or the per-batch AOT compile itself failing back to jit-full. Before
# this trigger the compile-failure path only warn()ed once at setup —
# a cluster could serve every OFFER through the slow architecture with
# healthy-looking aggregate counters and no flight-record evidence
TRIG_EXPRESS_FALLBACK = "express_fallback"
# the cluster fabric's failure detector changed a member's verdict
# (ISSUE 19): suspect (beats stopped — possible partition), gray (beats
# flowing but the serving-health word stalled — Huang HotOS'17), or
# down (quorum of observers accused it). Suspicion transitions are the
# earliest cluster-failure evidence; the ring around one shows whether
# the beats died, the datagrams were rejected (bad sig / replay / skew
# counters) or the member wedged while still answering
TRIG_MEMBER_SUSPECT = "member_suspect"
# every fabric member on one host went DOWN by accusation quorum
# (ISSUE 20): the box vanished with both of its HA halves' state, so
# the surviving host's standbys promote as a group instead of waiting
# out the per-member failover stagger. The ring around the trigger
# shows the detection→promotion timeline PERF_NOTES §22 decomposes
TRIG_HOST_LOSS = "host_loss"


def default_trace_dir() -> str:
    return (os.environ.get("BNG_TRACE_DIR")
            or os.path.join(tempfile.gettempdir(), "bng-flightrec"))


@dataclass
class RecorderConfig:
    capacity: int = 256  # last-N batch records kept
    latency_budget_us: float = 0.0  # batch-total excursion trigger; 0=off
    shed_burst: int = 64  # sheds in one batch (or one shed report)
    min_dump_interval_s: float = 1.0
    max_dumps: int = 16  # hard per-process cap
    out_dir: str = ""  # "" -> $BNG_TRACE_DIR or <tmp>/bng-flightrec


class FlightRecorder:
    def __init__(self, cfg: RecorderConfig | None = None,
                 clock=time.time):
        self.cfg = cfg or RecorderConfig()
        self.clock = clock
        n = self.cfg.capacity
        self._dur = np.zeros((n, NSTAGES), dtype=np.float64)
        self._stamp = np.zeros((n, NSTAGES), dtype=np.int64)
        self._meta = np.zeros((n, 5), dtype=np.int64)  # lane,n,shed,punt,seq
        self._t = np.zeros(n, dtype=np.float64)  # unix ts at finalize
        self._valid = np.zeros(n, dtype=bool)
        self._w = 0
        self.meta: dict = {"backend": "unknown"}
        self.triggers: dict[str, int] = {}
        self.dump_paths: list[str] = []
        self.dump_errors = 0
        self._last_dump_t = 0.0

    def set_backend(self, backend: str) -> None:
        self.meta["backend"] = backend

    # -- the ring (called by Tracer.end — must stay allocation-free) ------

    def push(self, lane: int, size: int, shed: int, punt: int, seq: int,
             dur_row: np.ndarray, stamp_row: np.ndarray) -> None:
        w = self._w
        self._dur[w] = dur_row  # row copy into preallocated storage
        self._stamp[w] = stamp_row
        self._meta[w, 0] = lane
        self._meta[w, 1] = size
        self._meta[w, 2] = shed
        self._meta[w, 3] = punt
        self._meta[w, 4] = seq
        self._t[w] = self.clock()
        self._valid[w] = True
        self._w = (w + 1) % self.cfg.capacity
        # anomaly checks on the record just written
        budget = self.cfg.latency_budget_us
        if budget > 0 and dur_row[NSTAGES - 1] > budget:  # TOTAL is last
            self.trigger(TRIG_LATENCY,
                         f"batch total {dur_row[NSTAGES - 1]:.1f}us > "
                         f"budget {budget:.1f}us")
        if shed >= self.cfg.shed_burst > 0:
            self.trigger(TRIG_SHED, f"{shed} sheds in one batch")

    def note_shed(self, n: int) -> None:
        """Shed report with no open batch record (fleet driven outside a
        traced batch): burst detection still applies."""
        if n >= self.cfg.shed_burst > 0:
            self.trigger(TRIG_SHED, f"{n} sheds in one report")

    # -- dumps ------------------------------------------------------------

    def trigger(self, reason: str, detail: str = "") -> str | None:
        """Record the trigger; dump unless rate-limited/capped. Returns
        the dump path (None when suppressed or the write failed)."""
        self.triggers[reason] = self.triggers.get(reason, 0) + 1
        now = self.clock()
        if len(self.dump_paths) >= self.cfg.max_dumps:
            return None
        if now - self._last_dump_t < self.cfg.min_dump_interval_s:
            return None
        self._last_dump_t = now
        return self.dump(reason, detail)

    def records(self) -> list[dict]:
        """Valid records, oldest first (the dump body)."""
        n = self.cfg.capacity
        order = [(self._w + i) % n for i in range(n)]
        out = []
        for i in order:
            if not self._valid[i]:
                continue
            stages = {STAGE_NAMES[s]: round(float(self._dur[i, s]), 2)
                      for s in range(NSTAGES) if self._dur[i, s] > 0.0}
            stamps = {STAGE_NAMES[s]: int(self._stamp[i, s])
                      for s in range(NSTAGES) if self._stamp[i, s] > 0}
            lane = int(self._meta[i, 0])
            out.append({
                "seq": int(self._meta[i, 4]),
                "t": round(float(self._t[i]), 6),
                "lane": (LANE_NAMES[lane] if lane < len(LANE_NAMES)
                         else str(lane)),
                "n": int(self._meta[i, 1]),
                "shed": int(self._meta[i, 2]),
                "punt": int(self._meta[i, 3]),
                "stages_us": stages,
                "stamps_ns": stamps,
            })
        return out

    def dump(self, reason: str, detail: str = "",
             path: str | None = None) -> str | None:
        """Write the ring to a bounded JSON file (capacity is fixed, so
        the file is ~O(100 KB) worst case). Never raises."""
        body = {
            "reason": reason,
            "detail": detail,
            "t": self.clock(),
            "meta": dict(self.meta),
            "triggers": dict(self.triggers),
            "records": self.records(),
        }
        try:
            if path is None:
                out_dir = self.cfg.out_dir or default_trace_dir()
                os.makedirs(out_dir, exist_ok=True)
                path = os.path.join(
                    out_dir,
                    f"flight-{int(self.clock() * 1000)}-{reason}.json")
            elif os.path.dirname(path):
                os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(body, f)
            os.replace(tmp, path)
        except OSError:
            self.dump_errors += 1
            return None
        self.dump_paths.append(path)
        return path

    def snapshot_meta(self) -> dict:
        return {
            "backend": self.meta.get("backend", "unknown"),
            "valid_records": int(self._valid.sum()),
            "capacity": self.cfg.capacity,
            "triggers": dict(self.triggers),
            "dumps": list(self.dump_paths),
            "dump_errors": self.dump_errors,
        }


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------

def chrome_trace(tracer, label: str = "bng-tpu") -> dict:
    """Convert a Tracer's span-event log (built with keep_events > 0)
    into Chrome Trace Event JSON — loads in chrome://tracing and
    Perfetto. One pid (this process), one tid per lane, "X" complete
    events with ts/dur in microseconds (the format's unit)."""
    if tracer.events is None:
        raise ValueError("tracer was built without keep_events — "
                         "no span events to export")
    events = list(tracer.events)
    t_origin = min((t0 for _s, _l, t0, _d in events), default=0)
    out = [{"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
            "args": {"name": label}}]
    lanes = sorted({lane for _s, lane, _t, _d in events})
    for lane in lanes:
        out.append({"ph": "M", "name": "thread_name", "pid": 0,
                    "tid": int(lane),
                    "args": {"name": f"lane:{LANE_NAMES[lane]}"
                             if lane < len(LANE_NAMES) else f"lane:{lane}"}})
    for stage, lane, t0, dur_ns in events:
        out.append({
            "name": STAGE_NAMES[stage],
            "cat": "bng",
            "ph": "X",
            "pid": 0,
            "tid": int(lane),
            "ts": (t0 - t_origin) / 1000.0,
            "dur": max(dur_ns, 1) / 1000.0,
        })
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {"tool": label,
                      "stages": list(STAGE_NAMES),
                      "records": tracer.seq},
    }
