"""Device-resident edge protection (ISSUE 17): intercept tap-match +
next-hop route rewrite on the fast path.

- `edge.ops` — the two device kernels (tap_match, route_rewrite) and
  their word layouts, probed via the `BNG_TABLE_IMPL`-dispatched
  `lookup()`.
- `edge.tables` — `EdgeTables`, the host single-writer authority whose
  bounded deltas ride the engine's existing update drain.
- `edge.compile` — warrant/routing compilers + the `MirrorPump` host
  retire sink that feeds `RecordCC`/HI3 export.
"""

from bng_tpu.edge.compile import (CLASS_CODES, InterceptTapProgram,
                                  MirrorPump, RouteProgram)
from bng_tpu.edge.ops import (EDGE_NSTATS, EST_MIRRORED, EST_ROUTE_MISSES,
                              EST_ROUTE_REWRITES, EST_TAP_FILTERED,
                              ROUTE_WORDS, TAP_WORDS, RouteResult, TapResult,
                              route_rewrite, tap_match)
from bng_tpu.edge.tables import MAX_TAP_FILTERS, EdgeTables

__all__ = [
    "CLASS_CODES", "EDGE_NSTATS", "EST_MIRRORED", "EST_ROUTE_MISSES",
    "EST_ROUTE_REWRITES", "EST_TAP_FILTERED", "EdgeTables",
    "InterceptTapProgram", "MAX_TAP_FILTERS", "MirrorPump", "ROUTE_WORDS",
    "RouteProgram", "RouteResult", "TAP_WORDS", "TapResult",
    "route_rewrite", "tap_match",
]
