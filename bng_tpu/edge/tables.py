"""Host authority for the edge-protection device tables (ISSUE 17).

`EdgeTables` is the single writer for the tap-match and next-hop route
tables, in the `runtime/tables.py` mold: numpy host mirrors of the
device cuckoo tables plus dense side arrays, draining bounded
`TableUpdate` batches through the engine's existing update tail. The
compile layer (`edge/compile.py`) translates `control/intercept.py`
warrants and `control/routing.py` manager state into row mutations
here; nothing else writes (bngcheck single-writer allowlist).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.edge.ops import (
    ROUTE_WORDS,
    RW_CLASS,
    RW_FLAG,
    RW_MAC_HI,
    RW_MAC_LO,
    RW_TABLE,
    TAP_CONFIG_WORDS,
    TAP_FILTER_COLS,
    TAP_WORDS,
    TC_ARMED,
    TF_PEER,
    TF_PORT,
    TF_PROTO,
    TF_WID,
    TW_FLAG,
    TW_WID,
)
from bng_tpu.ops.table import HostTable, TableGeom

MAX_TAP_FILTERS = 64


class EdgeTables:
    """Host side of the device tap-match + route tables.

    Both tables key on the subscriber IPv4 (one uint32 word). The tap
    table's dense companions — `tap_filters[F, 4]` rows and the
    `tap_config` armed predicate — ride every update batch wholesale
    (they are tiny), exactly like FastPathTables' pools/server arrays.
    """

    def __init__(self, nbuckets: int = 1 << 10, stash: int = 64,
                 update_slots: int = 64,
                 max_filters: int = MAX_TAP_FILTERS):
        self.tap = HostTable(nbuckets, key_words=1, val_words=TAP_WORDS,
                             stash=stash, name="edge_tap")
        self.route = HostTable(nbuckets, key_words=1, val_words=ROUTE_WORDS,
                               stash=stash, name="edge_route")
        self.tap_filters = np.zeros((max_filters, TAP_FILTER_COLS),
                                    dtype=np.uint32)
        self.tap_config = np.zeros((TAP_CONFIG_WORDS,), dtype=np.uint32)
        self.geom = TableGeom(nbuckets, stash)
        self.update_slots = update_slots
        self._armed = 0  # live tap rows (the TC_ARMED predicate source)

    # -- tap CRUD (writer: edge/compile.py InterceptTapProgram) ---------
    def arm_tap(self, subscriber_ip: int, wid: int,
                filters: list[tuple[int, int, int]] | tuple = ()) -> None:
        """Arm a tap row for `subscriber_ip` under warrant id `wid`.
        `filters` is a list of (port, proto, peer_ip) conjunct rows
        (0 = wildcard column); the lane mirrors if ANY row matches.
        Re-arming the same IP replaces the row (upsert)."""
        if wid <= 0:
            raise ValueError("warrant id must be positive (0 = free row)")
        prior = self.tap.lookup([subscriber_ip])
        row = np.zeros((TAP_WORDS,), dtype=np.uint32)
        row[TW_FLAG] = 1
        row[TW_WID] = wid
        self.tap.insert([subscriber_ip], row)
        if prior is None:
            self._armed += 1
        self.set_tap_filters(wid, filters)
        self.tap_config[TC_ARMED] = self._armed

    def disarm_tap(self, subscriber_ip: int) -> bool:
        """Remove the tap row for `subscriber_ip`. The wid's filter rows
        stay until the compiler clears them (another IP may share the
        warrant); orphaned filter rows are harmless — no row carries
        their wid."""
        ok = self.tap.delete([subscriber_ip])
        if ok:
            self._armed -= 1
            self.tap_config[TC_ARMED] = self._armed
        return ok

    def get_tap(self, subscriber_ip: int):
        return self.tap.lookup([subscriber_ip])

    def set_tap_filters(self, wid: int,
                        filters: list[tuple[int, int, int]] | tuple) -> int:
        """Replace warrant `wid`'s dense filter rows; returns rows
        written (silently truncates at the dense array capacity — the
        compiler logs the drop)."""
        fw = self.tap_filters[:, TF_WID]
        rows = self.tap_filters[(fw != 0) & (fw != np.uint32(wid))]
        self.tap_filters[:] = 0
        self.tap_filters[:len(rows)] = rows
        free = len(self.tap_filters) - len(rows)
        wrote = 0
        for port, proto, peer in tuple(filters)[:free]:
            r = self.tap_filters[len(rows) + wrote]
            r[TF_WID] = wid
            r[TF_PORT] = port
            r[TF_PROTO] = proto
            r[TF_PEER] = peer
            wrote += 1
        return wrote

    # -- route CRUD (writer: edge/compile.py RouteProgram) --------------
    def set_route(self, subscriber_ip: int, nh_mac: bytes, table_id: int,
                  klass: int = 0) -> None:
        """Install/replace the next-hop row for `subscriber_ip`:
        gateway MAC + ISP table id + the class code the ECMP selection
        was made under."""
        row = np.zeros((ROUTE_WORDS,), dtype=np.uint32)
        row[RW_FLAG] = 1
        row[RW_MAC_HI] = int.from_bytes(nh_mac[:2], "big")
        row[RW_MAC_LO] = int.from_bytes(nh_mac[2:6], "big")
        row[RW_TABLE] = table_id
        row[RW_CLASS] = klass
        self.route.insert([subscriber_ip], row)

    def clear_route(self, subscriber_ip: int) -> bool:
        return self.route.delete([subscriber_ip])

    def get_route(self, subscriber_ip: int):
        return self.route.lookup([subscriber_ip])

    # -- row iteration (audit surface) ----------------------------------
    def tap_rows(self) -> list[tuple[int, np.ndarray]]:
        """[(subscriber_ip, row)] for every live tap row."""
        return self._rows(self.tap)

    def route_rows(self) -> list[tuple[int, np.ndarray]]:
        return self._rows(self.route)

    @staticmethod
    def _rows(table: HostTable) -> list[tuple[int, np.ndarray]]:
        out = [(int(table.keys[s, 0]), table.vals[s].copy())
               for s in np.nonzero(table.used)[0]]
        out.sort(key=lambda kv: kv[0])
        return out

    # -- device sync ----------------------------------------------------
    def make_updates(self):
        """(tap delta, filters, config, route delta) — the edge tail of
        the engine's per-step update batch."""
        return (self.tap.make_update(self.update_slots),
                jnp.asarray(self.tap_filters),
                jnp.asarray(self.tap_config),
                self.route.make_update(self.update_slots))

    def empty_updates(self):
        """No-op deltas that do not consume dirty tracking (scheduler
        bulk lane); dense arrays are re-read — they apply wholesale."""
        return (self.tap.empty_update(self.update_slots),
                jnp.asarray(self.tap_filters),
                jnp.asarray(self.tap_config),
                self.route.empty_update(self.update_slots))

    def dirty_count(self) -> int:
        return self.tap.dirty_count() + self.route.dirty_count()

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    def checkpoint_state(self) -> tuple[dict, dict]:
        meta = {"geom": {"tap": self.tap.checkpoint_geom(),
                         "route": self.route.checkpoint_geom()},
                "max_filters": len(self.tap_filters)}
        arrays = {f"{t}.{k}": v
                  for t in ("tap", "route")
                  for k, v in getattr(self, t).checkpoint_arrays().items()}
        arrays["tap_filters"] = self.tap_filters
        arrays["tap_config"] = self.tap_config
        return meta, arrays

    def restore_state(self, meta: dict, arrays: dict) -> dict[str, int]:
        rows = {}
        for t in ("tap", "route"):
            rows[t] = getattr(self, t).restore_arrays(
                {k: arrays[f"{t}.{k}"] for k in ("keys", "vals", "used")},
                meta["geom"][t])
        if arrays["tap_filters"].shape != self.tap_filters.shape:
            raise ValueError(
                f"checkpoint tap_filters shape "
                f"{arrays['tap_filters'].shape} != {self.tap_filters.shape}")
        self.tap_filters[:] = arrays["tap_filters"]
        self.tap_config[:] = arrays["tap_config"]
        self._armed = rows["tap"]
        self.tap_config[TC_ARMED] = self._armed
        return rows
