"""Control-plane -> device-row compilers for the edge subsystem.

Three pieces, all single-purpose and host-side:

- `InterceptTapProgram` — compiles `control/intercept.py` warrants into
  `EdgeTables` tap rows + dense filter rows. A device row exists iff
  its warrant is ACTIVE and inside its validity window; `sync()` is the
  reconcile sweep that arms newly-active warrants (by `target_ipv4`)
  and reaps rows whose warrant expired/was revoked — the audit clause
  (`_audit_edge`) proves exactly this correspondence.
- `RouteProgram` — compiles `control/routing.py` manager state (ISP
  tables + per-class ECMP across non-DOWN upstreams) into next-hop
  rows. Link flaps arrive via the manager's `on_upstream_down/up`
  hooks and recompile ONLY the rows whose selection changed — bounded
  dirty-slot deltas through the existing drain, never a resync.
- `MirrorPump` — the host retire half of interception: the engine's
  `mirror_sink` hands it (lane, frame, wid) for every MIRROR-flagged
  lane; it resolves the warrant, parses the flow 5-tuple from the
  frame bytes and feeds `InterceptManager.record_cc` (which applies
  the authoritative filters and delivers HI3 via the configured
  exporter, e.g. `ETSIExporter`).
"""

from __future__ import annotations

import time

from bng_tpu.control.intercept import (Direction, InterceptManager, Warrant,
                                       WarrantStatus)
from bng_tpu.edge.tables import EdgeTables
from bng_tpu.utils.net import fnv1a32, ip_to_u32, u32_to_ip

# subscriber-class wire codes (RW_CLASS word); parity with the BGP
# community split in control/routing.py SubscriberRouteManager
CLASS_CODES = {"residential": 1, "business": 2, "wholesale": 3}


def _active_in_window(w: Warrant, now: float) -> bool:
    return (w.status == WarrantStatus.ACTIVE
            and w.valid_from <= now < w.valid_until)


class InterceptTapProgram:
    """Single writer for the tap table: warrant -> device rows."""

    def __init__(self, edge: EdgeTables, manager: InterceptManager,
                 clock=time.time):
        self.edge = edge
        self.manager = manager
        self._clock = clock
        self._wid_by_warrant: dict[str, int] = {}
        self._warrant_by_wid: dict[int, str] = {}
        self._ips_by_wid: dict[int, set[int]] = {}
        self._next_wid = 1
        self.stats = {"armed": 0, "disarmed": 0, "reaped": 0, "syncs": 0,
                      "filters_dropped": 0}

    # -- identity -------------------------------------------------------
    def wid_for(self, warrant_id: str) -> int:
        """Stable device wid for a warrant (assigned on first use)."""
        wid = self._wid_by_warrant.get(warrant_id)
        if wid is None:
            wid = self._next_wid
            self._next_wid += 1
            self._wid_by_warrant[warrant_id] = wid
            self._warrant_by_wid[wid] = warrant_id
        return wid

    def warrant_for(self, wid: int) -> str | None:
        return self._warrant_by_wid.get(wid)

    def armed_ips(self, wid: int) -> set[int]:
        return set(self._ips_by_wid.get(wid, ()))

    # -- filter compilation --------------------------------------------
    @staticmethod
    def compile_filters(w: Warrant) -> list[tuple[int, int, int]]:
        """Warrant filter lists -> dense conjunct rows (port, proto,
        peer). List semantics are AND across non-empty dimensions, OR
        within one — compiled as the cartesian product with 0 standing
        for a wildcard dimension. The device match is a pre-filter (its
        single port column matches src OR dst); `record_cc` re-applies
        the exact host filters on every mirrored frame."""
        ports = sorted(set(w.filter_source_ports) | set(w.filter_dest_ports))
        protos = sorted(set(w.filter_protocols))
        peers = sorted({ip_to_u32(ip) for ip in w.filter_dest_ips
                        if ip and ":" not in ip})
        if not (ports or protos or peers):
            return []
        rows = []
        for port in ports or (0,):
            for proto in protos or (0,):
                for peer in peers or (0,):
                    rows.append((port, proto, peer))
        return rows

    # -- arming ---------------------------------------------------------
    def arm_session(self, warrant: Warrant, ipv4: str | int) -> int:
        """Arm a tap on a live session's IPv4 under `warrant`; returns
        the device wid. Explicit-arm path for session-matched warrants
        (e.g. `match_session` hits mid-storm); `sync()` covers
        IP-targeted warrants."""
        ip = ipv4 if isinstance(ipv4, int) else ip_to_u32(ipv4)
        wid = self.wid_for(warrant.id)
        rows = self.compile_filters(warrant)
        self.edge.arm_tap(ip, wid, rows)
        if rows and self.edge.set_tap_filters(wid, rows) < len(rows):
            self.stats["filters_dropped"] += 1
        self._ips_by_wid.setdefault(wid, set()).add(ip)
        self.stats["armed"] += 1
        return wid

    def disarm_session(self, warrant_id: str, ipv4: str | int) -> bool:
        ip = ipv4 if isinstance(ipv4, int) else ip_to_u32(ipv4)
        wid = self._wid_by_warrant.get(warrant_id)
        if wid is None:
            return False
        ok = self.edge.disarm_tap(ip)
        if ok:
            self.stats["disarmed"] += 1
            ips = self._ips_by_wid.get(wid, set())
            ips.discard(ip)
            if not ips:
                self.edge.set_tap_filters(wid, ())
        return ok

    # -- reconcile sweep ------------------------------------------------
    def sync(self) -> dict:
        """Make the device table agree with the warrant store: arm
        ACTIVE in-window warrants that target an IPv4; reap every row
        whose warrant is expired/revoked/suspended or gone. Bounded by
        the warrant store size, idempotent."""
        now = self._clock()
        active: dict[str, Warrant] = {
            w.id: w for w in self.manager.list_warrants()
            if _active_in_window(w, now)}
        armed_now = 0
        for w in active.values():
            if w.target_ipv4:
                ip = ip_to_u32(w.target_ipv4)
                wid = self.wid_for(w.id)
                # check the device row too, not just our bookkeeping: a
                # row lost behind our back (restore into a smaller
                # geometry, manual delete) must re-arm here
                if (ip not in self._ips_by_wid.get(wid, set())
                        or self.edge.get_tap(ip) is None):
                    self.arm_session(w, ip)
                    armed_now += 1
        reaped = 0
        for wid, ips in list(self._ips_by_wid.items()):
            wid_warrant = self._warrant_by_wid[wid]
            if wid_warrant in active:
                continue
            for ip in list(ips):
                if self.edge.disarm_tap(ip):
                    reaped += 1
                ips.discard(ip)
            self.edge.set_tap_filters(wid, ())
        self.stats["reaped"] += reaped
        self.stats["syncs"] += 1
        return {"armed": armed_now, "reaped": reaped,
                "rows": len(self.edge.tap_rows())}


class RouteProgram:
    """Single writer for the next-hop table: routing manager -> rows.

    Next-hop selection is deterministic weighted ECMP: hash the
    subscriber IP (FNV-1a32 over the 4 wire-order bytes — the same
    family as the cluster's MAC steering) modulo the total weight of
    eligible upstreams, walked in name order. Eligible = not DOWN, has
    a resolved neighbor MAC, and allowed for the subscriber's class
    (`class_tables`, empty = any). A flap changes eligibility, so
    `recompile()` after `on_upstream_down/up` rewrites exactly the
    rows whose selection moved — the bounded delta the drain ships.
    """

    def __init__(self, edge: EdgeTables, manager,
                 class_tables: dict[str, tuple[int, ...]] | None = None):
        self.edge = edge
        self.manager = manager
        self.class_tables = dict(class_tables or {})
        self._neighbors: dict[str, bytes] = {}   # gateway ip -> MAC
        self._bindings: dict[int, str] = {}      # sub ip u32 -> class
        self.stats = {"bound": 0, "recompiles": 0, "deltas": 0,
                      "flaps": 0, "unroutable": 0}

    def attach(self) -> None:
        """Install the flap hooks on the manager (health checks then
        drive bounded recompiles with no further wiring)."""
        self.manager.on_upstream_down = self.on_upstream_down
        self.manager.on_upstream_up = self.on_upstream_up

    def set_neighbor(self, gateway_ip: str, mac: bytes) -> None:
        """ARP/ND stand-in: resolved L2 next-hop for a gateway."""
        self._neighbors[gateway_ip] = bytes(mac[:6])
        self.recompile()

    # -- selection ------------------------------------------------------
    def _eligible(self, klass: str):
        from bng_tpu.control.routing import LinkState

        allowed = self.class_tables.get(klass)
        out = []
        for up in sorted(self.manager.list_upstreams(),
                         key=lambda u: u.name):
            if up.state == LinkState.DOWN:
                continue
            if up.gateway not in self._neighbors:
                continue
            if allowed is not None and up.table not in allowed:
                continue
            out.append(up)
        return out

    def select(self, sub_ip: int, klass: str):
        """(upstream, mac) for a subscriber, or None if nothing routes."""
        ups = self._eligible(klass)
        total = sum(max(1, u.weight) for u in ups)
        if total == 0:
            return None
        h = fnv1a32(int(sub_ip).to_bytes(4, "big")) % total
        acc = 0
        for up in ups:
            acc += max(1, up.weight)
            if h < acc:
                return up, self._neighbors[up.gateway]
        return None  # unreachable

    def expected_row(self, sub_ip: int):
        """(mac_hi, mac_lo, table, class_code) the device row must hold
        for a bound subscriber — the audit's recompute oracle."""
        klass = self._bindings.get(sub_ip)
        if klass is None:
            return None
        sel = self.select(sub_ip, klass)
        if sel is None:
            return None
        up, mac = sel
        return (int.from_bytes(mac[:2], "big"),
                int.from_bytes(mac[2:6], "big"),
                up.table, CLASS_CODES.get(klass, 0))

    # -- binding + recompile -------------------------------------------
    def bind_subscriber(self, ip: str | int,
                        klass: str = "residential") -> bool:
        """Steer a subscriber's upstream traffic through its class's
        ECMP selection; installs the row immediately. Returns False if
        nothing is eligible (row left absent, counted unroutable)."""
        sub = ip if isinstance(ip, int) else ip_to_u32(ip)
        self._bindings[sub] = klass
        self.stats["bound"] += 1
        return self._install(sub) is not None

    def unbind_subscriber(self, ip: str | int) -> bool:
        sub = ip if isinstance(ip, int) else ip_to_u32(ip)
        self._bindings.pop(sub, None)
        return self.edge.clear_route(sub)

    def _install(self, sub: int):
        want = self.expected_row(sub)
        if want is None:
            self.stats["unroutable"] += 1
            self.edge.clear_route(sub)
            return None
        from bng_tpu.edge.ops import RW_CLASS, RW_MAC_HI, RW_MAC_LO, RW_TABLE

        have = self.edge.get_route(sub)
        if have is not None and (int(have[RW_MAC_HI]), int(have[RW_MAC_LO]),
                                 int(have[RW_TABLE]),
                                 int(have[RW_CLASS])) == want:
            return want  # selection unchanged: no dirty slot
        mac = (want[0].to_bytes(2, "big") + want[1].to_bytes(4, "big"))
        self.edge.set_route(sub, mac, want[2], want[3])
        self.stats["deltas"] += 1
        return want

    def recompile(self, ips=None) -> dict:
        """Re-run selection for bound subscribers; write only changed
        rows. Returns {"checked", "rewritten"} — `rewritten` is the
        bounded delta size a flap actually ships to the device."""
        before = self.stats["deltas"]
        targets = list(self._bindings) if ips is None else list(ips)
        for sub in targets:
            if sub in self._bindings:
                self._install(sub)
        self.stats["recompiles"] += 1
        return {"checked": len(targets),
                "rewritten": self.stats["deltas"] - before}

    # -- flap hooks (manager.check_health callbacks) -------------------
    def on_upstream_down(self, name: str) -> dict:
        self.stats["flaps"] += 1
        return self.recompile()

    def on_upstream_up(self, name: str) -> dict:
        self.stats["flaps"] += 1
        return self.recompile()


class MirrorPump:
    """Host retire half of interception: MIRROR-flagged frames ->
    `record_cc`/HI3. Plugs into the engine as `mirror_sink`."""

    def __init__(self, program: InterceptTapProgram,
                 manager: InterceptManager | None = None):
        self.program = program
        self.manager = manager or program.manager
        self.stats = {"mirrored": 0, "cc_records": 0, "filtered": 0,
                      "dropped": 0}

    def __call__(self, lane: int, frame: bytes, wid: int) -> None:
        self.stats["mirrored"] += 1
        warrant_id = self.program.warrant_for(wid)
        if warrant_id is None:
            self.stats["dropped"] += 1
            return
        try:
            warrant = self.manager.get_warrant(warrant_id)
        except KeyError:
            self.stats["dropped"] += 1
            return
        flow = self._parse(frame)
        if flow is None:
            self.stats["dropped"] += 1
            return
        src, dst, sport, dport, proto = flow
        sid = f"tap-{wid}"
        session = self.manager.get_session(sid)
        if session is None:
            session = self.manager.start_intercept_session(
                warrant, sid, subscriber_id=warrant.target_subscriber_id,
                ipv4=warrant.target_ipv4)
        direction = (Direction.UPSTREAM
                     if ip_to_u32(src) in self.program.armed_ips(wid)
                     else Direction.DOWNSTREAM)
        if self.manager.record_cc(warrant, session, direction, src, dst,
                                  sport, dport, proto, frame):
            self.stats["cc_records"] += 1
        else:
            self.stats["filtered"] += 1

    @staticmethod
    def _parse(frame: bytes):
        """(src, dst, sport, dport, proto) from an IPv4 frame, or None.
        Mirrors ops/parse.py's VLAN walk (one 802.1Q or QinQ pair)."""
        if len(frame) < 34:
            return None
        off = 12
        et = int.from_bytes(frame[off:off + 2], "big")
        while et in (0x8100, 0x88A8) and len(frame) >= off + 6:
            off += 4
            et = int.from_bytes(frame[off:off + 2], "big")
        if et != 0x0800:
            return None
        l3 = off + 2
        if len(frame) < l3 + 20:
            return None
        ihl = (frame[l3] & 0x0F) * 4
        proto = frame[l3 + 9]
        src = u32_to_ip(int.from_bytes(frame[l3 + 12:l3 + 16], "big"))
        dst = u32_to_ip(int.from_bytes(frame[l3 + 16:l3 + 20], "big"))
        sport = dport = 0
        l4 = l3 + ihl
        if proto in (6, 17) and len(frame) >= l4 + 4:
            sport = int.from_bytes(frame[l4:l4 + 2], "big")
            dport = int.from_bytes(frame[l4 + 2:l4 + 4], "big")
        return src, dst, sport, dport, proto
