"""Device kernels for edge protection: intercept tap-match + next-hop
route rewrite (ISSUE 17).

Both kernels follow the `ops/antispoof.py` mold: a bucketized-cuckoo
probe through the `BNG_TABLE_IMPL`-dispatched `lookup()`, dense side
arrays for per-row config, and a packed uint32 stats vector the engine
folds host-side.

Tap-match
---------
Warrants from `control/intercept.py` compile (via `edge/compile.py`)
into device rows keyed by the *subscriber* IPv4 (src for upstream
lanes, post-DNAT dst for downstream lanes). A row carries the warrant
id (`wid`); optional port/proto/peer filters live in a dense
`tap_filters[F, 4]` array keyed back to the wid. A matching lane gets
`wid` in the per-lane MIRROR word of the pipeline result (0 = not
mirrored) — deliberately a side array, NOT a bit OR'd into the verdict
word, so verdict histograms and `== VERDICT_*` comparisons stay exact.
The host retire path extracts flagged frames and feeds
`RecordCC`/HI3 export.

The zero-warrant configuration must add no device work beyond one
predicate: the whole armed body sits under a `jax.lax.cond` on
`tap_config[TC_ARMED]`, so a disarmed table costs a single scalar
branch, not a probe.

Route rewrite
-------------
`control/routing.py`'s manager state (ISP table + ECMP next-hop
selection by subscriber class) compiles into device rows keyed by the
subscriber IPv4. Upstream lanes that hit get their L2 destination MAC
rewritten in place to the selected next-hop gateway (the same masked
scatter mold as `pppoe_encap`'s MAC stamp) and the rewrite lands in
the downstream verdict as a FWD. Route flap churn arrives as bounded
dirty-slot deltas through the existing drain — never a resync.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

import bng_tpu.ops.bytes as B_
from bng_tpu.ops.table import TableGeom, TableState, lookup

# --- tap row value words --------------------------------------------------
# TW_FLAG: 1 = armed row (0-valued rows are dead slots)
# TW_WID:  warrant id the row mirrors for (host maps wid -> warrant)
(TW_FLAG, TW_WID) = range(2)
TAP_WORDS = 8

# dense filter rows [F, 4]; a row belongs to a wid, lane passes if ANY
# of its wid's rows match (0 in a column = wildcard; wid 0 = free row)
(TF_WID, TF_PORT, TF_PROTO, TF_PEER) = range(4)
TAP_FILTER_COLS = 4

# dense tap config words; TC_ARMED = count of armed rows (the single
# disarmed-path predicate)
TC_ARMED = 0
TAP_CONFIG_WORDS = 2

# --- route row value words ------------------------------------------------
# RW_FLAG:   1 = live next-hop row
# RW_MAC_*:  next-hop gateway MAC (hi16 / lo32, same split as pppoe rows)
# RW_TABLE:  ISP routing table id (telemetry/audit only on device)
# RW_CLASS:  subscriber class code the selection was made under
(RW_FLAG, RW_MAC_HI, RW_MAC_LO, RW_TABLE, RW_CLASS) = range(5)
ROUTE_WORDS = 8

# --- packed stats ---------------------------------------------------------
(EST_MIRRORED, EST_TAP_FILTERED, EST_ROUTE_REWRITES,
 EST_ROUTE_MISSES) = range(4)
EDGE_NSTATS = 4


class TapResult(NamedTuple):
    mirror: jax.Array   # [B] uint32: warrant id where mirrored, 0 = no
    stats: jax.Array    # [2] uint32: (mirrored, filtered-out)


class RouteResult(NamedTuple):
    out_pkt: jax.Array  # [B, S] uint8, dst MAC rewritten on hit lanes
    hit: jax.Array      # [B] bool: next-hop rewrite applied
    stats: jax.Array    # [2] uint32: (rewrites, eligible misses)


def tap_match(sub_ip: jax.Array, src_port: jax.Array, dst_port: jax.Array,
              proto: jax.Array, peer_ip: jax.Array, eligible: jax.Array,
              taps: TableState, filters: jax.Array, config: jax.Array,
              geom: TableGeom) -> TapResult:
    """Per-lane intercept tap match. `sub_ip`/`peer_ip` are uint32
    host-order IPv4 (subscriber side / far side of the flow); `eligible`
    gates to parsed IPv4 data lanes. Disarmed (zero armed rows) costs
    one predicate — the probe and filter scan never execute."""
    bsz = sub_ip.shape[0]

    def _armed(_):
        res = lookup(taps, sub_ip[:, None].astype(jnp.uint32), geom)
        hit = res.found & (res.vals[:, TW_FLAG] != 0) & eligible
        wid = res.vals[:, TW_WID]
        fw = filters[:, TF_WID]
        # [B, F]: filter row belongs to this lane's warrant
        mine = (fw[None, :] != 0) & (fw[None, :] == wid[:, None])
        port = filters[:, TF_PORT]
        port_ok = ((port[None, :] == 0)
                   | (src_port.astype(jnp.uint32)[:, None] == port[None, :])
                   | (dst_port.astype(jnp.uint32)[:, None] == port[None, :]))
        prt = filters[:, TF_PROTO]
        proto_ok = ((prt[None, :] == 0)
                    | (proto.astype(jnp.uint32)[:, None] == prt[None, :]))
        per = filters[:, TF_PEER]
        peer_ok = ((per[None, :] == 0)
                   | (peer_ip.astype(jnp.uint32)[:, None] == per[None, :]))
        has_filter = mine.any(axis=1)
        passes = (mine & port_ok & proto_ok & peer_ok).any(axis=1)
        matched = hit & (~has_filter | passes)
        mirror = jnp.where(matched, wid, 0).astype(jnp.uint32)
        stats = jnp.stack([
            matched.sum().astype(jnp.uint32),
            (hit & ~matched).sum().astype(jnp.uint32),
        ])
        return mirror, stats

    def _disarmed(_):
        return (jnp.zeros((bsz,), jnp.uint32), jnp.zeros((2,), jnp.uint32))

    mirror, stats = jax.lax.cond(config[TC_ARMED] > 0, _armed, _disarmed, 0)
    return TapResult(mirror=mirror, stats=stats)


def route_rewrite(pkt: jax.Array, sub_ip: jax.Array, eligible: jax.Array,
                  routes: TableState, geom: TableGeom) -> RouteResult:
    """Per-lane next-hop rewrite for upstream (subscriber -> ISP)
    traffic: probe by subscriber IPv4, stamp the selected gateway MAC
    into the L2 destination (offset 0) on hit lanes. Same masked
    scatter mold as pppoe_encap's MAC stamp — one fused VPU pass, no
    gather/scatter of whole frames."""
    res = lookup(routes, sub_ip[:, None].astype(jnp.uint32), geom)
    hit = res.found & (res.vals[:, RW_FLAG] != 0) & eligible
    z = jnp.zeros(sub_ip.shape, dtype=jnp.int32)
    out = B_.scatter_be16_at_masked(pkt, z, res.vals[:, RW_MAC_HI], hit)
    out = B_.scatter_be32_at_masked(out, z + 2, res.vals[:, RW_MAC_LO], hit)
    stats = jnp.stack([
        hit.sum().astype(jnp.uint32),
        (eligible & ~hit).sum().astype(jnp.uint32),
    ])
    return RouteResult(out_pkt=out, hit=hit, stats=stats)
