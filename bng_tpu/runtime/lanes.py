"""Latency-class lanes for the tiered dataplane scheduler.

A lane is a host-side staging queue with a batch-close policy and a
bounded completion ring — the building blocks runtime/scheduler.py
composes into the express (DHCP) / bulk (fused pipeline) split. The
shape is Orca-style iteration-level scheduling re-hosted: instead of one
monolithic fused step where an OFFER waits behind a 512-frame NAT+QoS
batch, each latency class closes and dispatches batches on its own
terms:

- CLOSE_FULL: the batch reached the lane's device batch size.
- CLOSE_DEADLINE: the oldest queued frame aged past max_wait_us — a
  partial batch ships rather than letting the tail latency grow while
  the queue fills (continuous-batching deadline close).

The completion ring bounds device-side pipelining: dispatches enter as
futures; push() hands back the overflow entry the caller must retire
(block on) — `block_until_ready` happens only there, never per step.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, NamedTuple

LANE_EXPRESS = "express"
LANE_BULK = "bulk"

CLOSE_FULL = "full"
CLOSE_DEADLINE = "deadline"
CLOSE_FLUSH = "flush"


@dataclass
class LaneConfig:
    name: str
    batch: int  # lanes per device dispatch (compile shape)
    max_wait_us: float  # oldest-frame age that forces a partial close
    depth: int  # max in-flight dispatches (completion ring size)
    max_queue: int = 1 << 16  # backpressure bound; beyond it push() drops


class PendingFrame(NamedTuple):
    frame: bytes
    from_access: bool
    enq_t: float  # lane clock at submit (dispatch-latency origin)
    tag: object  # caller correlation token (e.g. submission index)
    # express descriptor (ops/express.ExpressDesc), extracted ONCE at
    # admission — the AOT express dispatch consumes these columns
    # directly instead of re-peeking the frame bytes at batch close.
    # None on the bulk lane and on the jit-full express path.
    desc: object = None


@dataclass
class LaneStats:
    enqueued: int = 0
    dropped_overflow: int = 0
    frames_dispatched: int = 0
    batches: int = 0
    batches_full: int = 0
    batches_deadline: int = 0
    batches_flush: int = 0
    occupancy_sum: float = 0.0  # sum of n/batch over dispatches

    def occupancy_avg(self) -> float:
        return self.occupancy_sum / self.batches if self.batches else 0.0


class Lane:
    """One latency class: staging queue + close policy + counters."""

    def __init__(self, cfg: LaneConfig, clock: Callable[[], float] = time.time):
        self.cfg = cfg
        self.clock = clock
        self.q: deque[PendingFrame] = deque()
        self.stats = LaneStats()

    def __len__(self) -> int:
        return len(self.q)

    def push(self, frame: bytes, from_access: bool, now: float | None = None,
             tag: object = None, desc: object = None) -> bool:
        """Queue a frame; False = lane over max_queue (frame dropped —
        the caller counts it as backpressure, like an RX ring overflow)."""
        if len(self.q) >= self.cfg.max_queue:
            self.stats.dropped_overflow += 1
            return False
        now = now if now is not None else self.clock()
        self.q.append(PendingFrame(frame, from_access, now, tag, desc))
        self.stats.enqueued += 1
        return True

    def oldest_age_us(self, now: float) -> float:
        return (now - self.q[0].enq_t) * 1e6 if self.q else 0.0

    def close_reason(self, now: float) -> str | None:
        """Why a batch should close right now (None = keep filling)."""
        if len(self.q) >= self.cfg.batch:
            return CLOSE_FULL
        if self.q and self.oldest_age_us(now) >= self.cfg.max_wait_us:
            return CLOSE_DEADLINE
        return None

    def close_batch(self, now: float,
                    reason: str | None = None) -> tuple[list[PendingFrame], str]:
        """Pop up to `batch` frames and account the close. With no
        explicit reason the close policy decides; callers flushing pass
        CLOSE_FLUSH to ship a partial batch regardless of deadline."""
        reason = reason or self.close_reason(now)
        if reason is None or not self.q:
            return [], reason or CLOSE_FLUSH
        n = min(len(self.q), self.cfg.batch)
        out = [self.q.popleft() for _ in range(n)]
        st = self.stats
        st.batches += 1
        st.frames_dispatched += n
        st.occupancy_sum += n / self.cfg.batch
        if reason == CLOSE_FULL:
            st.batches_full += 1
        elif reason == CLOSE_DEADLINE:
            st.batches_deadline += 1
        else:
            st.batches_flush += 1
        return out, reason


@dataclass
class InflightEntry:
    """One dispatched-but-unretired device batch."""

    res: object  # device result (futures)
    pending: list[PendingFrame]
    dispatch_t: float
    close_reason: str
    trace: object = None  # telemetry batch-record token (None = disarmed)
    # dispatch-epoch snapshot the retire path must read instead of the
    # live host mirrors (the AOT express retire renders replies from
    # pool/server config that must match the table epoch the device
    # verdict was computed against — a config rewrite between dispatch
    # and retire would otherwise produce a mixed-epoch reply)
    meta: object = None


class CompletionRing:
    """Bounded in-flight window (depth-N async pipelining).

    push() returns the entry that OVERFLOWED the ring — the single point
    where the scheduler is allowed to block on device results. pop_ready
    lets callers retire early finishers opportunistically without
    blocking (jax.Array.is_ready probes)."""

    def __init__(self, depth: int):
        self.depth = max(1, depth)
        self._ring: deque[InflightEntry] = deque()

    def __len__(self) -> int:
        return len(self._ring)

    def push(self, entry: InflightEntry) -> InflightEntry | None:
        self._ring.append(entry)
        if len(self._ring) > self.depth:
            return self._ring.popleft()
        return None

    def pop_oldest(self) -> InflightEntry | None:
        return self._ring.popleft() if self._ring else None

    def pop_ready(self, is_ready: Callable[[InflightEntry], bool]
                  ) -> list[InflightEntry]:
        """Retire the FIFO prefix whose device results are already done
        (retire order stays dispatch order — lane-level FIFO semantics)."""
        out = []
        while self._ring and is_ready(self._ring[0]):
            out.append(self._ring.popleft())
        return out

    def drain(self) -> list[InflightEntry]:
        out = list(self._ring)
        self._ring.clear()
        return out
