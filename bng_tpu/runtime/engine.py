"""Host runtime engine: the packet ring <-> device pipeline glue.

This is the role pkg/ebpf plays in the reference (SURVEY.md §1 L1), turned
inside out for TPU: instead of loading programs into the kernel and writing
maps via syscalls, the engine

1. assembles frames into fixed [B, L] uint8 batches (the AF_XDP RX ring
   consumer; a C++ ring feeds this in production, synthetic sources in
   tests/bench),
2. drains bounded table-update batches from the host managers (the
   bpf_map_update_elem replacement),
3. invokes ONE donated jitted step: updates -> fused pipeline -> verdicts,
4. applies verdicts: TX/FWD frames out, DROP counted, PASS lanes handed to
   the slow-path handlers (DHCP server, NAT new-flow manager) exactly like
   XDP_PASS delivers to the Go servers,
5. accumulates device stats into host counters (u64 in Python ints,
   mirroring pkg/metrics' 5s scrapes of the stats maps).

Single-chip engine; the sharded multi-chip variant lives in
bng_tpu.parallel.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Callable, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.analysis.sanitize import owned_by
from bng_tpu.chaos.faults import FaultInjectedError, fault_point
from bng_tpu.telemetry import spans as tele
from bng_tpu.control.nat import NATManager, apply_nat_updates
from bng_tpu.ops.antispoof import ANTISPOOF_NSTATS, AntispoofGeom
from bng_tpu.ops.dhcp import NSTATS as DHCP_NSTATS
from bng_tpu.ops.nat44 import NAT_NSTATS
from bng_tpu.ops.pppoe import PPPOE_NSTATS
from bng_tpu.ops.pipeline import (
    PipelineGeom,
    PipelineResult,
    PipelineTables,
    VERDICT_DROP,
    VERDICT_FWD,
    VERDICT_PASS,
    VERDICT_TX,
    pipeline_step,
)
from bng_tpu.ops.qos import QOS_NSTATS
from bng_tpu.ops.antispoof import ANTISPOOF_WORDS
from bng_tpu.ops.qtable import HostQTable, QTableGeom, apply_qupdate
from bng_tpu.ops import table as table_mod
from bng_tpu.ops.table import HostTable, TableGeom, apply_update
from bng_tpu.runtime import hostpath
from bng_tpu.runtime.ring import FLAG_DHCP_CTRL
from bng_tpu.runtime.tables import (FastPathTables, PPPoEFastPathTables,
                                    apply_fastpath_updates)
from bng_tpu.utils.structlog import ErrorLog, SlowPathErrorLog

# default per-lane packet slot: a full MTU frame (1500) + headroom for
# QinQ/PPPoE encap, like the reference's XDP frame slot. Engines that only
# ever see control traffic may shrink it (bench uses 512-byte slots).
PKT_SLOT = 1536


def _apply_all_updates(tables: PipelineTables, upd) -> PipelineTables:
    """upd layout: 7 mandatory entries + optional named tails — garden
    (garden_upd, allowed_rows), then pppoe (sid_upd, ip_upd), then edge
    (tap_upd, tap_filters, tap_config, route_upd) — each present exactly
    when the corresponding device stage is compiled in."""
    fp_upd, nat_upd, qup, qdown, sp_upd, sp_ranges, sp_config, *tails = upd
    tails = list(tails)
    g_state, g_allowed = tables.garden, tables.garden_allowed
    if tables.garden is not None:
        g_state = apply_update(tables.garden, tails.pop(0))
        g_allowed = tails.pop(0)
    p_sid, p_ip = tables.pppoe_by_sid, tables.pppoe_by_ip
    if p_sid is not None:
        p_sid = apply_update(p_sid, tails.pop(0))
        p_ip = apply_update(p_ip, tails.pop(0))
    e_tap, e_filters, e_config, e_route = (tables.tap, tables.tap_filters,
                                           tables.tap_config, tables.route)
    if e_tap is not None:
        e_tap = apply_update(e_tap, tails.pop(0))
        e_filters = tails.pop(0)
        e_config = tails.pop(0)
        e_route = apply_update(e_route, tails.pop(0))
    return PipelineTables(
        dhcp=apply_fastpath_updates(tables.dhcp, fp_upd),
        nat=apply_nat_updates(tables.nat, nat_upd),
        qos_up=apply_qupdate(tables.qos_up, qup),
        qos_down=apply_qupdate(tables.qos_down, qdown),
        spoof=apply_update(tables.spoof, sp_upd),
        spoof_ranges=sp_ranges,
        spoof_config=sp_config,
        garden=g_state,
        garden_allowed=g_allowed,
        pppoe_by_sid=p_sid,
        pppoe_by_ip=p_ip,
        pppoe_server_mac=tables.pppoe_server_mac,
        tap=e_tap,
        tap_filters=e_filters,
        tap_config=e_config,
        route=e_route,
    )


@functools.lru_cache(maxsize=8)
def _pipeline_jit(geom: PipelineGeom, table_impl: str = "xla"):
    """`table_impl` pins the device_lookup implementation for THIS
    compiled program (ops.table.forced_impl runs at trace time): two
    engines in one process can hold programs traced under different
    impls (the bench A/B race) without racing a global."""
    def step(tables, upd, pkt, length, from_access, now_s, now_us):
        tables = _apply_all_updates(tables, upd)
        with table_mod.forced_impl(table_impl):
            return pipeline_step(tables, pkt, length, from_access, geom,
                                 now_s, now_us)

    # donate the device tables: updates + counter writes are in-place
    return jax.jit(step, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _apply_updates_jit(geom: PipelineGeom, has_garden: bool, has_pppoe: bool,
                       has_edge: bool = False):
    """Packet-free update application — the scheduler's safety net for a
    PREFETCHED bulk drain that no later batch consumed (overlap-drain
    mode builds the scatter for step N+1 while step N executes; at
    flush/quiesce a dangling prefetch must still reach the device or
    the host mirrors and HBM silently diverge).

    The dhcp chain is passed as None and threads through UNTOUCHED: a
    bulk drain's fastpath entry is always the empty no-op update, and
    the authoritative chain may live on the express lane's own device —
    including it would force a cross-device program. geom rides in the
    key only to separate engines whose update pytrees differ."""
    del geom, has_garden, has_pppoe, has_edge

    def apply_only(tables, upd):
        fp_upd, nat_upd, qup, qdown, sp_upd, sp_ranges, sp_config, *tails = upd
        del fp_upd  # the bulk drain's fastpath entry is a no-op by design
        tails = list(tails)
        g_state, g_allowed = tables.garden, tables.garden_allowed
        if tables.garden is not None:
            g_state = apply_update(tables.garden, tails.pop(0))
            g_allowed = tails.pop(0)
        p_sid, p_ip = tables.pppoe_by_sid, tables.pppoe_by_ip
        if p_sid is not None:
            p_sid = apply_update(p_sid, tails.pop(0))
            p_ip = apply_update(p_ip, tails.pop(0))
        e_tap, e_filters, e_config, e_route = (tables.tap, tables.tap_filters,
                                               tables.tap_config, tables.route)
        if e_tap is not None:
            e_tap = apply_update(e_tap, tails.pop(0))
            e_filters = tails.pop(0)
            e_config = tails.pop(0)
            e_route = apply_update(e_route, tails.pop(0))
        from bng_tpu.control.nat import apply_nat_updates

        return tables._replace(
            nat=apply_nat_updates(tables.nat, nat_upd),
            qos_up=apply_qupdate(tables.qos_up, qup),
            qos_down=apply_qupdate(tables.qos_down, qdown),
            spoof=apply_update(tables.spoof, sp_upd),
            spoof_ranges=sp_ranges, spoof_config=sp_config,
            garden=g_state, garden_allowed=g_allowed,
            pppoe_by_sid=p_sid, pppoe_by_ip=p_ip,
            tap=e_tap, tap_filters=e_filters, tap_config=e_config,
            route=e_route)

    return jax.jit(apply_only, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _dhcp_jit(geom, table_impl: str = "xla"):
    """DHCP-only device program — the latency fast lane.

    In the reference the DHCP fast path is its OWN XDP program
    (bpf/dhcp_fastpath.c): an XDP_TX reply never traverses the TC
    NAT/QoS/antispoof hooks. A pre-classified control batch (UDP:67)
    therefore only needs parse + 3-tier lookup + OFFER compose — a
    several-fold smaller program than the fused step, which is what the
    p99-OFFER target is measured against. Shares (and donates) the same
    dhcp table leaves as the fused step, so the two programs can never
    fork state.

    The packet batch is donated too (argnum 2): out_pkt is shaped
    exactly like pkt, so XLA aliases the reply buffer onto the input
    staging upload instead of allocating per dispatch — the VERDICT r5
    input-output-aliasing item on the express-lane OFFER program.
    Every caller stages from numpy (_pack_frames / ring buffers), so
    the donated device buffer is always a fresh upload, never a live
    caller array."""
    from bng_tpu.ops.dhcp import dhcp_fastpath
    from bng_tpu.ops.parse import parse_batch

    def step(dhcp_tables, upd, pkt, length, now_s):
        dhcp_tables = apply_fastpath_updates(dhcp_tables, upd)
        with table_mod.forced_impl(table_impl):
            par = parse_batch(pkt, length)
            res = dhcp_fastpath(pkt, length, par, dhcp_tables, geom, now_s)
        return dhcp_tables, res.is_reply, res.out_pkt, res.out_len, res.stats

    return jax.jit(step, donate_argnums=(0, 2))


@functools.lru_cache(maxsize=8)
def _express_jit(geom, table_impl: str = "xla"):
    """AOT express-lane OFFER program — the minimal program the 50us
    device budget permits (ISSUE 13).

    Consumes pre-parsed express descriptors (ops/express.py: MAC/xid/
    vlan/cid lane columns extracted once at admission) and emits only
    the verdict block (verdict + yiaddr + pool/lease words); the host
    patches replies into preassembled wire templates at retire. Donates
    the dhcp chain (argnum 0 — updates scatter in place, one
    authoritative chain shared with the full programs) AND the
    descriptor batch (argnum 2 — the verdict block is shaped exactly
    like it, so XLA aliases the output onto the input staging upload;
    every caller stages descriptors from numpy, never a live device
    array).

    The jit wrapper exists for tracing; the serving path compiles it
    ahead of time (`Engine.compile_express_aot`) for the express lane's
    fixed batch geometry and calls the compiled executable directly, so
    a dispatch pays neither trace nor jit-cache lookup."""
    from bng_tpu.ops.express import express_verdicts

    def step(dhcp_tables, upd, desc, now_s):
        dhcp_tables = apply_fastpath_updates(dhcp_tables, upd)
        with table_mod.forced_impl(table_impl):
            res = express_verdicts(dhcp_tables, desc, geom, now_s)
        return dhcp_tables, res.block, res.stats

    return jax.jit(step, donate_argnums=(0, 2))


# AOT-compiled express executables, shared across engines of one
# geometry (the _dhcp_jit sharing discipline, extended to compiled
# executables): (dhcp geom, batch, table impl, device) -> Compiled.
_EXPRESS_AOT: dict = {}


@functools.lru_cache(maxsize=1)
def _process_default_device():
    """The device the executable itself would place host arrays on.
    Cached once per process: jax backends are process-stable, and the
    devloop dispatch path asks on every ring."""
    return jax.local_devices()[0]


@functools.lru_cache(maxsize=512)
def _u32_scalar(v: int):
    """Device-resident u32 scalar, cached by value. The devloop ring
    passes `n_slots` (always k on a full ring) and `now` (advances once
    a second) on every dispatch — converting them fresh costs ~0.4ms of
    host ceremony per ring on CPU. Safe to share: neither argument is
    in the megakernel's donate set."""
    return jnp.uint32(v)


class _ExpressAotResult(NamedTuple):
    """AOT express dispatch result (futures until the ring retire).

    Shaped for Engine._fold_stats like _DhcpBatchResult; the verdict
    block replaces per-lane packet outputs — the scheduler's retire
    patches replies host-side from wire templates."""

    block: "jax.Array"  # [B, XD_WORDS] uint32 (ops/express VB_* cols)
    dhcp_stats: "jax.Array"  # [DHCP_NSTATS]
    nat_stats: np.ndarray  # zeros (no NAT on this program)
    qos_stats: np.ndarray  # zeros
    spoof_stats: np.ndarray  # zeros


class _DhcpBatchResult(NamedTuple):
    """DHCP-only step result, shaped for the ring verdict demux AND the
    stats fold — async like PipelineResult (device outputs stay futures
    until the ring retire forces them, so the fast lane pipelines too)."""

    verdict: "jax.Array"  # [B] uint8 (TX / PASS only)
    out_pkt: "jax.Array"
    out_len: "jax.Array"
    nat_punt: np.ndarray  # [B] all-False (no NAT on this program)
    spoof_violation: np.ndarray  # [B] all-False
    dhcp_stats: "jax.Array"  # [DHCP_NSTATS]
    nat_stats: np.ndarray  # zeros
    qos_stats: np.ndarray  # zeros
    spoof_stats: np.ndarray  # zeros


@dataclass
class EngineStats:
    dhcp: np.ndarray = field(default_factory=lambda: np.zeros(DHCP_NSTATS, dtype=np.uint64))
    nat: np.ndarray = field(default_factory=lambda: np.zeros(NAT_NSTATS, dtype=np.uint64))
    qos: np.ndarray = field(default_factory=lambda: np.zeros(QOS_NSTATS, dtype=np.uint64))
    spoof: np.ndarray = field(default_factory=lambda: np.zeros(ANTISPOOF_NSTATS, dtype=np.uint64))
    # device walled-garden gate: [gated_drops, allowed_hits] (ops/garden.py)
    garden: np.ndarray = field(default_factory=lambda: np.zeros(2, dtype=np.uint64))
    # device PPPoE decap/encap (ops/pppoe.py)
    pppoe: np.ndarray = field(default_factory=lambda: np.zeros(PPPOE_NSTATS, dtype=np.uint64))
    # device edge protection: tap mirror + route rewrite (edge/ops.py EST_*)
    edge: np.ndarray = field(default_factory=lambda: np.zeros(4, dtype=np.uint64))
    batches: int = 0
    tx: int = 0
    fwd: int = 0
    dropped: int = 0
    passed: int = 0
    slow_errors: int = 0


class QoSTables:
    """Host side of the two QoS maps (pkg/qos/manager.go:167-246 role)."""

    def __init__(self, nbuckets: int = 1 << 12, stash: int = 64, update_slots: int = 128):
        # stash accepted for signature compat; the packed table has none
        # (capacity policy: size nbuckets >= subscribers/2, resize on full)
        self.up = HostQTable(nbuckets, name="qos_ingress")
        self.down = HostQTable(nbuckets, name="qos_egress")
        self.geom = QTableGeom(nbuckets)
        self.update_slots = update_slots

    def set_subscriber(self, ip: int, down_bps: int, up_bps: int,
                       down_burst: int | None = None, up_burst: int | None = None,
                       priority: int = 0) -> None:
        # burst default: 1.25s at rate /8 -> bytes (manager.go burst calc role)
        down_burst = down_burst if down_burst is not None else max(int(down_bps / 8 * 1.25), 1500)
        up_burst = up_burst if up_burst is not None else max(int(up_bps / 8 * 1.25), 1500)
        self.down.insert(ip, down_bps, down_burst, priority)
        self.up.insert(ip, up_bps, up_burst, priority)

    def bulk_set_subscribers(self, ips, down_bps: int, up_bps: int) -> None:
        """Vectorized install for table builds at the 1M-subscriber scale."""
        ips = np.asarray(ips, dtype=np.uint32)
        down_burst = max(int(down_bps / 8 * 1.25), 1500)
        up_burst = max(int(up_bps / 8 * 1.25), 1500)
        n = len(ips)
        self.down.bulk_insert(ips, np.full(n, down_bps, np.uint64),
                              np.full(n, down_burst, np.uint32))
        self.up.bulk_insert(ips, np.full(n, up_bps, np.uint64),
                            np.full(n, up_burst, np.uint32))

    def remove_subscriber(self, ip: int) -> None:
        self.down.delete(ip)
        self.up.delete(ip)


class AntispoofTables:
    """Host side of antispoof (pkg/antispoof/manager.go role)."""

    def __init__(self, nbuckets: int = 1 << 12, stash: int = 64, update_slots: int = 128):
        from bng_tpu.ops.antispoof import MODE_DISABLED

        self.bindings = HostTable(nbuckets, 2, ANTISPOOF_WORDS, stash=stash, name="subscriber_bindings")
        self.ranges = np.zeros((256, 2), dtype=np.uint32)
        self.config = np.array([MODE_DISABLED, 0], dtype=np.uint32)
        self.geom = TableGeom(nbuckets, stash)
        self.update_slots = update_slots

    def set_config(self, default_mode: int, log_violations: bool) -> None:
        self.config[0] = default_mode
        self.config[1] = 1 if log_violations else 0

    def add_binding(self, mac, ipv4: int, mode: int) -> None:
        from bng_tpu.ops.antispoof import AB_IPV4, AB_MODE, AB_VALIDS, VALID_V4
        from bng_tpu.utils.net import mac_to_u64, split_u64

        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        row = np.zeros((ANTISPOOF_WORDS,), dtype=np.uint32)
        row[AB_IPV4] = ipv4
        row[AB_VALIDS] = VALID_V4
        row[AB_MODE] = mode
        self.bindings.insert([hi, lo], row)

    def add_binding_v6(self, mac, ipv6_words: list[int], mode: int) -> None:
        from bng_tpu.ops.antispoof import AB_MODE, AB_V6_0, AB_VALIDS, VALID_V6
        from bng_tpu.utils.net import mac_to_u64, split_u64

        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        existing = self.bindings.lookup([hi, lo])
        row = existing if existing is not None else np.zeros((ANTISPOOF_WORDS,), dtype=np.uint32)
        row[AB_V6_0 : AB_V6_0 + 4] = np.asarray(ipv6_words, dtype=np.uint32)
        row[AB_VALIDS] |= VALID_V6
        row[AB_MODE] = mode
        self.bindings.insert([hi, lo], row)

    def remove_binding(self, mac) -> bool:
        from bng_tpu.utils.net import mac_to_u64, split_u64

        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        return self.bindings.delete([hi, lo])

    def add_allowed_range(self, network: int, prefix_len: int) -> None:
        free = np.nonzero(self.ranges[:, 0] == 0)[0]
        if len(free) == 0:
            raise RuntimeError("allowed-ranges table full")
        self.ranges[free[0]] = (prefix_len, network)


class GardenTables:
    """Host side of the device walled-garden gate (ops/garden.py).

    Beyond the reference: its walled garden never reaches a bpf program
    (walledgarden/manager.go:172-178 hooks are unconsumed), so pre-auth
    data traffic PASSes to the host. Here membership (subscriber private
    IP -> gardened flag) and the allowed destinations (portal, DNS —
    manager.go:95-103) live on-device and gate in the fused pipeline.
    Driven by WalledGardenManager state transitions through the normal
    bounded update drain."""

    def __init__(self, nbuckets: int = 1 << 12, stash: int = 64,
                 update_slots: int = 128, max_allowed: int = 64):
        from bng_tpu.ops.garden import GARDEN_WORDS

        self.subscribers = HostTable(nbuckets, 1, GARDEN_WORDS, stash=stash,
                                     name="garden_subscribers")
        self.allowed = np.zeros((max_allowed, 3), dtype=np.uint32)
        self.geom = TableGeom(nbuckets, stash)
        self.update_slots = update_slots

    def set_gardened(self, ip: int, gardened: bool) -> None:
        """Mark/unmark a subscriber IP as gardened (idempotent; insert is
        an upsert, so re-gardening costs one dirty slot, not two)."""
        from bng_tpu.ops.garden import GARDEN_WORDS, GV_FLAG

        if gardened:
            row = np.zeros((GARDEN_WORDS,), dtype=np.uint32)
            row[GV_FLAG] = 1
            self.subscribers.insert([ip], row)
        else:
            self.subscribers.delete([ip])

    def allow_destination(self, ip: int, port: int = 0, proto: int = 0) -> None:
        """port/proto 0 = wildcard (manager.go:237-242 key semantics)."""
        free = np.nonzero(self.allowed[:, 0] == 0)[0]
        if len(free) == 0:
            raise RuntimeError("allowed-destinations table full")
        self.allowed[free[0]] = (ip, port, proto)


@owned_by("loop", attrs=("tables",))
class Engine:
    def __init__(
        self,
        fastpath: FastPathTables,
        nat: NATManager,
        qos: QoSTables | None = None,
        antispoof: AntispoofTables | None = None,
        garden: "GardenTables | None" = None,
        pppoe: "PPPoEFastPathTables | None" = None,
        batch_size: int = 256,
        pkt_slot: int = PKT_SLOT,
        slow_path: Callable[[bytes], bytes | None] | None = None,
        violation_sink: Callable[[int, bytes], None] | None = None,
        clock: Callable[[], float] = time.time,
        device_tables: "PipelineTables | None" = None,
        edge: "EdgeTables | None" = None,
        mirror_sink: Callable[[int, bytes, int], None] | None = None,
    ):
        self.fastpath = fastpath
        self.nat = nat
        self.qos = qos or QoSTables()
        self.antispoof = antispoof or AntispoofTables()
        # None = device gate off: the pipeline compiles WITHOUT the garden
        # kernel (no per-batch lookup/compare for a disabled feature); the
        # composition root passes GardenTables only when the walled garden
        # is enabled (nil-safe optional maps, manager.go:113-116 role)
        self.garden = garden
        # None = no PPPoE stage in the compiled pipeline (IPoE-only
        # deployments pay nothing); the composition root passes
        # PPPoEFastPathTables when the PPPoE server is constructed
        self.pppoe = pppoe
        # None = no edge-protection stage (tap mirror + route rewrite) in
        # the compiled pipeline; the composition root passes EdgeTables
        # when intercept/routing programs are wired (edge/compile.py)
        self.edge = edge
        # host retire hook for MIRROR-flagged lanes: (lane, frame, wid).
        # The MirrorPump (edge/compile.py) feeds RecordCC/HI3 export here.
        self.mirror_sink = mirror_sink
        self.B = batch_size
        self.L = pkt_slot
        self.slow_path = slow_path
        # batched slow-path handler (the slow-path fleet's fan-out hook):
        # [(lane, frame)] -> [(lane, reply|None)] in ascending lane
        # order. When set it takes precedence over the per-frame
        # slow_path for every PASS-lane drain (process / process_dhcp /
        # ring / scheduler retire).
        self.slow_path_batch = None
        self.violation_sink = violation_sink
        self.clock = clock
        self.stats = EngineStats()
        self._inflight = None  # pipelined ring mode (process_ring_pipelined)
        self._stage_bufs = [None, None]  # ping-pong staging (lazy alloc)
        self._stage_idx = 0
        # slow-path failures are counted AND logged (rate-limited): the
        # counter alone dropped the traceback (server.go:330 logs each)
        self._slow_err_log = SlowPathErrorLog("engine")
        # antispoof violation lanes are logged rate-limited (ISSUE 17
        # satellite): counters alone hid WHO is spoofing; an unbounded
        # log would melt under a DDoS burst storm
        self._viol_log = ErrorLog("antispoof", "antispoof violation",
                                  rate=5.0, burst=10)
        # bumped by resync_tables(); the scheduler watches it to know its
        # bulk-lane DHCP replica / express placement went stale
        self.resync_count = 0

        self.geom = PipelineGeom(
            dhcp=fastpath.geom, nat=nat.geom, qos=self.qos.geom,
            spoof=self.antispoof.geom,
            garden=self.garden.geom if self.garden else None,
            pppoe=self.pppoe.geom if self.pppoe else None,
            tap=self.edge.geom if self.edge else None,
            route=self.edge.geom if self.edge else None,
        )
        # `device_tables` adopts a prebuilt geometry-identical device
        # pytree (the blue/green standby's snapshot-hydrated chain,
        # runtime/ops.py) in place of the init upload — without it the
        # standby would pay a full H2D upload of the live mirrors only
        # to discard it, doubling the swap's quiesce-held hydrate cost
        self.tables: PipelineTables = (
            device_tables if device_tables is not None
            else self._device_tables())
        # jit cache is keyed on geometry so Engine instances with identical
        # table shapes share one compile (tests build many engines). The
        # table-probe impl (BNG_TABLE_IMPL / autotune choice) is resolved
        # ONCE at engine construction and keys the cache too — an env/auto
        # flip after construction needs a new Engine, same discipline the
        # qos PREFIX_IMPL documents for its jits.
        self.table_impl = table_mod.resolved_table_impl()
        self._step = _pipeline_jit(self.geom, self.table_impl)
        self._dhcp_step = _dhcp_jit(fastpath.geom, self.table_impl)
        # host-path snapshot (ISSUE 14): vector = batch-native frame
        # staging through a cycling preallocated pool instead of a
        # fresh np.zeros + per-frame copy loop per dispatch. Resolved
        # once at construction, like table_impl.
        self.host_path = hostpath.resolved_host_path()
        self._stage_pool = (hostpath.StagingPool(self.L)
                            if self.host_path == "vector" else None)

    def _device_tables(self) -> PipelineTables:
        return PipelineTables(
            dhcp=self.fastpath.device_tables(),
            nat=self.nat.device_tables(),
            qos_up=self.qos.up.device_state(),
            qos_down=self.qos.down.device_state(),
            spoof=self.antispoof.bindings.device_state(),
            spoof_ranges=jnp.asarray(self.antispoof.ranges),
            spoof_config=jnp.asarray(self.antispoof.config),
            garden=(self.garden.subscribers.device_state()
                    if self.garden else None),
            garden_allowed=(jnp.asarray(self.garden.allowed)
                            if self.garden else None),
            pppoe_by_sid=(self.pppoe.by_sid.device_state()
                          if self.pppoe else None),
            pppoe_by_ip=(self.pppoe.by_ip.device_state()
                         if self.pppoe else None),
            pppoe_server_mac=(jnp.asarray(self.pppoe.server_mac)
                              if self.pppoe else None),
            tap=(self.edge.tap.device_state() if self.edge else None),
            tap_filters=(jnp.asarray(self.edge.tap_filters)
                         if self.edge else None),
            tap_config=(jnp.asarray(self.edge.tap_config)
                        if self.edge else None),
            route=(self.edge.route.device_state() if self.edge else None),
        )

    def resync_tables(self) -> None:
        """Full device re-upload after a bulk host-table build.

        A large bulk_insert abandons bounded-delta tracking (_dirty_all);
        this refreshes every device table from the host mirrors so the
        next step proceeds. Device-authoritative state written since the
        last upload (QoS tokens, NAT/session counters) resets to the host
        view — bulk installs are a provisioning-time operation."""
        self.tables = self._device_tables()
        self.resync_count += 1

    def _drain_with_resync(self, drain):
        """Run a make-updates drain; on the bulk-build "full upload" signal
        (bulk_insert abandoned dirty tracking) answer with one full device
        re-upload and drain again (now-clean) — a bulk build on a live
        engine must not brick the step loop."""
        try:
            return drain()
        except RuntimeError as e:
            if "full upload" not in str(e):
                raise
            self.resync_tables()
            return drain()

    def _drain_updates(self):
        # vector host path (ISSUE 14): a clean mirror set drains the
        # CACHED no-op batch instead of rebuilding fresh scatter buffers
        # for every table (~1.7ms/table-set per dispatch with zero dirty
        # slots) — the _drain_fastpath_updates discipline extended to
        # the fused step. Any dirty slot anywhere takes the real bounded
        # drain; dense config arrays are re-read wholesale either way,
        # so the device sees identical state.
        if self._stage_pool is not None and self.pending_dirty() == 0:
            return self._empty_updates()
        return self._drain_with_resync(lambda: (
            self.fastpath.make_updates(),
            self.nat.make_updates(),
            self.qos.up.make_update(self.qos.update_slots),
            self.qos.down.make_update(self.qos.update_slots),
            self.antispoof.bindings.make_update(self.antispoof.update_slots),
            jnp.asarray(self.antispoof.ranges),
            jnp.asarray(self.antispoof.config),
            *((self.garden.subscribers.make_update(self.garden.update_slots),
               jnp.asarray(self.garden.allowed)) if self.garden else ()),
            *((self.pppoe.by_sid.make_update(self.pppoe.update_slots),
               self.pppoe.by_ip.make_update(self.pppoe.update_slots))
              if self.pppoe else ()),
            *(self.edge.make_updates() if self.edge else ()),
        ))

    # -- latency-tiered scheduler support (runtime/scheduler.py) ----------
    #
    # The scheduler splits the steady-state loop into an express lane
    # (DHCP-only program, authoritative dhcp chain = self.tables.dhcp) and
    # a bulk lane (fused pipeline over a dhcp READ REPLICA, so a bulk
    # dispatch never rebinds — and an express dispatch never waits on —
    # the dhcp leaves). These helpers keep the donation bookkeeping here,
    # next to the invariants they must preserve.

    def _make_bulk_updates(self):
        """Update drain for a scheduler bulk step: real deltas for every
        bulk-owned table, a NO-OP for the fastpath tables — the express
        lane is the single consumer of the fastpath drain (one
        authoritative device DHCP chain, never forked)."""
        return (
            self.fastpath.empty_updates(),
            self.nat.make_updates(),
            self.qos.up.make_update(self.qos.update_slots),
            self.qos.down.make_update(self.qos.update_slots),
            self.antispoof.bindings.make_update(self.antispoof.update_slots),
            jnp.asarray(self.antispoof.ranges),
            jnp.asarray(self.antispoof.config),
            *((self.garden.subscribers.make_update(self.garden.update_slots),
               jnp.asarray(self.garden.allowed)) if self.garden else ()),
            *((self.pppoe.by_sid.make_update(self.pppoe.update_slots),
               self.pppoe.by_ip.make_update(self.pppoe.update_slots))
              if self.pppoe else ()),
            *(self.edge.make_updates() if self.edge else ()),
        )

    def _empty_updates(self):
        """No-op update batch for scheduler bulk steps between
        drain-cadence points. The big scatter buffers (update_slots x row
        words per table — the real per-step host->HBM traffic) come from
        the per-table empty_update caches; the small dense config arrays
        (spoof ranges/config, garden allowlist, NAT hairpin/alg/config,
        DHCP pools/server) are re-read from host state EVERY call because
        the step applies them wholesale — a cached snapshot would revert
        live config changes on every no-drain step."""
        return (
            self.fastpath.empty_updates(),
            self.nat.empty_updates(),
            self.qos.up.empty_update(self.qos.update_slots),
            self.qos.down.empty_update(self.qos.update_slots),
            self.antispoof.bindings.empty_update(self.antispoof.update_slots),
            jnp.asarray(self.antispoof.ranges),
            jnp.asarray(self.antispoof.config),
            *((self.garden.subscribers.empty_update(self.garden.update_slots),
               jnp.asarray(self.garden.allowed)) if self.garden else ()),
            *((self.pppoe.by_sid.empty_update(self.pppoe.update_slots),
               self.pppoe.by_ip.empty_update(self.pppoe.update_slots))
              if self.pppoe else ()),
            *(self.edge.empty_updates() if self.edge else ()),
        )

    def prefetch_bulk_updates(self):
        """Build (and start uploading) the NEXT bulk drain's update batch
        while the current step still executes — the overlap-drain half of
        VERDICT r5 item 3. Consumes the host dirty sets exactly like the
        in-dispatch drain (the delta is simply built one step early;
        writes landing after the prefetch ride the following drain), and
        the jnp.asarray transfers inside start their H2D copies
        immediately, so by the next dispatch the scatter operands are
        already device-resident. The caller (TieredScheduler) OWNS the
        returned batch: it must reach the device via the next
        dispatch_scheduled_bulk(upd=...) or apply_updates_now(), or host
        and HBM silently diverge."""
        return self._drain_with_resync(self._make_bulk_updates)

    def apply_updates_now(self, upd) -> None:
        """Apply one already-built BULK update batch with no packet batch
        — the flush/quiesce path for a prefetched drain no later batch
        consumed. Donates and rebinds the non-dhcp device tables like
        the step; the authoritative dhcp chain (possibly express-lane
        device-resident) never enters the program."""
        step = _apply_updates_jit(self.geom, self.garden is not None,
                                  self.pppoe is not None,
                                  self.edge is not None)
        rest = step(self.tables._replace(dhcp=None), upd)
        self.tables = rest._replace(dhcp=self.tables.dhcp)

    def dispatch_scheduled_bulk(self, pkt, length, fa, now: float,
                                dhcp_replica, drain: bool = True,
                                upd=None):
        """Async bulk-lane dispatch for the tiered scheduler.

        Runs the fused step over `dhcp_replica` instead of the
        authoritative dhcp chain: self.tables.dhcp is NOT an input, so the
        express program's next dispatch has no data dependency on this
        step. The replica is donated and threaded bulk->bulk by the
        caller. drain=False passes the cached no-op update batch — the
        scheduler owns the drain cadence; a prefetched batch from
        prefetch_bulk_updates() arrives via `upd` (overlap-drain mode)
        and takes the drain's place. Returns (res, new_replica);
        outputs are futures (retire at the completion ring, never here).
        """
        now_s = np.uint32(int(now))
        now_us = np.uint32(int(now * 1e6) & 0xFFFFFFFF)
        if upd is not None:
            pass  # prefetched drain: built (and uploading) since step N-1
        elif drain:
            upd = self._drain_with_resync(self._make_bulk_updates)
        else:
            upd = self._empty_updates()
        # read self.tables AFTER the drain (a bulk-build resync rebinds it)
        tables_in = self.tables._replace(dhcp=dhcp_replica)
        res: PipelineResult = self._step(
            tables_in, upd, jnp.asarray(pkt), jnp.asarray(length),
            jnp.asarray(fa), now_s, now_us)
        # keep the authoritative dhcp chain out of the bulk rebind; the
        # replica-out threads back to the scheduler
        self.tables = res.tables._replace(dhcp=self.tables.dhcp)
        self.stats.batches += 1
        return res, res.tables.dhcp

    def _pack_frames(self, frames: list[bytes], B: int):
        """Stage a frame list into device-shaped [B, L] + lengths.

        Vector host path: one ragged scatter into a pooled staging pair
        (hostpath.StagingPool — no per-dispatch allocation, no
        per-frame copy loop); scalar: the per-frame oracle."""
        if len(frames) > B:
            raise ValueError(f"batch of {len(frames)} exceeds batch size {B}")
        if self._stage_pool is not None:
            if not frames:
                return self._stage_pool.stage(frames, B)
            lens = hostpath.frame_lens(frames)
            if int(lens.max()) > self.L:
                # never truncate silently: a clipped frame would be
                # shaped and NAT-accounted at the wrong length and
                # TX'd corrupt
                raise ValueError(
                    f"frame of {int(lens.max())} bytes exceeds engine "
                    f"pkt_slot {self.L}")
            return self._stage_pool.stage(frames, B, lens=lens)
        pkt = np.zeros((B, self.L), dtype=np.uint8)
        length = np.zeros((B,), dtype=np.uint32)
        for i, f in enumerate(frames):
            if len(f) > self.L:
                # never truncate silently: a clipped frame would be shaped
                # and NAT-accounted at the wrong length and TX'd corrupt
                raise ValueError(
                    f"frame of {len(f)} bytes exceeds engine pkt_slot {self.L}")
            pkt[i, : len(f)] = np.frombuffer(f, dtype=np.uint8)
            length[i] = len(f)
        return pkt, length

    def _handle_slow_lanes(self, items: list, path: str) -> list:
        """Drain a batch of PASS-lane frames through the slow path:
        the batched fleet handler when wired (fan-out to workers,
        replies re-merged in lane order), else the per-frame handler.
        items: [(lane, frame)] or [(lane, frame, enq_t)] (the scheduler
        threads per-frame enqueue times through for deadline shedding)
        -> [(lane, reply|None)] ascending-lane."""
        if not items:
            return []
        t0 = tele.t()
        if t0 is None:
            return self._handle_slow_lanes_inner(items, path)
        tele.stamp(tele.SLOW)
        out = self._handle_slow_lanes_inner(items, path)
        tele.lap(tele.SLOW, t0)
        return out

    def _handle_slow_lanes_inner(self, items: list, path: str) -> list:
        fp = fault_point("engine.slow_drain")
        if fp is not None and fp.kind == "fail":
            # chaos: the whole slow batch is lost BEFORE any handler
            # runs — no half-allocation is possible, clients retransmit
            self.stats.slow_errors += 1
            return [(item[0], None) for item in items]
        if self.slow_path_batch is not None:
            try:
                out = self.slow_path_batch(items)
            except Exception as e:  # noqa: BLE001 — fleet IPC can fail
                self.stats.slow_errors += 1
                self._slow_err_log.report(e, path=path, lane=-1)
                return [(item[0], None) for item in items]
            return sorted(out, key=lambda t: t[0])
        results = []
        for lane, frame in ((item[0], item[1]) for item in items):
            reply = None
            try:
                if self.slow_path is not None:
                    reply = self.slow_path(frame)
            except Exception as e:  # noqa: BLE001 — slow path is untrusted input
                self.stats.slow_errors += 1
                self._slow_err_log.report(e, path=path, lane=lane)
            results.append((lane, reply))
        return results

    def process(
        self,
        frames: list[bytes],
        from_access: list[bool] | bool = True,
        now: float | None = None,
    ) -> dict:
        """Run one batch through the device pipeline and apply verdicts.

        Returns {"tx": [(lane, frame)], "fwd": [...], "dropped": [lanes],
        "slow": [(lane, reply_frame|None)]}.
        """
        now = now if now is not None else self.clock()
        now_s = np.uint32(int(now))
        now_us = np.uint32(int(now * 1e6) & 0xFFFFFFFF)

        pkt, length = self._pack_frames(frames, self.B)
        if isinstance(from_access, bool):
            fa = np.full((self.B,), from_access, dtype=bool)
        else:
            fa = np.zeros((self.B,), dtype=bool)
            fa[: len(from_access)] = from_access

        tok = tele.begin_batch(tele.LANE_ENGINE, len(frames))
        t0 = tele.t()
        try:
            res = self._run_step(pkt, length, fa, now_s, now_us)
        except BaseException:
            tele.cancel_batch(tok)  # a failed dispatch must not leak a slot
            raise
        tele.lap(tele.DISPATCH, t0, tok)

        t0 = tele.t()
        verdict = np.asarray(res.verdict)[: len(frames)]
        out_len = np.asarray(res.out_len)
        tele.lap(tele.DEVICE_WAIT, t0, tok)
        out_pkt = res.out_pkt  # fetch rows lazily
        punt = np.asarray(res.nat_punt)[: len(frames)]
        viol = np.asarray(res.spoof_violation)[: len(frames)]
        mir = (np.asarray(res.mirror)[: len(frames)]
               if getattr(res, "mirror", None) is not None else None)

        out = {"tx": [], "fwd": [], "dropped": [], "slow": []}
        out_rows = None
        slow_items = []  # non-punt PASS lanes, drained in one batch below
        punt_lanes = []
        t0 = tele.t()
        for i, v in enumerate(verdict):
            if v == VERDICT_TX:
                if out_rows is None:
                    out_rows = np.asarray(out_pkt)
                out["tx"].append((i, bytes(out_rows[i, : int(out_len[i])])))
                self.stats.tx += 1
            elif v == VERDICT_FWD:
                if out_rows is None:
                    out_rows = np.asarray(out_pkt)
                out["fwd"].append((i, bytes(out_rows[i, : int(out_len[i])])))
                self.stats.fwd += 1
            elif v == VERDICT_DROP:
                out["dropped"].append(i)
                self.stats.dropped += 1
            else:
                self.stats.passed += 1
                if punt[i]:
                    try:
                        self._punt_new_flow(frames[i], int(now))
                    except Exception as e:  # noqa: BLE001 — untrusted input
                        self.stats.slow_errors += 1
                        self._slow_err_log.report(e, path="process", lane=i)
                    punt_lanes.append(i)
                else:
                    slow_items.append((i, frames[i]))
            if viol[i]:
                self._viol_log.report(ValueError("spoofed source address"),
                                      path="process", lane=i)
                if self.violation_sink is not None:
                    self.violation_sink(i, frames[i])
            if mir is not None and mir[i] and self.mirror_sink is not None:
                # interception observes the ORIGINAL frame even on lanes
                # the verdict later drops (garden/QoS/antispoof)
                self.mirror_sink(i, frames[i], int(mir[i]))
        tele.lap(tele.REPLY, t0, tok)
        out["slow"] = sorted(
            [(i, None) for i in punt_lanes]
            + self._handle_slow_lanes(slow_items, path="process"),
            key=lambda t: t[0])
        tele.end_batch(tok, punt=len(punt_lanes))
        return out

    # fast-lane compile-shape budget: every auto-sized control batch maps
    # onto one of these pow2 buckets, so a latency sweep over arbitrary
    # batch sizes can trigger at most len(DHCP_BATCH_BUCKETS) compiles of
    # the DHCP-only program (pinned by tests/test_hlo_structure.py)
    DHCP_BATCH_FLOOR = 64
    DHCP_BATCH_CAP = 8192

    @classmethod
    def dhcp_batch_bucket(cls, n: int) -> int:
        """Pow2 bucket (floor 64, cap 8192) for a fast-lane batch of n
        frames. The cap bounds the compile set; a caller with more than
        DHCP_BATCH_CAP control frames should split the batch (the engine's
        ring assembler never produces one that large)."""
        b = max(cls.DHCP_BATCH_FLOOR, 1 << max(0, n - 1).bit_length())
        return min(b, cls.DHCP_BATCH_CAP)

    def process_dhcp(self, frames: list[bytes], now: float | None = None,
                     batch: int | None = None) -> dict:
        """Latency fast lane: run a PRE-CLASSIFIED control batch (DHCP to
        UDP:67) through the DHCP-only device program.

        Reference hook-order parity: dhcp_fastpath.c is its own XDP
        program; an XDP_TX reply never traverses the TC NAT/QoS/antispoof
        chain, so a control batch must not pay the fused step's cost.
        Non-DHCP frames in the batch simply fall out as "slow" lanes
        (is_reply False), exactly like XDP_PASS.

        The dhcp table leaves of self.tables thread through this step
        (donated) just as the fused step threads them — one authoritative
        device copy, whichever program runs next. Returns
        {"tx": [(lane, frame)], "slow": [(lane, reply|None)]}.
        """
        if batch is None and len(frames) > self.DHCP_BATCH_CAP:
            # above the compile-shape cap: split into capped chunks and
            # merge (lane indices re-based), so callers keep the old
            # any-size behavior without growing the compile set
            out = {"tx": [], "slow": []}
            for base in range(0, len(frames), self.DHCP_BATCH_CAP):
                part = self.process_dhcp(frames[base : base + self.DHCP_BATCH_CAP],
                                         now=now)
                for k in ("tx", "slow"):
                    out[k].extend((base + i, v) for i, v in part[k])
            return out
        if batch is not None:
            B = batch
        else:
            B = self.dhcp_batch_bucket(len(frames))
        now = now if now is not None else self.clock()
        pkt, length = self._pack_frames(frames, B)
        tok = tele.begin_batch(tele.LANE_ENGINE, len(frames))
        t0 = tele.t()
        try:
            res = self._run_dhcp_batch(pkt, length, now)
        except BaseException:
            tele.cancel_batch(tok)  # a failed dispatch must not leak a slot
            raise
        tele.lap(tele.DISPATCH, t0, tok)
        t0 = tele.t()
        reply = np.asarray(res.verdict)[: len(frames)] == VERDICT_TX
        tele.lap(tele.DEVICE_WAIT, t0, tok)
        self._fold_stats(res)
        out_pkt, out_len = res.out_pkt, res.out_len
        out = {"tx": [], "slow": []}
        out_rows = None
        ol = np.asarray(out_len)
        slow_items = []
        t0 = tele.t()
        for i, r in enumerate(reply):
            if r:
                if out_rows is None:
                    out_rows = np.asarray(out_pkt)
                out["tx"].append((i, bytes(out_rows[i, : int(ol[i])])))
                self.stats.tx += 1
            else:
                self.stats.passed += 1
                slow_items.append((i, frames[i]))
        tele.lap(tele.REPLY, t0, tok)
        out["slow"] = self._handle_slow_lanes(slow_items, path="process_dhcp")
        tele.end_batch(tok)
        return out

    def _place_dhcp_chain(self, device) -> None:
        """Migrate the authoritative dhcp chain to `device` (the
        scheduler's express-lane isolation: its own execution stream, so
        an express dispatch cannot queue behind bulk work). Idempotent —
        and self-healing after a resync_tables() rebind put the fresh
        upload back on the default device."""
        leaf = jax.tree_util.tree_leaves(self.tables.dhcp)[0]
        if device in leaf.devices():
            return
        self.tables = self.tables._replace(
            dhcp=jax.device_put(self.tables.dhcp, device))

    def _run_dhcp_batch(self, pkt, length, now: float,
                        device=None) -> "_DhcpBatchResult":
        """Dispatch one staged batch to the DHCP-only device program,
        threading (and donating) the shared dhcp table leaves. Outputs are
        futures (async, like _dispatch_step) — the caller folds stats and
        forces verdicts when it needs them (TX for on-device replies,
        PASS otherwise; no NAT punts or spoof violations exist on this
        program). `device` pins the dispatch (tables + inputs) to a
        specific device — the scheduler's express lane."""
        self._dispatch_fault()
        B = pkt.shape[0]
        upd = self._drain_fastpath_updates()
        # donation safety: the program donates the packet batch (out_pkt
        # aliases the staging upload). Every caller stages from numpy —
        # asarray then creates a fresh device buffer — but a jax-array
        # input would ALIAS the caller's live buffer into the donation,
        # so copy it defensively rather than consume it.
        pkt_d = (jnp.array(pkt, copy=True) if isinstance(pkt, jax.Array)
                 else jnp.asarray(pkt))
        len_d = jnp.asarray(length)
        if device is not None:
            # placement AFTER the drain: a bulk-build resync inside it
            # rebinds self.tables onto the default device
            self._place_dhcp_chain(device)
            upd = jax.device_put(upd, device)
            pkt_d = jax.device_put(pkt_d, device)
            len_d = jax.device_put(len_d, device)
        dhcp_tables, is_reply, out_pkt, out_len, stats = self._dhcp_step(
            self.tables.dhcp, upd, pkt_d, len_d,
            np.uint32(int(now)))
        self.tables = self.tables._replace(dhcp=dhcp_tables)
        self.stats.batches += 1
        verdict = jnp.where(is_reply, np.uint8(VERDICT_TX),
                            np.uint8(VERDICT_PASS))
        no = np.zeros((B,), dtype=bool)
        return _DhcpBatchResult(
            verdict=verdict, out_pkt=out_pkt, out_len=out_len,
            nat_punt=no, spoof_violation=no, dhcp_stats=stats,
            nat_stats=np.zeros(NAT_NSTATS, dtype=np.uint32),
            qos_stats=np.zeros(QOS_NSTATS, dtype=np.uint32),
            spoof_stats=np.zeros(ANTISPOOF_NSTATS, dtype=np.uint32))

    def _run_dhcp_batch_sync(self, pkt, length, now: float) -> "_DhcpBatchResult":
        """Dispatch + fold — the sync-path pairing (mirrors _run_step for
        the fused program; the pipelined path folds at retire instead)."""
        res = self._run_dhcp_batch(pkt, length, now)
        self._fold_stats(res)
        return res

    def _drain_fastpath_updates(self):
        """Fastpath-only update drain for the express programs. The
        steady-state fast lane has NOTHING dirty (lease writes arrive in
        bursts from the slow path), and building a real drain allocates
        fresh scatter buffers for every table — ~40% of the express
        dispatch's host cost measured on CPU. A clean mirror set drains
        the CACHED no-op batch instead (pools/server still re-read
        wholesale, exactly like the bulk lane's empty drain); any dirty
        slot takes the real bounded drain, so an OFFER still always sees
        the newest lease. Shapes are identical either way — both batches
        feed the same compiled programs."""
        fp = self.fastpath
        if fp.dirty_count() == 0:
            return fp.empty_updates()
        return self._drain_with_resync(fp.make_updates)

    # -- AOT express OFFER path (runtime/scheduler.py fast lane) ----------

    def _express_aot_key(self, batch: int, device) -> tuple:
        # DHCPGeom covers only bucket/stash shapes; the compiled
        # executable's avals also bake the dense pools array
        # ([max_pools, POOL_WORDS]) and the update-batch scatter shapes
        # (update_slots) — two engines differing only there must not
        # share an executable (a call-time shape mismatch would crash
        # the dispatch instead of falling back)
        return (self.fastpath.geom, len(self.fastpath.pools),
                self.fastpath.update_slots, batch, self.table_impl,
                None if device is None else str(device))

    def express_aot(self, batch: int, device=None):
        """The compiled express executable for `batch`, or None — a None
        here is the GEOMETRY MISS the scheduler must fall back (loudly)
        from; it never compiles."""
        return _EXPRESS_AOT.get(self._express_aot_key(batch, device))

    def compile_express_aot(self, batch: int, device=None):
        """`jax.jit(...).lower(...).compile()` the express program for
        one fixed batch geometry — engine/scheduler init time, NEVER the
        dispatch path. Cached on (geometry, impl, device) so engines of
        one shape share a single executable. Lowering uses the live
        chain's avals plus an EMPTY update batch (same pytree shapes as
        a real drain; a real make_updates() here would consume dirty
        state the next dispatch needs)."""
        from bng_tpu.ops.express import XD_WORDS

        key = self._express_aot_key(batch, device)
        exe = _EXPRESS_AOT.get(key)
        if exe is not None:
            return exe
        if device is not None:
            self._place_dhcp_chain(device)
        dev = device if device is not None else jax.devices()[0]
        upd = jax.device_put(self.fastpath.empty_updates(), dev)
        desc = jax.device_put(jnp.zeros((batch, XD_WORDS), jnp.uint32), dev)
        now_d = jax.device_put(jnp.uint32(0), dev)
        exe = _express_jit(self.fastpath.geom, self.table_impl).lower(
            self.tables.dhcp, upd, desc, now_d).compile()
        _EXPRESS_AOT[key] = exe
        return exe

    def run_express_aot(self, express_exe, desc: np.ndarray, now: float,
                        device=None) -> "_ExpressAotResult":
        """Dispatch one staged descriptor batch to the AOT-compiled
        express program. Same discipline as _run_dhcp_batch: the
        fastpath delta drains first (an OFFER must see the newest
        lease), the authoritative dhcp chain threads (donated) through
        the program, outputs stay futures until the ring retire."""
        self._dispatch_fault()
        upd = self._drain_fastpath_updates()
        # donation safety (the _run_dhcp_batch pkt guard): the program
        # donates the descriptor and writes the verdict block over its
        # lead columns. Callers stage from numpy (fresh device buffer);
        # a jax-array input would alias the caller's LIVE buffer into
        # the donation, so copy it defensively rather than consume it.
        desc_d = (jnp.array(desc, copy=True) if isinstance(desc, jax.Array)
                  else jnp.asarray(desc))
        if device is not None:
            # placement AFTER the drain: a bulk-build resync inside it
            # rebinds self.tables onto the default device
            self._place_dhcp_chain(device)
            upd = jax.device_put(upd, device)
            desc_d = jax.device_put(desc_d, device)
            now_d = jax.device_put(jnp.uint32(int(now)), device)
        else:
            # default device: the compiled executable places host
            # arrays itself; an explicit device_put here costs ~0.3ms
            # of pure ceremony per dispatch on CPU
            now_d = jnp.uint32(int(now))
        dhcp_tables, block, stats = express_exe(
            self.tables.dhcp, upd, desc_d, now_d)
        self.tables = self.tables._replace(dhcp=dhcp_tables)
        self.stats.batches += 1
        return _ExpressAotResult(
            block=block, dhcp_stats=stats,
            nat_stats=np.zeros(NAT_NSTATS, dtype=np.uint32),
            qos_stats=np.zeros(QOS_NSTATS, dtype=np.uint32),
            spoof_stats=np.zeros(ANTISPOOF_NSTATS, dtype=np.uint32))

    # -- devloop megakernel path (devloop/host.py ring pump) --------------

    def devloop_aot(self, k: int, batch: int, device=None):
        """The compiled devloop megakernel for this (k, batch) ring
        geometry, or None — the geometry-miss contract mirrors
        express_aot: a None never compiles on the serving path."""
        from bng_tpu.devloop import kernel

        return kernel.get_compiled(self, k, batch, device)

    def compile_devloop_aot(self, k: int, batch: int, device=None):
        """Compile the devloop megakernel at setup time (the
        compile_express_aot discipline — never on the dispatch path)."""
        from bng_tpu.devloop import kernel

        if device is not None:
            self._place_dhcp_chain(device)
        return kernel.compile_devloop(self, k, batch, device)

    def prepare_devloop_dispatch(self, ring, n_slots: int, now: float,
                                 device=None):
        """Main-thread half of a devloop ring dispatch: fault point,
        update drain and argument staging — everything that must stay
        ORDERED with admission and the control plane so two chaos runs
        drain the same deltas at the same ring boundaries. Returns
        ``((upd, ring_d, n_d, now_d), resynced)``; `resynced` flags a
        bulk-build resync inside the drain (the engine chain was
        rebound wholesale — the pump must re-seed its device-resident
        chain from `tables.dhcp` before the next call)."""
        self._dispatch_fault()
        chain_before = self.tables.dhcp
        upd = self._drain_fastpath_updates()
        resynced = self.tables.dhcp is not chain_before
        # donation safety (the run_express_aot guard): the program
        # donates the ring and writes verdict blocks over it. The pump
        # stages from numpy (fresh device buffer); defensively copy a
        # jax-array ring rather than consume a caller's live buffer.
        ring_d = (jnp.array(ring, copy=True) if isinstance(ring, jax.Array)
                  else jnp.asarray(ring))
        if device is not None and device != _process_default_device():
            # explicit placement ONLY when the express stream lives off
            # the process-default device: on the default device the
            # executable places host arrays itself, and walking the
            # ~26 chain/update leaves through device_put costs ~1.5ms
            # of pure dispatch ceremony per ring on CPU — the exact
            # host-side cost this lane exists to amortize. Placement
            # AFTER the drain (resync rebinds self.tables).
            self._place_dhcp_chain(device)
            upd = jax.device_put(upd, device)
            ring_d = jax.device_put(ring_d, device)
            n_d = jax.device_put(jnp.uint32(int(n_slots)), device)
            now_d = jax.device_put(jnp.uint32(int(now)), device)
        else:
            n_d = _u32_scalar(int(n_slots))
            now_d = _u32_scalar(int(now))
        return (upd, ring_d, n_d, now_d), resynced

    @staticmethod
    def call_devloop_aot(exe, dhcp_chain, cursors, prepared, device=None):
        """Executable half of a ring dispatch: PURE — touches no engine
        state, so the pump's dispatch worker may run it off the main
        thread while admission keeps filling the next ring. The chain
        is double-buffered (input NOT donated): `dhcp_chain` stays a
        live, readable handle while the call is in flight, which is
        what lets `tables.dhcp` remain published to the rest of the
        engine until the retire adopts the returned chain."""
        from bng_tpu.devloop.kernel import DevloopResult

        cur_d = (cursors if isinstance(cursors, jax.Array)
                 else jnp.asarray(cursors))
        if (device is not None and device != _process_default_device()
                and not isinstance(cursors, jax.Array)):
            cur_d = jax.device_put(cur_d, device)
        upd, ring_d, n_d, now_d = prepared
        dhcp_tables, blocks, cursors_out, stats = exe(
            dhcp_chain, upd, ring_d, n_d, cur_d, now_d)
        return DevloopResult(
            dhcp_tables=dhcp_tables, blocks=blocks, cursors=cursors_out,
            dhcp_stats=stats,
            nat_stats=np.zeros(NAT_NSTATS, dtype=np.uint32),
            qos_stats=np.zeros(QOS_NSTATS, dtype=np.uint32),
            spoof_stats=np.zeros(ANTISPOOF_NSTATS, dtype=np.uint32))

    def adopt_devloop_chain(self, dhcp_tables, *, count: bool = True) -> None:
        """Publish a retired ring's output chain as the authoritative
        dhcp table state (main thread, at retire — the single
        `engine.tables` writer discipline, BNG041). Monotone: with
        depth>1 rings in flight each retire publishes an older chain
        than the worker is already threading; the final flush publishes
        the newest. ``count=False`` republishes a chain without claiming
        a ring dispatch happened (the pump's resync-race repair)."""
        self.tables = self.tables._replace(dhcp=dhcp_tables)
        if count:
            self.stats.batches += 1

    def run_devloop_aot(self, exe, ring, n_slots: int, cursors, now: float,
                        device=None):
        """Synchronous composition of one ring dispatch (prepare ->
        call -> adopt): one update drain, one executable call, one
        table-chain thread for the WHOLE ring — the k-fold amortization
        this lane exists for. The pump splits these halves across its
        dispatch worker; tests and direct callers get the one-shot
        form. Callers must adopt the returned `cursors` handle."""
        prepared, _resynced = self.prepare_devloop_dispatch(
            ring, n_slots, now, device)
        res = self.call_devloop_aot(exe, self.tables.dhcp, cursors,
                                    prepared, device)
        self.adopt_devloop_chain(res.dhcp_tables)
        return res

    def _dispatch_step(self, pkt, length, fa, now_s, now_us) -> PipelineResult:
        """Enqueue one jitted step (async — outputs are futures). The table
        state threads immediately; callers force outputs when they need
        them (sync path: right away; pipelined path: one batch later)."""
        self._dispatch_fault()
        # drain FIRST: a bulk-build resync rebinds self.tables, and Python
        # evaluates arguments left-to-right — reading self.tables before
        # the drain would pass (and donate) the stale pre-resync reference
        upd = self._drain_updates()
        res: PipelineResult = self._step(
            self.tables, upd, jnp.asarray(pkt), jnp.asarray(length),
            jnp.asarray(fa), now_s, now_us,
        )
        self.tables = res.tables
        self.stats.batches += 1
        return res

    @staticmethod
    def _dispatch_fault() -> None:
        """Chaos hook on every device dispatch: `delay` simulates a slow
        device (bounded sleep), `fail` a failing one — raised BEFORE the
        update drain is consumed, so no table delta is lost with the
        batch. Disarmed: one no-op call per batch."""
        fp = fault_point("engine.dispatch")
        if fp is not None:
            if fp.kind == "fail":
                raise FaultInjectedError(
                    "chaos: injected device dispatch failure")
            if fp.kind == "delay":
                time.sleep(min(max(fp.arg, 0.0), 0.05))

    def _fold_stats(self, res: PipelineResult) -> None:
        self.stats.dhcp += np.asarray(res.dhcp_stats, dtype=np.uint64)
        self.stats.nat += np.asarray(res.nat_stats, dtype=np.uint64)
        self.stats.qos += np.asarray(res.qos_stats, dtype=np.uint64)
        self.stats.spoof += np.asarray(res.spoof_stats, dtype=np.uint64)
        gs = getattr(res, "garden_stats", None)  # DHCP-only batches have none
        if gs is not None:
            self.stats.garden += np.asarray(gs, dtype=np.uint64)
        ps = getattr(res, "pppoe_stats", None)
        if ps is not None:
            self.stats.pppoe += np.asarray(ps, dtype=np.uint64)
        es = getattr(res, "edge_stats", None)
        if es is not None:
            self.stats.edge += np.asarray(es, dtype=np.uint64)

    def _run_step(self, pkt, length, fa, now_s, now_us) -> PipelineResult:
        """Dispatch + fold (the synchronous step both process paths use)."""
        res = self._dispatch_step(pkt, length, fa, now_s, now_us)
        self._fold_stats(res)
        return res

    def process_ring(self, ring, now: float | None = None) -> int:
        """Drain one batch from a packet ring (NativeRing/PyRing) through
        the device pipeline and apply verdicts back to the ring.

        This is the production I/O loop: the ring's assembler writes frames
        straight into the [B, L] staging buffer that goes to the device,
        and complete() demuxes the verdicts (TX/FWD back to the wire, PASS
        to the slow ring — drained here into the slow-path handlers, the
        XDP_PASS delivery). Returns the number of frames processed.
        """
        if self._inflight is not None:
            # a pipelined batch holds one of its ring's assemble windows;
            # retire it (against the ring it came from — not necessarily
            # this one) or the sync path would starve (assemble -> 0)
            self.flush_pipeline()
        pkt = np.zeros((self.B, self.L), dtype=np.uint8)
        length = np.zeros((self.B,), dtype=np.uint32)
        flags = np.zeros((self.B,), dtype=np.uint32)
        t0 = tele.t()
        n = ring.assemble(pkt, length, flags)
        if n == 0:
            return 0
        tok = tele.begin_batch(tele.LANE_RING_L, n)
        tele.lap(tele.RING, t0, tok)
        now = now if now is not None else self.clock()
        now_s = np.uint32(int(now))
        now_us = np.uint32(int(now * 1e6) & 0xFFFFFFFF)
        fa = (flags & 0x1) != 0

        # all-control batches (ring-classified DHCP, flag bit1) take the
        # DHCP-only fast lane — reference hook-order parity, and a
        # several-fold smaller program for the latency-sensitive traffic.
        # Mixed batches run the fused step: one dispatch beats two.
        t0 = tele.t()
        try:
            if bool(((flags[:n] & FLAG_DHCP_CTRL) != 0).all()):
                res = self._run_dhcp_batch_sync(pkt, length, now)
            else:
                res = self._run_step(pkt, length, fa, now_s, now_us)
        except BaseException:
            tele.cancel_batch(tok)  # a failed dispatch must not leak a slot
            raise
        tele.lap(tele.DISPATCH, t0, tok)
        self._apply_ring_verdicts(ring, res, pkt, length, n, now)
        tele.end_batch(tok)
        return n

    def _apply_ring_verdicts(self, ring, res: PipelineResult, pkt, length,
                             n: int, now: float) -> None:
        """Force the step's outputs and demux verdicts back to the ring."""
        t0 = tele.t()
        vv = np.asarray(res.verdict)[:n]
        out_pkt = np.asarray(res.out_pkt)
        out_len = np.asarray(res.out_len).astype(np.uint32)
        tele.lap(tele.DEVICE_WAIT, t0)
        t0 = tele.t()
        ring.complete(vv.astype(np.uint8), out_pkt, out_len, n)

        self.stats.tx += int((vv == VERDICT_TX).sum())
        self.stats.fwd += int((vv == VERDICT_FWD).sum())
        self.stats.dropped += int((vv == VERDICT_DROP).sum())
        self.stats.passed += int((vv == VERDICT_PASS).sum())

        viol = np.asarray(res.spoof_violation)[:n]
        for lane in np.nonzero(viol)[0]:
            self._viol_log.report(ValueError("spoofed source address"),
                                  path="ring", lane=int(lane))
            if self.violation_sink is not None:
                self.violation_sink(int(lane), bytes(pkt[lane, : int(length[lane])]))
        mir = getattr(res, "mirror", None)  # DHCP-only batches have none
        if mir is not None and self.mirror_sink is not None:
            mirw = np.asarray(mir)[:n]
            for lane in np.nonzero(mirw)[0]:
                # original ring bytes: interception sees the frame as it
                # arrived, regardless of the verdict demux above
                self.mirror_sink(int(lane),
                                 bytes(pkt[lane, : int(length[lane])]),
                                 int(mirw[lane]))

        # Drain the slow ring: the slow ring preserves lane order (PASS
        # frames are queued in lane order by complete()), so align pops
        # with the PASS lanes to recover per-lane punt flags. NAT new-flow
        # punts are handled inline; everything else goes to the slow-path
        # handler, whose replies are injected on the TX ring (the Go
        # server's socket-write role). Per-frame handler errors must not
        # abort the drain: a partially drained slow ring would misalign
        # every later batch's lane/punt matching (and wedge PyRing).
        punt = np.asarray(res.nat_punt)[:n]
        slow_items = []  # (lane, frame); from_access flags kept aside
        slow_fa = {}
        punts = 0
        for lane in np.nonzero(vv == VERDICT_PASS)[0]:
            got = ring.slow_pop()
            if got is None:
                break  # slow ring overflowed during complete()
            frame, fl = got
            if punt[lane]:
                punts += 1
                try:
                    self._punt_new_flow(frame, int(now))
                except Exception as e:  # noqa: BLE001 — untrusted input
                    self.stats.slow_errors += 1
                    self._slow_err_log.report(e, path="ring", lane=int(lane))
            else:
                slow_items.append((int(lane), frame))
                slow_fa[int(lane)] = (fl & 0x1) != 0
        tele.lap(tele.REPLY, t0)
        tele.add(punt=punts)
        # fan-out/fan-in: replies come back re-merged in lane order, so
        # TX injection keeps the slow ring's arrival order on the wire
        for lane, reply in self._handle_slow_lanes(slow_items, path="ring"):
            if reply is not None:
                ring.tx_inject(reply, from_access=slow_fa[lane])

    def _staging(self, idx: int):
        """Ping-pong staging buffers (allocated once; the in-flight batch
        owns one while the next assembles into the other)."""
        if self._stage_bufs[idx] is None:
            self._stage_bufs[idx] = (
                np.zeros((self.B, self.L), dtype=np.uint8),
                np.zeros((self.B,), dtype=np.uint32),
                np.zeros((self.B,), dtype=np.uint32),
            )
        return self._stage_bufs[idx]

    def process_ring_pipelined(self, ring, now: float | None = None) -> int:
        """Double-buffered ring loop: dispatch batch k+1, THEN retire k.

        The SURVEY §7 'hard parts' dispatch design. Per call: assemble the
        next batch into the idle ping-pong buffer and dispatch it (the
        device starts immediately), then force + demux the PREVIOUS
        batch's verdicts — so host demux work overlaps device execution.
        Requires ring backends that tolerate two outstanding
        assemble..complete windows (bngring MAX_INFLIGHT=2; complete()
        retires FIFO, matching this loop's order). Per-batch latency grows
        by one batch window; call flush_pipeline() before reading final
        state (shutdown/tests). Returns frames retired this call.
        """
        now = now if now is not None else self.clock()
        prev = self._inflight
        self._inflight = None

        try:
            # 1. feed the device first: assemble into the buffer prev is
            # NOT using, so its frames stay intact until retirement
            idx = 1 - self._stage_idx
            pkt, length, flags = self._staging(idx)
            t0 = tele.t()
            n = ring.assemble(pkt, length, flags)
            if n:
                tok = tele.begin_batch(tele.LANE_RING_L, n)
                tele.lap(tele.RING, t0, tok)
                now_s = np.uint32(int(now))
                now_us = np.uint32(int(now * 1e6) & 0xFFFFFFFF)
                t0 = tele.t()
                try:
                    # all-control batches ride the DHCP-only fast lane here
                    # too — its outputs are equally async, so the overlap
                    # with the previous batch's retire is preserved
                    if bool(((flags[:n] & FLAG_DHCP_CTRL) != 0).all()):
                        res = self._run_dhcp_batch(pkt, length, now)
                    else:
                        res = self._dispatch_step(pkt, length,
                                                  (flags & 0x1) != 0,
                                                  now_s, now_us)
                except BaseException:
                    # fail closed: the assemble opened a ring window that
                    # must not wedge. complete() retires FIFO, so the
                    # previous batch's (older) window must retire FIRST —
                    # dropping into it would mis-complete prev's frames.
                    tele.cancel_batch(tok)
                    self._retire(prev)
                    prev = None
                    ring.complete(np.full((n,), VERDICT_DROP, dtype=np.uint8),
                                  pkt, length, n)
                    raise
                tele.lap(tele.DISPATCH, t0, tok)
                self._inflight = (ring, res, pkt, length, n, now, tok)
                self._stage_idx = idx
        finally:
            # 2. retire the previous batch (even if dispatch raised) while
            # the device runs the new one
            retired = self._retire(prev)
        return retired

    def _retire(self, entry) -> int:
        """Apply a pipelined batch's verdicts to the ring it came from."""
        if entry is None:
            return 0
        ring, res, pkt, length, n, now, tok = entry
        tele.focus(tok)
        self._apply_ring_verdicts(ring, res, pkt, length, n, now)
        self._fold_stats(res)
        tele.end_batch(tok)
        return n

    def flush_pipeline(self, ring=None) -> int:
        """Retire any in-flight pipelined batch (shutdown/test barrier).

        The batch retires against the ring it was assembled from; the
        optional argument is accepted for call-site symmetry only."""
        entry = self._inflight
        self._inflight = None
        return self._retire(entry)

    @staticmethod
    def _strip_pppoe_host(frame: bytes) -> bytes:
        """Host-side mirror of the device decap for NAT punt frames: the
        punt handler sees the ORIGINAL ring bytes, which for a PPPoE
        subscriber still carry the session framing the device stripped.
        Returns the inner Ethernet+IPv4 view (or the frame unchanged)."""
        off = 12
        et = int.from_bytes(frame[off : off + 2], "big")
        while et in (0x8100, 0x88A8) and len(frame) >= off + 8:
            off += 4
            et = int.from_bytes(frame[off : off + 2], "big")
        if et != 0x8864 or len(frame) < off + 10:
            return frame
        if int.from_bytes(frame[off + 8 : off + 10], "big") != 0x0021:
            return frame
        return frame[:off] + b"\x08\x00" + frame[off + 10 :]

    def _punt_new_flow(self, frame: bytes, now: int) -> None:
        """Device egress-miss: create the session host-side (packet 1 of a
        new flow; parity with the conntrack-hybrid slow path)."""
        from bng_tpu.control import packets as P

        if self.pppoe is not None:
            frame = self._strip_pppoe_host(frame)
        try:
            d = P.decode(frame)
        except Exception:
            return
        if d.ethertype != 0x0800:
            return
        src_port = d.icmp_id if d.proto == 1 else d.src_port
        dst_port = 0 if d.proto == 1 else d.dst_port
        self.nat.handle_new_flow(d.src_ip, d.dst_ip, src_port, dst_port,
                                 d.proto, len(frame), now)

    def fetch_session_vals(self) -> np.ndarray:
        """Device-authoritative session counters for accounting/expiry."""
        return np.asarray(self.tables.nat.sessions.vals)

    # -- checkpoint/warm-restart support (runtime/checkpoint.py) ---------

    def quiesce(self) -> int:
        """Drain barrier for the engine-driven loops (no scheduler):
        retire any in-flight pipelined batch, then block until the
        threaded device table state has materialized — after this no
        scatter is in flight, so a checkpoint can fetch HBM arrays
        without interleaving with an update. Returns frames retired."""
        n = self.flush_pipeline()
        jax.block_until_ready(jax.tree_util.tree_leaves(self.tables))
        return n

    # -- blue/green engine swap support (runtime/ops.py) ------------------

    def adopt_device_tables(self, tables: PipelineTables) -> None:
        """Standby hydration: adopt a device pytree built from a
        checkpoint snapshot (via geometry-identical clone mirrors) in
        place of the init-time upload. Must be shape-identical to
        self.geom — callers hydrate through restore_checkpoint, whose
        verify gate already enforced that. This is the ONE sanctioned
        rebind of .tables outside the step/resync paths; the delta
        accumulated since the snapshot is replayed afterwards through
        the normal bounded update drain (ops.replay_delta_since)."""
        self.tables = tables

    def host_mirror_tables(self) -> dict:
        """{name: HostTable|HostQTable} of every sparse host mirror this
        engine drains — the delta-replay walk surface (runtime/ops.py).
        Dense config arrays (pools/server, spoof ranges, garden allowed,
        NAT hairpin/alg) are re-read wholesale on every drain and need
        no diffing."""
        out = {
            "fastpath/sub": self.fastpath.sub,
            "fastpath/vlan": self.fastpath.vlan,
            "fastpath/cid": self.fastpath.cid,
            "nat/sessions": self.nat.sessions,
            "nat/reverse": self.nat.reverse,
            "nat/sub_nat": self.nat.sub_nat,
            "qos/up": self.qos.up,
            "qos/down": self.qos.down,
            "antispoof/bindings": self.antispoof.bindings,
        }
        if self.garden is not None:
            out["garden/subscribers"] = self.garden.subscribers
        if self.pppoe is not None:
            out["pppoe/by_sid"] = self.pppoe.by_sid
            out["pppoe/by_ip"] = self.pppoe.by_ip
        if self.edge is not None:
            out["edge/tap"] = self.edge.tap
            out["edge/route"] = self.edge.route
        return out

    def pending_dirty(self) -> int:
        """Dirty slots across every drained host mirror — 0 means the
        device chain is current (the delta-replay completion test)."""
        return sum(t.dirty_count() for t in self.host_mirror_tables().values())

    @staticmethod
    def _uploaded_mask(table, live: np.ndarray) -> np.ndarray:
        """Slots whose host row has actually SHIPPED to the device: live
        minus the pending dirty set (a host insert the bounded drain has
        not scattered yet reads back as zeros/stale from HBM — folding
        it would destroy the newer host row). A _dirty_all table has
        shipped nothing since its bulk build."""
        if table._dirty_all:
            return np.zeros_like(live)
        if not table._dirty:
            return live
        mask = live.copy()
        mask[np.fromiter(table._dirty, dtype=np.int64,
                         count=len(table._dirty))] = False
        return mask

    def fold_device_authoritative(self) -> None:
        """Pull the device-WRITTEN words back into the host mirrors — the
        pre-checkpoint fetch. Two tables carry device-authoritative
        state: NAT session rows (counters + last_seen, written by the
        NAT44 kernel) and the QoS token buckets (tokens + last_us words
        of the packed way rows). Everything else is host-authoritative
        already. Only slots whose host row has shipped are folded (see
        _uploaded_mask); not-yet-drained host writes stay authoritative.
        Call behind quiesce(): a fetch that overlaps an in-flight
        scatter could tear a row."""
        from bng_tpu.ops.qtable import QW_FLAGS, QW_LAST_US, QW_TOKENS

        dev = self.fetch_session_vals()
        mask = self._uploaded_mask(self.nat.sessions,
                                   self.nat.sessions.used.astype(bool))
        self.nat.sessions.vals[mask] = dev[mask]
        for host, dev_rows in ((self.qos.up, self.tables.qos_up.rows),
                               (self.qos.down, self.tables.qos_down.rows)):
            rows = np.asarray(dev_rows)
            live = self._uploaded_mask(host,
                                       (host.rows[:, QW_FLAGS] & 1) != 0)
            host.rows[live, QW_TOKENS] = rows[live, QW_TOKENS]
            host.rows[live, QW_LAST_US] = rows[live, QW_LAST_US]

    def expire(self, now: int | None = None) -> int:
        now = int(now if now is not None else self.clock())
        return self.nat.expire_sessions(now, device_vals=self.fetch_session_vals())
