"""TPU-lowering verification gate — the verifier-harness analog.

The reference refuses to ship an eBPF program the kernel verifier rejects
(cmd/verify-bpf/main.go:58-112, bpf/test-verifier.sh). The TPU analog of
"passes the verifier" is "lowers through Mosaic/XLA for the TPU target":
round 2 proved interpret-mode tests are false confidence — ops/pallas_qos
passed its CPU suite while Mosaic rejected its block shapes on hardware.

`verify_tpu_lowering()` AOT-compiles every hot program for the attached
TPU: the fused pipeline step (engine jit, donated-update form), the QoS
kernel in BOTH prefix impls, the raw Pallas kernel, and the sharded
multi-chip step. Run it

  - as a pytest (tests/test_tpu_lowering.py, auto-skip off-TPU), and
  - as the bench pre-step: `python bench.py --verify-lowering`
    (bench also runs it automatically before the headline on TPU).

CI one-liner:  python bench.py --verify-lowering  (exit != 0 on failure)
"""

from __future__ import annotations

import traceback
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp


def _lower_compile(fn: Callable, *args, **jit_kw) -> None:
    jax.jit(fn, **jit_kw).lower(*args).compile()


def _check_qos(impl: str) -> None:
    import bng_tpu.ops.qos as qos_mod
    from bng_tpu.runtime.engine import QoSTables

    B = 256
    qos = QoSTables(nbuckets=256)
    for i in range(32):
        qos.set_subscriber((10 << 24) | (i + 2), down_bps=8_000_000, up_bps=8_000_000)
    table = qos.up.device_state()
    ips = jnp.asarray(((10 << 24) + 2 + np.arange(B) % 64).astype(np.uint32))
    lens = jnp.full((B,), 900, dtype=jnp.uint32)
    active = jnp.ones((B,), dtype=bool)

    old = qos_mod.PREFIX_IMPL
    qos_mod.PREFIX_IMPL = impl
    try:
        _lower_compile(
            lambda t, i, l: qos_mod.qos_kernel(i, l, active, t, qos.geom,
                                               jnp.uint32(1)).allowed,
            table, ips, lens)
    finally:
        qos_mod.PREFIX_IMPL = old


def _check_pallas_raw() -> None:
    from bng_tpu.ops.pallas_qos import seg_prefix_total

    B = 1024
    slot = jnp.asarray((np.arange(B) % 37).astype(np.int32))
    vec = jnp.full((B,), 900.0, dtype=jnp.float32)
    # interpret=False: force real Mosaic lowering
    jax.jit(lambda s, v: seg_prefix_total(s, v, interpret=False)
            ).lower(slot, vec).compile()


def _rep_table_state(nbuckets: int = 1 << 10, K: int = 2, V: int = 8,
                     stash: int = 64):
    """Representative populated table (the dhcp sub-table shape)."""
    from bng_tpu.ops.table import HostTable

    t = HostTable(nbuckets, K, V, stash=stash, name="verify")
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 2**32, size=(256, K), dtype=np.uint32)
    for k in np.unique(keys, axis=0):
        t.insert(k, np.arange(V, dtype=np.uint32))
    q = jnp.asarray(keys[:256])
    return t.device_state(), q, t.nbuckets, t.stash


def _check_table(impl: str, interpret: bool | None = None) -> None:
    """Compile the impl-dispatched probe (the surface every hot-path
    kernel funnels through). impl='pallas', interpret=False forces real
    Mosaic lowering — the TPU gate for the fused probe kernel."""
    from bng_tpu.ops import table as table_mod

    state, q, nb, stash = _rep_table_state()

    def look(state, q):
        with table_mod.forced_impl(impl):
            if impl == "pallas" and interpret is not None:
                from bng_tpu.ops.pallas_table import pallas_lookup

                r = pallas_lookup(state, q, nb, stash, interpret=interpret)
            else:
                from bng_tpu.ops.table import device_lookup

                r = device_lookup(state, q, nb, stash)
        return r.found, r.slot, r.vals

    _lower_compile(look, state, q)


def _check_dhcp_express(impl: str) -> None:
    """The express-lane OFFER program (donated chain + aliased packet
    batch) under one table impl — the program the 50us target gates."""
    from bng_tpu.runtime.engine import _dhcp_jit
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32

    B, L = 64, 512
    fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=256,
                        cid_nbuckets=256, max_pools=4, stash=64)
    fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
    step = _dhcp_jit(fp.geom, impl)
    step.lower(fp.device_tables(), fp.make_updates(),
               jnp.zeros((B, L), dtype=jnp.uint8),
               jnp.zeros((B,), dtype=jnp.uint32),
               np.uint32(1)).compile()


def _check_express_aot(impl: str) -> None:
    """The AOT express OFFER program (ISSUE 13): descriptor in, verdict
    block out, tables + descriptor donated. Exactly the lower+compile
    the serving path performs at scheduler init — a program that fails
    HERE would turn every express dispatch into a counted jit-full
    fallback, so the gate refuses it up front."""
    from bng_tpu.ops.express import XD_WORDS
    from bng_tpu.runtime.engine import _express_jit
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32

    B = 64
    fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=256,
                        cid_nbuckets=256, max_pools=4, stash=64)
    fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
    step = _express_jit(fp.geom, impl)
    step.lower(fp.device_tables(), fp.empty_updates(),
               jnp.zeros((B, XD_WORDS), dtype=jnp.uint32),
               jnp.uint32(1)).compile()


def _check_pipeline() -> None:
    from bng_tpu.control.nat import NATManager
    from bng_tpu.ops.pipeline import PipelineGeom, PipelineTables, pipeline_step
    from bng_tpu.runtime.engine import AntispoofTables, GardenTables, QoSTables
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32

    B, L = 256, 512
    fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=256,
                        cid_nbuckets=256, max_pools=4, stash=64)
    fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sub_nat_nbuckets=1 << 10)
    qos = QoSTables(nbuckets=256)
    spoof = AntispoofTables(nbuckets=256)
    garden = GardenTables(nbuckets=256)  # gate ON: compile the real program
    geom = PipelineGeom(dhcp=fp.geom, nat=nat.geom, qos=qos.geom,
                        spoof=spoof.geom, garden=garden.geom)
    tables = PipelineTables(
        dhcp=fp.device_tables(), nat=nat.device_tables(),
        qos_up=qos.up.device_state(), qos_down=qos.down.device_state(),
        spoof=spoof.bindings.device_state(),
        spoof_ranges=jnp.asarray(spoof.ranges),
        spoof_config=jnp.asarray(spoof.config),
        garden=garden.subscribers.device_state(),
        garden_allowed=jnp.asarray(garden.allowed),
    )
    pkt = jnp.zeros((B, L), dtype=jnp.uint8)
    ln = jnp.full((B,), 300, dtype=jnp.uint32)
    fa = jnp.ones((B,), dtype=bool)

    def step(tables, pkt, ln, fa):
        res = pipeline_step(tables, pkt, ln, fa, geom,
                            jnp.uint32(1), jnp.uint32(1))
        return res.verdict, res.tables

    _lower_compile(step, tables, pkt, ln, fa, donate_argnums=(0,))


def _check_sharded() -> None:
    """Sharded step over every attached device (n=1 on the bench chip —
    the 8-way variant is exercised by dryrun_multichip on the CPU mesh)."""
    from bng_tpu.parallel.sharded import ShardedCluster

    n = len(jax.devices())
    cl = ShardedCluster(n_shards=n, batch_per_shard=64)
    pkt = np.zeros((n * 64, 512), dtype=np.uint8)
    ln = np.full((n * 64,), 0, dtype=np.uint32)
    fa = np.ones((n * 64,), dtype=bool)
    cl.step(pkt, ln, fa, 1, 1)
    cl.dhcp_step(pkt, ln, 1)  # the sharded control fast lane too


# (name, check, tpu_only).  tpu_only checks force real Mosaic lowering and
# cannot run elsewhere; the rest also run on CPU so the *harness itself*
# (table constructors, kernel signatures) is exercised by the plain test
# suite — round 3 found the gate broken by NATManager API drift that the
# auto-skip had hidden.
CHECKS: list[tuple[str, Callable[[], None], bool]] = [
    ("qos_kernel[sort]", lambda: _check_qos("sort"), False),
    ("qos_kernel[pallas]", lambda: _check_qos("pallas"), True),
    ("pallas_seg_prefix_total", _check_pallas_raw, True),
    # the impl-dispatched cuckoo probe (ISSUE 11): the interp variant
    # exercises the Pallas harness on every backend; the compiled
    # variant is the Mosaic gate for the fused probe kernel
    ("table_lookup[xla]", lambda: _check_table("xla"), False),
    ("table_lookup[pallas-interp]",
     lambda: _check_table("pallas", interpret=True), False),
    ("table_lookup[pallas]",
     lambda: _check_table("pallas", interpret=False), True),
    ("dhcp_express[xla]", lambda: _check_dhcp_express("xla"), False),
    ("dhcp_express[pallas]", lambda: _check_dhcp_express("pallas"), True),
    # the AOT minimal OFFER program (ISSUE 13) — the architecture the
    # offer_device_only_p99_us gate measures on the express lane
    ("express_aot[xla]", lambda: _check_express_aot("xla"), False),
    ("express_aot[pallas]", lambda: _check_express_aot("pallas"), True),
    ("fused_pipeline_step", _check_pipeline, False),
    ("sharded_step", _check_sharded, False),
]


def verify_tpu_lowering(verbose: bool = True,
                        tpu: bool = True) -> list[tuple[str, str | None]]:
    """Compile every hot program for the attached backend.

    tpu=False (CPU test suite) skips the Mosaic-only checks but still
    compiles everything else, catching harness/API drift off-hardware.
    Returns [(name, None | error_string)]. Raises nothing; callers decide
    (pytest asserts, bench exits non-zero).
    """
    results: list[tuple[str, str | None]] = []
    for name, check, tpu_only in CHECKS:
        if tpu_only and not tpu:
            continue
        try:
            check()
            results.append((name, None))
            if verbose:
                print(f"  lowering OK   {name}")
        except Exception:
            err = traceback.format_exc(limit=3)
            results.append((name, err))
            if verbose:
                print(f"  lowering FAIL {name}\n{err}")
    return results
