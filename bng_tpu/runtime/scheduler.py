"""Latency-tiered dataplane scheduler: express DHCP + depth-pipelined bulk.

The round-5 verdict's architectural gap: the engine ran one monolithic
fused step, so a DHCP OFFER queued behind a 512-frame NAT44+QoS batch and
every benchmark blocked per step — conflating the axon tunnel's ~66 ms
completion-poll artifact (PERF_NOTES §1) with device time. The reference
BNG never sees this shape because per-packet XDP has no batches; an
inference server solves it with iteration-level scheduling and latency
classes (Orca-style continuous batching). This module is that scheduler
for the TPU re-host:

- **express lane** — frames classifying as genuine access-side DHCP
  (ring.classify_dhcp, the dhcp_fastpath.c parity classifier) run the
  pre-compiled DHCP-only program at a small fixed batch with
  deadline-based close: dispatch when full OR when the oldest frame has
  waited max_wait_us. The lane owns the authoritative device DHCP chain
  and, when >1 device is attached, its OWN device — so an express
  dispatch has neither a data dependency nor an execution-stream
  dependency on in-flight bulk work (XLA runs one FIFO stream per
  device; a same-device express dispatch would still queue behind an
  enqueued bulk step no matter how it is interleaved).

- **bulk lane** — everything else runs the fused NAT44+QoS+antispoof
  pipeline at large batch with depth-N async pipelining: dispatches
  enter a completion ring as futures and `block_until_ready` happens
  only when the ring overflows its depth (>= 2), never per step. The
  bulk program consumes a READ REPLICA of the dhcp tables (refreshed on
  a cadence), which is what breaks the data dependency: a bulk dispatch
  never rebinds the dhcp leaves the express program consumes.

The scheduler also owns the cadence of the engine's bounded table-update
drain: the express lane drains the fastpath delta before every dispatch
(an OFFER must see the newest lease), while bulk steps apply real
NAT/QoS/antispoof deltas only every `drain_every` dispatches and cached
no-op update batches in between (zero host->HBM traffic on non-drain
steps).

Single-process, poll-driven: `submit()` frames, `poll()` each beat (the
CLI run loop), or use `process()` — the batch-synchronous facade the
loadtest harness drives.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass
from typing import Callable, NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.control.dhcp_codec import ACK, DISCOVER, OFFER, ExpressTemplateCache
from bng_tpu.ops.dhcp import (PV_DNS1, PV_DNS2, PV_GATEWAY, PV_PREFIX,
                              SC_IP, SC_MAC_HI, SC_MAC_LO)
from bng_tpu.ops.express import (VB_LEASE_T, VB_POOL, VB_VERDICT, VB_YIADDR,
                                 XD_WORDS, parse_express)
from bng_tpu.ops.pipeline import VERDICT_DROP, VERDICT_FWD, VERDICT_TX
from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry.recorder import (TRIG_EXPRESS_AOT_MISS,
                                        TRIG_EXPRESS_FALLBACK)
from bng_tpu.runtime import hostpath
from bng_tpu.runtime.engine import _ExpressAotResult
from bng_tpu.runtime.lanes import (CLOSE_FLUSH, CompletionRing, InflightEntry,
                                   Lane, LaneConfig, LANE_BULK, LANE_EXPRESS)
from bng_tpu.runtime.ring import classify_dhcp
from bng_tpu.utils.net import prefix_to_mask
from bng_tpu.utils.structlog import get_logger


@dataclass
class SchedulerConfig:
    """Knobs for the two lanes + drain/replica cadences."""

    express_batch: int = 64
    express_max_wait_us: float = 200.0
    # depth-k pipelining on the fast lane: up to `express_depth` express
    # dispatches stay in flight inside one poll, so host-side retire
    # work (template patch-in, completions) overlaps device execution
    express_depth: int = 2
    # AOT express OFFER path (ISSUE 13): descriptors extracted at
    # admission, the minimal express program compiled ahead of time for
    # this lane's batch geometry, replies patched into preassembled
    # wire templates at retire. False = the jit full-program path
    # (also reachable via BNG_EXPRESS_AOT=0).
    express_aot: bool = True
    bulk_batch: int | None = None  # None = engine.B
    bulk_max_wait_us: float = 2000.0
    bulk_depth: int = 2  # completion-ring depth (>=2: never block per step)
    drain_every: int = 1  # bulk host-update drain cadence (1 = every step)
    # overlap-drain (VERDICT r5 item 3): build + upload the NEXT drain's
    # bounded scatter right after dispatching step N, so it overlaps with
    # step N's device execution instead of sitting on the batch-close ->
    # dispatch critical path of step N+1
    overlap_drain: bool = True
    dhcp_refresh_every: int = 16  # bulk dhcp-replica refresh cadence
    express_max_queue: int = 1 << 14
    bulk_max_queue: int = 1 << 16
    # express device isolation: None = auto (second attached device when
    # one exists, else share). An int pins jax.devices()[i]; -1 forces
    # same-device mode (single-chip: interleave-only isolation).
    express_device_index: int | None = None
    # device-resident express serving loop (ISSUE 18): "aot" = the
    # per-batch AOT lane (default until the devloop cohort baselines in
    # the perf ledger), "devloop" = the k-batch ring megakernel
    # (bng_tpu/devloop/), "auto" = devloop when its megakernel compiles,
    # aot otherwise. BNG_EXPRESS_LOOP overrides.
    express_loop: str = "aot"
    devloop_k: int = 8        # ring slots per megakernel dispatch
    devloop_depth: int = 2    # in-flight rings (async retire window)


class Completion(NamedTuple):
    """One frame's terminal outcome, delivered at retire time."""

    tag: object
    lane: str
    verdict: str  # "tx" | "fwd" | "drop" | "slow"
    frame: bytes | None  # device output (tx/fwd) or slow-path reply
    from_access: bool
    latency_s: float  # submit -> retire (queue wait + device + demux)


class TieredScheduler:
    """Owns the steady-state device loop over an Engine's two programs."""

    is_scheduler = True  # duck-type marker (loadtest harness routing)

    def __init__(self, engine, cfg: SchedulerConfig | None = None,
                 metrics=None, clock: Callable[[], float] | None = None):
        self.engine = engine
        self.cfg = cfg or SchedulerConfig()
        self.metrics = metrics
        self.clock = clock or engine.clock
        bulk_batch = self.cfg.bulk_batch or engine.B
        self.express = Lane(LaneConfig(
            LANE_EXPRESS, self.cfg.express_batch,
            self.cfg.express_max_wait_us, self.cfg.express_depth,
            self.cfg.express_max_queue), self.clock)
        self.bulk = Lane(LaneConfig(
            LANE_BULK, bulk_batch, self.cfg.bulk_max_wait_us,
            self.cfg.bulk_depth, self.cfg.bulk_max_queue), self.clock)
        self._express_ring = CompletionRing(self.cfg.express_depth)
        self._bulk_ring = CompletionRing(self.cfg.bulk_depth)
        self.completions: deque[Completion] = deque()
        self.completions_dropped = 0
        self.oversize_dropped = 0
        self._seq = 0
        # bulk-lane dhcp read replica (lazy; refreshed on cadence/resync)
        self._bulk_dhcp = None
        self._replica_resync = -1
        self._bulk_seq = 0
        self._drains_applied = 0
        self._drains_prefetched = 0
        # overlap-drain: the update batch built for the NEXT drain-due
        # bulk step (engine.prefetch_bulk_updates). The scheduler owns
        # it — _flush_prefetched() is the no-more-traffic safety net.
        self._prefetched_upd = None
        self._replica_refreshes = 0
        self._express_dev = self._pick_express_device()
        self._bulk_dev = jax.devices()[0]
        # AOT express path: compile the minimal program for THIS lane's
        # fixed batch geometry at init (never on the dispatch path). A
        # compile failure downgrades to the jit-full path loudly and
        # permanently — every subsequent express dispatch counts as an
        # AOT miss, so a silent downgrade is impossible.
        self._log = get_logger("scheduler")
        self.express_aot_misses = 0
        self.express_aot_dispatches = 0
        self.express_jit_dispatches = 0
        self._aot_enabled = (self.cfg.express_aot
                             and os.environ.get("BNG_EXPRESS_AOT") != "0")
        # express rung-fallback accounting (ISSUE 18 gray-failure
        # hardening): reason -> count, folded into
        # bng_express_fallback_total by control/metrics.py. Populated by
        # _note_fallback and the dispatch-time geometry-miss path — any
        # express serving rung below the one configured shows up here.
        self.express_fallbacks: dict[str, int] = {}
        self._devloop = None           # DevloopPump when the loop is live
        self.express_loop = "aot"      # the RESOLVED loop (cf. cfg wish)
        # _aot_ready gates the per-frame admission parse only: after a
        # permanent compile failure no executable will ever consume a
        # descriptor, so submit() must not keep paying parse_express on
        # the latency-critical path. Dispatch-side miss accounting keys
        # on _aot_enabled alone — the degraded state stays loud.
        self._aot_ready = False
        self._express_templates = ExpressTemplateCache()
        # host-path snapshot (ISSUE 14): vector = cycling descriptor
        # staging buffers (no per-dispatch np.zeros) + batched template
        # patch-in at the AOT express retire
        self._vec = hostpath.resolved_host_path() == "vector"
        # express_depth dispatches may be in flight plus one staging;
        # run_express_aot copies the staged rows to the device
        self._desc_bufs = [
            np.zeros((self.cfg.express_batch, XD_WORDS), dtype=np.uint32)
            for _ in range(self.cfg.express_depth + 2)]
        self._desc_i = 0
        self._ensure_engine_staging()
        if self._aot_enabled:
            self._compile_express_aot()
        self._setup_devloop()

    def _ensure_engine_staging(self) -> None:
        """Declare this scheduler's worst-case in-flight dispatch count
        to the engine's frame staging pool (vector host path): both
        lanes stage through it, the depths are configurable, and
        express_batch == bulk_batch would even share one B-keyed buffer
        ring — the pool must cycle past every dispatch that could still
        be reading a staged buffer."""
        pool = getattr(self.engine, "_stage_pool", None)
        if pool is not None:
            pool.ensure_depth(self.cfg.express_depth
                              + self.cfg.bulk_depth + 2)

    def _compile_express_aot(self) -> None:
        # reset FIRST: an adopt-time recompile failure (new engine
        # geometry that refuses to lower) must drop readiness from the
        # previous engine's success, or submit() keeps paying the
        # per-frame descriptor parse for a program that no longer exists
        self._aot_ready = False
        try:
            self.engine.compile_express_aot(self.express.cfg.batch,
                                            self._express_dev)
            self._aot_ready = True
        except Exception as e:  # noqa: BLE001 — downgrade, never brick
            # gray-failure hardening (ISSUE 18): before this, the
            # permanent downgrade only warn()ed once at setup — count it
            # and flight-record it so a cluster serving every OFFER
            # through the jit-full rung is visible in metrics, not just
            # in one scrollback line
            self._note_fallback(
                "compile_failed",
                f"express AOT compile failed, jit-full will serve: "
                f"{type(e).__name__}: {e}")

    def _note_fallback(self, reason: str, detail: str) -> None:
        """One express rung-fallback event: counted (per reason, for
        bng_express_fallback_total), flight-recorded (the
        backend_fallback discipline — evidence survives the process),
        and logged. Serving continues on the lower rung either way;
        this exists so it can never do so silently."""
        self.express_fallbacks[reason] = (
            self.express_fallbacks.get(reason, 0) + 1)
        tele.trigger(TRIG_EXPRESS_FALLBACK,
                     f"express fallback ({reason}): {detail}")
        self._log.warning("express fallback", reason=reason, detail=detail)

    def _setup_devloop(self) -> None:
        """Resolve + arm the express serving loop (ISSUE 18). The
        devloop megakernel compiles HERE (init / engine-adopt), never on
        the dispatch path; any refusal to arm falls back to the
        per-batch AOT lane loudly when devloop was explicitly asked
        for."""
        if self._devloop is not None:
            self._devloop.close()  # release the old pump's worker thread
        self._devloop = None
        want = os.environ.get("BNG_EXPRESS_LOOP", self.cfg.express_loop)
        if want not in ("aot", "devloop", "auto"):
            raise ValueError(
                f"BNG_EXPRESS_LOOP/express_loop must be aot|devloop|auto,"
                f" got {want!r}")
        self.express_loop = "aot"
        if want == "aot":
            return
        if not (self._aot_enabled and self._aot_ready):
            # no descriptors at admission -> nothing to stage in a ring;
            # explicit devloop requests degrade LOUDLY, auto quietly
            # (the compile-failure fallback above already fired)
            if want == "devloop":
                self._note_fallback(
                    "devloop_unavailable",
                    "devloop requires the AOT express lane (descriptor "
                    "admission); serving per-batch")
            return
        k = int(os.environ.get("BNG_DEVLOOP_K", self.cfg.devloop_k))
        try:
            self.engine.compile_devloop_aot(k, self.express.cfg.batch,
                                            self._express_dev)
        except Exception as e:  # noqa: BLE001 — downgrade, never brick
            self._note_fallback(
                "devloop_compile_failed",
                f"megakernel k={k} batch={self.express.cfg.batch} "
                f"refused to compile, per-batch AOT will serve: "
                f"{type(e).__name__}: {e}")
            return
        from bng_tpu.devloop.host import DevloopPump

        self._devloop = DevloopPump(self, k, self.cfg.devloop_depth)
        self.express_loop = "devloop"

    def _pick_express_device(self):
        idx = self.cfg.express_device_index
        devs = jax.devices()
        if idx is None:
            return devs[1] if len(devs) > 1 else None
        if idx < 0:
            return None
        return devs[idx]

    # -- ingress ---------------------------------------------------------

    def classify(self, frame: bytes, from_access: bool) -> str:
        """DHCP discover/request from the access side -> express;
        everything else -> bulk (the ring classifier, bit-for-bit the
        dhcp_fastpath.c attach condition)."""
        if from_access and classify_dhcp(frame):
            return LANE_EXPRESS
        return LANE_BULK

    def submit(self, frame: bytes, from_access: bool = True,
               now: float | None = None, tag: object = None,
               lane: str | None = None) -> str | None:
        """Classify + enqueue one frame. Returns the lane name, or None
        when the frame is dropped (lane over its backpressure bound, or
        frame larger than the engine's packet slot). Callers that already
        classified (the ring stamps FLAG_DHCP_CTRL at rx_push) pass
        `lane` to skip the second Python header parse."""
        now = now if now is not None else self.clock()
        if tag is None:
            tag = self._seq
        self._seq += 1
        if len(frame) > self.engine.L:
            # rings admit frames up to their frame_size, which can exceed
            # the engine slot; _pack_frames refuses to truncate silently,
            # so the drop (counted) happens here, not as a dispatch crash
            self.oversize_dropped += 1
            return None
        lane_name = lane or self.classify(frame, from_access)
        if lane_name == LANE_EXPRESS:
            # admission→dispatch bypass (ISSUE 13): the express
            # descriptor (MAC/xid/vlan/cid lane columns) is extracted
            # exactly once, HERE — batch close stages descriptor rows
            # straight to the device with no second peek at the frame
            # bytes. None (AOT off / frame the device would PASS anyway)
            # rides along and retires through the slow path.
            desc = parse_express(frame) if self._aot_ready else None
            ok = self.express.push(frame, from_access, now, tag, desc=desc)
            return LANE_EXPRESS if ok else None
        return lane_name if self.bulk.push(frame, from_access, now, tag) else None

    # -- the beat --------------------------------------------------------

    def poll(self, now: float | None = None) -> int:
        """One scheduler beat: express strictly first (an express dispatch
        is never queued behind a bulk close waiting in THIS beat), then
        bulk ring management. Returns frames retired."""
        now = now if now is not None else self.clock()
        retired = 0
        retired += self._pump_express(now)
        retired += self._pump_bulk(now)
        return retired

    def flush(self, now: float | None = None) -> int:
        """Ship every queued frame (partial batches close immediately)
        and retire everything in flight — the shutdown/test barrier."""
        now = now if now is not None else self.clock()
        retired = 0
        while len(self.express):
            # let the close policy label full/aged batches honestly; only
            # the partial tail is a forced flush close (the close-reason
            # stats feed the bench JSON — they must stay meaningful in
            # the process() facade, which flushes every batch)
            reason = self.express.close_reason(now) or CLOSE_FLUSH
            pend, reason = self.express.close_batch(now, reason)
            retired += self._dispatch_express(pend, now, reason)
        if self._devloop is not None:
            # ship the partial ring + retire every in-flight ring BEFORE
            # the per-batch ring drain: a devloop miss re-dispatches
            # slots through the direct path, which lands entries there
            retired += self._devloop.flush(now)
        retired += self._retire_express_all()
        while len(self.bulk):
            reason = self.bulk.close_reason(now) or CLOSE_FLUSH
            pend, reason = self.bulk.close_batch(now, reason)
            over = self._dispatch_bulk(pend, now, reason)
            if over is not None:
                retired += self._retire_bulk(over)
        for entry in self._bulk_ring.drain():
            retired += self._retire_bulk(entry)
        self._flush_prefetched()
        return retired

    close = flush  # CLI cleanup symmetry

    def _flush_prefetched(self) -> None:
        """Apply a prefetched drain no bulk batch consumed (traffic went
        quiet after the prefetch): its dirty slots are already drained
        host-side, so it MUST reach the device — a dropped batch would
        leave HBM stale behind healthy-looking host mirrors."""
        upd = self._prefetched_upd
        if upd is None:
            return
        self._prefetched_upd = None
        self.engine.apply_updates_now(upd)
        self._drains_applied += 1

    def quiesce(self, now: float | None = None) -> int:
        """Checkpoint drain barrier: ship every queued frame, retire every
        in-flight dispatch on BOTH completion rings (flush), then block
        until the threaded device table state (express dhcp chain AND the
        bulk-threaded tables) has materialized. After quiesce() returns,
        no table scatter is in flight and no pending FastPathUpdates wait
        in a dispatched-but-unretired step — a snapshot taken now can
        fetch the HBM arrays without interleaving with an update. The
        lanes stay usable; traffic resumes on the next submit/poll."""
        retired = self.flush(now)
        jax.block_until_ready(jax.tree_util.tree_leaves(self.engine.tables))
        if self._devloop is not None:
            # the ring's cursor handle materializes too: after quiesce
            # the devloop audit (cursor-vs-host agreement) is legal —
            # nothing in flight ahead of the handle, nothing donated
            jax.block_until_ready(self._devloop.ring.cursors)
        return retired

    def adopt_engine(self, engine) -> int:
        """Blue/green flip (runtime/ops.py): retire everything in flight
        against the OLD engine's programs, then atomically re-point both
        lanes at the standby. The bulk dhcp replica is invalidated — it
        derives from the old authoritative chain — and rebuilds from the
        new engine's leaves on the next bulk dispatch. Returns frames
        retired by the drain (the batches-deferred cost of the flip)."""
        retired = self.flush()
        self.engine = engine
        self._bulk_dhcp = None
        self._replica_resync = -1
        self._ensure_engine_staging()  # the standby's pool starts at
        # the construction default; re-declare this scheduler's depths
        if self._aot_enabled:
            # the standby's geometry usually matches (cache hit); a
            # changed geometry compiles here, at the flip, not on the
            # first post-flip dispatch
            self._compile_express_aot()
        # re-arm the serving loop against the standby's geometry — a
        # standby that refuses to lower the megakernel downgrades the
        # loop to per-batch AOT at the flip, loudly, never mid-dispatch
        self._setup_devloop()
        return retired

    # -- express lane ----------------------------------------------------

    def _pump_express(self, now: float) -> int:
        retired = 0
        while True:
            reason = self.express.close_reason(now)
            if reason is None:
                break
            pend, reason = self.express.close_batch(now, reason)
            retired += self._dispatch_express(pend, now, reason)
        if self._devloop is not None:
            # the loop's own beat: opportunistic ring retire + the ring
            # deadline close (a partial ring must not strand slots)
            retired += self._devloop.poll(now)
        return retired + self._retire_express_all()

    def _dispatch_express(self, pend, now: float, reason: str) -> int:
        """Route one closed express batch to the resolved serving loop:
        the devloop ring pump stages it as one ring slot (device touched
        once per k batches), the per-batch path dispatches immediately.
        Returns frames retired as a side effect (ring overflow)."""
        if not pend:
            return 0
        if self._devloop is not None:
            return self._devloop.add_batch(pend, now, reason)
        return self._dispatch_express_direct(pend, now, reason)

    def _dispatch_express_direct(self, pend, now: float,
                                 reason: str) -> int:
        """Dispatch one express batch per-batch; returns frames retired
        as a side effect of the completion ring overflowing its depth.

        AOT path: descriptor rows (staged at admission) go straight to
        the compiled minimal program. A geometry miss — the compiled
        executable for this batch shape is absent (compile failed, lane
        geometry changed under a live scheduler) — falls back to the
        jit-full `_dhcp_jit` path, counts `bng_express_aot_miss_total`
        (+ the bng_express_fallback_total family) and drops a
        flight-recorder note: a fallback storm can never masquerade as
        a healthy express hit."""
        if not pend:
            return 0
        eng = self.engine
        tok = tele.begin_batch(tele.LANE_EXPRESS_L, len(pend))
        if tok is not None:
            # lane wait of the batch's OLDEST frame — the worst case the
            # deadline close bounds (computed from enqueue stamps, so the
            # per-frame submit path pays no telemetry cost at all)
            tele.observe(tele.LANE_WAIT, (now - pend[0].enq_t) * 1e6, tok)
        exe = None
        if self._aot_enabled:
            # _aot_ready gate: pending frames carry descriptors only
            # when the init-time compile succeeded — an executable from
            # the shared cache must not serve descriptor-less frames
            exe = (eng.express_aot(self.express.cfg.batch,
                                   self._express_dev)
                   if self._aot_ready else None)
            if exe is None:
                self.express_aot_misses += 1
                # counted into the rung-fallback family too (no extra
                # log line — a miss storm already triggers per batch)
                self.express_fallbacks["geometry_miss"] = (
                    self.express_fallbacks.get("geometry_miss", 0) + 1)
                tele.trigger(TRIG_EXPRESS_AOT_MISS,
                             f"no compiled express program for batch="
                             f"{self.express.cfg.batch} impl="
                             f"{eng.table_impl}: jit-full fallback served")
        t0 = tele.t()
        cfg_epoch = None
        try:
            if exe is not None:
                # descriptor rows staged into a cycling preallocated
                # buffer (run_express_aot copies host->device, so the
                # buffer is free to rewrite after depth+1 dispatches);
                # the fill is ONE stacked numpy assignment, not a
                # per-frame copy loop
                desc = self._desc_bufs[self._desc_i]
                self._desc_i = (self._desc_i + 1) % len(self._desc_bufs)
                desc[:] = 0
                rows = [p.desc.words for p in pend if p.desc is not None]
                if rows:
                    idxs = [i for i, p in enumerate(pend)
                            if p.desc is not None]
                    desc[idxs] = rows
                res = eng.run_express_aot(exe, desc, now,
                                          device=self._express_dev)
                # snapshot the pool/server config of THIS dispatch's
                # table epoch: the retire (one poll later at depth>1)
                # must render from the rows the device verdict saw, not
                # from mirrors a control-plane write may have moved on
                cfg_epoch = (eng.fastpath.pools.copy(),
                             eng.fastpath.server.copy())
                self.express_aot_dispatches += 1
                tele.set_meta("express_program", "aot-express")
            else:
                pkt, length = eng._pack_frames([p.frame for p in pend],
                                               self.express.cfg.batch)
                res = eng._run_dhcp_batch(pkt, length, now,
                                          device=self._express_dev)
                self.express_jit_dispatches += 1
                tele.set_meta("express_program", "jit-full")
        except BaseException:
            tele.cancel_batch(tok)  # a failed dispatch must not leak a slot
            raise
        tele.lap(tele.DISPATCH, t0, tok)
        self._observe_dispatch(LANE_EXPRESS, len(pend), reason)
        over = self._express_ring.push(
            InflightEntry(res, pend, now, reason, trace=tok,
                          meta=cfg_epoch))
        return self._retire_express(over) if over is not None else 0

    def _retire_express_all(self) -> int:
        n = 0
        while True:
            entry = self._express_ring.pop_oldest()
            if entry is None:
                return n
            n += self._retire_express(entry)

    def _retire_express(self, entry: InflightEntry) -> int:
        """Force + demux one express batch (TX replies / PASS to the slow
        path). Blocks only on the express program's own outputs."""
        if isinstance(entry.res, _ExpressAotResult):
            return self._retire_express_aot(entry)
        eng = self.engine
        res = entry.res
        n = len(entry.pending)
        tele.focus(entry.trace)
        t0 = tele.t()
        verdict = np.asarray(res.verdict)[:n]
        out_len = np.asarray(res.out_len)
        tele.lap(tele.DEVICE_WAIT, t0, entry.trace)
        out_rows = None
        eng._fold_stats(res)
        now = self.clock()
        # batched slow-path fan-out (the fleet hook): collect every
        # PASS lane, drain once, replies re-merged in lane order — the
        # per-frame enqueue time rides along for deadline shedding
        slow_items = [(i, p.frame, p.enq_t)
                      for i, p in enumerate(entry.pending)
                      if verdict[i] != VERDICT_TX]
        replies = dict(eng._handle_slow_lanes(slow_items,
                                              path="sched_express"))
        t0 = tele.t()
        for i, p in enumerate(entry.pending):
            if verdict[i] == VERDICT_TX:
                if out_rows is None:
                    out_rows = np.asarray(res.out_pkt)
                frame = bytes(out_rows[i, : int(out_len[i])])
                eng.stats.tx += 1
                self._complete(p, LANE_EXPRESS, "tx", frame, now)
            else:
                eng.stats.passed += 1
                self._complete(p, LANE_EXPRESS, "slow", replies.get(i), now)
        tele.lap(tele.REPLY, t0, entry.trace)
        tele.end_batch(entry.trace)
        self._observe_retire(LANE_EXPRESS, entry, now)
        return n

    def _retire_express_aot(self, entry: InflightEntry) -> int:
        """Retire one AOT express batch: force the verdict block, patch
        on-device answers into preassembled wire templates
        (control/dhcp_codec.ExpressWireTemplate — UNCONDITIONALLY; the
        express retire path never re-enters the generic per-option
        reply encode), hand the rest to the slow path."""
        eng = self.engine
        n = len(entry.pending)
        tele.focus(entry.trace)
        t0 = tele.t()
        block = np.asarray(entry.res.block)[:n]
        tele.lap(tele.DEVICE_WAIT, t0, entry.trace)
        eng._fold_stats(entry.res)
        now = self.clock()
        slow_items = [(i, p.frame, p.enq_t)
                      for i, p in enumerate(entry.pending)
                      if not block[i, VB_VERDICT]]
        replies = dict(eng._handle_slow_lanes(slow_items,
                                              path="sched_express"))
        t0 = tele.t()
        pools, server = entry.meta  # the dispatch-epoch config snapshot
        txr = (self._express_replies_vec(entry.pending, block, pools,
                                         server) if self._vec else None)
        for i, p in enumerate(entry.pending):
            if block[i, VB_VERDICT]:
                eng.stats.tx += 1
                self._complete(p, LANE_EXPRESS, "tx",
                               txr[i] if txr is not None else
                               self._express_reply(p, block[i], pools,
                                                   server), now)
            else:
                eng.stats.passed += 1
                self._complete(p, LANE_EXPRESS, "slow", replies.get(i), now)
        tele.lap(tele.REPLY, t0, entry.trace)
        tele.end_batch(entry.trace)
        self._observe_retire(LANE_EXPRESS, entry, now)
        return n

    def _express_replies_vec(self, pend, block: np.ndarray,
                             pools: np.ndarray,
                             server: np.ndarray) -> dict:
        """Batched express reply render (ISSUE 14): TX lanes grouped by
        (template, addressing) identity — one storm batch is typically
        ONE group — then each group's per-client words are patched in a
        single vectorized pass (ExpressWireTemplate.render_batch,
        byte-identical to the per-frame render). Returns lane->bytes."""
        server_ip0 = int(server[SC_IP])
        server_mac = (int(server[SC_MAC_HI]).to_bytes(2, "big")
                      + int(server[SC_MAC_LO]).to_bytes(4, "big"))
        groups: dict[tuple, list] = {}
        for i, p in enumerate(pend):
            if block[i, VB_VERDICT]:
                d = p.desc
                groups.setdefault(
                    (int(block[i, VB_POOL]), int(block[i, VB_LEASE_T]),
                     d.msg_type, d.vlan_off, d.dhcp_off, d.relayed,
                     d.use_bcast), []).append(i)
        out: dict[int, bytes] = {}
        for key, lanes in groups.items():
            (pool_id, lease_t, msg, vlan_off, dhcp_off, relayed,
             use_bcast) = key
            prow = pools[pool_id]
            tmpl = self._express_templates.get(
                server_mac, server_ip0 or int(prow[PV_GATEWAY]),
                int(prow[PV_GATEWAY]), int(prow[PV_DNS1]),
                int(prow[PV_DNS2]), lease_t,
                prefix_to_mask(int(prow[PV_PREFIX])),
                OFFER if msg == DISCOVER else ACK)
            fmat, _l = hostpath.pack_rows([pend[i].frame for i in lanes])
            reps = tmpl.render_batch(
                fmat, vlan_off, dhcp_off, relayed, use_bcast,
                block[np.asarray(lanes, dtype=np.int64), VB_YIADDR])
            out.update(zip(lanes, reps))
        return out

    def _express_reply(self, p, row: np.ndarray, pools: np.ndarray,
                       server: np.ndarray) -> bytes:
        """One verdict row -> reply bytes: select the per-(pool, reply
        type) wire template and patch the per-client words. Pool/server
        config comes from the DISPATCH-EPOCH snapshot (the device
        pools/server arrays were refreshed from exactly those rows at
        dispatch; reading the live mirrors here could mix a newer
        config into a verdict computed against the old one); the lease
        words come from the DEVICE-reported block, so the rendered
        lease triplet always reflects the serving table."""
        prow = pools[int(row[VB_POOL])]
        server_ip = int(server[SC_IP]) or int(prow[PV_GATEWAY])
        server_mac = (int(server[SC_MAC_HI]).to_bytes(2, "big")
                      + int(server[SC_MAC_LO]).to_bytes(4, "big"))
        d = p.desc
        tmpl = self._express_templates.get(
            server_mac, server_ip, int(prow[PV_GATEWAY]),
            int(prow[PV_DNS1]), int(prow[PV_DNS2]), int(row[VB_LEASE_T]),
            prefix_to_mask(int(prow[PV_PREFIX])),
            OFFER if d.msg_type == DISCOVER else ACK)
        return tmpl.render(p.frame, d.vlan_off, d.dhcp_off, d.relayed,
                           d.use_bcast, int(row[VB_YIADDR]))

    # -- bulk lane -------------------------------------------------------

    def _pump_bulk(self, now: float) -> int:
        retired = 0
        # opportunistic: retire the already-finished FIFO prefix
        for entry in self._bulk_ring.pop_ready(self._entry_ready):
            retired += self._retire_bulk(entry)
        while True:
            reason = self.bulk.close_reason(now)
            if reason is None:
                break
            pend, reason = self.bulk.close_batch(now, reason)
            over = self._dispatch_bulk(pend, now, reason)
            if over is not None:
                # the completion ring overflowed its depth: the single
                # place the bulk lane blocks on device results
                retired += self._retire_bulk(over)
        return retired

    @staticmethod
    def _entry_ready(entry: InflightEntry) -> bool:
        is_ready = getattr(entry.res.verdict, "is_ready", None)
        return bool(is_ready()) if is_ready is not None else False

    def _ensure_bulk_replica(self) -> None:
        eng = self.engine
        refresh_due = (self.cfg.dhcp_refresh_every > 0
                       and self._bulk_seq % self.cfg.dhcp_refresh_every == 0)
        if (self._bulk_dhcp is not None and not refresh_due
                and self._replica_resync == eng.resync_count):
            return
        self._bulk_dhcp = jax.tree_util.tree_map(self._copy_to_bulk,
                                                 eng.tables.dhcp)
        self._replica_resync = eng.resync_count
        self._replica_refreshes += 1

    def _copy_to_bulk(self, x):
        """A buffer the bulk chain may freely donate: device transfer when
        the authority lives elsewhere, a fresh same-device copy otherwise
        (device_put to the same device can alias, and donating an aliased
        buffer would consume the express chain's live tables)."""
        if self._bulk_dev not in x.devices():
            return jax.device_put(x, self._bulk_dev)
        return jnp.copy(x)

    def _dispatch_bulk(self, pend, now: float,
                       reason: str) -> InflightEntry | None:
        """Dispatch one bulk batch (async); returns the completion-ring
        overflow entry the caller must retire, if any."""
        if not pend:
            return None
        eng = self.engine
        tok = tele.begin_batch(tele.LANE_BULK_L, len(pend))
        if tok is not None:
            tele.observe(tele.LANE_WAIT, (now - pend[0].enq_t) * 1e6, tok)
        B = self.bulk.cfg.batch
        pkt, length = eng._pack_frames([p.frame for p in pend], B)
        fa = np.zeros((B,), dtype=bool)
        fa[: len(pend)] = [p.from_access for p in pend]
        t0 = tele.t()
        try:
            self._ensure_bulk_replica()
            # a pending prefetched drain is consumed the moment a bulk
            # step ships, whatever the cadence says — stranding it would
            # desync host mirrors from HBM (its dirty slots are already
            # drained host-side)
            upd = self._prefetched_upd
            self._prefetched_upd = None
            drain = (upd is not None
                     or self.cfg.drain_every <= 1
                     or self._bulk_seq % self.cfg.drain_every == 0)
            before = eng.resync_count
            try:
                res, self._bulk_dhcp = eng.dispatch_scheduled_bulk(
                    pkt, length, fa, now, self._bulk_dhcp, drain=drain,
                    upd=upd)
            except BaseException:
                # the batch is lost but the prefetched drain must not be:
                # its dirty slots are already drained host-side, so it
                # re-queues for the next dispatch (or _flush_prefetched)
                self._prefetched_upd = upd
                raise
        except BaseException:
            tele.cancel_batch(tok)  # a failed dispatch must not leak a slot
            raise
        tele.lap(tele.DISPATCH, t0, tok)
        if eng.resync_count != before:
            # a bulk-build resync fired inside the drain: the replica we
            # just threaded derives from pre-resync leaves; rebuild next
            # dispatch (this step's results stay valid)
            self._replica_resync = -1
        self._bulk_seq += 1
        if drain:
            self._drains_applied += 1
        if (self.cfg.overlap_drain
                and (self.cfg.drain_every <= 1
                     or self._bulk_seq % self.cfg.drain_every == 0)):
            # step N is on the device; build + start uploading step N+1's
            # bounded scatter NOW so the next dispatch pays no drain cost
            self._prefetched_upd = eng.prefetch_bulk_updates()
            self._drains_prefetched += 1
        self._observe_dispatch(LANE_BULK, len(pend), reason)
        return self._bulk_ring.push(
            InflightEntry(res, pend, now, reason, trace=tok))

    def _retire_bulk(self, entry: InflightEntry) -> int:
        """Force + demux one bulk batch's verdicts (the completion-ring
        block point)."""
        eng = self.engine
        res = entry.res
        n = len(entry.pending)
        tele.focus(entry.trace)
        t0 = tele.t()
        vv = np.asarray(res.verdict)[:n]
        out_len = np.asarray(res.out_len)
        punt = np.asarray(res.nat_punt)[:n]
        viol = np.asarray(res.spoof_violation)[:n]
        tele.lap(tele.DEVICE_WAIT, t0, entry.trace)
        out_rows = None
        eng._fold_stats(res)
        now = self.clock()
        # NAT punts stay inline (parent-owned manager); everything else
        # drains through the batched slow path in one fan-out
        slow_items = []
        punts = 0
        for i, p in enumerate(entry.pending):
            if int(vv[i]) in (VERDICT_TX, VERDICT_FWD, VERDICT_DROP):
                continue
            if punt[i]:
                punts += 1
                try:
                    eng._punt_new_flow(p.frame, int(entry.dispatch_t))
                except Exception as e:  # noqa: BLE001 — untrusted input
                    eng.stats.slow_errors += 1
                    eng._slow_err_log.report(e, path="sched_bulk", lane=i)
            else:
                slow_items.append((i, p.frame, p.enq_t))
        replies = dict(eng._handle_slow_lanes(slow_items, path="sched_bulk"))
        t0 = tele.t()
        for i, p in enumerate(entry.pending):
            v = int(vv[i])
            if v == VERDICT_TX or v == VERDICT_FWD:
                if out_rows is None:
                    out_rows = np.asarray(res.out_pkt)
                frame = bytes(out_rows[i, : int(out_len[i])])
                kind = "tx" if v == VERDICT_TX else "fwd"
                if v == VERDICT_TX:
                    eng.stats.tx += 1
                else:
                    eng.stats.fwd += 1
                self._complete(p, LANE_BULK, kind, frame, now)
            elif v == VERDICT_DROP:
                eng.stats.dropped += 1
                self._complete(p, LANE_BULK, "drop", None, now)
            else:
                eng.stats.passed += 1
                self._complete(p, LANE_BULK, "slow", replies.get(i), now)
            if viol[i] and eng.violation_sink is not None:
                eng.violation_sink(i, p.frame)
        tele.lap(tele.REPLY, t0, entry.trace)
        tele.end_batch(entry.trace, punt=punts)
        self._observe_retire(LANE_BULK, entry, now)
        return n

    # -- completion delivery / observability -----------------------------

    _COMPLETIONS_CAP = 1 << 17

    def _complete(self, p, lane: str, verdict: str, frame, now: float) -> None:
        if len(self.completions) >= self._COMPLETIONS_CAP:
            self.completions.popleft()
            self.completions_dropped += 1
        self.completions.append(Completion(
            p.tag, lane, verdict, frame, p.from_access, now - p.enq_t))

    def drain_completions(self) -> list[Completion]:
        out = list(self.completions)
        self.completions.clear()
        return out

    def _observe_dispatch(self, lane: str, n: int, reason: str) -> None:
        m = self.metrics
        if m is None:
            return
        batch = (self.express if lane == LANE_EXPRESS else self.bulk).cfg.batch
        m.sched_dispatches.inc(lane=lane, close=reason)
        m.sched_batch_occupancy.observe(n / batch, lane=lane)

    def _observe_retire(self, lane: str, entry: InflightEntry,
                        now: float) -> None:
        m = self.metrics
        if m is None:
            return
        # oldest frame of the batch = the batch's worst-case latency
        if entry.pending:
            m.sched_dispatch_latency.observe(now - entry.pending[0].enq_t,
                                             lane=lane)
        m.sched_frames.inc(len(entry.pending), lane=lane)

    def stats_snapshot(self) -> dict:
        """Poll-style counters for metrics collection / bench JSON."""
        out = {}
        for name, lane, ring in ((LANE_EXPRESS, self.express, self._express_ring),
                                 (LANE_BULK, self.bulk, self._bulk_ring)):
            s = lane.stats
            out[name] = {
                "queue_depth": len(lane),
                "inflight": len(ring),
                "enqueued": s.enqueued,
                "dropped_overflow": s.dropped_overflow,
                "frames_dispatched": s.frames_dispatched,
                "batches": s.batches,
                "batches_full": s.batches_full,
                "batches_deadline": s.batches_deadline,
                "batches_flush": s.batches_flush,
                "occupancy_avg": round(s.occupancy_avg(), 4),
            }
        out["bulk"]["drains_applied"] = self._drains_applied
        out["bulk"]["drains_prefetched"] = self._drains_prefetched
        out["bulk"]["replica_refreshes"] = self._replica_refreshes
        out["express"]["own_device"] = (str(self._express_dev)
                                        if self._express_dev is not None
                                        else None)
        out["express"]["aot_enabled"] = self._aot_enabled
        out["express"]["aot_dispatches"] = self.express_aot_dispatches
        out["express"]["jit_dispatches"] = self.express_jit_dispatches
        out["express"]["aot_misses"] = self.express_aot_misses
        out["express"]["loop"] = self.express_loop
        out["express"]["fallbacks"] = dict(self.express_fallbacks)
        if self._devloop is not None:
            out["express"]["devloop"] = self._devloop.stats()
        out["completions_dropped"] = self.completions_dropped
        out["oversize_dropped"] = self.oversize_dropped
        return out

    # -- batch-synchronous facade (loadtest harness / tests) -------------

    # Engine.process-shaped surface so DHCPBenchmark can drive the
    # scheduler unmodified (it reads .stats/.fastpath for counters).
    @property
    def stats(self):
        return self.engine.stats

    @property
    def fastpath(self):
        return self.engine.fastpath

    def process(self, frames: list[bytes],
                from_access: list[bool] | bool = True,
                now: float | None = None) -> dict:
        """Submit a frame list, flush, and return Engine.process-shaped
        verdict lists keyed by submission index. The express/bulk split
        still applies inside — a mixed batch fans out to both programs."""
        out = {"tx": [], "fwd": [], "dropped": [], "slow": []}
        start = self._seq
        for i, f in enumerate(frames):
            fa = from_access if isinstance(from_access, bool) else from_access[i]
            if self.submit(f, fa, now=now) is None:
                out["dropped"].append(i)
        self.flush(now=now)
        for c in self.drain_completions():
            if not isinstance(c.tag, int) or c.tag < start:
                continue  # a stray completion from earlier poll-mode use
            i = c.tag - start
            if c.verdict in ("tx", "fwd"):
                out[c.verdict].append((i, c.frame))
            elif c.verdict == "drop":
                out["dropped"].append(i)
            else:
                out["slow"].append((i, c.frame))
        for k in ("tx", "fwd", "slow"):
            out[k].sort(key=lambda t: t[0])
        out["dropped"].sort()
        return out
