"""Zero-downtime engine operations: blue/green swap with delta replay.

Changing engine state used to mean restart-with-checkpoint — every
config or recovery action was an outage. This module turns the PR 2-4
machinery (quiesce barrier, versioned snapshot codec, invariant auditor)
into an ONLINE operation:

1. **snapshot** — at the quiesce barrier (scheduler.quiesce() or
   engine.quiesce(); nothing in flight, device-authoritative words
   folded back), build an in-memory checkpoint of every engine-owned
   host mirror and round-trip it through the encode/verify/decode codec
   (`roundtrip_checkpoint`) — the same rejection surface as the disk
   path, so a snapshot that could never restore aborts the swap here.

2. **hydrate** — build geometry-identical CLONE mirrors, restore the
   snapshot into them through the normal all-verified-then-hydrate gate,
   and upload them as the STANDBY engine's device chain. The standby
   shares the live host managers (they are the single-writer authority
   and are not being swapped); only its device pytree comes from the
   snapshot.

3. **delta replay** — host mirrors kept moving while the standby
   hydrated. `replay_delta_since` diffs every sparse host mirror against
   the snapshot arrays, marks exactly the changed slots dirty, and ships
   them to the standby chain through the SAME bounded update drain every
   other table producer uses (single-writer discipline preserved; a
   bulk-sized delta falls back to one resync_tables upload).

4. **audit + flip/rollback** — the cross-authority auditor is the
   steady-state hypothesis (Chaos Engineering, PAPERS.md): the standby
   must prove host==device and every ownership invariant BEFORE it
   serves. On a clean audit the flip is atomic at the barrier: the
   composition root's engine reference and the scheduler's lanes
   re-point in one step (callers hold the app's control lock). On any
   violation — or a chaos `ops.swap` fail, or a snapshot/restore
   reject — the standby is discarded, the ACTIVE engine is re-synced
   (healing any delta the replay already consumed) and keeps serving.

Fault points: `ops.snapshot` (io_error, in roundtrip_checkpoint) and
`ops.swap` (fail, at the flip barrier). Chaos scenario:
`engine_swap_crash_rollback` (chaos/scenarios.py).
"""

from __future__ import annotations

import time

import numpy as np

from bng_tpu.chaos.faults import FaultInjectedError, fault_point
from bng_tpu.runtime.checkpoint import (CheckpointError, build_checkpoint,
                                        restore_checkpoint,
                                        roundtrip_checkpoint)
from bng_tpu.telemetry import spans as tele
from bng_tpu.utils.structlog import get_logger

_log = get_logger("ops.swap")

# bounded drain passes for the delta replay: update_slots per table per
# step, so this covers update_slots * max steps changed rows before the
# resync fallback takes over
MAX_REPLAY_STEPS = 256


def clone_mirrors(engine) -> dict:
    """Fresh, EMPTY host-mirror objects geometry-identical to the
    engine's — the hydration targets for the standby's device chain.
    Only components the engine actually has are cloned (restore rejects
    a component with no target, and rightly so)."""
    from bng_tpu.control.nat import NATManager
    from bng_tpu.runtime.engine import AntispoofTables, GardenTables, QoSTables
    from bng_tpu.runtime.tables import FastPathTables, PPPoEFastPathTables

    fp = engine.fastpath
    nat = engine.nat
    out = {
        "fastpath": FastPathTables(
            sub_nbuckets=fp.sub.nbuckets, vlan_nbuckets=fp.vlan.nbuckets,
            cid_nbuckets=fp.cid.nbuckets, max_pools=len(fp.pools),
            stash=fp.sub.stash, update_slots=fp.update_slots),
        "nat": NATManager(
            public_ips=list(nat.public_ips),
            ports_per_subscriber=nat.ports_per_subscriber,
            port_range=tuple(nat.port_range), flags=nat.flags,
            sessions_nbuckets=nat.sessions.nbuckets,
            sub_nat_nbuckets=nat.sub_nat.nbuckets,
            stash=nat.sessions.stash, update_slots=nat.update_slots),
        "qos": QoSTables(nbuckets=engine.qos.up.nbuckets,
                         update_slots=engine.qos.update_slots),
        "antispoof": AntispoofTables(
            nbuckets=engine.antispoof.bindings.nbuckets,
            stash=engine.antispoof.bindings.stash,
            update_slots=engine.antispoof.update_slots),
    }
    if engine.garden is not None:
        out["garden"] = GardenTables(
            nbuckets=engine.garden.subscribers.nbuckets,
            stash=engine.garden.subscribers.stash,
            update_slots=engine.garden.update_slots,
            max_allowed=engine.garden.allowed.shape[0])
    if engine.pppoe is not None:
        out["pppoe"] = PPPoEFastPathTables(
            nbuckets=engine.pppoe.by_sid.nbuckets,
            stash=engine.pppoe.by_sid.stash,
            update_slots=engine.pppoe.update_slots)
    return out


def _changed_slots(table, arrays: dict, name: str) -> np.ndarray:
    """Slot indexes whose host row differs from the snapshot arrays.
    A table absent from the snapshot (shouldn't happen — the snapshot
    came from the same engine) degrades to every occupied slot."""
    if hasattr(table, "keys"):  # HostTable
        snap_k = arrays.get(f"{name}.keys")
        snap_v = arrays.get(f"{name}.vals")
        snap_u = arrays.get(f"{name}.used")
        if snap_k is None or snap_v is None or snap_u is None:
            return np.nonzero(table.used)[0]
        changed = ((table.keys != snap_k).any(axis=1)
                   | (table.vals != snap_v).any(axis=1)
                   | (table.used != snap_u))
        return np.nonzero(changed)[0]
    # HostQTable: one packed row array
    snap_r = arrays.get(f"{name}.rows")
    if snap_r is None:
        return np.nonzero(table.rows.any(axis=1))[0]
    return np.nonzero((table.rows != snap_r).any(axis=1))[0]


def replay_delta_since(engine, arrays: dict,
                       max_steps: int = MAX_REPLAY_STEPS) -> dict:
    """Ship every host-mirror row that changed since `arrays` (a
    checkpoint's array dict) to the engine's device chain through the
    normal bounded update drain. The engine's chain is assumed to be AT
    the snapshot state (adopt_device_tables); after this it is current.

    Returns {"rows": slots re-shipped, "steps": empty drain steps run,
    "resync": whether a bulk-sized delta forced one full upload}.
    """
    rows = 0
    resync = False
    for name, table in engine.host_mirror_tables().items():
        if table._dirty_all:
            resync = True
            continue
        rows += table.mark_dirty(_changed_slots(table, arrays, name))
    if resync:
        # a bulk build happened during hydration: bounded deltas can't
        # express it — one full upload, the same path a cold start takes
        engine.resync_tables()
        return {"rows": rows, "steps": 0, "resync": True}
    steps = 0
    while engine.pending_dirty() > 0 and steps < max_steps:
        # an empty batch runs the full update drain and nothing else —
        # the cheapest way to ship deltas without a second drain path
        engine.process([])
        steps += 1
    if engine.pending_dirty() > 0:
        raise CheckpointError(
            f"delta replay did not converge in {max_steps} steps "
            f"({engine.pending_dirty()} slots still dirty)")
    return {"rows": rows, "steps": steps, "resync": False}


def blue_green_swap(components, *, audit: bool = True, metrics=None,
                    node_id: str = "bluegreen") -> dict:
    """Hydrate a standby engine from an in-memory snapshot, replay the
    delta, audit, and flip — or roll back with the active untouched.

    `components` is the composition root's dict (BNGApp.components or a
    scenario-built equivalent): needs "engine"; uses "scheduler",
    "pools", "dhcp", "fleet" when present. On success
    components["engine"] IS the standby. Callers serialize against the
    dataplane loop (BNGApp holds _ctl); the flip itself is one dict
    store + one scheduler re-point at the quiesce barrier.
    """
    from bng_tpu.runtime.engine import Engine

    eng = components["engine"]
    sched = components.get("scheduler")
    report: dict = {"op": "engine_swap", "outcome": "failed"}
    t_all = time.perf_counter()
    consumed_delta = False
    try:
        # 1. quiesce + in-memory snapshot (codec round-trip verified)
        t0 = tele.t()
        t_q = time.perf_counter()
        deferred = sched.quiesce() if sched is not None else eng.quiesce()
        eng.fold_device_authoritative()
        report["frames_deferred"] = deferred
        ckpt = build_checkpoint(
            0, eng.clock(), fastpath=eng.fastpath, nat=eng.nat, qos=eng.qos,
            antispoof=eng.antispoof, garden=eng.garden, pppoe=eng.pppoe,
            node_id=node_id)
        ckpt = roundtrip_checkpoint(ckpt)  # ops.snapshot chaos point
        report["quiesce_s"] = time.perf_counter() - t_q
        tele.lap(tele.OPS, t0)

        # 2. standby hydration: clone mirrors -> verified restore ->
        # device upload; the standby engine shares the LIVE host
        # managers (they stay the single-writer authority) and adopts
        # the snapshot-built device chain in place of its init upload.
        t0 = tele.t()
        t_h = time.perf_counter()
        tmp = clone_mirrors(eng)
        report["restored_rows"] = restore_checkpoint(ckpt, **tmp)
        hydrator = Engine(
            tmp["fastpath"], tmp["nat"], qos=tmp["qos"],
            antispoof=tmp["antispoof"], garden=tmp.get("garden"),
            pppoe=tmp.get("pppoe"), batch_size=eng.B, pkt_slot=eng.L,
            clock=eng.clock)
        standby = Engine(
            eng.fastpath, eng.nat, qos=eng.qos, antispoof=eng.antispoof,
            garden=eng.garden, pppoe=eng.pppoe, batch_size=eng.B,
            pkt_slot=eng.L, slow_path=eng.slow_path,
            violation_sink=eng.violation_sink, clock=eng.clock,
            device_tables=hydrator.tables)
        standby.slow_path_batch = eng.slow_path_batch
        standby.stats = eng.stats  # operational counters never reset
        report["hydrate_s"] = time.perf_counter() - t_h
        tele.lap(tele.OPS, t0)

        # 3. delta replay at the barrier: host mirrors moved while the
        # standby hydrated; ship exactly the changed slots
        t0 = tele.t()
        consumed_delta = True
        delta = replay_delta_since(standby, ckpt.arrays)
        report["delta_rows"] = delta["rows"]
        report["delta_steps"] = delta["steps"]
        report["delta_resync"] = delta["resync"]
        tele.lap(tele.OPS, t0)

        # 4. chaos flip barrier + audit — the steady-state hypothesis
        fp = fault_point("ops.swap")
        if fp is not None and fp.kind == "fail":
            raise FaultInjectedError("chaos: injected crash mid-swap")
        if audit:
            from bng_tpu.chaos.invariants import audit_invariants

            t0 = tele.t()
            audit_rep = audit_invariants(
                engine=standby, pools=components.get("pools"),
                dhcp=components.get("dhcp"), fleet=components.get("fleet"),
                nat=eng.nat, check_roundtrip=False)
            report["audit_ok"] = audit_rep.ok
            report["violations"] = audit_rep.violations_by_kind()
            tele.lap(tele.OPS, t0)
            if not audit_rep.ok:
                raise CheckpointError(
                    f"standby failed the invariant audit: "
                    f"{audit_rep.violations_by_kind()}")

        # 5. the flip: one reference store + scheduler re-point
        t0 = tele.t()
        t_f = time.perf_counter()
        components["engine"] = standby
        if sched is not None:
            sched.adopt_engine(standby)
        report["flip_s"] = time.perf_counter() - t_f
        tele.lap(tele.OPS, t0)
        report["outcome"] = "ok"
    except Exception as e:  # noqa: BLE001 — ANY failure must run the heal
        # rollback: the active engine keeps serving. If the replay/audit
        # already consumed dirty marks into the (now discarded) standby
        # chain, re-sync the ACTIVE chain from the host mirrors — the
        # same full-upload heal a bulk build uses — so no delta is lost.
        # Catching only the expected types would leave the active device
        # chain silently missing those rows on an unexpected one (XLA
        # runtime errors are plain RuntimeError).
        report["outcome"] = "rolled_back" if consumed_delta else "failed"
        report["error"] = f"{type(e).__name__}: {e}"[:300]
        _log.error("engine swap did not flip", outcome=report["outcome"],
                   error=report["error"], healed=consumed_delta)
        if consumed_delta:
            eng.resync_tables()
    report["duration_s"] = time.perf_counter() - t_all
    if metrics is not None:
        metrics.record_transition(report)
    return report


def sharded_blue_green_swap(components, *, audit: bool = True, metrics=None,
                            node_id: str = "bluegreen",
                            clock=time.time) -> dict:
    """Blue/green swap for the ICI-sharded serving path (ISSUE 12):
    hydrate a STANDBY ShardedCluster from an in-memory sharded snapshot
    and flip the composition root's cluster reference — or discard the
    standby with the active cluster untouched.

    Differences from the engine swap that make this one simpler, not
    weaker: callers hold the app's control lock for the whole
    transition (the sharded drive loop cannot run concurrently), so the
    host authorities cannot move between snapshot and flip — no delta
    replay pass is needed; and the standby is built from a geometry
    clone sharing the live mesh, so its jit caches hit the compiled
    programs instead of recompiling. The same failure surfaces stay
    armed: the snapshot round-trips through the versioned codec
    (`ops.snapshot` io_error), the restore runs the full
    all-verified-then-hydrate gate, the cross-authority sharded audit
    must pass BEFORE the flip, and the `ops.swap` chaos point crashes
    at the flip barrier — any failure leaves the ACTIVE cluster
    serving (it was never mutated)."""
    from bng_tpu.runtime.checkpoint import (build_sharded_checkpoint,
                                            restore_sharded_checkpoint)

    cl = components["cluster"]
    report: dict = {"op": "sharded_swap", "outcome": "failed",
                    "shards": cl.n}
    t_all = time.perf_counter()
    try:
        # 1. quiesce + in-memory snapshot, codec round-trip verified
        t0 = tele.t()
        t_q = time.perf_counter()
        report["frames_deferred"] = cl.quiesce()
        # the DHCP lease book is NOT part of the snapshot: the live
        # server keeps the host authority across the flip (engine-swap
        # discipline — only the device-backed shard state swaps)
        ckpt = build_sharded_checkpoint(cl, 0, clock(), node_id=node_id)
        ckpt = roundtrip_checkpoint(ckpt)  # ops.snapshot chaos point
        report["quiesce_s"] = time.perf_counter() - t_q
        tele.lap(tele.OPS, t0)

        # 2. standby hydration: geometry clone + verified restore + one
        # full device upload (inside restore_sharded_checkpoint)
        t0 = tele.t()
        t_h = time.perf_counter()
        standby = cl.clone_empty()
        report["restored_rows"] = restore_sharded_checkpoint(
            ckpt, standby, now=int(clock()))
        report["hydrate_s"] = time.perf_counter() - t_h
        tele.lap(tele.OPS, t0)

        # 3. chaos flip barrier + the sharded cross-authority audit —
        # the standby must prove the partition invariants BEFORE serving
        fp = fault_point("ops.swap")
        if fp is not None and fp.kind == "fail":
            raise FaultInjectedError("chaos: injected crash mid-swap")
        if audit:
            from bng_tpu.chaos.invariants import audit_invariants

            t0 = tele.t()
            audit_rep = audit_invariants(
                cluster=standby, pools=components.get("pools"),
                dhcp=components.get("dhcp"), check_roundtrip=False)
            report["audit_ok"] = audit_rep.ok
            report["violations"] = audit_rep.violations_by_kind()
            tele.lap(tele.OPS, t0)
            if not audit_rep.ok:
                raise CheckpointError(
                    f"standby cluster failed the invariant audit: "
                    f"{audit_rep.violations_by_kind()}")

        # 4. the flip: one reference store (the drive loop reads
        # components["cluster"] every beat)
        t0 = tele.t()
        t_f = time.perf_counter()
        components["cluster"] = standby
        report["flip_s"] = time.perf_counter() - t_f
        tele.lap(tele.OPS, t0)
        report["outcome"] = "ok"
    except Exception as e:  # noqa: BLE001 — ANY failure keeps the active
        # the active cluster was never mutated (the snapshot reads, the
        # standby owns every write): discard the standby and keep serving
        report["outcome"] = "failed"
        report["error"] = f"{type(e).__name__}: {e}"[:300]
        _log.error("sharded swap did not flip", error=report["error"])
    report["duration_s"] = time.perf_counter() - t_all
    if metrics is not None:
        metrics.record_transition(report)
    return report
