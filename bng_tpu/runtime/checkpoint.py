"""Versioned snapshots of the HBM device tables — warm restart.

The reference BNG survives a userspace restart for free: its state lives
in kernel-pinned eBPF maps that outlive the agent. The TPU re-host has no
kernel to pin into — a crash or deploy threw away every lease row, NAT
session and QoS bucket, and recovery meant re-DORA-ing the subscriber
base through the slow path. This module is the replacement, shaped like
ML training checkpointing (snapshot device-resident arrays without
stalling the step loop):

- **snapshot** (`build_checkpoint`): at a scheduler drain barrier
  (`TieredScheduler.quiesce()` / `Engine.quiesce()` — flush pending
  dispatches, block until the threaded table state materializes, so a
  snapshot never interleaves with an in-flight scatter), fold the
  device-authoritative words back into the host mirrors
  (`Engine.fold_device_authoritative`: NAT session counters/last_seen,
  QoS token buckets) and collect every host authority slot-exact: the
  DHCP fast-path tables, NAT tables + allocator bookkeeping, QoS policy
  rows, antispoof bindings, garden membership, PPPoE session tables, the
  DHCP lease book and the HA session store.

- **format** (`encode_checkpoint` / `decode_checkpoint`): one file =
  magic + JSON header (schema version, monotonic seq, array manifest
  with shapes/dtypes, payload CRC32) + raw array payload. Loads REJECT
  on any mismatch — wrong magic, unknown schema, truncated payload, bad
  checksum — with a `CheckpointError` naming the reason; the process
  falls back to cold start instead of hydrating garbage.

- **restore** (`restore_checkpoint`): hydrate the host mirrors, then one
  full device upload via the existing bulk path
  (`Engine.resync_tables()` — the same startup upload a cold boot does),
  recovering leases, NAT blocks, sessions and EIM mappings with zero
  slow-path DHCP exchanges.

File lifecycle (directories, atomic rename, retention, the periodic
cadence, HA standby hydration) lives in `control/statestore.py`.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import NamedTuple

import numpy as np

MAGIC = b"BNGCKPT1"
SCHEMA_VERSION = 1
# layout: MAGIC + u32 header_len + u32 header_crc32 + header JSON + payload
_HDR_LEN = struct.Struct("<II")
# hard bound on the header blob, enforced symmetrically at encode AND
# decode: the header only carries schema/seq/geometry dicts (the big
# per-row state — arrays, lease book, NAT bookkeeping, HA sessions —
# lives in the CRC-covered payload), so a header anywhere near this is a
# bug, and a corrupt length prefix must not make the decoder json-parse
# gigabytes
_MAX_HEADER = 1 << 26

# marker for dict components too large for the header: the JSON blob is
# stored as a uint8 array named '<component>/__json__' in the payload
# (CRC32-covered, unlike the header) and the header keeps only this stub
_JSON_MARKER = "__payload_json__"
_PAYLOAD_JSON_COMPONENTS = ("nat", "dhcp", "ha", "fleet")


class CheckpointError(RuntimeError):
    """A checkpoint that must not be restored (corrupt, truncated, or
    schema/geometry mismatched). Callers catch this to fall back to a
    cold start."""


class Checkpoint(NamedTuple):
    """Decoded checkpoint: JSON-safe meta + named numpy arrays."""

    meta: dict
    arrays: dict[str, np.ndarray]

    @property
    def seq(self) -> int:
        return int(self.meta.get("seq", 0))


# ---------------------------------------------------------------------------
# binary format
# ---------------------------------------------------------------------------

def encode_checkpoint(ckpt: Checkpoint) -> bytes:
    """Checkpoint -> file bytes (magic + JSON header + array payload)."""
    names = sorted(ckpt.arrays)
    manifest = []
    chunks = []
    offset = 0
    for name in names:
        arr = np.ascontiguousarray(ckpt.arrays[name])
        raw = arr.tobytes()
        manifest.append({"name": name, "dtype": arr.dtype.str,
                         "shape": list(arr.shape), "offset": offset,
                         "nbytes": len(raw)})
        chunks.append(raw)
        offset += len(raw)
    payload = b"".join(chunks)
    header = json.dumps({
        "schema_version": SCHEMA_VERSION,
        "meta": ckpt.meta,
        "arrays": manifest,
        "payload_len": len(payload),
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }, separators=(",", ":")).encode()
    if len(header) > _MAX_HEADER:
        # symmetric with decode_header's bound: a save that could never
        # be restored must fail HERE, not at the restore that needed it
        raise CheckpointError(
            f"checkpoint header is {len(header)} bytes (> {_MAX_HEADER}): "
            "oversized meta belongs in the payload")
    return (MAGIC
            + _HDR_LEN.pack(len(header), zlib.crc32(header) & 0xFFFFFFFF)
            + header + payload)


def decode_header(data: bytes) -> tuple[dict, int]:
    """Parse + validate the header only -> (header dict, payload offset).
    Raises CheckpointError on structural problems; does NOT touch the
    payload (the cheap path for `checkpoint info` listings)."""
    if len(data) < len(MAGIC) + _HDR_LEN.size:
        raise CheckpointError("not a checkpoint: file shorter than header")
    if data[: len(MAGIC)] != MAGIC:
        raise CheckpointError(
            f"not a checkpoint: bad magic {data[:len(MAGIC)]!r}")
    hlen, want_crc = _HDR_LEN.unpack_from(data, len(MAGIC))
    if hlen > _MAX_HEADER or len(MAGIC) + _HDR_LEN.size + hlen > len(data):
        raise CheckpointError("corrupt checkpoint: truncated header")
    start = len(MAGIC) + _HDR_LEN.size
    raw = data[start : start + hlen]
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    if crc != want_crc:
        raise CheckpointError(
            f"corrupt checkpoint: header crc32 {crc:#010x} != "
            f"{want_crc:#010x}")
    try:
        header = json.loads(raw)
    except ValueError as e:
        raise CheckpointError(f"corrupt checkpoint header: {e}") from e
    got = header.get("schema_version")
    if got != SCHEMA_VERSION:
        raise CheckpointError(
            f"checkpoint schema version {got} != supported "
            f"{SCHEMA_VERSION}: refusing to restore")
    return header, start + hlen


def verify_checkpoint_bytes(data: bytes) -> tuple[dict, int]:
    """Full structural validation (header + payload length + CRC32)
    without materializing any array -> (header, payload offset). The
    shared gate for decode_checkpoint and store listings. Checksumming
    goes through a memoryview — a multi-hundred-MB payload is never
    copied just to validate it."""
    header, payload_off = decode_header(data)
    payload = memoryview(data)[payload_off:]
    want_len = int(header.get("payload_len", -1))
    if len(payload) != want_len:
        raise CheckpointError(
            f"corrupt checkpoint: payload is {len(payload)} bytes, "
            f"header promises {want_len} (truncated write?)")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(header.get("payload_crc32", -1)):
        raise CheckpointError(
            f"corrupt checkpoint: payload crc32 {crc:#010x} != header "
            f"{int(header.get('payload_crc32', -1)):#010x}")
    return header, payload_off


def roundtrip_checkpoint(ckpt: Checkpoint) -> Checkpoint:
    """In-memory encode -> verify -> decode: the blue/green standby
    hydration source (runtime/ops.py). Exercises the exact rejection
    surface the disk path has (magic/CRC/manifest/truncation) with no
    file round-trip, so a snapshot that could never restore fails the
    swap BEFORE a standby is built from it. The `ops.snapshot` chaos
    point injects encode-side I/O errors (the disk-full / OOM class) —
    surfaced as OSError, which the swap orchestrator turns into a clean
    abort with the active engine untouched."""
    from bng_tpu.chaos.faults import fault_point

    data = encode_checkpoint(ckpt)
    fp = fault_point("ops.snapshot")
    if fp is not None and fp.kind == "io_error":
        raise OSError("chaos: injected I/O error at ops.snapshot")
    return decode_checkpoint(data)


def decode_checkpoint(data: bytes) -> Checkpoint:
    """File bytes -> Checkpoint, rejecting truncation and corruption.
    Peak memory = the input buffer + one owned copy per array (the
    copies detach the result from `data` so the caller can drop it)."""
    header, payload_off = verify_checkpoint_bytes(data)
    payload = memoryview(data)[payload_off:]
    arrays = {}
    try:
        for ent in header["arrays"]:
            off, nbytes = int(ent["offset"]), int(ent["nbytes"])
            buf = payload[off : off + nbytes]
            arr = np.frombuffer(buf, dtype=np.dtype(ent["dtype"])).copy()
            arrays[ent["name"]] = arr.reshape(ent["shape"])
    except (KeyError, TypeError, ValueError) as e:
        # a CRC-valid payload with an inconsistent manifest is still a
        # corrupt checkpoint, not an internal error
        raise CheckpointError(f"corrupt checkpoint manifest: {e}") from e
    return Checkpoint(meta=header["meta"], arrays=arrays)


# ---------------------------------------------------------------------------
# snapshot / restore
# ---------------------------------------------------------------------------

def _ns(prefix: str, arrays: dict) -> dict:
    return {f"{prefix}/{k}": v for k, v in arrays.items()}


def _denamespace(prefix: str, arrays: dict) -> dict:
    plen = len(prefix) + 1
    return {k[plen:]: v for k, v in arrays.items()
            if k.startswith(prefix + "/")}


def build_checkpoint(seq: int, now: float, *, engine=None, scheduler=None,
                     fastpath=None, nat=None, qos=None, antispoof=None,
                     garden=None, pppoe=None, edge=None, dhcp=None, ha=None,
                     fleet=None, cluster_plan=None,
                     node_id: str = "") -> Checkpoint:
    """Collect a consistent snapshot of the authoritative state.

    With an `engine`, the table managers default from it, and the
    snapshot runs the full consistency protocol first: quiesce the
    scheduler (or the engine's pipelined loop) so nothing is in flight,
    then fold the device-authoritative words into the host mirrors.
    Without an engine (control-plane-only callers, tests) the host
    mirrors are taken as-is.
    """
    if engine is not None:
        fastpath = fastpath if fastpath is not None else engine.fastpath
        nat = nat if nat is not None else engine.nat
        qos = qos if qos is not None else engine.qos
        antispoof = antispoof if antispoof is not None else engine.antispoof
        garden = garden if garden is not None else engine.garden
        pppoe = pppoe if pppoe is not None else engine.pppoe
        edge = edge if edge is not None else getattr(engine, "edge", None)
        if scheduler is not None:
            scheduler.quiesce()
        else:
            engine.quiesce()
        engine.fold_device_authoritative()

    meta: dict = {"seq": int(seq), "created_at": float(now),
                  "node_id": node_id, "components": {}}
    arrays: dict[str, np.ndarray] = {}

    if fastpath is not None:
        m, a = fastpath.checkpoint_state()
        meta["components"]["fastpath"] = m
        arrays.update(_ns("fastpath", a))
    if nat is not None:
        m, a = nat.checkpoint_state()
        meta["components"]["nat"] = m
        arrays.update(_ns("nat", a))
    if qos is not None:
        meta["components"]["qos"] = {
            "geom": {"up": qos.up.checkpoint_geom(),
                     "down": qos.down.checkpoint_geom()}}
        arrays.update(_ns("qos", {"up.rows": qos.up.rows,
                                  "down.rows": qos.down.rows}))
    if antispoof is not None:
        meta["components"]["antispoof"] = {
            "geom": antispoof.bindings.checkpoint_geom()}
        arrays.update(_ns("antispoof", {
            **{f"bindings.{k}": v
               for k, v in antispoof.bindings.checkpoint_arrays().items()},
            "ranges": antispoof.ranges, "config": antispoof.config}))
    if garden is not None:
        meta["components"]["garden"] = {
            "geom": garden.subscribers.checkpoint_geom()}
        arrays.update(_ns("garden", {
            **{f"subscribers.{k}": v
               for k, v in garden.subscribers.checkpoint_arrays().items()},
            "allowed": garden.allowed}))
    if pppoe is not None:
        m, a = pppoe.checkpoint_state()
        meta["components"]["pppoe"] = m
        arrays.update(_ns("pppoe", a))
    if edge is not None:
        m, a = edge.checkpoint_state()
        meta["components"]["edge"] = m
        arrays.update(_ns("edge", a))
    if dhcp is not None:
        meta["components"]["dhcp"] = dhcp.export_leases()
    if ha is not None:
        meta["components"]["ha"] = ha.checkpoint_state()
    if fleet is not None:
        # per-worker lease books of the slow-path fleet (control/fleet.py);
        # sharding is recomputed at restore so a changed worker count
        # still lands every lease on its new owner
        meta["components"]["fleet"] = fleet.export_state()
    if cluster_plan is not None:
        # carve authority of a cluster-of-BNGs coordinator
        # (bng_tpu/cluster): O(members) and header-safe — lease books
        # ride per-instance checkpoints, not this document
        meta["components"]["cluster_plan"] = cluster_plan.checkpoint_plan()
    # per-row dict state (NAT allocator bookkeeping, lease book, HA
    # sessions) scales with the subscriber count: it rides the payload
    # as a uint8 JSON blob — CRC32-covered, and the header stays small
    # (its size bound is enforced at encode AND decode)
    for name in _PAYLOAD_JSON_COMPONENTS:
        comp = meta["components"].get(name)
        if comp is None:
            continue
        blob = json.dumps(comp, separators=(",", ":")).encode()
        arrays[f"{name}/{_JSON_MARKER}"] = np.frombuffer(
            blob, dtype=np.uint8).copy()
        meta["components"][name] = {_JSON_MARKER: True}
    return Checkpoint(meta=meta, arrays=arrays)


def _resolve_component_meta(ckpt: Checkpoint, comps: dict, name: str):
    """Return a component's meta dict, inflating the payload-JSON stub
    when present (CheckpointError on a missing/corrupt blob)."""
    m = comps.get(name)
    if not (isinstance(m, dict) and m.get(_JSON_MARKER)):
        return m
    blob = ckpt.arrays.get(f"{name}/{_JSON_MARKER}")
    if blob is None:
        raise CheckpointError(
            f"{name}: header stub points at a missing payload meta blob")
    try:
        return json.loads(bytes(np.asarray(blob, dtype=np.uint8)))
    except ValueError as e:
        raise CheckpointError(f"{name}: corrupt payload meta: {e}") from e


def _check_table(table, arrays: dict, geom: dict, label: str) -> None:
    """Geometry + array shape/dtype pre-check for one cuckoo/QoS mirror,
    mutating nothing."""
    if geom != table.checkpoint_geom():
        raise CheckpointError(
            f"{label}: checkpoint geometry {geom} != live "
            f"{table.checkpoint_geom()}")
    for k, live in table.checkpoint_arrays().items():
        src = arrays.get(k)
        if src is None:
            raise CheckpointError(f"{label}: checkpoint missing array {k!r}")
        if src.shape != live.shape or src.dtype != live.dtype:
            raise CheckpointError(
                f"{label}: checkpoint array {k!r} is {src.dtype}{src.shape},"
                f" expected {live.dtype}{live.shape}")


def _check_dense(arrays: dict, name: str, live: np.ndarray,
                 label: str) -> None:
    src = arrays.get(name)
    if src is None:
        raise CheckpointError(f"{label}: checkpoint missing array {name!r}")
    if src.shape != live.shape:
        raise CheckpointError(
            f"{label}: checkpoint array {name!r} shape {src.shape} != "
            f"live {live.shape}")


def _verify_components(ckpt: Checkpoint, comps: dict, targets: dict) -> None:
    """All-or-nothing gate: raise CheckpointError on ANY mismatch before
    a single host-mirror write happens."""
    if "fastpath" in comps:
        fp, a = targets["fastpath"], _denamespace("fastpath", ckpt.arrays)
        for t in fp._CKPT_TABLES:
            _check_table(getattr(fp, t),
                         {k: a.get(f"{t}.{k}")
                          for k in ("keys", "vals", "used")},
                         comps["fastpath"]["geom"][t], f"fastpath.{t}")
        _check_dense(a, "pools", fp.pools, "fastpath")
        _check_dense(a, "server", fp.server, "fastpath")
    if "nat" in comps:
        nm, a = targets["nat"], _denamespace("nat", ckpt.arrays)
        for t in nm._CKPT_TABLES:
            _check_table(getattr(nm, t),
                         {k: a.get(f"{t}.{k}")
                          for k in ("keys", "vals", "used")},
                         comps["nat"]["geom"][t], f"nat.{t}")
        _check_dense(a, "hairpin", nm.hairpin, "nat")
        _check_dense(a, "alg", nm.alg, "nat")
    if "qos" in comps:
        q, a = targets["qos"], _denamespace("qos", ckpt.arrays)
        _check_table(q.up, {"rows": a.get("up.rows")},
                     comps["qos"]["geom"]["up"], "qos.up")
        _check_table(q.down, {"rows": a.get("down.rows")},
                     comps["qos"]["geom"]["down"], "qos.down")
    if "antispoof" in comps:
        sp, a = targets["antispoof"], _denamespace("antispoof", ckpt.arrays)
        _check_table(sp.bindings,
                     {k: a.get(f"bindings.{k}")
                      for k in ("keys", "vals", "used")},
                     comps["antispoof"]["geom"], "antispoof.bindings")
        _check_dense(a, "ranges", sp.ranges, "antispoof")
        _check_dense(a, "config", sp.config, "antispoof")
    if "garden" in comps:
        gd, a = targets["garden"], _denamespace("garden", ckpt.arrays)
        _check_table(gd.subscribers,
                     {k: a.get(f"subscribers.{k}")
                      for k in ("keys", "vals", "used")},
                     comps["garden"]["geom"], "garden.subscribers")
        _check_dense(a, "allowed", gd.allowed, "garden")
    if "pppoe" in comps:
        pe, a = targets["pppoe"], _denamespace("pppoe", ckpt.arrays)
        for t in ("by_sid", "by_ip"):
            _check_table(getattr(pe, t),
                         {k: a.get(f"{t}.{k}")
                          for k in ("keys", "vals", "used")},
                         comps["pppoe"]["geom"][t], f"pppoe.{t}")
        _check_dense(a, "server_mac", pe.server_mac, "pppoe")
    if "edge" in comps:
        ed, a = targets["edge"], _denamespace("edge", ckpt.arrays)
        for t in ("tap", "route"):
            _check_table(getattr(ed, t),
                         {k: a.get(f"{t}.{k}")
                          for k in ("keys", "vals", "used")},
                         comps["edge"]["geom"][t], f"edge.{t}")
        _check_dense(a, "tap_filters", ed.tap_filters, "edge")
        _check_dense(a, "tap_config", ed.tap_config, "edge")
    # dry-parse the dict-driven components: their meta is consumed
    # during mutation, so a parse fault there must be caught HERE or the
    # reject would leave the process half-hydrated
    if "nat" in comps:
        try:
            targets["nat"].parse_checkpoint_meta(comps["nat"])
        except (KeyError, ValueError, TypeError) as e:
            raise CheckpointError(
                f"nat: corrupt checkpoint meta: {e!r}") from e
    if "dhcp" in comps:
        from bng_tpu.control.dhcp_server import DHCPServer

        try:
            DHCPServer.parse_lease_state(comps["dhcp"])
        except (KeyError, ValueError, TypeError) as e:
            raise CheckpointError(
                f"dhcp: corrupt checkpoint lease book: {e!r}") from e
    if "ha" in comps:
        try:
            targets["ha"].parse_checkpoint_state(comps["ha"])
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            raise CheckpointError(
                f"ha: corrupt checkpoint session store: {e!r}") from e
    if "fleet" in comps:
        from bng_tpu.control.fleet import SlowPathFleet

        try:
            SlowPathFleet.parse_state(comps["fleet"])
        except (KeyError, ValueError, TypeError) as e:
            raise CheckpointError(
                f"fleet: corrupt checkpoint lease books: {e!r}") from e
    if "cluster_plan" in comps:
        from bng_tpu.cluster import ClusterCoordinator

        try:
            ClusterCoordinator.parse_plan(comps["cluster_plan"])
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            raise CheckpointError(
                f"cluster_plan: corrupt carve plan: {e!r}") from e


def restore_checkpoint(ckpt: Checkpoint, *, engine=None, fastpath=None,
                       nat=None, qos=None, antispoof=None, garden=None,
                       pppoe=None, edge=None, dhcp=None, ha=None,
                       fleet=None, cluster_coord=None) -> dict[str, int]:
    """Hydrate the host mirrors from a decoded checkpoint and re-upload.

    Reject-on-mismatch: every table component present in the checkpoint
    must have a matching live target with identical geometry, or the
    whole restore raises `CheckpointError` and NOTHING is uploaded to
    the device (engine.resync_tables runs only after every component
    hydrated). A live subsystem absent from the checkpoint (enabled
    after the snapshot was taken) simply starts empty. Returns restored
    row counts per component (the bng_ckpt_restore_rows feed).
    """
    if ckpt.meta.get("sharded") is not None:
        raise CheckpointError(
            f"sharded checkpoint "
            f"(n_shards={ckpt.meta['sharded'].get('n_shards')}) cannot "
            f"hydrate a single-engine process: restore with --shards / "
            f"restore_sharded_checkpoint")
    if engine is not None:
        fastpath = fastpath if fastpath is not None else engine.fastpath
        nat = nat if nat is not None else engine.nat
        qos = qos if qos is not None else engine.qos
        antispoof = antispoof if antispoof is not None else engine.antispoof
        garden = garden if garden is not None else engine.garden
        pppoe = pppoe if pppoe is not None else engine.pppoe
        edge = edge if edge is not None else getattr(engine, "edge", None)
    comps = dict(ckpt.meta.get("components", {}))
    for name in _PAYLOAD_JSON_COMPONENTS:
        if name in comps:
            comps[name] = _resolve_component_meta(ckpt, comps, name)
    targets = {"fastpath": fastpath, "nat": nat, "qos": qos,
               "antispoof": antispoof, "garden": garden, "pppoe": pppoe,
               "edge": edge, "dhcp": dhcp, "ha": ha, "fleet": fleet,
               "cluster_plan": cluster_coord}
    missing = []
    for name in comps:
        tgt = targets.get(name)
        if tgt is None and name in ("fleet", "dhcp"):
            # lease books are one format: worker books merge into the
            # parent server when the fleet is off, and the parent book
            # re-shards into the fleet when it is on — a changed
            # --slowpath-workers (including 1 <-> N) must never force a
            # cold start that discards every other component
            tgt = targets.get("dhcp" if name == "fleet" else "fleet")
        if tgt is None:
            missing.append(name)
    if missing:
        raise CheckpointError(
            f"checkpoint carries {sorted(missing)} but the live process "
            f"has no such component(s): refusing a partial restore")
    # verify EVERY component before mutating ANY host mirror: a reject
    # halfway through would leave the process half-hydrated — worse than
    # the cold start the caller falls back to
    _verify_components(ckpt, comps, targets)

    rows: dict[str, int] = {}
    try:
        if "fastpath" in comps:
            got = fastpath.restore_state(comps["fastpath"],
                                         _denamespace("fastpath", ckpt.arrays))
            rows.update({f"fastpath.{k}": v for k, v in got.items()})
        if "nat" in comps:
            got = nat.restore_state(comps["nat"],
                                    _denamespace("nat", ckpt.arrays))
            rows.update({f"nat.{k}": v for k, v in got.items()})
        if "qos" in comps:
            a = _denamespace("qos", ckpt.arrays)
            g = comps["qos"]["geom"]
            rows["qos.up"] = qos.up.restore_arrays({"rows": a["up.rows"]},
                                                   g["up"])
            rows["qos.down"] = qos.down.restore_arrays(
                {"rows": a["down.rows"]}, g["down"])
        if "antispoof" in comps:
            a = _denamespace("antispoof", ckpt.arrays)
            rows["antispoof.bindings"] = antispoof.bindings.restore_arrays(
                {k: a[f"bindings.{k}"] for k in ("keys", "vals", "used")},
                comps["antispoof"]["geom"])
            antispoof.ranges[:] = a["ranges"]
            antispoof.config[:] = a["config"]
        if "garden" in comps:
            a = _denamespace("garden", ckpt.arrays)
            rows["garden.subscribers"] = garden.subscribers.restore_arrays(
                {k: a[f"subscribers.{k}"] for k in ("keys", "vals", "used")},
                comps["garden"]["geom"])
            garden.allowed[:] = a["allowed"]
        if "pppoe" in comps:
            got = pppoe.restore_state(comps["pppoe"],
                                      _denamespace("pppoe", ckpt.arrays))
            rows.update({f"pppoe.{k}": v for k, v in got.items()})
        if "edge" in comps:
            got = edge.restore_state(comps["edge"],
                                     _denamespace("edge", ckpt.arrays))
            rows.update({f"edge.{k}": v for k, v in got.items()})
        if "dhcp" in comps or "fleet" in comps:
            worker_books = (list(comps["fleet"]["workers"])
                            if "fleet" in comps else [])
            parent_book = comps.get("dhcp")
            if fleet is not None:
                # the fleet owns DHCPv4: EVERY lease book (per-worker +
                # parent) re-shards into the workers. The parent book is
                # deliberately NOT hydrated too — double ownership would
                # let the parent's expiry sweep release worker-held
                # addresses back to the pool (double-allocation risk).
                books = worker_books + (
                    [parent_book] if parent_book else [])
                rows["fleet.leases"] = fleet.restore_state(
                    {"workers": books})
            else:
                # fleet checkpoint, single-worker process now: worker
                # books merge into the parent server (same format) —
                # a config change never costs a cold start
                total = 0
                if parent_book is not None:
                    total += dhcp.restore_leases(parent_book)
                for book in worker_books:
                    total += dhcp.restore_leases(book)
                rows["dhcp.leases"] = total
        if "ha" in comps:
            # role decides the direction: a restarted active resumes its
            # seq; a standby bootstraps then catches up via replay_since
            if hasattr(ha, "bootstrap_state"):
                rows["ha.sessions"] = ha.bootstrap_state(comps["ha"])
            else:
                rows["ha.sessions"] = ha.restore_state(comps["ha"])
        if "cluster_plan" in comps:
            # the plan document replays through the coordinator's store
            # so every member applies the checkpointed carve epoch
            rows["cluster_plan.members"] = cluster_coord.restore_plan(
                comps["cluster_plan"])
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        raise CheckpointError(f"checkpoint restore rejected: {e}") from e

    if engine is not None:
        # one full device upload — the same bulk path a cold start takes
        engine.resync_tables()
    return rows


# ---------------------------------------------------------------------------
# sharded (ICI dataplane) snapshot / restore — ISSUE 12
# ---------------------------------------------------------------------------
# One file holds EVERY shard's host authorities namespaced
# `shard<i>/<component>/...` plus the flat non-shard components (lease
# book, HA store, fleet books) exactly as the single-engine format
# carries them. `meta["sharded"]` records the topology; restore either
# hydrates slot-exact (same shard count + geometry) or RE-SHARDS every
# row onto its owner under the new topology — the same FNV-1a32 owner
# discipline the fleet lease-book re-shard uses. NAT port-block
# placements cannot move verbatim across a topology change (each shard
# owns its public IPs exclusively), so blocks re-allocate on the new
# owner shard and live flows re-establish through the normal new-flow
# punt; everything host-authoritative (leases, subscriber rows, QoS
# policy, bindings, garden membership, PPPoE sessions) moves losslessly.

def _shard_prefix(i: int) -> str:
    return f"shard{i}"


def build_sharded_checkpoint(cluster, seq: int, now: float, *, dhcp=None,
                             ha=None, fleet=None, quiesce: bool = True,
                             node_id: str = "") -> Checkpoint:
    """Snapshot an N-shard ShardedCluster (parallel/sharded.py) plus the
    flat control-plane components, at the cluster quiesce barrier with
    device-authoritative words folded back — the sharded analog of
    build_checkpoint(engine=...)."""
    if quiesce:
        cluster.quiesce()
        cluster.fold_device_authoritative()
    base = build_checkpoint(seq, now, dhcp=dhcp, ha=ha, fleet=fleet,
                            node_id=node_id)
    meta = base.meta
    arrays = dict(base.arrays)
    meta["sharded"] = {"n_shards": int(cluster.n), "shards": []}
    for i in range(cluster.n):
        sub = build_checkpoint(seq, now, node_id=node_id,
                               **cluster.shard_components(i))
        meta["sharded"]["shards"].append(sub.meta["components"])
        pref = _shard_prefix(i)
        arrays.update({f"{pref}/{k}": v for k, v in sub.arrays.items()})
    return Checkpoint(meta=meta, arrays=arrays)


def _shard_sub_checkpoint(ckpt: Checkpoint, i: int, comps: dict) -> Checkpoint:
    """Shard i's slice of a sharded checkpoint, re-shaped into the flat
    single-engine format (components meta + de-prefixed arrays) so the
    existing verify/restore machinery applies unchanged."""
    pref = _shard_prefix(i) + "/"
    arrays = {k[len(pref):]: v for k, v in ckpt.arrays.items()
              if k.startswith(pref)}
    return Checkpoint(meta={"components": comps}, arrays=arrays)


def _sharded_meta(ckpt: Checkpoint) -> tuple[int, list[dict]]:
    sh = ckpt.meta.get("sharded")
    if not isinstance(sh, dict):
        raise CheckpointError(
            "not a sharded checkpoint (no sharded topology meta): "
            "refusing to hydrate a cluster from a single-engine snapshot")
    try:
        src_n = int(sh["n_shards"])
        shards = list(sh["shards"])
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointError(f"corrupt sharded topology meta: {e}") from e
    if src_n < 1 or len(shards) != src_n:
        raise CheckpointError(
            f"corrupt sharded topology meta: n_shards={src_n} but "
            f"{len(shards)} shard component sets")
    return src_n, shards


def _used_rows(arrays: dict, name: str, label: str):
    """(keys[used], vals[used]) of one checkpointed HostTable, with the
    structural validation the re-shard walk needs."""
    keys = arrays.get(f"{name}.keys")
    vals = arrays.get(f"{name}.vals")
    used = arrays.get(f"{name}.used")
    if keys is None or vals is None or used is None:
        raise CheckpointError(f"{label}: checkpoint missing {name} arrays")
    if not (keys.ndim == 2 and vals.ndim == 2
            and keys.shape[0] == vals.shape[0] == used.shape[0]):
        raise CheckpointError(
            f"{label}: inconsistent {name} array shapes "
            f"{keys.shape}/{vals.shape}/{used.shape}")
    m = used.astype(bool)
    return keys[m], vals[m]


def _reshard_walk(ckpt: Checkpoint, shards_meta: list[dict], src_n: int,
                  target, now: int) -> dict[str, int]:
    """Re-insert every source shard's rows into `target` (a fresh
    ShardedCluster clone) under ITS owner routing — FNV-1a32 key hash
    for the DHCP tables, subscriber-IP affinity for the chip-local
    state. Raises CheckpointError on structural problems; an insert
    overflow (target shards too small for the re-balanced load) also
    rejects — the caller's throwaway target makes that safe."""
    from bng_tpu.edge.ops import TC_ARMED
    from bng_tpu.ops.antispoof import AB_IPV4
    from bng_tpu.ops.pppoe import PS_IP
    from bng_tpu.ops.qtable import (QW_BURST, QW_FLAGS, QW_KEY,
                                    QW_PRIORITY, QW_RATE_HI, QW_RATE_LO)
    from bng_tpu.ops.table import shard_owner

    rows = {"dhcp_rows": 0, "qos_rows": 0, "spoof_rows": 0,
            "garden_rows": 0, "pppoe_rows": 0, "nat_blocks": 0,
            "edge_taps": 0, "edge_routes": 0}
    try:
        for i in range(src_n):
            comps = dict(shards_meta[i])
            sub = _shard_sub_checkpoint(ckpt, i, comps)
            for name in _PAYLOAD_JSON_COMPONENTS:
                if name in comps:
                    comps[name] = _resolve_component_meta(sub, comps, name)
            a = sub.arrays
            label = _shard_prefix(i)

            if "fastpath" in comps:
                fa = _denamespace("fastpath", a)
                for t in ("sub", "vlan", "cid"):
                    keys, vals = _used_rows(fa, t, f"{label}.fastpath")
                    if len(keys) == 0:
                        continue
                    owners = shard_owner(
                        [keys[:, k] for k in range(keys.shape[1])],
                        target.n)
                    for r in range(len(keys)):
                        getattr(target.fastpath[int(owners[r])],
                                t).insert(keys[r], vals[r])
                        rows["dhcp_rows"] += 1
                # pool/server config is replicated cluster-wide: shard
                # 0's copy is authoritative for every target shard
                if i == 0:
                    for fp in target.fastpath:
                        _check_dense(fa, "pools", fp.pools,
                                     f"{label}.fastpath")
                        _check_dense(fa, "server", fp.server,
                                     f"{label}.fastpath")
                        fp.pools[:] = fa["pools"]
                        fp.server[:] = fa["server"]

            if "qos" in comps:
                qa = _denamespace("qos", a)
                for side in ("up", "down"):
                    rws = qa.get(f"{side}.rows")
                    if rws is None or rws.ndim != 2:
                        raise CheckpointError(
                            f"{label}.qos: missing/odd {side} rows")
                    for r in rws[(rws[:, QW_FLAGS] & 1) != 0]:
                        ip = int(r[QW_KEY])
                        o = target.affinity_shard_ip(ip)
                        rate = int(r[QW_RATE_LO]) | (int(r[QW_RATE_HI]) << 32)
                        # tokens re-seed to full burst on the new owner
                        # (host cannot carry device tokens across a
                        # re-hash — same rule as in-table relocation)
                        getattr(target.qos[o], side).insert(
                            ip, rate, int(r[QW_BURST]),
                            int(r[QW_PRIORITY]))
                        rows["qos_rows"] += 1

            if "antispoof" in comps:
                sa = _denamespace("antispoof", a)
                keys, vals = _used_rows(sa, "bindings", f"{label}.antispoof")
                for r in range(len(keys)):
                    o = target.affinity_shard_ip(int(vals[r][AB_IPV4]))
                    target.spoof[o].bindings.insert(keys[r], vals[r])
                    rows["spoof_rows"] += 1
                if i == 0:
                    for sp in target.spoof:
                        _check_dense(sa, "ranges", sp.ranges,
                                     f"{label}.antispoof")
                        _check_dense(sa, "config", sp.config,
                                     f"{label}.antispoof")
                        sp.ranges[:] = sa["ranges"]
                        sp.config[:] = sa["config"]

            if "garden" in comps and target.garden is None:
                raise CheckpointError(
                    f"{label} carries garden state but the target "
                    f"cluster has no garden gate: refusing a partial "
                    f"restore")
            if "pppoe" in comps and target.pppoe is None:
                raise CheckpointError(
                    f"{label} carries pppoe state but the target "
                    f"cluster has pppoe disabled: refusing a partial "
                    f"restore")
            if "garden" in comps and target.garden is not None:
                ga = _denamespace("garden", a)
                keys, vals = _used_rows(ga, "subscribers", f"{label}.garden")
                for r in range(len(keys)):
                    o = target.affinity_shard_ip(int(keys[r][0]))
                    target.garden[o].subscribers.insert(keys[r], vals[r])
                    rows["garden_rows"] += 1
                if i == 0:
                    for gd in target.garden:
                        _check_dense(ga, "allowed", gd.allowed,
                                     f"{label}.garden")
                        gd.allowed[:] = ga["allowed"]

            if "pppoe" in comps and target.pppoe is not None:
                pa = _denamespace("pppoe", a)
                for t in ("by_sid", "by_ip"):
                    keys, vals = _used_rows(pa, t, f"{label}.pppoe")
                    for r in range(len(keys)):
                        # both directions land on the session's affinity
                        # shard — the ring steers both sides there
                        o = target.affinity_shard_ip(int(vals[r][PS_IP]))
                        getattr(target.pppoe[o], t).insert(keys[r], vals[r])
                        rows["pppoe_rows"] += 1
                if i == 0 and pa.get("server_mac") is not None:
                    for pe in target.pppoe:
                        pe.server_mac[:] = pa["server_mac"]

            if "edge" in comps and getattr(target, "edge", None) is None:
                raise CheckpointError(
                    f"{label} carries edge state but the target cluster "
                    f"has edge protection disabled: refusing a partial "
                    f"restore")
            if "edge" in comps and getattr(target, "edge", None) is not None:
                ea = _denamespace("edge", a)
                keys, vals = _used_rows(ea, "tap", f"{label}.edge")
                for r in range(len(keys)):
                    # chip-local by subscriber affinity, like the ring
                    o = target.affinity_shard_ip(int(keys[r][0]))
                    target.edge[o].tap.insert(keys[r], vals[r])
                    target.edge[o]._armed += 1
                    target.edge[o].tap_config[TC_ARMED] = \
                        target.edge[o]._armed
                    rows["edge_taps"] += 1
                keys, vals = _used_rows(ea, "route", f"{label}.edge")
                for r in range(len(keys)):
                    o = target.affinity_shard_ip(int(keys[r][0]))
                    target.edge[o].route.insert(keys[r], vals[r])
                    rows["edge_routes"] += 1
                if i == 0:
                    # filter rows are warrant-global: replicated to
                    # every shard, shard 0's copy authoritative
                    for ed in target.edge:
                        _check_dense(ea, "tap_filters", ed.tap_filters,
                                     f"{label}.edge")
                        ed.tap_filters[:] = ea["tap_filters"]

            if "nat" in comps:
                from bng_tpu.control.nat import NATManager

                parsed = NATManager.parse_checkpoint_meta(comps["nat"])
                # port blocks re-allocate on the new owner (public-IP
                # ownership is per-shard and exclusive; a block cannot
                # move between public IPs verbatim). Live flows
                # re-establish via the device's new-flow punt.
                for priv_ip in sorted(parsed["blocks"]):
                    o = target.affinity_shard_ip(int(priv_ip))
                    if target.nat[o].allocate_nat(int(priv_ip),
                                                  int(now)) is None:
                        # exhaustion is NOT recoverable-by-punt (the
                        # punt's allocation hits the same empty pool):
                        # reject like any other overflow, loudly
                        raise CheckpointError(
                            f"NAT block for {priv_ip:#x} does not fit "
                            f"shard {o}'s port space under the new "
                            f"topology ({target.n} shards): provision "
                            f"more public IPs / wider port ranges "
                            f"before re-sharding down")
                    rows["nat_blocks"] += 1
                na = _denamespace("nat", a)
                if i == 0 and na.get("hairpin") is not None \
                        and na.get("alg") is not None:
                    # hairpin/ALG policy config is cluster-global
                    for nm in target.nat:
                        nm.hairpin[:] = na["hairpin"]
                        nm.alg[:] = na["alg"]
    except CheckpointError:
        raise
    except (KeyError, IndexError, TypeError, ValueError, RuntimeError) as e:
        raise CheckpointError(
            f"sharded re-shard rejected: {type(e).__name__}: {e}") from e
    return rows


def restore_sharded_checkpoint(ckpt: Checkpoint, cluster, *, dhcp=None,
                               ha=None, fleet=None,
                               now: int = 0) -> dict[str, int]:
    """Hydrate a ShardedCluster (and the flat components) from a sharded
    checkpoint, then one full device upload — reject-on-mismatch like
    the single-engine restore, all-or-nothing across EVERY shard.

    Topology aware: a checkpoint taken at N shards restores into an
    M-shard cluster by re-inserting every row on its owner under the
    new topology (the fleet lease-book re-shard discipline). The
    hydration happens into a throwaway geometry clone first and the
    host authorities are adopted wholesale on success, so a reject can
    never leave the live cluster half-hydrated.
    """
    src_n, shards_meta = _sharded_meta(ckpt)

    tmp = cluster.clone_empty()
    if src_n == cluster.n:
        # slot-exact fast path: verify EVERY shard against the clone's
        # geometry, then hydrate shard by shard (preserves cuckoo/stash
        # placement and the folded device-authoritative words)
        subs = []
        for i in range(src_n):
            comps = dict(shards_meta[i])
            sub = _shard_sub_checkpoint(ckpt, i, comps)
            for name in _PAYLOAD_JSON_COMPONENTS:
                if name in comps:
                    comps[name] = _resolve_component_meta(sub, comps, name)
            targets = tmp.shard_components(i)
            missing = sorted(set(comps) - set(targets))
            if missing:
                raise CheckpointError(
                    f"shard{i} carries {missing} but the live cluster "
                    f"has no such component(s): refusing a partial "
                    f"restore")
            _verify_components(sub, comps, targets)
            subs.append((sub, comps, targets))
        rows: dict[str, int] = {}
        for i, (sub, _comps, targets) in enumerate(subs):
            # the flat restore path knows every component shape; reuse
            # it wholesale per shard (no engine kwarg: the one device
            # upload happens once, below, for all shards together)
            got = restore_checkpoint(sub, **targets)
            rows.update({f"shard{i}.{k}": v for k, v in got.items() if v})
    else:
        rows = _reshard_walk(ckpt, shards_meta, src_n, tmp, now)
        rows["resharded_from"] = src_n
        rows["resharded_to"] = cluster.n

    # flat components (lease book / HA / fleet) hydrate exactly like the
    # single-engine path — the book formats are topology-independent
    flat_comps = dict(ckpt.meta.get("components", {}))
    if flat_comps:
        flat = Checkpoint(
            meta={"components": ckpt.meta.get("components", {})},
            arrays={k: v for k, v in ckpt.arrays.items()
                    if not k.startswith("shard")})
        rows.update(restore_checkpoint(flat, dhcp=dhcp, ha=ha, fleet=fleet))

    # adopt the hydrated authorities wholesale (tmp is a geometry clone,
    # so presence/absence of garden/pppoe matches); then the one full
    # upload — the same bulk path a cold start takes
    cluster.fastpath = tmp.fastpath
    cluster.nat = tmp.nat
    cluster.qos = tmp.qos
    cluster.spoof = tmp.spoof
    cluster.garden = tmp.garden
    cluster.pppoe = tmp.pppoe
    cluster.edge = tmp.edge
    cluster._pub_owner_cache = None
    cluster.resync_tables()
    return rows
