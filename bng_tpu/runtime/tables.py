"""Host-side fast-path table management — the pkg/ebpf/loader.go role.

The reference's Loader owns typed Go mirrors of every eBPF map and all CRUD
(pkg/ebpf/loader.go:74-661: AddSubscriber, AddPool, SetServerConfig,
circuit-ID ops). Here the same surface manages numpy mirrors of the HBM
cuckoo tables plus the dense pool/server-config arrays, and emits bounded
TableUpdate batches that the jitted device step scatters into HBM — the
replacement for bpf_map_update_elem syscalls.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.ops.dhcp import (
    ASSIGN_WORDS,
    POOL_WORDS,
    SERVER_WORDS,
    AV_POOL_ID,
    AV_IP,
    AV_VLAN,
    AV_CLASS,
    AV_LEASE_EXP,
    AV_FLAGS,
    PV_NETWORK,
    PV_PREFIX,
    PV_GATEWAY,
    PV_DNS1,
    PV_DNS2,
    PV_LEASE_T,
    PV_VALID,
    SC_MAC_HI,
    SC_MAC_LO,
    SC_IP,
    CID_KEY_LEN,
    DHCPGeom,
    DHCPTables,
)
from bng_tpu.ops.pppoe import (
    PPPOE_WORDS,
    PS_IP,
    PS_MAC_HI,
    PS_MAC_LO,
    PS_SESSION_ID,
)
from bng_tpu.ops.table import HostTable, TableGeom, TableUpdate, apply_update
from bng_tpu.utils.net import mac_to_u64, split_u64


def pack_cid_host(circuit_id: bytes) -> np.ndarray:
    """32-byte (padded/truncated) circuit-id -> 8 big-endian uint32 words.

    Must match ops.dhcp.pack_cid_words; parity with the fixed 32-byte key of
    bpf/maps.h:216-220 (truncate long, zero-pad short).
    """
    buf = (circuit_id[:CID_KEY_LEN] + b"\x00" * CID_KEY_LEN)[:CID_KEY_LEN]
    return np.frombuffer(buf, dtype=">u4").astype(np.uint32)


class FastPathUpdates(NamedTuple):
    """Per-step bounded update batch for all DHCP-path tables (pytree)."""

    sub: TableUpdate
    vlan: TableUpdate
    cid: TableUpdate
    pools: jax.Array  # [P, POOL_WORDS] full (tiny) refresh
    server: jax.Array  # [SERVER_WORDS]


def apply_fastpath_updates(tables: DHCPTables, upd: FastPathUpdates) -> DHCPTables:
    """Jit-side application of one update batch."""
    return DHCPTables(
        sub=apply_update(tables.sub, upd.sub),
        vlan=apply_update(tables.vlan, upd.vlan),
        cid=apply_update(tables.cid, upd.cid),
        pools=upd.pools,
        server=upd.server,
    )


class FastPathTables:
    """Host authority for subscriber/VLAN/circuit-ID/pool/server tables."""

    def __init__(
        self,
        sub_nbuckets: int = 1 << 15,
        vlan_nbuckets: int = 1 << 12,
        cid_nbuckets: int = 1 << 12,
        max_pools: int = 256,
        stash: int = 64,
        update_slots: int = 256,
    ):
        self.sub = HostTable(sub_nbuckets, key_words=2, val_words=ASSIGN_WORDS, stash=stash, name="subscriber_pools")
        self.vlan = HostTable(vlan_nbuckets, key_words=1, val_words=ASSIGN_WORDS, stash=stash, name="vlan_subscriber_pools")
        self.cid = HostTable(cid_nbuckets, key_words=8, val_words=ASSIGN_WORDS, stash=stash, name="circuit_id_subscribers")
        self.pools = np.zeros((max_pools, POOL_WORDS), dtype=np.uint32)
        self.server = np.zeros((SERVER_WORDS,), dtype=np.uint32)
        self.update_slots = update_slots
        self.geom = DHCPGeom(
            sub=TableGeom(sub_nbuckets, stash),
            vlan=TableGeom(vlan_nbuckets, stash),
            cid=TableGeom(cid_nbuckets, stash),
        )

    # -- CRUD (parity: pkg/ebpf/loader.go AddSubscriber :352, AddPool :402,
    #    SetServerConfig :444, AddVLANSubscriber :470, circuit-ID ops :556+) --
    @staticmethod
    def _assignment(pool_id, ip, lease_expiry, vlan_id, client_class, flags):
        v = np.zeros((ASSIGN_WORDS,), dtype=np.uint32)
        v[AV_POOL_ID] = pool_id
        v[AV_IP] = ip
        v[AV_VLAN] = vlan_id
        v[AV_CLASS] = client_class
        v[AV_LEASE_EXP] = lease_expiry
        v[AV_FLAGS] = flags
        return v

    def add_subscriber(self, mac, pool_id: int, ip: int, lease_expiry: int,
                       vlan_id: int = 0, client_class: int = 0, flags: int = 0) -> None:
        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        self.sub.insert([hi, lo], self._assignment(pool_id, ip, lease_expiry, vlan_id, client_class, flags))

    def add_subscribers_bulk(self, macs_u64, pool_ids, ips, lease_expiries,
                             vlan_ids=0, client_classes=0, flags=0) -> None:
        """Vectorized batch insert for reference-scale table builds.

        The reference sizes subscriber maps for 1M entries
        (/root/reference/bpf/maps.h:10); a per-subscriber Python insert loop
        makes that infeasible, so the bench/restore path assembles key/value
        arrays and hands them to HostTable.bulk_insert (8 vectorized
        placement passes). MACs must be unique and not already present.
        Follow with device_tables() for a full upload.
        """
        macs_u64 = np.asarray(macs_u64, dtype=np.uint64)
        n = len(macs_u64)
        keys = np.zeros((n, 2), dtype=np.uint32)
        keys[:, 0] = (macs_u64 >> np.uint64(32)).astype(np.uint32)  # hi
        keys[:, 1] = (macs_u64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)  # lo
        vals = np.zeros((n, ASSIGN_WORDS), dtype=np.uint32)
        vals[:, AV_POOL_ID] = pool_ids
        vals[:, AV_IP] = ips
        vals[:, AV_VLAN] = vlan_ids
        vals[:, AV_CLASS] = client_classes
        vals[:, AV_LEASE_EXP] = lease_expiries
        vals[:, AV_FLAGS] = flags
        self.sub.bulk_insert(keys, vals)

    def remove_subscriber(self, mac) -> bool:
        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        return self.sub.delete([hi, lo])

    def get_subscriber(self, mac):
        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        return self.sub.lookup([hi, lo])

    def add_vlan_subscriber(self, s_tag: int, c_tag: int, pool_id: int, ip: int,
                            lease_expiry: int, client_class: int = 0, flags: int = 0) -> None:
        self.vlan.insert([(s_tag << 16) | c_tag],
                         self._assignment(pool_id, ip, lease_expiry, 0, client_class, flags))

    def remove_vlan_subscriber(self, s_tag: int, c_tag: int) -> bool:
        return self.vlan.delete([(s_tag << 16) | c_tag])

    def add_circuit_id_subscriber(self, circuit_id: bytes, pool_id: int, ip: int,
                                  lease_expiry: int, client_class: int = 0, flags: int = 0) -> None:
        self.cid.insert(pack_cid_host(circuit_id),
                        self._assignment(pool_id, ip, lease_expiry, 0, client_class, flags))

    def remove_circuit_id_subscriber(self, circuit_id: bytes) -> bool:
        return self.cid.delete(pack_cid_host(circuit_id))

    def add_pool(self, pool_id: int, network: int, prefix_len: int, gateway: int,
                 dns_primary: int = 0, dns_secondary: int = 0, lease_time: int = 3600) -> None:
        if pool_id >= len(self.pools):
            raise ValueError(f"pool_id {pool_id} >= max_pools {len(self.pools)}")
        row = self.pools[pool_id]
        row[PV_NETWORK] = network
        row[PV_PREFIX] = prefix_len
        row[PV_GATEWAY] = gateway
        row[PV_DNS1] = dns_primary
        row[PV_DNS2] = dns_secondary
        row[PV_LEASE_T] = lease_time
        row[PV_VALID] = 1

    def remove_pool(self, pool_id: int) -> None:
        self.pools[pool_id] = 0

    def set_server_config(self, mac, ip: int) -> None:
        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        self.server[SC_MAC_HI] = hi
        self.server[SC_MAC_LO] = lo
        self.server[SC_IP] = ip

    def touch_lease(self, mac, lease_expiry: int) -> bool:
        """Refresh a subscriber's lease expiry in place."""
        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        return self.sub.update_val_words([hi, lo], AV_LEASE_EXP, [lease_expiry])

    # -- device sync --
    def device_tables(self) -> DHCPTables:
        """Full upload (startup)."""
        return DHCPTables(
            sub=self.sub.device_state(),
            vlan=self.vlan.device_state(),
            cid=self.cid.device_state(),
            pools=jnp.asarray(self.pools),
            server=jnp.asarray(self.server),
        )

    def make_updates(self) -> FastPathUpdates:
        """Drain dirty slots into one bounded per-step update batch."""
        return FastPathUpdates(
            sub=self.sub.make_update(self.update_slots),
            vlan=self.vlan.make_update(self.update_slots),
            cid=self.cid.make_update(self.update_slots),
            pools=jnp.asarray(self.pools),
            server=jnp.asarray(self.server),
        )

    def empty_updates(self) -> FastPathUpdates:
        """A no-op table-delta batch that does NOT consume dirty tracking.

        The latency scheduler's bulk lane passes this on every step: the
        express lane is the single consumer of the real fastpath drain
        (one authoritative device DHCP chain), and the bulk lane's DHCP
        leaves are a read replica. The sub/vlan/cid scatter buffers are
        cached (they are the per-step transfer cost); pools/server are
        re-read every call — the step applies those dense arrays
        wholesale, so the replica tracks live pool/server config even
        between replica refreshes."""
        return FastPathUpdates(
            sub=self.sub.empty_update(self.update_slots),
            vlan=self.vlan.empty_update(self.update_slots),
            cid=self.cid.empty_update(self.update_slots),
            pools=jnp.asarray(self.pools),
            server=jnp.asarray(self.server),
        )

    def dirty_count(self) -> int:
        return self.sub.dirty_count() + self.vlan.dirty_count() + self.cid.dirty_count()

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    _CKPT_TABLES = ("sub", "vlan", "cid")

    def checkpoint_state(self) -> tuple[dict, dict]:
        """(meta, arrays) for the whole DHCP fast-path authority: the
        three cuckoo mirrors slot-exact plus the dense pool/server
        config. Array keys are '<table>.<array>' namespaced."""
        meta = {"geom": {t: getattr(self, t).checkpoint_geom()
                         for t in self._CKPT_TABLES},
                "max_pools": len(self.pools)}
        arrays = {f"{t}.{k}": v
                  for t in self._CKPT_TABLES
                  for k, v in getattr(self, t).checkpoint_arrays().items()}
        arrays["pools"] = self.pools
        arrays["server"] = self.server
        return meta, arrays

    def restore_state(self, meta: dict, arrays: dict) -> dict[str, int]:
        """Hydrate from a checkpoint; ValueError on geometry mismatch.
        Caller must follow with a full device upload (resync_tables)."""
        rows = {}
        for t in self._CKPT_TABLES:
            rows[t] = getattr(self, t).restore_arrays(
                {k: arrays[f"{t}.{k}"] for k in ("keys", "vals", "used")},
                meta["geom"][t])
        if arrays["pools"].shape != self.pools.shape:
            raise ValueError(
                f"checkpoint pools shape {arrays['pools'].shape} != "
                f"{self.pools.shape}")
        self.pools[:] = arrays["pools"]
        self.server[:] = arrays["server"]
        rows["pools"] = int(np.count_nonzero(self.pools[:, PV_VALID]))
        return rows


class PPPoEFastPathTables:
    """Host side of the device PPPoE session tables (ops.pppoe).

    The PPPoE control plane (control.pppoe.server) negotiates sessions in
    userspace; established sessions are published here so session-stage
    DATA frames decap/encap on device. session_up/session_down plug
    directly into PPPoEServer's on_open/on_close hooks — the same
    slow-path-populates-cache shape as DHCP's updateFastPathCache
    (pkg/dhcp/server.go:1057-1097).
    """

    def __init__(self, nbuckets: int = 1 << 12, stash: int = 64,
                 update_slots: int = 128,
                 server_mac: bytes = b"\x02\xbb\x00\x00\x00\x01"):
        # pre-ISSUE-11 checkpoints carried 6-word session rows; live 8 is
        # a pure zero-pad (PS_* indices unchanged) — warm restarts keep
        # working across the widening
        self.by_sid = HostTable(nbuckets, key_words=1, val_words=PPPOE_WORDS,
                                stash=stash, name="pppoe_by_sid",
                                compat_val_pad_from=(6,))
        self.by_ip = HostTable(nbuckets, key_words=1, val_words=PPPOE_WORDS,
                               stash=stash, name="pppoe_by_ip",
                               compat_val_pad_from=(6,))
        self.geom = TableGeom(nbuckets, stash)
        self.update_slots = update_slots
        # AC MAC, stamped as L2 source of every encapped downstream frame
        # (pppoe_encap's server_mac argument — (hi16, lo32) words)
        self.server_mac = np.array(
            [int.from_bytes(server_mac[:2], "big"),
             int.from_bytes(server_mac[2:], "big")], dtype=np.uint32)

    def session_up(self, sess) -> None:
        """on_open hook: publish an OPEN session to the device tables."""
        row = np.zeros((PPPOE_WORDS,), dtype=np.uint32)
        row[PS_SESSION_ID] = sess.session_id
        row[PS_MAC_HI] = int.from_bytes(sess.client_mac[:2], "big")
        row[PS_MAC_LO] = int.from_bytes(sess.client_mac[2:], "big")
        row[PS_IP] = sess.assigned_ip or 0
        self.by_sid.insert([sess.session_id], row)
        if sess.assigned_ip:
            self.by_ip.insert([sess.assigned_ip], row)

    def session_down(self, event) -> None:
        """on_close hook (takes the server's TeardownEvent)."""
        sess = getattr(event, "session", event)
        self.by_sid.delete([sess.session_id])
        if sess.assigned_ip:
            self.by_ip.delete([sess.assigned_ip])

    def make_updates(self):
        return (self.by_sid.make_update(self.update_slots),
                self.by_ip.make_update(self.update_slots))

    def empty_updates(self):
        """No-op update pair for scheduler no-drain bulk steps (cached)."""
        return (self.by_sid.empty_update(self.update_slots),
                self.by_ip.empty_update(self.update_slots))

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    def checkpoint_state(self) -> tuple[dict, dict]:
        meta = {"geom": {"by_sid": self.by_sid.checkpoint_geom(),
                         "by_ip": self.by_ip.checkpoint_geom()}}
        arrays = {f"{t}.{k}": v
                  for t in ("by_sid", "by_ip")
                  for k, v in getattr(self, t).checkpoint_arrays().items()}
        arrays["server_mac"] = self.server_mac
        return meta, arrays

    def restore_state(self, meta: dict, arrays: dict) -> dict[str, int]:
        rows = {}
        for t in ("by_sid", "by_ip"):
            rows[t] = getattr(self, t).restore_arrays(
                {k: arrays[f"{t}.{k}"] for k in ("keys", "vals", "used")},
                meta["geom"][t])
        self.server_mac[:] = arrays["server_mac"]
        return rows
