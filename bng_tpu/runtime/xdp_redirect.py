"""XDP xskmap-redirect program: load + attach via raw bpf(2) syscalls.

An AF_XDP socket only receives traffic that an XDP program redirects into
it through an XSKMAP — binding alone is not enough. The reference ships
compiled BPF objects and loads them with cilium/ebpf
(/root/reference/pkg/ebpf/loader.go:176-322); here the one program the
TPU build still needs in the kernel is this six-instruction redirect
trampoline, so it is assembled inline and loaded through the raw bpf(2)
syscall — no clang, no libbpf, and the kernel VERIFIER still checks it
(the reference's verifier-gate discipline, bpf/test-verifier.sh).

    prog:  r2 = ctx->rx_queue_index
           r1 = &xsks_map           (ld_imm64 BPF_PSEUDO_MAP_FD)
           r3 = XDP_PASS            (fallback when the map slot is empty)
           call bpf_redirect_map
           exit

Attachment uses bpf_link (BPF_LINK_CREATE, kernel >= 5.7) in generic/SKB
mode — the same driver->generic degradation as the attach ladder. The
link fd pins the attachment: closing it detaches, so cleanup is
crash-safe (process death detaches the program automatically).
"""

from __future__ import annotations

import ctypes as C
import os
import socket
import struct

_SYS_BPF = 321  # x86_64

BPF_MAP_CREATE = 0
BPF_MAP_UPDATE_ELEM = 2
BPF_MAP_DELETE_ELEM = 3
BPF_PROG_LOAD = 5
BPF_LINK_CREATE = 28

BPF_MAP_TYPE_XSKMAP = 17
BPF_PROG_TYPE_XDP = 6
BPF_XDP = 37  # attach_type
BPF_PSEUDO_MAP_FD = 1
BPF_F_XDP_SKB_MODE = 1 << 1  # XDP_FLAGS_SKB_MODE (generic rung)

BPF_FUNC_redirect_map = 51
XDP_PASS = 2

_libc = C.CDLL(None, use_errno=True)


def _bpf(cmd: int, attr: bytes) -> int:
    buf = C.create_string_buffer(attr, len(attr))
    rc = _libc.syscall(_SYS_BPF, cmd, buf, len(attr))
    if rc < 0:
        err = C.get_errno()
        raise OSError(err, f"bpf(cmd={cmd}): {os.strerror(err)}")
    return rc


def _insn(code: int, dst: int, src: int, off: int, imm: int) -> bytes:
    return struct.pack("<BBhi", code, (src << 4) | dst, off, imm)


class XdpRedirect:
    """Loaded + attached xskmap-redirect program on one interface.

    Create with the interface name and a mapping of queue -> AF_XDP
    socket fd. Detaches and releases everything on close() (or process
    exit — all state is fd-backed)."""

    def __init__(self, ifname: str, xsk_fds: dict[int, int],
                 max_queues: int = 64):
        self.ifname = ifname
        self.map_fd = -1
        self.prog_fd = -1
        self.link_fd = -1
        try:
            self._load(ifname, xsk_fds, max_queues)
        except BaseException:
            self.close()
            raise

    def _load(self, ifname: str, xsk_fds: dict[int, int],
              max_queues: int) -> None:
        ifindex = socket.if_nametoindex(ifname)

        # xsks_map: queue index -> socket fd
        attr = struct.pack("<IIIII", BPF_MAP_TYPE_XSKMAP, 4, 4,
                           max_queues, 0).ljust(128, b"\x00")
        self.map_fd = _bpf(BPF_MAP_CREATE, attr)
        for queue, fd in xsk_fds.items():
            self.update_queue(queue, fd)

        insns = b"".join([
            _insn(0x61, 2, 1, 16, 0),                 # r2 = ctx->rx_queue_index
            _insn(0x18, 1, BPF_PSEUDO_MAP_FD, 0, self.map_fd),  # r1 = map
            _insn(0x00, 0, 0, 0, 0),                  # (ld_imm64 second half)
            _insn(0xB7, 3, 0, 0, XDP_PASS),           # r3 = XDP_PASS fallback
            _insn(0x85, 0, 0, 0, BPF_FUNC_redirect_map),
            _insn(0x95, 0, 0, 0, 0),                  # exit
        ])
        license_ = C.create_string_buffer(b"GPL")
        insn_buf = C.create_string_buffer(insns, len(insns))
        log_buf = C.create_string_buffer(4096)
        # bpf_attr PROG_LOAD layout: prog_type, insn_cnt, insns*, license*,
        # log_level, log_size, log_buf*, kern_version, prog_flags,
        # prog_name[16], prog_ifindex, expected_attach_type
        attr = struct.pack(
            "<IIQQIIQII16sII",
            BPF_PROG_TYPE_XDP, len(insns) // 8,
            C.addressof(insn_buf), C.addressof(license_),
            1, len(log_buf), C.addressof(log_buf),
            0, 0, b"bng_xsk_redir", 0, BPF_XDP).ljust(128, b"\x00")
        try:
            self.prog_fd = _bpf(BPF_PROG_LOAD, attr)
        except OSError as e:
            log = log_buf.value.decode(errors="replace").strip()
            raise OSError(e.errno,
                          f"XDP prog rejected by verifier: {log[-400:]}") from e

        # bpf_link attach (generic/SKB rung; detaches when the fd closes)
        attr = struct.pack("<IIII", self.prog_fd, ifindex, BPF_XDP,
                           BPF_F_XDP_SKB_MODE).ljust(128, b"\x00")
        self.link_fd = _bpf(BPF_LINK_CREATE, attr)

    def update_queue(self, queue: int, xsk_fd: int) -> None:
        key = struct.pack("<I", queue)
        val = struct.pack("<I", xsk_fd)
        kb = C.create_string_buffer(key, 4)
        vb = C.create_string_buffer(val, 4)
        attr = struct.pack("<IIQQQ", self.map_fd, 0, C.addressof(kb),
                           C.addressof(vb), 0).ljust(128, b"\x00")
        _bpf(BPF_MAP_UPDATE_ELEM, attr)

    def close(self) -> None:
        for name in ("link_fd", "prog_fd", "map_fd"):
            fd = getattr(self, name)
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
                setattr(self, name, -1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def probe() -> bool:
    """Can this process create BPF maps (CAP_BPF/CAP_SYS_ADMIN)?"""
    try:
        attr = struct.pack("<IIIII", BPF_MAP_TYPE_XSKMAP, 4, 4, 1,
                           0).ljust(128, b"\x00")
        fd = _bpf(BPF_MAP_CREATE, attr)
        os.close(fd)
        return True
    except OSError:
        return False
