"""AF_XDP socket ladder — the wire attach path with graceful fallback.

Role parity: pkg/ebpf/loader.go:294-315 attaches XDP driver-mode first,
falls back to generic mode, then to a stub on dev machines. Here the
rungs are AF_XDP bind modes feeding the TPU dataplane's UMEM
(native/bngxsk.cpp):

    zerocopy  NIC DMA straight into the bngring UMEM (production NICs)
    copy      generic AF_XDP, one kernel copy (veth/dev kernels)
    memory    no AF_XDP (containers without CAP_NET_RAW, CI, macOS):
              the in-memory bngring alone — synthetic sources and the
              wire pump keep the same API

`open_wire(ring, ifname)` walks the ladder and reports which rung it
landed on; every consumer keeps working on any rung.
"""

from __future__ import annotations

import ctypes as C
import os
import subprocess
import threading
from dataclasses import dataclass

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")
_SO_PATH = os.path.join(_HERE, "libbngxsk.so")

MODE_ZEROCOPY = "zerocopy"
MODE_COPY = "copy"
MODE_MEMORY = "memory"

_ERRS = {
    -1: "socket(AF_XDP) failed (kernel support / CAP_NET_RAW)",
    -2: "UMEM registration rejected",
    -3: "ring setsockopts failed",
    -4: "ring mmap failed",
    -5: "interface not found",
    -6: "bind failed in both zerocopy and copy modes",
}

_lib = None
_lib_lock = threading.Lock()


def _build_so() -> str | None:
    src = os.path.join(_SRC_DIR, "bngxsk.cpp")
    if not os.path.exists(src):
        return None
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src):
        return _SO_PATH
    cmd = ["g++", "-O2", "-g", "-Wall", "-fPIC", "-std=c++17", "-shared",
           "-o", _SO_PATH, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return _SO_PATH


def load_native():
    """Load (building if needed) the xsk library, or None off-Linux."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _build_so()
        if path is None:
            return None
        try:
            lib = C.CDLL(path)
        except OSError:
            return None
        lib.bng_xsk_probe.restype = C.c_int
        lib.bng_xsk_probe.argtypes = []
        lib.bng_xsk_open.restype = C.c_void_p
        lib.bng_xsk_open.argtypes = [C.c_char_p, C.c_uint32, C.c_void_p,
                                     C.c_uint64, C.c_uint32, C.c_uint32,
                                     C.POINTER(C.c_int)]
        lib.bng_xsk_mode.restype = C.c_int
        lib.bng_xsk_mode.argtypes = [C.c_void_p]
        lib.bng_xsk_fd.restype = C.c_int
        lib.bng_xsk_fd.argtypes = [C.c_void_p]
        lib.bng_xsk_close.argtypes = [C.c_void_p]
        for name in ("bng_xsk_fill", "bng_xsk_tx"):
            fn = getattr(lib, name)
            fn.restype = C.c_uint32
        lib.bng_xsk_fill.argtypes = [C.c_void_p, C.POINTER(C.c_uint64), C.c_uint32]
        lib.bng_xsk_rx.restype = C.c_uint32
        lib.bng_xsk_rx.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                                   C.POINTER(C.c_uint32), C.c_uint32]
        lib.bng_xsk_tx.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                                   C.POINTER(C.c_uint32), C.c_uint32]
        lib.bng_xsk_complete.restype = C.c_uint32
        lib.bng_xsk_complete.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                                         C.c_uint32]
        _lib = lib
        return _lib


def probe() -> str:
    """Cheapest rung check: can this kernel/container create an AF_XDP
    socket at all? (One syscall, no interface required.)"""
    lib = load_native()
    if lib is None:
        return MODE_MEMORY
    mode = lib.bng_xsk_probe()
    return MODE_MEMORY if mode == 2 else MODE_COPY


@dataclass
class WireAttachment:
    """Result of walking the attach ladder."""

    mode: str  # zerocopy | copy | memory
    xsk: "XskSocket | None"  # None on the memory rung
    detail: str = ""


class XskSocket:
    """A bound AF_XDP socket over a NativeRing's UMEM."""

    def __init__(self, lib, handle, ring):
        self._lib = lib
        self._h = handle
        self.ring = ring  # keeps the UMEM alive
        self.mode = MODE_ZEROCOPY if lib.bng_xsk_mode(handle) == 0 else MODE_COPY

    @property
    def fd(self) -> int:
        return self._lib.bng_xsk_fd(self._h)

    def close(self) -> None:
        if self._h is not None:
            self._lib.bng_xsk_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def open_wire(ring, ifname: str = "", queue: int = 0,
              ring_size: int = 2048) -> WireAttachment:
    """Walk the attach ladder for `ring` (a NativeRing or PyRing).

    With a NativeRing and a usable NIC queue this binds AF_XDP over the
    ring's UMEM (zerocopy, then copy). Anything else lands on the memory
    rung: the in-memory ring keeps serving the same assemble/complete API
    (the reference's stub rung, loader.go:312-315).
    """
    if not ifname:
        return WireAttachment(MODE_MEMORY, None, "no interface requested")
    lib = load_native()
    if lib is None:
        return WireAttachment(MODE_MEMORY, None, "no native xsk library")
    umem = getattr(ring, "umem_ptr", None)
    if umem is None:
        return WireAttachment(MODE_MEMORY, None,
                              "ring has no native UMEM (PyRing)")
    err = C.c_int(0)
    h = lib.bng_xsk_open(ifname.encode(), queue, umem,
                         ring.umem_size, ring.frame_size, ring_size,
                         C.byref(err))
    if not h:
        detail = _ERRS.get(err.value, f"error {err.value}")
        return WireAttachment(MODE_MEMORY, None,
                              f"AF_XDP open on {ifname!r} failed: {detail}")
    sock = XskSocket(lib, h, ring)
    return WireAttachment(sock.mode, sock, f"bound {ifname}:{queue}")
