"""AF_XDP socket ladder — the wire attach path with graceful fallback.

Role parity: pkg/ebpf/loader.go:294-315 attaches XDP driver-mode first,
falls back to generic mode, then to a stub on dev machines. Here the
rungs are AF_XDP bind modes feeding the TPU dataplane's UMEM
(native/bngxsk.cpp):

    zerocopy  NIC DMA straight into the bngring UMEM (production NICs)
    copy      generic AF_XDP, one kernel copy (veth/dev kernels)
    memory    no AF_XDP (containers without CAP_NET_RAW, CI, macOS):
              the in-memory bngring alone — synthetic sources and the
              wire pump keep the same API

`open_wire(ring, ifname)` walks the ladder and reports which rung it
landed on; every consumer keeps working on any rung.
"""

from __future__ import annotations

import ctypes as C
from dataclasses import dataclass

from bng_tpu.runtime import nativelib

MODE_ZEROCOPY = "zerocopy"
MODE_COPY = "copy"
MODE_MEMORY = "memory"

_ERRS = {
    -1: "socket(AF_XDP) failed (kernel support / CAP_NET_RAW)",
    -2: "UMEM registration rejected",
    -3: "ring setsockopts failed",
    -4: "ring mmap failed",
    -5: "interface not found",
    -6: "bind failed in both zerocopy and copy modes",
}

def _configure(lib: C.CDLL) -> None:
    lib.bng_xsk_probe.restype = C.c_int
    lib.bng_xsk_probe.argtypes = []
    lib.bng_xsk_open.restype = C.c_void_p
    lib.bng_xsk_open.argtypes = [C.c_char_p, C.c_uint32, C.c_void_p,
                                 C.c_uint64, C.c_uint32, C.c_uint32,
                                 C.POINTER(C.c_int)]
    lib.bng_xsk_mode.restype = C.c_int
    lib.bng_xsk_mode.argtypes = [C.c_void_p]
    lib.bng_xsk_fd.restype = C.c_int
    lib.bng_xsk_fd.argtypes = [C.c_void_p]
    lib.bng_xsk_close.argtypes = [C.c_void_p]
    lib.bng_xsk_fill.restype = C.c_uint32
    lib.bng_xsk_fill.argtypes = [C.c_void_p, C.POINTER(C.c_uint64), C.c_uint32]
    lib.bng_xsk_rx.restype = C.c_uint32
    lib.bng_xsk_rx.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                               C.POINTER(C.c_uint32), C.c_uint32]
    lib.bng_xsk_tx.restype = C.c_uint32
    lib.bng_xsk_tx.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                               C.POINTER(C.c_uint32), C.c_uint32]
    lib.bng_xsk_complete.restype = C.c_uint32
    lib.bng_xsk_complete.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                                     C.c_uint32]


def load_native():
    """Load (building if needed) the xsk library, or None off-Linux."""
    return nativelib.load("bngxsk", _configure)


def probe() -> str:
    """Cheapest rung check: can this kernel/container create an AF_XDP
    socket at all? (One syscall, no interface required.)"""
    lib = load_native()
    if lib is None:
        return MODE_MEMORY
    mode = lib.bng_xsk_probe()
    return MODE_MEMORY if mode == 2 else MODE_COPY


@dataclass
class WireAttachment:
    """Result of walking the attach ladder."""

    mode: str  # zerocopy | copy | memory
    xsk: "XskSocket | None"  # None on the memory rung
    detail: str = ""


class XskSocket:
    """A bound AF_XDP socket over a NativeRing's UMEM."""

    def __init__(self, lib, handle, ring):
        self._lib = lib
        self._h = handle
        self.ring = ring  # keeps the UMEM alive
        self.mode = MODE_ZEROCOPY if lib.bng_xsk_mode(handle) == 0 else MODE_COPY
        self._tx_pending: list[tuple[int, int]] = []  # (addr, len) awaiting slots
        self.pump_stats = {"filled": 0, "rx": 0, "tx": 0, "completed": 0,
                           "rx_submit_fail": 0}

    def pump(self, budget: int = 64, from_access: bool = True) -> int:
        """One wire-pump round: the glue that makes the real AF_XDP rungs
        serve the engine (the loader.go attach-ladder's data-moving role).

        (a) feed the kernel fill ring from the bngring free pool,
        (b) drain kernel RX -> bng_ring_rx_submit (zero-copy: the frame
            is already in UMEM; classification/steering run there),
        (c) pop TX/FWD verdict descriptors -> kernel TX ring (zero-copy),
        (d) reap TX completions -> frames back to the free pool.
        Returns frames moved (rx+tx)."""
        lib, ring = self._lib, self.ring
        rlib, rh = ring._lib, ring._h
        moved = 0
        # (a) fill
        addrs = []
        for _ in range(budget):
            a = rlib.bng_ring_rx_reserve(rh)
            if a == 0xFFFFFFFFFFFFFFFF:
                break
            addrs.append(a)
        if addrs:
            arr = (C.c_uint64 * len(addrs))(*addrs)
            pushed = lib.bng_xsk_fill(self._h, arr, len(addrs))
            self.pump_stats["filled"] += pushed
            for a in addrs[pushed:]:  # fill ring full: hand frames back
                rlib.bng_ring_frame_free(rh, a)
        # (b) RX. The kernel places the packet at chunk_base + headroom
        # and reports THAT address; the ring's descriptors are chunk-based
        # (the fill pool recycles by base), so normalize: slide the bytes
        # to the chunk start and submit the base. In copy mode the kernel
        # already copied once; this small memmove keeps rung 1 simple —
        # the zerocopy rung will want headroom-aware descriptors instead.
        oa = (C.c_uint64 * budget)()
        ol = (C.c_uint32 * budget)()
        n = lib.bng_xsk_rx(self._h, oa, ol, budget)
        fl = 0x1 if from_access else 0  # FLAG_FROM_ACCESS
        umem_base = C.addressof(ring.umem_ptr.contents)
        for i in range(n):
            off = oa[i] % ring.frame_size
            base = oa[i] - off
            if off:
                C.memmove(umem_base + base, umem_base + oa[i], ol[i])
            if rlib.bng_ring_rx_submit(rh, base, ol[i], fl) != 0:
                self.pump_stats["rx_submit_fail"] += 1
        self.pump_stats["rx"] += n
        moved += n
        # (c) TX: retries first, then fresh verdict descriptors
        txq = self._tx_pending
        addr = C.c_uint64()
        ln = C.c_uint32()
        while len(txq) < budget:
            got = rlib.bng_ring_tx_pop_desc(rh, C.byref(addr), C.byref(ln),
                                            None)
            if not got:
                got = rlib.bng_ring_fwd_pop_desc(rh, C.byref(addr),
                                                 C.byref(ln), None)
            if not got:
                break
            txq.append((addr.value, ln.value))
        if txq:
            ta = (C.c_uint64 * len(txq))(*[a for a, _ in txq])
            tl = (C.c_uint32 * len(txq))(*[l for _, l in txq])
            sent = lib.bng_xsk_tx(self._h, ta, tl, len(txq))
            self.pump_stats["tx"] += sent
            moved += sent
            del txq[:sent]  # unsent stay pending for the next round
        # (d) completions
        ca = (C.c_uint64 * budget)()
        c = lib.bng_xsk_complete(self._h, ca, budget)
        for i in range(c):
            rlib.bng_ring_frame_free(rh, ca[i])
        self.pump_stats["completed"] += c
        return moved

    @property
    def fd(self) -> int:
        return self._lib.bng_xsk_fd(self._h)

    def close(self) -> None:
        if self._h is not None:
            self._lib.bng_xsk_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def open_wire(ring, ifname: str = "", queue: int = 0,
              ring_size: int = 2048) -> WireAttachment:
    """Walk the attach ladder for `ring` (a NativeRing or PyRing).

    With a NativeRing and a usable NIC queue this binds AF_XDP over the
    ring's UMEM (zerocopy, then copy). Anything else lands on the memory
    rung: the in-memory ring keeps serving the same assemble/complete API
    (the reference's stub rung, loader.go:312-315).
    """
    if not ifname:
        return WireAttachment(MODE_MEMORY, None, "no interface requested")
    lib = load_native()
    if lib is None:
        return WireAttachment(MODE_MEMORY, None, "no native xsk library")
    umem = getattr(ring, "umem_ptr", None)
    if umem is None:
        return WireAttachment(MODE_MEMORY, None,
                              "ring has no native UMEM (PyRing)")
    err = C.c_int(0)
    h = lib.bng_xsk_open(ifname.encode(), queue, umem,
                         ring.umem_size, ring.frame_size, ring_size,
                         C.byref(err))
    if not h:
        detail = _ERRS.get(err.value, f"error {err.value}")
        return WireAttachment(MODE_MEMORY, None,
                              f"AF_XDP open on {ifname!r} failed: {detail}")
    sock = XskSocket(lib, h, ring)
    return WireAttachment(sock.mode, sock, f"bound {ifname}:{queue}")
