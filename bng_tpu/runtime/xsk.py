"""AF_XDP socket ladder + wire pump — the wire attach path with
graceful fallback and the batch-native pump that feeds it.

Role parity: pkg/ebpf/loader.go:294-315 attaches XDP driver-mode first,
falls back to generic mode, then to a stub on dev machines. Here the
rungs are AF_XDP bind modes feeding the TPU dataplane's UMEM
(native/bngxsk.cpp):

    zerocopy  NIC DMA straight into the bngring UMEM (production NICs)
    copy      generic AF_XDP, one kernel copy (veth/dev kernels)
    memory    no AF_XDP (containers without CAP_NET_RAW, CI, macOS):
              the in-memory bngring alone — synthetic sources and the
              wire pump keep the same API

`open_wire(ring, ifname)` walks the ladder and reports which rung it
landed on; every consumer keeps working on any rung.

The WIRE PUMP (ISSUE 15) is the glue loop on a live rung: feed the
kernel fill ring from the ring's free pool, drain kernel RX into the
ring (classification/steering happen there), move TX/FWD verdict
descriptors to the kernel TX ring, and reap completions back to the
pool. Two implementations behind ``BNG_WIRE_PUMP`` (the BNG_HOST_PATH
mold — resolved at construction, snapshotted per pump):

- ``scalar`` (default) — the original per-frame ctypes loop: reserve
  per frame, normalize copy-mode headroom with a per-frame memmove,
  submit per frame, pop TX descriptors per frame. This is the A/B
  baseline cohort and the bit-identity oracle.
- ``vector`` — a handful of array-in/array-out ctypes calls over the
  native batch verbs (bngring.h rx_reserve_batch / rx_submit_batch /
  frame_free_batch / out_pop_desc_batch): headroom-aware descriptors
  make the per-frame memmove disappear entirely, and no per-frame
  Python runs on the unpressured path.

Chaos-armed rounds (faults.any_armed()) force the scalar path so
per-call fault-point hit accounting is preserved — the PR-14
fleet/admission discipline. The pump's two phases are named telemetry
stages (``wire_rx`` / ``wire_tx``, spans.py) with DEFAULT_SLOS budgets,
so the kernel<->UMEM hop answers to the same SLO gate as every other
stage (Dapper: the unbudgeted stage is where the regression hides).
"""

from __future__ import annotations

import ctypes as C
import os
from collections import deque
from dataclasses import dataclass

import numpy as np

from bng_tpu.chaos import faults
from bng_tpu.runtime import nativelib
from bng_tpu.telemetry import spans as tele

MODE_ZEROCOPY = "zerocopy"
MODE_COPY = "copy"
MODE_MEMORY = "memory"

_U64_MAX = 0xFFFFFFFFFFFFFFFF

_ERRS = {
    -1: "socket(AF_XDP) failed (kernel support / CAP_NET_RAW)",
    -2: "UMEM registration rejected",
    -3: "ring setsockopts failed",
    -4: "ring mmap failed",
    -5: "interface not found",
    -6: "bind failed in both zerocopy and copy modes",
}

# ---------------------------------------------------------------------------
# pump path selection (the BNG_HOST_PATH / BNG_TABLE_IMPL mold)
# ---------------------------------------------------------------------------

WIRE_PUMPS = ("scalar", "vector")

# Default from BNG_WIRE_PUMP; "scalar" until the vector cohort has
# baselined in the ledger (flip once --wire-ab history exists — the
# flip-after-measurement discipline every impl selector follows).
WIRE_PUMP = os.environ.get("BNG_WIRE_PUMP", "scalar")


def resolved_wire_pump() -> str:
    """The pump path WirePump constructions resolve against. Resolution
    happens at CONSTRUCTION time (snapshotted per pump instance, like
    PyRing.host_path): an env flip after construction needs a new
    attach."""
    if WIRE_PUMP not in WIRE_PUMPS:
        raise ValueError(
            f"BNG_WIRE_PUMP={WIRE_PUMP!r}: expected one of {WIRE_PUMPS}")
    return WIRE_PUMP


def current_wire_pump_label() -> str:
    """Best-effort label for fingerprints/bench lines — never raises
    (ledger.environment_fingerprint calls this via sys.modules)."""
    try:
        return resolved_wire_pump()
    except Exception:  # noqa: BLE001 — a bad env var must not sink a line
        return WIRE_PUMP


def _configure(lib: C.CDLL) -> None:
    lib.bng_xsk_probe.restype = C.c_int
    lib.bng_xsk_probe.argtypes = []
    lib.bng_xsk_open.restype = C.c_void_p
    lib.bng_xsk_open.argtypes = [C.c_char_p, C.c_uint32, C.c_void_p,
                                 C.c_uint64, C.c_uint32, C.c_uint32,
                                 C.POINTER(C.c_int)]
    lib.bng_xsk_mode.restype = C.c_int
    lib.bng_xsk_mode.argtypes = [C.c_void_p]
    lib.bng_xsk_fd.restype = C.c_int
    lib.bng_xsk_fd.argtypes = [C.c_void_p]
    lib.bng_xsk_close.argtypes = [C.c_void_p]
    lib.bng_xsk_fill.restype = C.c_uint32
    lib.bng_xsk_fill.argtypes = [C.c_void_p, C.POINTER(C.c_uint64), C.c_uint32]
    lib.bng_xsk_rx.restype = C.c_uint32
    lib.bng_xsk_rx.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                               C.POINTER(C.c_uint32), C.c_uint32]
    lib.bng_xsk_tx.restype = C.c_uint32
    lib.bng_xsk_tx.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                               C.POINTER(C.c_uint32), C.c_uint32]
    lib.bng_xsk_complete.restype = C.c_uint32
    lib.bng_xsk_complete.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                                     C.c_uint32]


def load_native():
    """Load (building if needed) the xsk library, or None off-Linux."""
    return nativelib.load("bngxsk", _configure)


def probe() -> str:
    """Cheapest rung check: can this kernel/container create an AF_XDP
    socket at all? (One syscall, no interface required.)"""
    lib = load_native()
    if lib is None:
        return MODE_MEMORY
    mode = lib.bng_xsk_probe()
    return MODE_MEMORY if mode == 2 else MODE_COPY


@dataclass
class WireAttachment:
    """Result of walking the attach ladder."""

    mode: str  # zerocopy | copy | memory
    xsk: "XskSocket | None"  # None on the memory rung
    detail: str = ""


def _u64p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint64))


def _u32p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint32))


# ---------------------------------------------------------------------------
# kernel ports: the four AF_XDP ring verbs the pump moves frames through
# ---------------------------------------------------------------------------

class XskKernel:
    """The real kernel's rings, via the native bngxsk verbs. Array
    arguments are NumPy buffers owned by the pump (zero per-call
    allocation); every method is one ctypes call."""

    def __init__(self, lib, handle):
        self._lib = lib
        self._h = handle

    def fill(self, addrs: np.ndarray, n: int) -> int:
        return int(self._lib.bng_xsk_fill(self._h, _u64p(addrs), n))

    def rx(self, out_addrs: np.ndarray, out_lens: np.ndarray) -> int:
        return int(self._lib.bng_xsk_rx(self._h, _u64p(out_addrs),
                                        _u32p(out_lens), len(out_addrs)))

    def tx(self, addrs: np.ndarray, lens: np.ndarray, n: int) -> int:
        return int(self._lib.bng_xsk_tx(self._h, _u64p(addrs),
                                        _u32p(lens), n))

    def complete(self, out_addrs: np.ndarray) -> int:
        return int(self._lib.bng_xsk_complete(self._h, _u64p(out_addrs),
                                              len(out_addrs)))


class _FifoU64:
    """Fixed-capacity NumPy FIFO — SimKernelRings' ring storage. Bulk
    push/pop so the sim kernel's verbs cost the same O(1)-ish work for
    both pump cohorts (a per-frame sim would dilute the A/B ratio)."""

    def __init__(self, cap: int, dtype=np.uint64):
        self.buf = np.zeros(cap, dtype=dtype)
        self.cap = cap
        self.h = 0
        self.n = 0

    def push(self, arr: np.ndarray, k: int) -> int:
        k = min(k, self.cap - self.n)
        if k:
            pos = (self.h + self.n + np.arange(k)) % self.cap
            self.buf[pos] = arr[:k]
            self.n += k
        return k

    def pop_into(self, out: np.ndarray, k: int) -> int:
        k = min(k, self.n)
        if k:
            pos = (self.h + np.arange(k)) % self.cap
            out[:k] = self.buf[pos]
            self.h = (self.h + k) % self.cap
            self.n -= k
        return k


class SimKernelRings:
    """Deterministic in-process stand-in for the kernel's AF_XDP rings —
    the memory rung's wire kernel (tests, `bench.py --wire-ab`,
    `bng loadtest --wire` without privileges).

    Same four verbs as XskKernel over the ring's REAL UMEM: fill
    stockpiles the pump's free frames, `inject()` plays the far end of
    the wire (frames land at chunk_base + headroom, the copy-mode
    shape), rx hands the pump headroom-offset descriptors, tx reads
    egress frames out of the UMEM, complete reports them sent. Fault
    knobs drive the identity corpus: ``tx_room`` throttles the TX ring
    (kernel TX stall), ``inject(..., claim_len=)`` forges a corrupt RX
    descriptor length (the kernel-misbehavior guard the leak fix pins).

    CONTRACT: delivery happens at inject() time (the far end produces
    asynchronously, outside pump cost), and ``drain_egress()`` must be
    called after a pump round BEFORE the next inject — a completed
    frame returns to the free pool and may be refilled/overwritten.
    """

    def __init__(self, ring, headroom: int = 256, ring_size: int = 2048,
                 tx_room: int | None = None):
        self.umem = ring.umem_view()  # NativeRing only
        self.frame_size = ring.frame_size
        self.headroom = min(headroom, ring.frame_size - 64)
        self.ring_size = ring_size
        self.tx_room = tx_room  # None = no stall
        self._fill = _FifoU64(ring_size)
        self._rx_a = _FifoU64(ring_size)
        self._rx_l = _FifoU64(ring_size, dtype=np.uint32)
        self._cq = _FifoU64(ring_size)
        self._pending: deque = deque()  # injected frames awaiting fill
        self._sent_a: list[int] = []
        self._sent_l: list[int] = []

    # -- far end ----------------------------------------------------------

    def inject(self, frame: bytes, claim_len: int | None = None) -> None:
        """Queue one far-end frame; delivered into UMEM as soon as a
        fill address is available (outside pump laps by contract)."""
        self._pending.append((bytes(frame), claim_len))
        self._deliver()

    def inject_many(self, frames) -> None:
        self._pending.extend((bytes(f), None) for f in frames)
        self._deliver()

    def _deliver(self) -> None:
        one_a = np.zeros(1, dtype=np.uint64)
        one_l = np.zeros(1, dtype=np.uint32)
        while self._pending and self._fill.n:
            if self._rx_a.n >= self.ring_size:
                break  # RX ring full: the real kernel would drop — hold
            frame, claim = self._pending.popleft()
            self._fill.pop_into(one_a, 1)
            base = int(one_a[0])
            room = self.frame_size - self.headroom
            data = frame[:room]
            a = base + self.headroom
            self.umem[a:a + len(data)] = np.frombuffer(data, dtype=np.uint8)
            one_a[0] = a
            one_l[0] = claim if claim is not None else len(data)
            self._rx_a.push(one_a, 1)
            self._rx_l.push(one_l, 1)

    def deliver(self) -> None:
        """Public poke: deliver pending injected frames with whatever
        fill addresses the last pump round stocked (drivers that inject
        before the first fill call this between pump rounds, outside
        the pump's laps by contract)."""
        self._deliver()

    def drain_egress(self) -> list[bytes]:
        """Frames that left the wire since the last drain, TX order.
        Reads the UMEM NOW — call before the next inject round."""
        out = [bytes(self.umem[a:a + ln])
               for a, ln in zip(self._sent_a, self._sent_l)]
        self._sent_a.clear()
        self._sent_l.clear()
        return out

    # -- the four kernel verbs (pump side) --------------------------------

    def fill(self, addrs: np.ndarray, n: int) -> int:
        taken = self._fill.push(addrs, n)
        return taken

    def rx(self, out_addrs: np.ndarray, out_lens: np.ndarray) -> int:
        n = self._rx_a.pop_into(out_addrs, len(out_addrs))
        self._rx_l.pop_into(out_lens, n)
        return n

    def tx(self, addrs: np.ndarray, lens: np.ndarray, n: int) -> int:
        if self.tx_room is not None:
            n = min(n, self.tx_room)
        n = min(n, self._cq.cap - self._cq.n)
        if n:
            self._sent_a.extend(int(a) for a in addrs[:n])
            self._sent_l.extend(int(x) for x in lens[:n])
            self._cq.push(addrs, n)
        return n

    def complete(self, out_addrs: np.ndarray) -> int:
        return self._cq.pop_into(out_addrs, len(out_addrs))


# ---------------------------------------------------------------------------
# the pump
# ---------------------------------------------------------------------------

class WirePump:
    """One wire-pump loop over (ring, kernel) — see the module
    docstring. ``pump()`` runs one round of four phases:

        (a) feed the kernel fill ring from the ring free pool
        (b) drain kernel RX -> ring submit (zero-copy: the frame is
            already in UMEM; classification/steering run in the ring)
        (c) TX/FWD verdict descriptors -> kernel TX ring (zero-copy)
        (d) reap TX completions -> frames back to the free pool

    (a)+(b) lap the ``wire_rx`` stage, (c)+(d) ``wire_tx``. Returns
    frames moved (rx + tx).

    ``_tx_pending`` (descriptors the kernel TX ring refused) is bounded
    by ``tx_pending_cap``: overflow frames are DROPPED back to the free
    pool and counted (``tx_overflow`` in pump_stats + the bng_wire_*
    family) instead of growing without limit under a kernel TX stall —
    the frames are retransmit-recoverable, the memory is not.
    """

    def __init__(self, ring, kernel, path: str | None = None,
                 tx_pending_cap: int = 4096):
        if not hasattr(ring, "umem_view"):
            raise ValueError("WirePump needs a NativeRing (UMEM-backed)")
        self.ring = ring
        self.kernel = kernel
        self.path = path or resolved_wire_pump()
        if self.path not in WIRE_PUMPS:
            raise ValueError(f"unknown wire pump {self.path!r}: "
                             f"expected one of {WIRE_PUMPS}")
        self.tx_pending_cap = int(tx_pending_cap)
        self.last_path = self.path  # what the LAST round actually ran
        self._txq: list[tuple[int, int]] = []  # (addr, len) awaiting slots
        self.pump_stats = {"filled": 0, "rx": 0, "tx": 0, "completed": 0,
                           "rx_submit_fail": 0, "tx_overflow": 0}
        self._cap = 0  # scratch capacity (grown to the largest budget)

    def tx_pending(self) -> int:
        """Verdict descriptors awaiting kernel TX slots (bounded by
        tx_pending_cap) — the bng_wire_tx_pending gauge's source."""
        return len(self._txq)

    # -- scratch ----------------------------------------------------------

    def _ensure(self, budget: int) -> None:
        if budget <= self._cap:
            return
        self._cap = budget
        self._ra = np.zeros(budget, dtype=np.uint64)   # reserve/fill
        self._rxa = np.zeros(budget, dtype=np.uint64)  # kernel RX addrs
        self._rxl = np.zeros(budget, dtype=np.uint32)  # kernel RX lens
        self._ok = np.zeros(budget, dtype=np.uint8)    # submit outcomes
        self._ta = np.zeros(budget, dtype=np.uint64)   # TX addrs
        self._tl = np.zeros(budget, dtype=np.uint32)   # TX lens
        self._ca = np.zeros(budget, dtype=np.uint64)   # completions

    # -- entry ------------------------------------------------------------

    def pump(self, budget: int = 64, from_access: bool = True) -> int:
        """One wire-pump round; returns frames moved (rx + tx)."""
        self._ensure(budget)
        if (self.path == "vector" and not faults.any_armed()):
            # chaos-armed rounds take the scalar oracle so per-call
            # fault-point hit accounting is preserved (the PR-14 mold)
            self.last_path = "vector"
            return self._pump_vector(budget, from_access)
        self.last_path = "scalar"
        return self._pump_scalar(budget, from_access)

    # -- scalar (the per-frame oracle) ------------------------------------

    def _pump_scalar(self, budget: int, from_access: bool) -> int:
        ring, kernel, st = self.ring, self.kernel, self.pump_stats
        rlib, rh = ring._lib, ring._h
        fsz = ring.frame_size
        moved = 0
        t0 = tele.t()
        # (a) fill
        addrs = []
        for _ in range(budget):
            a = rlib.bng_ring_rx_reserve(rh)
            if a == _U64_MAX:
                break
            addrs.append(a)
        if addrs:
            arr = np.array(addrs, dtype=np.uint64)
            pushed = kernel.fill(arr, len(addrs))
            st["filled"] += pushed
            for a in addrs[pushed:]:  # fill ring full: hand frames back
                rlib.bng_ring_frame_free(rh, a)
        # (b) RX. The kernel places the packet at chunk_base + headroom
        # and reports THAT address; the scalar path keeps chunk-based
        # descriptors (the historical shape), so normalize: slide the
        # bytes to the chunk start and submit the base. The vector path
        # submits the offset address as-is (headroom-aware descriptors)
        # and skips this memmove entirely.
        n = kernel.rx(self._rxa[:budget], self._rxl[:budget])
        fl = 0x1 if from_access else 0  # FLAG_FROM_ACCESS
        umem_base = C.addressof(ring.umem_ptr.contents)
        usz = ring.umem_size
        for i in range(n):
            a = int(self._rxa[i])
            ln = int(self._rxl[i])
            if a >= usz:
                # garbage descriptor address (kernel misbehavior):
                # nothing of ours to recycle — frame_free counts the
                # ring's bad_desc like the vector path's
                # rx_submit_batch, and memmove must never see it
                st["rx_submit_fail"] += 1
                rlib.bng_ring_frame_free(rh, a)
                continue
            off = a % fsz
            base = a - off
            if ln > fsz - off:
                # a length that cannot fit the chunk room (kernel
                # misbehavior): drop AND return the frame — an
                # unreturned frame drains the fill pool permanently
                # (the ISSUE 15 leak fix, pinned by test)
                st["rx_submit_fail"] += 1
                rlib.bng_ring_frame_free(rh, base)
                continue
            if off:
                C.memmove(umem_base + base, umem_base + a, ln)
            if rlib.bng_ring_rx_submit(rh, base, ln, fl) != 0:
                # rx-full: bngring recycled the frame internally — the
                # pool is whole either way
                st["rx_submit_fail"] += 1
        st["rx"] += n
        moved += n
        tele.lap(tele.WIRE_RX, t0)
        t0 = tele.t()
        # (c) TX: retries first, then fresh verdict descriptors
        txq = self._txq
        addr = C.c_uint64()
        ln_c = C.c_uint32()
        while len(txq) < budget:
            got = rlib.bng_ring_tx_pop_desc(rh, C.byref(addr),
                                            C.byref(ln_c), None)
            if not got:
                got = rlib.bng_ring_fwd_pop_desc(rh, C.byref(addr),
                                                 C.byref(ln_c), None)
            if not got:
                break
            txq.append((addr.value, ln_c.value))
        if txq:
            k = len(txq)
            self._ensure(k)
            self._ta[:k] = [a for a, _ in txq]
            self._tl[:k] = [l for _, l in txq]
            sent = kernel.tx(self._ta, self._tl, k)
            st["tx"] += sent
            moved += sent
            del txq[:sent]  # unsent stay pending for the next round
        self._bound_pending()
        # (d) completions
        c = kernel.complete(self._ca[:budget])
        for i in range(c):
            a = int(self._ca[i])
            rlib.bng_ring_frame_free(rh, a - a % fsz)
        st["completed"] += c
        tele.lap(tele.WIRE_TX, t0)
        return moved

    def _bound_pending(self) -> None:
        """Satellite: the pending-TX queue is explicitly bounded. Frames
        beyond the cap (kernel TX stalled for multiple rounds) drop back
        to the free pool, newest first, and are counted."""
        txq = self._txq
        cap = self.tx_pending_cap
        if len(txq) <= cap:
            return
        drop = txq[cap:]
        del txq[cap:]
        k = len(drop)
        drop_a = np.array([a for a, _ in drop], dtype=np.uint64)
        self.ring.frame_free_batch(drop_a, k)
        self.pump_stats["tx_overflow"] += k

    # -- vector (array-in/array-out over the native batch verbs) ----------

    def _pump_vector(self, budget: int, from_access: bool) -> int:
        ring, kernel, st = self.ring, self.kernel, self.pump_stats
        moved = 0
        t0 = tele.t()
        # (a) fill: one reserve call, one kernel call, one free call
        m = ring.rx_reserve_batch(self._ra[:budget])
        if m:
            pushed = kernel.fill(self._ra, m)
            st["filled"] += pushed
            if pushed < m:
                ring.frame_free_batch(self._ra[pushed:m], m - pushed)
        # (b) RX -> submit: headroom-offset addresses go in as-is; every
        # failed frame is recycled inside the ring verb
        n = kernel.rx(self._rxa[:budget], self._rxl[:budget])
        if n:
            fl = 0x1 if from_access else 0
            ok = ring.rx_submit_batch(self._rxa, self._rxl, fl,
                                      self._ok, n)
            st["rx_submit_fail"] += n - ok
        st["rx"] += n
        moved += n
        tele.lap(tele.WIRE_RX, t0)
        t0 = tele.t()
        # (c) TX: pending retries first (rare — kernel stalls), then one
        # batch pop of fresh verdict descriptors
        txq = self._txq
        p = len(txq)
        if p:
            self._ensure(p + budget)
            self._ta[:p] = np.array([a for a, _ in txq], dtype=np.uint64)
            self._tl[:p] = np.array([l for _, l in txq], dtype=np.uint32)
            txq.clear()
        fresh = ring.out_pop_desc_batch(self._ta[p:], self._tl[p:],
                                        max(0, budget - p))
        k = p + fresh
        if k:
            sent = kernel.tx(self._ta, self._tl, k)
            st["tx"] += sent
            moved += sent
            if sent < k:  # kernel TX stalled: keep the tail pending
                txq.extend(zip(self._ta[sent:k].tolist(),
                               self._tl[sent:k].tolist()))
                self._bound_pending()
        # (d) completions: one kernel call, one batch free
        c = kernel.complete(self._ca[:budget])
        if c:
            ring.frame_free_batch(self._ca, c)
            st["completed"] += c
        tele.lap(tele.WIRE_TX, t0)
        return moved


class XskSocket:
    """A bound AF_XDP socket over a NativeRing's UMEM."""

    def __init__(self, lib, handle, ring, pump_path: str | None = None):
        self._lib = lib
        self._h = handle
        self.ring = ring  # keeps the UMEM alive
        self.mode = MODE_ZEROCOPY if lib.bng_xsk_mode(handle) == 0 else MODE_COPY
        self.kernel = XskKernel(lib, handle)
        self.wire_pump = WirePump(ring, self.kernel, path=pump_path)

    def pump(self, budget: int = 64, from_access: bool = True) -> int:
        """One wire-pump round (see WirePump.pump)."""
        return self.wire_pump.pump(budget, from_access=from_access)

    @property
    def pump_stats(self) -> dict:
        return self.wire_pump.pump_stats

    @property
    def pump_path(self) -> str:
        return self.wire_pump.path

    @property
    def fd(self) -> int:
        return self._lib.bng_xsk_fd(self._h)

    def close(self) -> None:
        if self._h is not None:
            self._lib.bng_xsk_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def open_wire(ring, ifname: str = "", queue: int = 0,
              ring_size: int = 2048,
              pump_path: str | None = None) -> WireAttachment:
    """Walk the attach ladder for `ring` (a NativeRing or PyRing).

    With a NativeRing and a usable NIC queue this binds AF_XDP over the
    ring's UMEM (zerocopy, then copy). Anything else lands on the memory
    rung: the in-memory ring keeps serving the same assemble/complete API
    (the reference's stub rung, loader.go:312-315). ``pump_path``
    overrides BNG_WIRE_PUMP for the attached socket's pump.
    """
    if not ifname:
        return WireAttachment(MODE_MEMORY, None, "no interface requested")
    lib = load_native()
    if lib is None:
        return WireAttachment(MODE_MEMORY, None, "no native xsk library")
    umem = getattr(ring, "umem_ptr", None)
    if umem is None:
        return WireAttachment(MODE_MEMORY, None,
                              "ring has no native UMEM (PyRing)")
    err = C.c_int(0)
    h = lib.bng_xsk_open(ifname.encode(), queue, umem,
                         ring.umem_size, ring.frame_size, ring_size,
                         C.byref(err))
    if not h:
        detail = _ERRS.get(err.value, f"error {err.value}")
        return WireAttachment(MODE_MEMORY, None,
                              f"AF_XDP open on {ifname!r} failed: {detail}")
    sock = XskSocket(lib, h, ring, pump_path=pump_path)
    return WireAttachment(sock.mode, sock, f"bound {ifname}:{queue}")
