"""Python binding for the native packet ring (native/bngring.cpp).

The ring is the pkg/ebpf replacement's I/O half (SURVEY.md §7): an
AF_XDP-style UMEM + SPSC descriptor rings in C++, consumed here via
ctypes (no pybind11 in the image — C ABI + ctypes is the binding layer).

Build model: the .so is compiled on demand from the in-tree source with
g++ (mirroring how the reference ships bpf/ sources and compiles with
clang at build time, bpf/Makefile). If no C++ toolchain is available the
pure-Python `PyRing` fallback provides the same API — the _stub.go role
(SURVEY.md §4.6) — so tests and dev hosts never hard-require the native
build.
"""

from __future__ import annotations

import ctypes as C
from collections import deque

import numpy as np

from bng_tpu.runtime import hostpath
from bng_tpu.runtime import nativelib

FLAG_FROM_ACCESS = 0x1
# set by the ring on RX when the frame parses as IPv4/UDP dst:67 — the
# consumer may route an all-control batch through the DHCP-only device
# program (BNG_DESC_F_DHCP_CTRL in bngring.h)
FLAG_DHCP_CTRL = 0x2

# the vectorized kernels redeclare the flag bits (circular-import break);
# a drift here would silently mis-classify the whole vector path
assert hostpath.FLAG_FROM_ACCESS == FLAG_FROM_ACCESS
assert hostpath.FLAG_DHCP_CTRL == FLAG_DHCP_CTRL

VERDICT_PASS, VERDICT_DROP, VERDICT_TX, VERDICT_FWD = 0, 1, 2, 3


def classify_dhcp(frame: bytes) -> int:
    """Genuine-DHCP classifier (0-2 VLAN tags) — the PyRing mirror of
    bngring.cpp's classify_dhcp; must agree bit-for-bit. Strict on
    purpose: IPv4 non-fragment UDP dst:67 with BOOTREQUEST op AND the
    DHCP magic cookie — natable port-67 transit, fragments, and non-DHCP
    floods stay on the fused pipeline (NAT/antispoof/QoS treatment).
    Callers gate on from_access (the fused path only answers access-side
    DHCP: dhcp_tx = is_reply & from_access)."""
    if len(frame) < 14:
        return 0
    off = 12
    et = (frame[off] << 8) | frame[off + 1]
    for _ in range(2):
        if et not in (0x8100, 0x88A8):
            break
        off += 4
        if len(frame) < off + 2:
            return 0
        et = (frame[off] << 8) | frame[off + 1]
    off += 2  # L3 start
    if et != 0x0800 or len(frame) < off + 20 or (frame[off] >> 4) != 4:
        return 0
    ihl = (frame[off] & 0x0F) * 4
    if ihl < 20 or frame[off + 9] != 17:
        return 0
    if ((frame[off + 6] << 8) | frame[off + 7]) & 0x3FFF:
        return 0  # fragmented: no parseable L4
    l4 = off + ihl
    if len(frame) < l4 + 8:
        return 0
    dport = (frame[l4 + 2] << 8) | frame[l4 + 3]
    if dport != 67:
        return 0
    bootp = l4 + 8
    if len(frame) < bootp + 240 or frame[bootp] != 1:
        return 0
    magic = int.from_bytes(frame[bootp + 236 : bootp + 240], "big")
    return FLAG_DHCP_CTRL if magic == 0x63825363 else 0


def shard_of(frame: bytes, flags: int, n_shards: int,
             pub_ips: dict[int, int] | None = None) -> int:
    """Owner-shard steering decision — the PyRing mirror of bngring.cpp's
    bng_ring_shard_of; must agree bit-for-bit (spec in bngring.h).

    The subscriber-affinity placement chip-local NAT/QoS/antispoof state
    depends on (parallel/sharded.py): upstream by FNV-1a32(src IP),
    downstream by NAT-public-IP ownership (pub_ips: host-order IP ->
    shard) falling back to FNV-1a32(dst IP), DHCP-control and non-IPv4
    frames by FNV-1a32(src MAC). `flags` are the descriptor flags AFTER
    classification (FROM_ACCESS | DHCP_CTRL)."""
    from bng_tpu.utils.net import fnv1a32

    if n_shards == 1 or len(frame) < 14:
        return 0
    if not (flags & FLAG_DHCP_CTRL):
        off = 12
        et = (frame[off] << 8) | frame[off + 1]
        for _ in range(2):
            if et not in (0x8100, 0x88A8):
                break
            off += 4
            if len(frame) < off + 2:
                break
            et = (frame[off] << 8) | frame[off + 1]
        off += 2  # L3 start
        if et == 0x0800 and len(frame) >= off + 20 and (frame[off] >> 4) == 4:
            if flags & FLAG_FROM_ACCESS:
                return fnv1a32(frame[off + 12 : off + 16]) % n_shards
            dst = frame[off + 16 : off + 20]
            if pub_ips:
                s = pub_ips.get(int.from_bytes(dst, "big"))
                if s is not None and s < n_shards:
                    return s
            return fnv1a32(dst) % n_shards
        if (et == 0x8864 and (flags & FLAG_FROM_ACCESS)
                and len(frame) >= off + 8 + 20
                and frame[off] == 0x11 and frame[off + 1] == 0
                and ((frame[off + 6] << 8) | frame[off + 7]) == 0x0021
                and (frame[off + 8] >> 4) == 4):
            # PPPoE session DATA (PPP proto IPv4): steer by the INNER
            # source IP — the same affinity key the decap'd packet's
            # chip-local NAT/QoS/session state is placed with. PPPoE
            # control (discovery/LCP/auth/IPCP) falls through to the
            # sticky MAC hash; any shard's slow path handles it.
            return fnv1a32(frame[off + 8 + 12 : off + 8 + 16]) % n_shards
    return fnv1a32(frame[6:12]) % n_shards


class RingStats(C.Structure):
    _fields_ = [
        ("rx", C.c_uint64),
        ("tx", C.c_uint64),
        ("fwd", C.c_uint64),
        ("drop", C.c_uint64),
        ("slow", C.c_uint64),
        ("fill_empty", C.c_uint64),
        ("rx_full", C.c_uint64),
        ("tx_full", C.c_uint64),
        ("bad_desc", C.c_uint64),
    ]


class Desc(C.Structure):
    """Python mirror of bng_desc — layout asserted against the C side."""

    _fields_ = [
        ("addr", C.c_uint64),
        ("len", C.c_uint32),
        ("flags", C.c_uint32),
    ]


def _configure(lib: C.CDLL) -> None:
    lib.bng_ring_create.restype = C.c_void_p
    lib.bng_ring_create.argtypes = [C.c_uint32, C.c_uint32, C.c_uint32]
    lib.bng_ring_destroy.argtypes = [C.c_void_p]
    lib.bng_ring_umem.restype = C.POINTER(C.c_uint8)
    lib.bng_ring_umem.argtypes = [C.c_void_p]
    lib.bng_ring_umem_size.restype = C.c_uint64
    lib.bng_ring_umem_size.argtypes = [C.c_void_p]
    lib.bng_ring_frame_size.restype = C.c_uint32
    lib.bng_ring_frame_size.argtypes = [C.c_void_p]
    lib.bng_ring_rx_push.restype = C.c_int
    lib.bng_ring_rx_push.argtypes = [C.c_void_p, C.POINTER(C.c_uint8),
                                     C.c_uint32, C.c_uint32]
    lib.bng_batch_assemble.restype = C.c_uint32
    lib.bng_batch_assemble.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint8), C.POINTER(C.c_uint32),
        C.POINTER(C.c_uint32), C.c_uint32, C.c_uint32]
    lib.bng_ring_create_sharded.restype = C.c_void_p
    lib.bng_ring_create_sharded.argtypes = [C.c_uint32, C.c_uint32,
                                            C.c_uint32, C.c_uint32]
    lib.bng_ring_n_shards.restype = C.c_uint32
    lib.bng_ring_n_shards.argtypes = [C.c_void_p]
    lib.bng_ring_steer_pub_ip.restype = C.c_int
    lib.bng_ring_steer_pub_ip.argtypes = [C.c_void_p, C.c_uint32, C.c_uint32]
    lib.bng_ring_shard_of.restype = C.c_uint32
    lib.bng_ring_shard_of.argtypes = [C.c_void_p, C.POINTER(C.c_uint8),
                                      C.c_uint32, C.c_uint32]
    lib.bng_batch_assemble_sharded.restype = C.c_uint32
    lib.bng_batch_assemble_sharded.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint8), C.POINTER(C.c_uint32),
        C.POINTER(C.c_uint32), C.c_uint32, C.c_uint32]
    lib.bng_ring_shard_rx_pending.restype = C.c_uint32
    lib.bng_ring_shard_rx_pending.argtypes = [C.c_void_p, C.c_uint32]
    lib.bng_ring_rx_reserve.restype = C.c_uint64
    lib.bng_ring_rx_reserve.argtypes = [C.c_void_p]
    lib.bng_ring_rx_submit.restype = C.c_int
    lib.bng_ring_rx_submit.argtypes = [C.c_void_p, C.c_uint64, C.c_uint32,
                                       C.c_uint32]
    # batch wire verbs (vector wire pump, ISSUE 15)
    lib.bng_ring_rx_reserve_batch.restype = C.c_uint32
    lib.bng_ring_rx_reserve_batch.argtypes = [C.c_void_p,
                                              C.POINTER(C.c_uint64),
                                              C.c_uint32]
    lib.bng_ring_rx_submit_batch.restype = C.c_uint32
    lib.bng_ring_rx_submit_batch.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint64), C.POINTER(C.c_uint32),
        C.c_uint32, C.POINTER(C.c_uint8), C.c_uint32]
    lib.bng_ring_frame_free_batch.restype = C.c_uint32
    lib.bng_ring_frame_free_batch.argtypes = [C.c_void_p,
                                              C.POINTER(C.c_uint64),
                                              C.c_uint32]
    lib.bng_ring_out_pop_desc_batch.restype = C.c_uint32
    lib.bng_ring_out_pop_desc_batch.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint64), C.POINTER(C.c_uint32),
        C.c_uint32]
    for name in ("tx_pop_desc", "fwd_pop_desc"):
        fn = getattr(lib, f"bng_ring_{name}")
        fn.restype = C.c_int
        fn.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                       C.POINTER(C.c_uint32), C.POINTER(C.c_uint32)]
    lib.bng_ring_frame_free.restype = C.c_int
    lib.bng_ring_frame_free.argtypes = [C.c_void_p, C.c_uint64]
    lib.bng_ring_tx_inject.restype = C.c_int
    lib.bng_ring_tx_inject.argtypes = [C.c_void_p, C.POINTER(C.c_uint8),
                                       C.c_uint32, C.c_uint32]
    lib.bng_batch_complete.restype = C.c_int
    lib.bng_batch_complete.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint8), C.POINTER(C.c_uint8),
        C.POINTER(C.c_uint32), C.c_uint32, C.c_uint32]
    for name in ("tx", "fwd", "slow"):
        fn = getattr(lib, f"bng_ring_{name}_pop")
        fn.restype = C.c_int
        fn.argtypes = [C.c_void_p, C.POINTER(C.c_uint8), C.c_uint32,
                       C.POINTER(C.c_uint32)]
    for name in ("rx_pending", "tx_pending", "fwd_pending",
                 "slow_pending", "free_frames"):
        fn = getattr(lib, f"bng_ring_{name}")
        fn.restype = C.c_uint32
        fn.argtypes = [C.c_void_p]
    lib.bng_ring_get_stats.argtypes = [C.c_void_p, C.POINTER(RingStats)]
    lib.bng_wire_pump.restype = C.c_int
    lib.bng_wire_pump.argtypes = [C.c_void_p, C.c_void_p, C.c_uint32]
    for name in ("desc_size", "desc_addr_off", "desc_len_off",
                 "desc_flags_off", "stats_size", "version"):
        fn = getattr(lib, f"bng_abi_{name}")
        fn.restype = C.c_uint32
        fn.argtypes = []


def load_native():
    """Load (building if needed) the native library, or None."""
    return nativelib.load("bngring", _configure)


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint8))


def _u32p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint32))


def _u64p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint64))


class NativeRing:
    """One port's ring pair backed by the C++ UMEM/SPSC implementation."""

    def __init__(self, nframes: int = 4096, frame_size: int = 2048,
                 depth: int = 1024, n_shards: int = 1):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native ring library unavailable")
        self._lib = lib
        self._h = lib.bng_ring_create_sharded(nframes, frame_size, depth,
                                              n_shards)
        if not self._h:
            raise RuntimeError("bng_ring_create failed (sizes must be pow2, "
                               "1 <= n_shards <= 64)")
        self.frame_size = frame_size
        self.depth = depth
        self.n_shards = n_shards

    @property
    def umem_ptr(self):
        """Raw UMEM base pointer — the AF_XDP registration area (xsk.py)."""
        return self._lib.bng_ring_umem(self._h)

    @property
    def umem_size(self) -> int:
        return self._lib.bng_ring_umem_size(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.bng_ring_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    # -- producer --
    def rx_push(self, frame: bytes, from_access: bool = True) -> bool:
        buf = np.frombuffer(frame, dtype=np.uint8)
        fl = FLAG_FROM_ACCESS if from_access else 0
        return self._lib.bng_ring_rx_push(self._h, _u8p(buf), len(frame), fl) == 0

    def rx_push_batch(self, frames: list[bytes],
                      from_access: bool = True) -> int:
        """Batch producer: classification/steering already happen in C++
        per push, so the native ring just loops; the PyRing vector path
        overrides this with one vectorized classify+steer+stage pass.
        Returns frames accepted (stops at the first refusal, like a
        filling RX ring)."""
        n = 0
        for f in frames:
            if not self.rx_push(f, from_access=from_access):
                break
            n += 1
        return n

    def tx_inject(self, frame: bytes, from_access: bool = True) -> bool:
        buf = np.frombuffer(frame, dtype=np.uint8)
        fl = FLAG_FROM_ACCESS if from_access else 0
        return self._lib.bng_ring_tx_inject(self._h, _u8p(buf), len(frame), fl) == 0

    # -- batch wire verbs (the vector wire pump, runtime/xsk.py) --------
    def umem_view(self) -> np.ndarray:
        """Zero-copy uint8 view over the whole UMEM (the vector pump's
        and the sim kernel's frame access — no per-frame ctypes)."""
        if self._umem_view is None:
            self._umem_view = np.ctypeslib.as_array(
                self.umem_ptr, shape=(self.umem_size,))
        return self._umem_view

    _umem_view = None

    def rx_reserve_batch(self, out_addrs: np.ndarray) -> int:
        """Pop up to len(out_addrs) free frames into out_addrs (uint64).
        Returns the count reserved (one fill_empty stat on a dry pool)."""
        return int(self._lib.bng_ring_rx_reserve_batch(
            self._h, _u64p(out_addrs), len(out_addrs)))

    def rx_submit_batch(self, addrs: np.ndarray, lens: np.ndarray,
                        flags: int, out_ok: np.ndarray, n: int) -> int:
        """Headroom-aware batch submit (see bngring.h): every failed
        frame is already recycled to the fill pool. Returns count
        submitted; out_ok[:n] marks per-frame outcomes."""
        return int(self._lib.bng_ring_rx_submit_batch(
            self._h, _u64p(addrs), _u32p(lens), flags, _u8p(out_ok), n))

    def frame_free_batch(self, addrs: np.ndarray, n: int) -> int:
        """Return n frames to the fill pool (chunk-base normalized)."""
        return int(self._lib.bng_ring_frame_free_batch(
            self._h, _u64p(addrs), n))

    def out_pop_desc_batch(self, addrs: np.ndarray, lens: np.ndarray,
                           cap: int) -> int:
        """Drain up to cap TX-then-FWD descriptors (frames stay in
        UMEM). Returns count popped."""
        return int(self._lib.bng_ring_out_pop_desc_batch(
            self._h, _u64p(addrs), _u32p(lens), cap))

    # -- steering --
    def steer_pub_ip(self, ip: int, shard: int) -> bool:
        """Register a NAT public IP (host order) as owned by `shard`."""
        return self._lib.bng_ring_steer_pub_ip(self._h, ip, shard) == 0

    def shard_of(self, frame: bytes, flags: int) -> int:
        buf = np.frombuffer(frame, dtype=np.uint8)
        return int(self._lib.bng_ring_shard_of(self._h, _u8p(buf),
                                               len(frame), flags))

    # -- consumer --
    def assemble(self, out: np.ndarray, out_len: np.ndarray,
                 out_flags: np.ndarray) -> int:
        """Fill out[B, slot] (uint8 C-contiguous) from RX; returns count."""
        B, slot = out.shape
        return int(self._lib.bng_batch_assemble(
            self._h, _u8p(out), _u32p(out_len), _u32p(out_flags), B, slot))

    def assemble_sharded(self, out: np.ndarray, out_len: np.ndarray,
                         out_flags: np.ndarray) -> int:
        """Sharded assemble: out is [n_shards*b, slot]; shard i's lanes land
        at rows i*b..(i+1)*b (ShardedCluster.step's layout), padding rows
        zeroed. Returns the number of REAL frames staged; when nonzero the
        opened window must be completed with n = out.shape[0]."""
        B, slot = out.shape
        if B % self.n_shards:
            raise ValueError(f"batch {B} not divisible by {self.n_shards} shards")
        if B // self.n_shards > self.depth:
            # the C side refuses (total rows > in-flight capacity) by
            # returning 0 — which a caller cannot tell from "no traffic";
            # surface the geometry error loudly instead of stalling forever
            raise ValueError(
                f"b_per_shard {B // self.n_shards} exceeds ring depth "
                f"{self.depth}")
        return int(self._lib.bng_batch_assemble_sharded(
            self._h, _u8p(out), _u32p(out_len), _u32p(out_flags),
            B // self.n_shards, slot))

    def complete(self, verdict: np.ndarray, out: np.ndarray,
                 out_len: np.ndarray, n: int) -> None:
        slot = out.shape[1]
        rc = self._lib.bng_batch_complete(
            self._h, _u8p(verdict.astype(np.uint8, copy=False)), _u8p(out),
            _u32p(out_len), n, slot)
        if rc != 0:
            raise RuntimeError("batch_complete: no batch in flight / n mismatch")

    def _pop(self, which: str) -> tuple[bytes, int] | None:
        # one reused staging row (was a fresh np.zeros per pop — a pure
        # allocation on the reply drain; the C side overwrites [0, rc))
        buf = self._pop_buf
        if buf is None:
            buf = self._pop_buf = np.zeros((self.frame_size,),
                                           dtype=np.uint8)
        fl = C.c_uint32(0)
        rc = getattr(self._lib, f"bng_ring_{which}_pop")(
            self._h, _u8p(buf), self.frame_size, C.byref(fl))
        if rc <= 0:
            return None
        return bytes(buf[:rc]), fl.value

    _pop_buf = None  # lazy per-ring reply staging row

    def tx_pop(self):
        return self._pop("tx")

    def fwd_pop(self):
        return self._pop("fwd")

    def slow_pop(self):
        return self._pop("slow")

    def tx_pop_batch(self, limit: int | None = None) -> list:
        """Drain up to `limit` TX frames as [(bytes, flags)] — the C side
        pops per frame either way; the PyRing vector path overrides this
        with one gather."""
        out = []
        while limit is None or len(out) < limit:
            got = self.tx_pop()
            if got is None:
                break
            out.append(got)
        return out

    # -- introspection --
    def rx_pending(self) -> int:
        return self._lib.bng_ring_rx_pending(self._h)

    def shard_rx_pending(self, shard: int) -> int:
        return self._lib.bng_ring_shard_rx_pending(self._h, shard)

    def tx_pending(self) -> int:
        return self._lib.bng_ring_tx_pending(self._h)

    def fwd_pending(self) -> int:
        return self._lib.bng_ring_fwd_pending(self._h)

    def slow_pending(self) -> int:
        return self._lib.bng_ring_slow_pending(self._h)

    def free_frames(self) -> int:
        return self._lib.bng_ring_free_frames(self._h)

    def stats(self) -> dict:
        s = RingStats()
        self._lib.bng_ring_get_stats(self._h, C.byref(s))
        return {f: getattr(s, f) for f, _ in RingStats._fields_}


def wire_pump(a, b, budget: int = 256) -> int:
    """Loopback cable between two rings (tests/demo): moves TX+FWD output
    of each ring into the peer's RX, flipping the from_access flag (a
    frame leaving the access side arrives at the core side)."""
    if isinstance(a, NativeRing) and isinstance(b, NativeRing):
        return a._lib.bng_wire_pump(a._h, b._h, budget)
    moved = 0
    for src, dst in ((a, b), (b, a)):
        for _ in range(budget):
            got = src.tx_pop() or src.fwd_pop()
            if got is None:
                break
            frame, fl = got
            dst.rx_push(frame, from_access=(fl & FLAG_FROM_ACCESS) == 0)
            moved += 1
    return moved


class PyRing:
    """Pure-Python ring with the NativeRing API (the _stub.go fallback).

    Two host paths (ISSUE 14), selected per instance by BNG_HOST_PATH
    (or the `host_path` kwarg) in the BNG_TABLE_IMPL mold:

    - ``scalar`` (default) — the original per-frame implementation:
      frames live as bytes in deques, classify/steer run the scalar
      functions per push, assemble/complete loop per frame. This is
      the A/B baseline cohort and the oracle the vector path is pinned
      bit-identical against.
    - ``vector`` — batch-native structure-of-arrays staging: every
      frame lives in one preallocated [nframes, frame_size] uint8
      matrix with length/flag columns; `rx_push_batch` classifies and
      steers the whole batch with vectorized field extraction
      (runtime/hostpath.py), and assemble/assemble_sharded/complete
      are vectorized gathers/scatters. Pressured edge cases (free-pool
      exhaustion or per-shard backpressure mid-batch) fall back to the
      per-frame scalar decisions, so the two paths can never disagree.
    """

    def __init__(self, nframes: int = 4096, frame_size: int = 2048,
                 depth: int = 1024, n_shards: int = 1,
                 host_path: str | None = None):
        if not 1 <= n_shards <= 64:
            raise RuntimeError("1 <= n_shards <= 64")
        self.frame_size = frame_size
        self.depth = depth
        self.n_shards = n_shards
        self.nframes = nframes
        self.host_path = host_path or hostpath.resolved_host_path()
        if self.host_path not in hostpath.HOST_PATHS:
            raise ValueError(f"unknown host path {self.host_path!r}")
        self._vec = self.host_path == "vector"
        self._free = nframes
        self._tx: deque = deque()
        self._fwd: deque = deque()
        self._slow: deque = deque()
        # FIFO of batches; scalar entries are [(frame, fl) | None] lists
        # (None = sharded-assemble padding lane), vector entries are
        # (slot-id array, valid-lane mask) pairs
        self._inflight: list = []
        self._pub_ips: dict[int, int] = {}
        self._pub_sorted = None  # (keys u64 sorted, vals i64) mirror
        self._stats = {k: 0 for k, _ in RingStats._fields_}
        if self._vec:
            # SoA frame store: slot-indexed, preallocated once. The
            # invariant: a slot reachable from an RX queue is ZERO
            # beyond its _len (assemble gathers full-width rows, so a
            # stale tail would leak prior occupants into the device).
            # _ext tracks each slot's possibly-nonzero extent so every
            # writer restores the invariant with a plain rectangular
            # copy — no masked scatters on the hot path.
            self._buf = np.zeros((nframes, frame_size), dtype=np.uint8)
            self._len = np.zeros((nframes,), dtype=np.uint32)
            self._ext = np.zeros((nframes,), dtype=np.uint32)
            self._fl = np.zeros((nframes,), dtype=np.uint32)
            self._slot_stack = np.arange(nframes, dtype=np.uint32)
            # per-shard RX as bounded circular slot queues (depth each):
            # assemble converts queue slices to gathers with no
            # per-frame conversion cost
            self._rxq = np.zeros((n_shards, depth), dtype=np.uint32)
            self._rxh = np.zeros((n_shards,), dtype=np.int64)  # heads
            self._rxc = np.zeros((n_shards,), dtype=np.int64)  # counts
            self._spill: dict[int, bytes] = {}  # replies > frame_size
        else:
            self._rx: list[deque[tuple[bytes, int]]] = [
                deque() for _ in range(n_shards)]

    def close(self) -> None:
        pass

    # -- steering --
    def steer_pub_ip(self, ip: int, shard: int) -> bool:
        if shard >= self.n_shards:
            return False
        self._pub_ips[ip] = shard
        self._pub_sorted = None
        return True

    def shard_of(self, frame: bytes, flags: int) -> int:
        return shard_of(frame, flags, self.n_shards, self._pub_ips)

    def _pub_arrays(self):
        """Sorted-array mirror of the pub-IP steer map (rebuilt lazily
        after steer_pub_ip) — the vector path's O(log n) membership."""
        if self._pub_sorted is None:
            keys = np.fromiter(self._pub_ips.keys(), dtype=np.uint64,
                               count=len(self._pub_ips))
            vals = np.fromiter(self._pub_ips.values(), dtype=np.int64,
                               count=len(self._pub_ips))
            order = np.argsort(keys)
            self._pub_sorted = (keys[order], vals[order])
        return self._pub_sorted

    # -- producer ---------------------------------------------------------

    def rx_push(self, frame: bytes, from_access: bool = True) -> bool:
        if len(frame) > self.frame_size:
            self._stats["bad_desc"] += 1
            return False
        fl = FLAG_FROM_ACCESS if from_access else 0
        if from_access:  # direction gate — see classify_dhcp docstring
            fl |= classify_dhcp(frame)
        shard = self.shard_of(frame, fl)
        if self._free == 0 or self._shard_depth(shard) >= self.depth:
            self._stats["fill_empty" if self._free == 0 else "rx_full"] += 1
            return False
        self._free -= 1
        if self._vec:
            self._enqueue_slot(shard, self._stage_slot(frame, fl))
        else:
            self._rx[shard].append((frame, fl))
        return True

    def rx_push_batch(self, frames: list[bytes],
                      from_access: bool = True) -> int:
        """Batch producer. Scalar: the per-frame loop. Vector: ONE
        vectorized classify+steer pass over the whole batch, staged
        into the SoA store with a single ragged scatter — per-frame
        Python only on the pressured fallback (free-pool or per-shard
        backpressure mid-batch), where admission order matters."""
        if not self._vec:
            return self._push_scalar(frames, from_access)
        return self._rx_push_batch_vec(frames, from_access)

    def _push_scalar(self, frames: list[bytes], from_access: bool) -> int:
        """Per-frame push loop — the scalar batch producer AND the
        vector path's pressured fallback (one copy of the stop-at-
        first-refusal semantics)."""
        n = 0
        for f in frames:
            if not self.rx_push(f, from_access=from_access):
                break
            n += 1
        return n

    def _rx_push_batch_vec(self, frames: list[bytes],
                           from_access: bool) -> int:
        n = len(frames)
        if n == 0:
            return 0
        lens = hostpath.frame_lens(frames)
        if (int(lens.max()) > self.frame_size or self._free < n
                or n > self.nframes):
            # size rejection / free-pool pressure: per-frame decisions
            # (a rejected frame frees no slot; order matters) — the
            # scalar oracle takes over for the WHOLE batch
            return self._push_scalar(frames, from_access)
        # width floor 1: an all-empty batch must classify (to nothing)
        # instead of indexing a zero-width matrix — the scalar oracle
        # ACCEPTS zero-length frames (they hash to shard 0 and ride the
        # slow path), so the vector path must too
        buf = np.empty((n, max(int(lens.max()), 1)), dtype=np.uint8)
        hostpath.pack_into(frames, buf, np.empty((n,), np.uint32),
                           lens=lens)
        fl = np.full(n, FLAG_FROM_ACCESS if from_access else 0,
                     dtype=np.uint32)
        if from_access:
            fl |= hostpath.classify_dhcp_batch(buf, lens)
        if self.n_shards > 1:
            keys, vals = self._pub_arrays()
            shards = hostpath.shard_of_batch(buf, lens, fl, self.n_shards,
                                             keys, vals)
        else:
            shards = np.zeros(n, dtype=np.int64)
        counts = np.bincount(shards, minlength=self.n_shards)
        if ((self._rxc + counts) > self.depth).any():
            # per-shard backpressure mid-batch: scalar decisions
            return self._push_scalar(frames, from_access)
        slots = self._alloc_slots(n)
        self._scatter_frames(slots, buf, lens)
        self._fl[slots] = fl
        for s in np.nonzero(counts)[0]:
            self._enqueue_slots(int(s), slots[shards == s])
        self._free -= n
        return n

    def tx_inject(self, frame: bytes, from_access: bool = True) -> bool:
        if (len(frame) > self.frame_size or self._free == 0
                or len(self._tx) >= self.depth):
            return False
        self._free -= 1
        fl = FLAG_FROM_ACCESS if from_access else 0
        if self._vec:
            slot = self._stage_slot(frame, fl)
            self._tx.append(int(slot))
        else:
            self._tx.append((frame, fl))
        self._stats["tx"] += 1
        return True

    # -- vector SoA plumbing ---------------------------------------------

    def _alloc_slots(self, k: int) -> np.ndarray:
        free = self.nframes - self._used_slots
        assert k <= free
        out = self._slot_stack[free - k: free].copy()
        self._used_slots += k
        return out

    def _release_slots(self, slots: np.ndarray) -> None:
        k = len(slots)
        if k == 0:
            return
        free = self.nframes - self._used_slots
        self._slot_stack[free: free + k] = slots
        self._used_slots -= k

    def _release_slot(self, slot: int) -> None:
        """Single-slot release — the per-frame pop fast path (no array
        ceremony)."""
        self._slot_stack[self.nframes - self._used_slots] = slot
        self._used_slots -= 1

    _used_slots = 0

    def _stage_slot(self, frame: bytes, fl: int) -> int:
        """Single-frame SoA staging (the per-frame producer APIs)."""
        slot = int(self._alloc_slots(1)[0])
        row = self._buf[slot]
        prev = int(self._ext[slot])
        row[: len(frame)] = np.frombuffer(frame, dtype=np.uint8)
        if prev > len(frame):
            row[len(frame): prev] = 0  # restore the zero-tail invariant
        self._len[slot] = len(frame)
        self._ext[slot] = len(frame)
        self._fl[slot] = fl
        return slot

    def _scatter_frames(self, slots: np.ndarray, buf: np.ndarray,
                        lens: np.ndarray) -> None:
        """Packed rows -> SoA slots in ONE rectangular copy. `buf` rows
        are already zero beyond each frame's length (pack_into), so
        copying through the previous occupants' extent both stages the
        frames and restores the zero-tail invariant — no mask."""
        prev = self._ext[slots]
        w = min(int(max(int(lens.max()), int(prev.max()))), self.frame_size)
        src = buf[:, :w] if buf.shape[1] >= w else np.pad(
            buf, ((0, 0), (0, w - buf.shape[1])))
        self._buf[slots, :w] = src
        self._len[slots] = lens
        self._ext[slots] = lens

    def _enqueue_slot(self, shard: int, slot: int) -> None:
        pos = (self._rxh[shard] + self._rxc[shard]) % self.depth
        self._rxq[shard, pos] = slot
        self._rxc[shard] += 1

    def _enqueue_slots(self, shard: int, slots: np.ndarray) -> None:
        k = len(slots)
        pos = (self._rxh[shard] + self._rxc[shard]
               + np.arange(k)) % self.depth
        self._rxq[shard, pos] = slots
        self._rxc[shard] += k

    def _peek_slots(self, shard: int, k: int) -> np.ndarray:
        pos = (self._rxh[shard] + np.arange(k)) % self.depth
        return self._rxq[shard, pos]

    def _advance(self, shard: int, k: int) -> None:
        self._rxh[shard] = (self._rxh[shard] + k) % self.depth
        self._rxc[shard] -= k

    def _shard_depth(self, shard: int) -> int:
        return (int(self._rxc[shard]) if self._vec
                else len(self._rx[shard]))

    MAX_INFLIGHT = 2  # two assemble..complete windows (double buffering)

    def _stage(self, out, out_len, out_flags, row_i, frame, fl, slot):
        # writes the row in place (was a fresh np.zeros row per frame —
        # the ISSUE 14 per-frame-allocation fix on the scalar path too)
        copy = min(len(frame), slot)
        out[row_i, :copy] = np.frombuffer(frame[:copy], dtype=np.uint8)
        out[row_i, copy:] = 0
        out_len[row_i] = copy
        out_flags[row_i] = fl

    # -- consumer ---------------------------------------------------------

    def assemble(self, out: np.ndarray, out_len: np.ndarray,
                 out_flags: np.ndarray) -> int:
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return 0
        if self._vec:
            return self._assemble_vec(out, out_len, out_flags)
        B, slot = out.shape
        batch = []
        n = 0
        # round-robin over shard queues (n_shards==1: plain drain)
        idle, s = 0, 0
        while n < B and idle < self.n_shards:
            if not self._rx[s]:
                idle += 1
            else:
                idle = 0
                frame, fl = self._rx[s].popleft()
                self._stage(out, out_len, out_flags, n, frame, fl, slot)
                batch.append((frame, fl))
                n += 1
            s = (s + 1) % self.n_shards
        if n:
            self._inflight.append(batch)
        self._stats["rx"] += n
        return n

    def _assemble_vec(self, out, out_len, out_flags) -> int:
        """Vectorized assemble: the scalar round-robin drain order is
        exactly lexicographic (queue position, shard) starting at shard
        0 — one lexsort reproduces it bit-for-bit, then one gather
        stages the whole batch."""
        B, slot_w = out.shape
        total = int(self._rxc.sum())
        if total == 0:
            return 0
        if self.n_shards == 1:
            n = min(B, total)
            chosen = self._peek_slots(0, n).astype(np.int64)
            self._advance(0, n)
        else:
            live = np.nonzero(self._rxc)[0]
            # a shard can contribute at most B lanes to this batch: in
            # the (round, shard) lex order any item with per-shard index
            # >= B can never make the first B, so clipping bounds the
            # sort at B*n_live instead of the whole backlog (identical
            # drain order; deep queues made this O(total log total))
            counts = np.minimum(self._rxc[live], B)
            total = int(counts.sum())
            pend = [self._peek_slots(int(s), int(c))
                    for s, c in zip(live, counts)]
            shards_rep = np.repeat(live, counts)
            offs = np.concatenate(([0], np.cumsum(counts[:-1])))
            rounds = np.arange(total) - np.repeat(offs, counts)
            order = np.lexsort((shards_rep, rounds))[:B]
            n = len(order)
            chosen = np.concatenate(pend).astype(np.int64)[order]
            popped = np.bincount(shards_rep[order],
                                 minlength=self.n_shards)
            for s in np.nonzero(popped)[0]:
                self._advance(int(s), int(popped[s]))
        self._gather_rows(chosen, out, out_len, out_flags, 0, n, slot_w)
        self._inflight.append((chosen, np.ones(n, dtype=bool)))
        self._stats["rx"] += n
        return n

    def _gather_rows(self, slots, out, out_len, out_flags, base, n,
                     slot_w) -> None:
        w = min(slot_w, self.frame_size)
        out[base: base + n, :w] = self._buf[slots, :w]
        if slot_w > w:
            out[base: base + n, w:] = 0
        out_len[base: base + n] = np.minimum(self._len[slots], slot_w)
        out_flags[base: base + n] = self._fl[slots]

    def assemble_sharded(self, out: np.ndarray, out_len: np.ndarray,
                         out_flags: np.ndarray) -> int:
        """Per-shard lane ranges — see NativeRing.assemble_sharded."""
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return 0
        B, slot = out.shape
        if B % self.n_shards:
            raise ValueError(f"batch {B} not divisible by {self.n_shards} shards")
        b = B // self.n_shards
        if b > self.depth:  # NativeRing parity: geometry error, not "empty"
            raise ValueError(f"b_per_shard {b} exceeds ring depth {self.depth}")
        if self._vec:
            return self._assemble_sharded_vec(out, out_len, out_flags, b,
                                              slot)
        batch: list[tuple[bytes, int] | None] = []
        got = 0
        for s in range(self.n_shards):
            for _ in range(b):
                if self._rx[s]:
                    frame, fl = self._rx[s].popleft()
                    self._stage(out, out_len, out_flags, len(batch), frame,
                                fl, slot)
                    batch.append((frame, fl))
                    got += 1
                else:
                    out[len(batch)] = 0
                    out_len[len(batch)] = 0
                    out_flags[len(batch)] = 0
                    batch.append(None)  # padding lane
        if got:
            self._inflight.append(batch)
        self._stats["rx"] += got
        return got

    def _assemble_sharded_vec(self, out, out_len, out_flags, b,
                              slot_w) -> int:
        """Vectorized sharded assemble: one gather per LIVE shard (bound
        by n_shards, never by frames), padding lanes zeroed wholesale."""
        B = b * self.n_shards
        slots = np.zeros(B, dtype=np.int64)
        valid = np.zeros(B, dtype=bool)
        got = 0
        for s in range(self.n_shards):
            k = min(int(self._rxc[s]), b)
            base = s * b
            if k:
                sl = self._peek_slots(s, k).astype(np.int64)
                self._advance(s, k)
                self._gather_rows(sl, out, out_len, out_flags, base, k,
                                  slot_w)
                slots[base: base + k] = sl
                valid[base: base + k] = True
                got += k
            if k < b:
                out[base + k: base + b] = 0
                out_len[base + k: base + b] = 0
                out_flags[base + k: base + b] = 0
        if got:
            self._inflight.append((slots, valid))
        self._stats["rx"] += got
        return got

    def complete(self, verdict: np.ndarray, out: np.ndarray,
                 out_len: np.ndarray, n: int) -> None:
        # retires the OLDEST outstanding batch (FIFO, like the C side)
        if self._vec:
            if not self._inflight or n != len(self._inflight[0][0]):
                raise RuntimeError("batch_complete: n mismatch")
            return self._complete_vec(verdict, out, out_len, n)
        if not self._inflight or n != len(self._inflight[0]):
            raise RuntimeError("batch_complete: n mismatch")
        batch = self._inflight.pop(0)
        for i in range(n):
            if batch[i] is None:  # sharded-assemble padding lane
                continue
            frame, fl = batch[i]
            v = int(verdict[i])
            if v in (VERDICT_TX, VERDICT_FWD):
                payload = bytes(out[i, : int(out_len[i])])
                dst, stat = (self._tx, "tx") if v == VERDICT_TX else (self._fwd, "fwd")
            elif v == VERDICT_PASS:
                payload, dst, stat = frame, self._slow, "slow"
            else:
                self._stats["drop"] += 1
                self._free += 1
                continue
            if len(dst) < self.depth:
                dst.append((payload, fl))  # frame stays held until popped
                self._stats[stat] += 1
            else:
                self._stats["tx_full"] += 1
                self._free += 1

    def _complete_vec(self, verdict, out, out_len, n) -> None:
        """Vectorized verdict demux: masked rank accounting reproduces
        the scalar lane-order queue-capacity semantics (the first
        `room` lanes of each verdict class are accepted), and TX/FWD
        payloads scatter back into the SoA store in one ragged write —
        the per-frame reply-buffer rebuild this ISSUE exists to kill."""
        slots, valid = self._inflight.pop(0)
        vv = np.asarray(verdict)[:n]
        ol = np.asarray(out_len)[:n].astype(np.int64)
        freed = np.zeros(n, dtype=bool)
        for code, dst, stat in ((VERDICT_TX, self._tx, "tx"),
                                (VERDICT_FWD, self._fwd, "fwd"),
                                (VERDICT_PASS, self._slow, "slow")):
            m = valid & (vv == code)
            cnt = int(m.sum())
            if not cnt:
                continue
            room = self.depth - len(dst)
            if cnt > room:
                rank = np.cumsum(m) - 1
                acc = m & (rank < room)
                over = m & ~acc
                self._stats["tx_full"] += int(over.sum())
                freed |= over
                m = acc
                cnt = room
                if cnt <= 0:
                    continue
            if code != VERDICT_PASS:
                lanes = np.nonzero(m)[0]
                sl = slots[lanes]
                ll = ol[lanes]
                fit = ll <= self.frame_size
                if fit.all():
                    self._scatter_rows_from(out, lanes, sl, ll)
                else:
                    self._scatter_rows_from(out, lanes[fit], sl[fit],
                                            ll[fit])
                    for lane, slot in zip(lanes[~fit], sl[~fit]):
                        # reply wider than the UMEM slot: spill to bytes
                        # (per-frame on exactly these lanes; scalar
                        # parity — it stores the bytes either way)
                        self._spill[int(slot)] = bytes(
                            out[int(lane), : int(ol[lane])])
                dst.extend(sl.tolist())
            else:
                dst.extend(slots[m].tolist())
            self._stats[stat] += cnt
        drop = valid & ~np.isin(vv, (VERDICT_TX, VERDICT_FWD, VERDICT_PASS))
        ndrop = int(drop.sum())
        if ndrop:
            self._stats["drop"] += ndrop
            freed |= drop
        if freed.any():
            self._release_slots(slots[freed])
            self._free += int(freed.sum())

    def _scatter_rows_from(self, out, lanes, sl, ll) -> None:
        """TX/FWD payload write-back: out rows -> SoA slots in one
        rectangular copy. Device rows carry no zero guarantee beyond
        out_len, so the written width becomes the slot's possibly-dirty
        extent (_ext): pops read only [:len], and the next RX occupant
        zeroes through _ext before the slot can reach assemble again."""
        n_l = len(lanes)
        if n_l == 0:
            return
        prev = self._ext[sl]
        w = min(int(max(int(ll.max()), int(prev.max()))), self.frame_size)
        src = out if n_l == len(out) else out[lanes]
        if src.shape[1] >= w:
            src = src[:, :w]
        else:
            src = np.pad(src, ((0, 0), (0, w - src.shape[1])))
        self._buf[sl, :w] = src
        self._len[sl] = ll
        self._ext[sl] = w

    def _pop(self, q: deque):
        if not q:
            return None
        item = q.popleft()
        self._free += 1
        if not self._vec:
            return item
        slot = item
        sp = self._spill.pop(slot, None) if self._spill else None
        payload = (sp if sp is not None
                   else bytes(self._buf[slot, : self._len[slot]]))
        fl = int(self._fl[slot])
        self._release_slot(slot)
        return payload, fl

    def tx_pop(self):
        return self._pop(self._tx)

    def fwd_pop(self):
        return self._pop(self._fwd)

    def slow_pop(self):
        return self._pop(self._slow)

    def tx_pop_batch(self, limit: int | None = None) -> list:
        """Drain up to `limit` TX frames as [(bytes, flags)]. Vector:
        one SoA gather + one tobytes for the whole drain (the reply
        consumer's per-frame bytes() rebuild was ~5x the scalar pop
        cost); scalar: the per-frame loop."""
        k = len(self._tx)
        if limit is not None:
            k = min(k, limit)
        if k == 0:
            return []
        if not self._vec:
            out = []
            for _ in range(k):
                out.append(self._pop(self._tx))
            return out
        slots = np.fromiter((self._tx.popleft() for _ in range(k)),
                            dtype=np.int64, count=k)
        lens = self._len[slots].tolist()
        fls = self._fl[slots].tolist()
        W = self.frame_size
        big = self._buf[slots].tobytes()
        out = [(big[i * W: i * W + lens[i]], fls[i]) for i in range(k)]
        if self._spill:
            for i, s in enumerate(slots.tolist()):
                sp = self._spill.pop(int(s), None)
                if sp is not None:
                    out[i] = (sp, fls[i])
        self._release_slots(slots.astype(np.uint32))
        self._free += k
        return out

    def rx_pop(self):
        """Frame-level RX consumer (round-robin over shard queues) for
        the tiered scheduler, which stages frames in its own lanes
        instead of the ring's FIFO assemble..complete windows (two lanes
        retire out of order — FIFO complete would deadlock them).
        Returns (frame, flags) or None. PyRing only: the native ring's
        batch assemble is its contract, so the CLI falls back to the
        engine's pipelined loop there."""
        for off in range(self.n_shards):
            s = (self._rx_pop_next + off) % self.n_shards
            if self._shard_depth(s):
                self._rx_pop_next = (s + 1) % self.n_shards
                if self._vec:
                    slot = int(self._peek_slots(s, 1)[0])
                    self._advance(s, 1)
                    frame = bytes(self._buf[slot, : int(self._len[slot])])
                    fl = int(self._fl[slot])
                    self._release_slot(slot)
                else:
                    frame, fl = self._rx[s].popleft()
                self._free += 1
                self._stats["rx"] += 1
                return frame, fl
        return None

    _rx_pop_next = 0  # round-robin cursor for rx_pop

    def rx_pending(self) -> int:
        return (int(self._rxc.sum()) if self._vec
                else sum(len(q) for q in self._rx))

    def shard_rx_pending(self, shard: int) -> int:
        return self._shard_depth(shard) if shard < self.n_shards else 0

    def tx_pending(self) -> int:
        return len(self._tx)

    def fwd_pending(self) -> int:
        return len(self._fwd)

    def slow_pending(self) -> int:
        return len(self._slow)

    def free_frames(self) -> int:
        return self._free

    def stats(self) -> dict:
        return dict(self._stats)


def make_ring(nframes: int = 4096, frame_size: int = 2048,
              depth: int = 1024, prefer_native: bool = True,
              n_shards: int = 1):
    """NativeRing when the toolchain allows, PyRing otherwise."""
    if prefer_native:
        try:
            return NativeRing(nframes, frame_size, depth, n_shards)
        except RuntimeError:
            pass
    return PyRing(nframes, frame_size, depth, n_shards)
