"""Python binding for the native packet ring (native/bngring.cpp).

The ring is the pkg/ebpf replacement's I/O half (SURVEY.md §7): an
AF_XDP-style UMEM + SPSC descriptor rings in C++, consumed here via
ctypes (no pybind11 in the image — C ABI + ctypes is the binding layer).

Build model: the .so is compiled on demand from the in-tree source with
g++ (mirroring how the reference ships bpf/ sources and compiles with
clang at build time, bpf/Makefile). If no C++ toolchain is available the
pure-Python `PyRing` fallback provides the same API — the _stub.go role
(SURVEY.md §4.6) — so tests and dev hosts never hard-require the native
build.
"""

from __future__ import annotations

import ctypes as C
from collections import deque

import numpy as np

from bng_tpu.runtime import nativelib

FLAG_FROM_ACCESS = 0x1
# set by the ring on RX when the frame parses as IPv4/UDP dst:67 — the
# consumer may route an all-control batch through the DHCP-only device
# program (BNG_DESC_F_DHCP_CTRL in bngring.h)
FLAG_DHCP_CTRL = 0x2

VERDICT_PASS, VERDICT_DROP, VERDICT_TX, VERDICT_FWD = 0, 1, 2, 3


def classify_dhcp(frame: bytes) -> int:
    """Genuine-DHCP classifier (0-2 VLAN tags) — the PyRing mirror of
    bngring.cpp's classify_dhcp; must agree bit-for-bit. Strict on
    purpose: IPv4 non-fragment UDP dst:67 with BOOTREQUEST op AND the
    DHCP magic cookie — natable port-67 transit, fragments, and non-DHCP
    floods stay on the fused pipeline (NAT/antispoof/QoS treatment).
    Callers gate on from_access (the fused path only answers access-side
    DHCP: dhcp_tx = is_reply & from_access)."""
    if len(frame) < 14:
        return 0
    off = 12
    et = (frame[off] << 8) | frame[off + 1]
    for _ in range(2):
        if et not in (0x8100, 0x88A8):
            break
        off += 4
        if len(frame) < off + 2:
            return 0
        et = (frame[off] << 8) | frame[off + 1]
    off += 2  # L3 start
    if et != 0x0800 or len(frame) < off + 20 or (frame[off] >> 4) != 4:
        return 0
    ihl = (frame[off] & 0x0F) * 4
    if ihl < 20 or frame[off + 9] != 17:
        return 0
    if ((frame[off + 6] << 8) | frame[off + 7]) & 0x3FFF:
        return 0  # fragmented: no parseable L4
    l4 = off + ihl
    if len(frame) < l4 + 8:
        return 0
    dport = (frame[l4 + 2] << 8) | frame[l4 + 3]
    if dport != 67:
        return 0
    bootp = l4 + 8
    if len(frame) < bootp + 240 or frame[bootp] != 1:
        return 0
    magic = int.from_bytes(frame[bootp + 236 : bootp + 240], "big")
    return FLAG_DHCP_CTRL if magic == 0x63825363 else 0


def shard_of(frame: bytes, flags: int, n_shards: int,
             pub_ips: dict[int, int] | None = None) -> int:
    """Owner-shard steering decision — the PyRing mirror of bngring.cpp's
    bng_ring_shard_of; must agree bit-for-bit (spec in bngring.h).

    The subscriber-affinity placement chip-local NAT/QoS/antispoof state
    depends on (parallel/sharded.py): upstream by FNV-1a32(src IP),
    downstream by NAT-public-IP ownership (pub_ips: host-order IP ->
    shard) falling back to FNV-1a32(dst IP), DHCP-control and non-IPv4
    frames by FNV-1a32(src MAC). `flags` are the descriptor flags AFTER
    classification (FROM_ACCESS | DHCP_CTRL)."""
    from bng_tpu.utils.net import fnv1a32

    if n_shards == 1 or len(frame) < 14:
        return 0
    if not (flags & FLAG_DHCP_CTRL):
        off = 12
        et = (frame[off] << 8) | frame[off + 1]
        for _ in range(2):
            if et not in (0x8100, 0x88A8):
                break
            off += 4
            if len(frame) < off + 2:
                break
            et = (frame[off] << 8) | frame[off + 1]
        off += 2  # L3 start
        if et == 0x0800 and len(frame) >= off + 20 and (frame[off] >> 4) == 4:
            if flags & FLAG_FROM_ACCESS:
                return fnv1a32(frame[off + 12 : off + 16]) % n_shards
            dst = frame[off + 16 : off + 20]
            if pub_ips:
                s = pub_ips.get(int.from_bytes(dst, "big"))
                if s is not None and s < n_shards:
                    return s
            return fnv1a32(dst) % n_shards
        if (et == 0x8864 and (flags & FLAG_FROM_ACCESS)
                and len(frame) >= off + 8 + 20
                and frame[off] == 0x11 and frame[off + 1] == 0
                and ((frame[off + 6] << 8) | frame[off + 7]) == 0x0021
                and (frame[off + 8] >> 4) == 4):
            # PPPoE session DATA (PPP proto IPv4): steer by the INNER
            # source IP — the same affinity key the decap'd packet's
            # chip-local NAT/QoS/session state is placed with. PPPoE
            # control (discovery/LCP/auth/IPCP) falls through to the
            # sticky MAC hash; any shard's slow path handles it.
            return fnv1a32(frame[off + 8 + 12 : off + 8 + 16]) % n_shards
    return fnv1a32(frame[6:12]) % n_shards


class RingStats(C.Structure):
    _fields_ = [
        ("rx", C.c_uint64),
        ("tx", C.c_uint64),
        ("fwd", C.c_uint64),
        ("drop", C.c_uint64),
        ("slow", C.c_uint64),
        ("fill_empty", C.c_uint64),
        ("rx_full", C.c_uint64),
        ("tx_full", C.c_uint64),
        ("bad_desc", C.c_uint64),
    ]


class Desc(C.Structure):
    """Python mirror of bng_desc — layout asserted against the C side."""

    _fields_ = [
        ("addr", C.c_uint64),
        ("len", C.c_uint32),
        ("flags", C.c_uint32),
    ]


def _configure(lib: C.CDLL) -> None:
    lib.bng_ring_create.restype = C.c_void_p
    lib.bng_ring_create.argtypes = [C.c_uint32, C.c_uint32, C.c_uint32]
    lib.bng_ring_destroy.argtypes = [C.c_void_p]
    lib.bng_ring_umem.restype = C.POINTER(C.c_uint8)
    lib.bng_ring_umem.argtypes = [C.c_void_p]
    lib.bng_ring_umem_size.restype = C.c_uint64
    lib.bng_ring_umem_size.argtypes = [C.c_void_p]
    lib.bng_ring_frame_size.restype = C.c_uint32
    lib.bng_ring_frame_size.argtypes = [C.c_void_p]
    lib.bng_ring_rx_push.restype = C.c_int
    lib.bng_ring_rx_push.argtypes = [C.c_void_p, C.POINTER(C.c_uint8),
                                     C.c_uint32, C.c_uint32]
    lib.bng_batch_assemble.restype = C.c_uint32
    lib.bng_batch_assemble.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint8), C.POINTER(C.c_uint32),
        C.POINTER(C.c_uint32), C.c_uint32, C.c_uint32]
    lib.bng_ring_create_sharded.restype = C.c_void_p
    lib.bng_ring_create_sharded.argtypes = [C.c_uint32, C.c_uint32,
                                            C.c_uint32, C.c_uint32]
    lib.bng_ring_n_shards.restype = C.c_uint32
    lib.bng_ring_n_shards.argtypes = [C.c_void_p]
    lib.bng_ring_steer_pub_ip.restype = C.c_int
    lib.bng_ring_steer_pub_ip.argtypes = [C.c_void_p, C.c_uint32, C.c_uint32]
    lib.bng_ring_shard_of.restype = C.c_uint32
    lib.bng_ring_shard_of.argtypes = [C.c_void_p, C.POINTER(C.c_uint8),
                                      C.c_uint32, C.c_uint32]
    lib.bng_batch_assemble_sharded.restype = C.c_uint32
    lib.bng_batch_assemble_sharded.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint8), C.POINTER(C.c_uint32),
        C.POINTER(C.c_uint32), C.c_uint32, C.c_uint32]
    lib.bng_ring_shard_rx_pending.restype = C.c_uint32
    lib.bng_ring_shard_rx_pending.argtypes = [C.c_void_p, C.c_uint32]
    lib.bng_ring_rx_reserve.restype = C.c_uint64
    lib.bng_ring_rx_reserve.argtypes = [C.c_void_p]
    lib.bng_ring_rx_submit.restype = C.c_int
    lib.bng_ring_rx_submit.argtypes = [C.c_void_p, C.c_uint64, C.c_uint32,
                                       C.c_uint32]
    for name in ("tx_pop_desc", "fwd_pop_desc"):
        fn = getattr(lib, f"bng_ring_{name}")
        fn.restype = C.c_int
        fn.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                       C.POINTER(C.c_uint32), C.POINTER(C.c_uint32)]
    lib.bng_ring_frame_free.restype = C.c_int
    lib.bng_ring_frame_free.argtypes = [C.c_void_p, C.c_uint64]
    lib.bng_ring_tx_inject.restype = C.c_int
    lib.bng_ring_tx_inject.argtypes = [C.c_void_p, C.POINTER(C.c_uint8),
                                       C.c_uint32, C.c_uint32]
    lib.bng_batch_complete.restype = C.c_int
    lib.bng_batch_complete.argtypes = [
        C.c_void_p, C.POINTER(C.c_uint8), C.POINTER(C.c_uint8),
        C.POINTER(C.c_uint32), C.c_uint32, C.c_uint32]
    for name in ("tx", "fwd", "slow"):
        fn = getattr(lib, f"bng_ring_{name}_pop")
        fn.restype = C.c_int
        fn.argtypes = [C.c_void_p, C.POINTER(C.c_uint8), C.c_uint32,
                       C.POINTER(C.c_uint32)]
    for name in ("rx_pending", "tx_pending", "fwd_pending",
                 "slow_pending", "free_frames"):
        fn = getattr(lib, f"bng_ring_{name}")
        fn.restype = C.c_uint32
        fn.argtypes = [C.c_void_p]
    lib.bng_ring_get_stats.argtypes = [C.c_void_p, C.POINTER(RingStats)]
    lib.bng_wire_pump.restype = C.c_int
    lib.bng_wire_pump.argtypes = [C.c_void_p, C.c_void_p, C.c_uint32]
    for name in ("desc_size", "desc_addr_off", "desc_len_off",
                 "desc_flags_off", "stats_size", "version"):
        fn = getattr(lib, f"bng_abi_{name}")
        fn.restype = C.c_uint32
        fn.argtypes = []


def load_native():
    """Load (building if needed) the native library, or None."""
    return nativelib.load("bngring", _configure)


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint8))


def _u32p(arr: np.ndarray):
    return arr.ctypes.data_as(C.POINTER(C.c_uint32))


class NativeRing:
    """One port's ring pair backed by the C++ UMEM/SPSC implementation."""

    def __init__(self, nframes: int = 4096, frame_size: int = 2048,
                 depth: int = 1024, n_shards: int = 1):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native ring library unavailable")
        self._lib = lib
        self._h = lib.bng_ring_create_sharded(nframes, frame_size, depth,
                                              n_shards)
        if not self._h:
            raise RuntimeError("bng_ring_create failed (sizes must be pow2, "
                               "1 <= n_shards <= 64)")
        self.frame_size = frame_size
        self.depth = depth
        self.n_shards = n_shards

    @property
    def umem_ptr(self):
        """Raw UMEM base pointer — the AF_XDP registration area (xsk.py)."""
        return self._lib.bng_ring_umem(self._h)

    @property
    def umem_size(self) -> int:
        return self._lib.bng_ring_umem_size(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.bng_ring_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    # -- producer --
    def rx_push(self, frame: bytes, from_access: bool = True) -> bool:
        buf = np.frombuffer(frame, dtype=np.uint8)
        fl = FLAG_FROM_ACCESS if from_access else 0
        return self._lib.bng_ring_rx_push(self._h, _u8p(buf), len(frame), fl) == 0

    def tx_inject(self, frame: bytes, from_access: bool = True) -> bool:
        buf = np.frombuffer(frame, dtype=np.uint8)
        fl = FLAG_FROM_ACCESS if from_access else 0
        return self._lib.bng_ring_tx_inject(self._h, _u8p(buf), len(frame), fl) == 0

    # -- steering --
    def steer_pub_ip(self, ip: int, shard: int) -> bool:
        """Register a NAT public IP (host order) as owned by `shard`."""
        return self._lib.bng_ring_steer_pub_ip(self._h, ip, shard) == 0

    def shard_of(self, frame: bytes, flags: int) -> int:
        buf = np.frombuffer(frame, dtype=np.uint8)
        return int(self._lib.bng_ring_shard_of(self._h, _u8p(buf),
                                               len(frame), flags))

    # -- consumer --
    def assemble(self, out: np.ndarray, out_len: np.ndarray,
                 out_flags: np.ndarray) -> int:
        """Fill out[B, slot] (uint8 C-contiguous) from RX; returns count."""
        B, slot = out.shape
        return int(self._lib.bng_batch_assemble(
            self._h, _u8p(out), _u32p(out_len), _u32p(out_flags), B, slot))

    def assemble_sharded(self, out: np.ndarray, out_len: np.ndarray,
                         out_flags: np.ndarray) -> int:
        """Sharded assemble: out is [n_shards*b, slot]; shard i's lanes land
        at rows i*b..(i+1)*b (ShardedCluster.step's layout), padding rows
        zeroed. Returns the number of REAL frames staged; when nonzero the
        opened window must be completed with n = out.shape[0]."""
        B, slot = out.shape
        if B % self.n_shards:
            raise ValueError(f"batch {B} not divisible by {self.n_shards} shards")
        if B // self.n_shards > self.depth:
            # the C side refuses (total rows > in-flight capacity) by
            # returning 0 — which a caller cannot tell from "no traffic";
            # surface the geometry error loudly instead of stalling forever
            raise ValueError(
                f"b_per_shard {B // self.n_shards} exceeds ring depth "
                f"{self.depth}")
        return int(self._lib.bng_batch_assemble_sharded(
            self._h, _u8p(out), _u32p(out_len), _u32p(out_flags),
            B // self.n_shards, slot))

    def complete(self, verdict: np.ndarray, out: np.ndarray,
                 out_len: np.ndarray, n: int) -> None:
        slot = out.shape[1]
        rc = self._lib.bng_batch_complete(
            self._h, _u8p(verdict.astype(np.uint8, copy=False)), _u8p(out),
            _u32p(out_len), n, slot)
        if rc != 0:
            raise RuntimeError("batch_complete: no batch in flight / n mismatch")

    def _pop(self, which: str) -> tuple[bytes, int] | None:
        buf = np.zeros((self.frame_size,), dtype=np.uint8)
        fl = C.c_uint32(0)
        rc = getattr(self._lib, f"bng_ring_{which}_pop")(
            self._h, _u8p(buf), self.frame_size, C.byref(fl))
        if rc <= 0:
            return None
        return bytes(buf[:rc]), fl.value

    def tx_pop(self):
        return self._pop("tx")

    def fwd_pop(self):
        return self._pop("fwd")

    def slow_pop(self):
        return self._pop("slow")

    # -- introspection --
    def rx_pending(self) -> int:
        return self._lib.bng_ring_rx_pending(self._h)

    def shard_rx_pending(self, shard: int) -> int:
        return self._lib.bng_ring_shard_rx_pending(self._h, shard)

    def tx_pending(self) -> int:
        return self._lib.bng_ring_tx_pending(self._h)

    def fwd_pending(self) -> int:
        return self._lib.bng_ring_fwd_pending(self._h)

    def slow_pending(self) -> int:
        return self._lib.bng_ring_slow_pending(self._h)

    def free_frames(self) -> int:
        return self._lib.bng_ring_free_frames(self._h)

    def stats(self) -> dict:
        s = RingStats()
        self._lib.bng_ring_get_stats(self._h, C.byref(s))
        return {f: getattr(s, f) for f, _ in RingStats._fields_}


def wire_pump(a, b, budget: int = 256) -> int:
    """Loopback cable between two rings (tests/demo): moves TX+FWD output
    of each ring into the peer's RX, flipping the from_access flag (a
    frame leaving the access side arrives at the core side)."""
    if isinstance(a, NativeRing) and isinstance(b, NativeRing):
        return a._lib.bng_wire_pump(a._h, b._h, budget)
    moved = 0
    for src, dst in ((a, b), (b, a)):
        for _ in range(budget):
            got = src.tx_pop() or src.fwd_pop()
            if got is None:
                break
            frame, fl = got
            dst.rx_push(frame, from_access=(fl & FLAG_FROM_ACCESS) == 0)
            moved += 1
    return moved


class PyRing:
    """Pure-Python ring with the NativeRing API (the _stub.go fallback)."""

    def __init__(self, nframes: int = 4096, frame_size: int = 2048,
                 depth: int = 1024, n_shards: int = 1):
        if not 1 <= n_shards <= 64:
            raise RuntimeError("1 <= n_shards <= 64")
        self.frame_size = frame_size
        self.depth = depth
        self.n_shards = n_shards
        self._free = nframes
        self._rx: list[deque[tuple[bytes, int]]] = [deque()
                                                    for _ in range(n_shards)]
        self._tx: deque[tuple[bytes, int]] = deque()
        self._fwd: deque[tuple[bytes, int]] = deque()
        self._slow: deque[tuple[bytes, int]] = deque()
        # FIFO of batches; None entries = sharded-assemble padding lanes
        self._inflight: list[list[tuple[bytes, int] | None]] = []
        self._pub_ips: dict[int, int] = {}
        self._stats = {k: 0 for k, _ in RingStats._fields_}

    def close(self) -> None:
        pass

    # -- steering --
    def steer_pub_ip(self, ip: int, shard: int) -> bool:
        if shard >= self.n_shards:
            return False
        self._pub_ips[ip] = shard
        return True

    def shard_of(self, frame: bytes, flags: int) -> int:
        return shard_of(frame, flags, self.n_shards, self._pub_ips)

    def rx_push(self, frame: bytes, from_access: bool = True) -> bool:
        if len(frame) > self.frame_size:
            self._stats["bad_desc"] += 1
            return False
        fl = FLAG_FROM_ACCESS if from_access else 0
        if from_access:  # direction gate — see classify_dhcp docstring
            fl |= classify_dhcp(frame)
        shard = self.shard_of(frame, fl)
        if self._free == 0 or len(self._rx[shard]) >= self.depth:
            self._stats["fill_empty" if self._free == 0 else "rx_full"] += 1
            return False
        self._free -= 1
        self._rx[shard].append((frame, fl))
        return True

    def tx_inject(self, frame: bytes, from_access: bool = True) -> bool:
        if len(frame) > self.frame_size or self._free == 0 or len(self._tx) >= self.depth:
            return False
        self._free -= 1
        self._tx.append((frame, FLAG_FROM_ACCESS if from_access else 0))
        self._stats["tx"] += 1
        return True

    MAX_INFLIGHT = 2  # two assemble..complete windows (double buffering)

    def _stage(self, out, out_len, out_flags, row_i, frame, fl, slot):
        copy = min(len(frame), slot)
        row = np.zeros((slot,), dtype=np.uint8)
        row[:copy] = np.frombuffer(frame[:copy], dtype=np.uint8)
        out[row_i] = row
        out_len[row_i] = copy
        out_flags[row_i] = fl

    def assemble(self, out: np.ndarray, out_len: np.ndarray,
                 out_flags: np.ndarray) -> int:
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return 0
        B, slot = out.shape
        batch = []
        n = 0
        # round-robin over shard queues (n_shards==1: plain drain)
        idle, s = 0, 0
        while n < B and idle < self.n_shards:
            if not self._rx[s]:
                idle += 1
            else:
                idle = 0
                frame, fl = self._rx[s].popleft()
                self._stage(out, out_len, out_flags, n, frame, fl, slot)
                batch.append((frame, fl))
                n += 1
            s = (s + 1) % self.n_shards
        if n:
            self._inflight.append(batch)
        self._stats["rx"] += n
        return n

    def assemble_sharded(self, out: np.ndarray, out_len: np.ndarray,
                         out_flags: np.ndarray) -> int:
        """Per-shard lane ranges — see NativeRing.assemble_sharded."""
        if len(self._inflight) >= self.MAX_INFLIGHT:
            return 0
        B, slot = out.shape
        if B % self.n_shards:
            raise ValueError(f"batch {B} not divisible by {self.n_shards} shards")
        b = B // self.n_shards
        if b > self.depth:  # NativeRing parity: geometry error, not "empty"
            raise ValueError(f"b_per_shard {b} exceeds ring depth {self.depth}")
        batch: list[tuple[bytes, int] | None] = []
        got = 0
        for s in range(self.n_shards):
            for _ in range(b):
                if self._rx[s]:
                    frame, fl = self._rx[s].popleft()
                    self._stage(out, out_len, out_flags, len(batch), frame,
                                fl, slot)
                    batch.append((frame, fl))
                    got += 1
                else:
                    out[len(batch)] = 0
                    out_len[len(batch)] = 0
                    out_flags[len(batch)] = 0
                    batch.append(None)  # padding lane
        if got:
            self._inflight.append(batch)
        self._stats["rx"] += got
        return got

    def complete(self, verdict: np.ndarray, out: np.ndarray,
                 out_len: np.ndarray, n: int) -> None:
        # retires the OLDEST outstanding batch (FIFO, like the C side)
        if not self._inflight or n != len(self._inflight[0]):
            raise RuntimeError("batch_complete: n mismatch")
        batch = self._inflight.pop(0)
        for i in range(n):
            if batch[i] is None:  # sharded-assemble padding lane
                continue
            frame, fl = batch[i]
            v = int(verdict[i])
            if v in (VERDICT_TX, VERDICT_FWD):
                payload = bytes(out[i, : int(out_len[i])])
                dst, stat = (self._tx, "tx") if v == VERDICT_TX else (self._fwd, "fwd")
            elif v == VERDICT_PASS:
                payload, dst, stat = frame, self._slow, "slow"
            else:
                self._stats["drop"] += 1
                self._free += 1
                continue
            if len(dst) < self.depth:
                dst.append((payload, fl))  # frame stays held until popped
                self._stats[stat] += 1
            else:
                self._stats["tx_full"] += 1
                self._free += 1

    def _pop(self, q: deque):
        if not q:
            return None
        frame, fl = q.popleft()
        self._free += 1
        return frame, fl

    def tx_pop(self):
        return self._pop(self._tx)

    def fwd_pop(self):
        return self._pop(self._fwd)

    def slow_pop(self):
        return self._pop(self._slow)

    def rx_pop(self):
        """Frame-level RX consumer (round-robin over shard queues) for
        the tiered scheduler, which stages frames in its own lanes
        instead of the ring's FIFO assemble..complete windows (two lanes
        retire out of order — FIFO complete would deadlock them).
        Returns (frame, flags) or None. PyRing only: the native ring's
        batch assemble is its contract, so the CLI falls back to the
        engine's pipelined loop there."""
        for off in range(self.n_shards):
            s = (self._rx_pop_next + off) % self.n_shards
            if self._rx[s]:
                self._rx_pop_next = (s + 1) % self.n_shards
                frame, fl = self._rx[s].popleft()
                self._free += 1
                self._stats["rx"] += 1
                return frame, fl
        return None

    _rx_pop_next = 0  # round-robin cursor for rx_pop

    def rx_pending(self) -> int:
        return sum(len(q) for q in self._rx)

    def shard_rx_pending(self, shard: int) -> int:
        return len(self._rx[shard]) if shard < self.n_shards else 0

    def tx_pending(self) -> int:
        return len(self._tx)

    def fwd_pending(self) -> int:
        return len(self._fwd)

    def slow_pending(self) -> int:
        return len(self._slow)

    def free_frames(self) -> int:
        return self._free

    def stats(self) -> dict:
        return dict(self._stats)


def make_ring(nframes: int = 4096, frame_size: int = 2048,
              depth: int = 1024, prefer_native: bool = True,
              n_shards: int = 1):
    """NativeRing when the toolchain allows, PyRing otherwise."""
    if prefer_native:
        try:
            return NativeRing(nframes, frame_size, depth, n_shards)
        except RuntimeError:
            pass
    return PyRing(nframes, frame_size, depth, n_shards)
