"""Vectorized host-serving-path kernels (ISSUE 14).

PRs 11-13 moved the device half of the serving loop under the 50us
budget, which left the HOST as the ceiling: PERF_NOTES §15 measures
~4.1 ms p50 of host dispatch against a ~97 us device p99, and the stage
breakdown attributes it to per-frame Python — `PyRing` staged every
frame through a fresh `np.zeros` row, `complete()` rebuilt every reply
buffer, admission peeked frames one at a time, `_pack_frames` copied
lane by lane. At 4 ms of host work per batch the host caps throughput
near batch/4ms no matter how fast the chips get.

This module is the batch-native replacement: every per-frame classifier
and field extractor on the ring->dispatch->reply path, re-expressed as
NumPy over a [n, L] uint8 frame matrix + length/flag columns. Two hard
rules:

1. **The scalar functions stay the oracle.** Each kernel here mirrors
   its scalar twin (`ring.classify_dhcp`, `ring.shard_of`,
   `admission.peek_dhcp`, `utils.net.fnv1a32`) guard-for-guard and is
   pinned bit-identical across the frame corpus (runts, truncated
   headers, QinQ, PPPoE LCP/IPCP, relayed giaddr) by
   tests/test_hostpath.py. A vectorized kernel that drifts from its
   oracle is a correctness bug, not a perf trade.
2. **Vector handles the common case; pressure falls back to scalar.**
   Decisions with sequential cross-frame coupling (admission depth
   accounting under inbox pressure, ring free-frame exhaustion
   mid-batch) are taken by the scalar oracle on exactly the frames the
   batch test cannot prove uncoupled — so the two paths can never
   disagree, and the unpressured fast path touches no per-frame Python.

Path selection mirrors BNG_TABLE_IMPL (ops/table.py): BNG_HOST_PATH=
scalar|vector, resolved at construction time by the consumers (PyRing,
SlowPathFleet, Engine). The default stays `scalar` until the vector
cohort has baselined in the perf ledger (`bench.py --host-ab` emits
both cohorts under distinct `host_path` identities; the gate refuses
cross-path comparison with rc=3) — the same flip-after-measurement
discipline the table kernels and the AOT express lane followed.
"""

from __future__ import annotations

import os

import numpy as np

# keep in sync with runtime.ring (imported there; redeclared here to
# avoid a circular import — ring.py asserts they agree)
FLAG_FROM_ACCESS = 0x1
FLAG_DHCP_CTRL = 0x2

HOST_PATHS = ("scalar", "vector")

# Default from BNG_HOST_PATH; "scalar" until the vector cohort has
# baselined in the ledger (flip once --host-ab history exists — the
# BNG_TABLE_IMPL discipline).
HOST_PATH = os.environ.get("BNG_HOST_PATH", "scalar")


def resolved_host_path() -> str:
    """The host path ring/fleet/engine constructions resolve against.
    Resolution happens at CONSTRUCTION time (the resolved choice is
    snapshotted per instance, like Engine.table_impl): an env flip
    after construction needs new instances."""
    if HOST_PATH not in HOST_PATHS:
        raise ValueError(
            f"BNG_HOST_PATH={HOST_PATH!r}: expected one of {HOST_PATHS}")
    return HOST_PATH


def current_host_path_label() -> str:
    """Best-effort label for fingerprints/bench lines — never raises
    (ledger.environment_fingerprint calls this via sys.modules)."""
    try:
        return resolved_host_path()
    except Exception:  # noqa: BLE001 — a bad env var must not sink a line
        return HOST_PATH


# ---------------------------------------------------------------------------
# frame staging: list[bytes] -> [n, L] matrix (the SoA entry point)
# ---------------------------------------------------------------------------

def frame_lens(frames: list[bytes]) -> np.ndarray:
    return np.fromiter(map(len, frames), dtype=np.int64,
                       count=len(frames))


def pack_into(frames: list[bytes], out: np.ndarray, out_len: np.ndarray,
              lens: np.ndarray | None = None) -> int:
    """Stage a frame list into caller-owned [B, L] uint8 + length
    columns with ONE ragged scatter instead of a per-frame copy loop.
    Rows [0, n) are fully written (zero beyond each frame's length —
    staging buffers are reused, stale bytes must never reach the
    device); rows beyond n are left untouched (callers track n).
    Frames longer than L raise like Engine._pack_frames (never
    truncate silently). Returns n."""
    n = len(frames)
    if n == 0:
        return 0
    L = out.shape[1]
    if lens is None:
        lens = frame_lens(frames)
    if int(lens.max()) > L:
        raise ValueError(
            f"frame of {int(lens.max())} bytes exceeds staging slot {L}")
    if int(lens.max()) == 0:
        # all-empty batch: nothing to gather (flat would be size 0 and
        # the clamped index crash) — the scalar oracle accepts
        # zero-length frames, so the packed rows are simply all zeros
        out[:n] = 0
        out_len[:n] = 0
        return n
    flat = np.frombuffer(b"".join(frames), dtype=np.uint8)
    cols = np.arange(L, dtype=np.int64)
    starts = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    # single-pass ragged unpack: gather (clipped) then mask-select — a
    # boolean fancy scatter here costs 3-4x (nonzero scans)
    idx = starts[:, None] + cols[None, :]
    np.minimum(idx, flat.size - 1, out=idx)
    out[:n] = np.where(cols[None, :] < lens[:, None], flat[idx], 0)
    out_len[:n] = lens
    return n


def pack_rows(frames: list[bytes], width: int | None = None
              ) -> tuple[np.ndarray, np.ndarray]:
    """Fresh-matrix convenience wrapper over pack_into (corpus tests,
    one-shot callers). Width defaults to the longest frame."""
    lens = frame_lens(frames)
    w = width if width is not None else (int(lens.max()) if len(frames) else 0)
    buf = np.empty((len(frames), max(w, 1)), dtype=np.uint8)
    out_len = np.zeros((len(frames),), dtype=np.uint32)
    pack_into(frames, buf, out_len, lens=lens)
    return buf, out_len


class StagingPool:
    """Cycling pool of preallocated (pkt, length) staging pairs — the
    per-dispatch `np.zeros([B, L])` + per-frame-copy hoist. `depth`
    must cover the maximum number of dispatches in flight PLUS one
    being staged: a buffer is only rewritten after the dispatch that
    consumed it retired (jnp.asarray copies host->device eagerly, but
    the copy must never race a rewrite). Buffers whose footprint
    exceeds `max_bytes` are not pooled — a rare 16k-lane batch gets a
    fresh calloc rather than pinning hundreds of MB."""

    def __init__(self, width: int, depth: int = 4,
                 max_bytes: int = 8 << 20):
        self.width = width
        self.depth = max(2, depth)
        self.max_bytes = max_bytes
        self._bufs: dict[int, list] = {}
        self._next: dict[int, int] = {}

    def ensure_depth(self, depth: int) -> None:
        """Raise the cycle length (never shrink): a consumer that keeps
        more dispatches in flight than the construction-time default —
        the tiered scheduler's configurable express_depth/bulk_depth,
        whose two lanes can even share one B-keyed ring — must declare
        its worst case before buffers can be rewritten under an
        in-flight host->device copy. Existing rings grow in place."""
        if depth <= self.depth:
            return
        for B, ring in self._bufs.items():
            ring.extend([np.zeros((B, self.width), dtype=np.uint8),
                         np.zeros((B,), dtype=np.uint32), 0]
                        for _ in range(depth - len(ring)))
        self.depth = depth

    def stage(self, frames: list, B: int,
              lens: np.ndarray | None = None
              ) -> tuple[np.ndarray, np.ndarray]:
        """Pack `frames` into a pooled [B, width] pair with the padding
        region beyond len(frames) guaranteed zero (stale rows from the
        buffer's previous occupancy are cleared via a high-water
        mark)."""
        n = len(frames)
        if B * self.width > self.max_bytes:
            pkt = np.zeros((B, self.width), dtype=np.uint8)
            length = np.zeros((B,), dtype=np.uint32)
            pack_into(frames, pkt, length, lens=lens)
            return pkt, length
        ring = self._bufs.get(B)
        if ring is None:
            ring = [[np.zeros((B, self.width), dtype=np.uint8),
                     np.zeros((B,), dtype=np.uint32), 0]
                    for _ in range(self.depth)]
            self._bufs[B] = ring
            self._next[B] = 0
        i = self._next[B]
        self._next[B] = (i + 1) % self.depth
        pkt, length, high = ring[i]
        pack_into(frames, pkt, length, lens=lens)
        if high > n:
            pkt[n:high] = 0
            length[n:high] = 0
        ring[i][2] = n
        return pkt, length


# ---------------------------------------------------------------------------
# vectorized primitives
# ---------------------------------------------------------------------------

FNV1A32_OFFSET = np.uint32(2166136261)
FNV1A32_PRIME = np.uint32(16777619)


def fnv1a32_cols(rows: np.ndarray) -> np.ndarray:
    """FNV-1a32 over fixed-width uint8 columns ([n, K] -> [n] uint32) —
    bit-identical to utils.net.fnv1a32 on each row. K is small (6-byte
    MAC, 4-byte IP), so the byte recurrence unrolls into K vectorized
    xor/multiply steps; uint32 wraparound matches the scalar mask."""
    h = np.full(rows.shape[0], FNV1A32_OFFSET, dtype=np.uint32)
    for k in range(rows.shape[1]):
        h ^= rows[:, k]
        h *= FNV1A32_PRIME
    return h


def _gather(buf: np.ndarray, off: np.ndarray) -> np.ndarray:
    """buf[i, off[i]] with out-of-range offsets clipped (the scalar
    oracles guard every read with a length check FIRST; clipped lanes
    are always masked dead by the same guard here)."""
    return buf[np.arange(buf.shape[0]), np.minimum(off, buf.shape[1] - 1)]


def _u16g(buf: np.ndarray, off: np.ndarray) -> np.ndarray:
    return ((_gather(buf, off).astype(np.uint32) << 8)
            | _gather(buf, off + 1))


def _u32g(buf: np.ndarray, off: np.ndarray) -> np.ndarray:
    return ((_u16g(buf, off).astype(np.uint64) << 16) | _u16g(buf, off + 2))


def _l3_walk(buf: np.ndarray, lens: np.ndarray, strict: bool
             ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The shared 0-2-VLAN-tag walk. Returns (l3_off, ethertype, alive).

    `strict` mirrors the two scalar spellings of the truncated-tag edge:
    classify_dhcp/_bootp_off `return 0/None` when a tag's inner
    ethertype is cut off (lane dead), while shard_of `break`s with the
    tag ethertype still in hand (lane alive, falls through to the MAC
    hash because a tag value never matches 0x0800/0x8864)."""
    n = buf.shape[0]
    off = np.full(n, 12, dtype=np.int64)
    alive = lens >= 14
    et = np.where(alive, _u16g(buf, off), 0).astype(np.uint32)
    done = ~alive
    for _ in range(2):
        is_tag = ~done & ((et == 0x8100) | (et == 0x88A8))
        done |= ~is_tag
        off = np.where(is_tag, off + 4, off)
        short = is_tag & (lens < off + 2)
        if strict:
            alive &= ~short
        done |= short
        rd = is_tag & ~short
        et = np.where(rd, _u16g(buf, off), et)
    return off + 2, et, alive


def classify_dhcp_batch(buf: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Vectorized ring.classify_dhcp: [n] uint32 of {0, FLAG_DHCP_CTRL}.
    Guard-for-guard the scalar classifier — strict IPv4 non-fragment
    UDP dst:67 BOOTREQUEST with the DHCP magic; every scalar `return 0`
    is a mask term here."""
    lens = np.asarray(lens, dtype=np.int64)
    off, et, ok = _l3_walk(buf, lens, strict=True)
    ok = ok & (et == 0x0800) & (lens >= off + 20)
    first = _gather(buf, off)
    ok &= (first >> 4) == 4
    ihl = (first & 0x0F).astype(np.int64) * 4
    ok &= (ihl >= 20) & (_gather(buf, off + 9) == 17)
    ok &= (_u16g(buf, off + 6) & 0x3FFF) == 0  # fragmented: no L4
    l4 = off + ihl
    ok &= lens >= l4 + 8
    ok &= _u16g(buf, l4 + 2) == 67
    bootp = l4 + 8
    ok &= (lens >= bootp + 240) & (_gather(buf, bootp) == 1)
    ok &= _u32g(buf, bootp + 236) == 0x63825363
    return np.where(ok, np.uint32(FLAG_DHCP_CTRL), np.uint32(0))


def shard_of_batch(buf: np.ndarray, lens: np.ndarray, flags: np.ndarray,
                   n_shards: int,
                   pub_keys: np.ndarray | None = None,
                   pub_vals: np.ndarray | None = None) -> np.ndarray:
    """Vectorized ring.shard_of: [n] int64 owner shards. pub_keys must
    be SORTED host-order NAT public IPs with pub_vals their owner
    shards (PyRing keeps the sorted mirror of its steer map)."""
    n = buf.shape[0]
    lens = np.asarray(lens, dtype=np.int64)
    flags = np.asarray(flags, dtype=np.uint32)
    shard = np.zeros(n, dtype=np.int64)
    if n_shards == 1 or n == 0:
        return shard
    alive = lens >= 14
    # sticky MAC hash — the DHCP-control / non-IPv4 / PPPoE-control fall
    # line (shard stays 0 for runts, like the scalar early return)
    mac_hash = (fnv1a32_cols(buf[:, 6:12]) % np.uint32(n_shards)
                ).astype(np.int64)
    shard[alive] = mac_hash[alive]

    walk = alive & ((flags & FLAG_DHCP_CTRL) == 0)
    off, et, _ = _l3_walk(buf, lens, strict=False)
    first = _gather(buf, off)
    ip4 = walk & (et == 0x0800) & (lens >= off + 20) & ((first >> 4) == 4)
    from_access = (flags & FLAG_FROM_ACCESS) != 0

    # upstream IPv4: FNV of src IP
    up = ip4 & from_access
    if up.any():
        src = _ip_cols(buf, off + 12)
        shard[up] = (fnv1a32_cols(src) % np.uint32(n_shards)
                     ).astype(np.int64)[up]
    # downstream IPv4: NAT pub-IP ownership, else FNV of dst IP
    down = ip4 & ~from_access
    if down.any():
        dst = _ip_cols(buf, off + 16)
        dfnv = (fnv1a32_cols(dst) % np.uint32(n_shards)).astype(np.int64)
        shard[down] = dfnv[down]
        if pub_keys is not None and len(pub_keys):
            dst_u32 = ((dst[:, 0].astype(np.uint64) << 24)
                       | (dst[:, 1].astype(np.uint64) << 16)
                       | (dst[:, 2].astype(np.uint64) << 8)
                       | dst[:, 3])
            pos = np.searchsorted(pub_keys, dst_u32)
            pos_c = np.minimum(pos, len(pub_keys) - 1)
            hit = down & (pub_keys[pos_c] == dst_u32)
            owner = pub_vals[pos_c]
            hit &= owner < n_shards  # scalar: out-of-range owner ignored
            shard[hit] = owner[hit].astype(np.int64)

    # PPPoE session DATA (PPP proto IPv4): inner src IP affinity. The
    # proto check is the PR 12 precedence fix — the full 16-bit compare
    # against 0x0021, never `hi<<8 | (lo==0x0021)` (LCP/IPCP control
    # frames must fall through to the sticky MAC hash).
    ppp = (walk & ~ip4 & (et == 0x8864) & from_access
           & (lens >= off + 8 + 20))
    if ppp.any():
        ppp &= (_gather(buf, off) == 0x11) & (_gather(buf, off + 1) == 0)
        ppp &= _u16g(buf, off + 6) == 0x0021
        ppp &= (_gather(buf, off + 8) >> 4) == 4
        if ppp.any():
            isrc = _ip_cols(buf, off + 8 + 12)
            shard[ppp] = (fnv1a32_cols(isrc) % np.uint32(n_shards)
                          ).astype(np.int64)[ppp]
    return shard


def _ip_cols(buf: np.ndarray, off: np.ndarray) -> np.ndarray:
    """Gather 4 consecutive bytes per lane -> [n, 4] (clipped reads —
    callers mask dead lanes)."""
    ar = np.arange(buf.shape[0])
    cap = buf.shape[1] - 1
    return np.stack([buf[ar, np.minimum(off + k, cap)] for k in range(4)],
                    axis=1)


def bootp_off_batch(buf: np.ndarray, lens: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized admission._bootp_off: (bootp_off, valid). Accepts
    either UDP port pair exactly like the scalar (it peeks replies
    too — no dport guard)."""
    lens = np.asarray(lens, dtype=np.int64)
    off, et, ok = _l3_walk(buf, lens, strict=True)
    ok = ok & (et == 0x0800) & (lens >= off + 20)
    first = _gather(buf, off)
    ok &= (first >> 4) == 4
    ihl = (first & 0x0F).astype(np.int64) * 4
    ok &= (ihl >= 20) & (_gather(buf, off + 9) == 17)
    ok &= (_u16g(buf, off + 6) & 0x3FFF) == 0
    bootp = off + ihl + 8
    ok &= lens >= bootp + 240
    return bootp, ok


def peek_dhcp_batch(buf: np.ndarray, lens: np.ndarray
                    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized admission.peek_dhcp: (msg_type, mac_u64, parsed).
    parsed=False lanes mirror the scalar None (admitted as-is — the
    worker's per-frame isolation owns malformed input). The option-53
    scan runs the scalar's bounded 64-TLV walk with a per-lane cursor;
    lanes that exhaust the walk report msg_type 0 like the scalar
    fallthrough."""
    lens = np.asarray(lens, dtype=np.int64)
    bootp, parsed = bootp_off_batch(buf, lens)
    magic_ok = _u32g(buf, bootp + 236) == 0x63825363
    parsed = parsed & magic_ok
    mac = ((_u16g(buf, bootp + 28).astype(np.uint64) << 32)
           | _u32g(buf, bootp + 30))
    # bounded TLV scan for option 53
    n = buf.shape[0]
    cur = bootp + 240
    msg = np.zeros(n, dtype=np.int64)
    scanning = parsed.copy()
    OPT_PAD, OPT_END, OPT_MSG = 0, 255, 53
    for _ in range(64):
        if not scanning.any():
            break
        in_range = scanning & (cur < lens)
        scanning &= in_range
        code = _gather(buf, cur)
        scanning &= code != OPT_END
        pad = scanning & (code == OPT_PAD)
        has_len = scanning & ~pad & (cur + 1 < lens)
        scanning &= pad | has_len
        ln = _gather(buf, cur + 1).astype(np.int64)
        found = (has_len & (code == OPT_MSG) & (ln >= 1)
                 & (cur + 2 < lens))
        msg[found] = _gather(buf, cur + 2)[found]
        scanning &= ~found
        cur = np.where(pad, cur + 1, np.where(scanning, cur + 2 + ln, cur))
    return msg, mac, parsed
