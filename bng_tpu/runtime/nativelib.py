"""Shared build-on-demand loader for the in-tree C++ libraries.

One implementation of the compile/mtime-cache/CDLL/lock dance for every
native module (bngring, bngxsk, ...): the reference gets this from its
Makefile + cgo; here the .so is compiled from source on first use so the
package works from a plain checkout, and falls back to None (callers
degrade to their Python/stub paths) when no toolchain exists.
"""

from __future__ import annotations

import ctypes as C
import os
import subprocess
import threading
from typing import Callable

_HERE = os.path.dirname(os.path.abspath(__file__))
SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")

_libs: dict[str, object] = {}
_lock = threading.Lock()


def _build(src: str, so_path: str) -> str | None:
    if not os.path.exists(src):
        return None
    if (os.path.exists(so_path)
            and os.path.getmtime(so_path) >= os.path.getmtime(src)):
        return so_path
    cmd = ["g++", "-O2", "-g", "-Wall", "-fPIC", "-std=c++17", "-shared",
           "-o", so_path, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    return so_path


def load(src_name: str, configure: Callable[[C.CDLL], None]):
    """Load (building if stale) native/<src_name>.cpp as a CDLL.

    configure(lib) declares argtypes/restypes once. Returns the cached
    CDLL, or None when the source/toolchain is unavailable.
    """
    with _lock:
        if src_name in _libs:
            return _libs[src_name]
        src = os.path.join(SRC_DIR, f"{src_name}.cpp")
        so_path = os.path.join(_HERE, f"lib{src_name}.so")
        path = _build(src, so_path)
        if path is None:
            return None
        try:
            lib = C.CDLL(path)
        except OSError:
            return None
        configure(lib)
        _libs[src_name] = lib
        return lib
