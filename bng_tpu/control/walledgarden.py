"""Walled garden / captive portal state machine.

Parity: pkg/walledgarden — SubscriberState (manager.go:16-44), Config +
DefaultConfig (:65-105), Manager with subscriber CRUD (:244-345), expiry
checker (:347-396), stats (:398-428), allowed destinations incl. DNS
(:95-103, :187-242), redirect callback (:182).

TPU mapping: the reference writes state into an eBPF map consulted by the
kernel redirect program; here the manager keeps the authoritative host-side
table and (optionally, nil-safe like the reference's SetEBPFMaps) pushes
entries into the device fast-path tables so the packet pipeline can divert
unauthenticated subscribers' TCP:80 to the portal.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import IntEnum

from bng_tpu.utils.net import ip_to_u32, mac_to_u64


class SubscriberState(IntEnum):
    """manager.go:16-44. UNKNOWN gets the walled garden by default."""

    UNKNOWN = 0
    WALLED_GARDEN = 1
    PROVISIONED = 2
    BLOCKED = 3


IPPROTO_TCP = 6
IPPROTO_UDP = 17


@dataclass(frozen=True)
class AllowedDestination:
    """A destination that bypasses the garden (manager.go:56-63)."""

    ip: str
    port: int = 0  # 0 = any port
    proto: int = 0  # 0 = any proto

    def key(self) -> int:
        # Same packing idea as allowedDestKey (manager.go:237-242):
        # ip:port:proto folded into one u64 lookup key.
        return (ip_to_u32(self.ip) << 32) | (self.port << 8) | self.proto


@dataclass
class WalledGardenConfig:
    """manager.go:65-105 defaults."""

    portal_ip: str = "10.255.255.1"
    portal_port: int = 8080
    allowed_dns: list[str] = field(default_factory=lambda: ["8.8.8.8", "8.8.4.4"])
    allowed_destinations: list[AllowedDestination] = field(default_factory=list)
    default_timeout: float = 300.0  # seconds unknown MACs stay gardened
    max_entries: int = 100_000


@dataclass
class Entry:
    state: SubscriberState
    vlan_id: int = 0
    expiry_time: float = 0.0  # 0 = never
    added_at: float = 0.0


class WalledGardenManager:
    """Host-authoritative captive-portal table (manager.go:107-464)."""

    def __init__(self, config: WalledGardenConfig | None = None,
                 clock=time.time):
        self.config = config or WalledGardenConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: dict[int, Entry] = {}  # mac_u64 -> Entry
        self._allowed: dict[int, AllowedDestination] = {}
        self._on_redirect = None
        self._on_expire = None
        self._on_state_change = None
        self._stats = {"redirects": 0, "expired": 0}
        self._init_allowed_destinations()

    # -- setup ---------------------------------------------------------

    def _init_allowed_destinations(self) -> None:
        """Portal + DNS servers always bypass (manager.go:187-242)."""
        cfg = self.config
        base = [AllowedDestination(cfg.portal_ip, cfg.portal_port, IPPROTO_TCP)]
        base += [AllowedDestination(d, 53, IPPROTO_UDP) for d in cfg.allowed_dns]
        base += [AllowedDestination(d, 53, IPPROTO_TCP) for d in cfg.allowed_dns]
        base += list(cfg.allowed_destinations)
        for dest in base:
            self._allowed[dest.key()] = dest

    def on_redirect(self, callback) -> None:
        self._on_redirect = callback

    def on_expire(self, callback) -> None:
        self._on_expire = callback

    def on_state_change(self, callback) -> None:
        """callback(mac_u64, state) after every set_subscriber_state —
        lets enforcement points (the DNS resolver's per-client garden,
        the device-side gate) track membership without polling."""
        self._on_state_change = callback

    # -- subscriber state ----------------------------------------------

    def set_subscriber_state(self, mac: bytes | str, state: SubscriberState,
                             vlan_id: int = 0) -> None:
        key = mac_to_u64(mac)
        now = self._clock()
        with self._lock:
            if len(self._entries) >= self.config.max_entries and key not in self._entries:
                raise OverflowError("walled garden table full")
            expiry = 0.0
            if state in (SubscriberState.UNKNOWN, SubscriberState.WALLED_GARDEN):
                expiry = now + self.config.default_timeout
            self._entries[key] = Entry(state=state, vlan_id=vlan_id,
                                       expiry_time=expiry, added_at=now)
        if self._on_state_change:  # outside the lock: callbacks may re-enter
            self._on_state_change(key, state)

    def get_subscriber_state(self, mac: bytes | str) -> SubscriberState:
        with self._lock:
            e = self._entries.get(mac_to_u64(mac))
            return e.state if e else SubscriberState.UNKNOWN

    def add_to_walled_garden(self, mac: bytes | str, vlan_id: int = 0) -> None:
        self.set_subscriber_state(mac, SubscriberState.WALLED_GARDEN, vlan_id)

    def release_from_walled_garden(self, mac: bytes | str) -> None:
        """Promote to fully provisioned (manager.go:313-316)."""
        self.set_subscriber_state(mac, SubscriberState.PROVISIONED)

    def block_mac(self, mac: bytes | str) -> None:
        self.set_subscriber_state(mac, SubscriberState.BLOCKED)

    def remove_mac(self, mac: bytes | str) -> None:
        key = mac_to_u64(mac)
        with self._lock:
            removed = self._entries.pop(key, None) is not None
        # removal reverts the MAC to UNKNOWN (gardened by default): every
        # enforcement point must hear about it, same as a transition
        if removed and self._on_state_change:
            self._on_state_change(key, SubscriberState.UNKNOWN)

    def list_walled_macs(self) -> list[int]:
        with self._lock:
            return [k for k, e in self._entries.items()
                    if e.state == SubscriberState.WALLED_GARDEN]

    # -- packet-path decisions (host-side mirror of the device logic) --

    def is_destination_allowed(self, ip: str, port: int, proto: int) -> bool:
        with self._lock:
            # exact + each wildcard combination (port=0 any-port, proto=0 any-proto)
            for p, pr in ((port, proto), (port, 0), (0, proto), (0, 0)):
                if AllowedDestination(ip, p, pr).key() in self._allowed:
                    return True
        return False

    def should_redirect(self, mac: bytes | str, dst_ip: str, dst_port: int,
                        proto: int = IPPROTO_TCP) -> bool:
        """True if this flow should be diverted to the portal."""
        state = self.get_subscriber_state(mac)
        if state == SubscriberState.PROVISIONED:
            return False
        if self.is_destination_allowed(dst_ip, dst_port, proto):
            return False
        with self._lock:
            self._stats["redirects"] += 1
        if self._on_redirect:
            self._on_redirect(mac, dst_ip)
        return True

    # -- expiry (manager.go:347-396) -----------------------------------

    def check_expired(self) -> int:
        """Drop expired gardened entries; they revert to UNKNOWN."""
        now = self._clock()
        expired = []
        with self._lock:
            for key, e in list(self._entries.items()):
                if e.expiry_time and e.expiry_time <= now:
                    del self._entries[key]
                    expired.append(key)
            self._stats["expired"] += len(expired)
        for key in expired:
            if self._on_expire:
                self._on_expire(key)
            if self._on_state_change:  # expiry reverts to UNKNOWN
                self._on_state_change(key, SubscriberState.UNKNOWN)
        return len(expired)

    def stats(self) -> dict:
        with self._lock:
            by_state = {s.name: 0 for s in SubscriberState}
            for e in self._entries.values():
                by_state[e.state.name] += 1
            return {
                "total_entries": len(self._entries),
                "allowed_destinations": len(self._allowed),
                **by_state,
                **self._stats,
            }
