"""RADIUS-less "direct" authenticator: ONT-serial / circuit-ID -> subscriber.

Parity: pkg/direct — Config (authenticator.go:40-78), Authenticator with
the lookup cascade cache -> Nexus -> BSS (authenticator.go:182-351),
TTL cache by serial + circuit-ID (authenticator.go:353-391), SyncFromBSS
(authenticator.go:393-425), ReportBindingEvent (authenticator.go:427-451),
BSSClient interface + stub (authenticator.go:127-140, bss_stub.go:9).

Plugs into subscriber.SubscriberManager as its `authenticator` callable:
returns a profile dict on success, None on failure (-> walled garden).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from bng_tpu.control.nexus import NexusClient
from bng_tpu.control.subscriber import Session


@dataclass
class ONTMapping:
    """authenticator.go:93-125."""

    ont_serial: str = ""
    circuit_id: str = ""
    subscriber_id: str = ""
    isp_id: str = ""
    qos_policy: str = ""
    s_tag: int = 0
    c_tag: int = 0
    enabled: bool = True
    cached_at: float = 0.0


@dataclass
class BindingEvent:
    """authenticator.go:142-163: reported upstream for BSS reconciliation."""

    event_type: str  # "bind" | "unbind" | "reject"
    ont_serial: str = ""
    circuit_id: str = ""
    subscriber_id: str = ""
    mac: str = ""
    ip: str = ""
    timestamp: float = 0.0


@dataclass
class DirectConfig:
    """authenticator.go:40-78."""

    cache_ttl: float = 300.0
    allow_unknown: bool = False  # unknown ONT -> walled garden vs reject
    report_bindings: bool = True


class StubBSSClient:
    """bss_stub.go:9: fixture-backed BSS for tests/demo."""

    def __init__(self, mappings: list[ONTMapping] | None = None):
        self.mappings = {m.ont_serial: m for m in (mappings or [])}
        self.by_circuit = {m.circuit_id: m for m in (mappings or [])
                           if m.circuit_id}
        self.events: list[BindingEvent] = []

    def lookup_by_serial(self, serial: str) -> ONTMapping | None:
        return self.mappings.get(serial)

    def lookup_by_circuit_id(self, circuit_id: str) -> ONTMapping | None:
        return self.by_circuit.get(circuit_id)

    def list_mappings(self) -> list[ONTMapping]:
        return list(self.mappings.values())

    def report_event(self, event: BindingEvent) -> None:
        self.events.append(event)


class DirectAuthenticator:
    """authenticator.go:80-451."""

    def __init__(self, config: DirectConfig | None = None,
                 nexus: NexusClient | None = None, bss=None, clock=time.time):
        self.config = config or DirectConfig()
        self.nexus = nexus
        self.bss = bss
        self._clock = clock
        self._lock = threading.Lock()
        self._by_serial: dict[str, ONTMapping] = {}
        self._by_circuit: dict[str, ONTMapping] = {}
        self.stats = {"auth_success": 0, "auth_failure": 0, "cache_hits": 0,
                      "nexus_lookups": 0, "bss_lookups": 0, "bss_syncs": 0}

    def set_bss_client(self, bss) -> None:
        self.bss = bss

    # -- the SubscriberManager authenticator contract -------------------

    def __call__(self, session: Session) -> dict | None:
        return self.authenticate(session)

    def authenticate(self, session: Session) -> dict | None:
        """authenticator.go:182-263: resolve the session's ONT serial or
        circuit-ID to a subscriber profile; None -> walled garden."""
        serial = session.attributes.get("ont_serial", "")
        mapping = self.lookup(serial=serial, circuit_id=session.circuit_id,
                              mac=session.mac)
        if mapping is None or not mapping.enabled:
            self.stats["auth_failure"] += 1
            if self.config.report_bindings and self.bss is not None:
                self.bss.report_event(BindingEvent(
                    event_type="reject", ont_serial=serial,
                    circuit_id=session.circuit_id, mac=session.mac,
                    timestamp=self._clock()))
            return None
        self.stats["auth_success"] += 1
        if self.config.report_bindings and self.bss is not None:
            self.bss.report_event(BindingEvent(
                event_type="bind", ont_serial=mapping.ont_serial,
                circuit_id=mapping.circuit_id,
                subscriber_id=mapping.subscriber_id, mac=session.mac,
                timestamp=self._clock()))
        return {
            "subscriber_id": mapping.subscriber_id,
            "isp_id": mapping.isp_id,
            "qos_policy": mapping.qos_policy,
            "s_tag": mapping.s_tag,
            "c_tag": mapping.c_tag,
        }

    # -- lookup cascade (authenticator.go:265-351) ----------------------

    def lookup(self, serial: str = "", circuit_id: str = "",
               mac: str = "") -> ONTMapping | None:
        now = self._clock()
        with self._lock:
            m = None
            if serial:
                m = self._by_serial.get(serial)
            if m is None and circuit_id:
                m = self._by_circuit.get(circuit_id)
            if m is not None and now - m.cached_at < self.config.cache_ttl:
                self.stats["cache_hits"] += 1
                return m

        m = self._lookup_nexus(serial, circuit_id, mac)
        if m is None and self.bss is not None:
            self.stats["bss_lookups"] += 1
            if serial:
                m = self.bss.lookup_by_serial(serial)
            if m is None and circuit_id:
                m = self.bss.lookup_by_circuit_id(circuit_id)
        if m is not None:
            self._cache(m)
        return m

    def _lookup_nexus(self, serial: str, circuit_id: str,
                      mac: str) -> ONTMapping | None:
        if self.nexus is None:
            return None
        self.stats["nexus_lookups"] += 1
        sub = None
        if circuit_id:
            sub = self.nexus.get_subscriber_by_circuit_id(circuit_id)
        if sub is None and mac:
            sub = self.nexus.get_subscriber_by_mac(mac)
        if sub is None and serial:
            for s in self.nexus.subscribers.list().values():
                if s.nte_id == serial:
                    sub = s
                    break
        if sub is None or not sub.enabled:
            return None
        nte = self.nexus.ntes.get(sub.nte_id) if sub.nte_id else None
        return ONTMapping(
            ont_serial=sub.nte_id, circuit_id=sub.circuit_id,
            subscriber_id=sub.id, isp_id=sub.isp_id,
            qos_policy=sub.qos_policy,
            s_tag=nte.s_tag if nte else 0, c_tag=nte.c_tag if nte else 0,
            enabled=sub.enabled)

    def _cache(self, m: ONTMapping) -> None:
        m.cached_at = self._clock()
        with self._lock:
            if m.ont_serial:
                self._by_serial[m.ont_serial] = m
            if m.circuit_id:
                self._by_circuit[m.circuit_id] = m

    def invalidate_cache(self, serial: str = "", circuit_id: str = "") -> None:
        """authenticator.go:380-391."""
        with self._lock:
            if serial:
                self._by_serial.pop(serial, None)
            if circuit_id:
                self._by_circuit.pop(circuit_id, None)

    def sync_from_bss(self) -> int:
        """authenticator.go:393-425: bulk-refresh the cache."""
        if self.bss is None:
            return 0
        n = 0
        for m in self.bss.list_mappings():
            self._cache(m)
            n += 1
        self.stats["bss_syncs"] += 1
        return n
