"""DHCPv4 wire codec (RFC 2131/2132) — slow-path + test golden reference.

The reference uses the insomniacslk/dhcp library for its Go slow path
(pkg/dhcp/server.go); this is our from-scratch equivalent. Option-82
sub-option parsing mirrors parseOption82 (pkg/dhcp/server.go:201-238).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from bng_tpu.control.packets import ipv4_header, udp_header

DHCP_MAGIC = 0x63825363

# Message types
DISCOVER, OFFER, REQUEST, DECLINE, ACK, NAK, RELEASE, INFORM = range(1, 9)

# Option codes (subset used by the BNG; bpf/maps.h:24-41)
OPT_PAD = 0
OPT_SUBNET_MASK = 1
OPT_ROUTER = 3
OPT_DNS = 6
OPT_HOSTNAME = 12
OPT_REQUESTED_IP = 50
OPT_LEASE_TIME = 51
OPT_MSG_TYPE = 53
OPT_SERVER_ID = 54
OPT_PARAM_REQ_LIST = 55
OPT_RENEWAL_TIME = 58
OPT_REBIND_TIME = 59
OPT_VENDOR_CLASS = 60
OPT_CLIENT_ID = 61
OPT_RELAY_AGENT_INFO = 82
OPT_END = 255

OPT82_CIRCUIT_ID = 1
OPT82_REMOTE_ID = 2


@dataclass
class DHCPPacket:
    op: int = 1  # 1=BOOTREQUEST 2=BOOTREPLY
    htype: int = 1
    hlen: int = 6
    hops: int = 0
    xid: int = 0
    secs: int = 0
    flags: int = 0
    ciaddr: int = 0
    yiaddr: int = 0
    siaddr: int = 0
    giaddr: int = 0
    chaddr: bytes = b"\x00" * 6  # client MAC (first hlen bytes)
    sname: bytes = b""
    file: bytes = b""
    options: list[tuple[int, bytes]] = field(default_factory=list)
    # pre-encoded options (END included): when set AND `options` still
    # equals the snapshot taken by set_options_raw(), encode() uses these
    # bytes verbatim instead of TLV-encoding `options` — the slow-path
    # server caches its static per-pool reply suffix this way. ANY
    # mutation of `options` after the snapshot (append, replace-in-place,
    # delete) falls back to the full TLV encode automatically; the
    # identity fast path keeps the cached-suffix case O(1).
    options_raw: bytes | None = None
    _options_raw_snap: tuple | None = None
    # whole-payload fast path: a ReplyTemplate render of this packet
    # (fixed header + options already assembled). Same snapshot guard as
    # options_raw: any later mutation of `options` falls back to the
    # full field-by-field encode.
    encoded: bytes | None = None
    _encoded_snap: tuple | None = None

    def set_options_raw(self, raw: bytes) -> None:
        """Install pre-encoded option bytes for the CURRENT `options` list."""
        self.options_raw = raw
        self._options_raw_snap = tuple(self.options)

    def set_encoded(self, raw: bytes) -> None:
        """Install the complete pre-rendered payload (ReplyTemplate
        output) for the CURRENT `options` list. Header fields must
        already match the render — the slow-path server renders and
        installs in one place (_build_reply)."""
        self.encoded = raw
        self._encoded_snap = tuple(self.options)

    @staticmethod
    def _snap_matches(snap: tuple | None, options: list) -> bool:
        return (snap is not None and len(snap) == len(options)
                and all(a is b or a == b for a, b in zip(snap, options)))

    # -- option helpers --
    def opt(self, code: int) -> bytes | None:
        for c, v in self.options:
            if c == code:
                return v
        return None

    @property
    def msg_type(self) -> int:
        v = self.opt(OPT_MSG_TYPE)
        return v[0] if v else 0

    @property
    def requested_ip(self) -> int:
        v = self.opt(OPT_REQUESTED_IP)
        return struct.unpack("!I", v)[0] if v and len(v) == 4 else 0

    @property
    def server_id(self) -> int:
        v = self.opt(OPT_SERVER_ID)
        return struct.unpack("!I", v)[0] if v and len(v) == 4 else 0

    def option82(self) -> tuple[bytes, bytes]:
        """Extract (circuit_id, remote_id) from Option 82 sub-options.

        Parity: parseOption82, pkg/dhcp/server.go:201-238.
        """
        v = self.opt(OPT_RELAY_AGENT_INFO)
        circuit, remote = b"", b""
        if not v:
            return circuit, remote
        i = 0
        while i + 2 <= len(v):
            sub, slen = v[i], v[i + 1]
            data = v[i + 2 : i + 2 + slen]
            if sub == OPT82_CIRCUIT_ID:
                circuit = data
            elif sub == OPT82_REMOTE_ID:
                remote = data
            i += 2 + slen
        return circuit, remote

    def encode(self) -> bytes:
        if (self.encoded is not None
                and self._snap_matches(self._encoded_snap, self.options)):
            return self.encoded
        fixed = struct.pack(
            "!BBBBIHHIIII",
            self.op, self.htype, self.hlen, self.hops,
            self.xid, self.secs, self.flags,
            self.ciaddr, self.yiaddr, self.siaddr, self.giaddr,
        )
        chaddr = (self.chaddr + b"\x00" * 16)[:16]
        sname = (self.sname + b"\x00" * 64)[:64]
        bfile = (self.file + b"\x00" * 128)[:128]
        use_raw = (self.options_raw is not None
                   and self._snap_matches(self._options_raw_snap,
                                          self.options))
        opts = self.options_raw if use_raw else encode_options(self.options)
        return fixed + chaddr + sname + bfile + struct.pack("!I", DHCP_MAGIC) + opts


def encode_options(options: list[tuple[int, bytes]]) -> bytes:
    """TLV-encode an option list (END terminated). Exposed so callers with
    repeated static option sets (the slow-path server's per-pool reply
    suffix) can cache the encoded bytes."""
    parts = []
    for code, val in options:
        if code == OPT_PAD:
            parts.append(b"\x00")
        else:
            parts.append(bytes((code, len(val))) + val)
    parts.append(bytes((OPT_END,)))
    return b"".join(parts)


# fixed-field offsets in the BOOTP payload (RFC 2131 figure 1)
_OFF_XID = 4
_OFF_SECS = 8
_OFF_FLAGS = 10
_OFF_CIADDR = 12
_OFF_YIADDR = 16
_OFF_SIADDR = 20
_OFF_GIADDR = 24
_OFF_CHADDR = 28
_OFF_MAGIC = 236
_OPTIONS_START = 240


class ReplyTemplate:
    """Preassembled BOOTREPLY payload: the fixed 240-byte header, magic
    cookie and the full option bytes are built ONCE; per-reply `render`
    copies the prototype and patches only the per-client words
    (xid/flags/ciaddr/yiaddr/giaddr/chaddr). This replaces the hot
    path's per-reply struct.pack + pad + per-option concatenation with
    one memcpy and five fixed-offset writes — the slow-path encode cost
    that dominated config 1's run-to-run variance.

    The prototype bakes op=BOOTREPLY, htype/hlen, siaddr (per-server
    static) and the option bytes (per-pool static, END included)."""

    __slots__ = ("_proto", "options")

    def __init__(self, options: list[tuple[int, bytes]], siaddr: int = 0,
                 options_raw: bytes | None = None):
        raw = options_raw if options_raw is not None else encode_options(options)
        proto = bytearray(_OPTIONS_START + len(raw))
        proto[0] = 2  # op: BOOTREPLY
        proto[1] = 1  # htype: Ethernet
        proto[2] = 6  # hlen
        struct.pack_into("!I", proto, _OFF_SIADDR, siaddr)
        struct.pack_into("!I", proto, _OFF_MAGIC, DHCP_MAGIC)
        proto[_OPTIONS_START:] = raw
        self._proto = bytes(proto)
        # the decoded view of the baked options, so callers building a
        # DHCPPacket around a render keep a truthful .options list
        self.options = list(options)

    def render(self, xid: int, chaddr: bytes, yiaddr: int = 0,
               flags: int = 0, ciaddr: int = 0, giaddr: int = 0,
               secs: int = 0) -> bytes:
        buf = bytearray(self._proto)
        struct.pack_into("!I", buf, _OFF_XID, xid)
        struct.pack_into("!H", buf, _OFF_SECS, secs)
        struct.pack_into("!H", buf, _OFF_FLAGS, flags)
        struct.pack_into("!II", buf, _OFF_CIADDR, ciaddr, yiaddr)
        struct.pack_into("!I", buf, _OFF_GIADDR, giaddr)
        buf[_OFF_CHADDR : _OFF_CHADDR + 16] = (chaddr + b"\x00" * 16)[:16]
        return bytes(buf)


def decode(data: bytes) -> DHCPPacket:
    if len(data) < 240:
        raise ValueError(f"DHCP packet too short: {len(data)}")
    p = DHCPPacket()
    (p.op, p.htype, p.hlen, p.hops, p.xid, p.secs, p.flags,
     p.ciaddr, p.yiaddr, p.siaddr, p.giaddr) = struct.unpack_from("!BBBBIHHIIII", data, 0)
    p.chaddr = data[28 : 28 + max(p.hlen, 6)][:16]
    p.sname = data[44:108].rstrip(b"\x00")
    p.file = data[108:236].rstrip(b"\x00")
    magic = struct.unpack_from("!I", data, 236)[0]
    if magic != DHCP_MAGIC:
        raise ValueError(f"bad DHCP magic: {magic:#x}")
    i = 240
    while i < len(data):
        code = data[i]
        if code == OPT_END:
            break
        if code == OPT_PAD:
            i += 1
            continue
        if i + 1 >= len(data):
            break
        ln = data[i + 1]
        p.options.append((code, data[i + 2 : i + 2 + ln]))
        i += 2 + ln
    return p


def build_request(
    mac: bytes,
    msg_type: int,
    xid: int = 0x12345678,
    requested_ip: int = 0,
    server_id: int = 0,
    ciaddr: int = 0,
    giaddr: int = 0,
    broadcast: bool = False,
    circuit_id: bytes = b"",
    remote_id: bytes = b"",
    extra_options: list[tuple[int, bytes]] | None = None,
) -> DHCPPacket:
    """Build a client DISCOVER/REQUEST/... packet."""
    p = DHCPPacket(op=1, xid=xid, chaddr=mac, ciaddr=ciaddr, giaddr=giaddr)
    if broadcast:
        p.flags = 0x8000
    p.options.append((OPT_MSG_TYPE, bytes([msg_type])))
    if requested_ip:
        p.options.append((OPT_REQUESTED_IP, struct.pack("!I", requested_ip)))
    if server_id:
        p.options.append((OPT_SERVER_ID, struct.pack("!I", server_id)))
    if extra_options:
        p.options.extend(extra_options)
    if circuit_id or remote_id:
        sub = b""
        if circuit_id:
            sub += bytes([OPT82_CIRCUIT_ID, len(circuit_id)]) + circuit_id
        if remote_id:
            sub += bytes([OPT82_REMOTE_ID, len(remote_id)]) + remote_id
        p.options.append((OPT_RELAY_AGENT_INFO, sub))
    return p


# ---------------------------------------------------------------------------
# Express wire templates (ISSUE 13): the AOT express retire path
# ---------------------------------------------------------------------------

class ExpressWireTemplate:
    """Preassembled full-wire DHCP reply for the AOT express path.

    The express device program (ops/express.py) emits only
    verdict + yiaddr + pool/lease words; everything byte-static per
    (pool config, server config, reply type) is assembled ONCE here —
    the canonical IPv4+UDP header pair with the broadcast checksum
    folded, and the whole BOOTREPLY payload through a `ReplyTemplate`
    (the same preassembled machinery the slow-path server renders
    through, so the express retire path can never re-enter the generic
    per-option TLV encode). `render` patches only the per-client words
    and copies the request's tag stack verbatim — byte-identical to the
    device compose in ops/dhcp.py (option order 53,54,51,1,3,[6],58,59,
    END; TTL 64, IP id 0, UDP checksum 0, relayed/broadcast/unicast
    addressing), pinned by tests/test_express.py.
    """

    __slots__ = ("_src_mac", "_server_ip", "_bootp", "_l3", "_udp_len")

    def __init__(self, server_mac: bytes, server_ip: int, gateway: int,
                 dns1: int, dns2: int, lease_t: int, mask: int,
                 reply_type: int):
        opts = [
            (OPT_MSG_TYPE, bytes([reply_type])),
            (OPT_SERVER_ID, struct.pack("!I", server_ip)),
            (OPT_LEASE_TIME, struct.pack("!I", lease_t)),
            (OPT_SUBNET_MASK, struct.pack("!I", mask)),
            (OPT_ROUTER, struct.pack("!I", gateway)),
        ]
        if dns1:
            dns = struct.pack("!I", dns1)
            if dns2:
                dns += struct.pack("!I", dns2)
            opts.append((OPT_DNS, dns))
        opts.append((OPT_RENEWAL_TIME, struct.pack("!I", lease_t // 2)))
        opts.append((OPT_REBIND_TIME, struct.pack("!I", (lease_t * 7) // 8)))
        self._src_mac = server_mac
        self._server_ip = server_ip
        self._bootp = ReplyTemplate(opts, siaddr=server_ip)
        # canonical non-relayed L3+L4 prototype via the shared header
        # helpers (ONE copy of the IPv4 checksum arithmetic, the same
        # one the slow-path frames fold through) — ops/dhcp.py parity:
        # TTL 64, id 0, UDP checksum 0, broadcast dst
        self._udp_len = 8 + len(self._bootp._proto)
        self._l3 = (ipv4_header(server_ip, 0xFFFFFFFF, self._udp_len, 17)
                    + udp_header(67, 68, len(self._bootp._proto)))

    def render(self, frame: bytes, vlan_off: int, dhcp_off: int,
               relayed: bool, use_bcast: bool, yiaddr: int) -> bytes:
        """Patch the per-client words into the prototype. `frame` is the
        original request; xid/secs/flags/ciaddr/giaddr/chaddr and the
        VLAN tag stack are copied from it exactly as the device compose
        copies them."""
        xid, secs, flags16 = struct.unpack_from("!IHH", frame, dhcp_off + 4)
        ciaddr, = struct.unpack_from("!I", frame, dhcp_off + 12)
        giaddr, = struct.unpack_from("!I", frame, dhcp_off + 24)
        chaddr = frame[dhcp_off + 28: dhcp_off + 44]
        payload = self._bootp.render(xid, chaddr, yiaddr=yiaddr,
                                     flags=flags16, ciaddr=ciaddr,
                                     giaddr=giaddr, secs=secs)
        if relayed:
            # unicast to the relay on port 67 (ops/dhcp.py :734/:740)
            l3b = (ipv4_header(self._server_ip, giaddr, self._udp_len, 17)
                   + udp_header(67, 67, len(self._bootp._proto)))
            dst_mac = frame[6:12]  # requester (relay) src MAC
        else:
            l3b = self._l3
            dst_mac = b"\xff" * 6 if use_bcast else chaddr[:6]
        return dst_mac + self._src_mac + frame[12: 14 + vlan_off] + l3b + payload

    def render_batch(self, fmat, vlan_off: int, dhcp_off: int,
                     relayed: bool, use_bcast: bool, yiaddrs) -> list:
        """Vectorized `render` over one HOMOGENEOUS group of requests
        (same vlan_off/dhcp_off/relayed/use_bcast — the AOT express
        retire groups lanes by exactly the template + addressing
        identity): per-client words are column copies from the packed
        request matrix `fmat` ([n, >=dhcp_off+240] uint8), the relayed
        IPv4 checksum refolds vectorized from the per-frame giaddr, and
        the result materializes as n bytes objects from ONE contiguous
        buffer. Byte-identical to per-frame render(), pinned by
        tests/test_hostpath.py."""
        import numpy as np

        n = fmat.shape[0]
        proto = self._bootp._proto
        plen = len(proto)
        eth_l3 = 14 + vlan_off
        pb = eth_l3 + 28  # payload base (canonical 20B IPv4 + 8B UDP)
        out = np.empty((n, pb + plen), dtype=np.uint8)
        # L2: dst / src / tag stack + ethertype copied from the request
        if relayed:
            out[:, 0:6] = fmat[:, 6:12]  # requester (relay) src MAC
        elif use_bcast:
            out[:, 0:6] = 0xFF
        else:
            out[:, 0:6] = fmat[:, dhcp_off + 28: dhcp_off + 34]  # chaddr
        out[:, 6:12] = np.frombuffer(self._src_mac, dtype=np.uint8)
        out[:, 12: eth_l3] = fmat[:, 12: eth_l3]
        # L3+L4
        if not relayed:
            out[:, eth_l3: pb] = np.frombuffer(self._l3, dtype=np.uint8)
        else:
            gi = ((fmat[:, dhcp_off + 24].astype(np.int64) << 24)
                  | (fmat[:, dhcp_off + 25].astype(np.int64) << 16)
                  | (fmat[:, dhcp_off + 26].astype(np.int64) << 8)
                  | fmat[:, dhcp_off + 27])
            total = 20 + self._udp_len
            # ipv4_header's arithmetic checksum, vectorized over dst
            s = (0x4500 + total + ((64 << 8) | 17)
                 + (self._server_ip >> 16) + (self._server_ip & 0xFFFF)
                 + (gi >> 16) + (gi & 0xFFFF))
            s = (s & 0xFFFF) + (s >> 16)
            s = (s & 0xFFFF) + (s >> 16)
            csum = (~s) & 0xFFFF
            hdr = np.zeros((n, 20), dtype=np.uint8)
            hdr[:, 0] = 0x45
            hdr[:, 2] = total >> 8
            hdr[:, 3] = total & 0xFF
            hdr[:, 8] = 64
            hdr[:, 9] = 17
            hdr[:, 10] = csum >> 8
            hdr[:, 11] = csum & 0xFF
            hdr[:, 12:16] = np.frombuffer(
                self._server_ip.to_bytes(4, "big"), dtype=np.uint8)
            hdr[:, 16:20] = fmat[:, dhcp_off + 24: dhcp_off + 28]
            out[:, eth_l3: eth_l3 + 20] = hdr
            out[:, eth_l3 + 20: pb] = np.frombuffer(
                udp_header(67, 67, plen), dtype=np.uint8)
        # BOOTP payload: prototype + per-client column patches
        out[:, pb:] = np.frombuffer(proto, dtype=np.uint8)
        out[:, pb + _OFF_XID: pb + _OFF_CIADDR] = (
            fmat[:, dhcp_off + _OFF_XID: dhcp_off + _OFF_CIADDR]
        )  # xid + secs + flags in one copy
        out[:, pb + _OFF_CIADDR: pb + _OFF_YIADDR] = (
            fmat[:, dhcp_off + _OFF_CIADDR: dhcp_off + _OFF_YIADDR])
        out[:, pb + _OFF_YIADDR: pb + _OFF_YIADDR + 4] = (
            np.asarray(yiaddrs, dtype=">u4").view(np.uint8).reshape(n, 4))
        out[:, pb + _OFF_GIADDR: pb + _OFF_GIADDR + 4] = (
            fmat[:, dhcp_off + _OFF_GIADDR: dhcp_off + _OFF_GIADDR + 4])
        out[:, pb + _OFF_CHADDR: pb + _OFF_CHADDR + 16] = (
            fmat[:, dhcp_off + _OFF_CHADDR: dhcp_off + _OFF_CHADDR + 16])
        big = out.tobytes()
        w = pb + plen
        return [big[i * w: (i + 1) * w] for i in range(n)]


class ExpressTemplateCache:
    """Bounded value-keyed cache of ExpressWireTemplates.

    Keys carry every option-relevant VALUE (same discipline as the
    slow-path server's _static_reply_options key): a reconfigured pool
    or server can never serve a stale template, it simply builds a new
    entry. The lease time comes from the DEVICE-reported lease words,
    so the rendered option 51/58/59 always reflects the table state
    that actually served the probe."""

    def __init__(self, maxsize: int = 1024):
        self.maxsize = maxsize
        self._cache: dict[tuple, ExpressWireTemplate] = {}

    def get(self, server_mac: bytes, server_ip: int, gateway: int,
            dns1: int, dns2: int, lease_t: int, mask: int,
            reply_type: int) -> ExpressWireTemplate:
        key = (server_mac, server_ip, gateway, dns1, dns2, lease_t, mask,
               reply_type)
        tmpl = self._cache.get(key)
        if tmpl is None:
            tmpl = ExpressWireTemplate(server_mac, server_ip, gateway,
                                       dns1, dns2, lease_t, mask, reply_type)
            if len(self._cache) >= self.maxsize:
                self._cache.pop(next(iter(self._cache)))
            self._cache[key] = tmpl
        return tmpl
