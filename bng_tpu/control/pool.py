"""Local IP pool allocation for the DHCP slow path.

Parity: pkg/dhcp/pool.go — `Pool` (sequential allocator with free-list,
:23-204) and `PoolManager` (+ fast-path table sync, :232-341). The eBPF
ip_pools map sync becomes FastPathTables.add_pool.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from bng_tpu.chaos.faults import fault_point
from bng_tpu.utils.net import ip_to_u32, prefix_to_mask, u32_to_ip


class PoolExhaustedError(Exception):
    pass


@dataclass
class Pool:
    """One IPv4 pool: network/prefix with gateway/dns/lease config."""

    pool_id: int
    network: int  # host-order network address
    prefix_len: int
    gateway: int
    dns_primary: int = 0
    dns_secondary: int = 0
    lease_time: int = 3600
    client_class: int = 0  # 0 = any
    _next: int = field(init=False, default=0)
    _free: list[int] = field(init=False, default_factory=list)
    _allocated: dict[int, str] = field(init=False, default_factory=dict)  # ip -> owner key
    _declined: set[int] = field(init=False, default_factory=set)

    def __post_init__(self):
        mask = prefix_to_mask(self.prefix_len)
        self.network &= mask
        self.first = self.network + 1
        self.last = (self.network | (~mask & 0xFFFFFFFF)) - 1
        self._next = self.first

    @property
    def size(self) -> int:
        reserved = 1 if self.first <= self.gateway <= self.last else 0
        return max(0, self.last - self.first + 1 - reserved)

    @property
    def used(self) -> int:
        return len(self._allocated)

    def utilization(self) -> float:
        return self.used / self.size if self.size else 1.0

    def allocate(self, owner: str) -> int:
        """Sequential-then-freelist allocation (parity: pool.go:64-118)."""
        fp = fault_point("pool.allocate")
        if fp is not None and fp.kind == "exhaust":
            # chaos: simulated pool exhaustion — every caller already
            # owns this path (silent DISCOVER, empty carve grant)
            raise PoolExhaustedError(
                f"pool {self.pool_id}: chaos-injected exhaustion")
        while self._next <= self.last:
            ip = self._next
            self._next += 1
            if ip == self.gateway or ip in self._allocated or ip in self._declined:
                continue
            self._allocated[ip] = owner
            return ip
        while self._free:
            ip = self._free.pop()
            if ip in self._allocated or ip in self._declined:
                continue
            self._allocated[ip] = owner
            return ip
        raise PoolExhaustedError(f"pool {self.pool_id} ({u32_to_ip(self.network)}/{self.prefix_len}) exhausted")

    def allocate_specific(self, ip: int, owner: str) -> bool:
        if ip < self.first or ip > self.last or ip == self.gateway:
            return False
        if ip in self._declined:
            return False
        cur = self._allocated.get(ip)
        if cur is not None and cur != owner:
            return False
        self._allocated[ip] = owner
        return True

    def release(self, ip: int) -> bool:
        if ip in self._allocated:
            del self._allocated[ip]
            self._free.append(ip)
            return True
        return False

    def decline(self, ip: int) -> None:
        """Mark an address unusable (client saw a conflict)."""
        self._allocated.pop(ip, None)
        self._declined.add(ip)

    def contains(self, ip: int) -> bool:
        return self.first <= ip <= self.last


class PoolManager:
    """Pool registry + client classification (parity: pool.go:232-341)."""

    def __init__(self, fastpath_tables=None):
        self.pools: dict[int, Pool] = {}
        self.tables = fastpath_tables

    def add_pool(self, pool: Pool) -> None:
        self.pools[pool.pool_id] = pool
        if self.tables is not None:
            # sync to device ip_pools (the loader.AddPool role, pool.go:266-282)
            self.tables.add_pool(
                pool.pool_id, pool.network, pool.prefix_len, pool.gateway,
                pool.dns_primary, pool.dns_secondary, pool.lease_time,
            )

    def classify(self, client_class: int = 0) -> Pool | None:
        """Pick a pool for a client class (parity: ClassifyClient)."""
        best = None
        for p in self.pools.values():
            if p.client_class == client_class:
                return p
            if p.client_class == 0 and best is None:
                best = p
        return best

    def pool_for_ip(self, ip: int) -> Pool | None:
        for p in self.pools.values():
            if p.contains(ip):
                return p
        return None

    def stats(self) -> dict:
        return {
            pid: {"size": p.size, "used": p.used, "utilization": p.utilization()}
            for pid, p in self.pools.items()
        }
