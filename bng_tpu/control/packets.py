"""Host-side packet construction/parsing (pure Python, wire-accurate).

Used by the slow-path servers and by tests as the golden reference the
device kernels are checked against. Covers Ethernet (+802.1Q/802.1ad),
IPv4, UDP/TCP/ICMP — the protocol surface of the reference's fast path.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

ETH_P_IP = 0x0800
ETH_P_8021Q = 0x8100
ETH_P_8021AD = 0x88A8


def checksum16(data: bytes) -> int:
    # big-int fold: the 1's-complement 16-bit word sum equals the whole
    # buffer reduced mod 0xFFFF (one C-speed from_bytes, one bigint mod —
    # O(N) at every size, unlike a shift-by-16 fold loop which does ~N/2
    # O(N)-sized additions). A nonzero multiple of 0xFFFF folds to 0xFFFF,
    # not 0 — same as the iterative fold.
    if len(data) % 2:
        data += b"\x00"
    n = int.from_bytes(data, "big")
    s = n % 0xFFFF
    if s == 0 and n != 0:
        s = 0xFFFF
    return (~s) & 0xFFFF


def eth_header(dst: bytes, src: bytes, ethertype: int, vlans: list[int] | None = None) -> bytes:
    """L2 header; vlans = [outer_vid] or [outer_vid, inner_vid] (QinQ).

    QinQ uses 802.1ad for the outer tag like the reference's parser expects
    (bpf/dhcp_fastpath.c:373-387 accepts 0x8100 or 0x88a8 outer).
    """
    hdr = dst + src
    if vlans:
        if len(vlans) == 2:
            hdr += struct.pack("!HH", ETH_P_8021AD, vlans[0])
            hdr += struct.pack("!HH", ETH_P_8021Q, vlans[1])
        else:
            hdr += struct.pack("!HH", ETH_P_8021Q, vlans[0])
    hdr += struct.pack("!H", ethertype)
    return hdr


def ipv4_header(
    src_ip: int,
    dst_ip: int,
    payload_len: int,
    proto: int,
    ttl: int = 64,
    ident: int = 0,
    tos: int = 0,
) -> bytes:
    total = 20 + payload_len
    # checksum computed arithmetically from the fields (one pack, no
    # unpack round-trip — this is the slow-path server's hottest helper)
    s = ((0x4500 | tos) + total + ident + ((ttl << 8) | proto)
         + (src_ip >> 16) + (src_ip & 0xFFFF)
         + (dst_ip >> 16) + (dst_ip & 0xFFFF))
    s = (s & 0xFFFF) + (s >> 16)
    s = (s & 0xFFFF) + (s >> 16)
    return struct.pack("!BBHHHBBHII", 0x45, tos, total, ident, 0, ttl, proto,
                       (~s) & 0xFFFF, src_ip, dst_ip)


def udp_header(src_port: int, dst_port: int, payload_len: int, csum: int = 0) -> bytes:
    return struct.pack("!HHHH", src_port, dst_port, 8 + payload_len, csum)


def udp_packet(
    src_mac: bytes,
    dst_mac: bytes,
    src_ip: int,
    dst_ip: int,
    src_port: int,
    dst_port: int,
    payload: bytes,
    vlans: list[int] | None = None,
    ttl: int = 64,
) -> bytes:
    if vlans is None:
        # hot path (slow-path DHCP server replies): one pack for the whole
        # eth+ip+udp header stack, checksum folded arithmetically
        total = 28 + len(payload)
        s = (0x4500 + total + ((ttl << 8) | 17)
             + (src_ip >> 16) + (src_ip & 0xFFFF)
             + (dst_ip >> 16) + (dst_ip & 0xFFFF))
        s = (s & 0xFFFF) + (s >> 16)
        s = (s & 0xFFFF) + (s >> 16)
        return struct.pack(
            "!6s6sHBBHHHBBHIIHHHH",
            dst_mac, src_mac, ETH_P_IP,
            0x45, 0, total, 0, 0, ttl, 17, (~s) & 0xFFFF, src_ip, dst_ip,
            src_port, dst_port, 8 + len(payload), 0,
        ) + payload
    udp = udp_header(src_port, dst_port, len(payload)) + payload
    ip = ipv4_header(src_ip, dst_ip, len(udp), 17, ttl=ttl)
    return eth_header(dst_mac, src_mac, ETH_P_IP, vlans) + ip + udp


def tcp_packet(
    src_mac: bytes,
    dst_mac: bytes,
    src_ip: int,
    dst_ip: int,
    src_port: int,
    dst_port: int,
    payload: bytes = b"",
    flags: int = 0x18,  # PSH|ACK
    seq: int = 0,
    ack: int = 0,
    vlans: list[int] | None = None,
) -> bytes:
    tcp = struct.pack("!HHIIBBHHH", src_port, dst_port, seq, ack, 5 << 4, flags, 65535, 0, 0) + payload
    # TCP checksum over pseudo header
    pseudo = struct.pack("!IIBBH", src_ip, dst_ip, 0, 6, len(tcp))
    csum = checksum16(pseudo + tcp)
    tcp = tcp[:16] + struct.pack("!H", csum) + tcp[18:]
    ip = ipv4_header(src_ip, dst_ip, len(tcp), 6)
    return eth_header(dst_mac, src_mac, ETH_P_IP, vlans) + ip + tcp


def icmp_echo_packet(
    src_mac: bytes,
    dst_mac: bytes,
    src_ip: int,
    dst_ip: int,
    echo_id: int,
    seq: int = 1,
    payload: bytes = b"ping",
    reply: bool = False,
) -> bytes:
    icmp = struct.pack("!BBHHH", 0 if reply else 8, 0, 0, echo_id, seq) + payload
    csum = checksum16(icmp)
    icmp = icmp[:2] + struct.pack("!H", csum) + icmp[4:]
    ip = ipv4_header(src_ip, dst_ip, len(icmp), 1)
    return eth_header(dst_mac, src_mac, ETH_P_IP) + ip + icmp


@dataclass
class DecodedPacket:
    dst_mac: bytes = b""
    src_mac: bytes = b""
    vlans: list[int] = field(default_factory=list)
    ethertype: int = 0
    src_ip: int = 0
    dst_ip: int = 0
    ttl: int = 0
    proto: int = 0
    ip_total_len: int = 0
    ip_checksum: int = 0
    ip_checksum_ok: bool = False
    src_port: int = 0
    dst_port: int = 0
    udp_len: int = 0
    l4_checksum: int = 0
    payload: bytes = b""
    tcp_flags: int = 0
    icmp_id: int = 0


def udp6_packet(
    src_mac: bytes,
    dst_mac: bytes,
    src_ip: bytes,  # 16 bytes
    dst_ip: bytes,  # 16 bytes
    src_port: int,
    dst_port: int,
    payload: bytes,
    hop_limit: int = 64,
) -> bytes:
    """Eth + IPv6 + UDP frame (DHCPv6 control traffic). The UDP checksum
    is MANDATORY in IPv6 (RFC 8200 §8.1): computed over the v6
    pseudo-header + UDP header + payload."""
    udp_len = 8 + len(payload)
    udp_hdr = struct.pack("!HHHH", src_port, dst_port, udp_len, 0)
    pseudo = src_ip + dst_ip + struct.pack("!IHBB", udp_len, 0, 0, 17)
    csum = checksum16(pseudo + udp_hdr + payload)
    if csum == 0:  # all-zero means "no checksum" in UDP: transmit as ffff
        csum = 0xFFFF
    udp_hdr = struct.pack("!HHHH", src_port, dst_port, udp_len, csum)
    ip6 = struct.pack("!IHBB", 0x60000000, udp_len, 17, hop_limit) \
        + src_ip + dst_ip
    return dst_mac + src_mac + struct.pack("!H", 0x86DD) + ip6 \
        + udp_hdr + payload


def decode(raw: bytes) -> DecodedPacket:
    """Parse a raw frame back into fields (for asserting kernel output)."""
    p = DecodedPacket()
    p.dst_mac, p.src_mac = raw[0:6], raw[6:12]
    off = 12
    et = struct.unpack_from("!H", raw, off)[0]
    off += 2
    while et in (ETH_P_8021Q, ETH_P_8021AD):
        tci = struct.unpack_from("!H", raw, off)[0]
        p.vlans.append(tci & 0x0FFF)
        et = struct.unpack_from("!H", raw, off + 2)[0]
        off += 4
    p.ethertype = et
    if et != ETH_P_IP:
        return p
    (ver_ihl, _tos, p.ip_total_len, _ident, _frag, p.ttl, p.proto,
     p.ip_checksum, p.src_ip, p.dst_ip) = struct.unpack_from(
        "!BBHHHBBHII", raw, off)
    ihl = (ver_ihl & 0x0F) * 4
    p.ip_checksum_ok = checksum16(raw[off : off + ihl]) == 0
    l4 = off + ihl
    if p.proto == 17:
        p.src_port, p.dst_port, p.udp_len, p.l4_checksum = struct.unpack_from("!HHHH", raw, l4)
        p.payload = raw[l4 + 8 : l4 + p.udp_len]
    elif p.proto == 6:
        p.src_port, p.dst_port = struct.unpack_from("!HH", raw, l4)
        data_off = (raw[l4 + 12] >> 4) * 4
        p.tcp_flags = raw[l4 + 13]
        p.l4_checksum = struct.unpack_from("!H", raw, l4 + 16)[0]
        p.payload = raw[l4 + data_off : off + p.ip_total_len]
    elif p.proto == 1:
        p.l4_checksum = struct.unpack_from("!H", raw, l4 + 2)[0]
        p.icmp_id = struct.unpack_from("!H", raw, l4 + 4)[0]
        p.payload = raw[l4 + 8 : off + p.ip_total_len]
    return p
