"""Slow-path DHCPv4 server — the central integration point.

Parity: pkg/dhcp/server.go. The Go server is where RADIUS auth, QoS, NAT,
Nexus allocation and fast-path cache updates all meet (SURVEY.md §3.3);
this server has the same shape with pluggable hooks:

- handle_frame dispatch: server.go:302-383
- handleDiscover allocation cascade (nexus-lookup -> nexus-allocate ->
  local pool): server.go:398-553
- handleRequest (auth + lease + fast-path cache + qos + nat + acct):
  server.go:556-861
- handleRelease teardown: server.go:864-983
- updateFastPathCache: server.go:1057-1097 (nil-safe: works with
  tables=None, like the loader==nil path)
- lease cleanup loop: server.go:1100-1163

Wire I/O is frames-in/frames-out (bytes): the engine feeds PASS-verdict
lanes here and transmits returned frames, exactly like the kernel's
XDP_PASS -> UDP socket path.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.chaos.faults import fault_point
from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.dhcp_codec import (
    ACK,
    DECLINE,
    DISCOVER,
    INFORM,
    NAK,
    OFFER,
    RELEASE,
    REQUEST,
    DHCPPacket,
)
from bng_tpu.control.pool import Pool, PoolExhaustedError, PoolManager
from bng_tpu.utils.net import mac_to_u64, u32_to_ip
from bng_tpu.utils.structlog import ErrorLog


@dataclass
class Lease:
    """Parity: the Lease built in server.go:657-705."""

    mac: bytes
    ip: int
    pool_id: int
    expiry: int
    circuit_id: bytes = b""
    remote_id: bytes = b""
    s_tag: int = 0
    c_tag: int = 0
    session_id: str = ""
    client_class: int = 0
    username: str = ""
    qos_policy: str = ""  # applied rate plan (HA failover restores it)


@dataclass
class ServerStats:
    discover: int = 0
    offer: int = 0
    request: int = 0
    ack: int = 0
    nak: int = 0
    release: int = 0
    decline: int = 0
    inform: int = 0
    auth_reject: int = 0
    expired_cleaned: int = 0
    # allocation attempts refused because every pool (or the worker's
    # slice) was exhausted — the DISCOVER stays unanswered per the
    # protocol, but the degradation is COUNTED and rate-limit logged
    # (Yuan-class hygiene), never silent
    pool_exhausted: int = 0


class DHCPServer:
    def __init__(
        self,
        server_mac: bytes,
        server_ip: int,
        pool_manager: PoolManager,
        fastpath_tables=None,  # FastPathTables | None (nil-safe)
        authenticator: Callable[..., dict | None] | None = None,  # RADIUS role
        qos_hook: Callable[[int, str], None] | None = None,  # (ip, policy)
        nat_hook: Callable[[int, int], None] | None = None,  # (ip, now)
        release_hook: Callable[[Lease], None] | None = None,
        accounting_hook: Callable[[str, Lease, str], None] | None = None,  # (event, lease, sid)
        allocator=None,  # distributed allocator (Nexus role); optional
        lease_time_cap: int | None = None,
        clock: Callable[[], float] = time.time,
        lease_jitter_frac: float = 0.0,
    ):
        self.server_mac = server_mac
        self.server_ip = server_ip
        self.pools = pool_manager
        self.tables = fastpath_tables
        self.authenticator = authenticator
        self.qos_hook = qos_hook
        self.nat_hook = nat_hook
        self.release_hook = release_hook
        self.accounting_hook = accounting_hook
        self.allocator = allocator
        self.lease_time_cap = lease_time_cap
        self.lease_jitter_frac = lease_jitter_frac
        self.clock = clock
        self.leases: dict[int, Lease] = {}  # mac_u64 -> Lease
        self.leases_by_cid: dict[bytes, int] = {}  # circuit_id -> mac_u64
        self._offers: dict[int, tuple[int, int]] = {}  # mac -> (ip, pool_id)
        self.stats = ServerStats()
        self._session_seq = 0
        # (pool_id, lease_time, include_lease) -> (options list, TLV bytes)
        self._reply_opts_cache: dict[tuple, tuple[list, bytes]] = {}
        # (msg_type, static-options key) -> ReplyTemplate: the whole
        # BOOTREPLY payload preassembled, per-client words patched in at
        # render time (dhcp_codec.ReplyTemplate) — the hot encode path
        self._reply_template_cache: dict[tuple, dhcp_codec.ReplyTemplate] = {}
        self._exhaust_log = ErrorLog(
            "dhcp-pool", "DHCP pool exhausted — DISCOVER left unanswered")

    # ------------------------------------------------------------------
    def handle_frame(self, raw: bytes) -> bytes | None:
        """Process one slow-path frame; returns a reply frame or None."""
        try:
            dec = packets.decode(raw)
            if dec.proto != 17 or dec.dst_port != 67:
                return None
            req = dhcp_codec.decode(dec.payload)
        except (ValueError, IndexError, Exception):
            return None
        if req.op != 1:
            return None
        reply = self.handle_packet(req, vlans=dec.vlans, src_mac=dec.src_mac)
        if reply is None:
            return None
        return self._frame_for_reply(req, reply, dec)

    def handle_packet(self, req: DHCPPacket, vlans: list[int] | None = None,
                      src_mac: bytes = b"") -> DHCPPacket | None:
        """Dispatch (parity: handleDHCP, server.go:302-383)."""
        t = req.msg_type
        vlans = vlans or []
        if t == DISCOVER:
            return self._discover(req, vlans)
        if t == REQUEST:
            return self._request(req, vlans)
        if t == RELEASE:
            self._release(req)
            return None
        if t == DECLINE:
            self._decline(req)
            return None
        if t == INFORM:
            return self._inform(req)
        return None

    # ------------------------------------------------------------------
    def _now(self) -> int:
        return int(self.clock())

    def _mac_key(self, req: DHCPPacket) -> int:
        return mac_to_u64(req.chaddr[:6])

    def _find_lease(self, req: DHCPPacket) -> Lease | None:
        """Lease lookup by circuit-id then MAC (server.go:386-395)."""
        cid, _ = req.option82()
        if cid:
            mk = self.leases_by_cid.get(cid)
            if mk is not None:
                return self.leases.get(mk)
        return self.leases.get(self._mac_key(req))

    def _allocate_ip(self, req: DHCPPacket, client_class: int) -> tuple[int, int] | None:
        """Allocation cascade (parity: handleDiscover, server.go:398-553):
        distributed allocator first, then local pool."""
        mac = req.chaddr[:6]
        owner = mac.hex()
        if self.allocator is not None:
            got = self.allocator.allocate(owner)
            if got is not None:
                ip = got if isinstance(got, int) else got[0]
                pool = self.pools.pool_for_ip(ip)
                if pool is not None and pool.allocate_specific(ip, owner):
                    return ip, pool.pool_id
        pool = self.pools.classify(client_class)
        if pool is None:
            return None
        try:
            return pool.allocate(owner), pool.pool_id
        except PoolExhaustedError as e:
            # DISCOVER stays unanswered (server.go:529), but the
            # degradation is counted + rate-limit logged, never silent
            self.stats.pool_exhausted += 1
            self._exhaust_log.report(e, mac=owner)
            return None

    def _discover(self, req: DHCPPacket, vlans: list[int]) -> DHCPPacket | None:
        self.stats.discover += 1
        lease = self._find_lease(req)
        if lease is not None:
            ip, pool_id = lease.ip, lease.pool_id
        else:
            mk = self._mac_key(req)
            if mk in self._offers:
                ip, pool_id = self._offers[mk]
            else:
                got = self._allocate_ip(req, client_class=0)
                if got is None:
                    return None  # exhausted: stay silent (server.go:529)
                ip, pool_id = got
                self._offers[mk] = (ip, pool_id)
        pool = self.pools.pools[pool_id]
        self.stats.offer += 1
        return self._build_reply(req, OFFER, ip, pool)

    def _request(self, req: DHCPPacket, vlans: list[int]) -> DHCPPacket | None:
        """Parity: handleRequest (server.go:556-861)."""
        self.stats.request += 1
        now = self._now()
        mk = self._mac_key(req)
        mac = req.chaddr[:6]
        requested = req.requested_ip or req.ciaddr

        # authenticate new sessions (RADIUS role, server.go:595-627)
        profile: dict = {}
        lease = self.leases.get(mk)
        if lease is None and self.authenticator is not None:
            cid, rid = req.option82()
            result = self.authenticator(mac=mac, circuit_id=cid, remote_id=rid)
            if result is None:
                self.stats.auth_reject += 1
                self.stats.nak += 1
                return self._build_nak(req)
            profile = result

        # validate/confirm the address
        if lease is not None and (requested == 0 or requested == lease.ip):
            ip, pool_id = lease.ip, lease.pool_id
        else:
            offered = self._offers.get(mk)
            if offered is not None and (requested == 0 or requested == offered[0]):
                ip, pool_id = offered
            elif requested:
                pool = self.pools.pool_for_ip(requested)
                if pool is None or not pool.allocate_specific(requested, mac.hex()):
                    self.stats.nak += 1
                    return self._build_nak(req)
                ip, pool_id = requested, pool.pool_id
            else:
                self.stats.nak += 1
                return self._build_nak(req)

        pool = self.pools.pools[pool_id]
        lease_time = profile.get("lease_time", pool.lease_time)
        if self.lease_time_cap:
            lease_time = min(lease_time, self.lease_time_cap)
        lease_time = self._jittered_lease_time(lease_time, mk)
        cid, rid = req.option82()
        existing = self.leases.get(mk)
        is_renewal = existing is not None and existing.ip == ip
        if is_renewal:
            # RFC 2131 renewal: extend the session, don't create a new one
            # (a fresh session per REQUEST would leak accounting sessions)
            lease = existing
            lease.expiry = now + lease_time
            if lease.circuit_id and lease.circuit_id != cid:
                # subscriber moved access ports: drop the stale circuit-id
                # index + fast-path row or a future port user inherits it
                self.leases_by_cid.pop(lease.circuit_id, None)
                if self.tables is not None:
                    self.tables.remove_circuit_id_subscriber(lease.circuit_id)
            lease.circuit_id, lease.remote_id = cid, rid
        else:
            if existing is not None:
                # same MAC granted a different IP: the old lease's address
                # and accounting session must be torn down, not orphaned
                old_pool = self.pools.pools.get(existing.pool_id)
                if old_pool is not None:
                    old_pool.release(existing.ip)
                if existing.circuit_id:
                    self.leases_by_cid.pop(existing.circuit_id, None)
                if self.accounting_hook is not None:
                    self.accounting_hook("stop", existing, existing.session_id)
            self._session_seq += 1
            lease = Lease(
                mac=mac, ip=ip, pool_id=pool_id, expiry=now + lease_time,
                circuit_id=cid, remote_id=rid,
                s_tag=profile.get("s_tag", 0), c_tag=profile.get("c_tag", 0),
                session_id=f"bng-{now:x}-{self._session_seq:06x}",
                username=profile.get("username", ""),
                qos_policy=profile.get("qos_policy", ""),
            )
        self.leases[mk] = lease
        if cid:
            self.leases_by_cid[cid] = mk
        self._offers.pop(mk, None)

        # fast-path cache population (server.go:708, 1057-1097)
        self._update_fastpath(lease, pool)

        # QoS + NAT wiring (server.go:774-814) — new sessions only
        if not is_renewal:
            if self.qos_hook is not None:
                self.qos_hook(ip, profile.get("qos_policy", ""))
            if self.nat_hook is not None:
                self.nat_hook(ip, now)
            if self.accounting_hook is not None:
                self.accounting_hook("start", lease, lease.session_id)
        elif self.accounting_hook is not None:
            # renewals fire their own event: no new accounting session,
            # but consumers tracking lease state (HA replication's
            # lease_expiry) must see the extension or a standby holds a
            # stale expiry forever
            self.accounting_hook("renew", lease, lease.session_id)

        self.stats.ack += 1
        return self._build_reply(req, ACK, ip, pool, lease_time=lease_time)

    def _release(self, req: DHCPPacket) -> None:
        """Full teardown (parity: handleRelease, server.go:864-983)."""
        self.stats.release += 1
        mk = self._mac_key(req)
        lease = self.leases.pop(mk, None)
        if lease is None:
            return
        if lease.circuit_id:
            self.leases_by_cid.pop(lease.circuit_id, None)
        pool = self.pools.pools.get(lease.pool_id)
        if pool is not None:
            pool.release(lease.ip)
        if self.tables is not None:
            self.tables.remove_subscriber(lease.mac)
            if lease.circuit_id:
                self.tables.remove_circuit_id_subscriber(lease.circuit_id)
            if lease.s_tag or lease.c_tag:
                self.tables.remove_vlan_subscriber(lease.s_tag, lease.c_tag)
        if self.allocator is not None:
            self.allocator.release(lease.mac.hex())
        if self.release_hook is not None:
            self.release_hook(lease)
        if self.accounting_hook is not None:
            self.accounting_hook("stop", lease, lease.session_id)

    def _decline(self, req: DHCPPacket) -> None:
        """Client detected an address conflict (server.go dispatch)."""
        self.stats.decline += 1
        ip = req.requested_ip
        if not ip:
            return
        pool = self.pools.pool_for_ip(ip)
        if pool is not None:
            pool.decline(ip)
        mk = self._mac_key(req)
        lease = self.leases.pop(mk, None)
        if lease is not None and self.tables is not None:
            self.tables.remove_subscriber(lease.mac)

    def _inform(self, req: DHCPPacket) -> DHCPPacket | None:
        self.stats.inform += 1
        pool = self.pools.pool_for_ip(req.ciaddr) if req.ciaddr else None
        if pool is None:
            pool = self.pools.classify(0)
        if pool is None:
            return None
        # ACK without yiaddr/lease time (RFC 2131 §4.3.5)
        reply = self._build_reply(req, ACK, 0, pool, include_lease=False)
        return reply

    # ------------------------------------------------------------------
    def _update_fastpath(self, lease: Lease, pool: Pool) -> None:
        """Populate device tables (parity: updateFastPathCache +
        circuit-ID maps, server.go:1057-1097, 716-771). Nil-safe."""
        if self.tables is None:
            return
        self.tables.add_subscriber(
            lease.mac, pool_id=pool.pool_id, ip=lease.ip,
            lease_expiry=lease.expiry, client_class=lease.client_class,
        )
        if lease.circuit_id:
            self.tables.add_circuit_id_subscriber(
                lease.circuit_id, pool_id=pool.pool_id, ip=lease.ip,
                lease_expiry=lease.expiry, client_class=lease.client_class,
            )
        if lease.s_tag or lease.c_tag:
            self.tables.add_vlan_subscriber(
                lease.s_tag, lease.c_tag, pool_id=pool.pool_id, ip=lease.ip,
                lease_expiry=lease.expiry, client_class=lease.client_class,
            )

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    def export_leases(self) -> dict:
        """JSON-serializable lease book for the checkpoint meta blob.
        Bytes fields go out as hex; _offers (unanswered OFFERs) are
        transient and deliberately dropped — a client mid-DORA across a
        restart just re-DISCOVERs."""
        return {
            "session_seq": self._session_seq,
            "leases": [{
                "mac": l.mac.hex(), "ip": l.ip, "pool_id": l.pool_id,
                "expiry": l.expiry, "circuit_id": l.circuit_id.hex(),
                "remote_id": l.remote_id.hex(), "s_tag": l.s_tag,
                "c_tag": l.c_tag, "session_id": l.session_id,
                "client_class": l.client_class, "username": l.username,
                "qos_policy": l.qos_policy,
            } for l in self.leases.values()],
        }

    def export_offers(self) -> list[dict]:
        """The in-flight DORA state: un-ACKed OFFERs, JSON-safe. A
        checkpoint restart deliberately drops these (export_leases — the
        client re-DISCOVERs), but a LIVE transition (fleet resize,
        rolling restart) transfers them so a client whose OFFER is
        outstanding completes its DORA against the new owner."""
        return [{"mac": f"{mk:012x}", "ip": int(ip), "pool_id": int(pid)}
                for mk, (ip, pid) in self._offers.items()]

    def restore_offers(self, entries: list[dict]) -> int:
        """Re-arm transferred OFFERs: re-claim each offered address in
        its pool under the client's owner tag (exactly what _discover's
        allocate did on the old worker) and re-index _offers so the
        client's REQUEST lands on the offered-path, not a NAK. An
        address this server's pools cannot claim (not granted here —
        e.g. a raced re-allocation) drops the offer: the client retries
        its DORA, which is the checkpoint-restart behavior."""
        restored = 0
        for o in entries:
            mk = int(o["mac"], 16)
            ip, pid = int(o["ip"]), int(o["pool_id"])
            pool = self.pools.pools.get(pid)
            if pool is None or not pool.allocate_specific(
                    ip, o["mac"].lower()):
                continue
            self._offers[mk] = (ip, pid)
            restored += 1
        return restored

    @staticmethod
    def parse_lease_state(state: dict) -> tuple[int, list["Lease"]]:
        """export_leases() output -> (session_seq, Lease list), touching
        no server state. The restore pre-check runs this before any
        mutation so a corrupt lease book rejects all-or-nothing."""
        leases = [Lease(
            mac=bytes.fromhex(d["mac"]), ip=int(d["ip"]),
            pool_id=int(d["pool_id"]), expiry=int(d["expiry"]),
            circuit_id=bytes.fromhex(d.get("circuit_id", "")),
            remote_id=bytes.fromhex(d.get("remote_id", "")),
            s_tag=int(d.get("s_tag", 0)), c_tag=int(d.get("c_tag", 0)),
            session_id=d.get("session_id", ""),
            client_class=int(d.get("client_class", 0)),
            username=d.get("username", ""),
            qos_policy=d.get("qos_policy", ""))
            for d in state.get("leases", [])]
        return int(state.get("session_seq", 0)), leases

    def restore_leases(self, state: dict) -> int:
        """Rebuild the lease book from export_leases() output: the lease
        dict, the circuit-id index, and pool occupancy (each restored IP
        is re-claimed in its pool so fresh DORAs can never double-assign
        an address a restored subscriber still holds). The fast-path
        device rows ride the table checkpoint, not this path. Returns
        the number of leases restored."""
        seq, leases = self.parse_lease_state(state)
        self._session_seq = max(self._session_seq, seq)
        for lease in leases:
            mk = mac_to_u64(lease.mac)
            self.leases[mk] = lease
            if lease.circuit_id:
                self.leases_by_cid[lease.circuit_id] = mk
            pool = self.pools.pools.get(lease.pool_id)
            if pool is not None:
                pool.allocate_specific(lease.ip, lease.mac.hex())
        return len(leases)

    # expiry-jitter quantization: per-MAC lease times land in one of
    # this many buckets spread over [lt, lt*(1+jitter_frac)], so a mass
    # bring-up cannot manufacture a synchronized expiry cliff — and the
    # reply-template cache stays bounded at BUCKETS entries per pool
    # instead of one per subscriber
    LEASE_JITTER_BUCKETS = 16

    def _jittered_lease_time(self, lt: int, mk: int) -> int:
        """Deterministic per-MAC lease-time spread. Only ever EXTENDS the
        base lease time: the client renews at T1 = lt/2 of the value it
        was told, so shortening server-side would strand renewals."""
        frac = self.lease_jitter_frac
        if frac <= 0.0 or lt <= 0:
            return lt
        step = int(lt * frac) // self.LEASE_JITTER_BUCKETS
        if step <= 0:
            return lt
        # golden-ratio multiply: cheap, deterministic, uniform enough to
        # spread consecutive MACs across all buckets
        bucket = ((mk * 0x9E3779B97F4A7C15) >> 33) \
            % self.LEASE_JITTER_BUCKETS
        return lt + bucket * step

    def cleanup_expired(self, now: int | None = None,
                        max_reaps: int | None = None) -> int:
        """Lease expiry sweep (parity: server.go:1100-1163).

        `max_reaps` bounds the teardown work of ONE sweep (pool release,
        fast-path row removal, NAT/accounting hooks are the expensive
        part, not the scan): a synchronized lease cliff then costs
        ceil(cliff/max_reaps) ticks instead of starving one dataplane
        tick for the whole cliff. Leases past the bound stay expired and
        are reaped by the next sweep; every intermediate state keeps the
        cross-authority invariants (a not-yet-reaped lease still owns
        its address everywhere)."""
        now = now if now is not None else self._now()
        fp = fault_point("dhcp.expire")
        if fp is not None and fp.kind == "skew":
            # chaos: skewed expiry clock — early expiry costs a re-DORA
            # (service), never a double allocation (consistency)
            now = int(now + fp.arg)
        dead = []
        for mk, l in self.leases.items():
            if l.expiry < now:
                dead.append(mk)
                if max_reaps is not None and len(dead) >= max_reaps:
                    break
        for mk in dead:
            lease = self.leases.pop(mk)
            if lease.circuit_id:
                self.leases_by_cid.pop(lease.circuit_id, None)
            pool = self.pools.pools.get(lease.pool_id)
            if pool is not None:
                pool.release(lease.ip)
            if self.tables is not None:
                self.tables.remove_subscriber(lease.mac)
                if lease.circuit_id:
                    self.tables.remove_circuit_id_subscriber(lease.circuit_id)
                if lease.s_tag or lease.c_tag:
                    self.tables.remove_vlan_subscriber(lease.s_tag, lease.c_tag)
            if self.allocator is not None:
                self.allocator.release(lease.mac.hex())
            if self.release_hook is not None:
                self.release_hook(lease)
            if self.accounting_hook is not None:
                self.accounting_hook("stop", lease, lease.session_id)
            self.stats.expired_cleaned += 1
        return len(dead)

    # ------------------------------------------------------------------
    def _static_reply_options(self, pool: Pool, lt: int,
                              include_lease: bool) -> tuple[list, bytes, tuple]:
        """The reply options after MSG_TYPE are a function of (pool, lease
        config) only — build once per key, cache the list AND its encoded
        TLV suffix (the slow path's hottest allocation). Returns
        (options, tlv_bytes, cache_key); the key also keys the full
        reply templates."""
        # keyed on the option-relevant VALUES, so a reconfigured pool (or a
        # future runtime server-IP change — OPT_SERVER_ID is baked into the
        # cached bytes) can never serve a stale cached suffix
        key = (pool.pool_id, lt, include_lease, pool.prefix_len,
               pool.gateway, pool.dns_primary, pool.dns_secondary,
               self.server_ip)
        hit = self._reply_opts_cache.get(key)
        if hit is not None:
            return hit[0], hit[1], key
        from bng_tpu.utils.net import prefix_to_mask

        opts = [(dhcp_codec.OPT_SERVER_ID, struct.pack("!I", self.server_ip))]
        if include_lease:
            opts.append((dhcp_codec.OPT_LEASE_TIME, struct.pack("!I", lt)))
        opts.append((dhcp_codec.OPT_SUBNET_MASK, struct.pack("!I", prefix_to_mask(pool.prefix_len))))
        opts.append((dhcp_codec.OPT_ROUTER, struct.pack("!I", pool.gateway)))
        if pool.dns_primary:
            dns = struct.pack("!I", pool.dns_primary)
            if pool.dns_secondary:
                dns += struct.pack("!I", pool.dns_secondary)
            opts.append((dhcp_codec.OPT_DNS, dns))
        if include_lease:
            opts.append((dhcp_codec.OPT_RENEWAL_TIME, struct.pack("!I", lt // 2)))
            opts.append((dhcp_codec.OPT_REBIND_TIME, struct.pack("!I", (lt * 7) // 8)))
        hit = (opts, dhcp_codec.encode_options(opts))
        # bound the cache: per-subscriber lease times (authenticator
        # profiles) could otherwise grow it without limit
        if len(self._reply_opts_cache) >= 1024:
            self._reply_opts_cache.pop(next(iter(self._reply_opts_cache)))
        self._reply_opts_cache[key] = hit
        return hit[0], hit[1], key

    def _reply_template(self, msg_type: int, pool: Pool, lt: int,
                        include_lease: bool) -> dhcp_codec.ReplyTemplate:
        static_opts, static_raw, key = self._static_reply_options(
            pool, lt, include_lease)
        tkey = (msg_type,) + key
        tmpl = self._reply_template_cache.get(tkey)
        if tmpl is not None:
            return tmpl
        mt_raw = bytes((dhcp_codec.OPT_MSG_TYPE, 1, msg_type))
        tmpl = dhcp_codec.ReplyTemplate(
            [(dhcp_codec.OPT_MSG_TYPE, bytes([msg_type]))] + static_opts,
            siaddr=self.server_ip, options_raw=mt_raw + static_raw)
        if len(self._reply_template_cache) >= 1024:
            self._reply_template_cache.pop(
                next(iter(self._reply_template_cache)))
        self._reply_template_cache[tkey] = tmpl
        return tmpl

    def _build_reply(self, req: DHCPPacket, msg_type: int, ip: int, pool: Pool,
                     lease_time: int | None = None, include_lease: bool = True) -> DHCPPacket:
        lt = lease_time if lease_time is not None else pool.lease_time
        ciaddr = req.ciaddr if msg_type == ACK else 0
        tmpl = self._reply_template(msg_type, pool, lt, include_lease)
        p = DHCPPacket(
            op=2, xid=req.xid, flags=req.flags, ciaddr=ciaddr,
            yiaddr=ip, siaddr=self.server_ip, giaddr=req.giaddr, chaddr=req.chaddr,
        )
        # fresh list, shared option tuples: the snapshot identity check
        # keeps the template render valid until a caller mutates options
        p.options = list(tmpl.options)
        p.set_encoded(tmpl.render(req.xid, req.chaddr, yiaddr=ip,
                                  flags=req.flags, ciaddr=ciaddr,
                                  giaddr=req.giaddr))
        return p

    def _build_nak(self, req: DHCPPacket) -> DHCPPacket:
        p = DHCPPacket(op=2, xid=req.xid, flags=req.flags, giaddr=req.giaddr, chaddr=req.chaddr)
        p.options.append((dhcp_codec.OPT_MSG_TYPE, bytes([NAK])))
        p.options.append((dhcp_codec.OPT_SERVER_ID, struct.pack("!I", self.server_ip)))
        return p

    def _frame_for_reply(self, req: DHCPPacket, reply: DHCPPacket,
                         dec: packets.DecodedPacket) -> bytes:
        """L2/L3 reply addressing, mirroring the fast path (c:721-756)."""
        payload = reply.encode()
        if req.giaddr:
            return packets.udp_packet(
                src_mac=self.server_mac, dst_mac=dec.src_mac,
                src_ip=self.server_ip, dst_ip=req.giaddr,
                src_port=67, dst_port=67, payload=payload, vlans=dec.vlans or None,
            )
        use_bcast = bool(req.flags & 0x8000) or req.ciaddr == 0
        dst_mac = b"\xff" * 6 if use_bcast else req.chaddr[:6]
        return packets.udp_packet(
            src_mac=self.server_mac, dst_mac=dst_mac,
            src_ip=self.server_ip, dst_ip=0xFFFFFFFF,
            src_port=67, dst_port=68, payload=payload, vlans=dec.vlans or None,
        )
