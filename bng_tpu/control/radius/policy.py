"""QoS policy registry driven by RADIUS attributes.

Parity: pkg/radius/policy.go — PolicyManager (:18), DefaultPolicies
(:100-136: residential-100mbps etc.), attribute -> policy resolution.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class QoSPolicy:
    name: str
    download_bps: int
    upload_bps: int
    priority: int = 0
    burst_factor: float = 1.25  # burst = rate/8 * factor


def _mbps(n: float) -> int:
    return int(n * 1_000_000)


DEFAULT_POLICIES = [
    QoSPolicy("residential-100mbps", _mbps(100), _mbps(20), priority=0),
    QoSPolicy("residential-500mbps", _mbps(500), _mbps(100), priority=0),
    QoSPolicy("residential-1gbps", _mbps(1000), _mbps(200), priority=0),
    QoSPolicy("business-100mbps", _mbps(100), _mbps(100), priority=2),
    QoSPolicy("business-1gbps", _mbps(1000), _mbps(1000), priority=2),
    QoSPolicy("lite-25mbps", _mbps(25), _mbps(5), priority=0),
]


class PolicyManager:
    def __init__(self, policies: list[QoSPolicy] | None = None):
        self._by_name: dict[str, QoSPolicy] = {}
        for p in policies if policies is not None else DEFAULT_POLICIES:
            self.add(p)

    def add(self, policy: QoSPolicy) -> None:
        self._by_name[policy.name] = policy

    def get(self, name: str) -> QoSPolicy | None:
        return self._by_name.get(name)

    def names(self) -> list[str]:
        return sorted(self._by_name)

    def from_radius_attributes(self, filter_id: str | None = None,
                               vendor_rate_down: int | None = None,
                               vendor_rate_up: int | None = None) -> QoSPolicy | None:
        """Resolve a policy from an Access-Accept: Filter-Id names a
        registered policy; explicit vendor rate attrs build an ad-hoc one."""
        if filter_id:
            p = self.get(filter_id.strip())
            if p is not None:
                return p
        if vendor_rate_down or vendor_rate_up:
            return QoSPolicy(
                name=f"radius-{vendor_rate_down or 0}-{vendor_rate_up or 0}",
                download_bps=vendor_rate_down or 0,
                upload_bps=vendor_rate_up or 0,
            )
        return None
