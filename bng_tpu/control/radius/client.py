"""RADIUS client: auth + accounting with multi-server failover.

Parity: pkg/radius/client.go — Client.Authenticate (:157), SendAccounting
(:250), per-server failover and rate limiting, Message-Authenticator
signing (:405). Transport is injectable (tests use an in-memory server;
production uses UDP sockets) — the reference's testability pattern.
"""

from __future__ import annotations

import socket
import time
from dataclasses import dataclass, field

from bng_tpu.control.radius import packet as rp
from bng_tpu.control.radius.packet import RadiusPacket


@dataclass
class RadiusServerConfig:
    host: str
    auth_port: int = 1812
    acct_port: int = 1813
    secret: bytes = b""
    timeout_s: float = 3.0  # parity: cmd/bng/main.go:226 (3s)
    retries: int = 3  # parity: main.go:227


@dataclass
class AuthResult:
    success: bool
    framed_ip: int = 0
    session_timeout: int = 0
    idle_timeout: int = 0
    filter_id: str = ""
    policy_name: str = ""
    reply_message: str = ""
    radius_class: bytes = b""
    attributes: dict = field(default_factory=dict)


class _UDPTransport:
    def __call__(self, data: bytes, host: str, port: int, timeout: float) -> bytes | None:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.settimeout(timeout)
            s.sendto(data, (host, port))
            resp, _ = s.recvfrom(4096)
            return resp
        except (socket.timeout, OSError):
            return None
        finally:
            s.close()


class RadiusClient:
    def __init__(
        self,
        servers: list[RadiusServerConfig],
        nas_identifier: str = "bng-tpu",
        nas_ip: int = 0,
        transport=None,  # (data, host, port, timeout) -> bytes | None
        max_requests_per_second: float = 0.0,
        clock=time.time,
    ):
        if not servers:
            raise ValueError("need at least one RADIUS server")
        self.servers = servers
        self.nas_identifier = nas_identifier
        self.nas_ip = nas_ip
        self.transport = transport or _UDPTransport()
        self.clock = clock
        self._id = 0
        self._rate = max_requests_per_second
        self._last_req = 0.0
        self.stats = {"auth_ok": 0, "auth_reject": 0, "auth_timeout": 0,
                      "acct_ok": 0, "acct_timeout": 0, "failovers": 0,
                      "rate_limited": 0}

    def _next_id(self) -> int:
        self._id = (self._id + 1) & 0xFF
        return self._id

    def _rate_limit(self) -> bool:
        """Token-ish limiter (parity: client.go per-server rate limiting)."""
        if self._rate <= 0:
            return True
        now = self.clock()
        if now - self._last_req < 1.0 / self._rate:
            self.stats["rate_limited"] += 1
            return False
        self._last_req = now
        return True

    def _exchange(self, pkt: RadiusPacket, port_of,
                  password: bytes | None = None) -> tuple[RadiusPacket, RadiusServerConfig] | None:
        """Send with per-server retry then failover (client.go:157-248).

        `password` is the plaintext PAP password: User-Password ciphering
        is per-secret (RFC 2865 §5.2), so it must be re-encrypted for
        each failover server rather than reusing servers[0]'s cipher.
        """
        for si, srv in enumerate(self.servers):
            if password is not None:
                pkt.attributes = [(t, v) for (t, v) in pkt.attributes
                                  if t != rp.USER_PASSWORD]
                pkt.add(rp.USER_PASSWORD,
                        rp.encrypt_password(password, srv.secret,
                                            pkt.authenticator))
            raw = pkt.encode(srv.secret, sign_message_authenticator=(pkt.code == rp.ACCESS_REQUEST))
            for _ in range(srv.retries):
                resp_raw = self.transport(raw, srv.host, port_of(srv), srv.timeout_s)
                if resp_raw is None:
                    continue
                try:
                    resp = RadiusPacket.decode(resp_raw)
                except ValueError:
                    continue
                if resp.id != pkt.id:
                    continue
                if not resp.verify_response(srv.secret, pkt.authenticator, resp_raw):
                    continue
                return resp, srv
            if si + 1 < len(self.servers):
                self.stats["failovers"] += 1
        return None

    def _auth_result(self, resp: RadiusPacket) -> AuthResult:
        """Access-Accept/Reject -> AuthResult (+ ok/reject stats) —
        shared by the PAP and CHAP request paths."""
        if resp.code == rp.ACCESS_ACCEPT:
            self.stats["auth_ok"] += 1
            return AuthResult(
                success=True,
                framed_ip=resp.get_int(rp.FRAMED_IP_ADDRESS) or 0,
                session_timeout=resp.get_int(rp.SESSION_TIMEOUT) or 0,
                idle_timeout=resp.get_int(rp.IDLE_TIMEOUT) or 0,
                filter_id=resp.get_str(rp.FILTER_ID) or "",
                policy_name=resp.get_str(rp.FILTER_ID) or "",
                reply_message=resp.get_str(rp.REPLY_MESSAGE) or "",
                radius_class=resp.get(rp.CLASS) or b"",
            )
        self.stats["auth_reject"] += 1
        return AuthResult(success=False,
                          reply_message=resp.get_str(rp.REPLY_MESSAGE) or "")

    # ------------------------------------------------------------------
    def authenticate(self, username: str, password: str | bytes = "",
                     mac: bytes = b"", circuit_id: bytes = b"",
                     nas_port: int = 0) -> AuthResult | None:
        """PAP Access-Request. None = timeout everywhere (parity: the
        degraded-auth trigger for resilience.RADIUSHandler). password
        accepts raw bytes: PAP passwords are arbitrary octets (RFC 1334)
        and must not round-trip through text."""
        if not self._rate_limit():
            return None
        pkt = RadiusPacket(rp.ACCESS_REQUEST, self._next_id(),
                           rp.new_request_authenticator())
        pkt.add(rp.USER_NAME, username)
        pkt.add(rp.NAS_IDENTIFIER, self.nas_identifier)
        if self.nas_ip:
            pkt.add(rp.NAS_IP_ADDRESS, self.nas_ip)
        if nas_port:
            pkt.add(rp.NAS_PORT, nas_port)
        if mac:
            pkt.add(rp.CALLING_STATION_ID, "-".join(f"{b:02X}" for b in mac))
        if circuit_id:
            pkt.add(rp.CALLED_STATION_ID, circuit_id)

        pw = password if isinstance(password, bytes) else password.encode()
        got = self._exchange(pkt, lambda s: s.auth_port, password=pw)
        if got is None:
            self.stats["auth_timeout"] += 1
            return None
        resp, _ = got
        return self._auth_result(resp)

    def authenticate_chap(self, username: str, ident: int, challenge: bytes,
                          response: bytes, mac: bytes = b"") -> AuthResult | None:
        """CHAP Access-Request (RFC 2865 §2.2): CHAP-Password carries the
        ident + the client's MD5 response; CHAP-Challenge carries the
        challenge the AC issued. The PPPoE CHAP handler delegates here
        when RADIUS is the credential backend (auth.go's radius mode).
        None = timeout everywhere (degraded-auth trigger, like PAP)."""
        if not self._rate_limit():
            return None
        pkt = RadiusPacket(rp.ACCESS_REQUEST, self._next_id(),
                           rp.new_request_authenticator())
        pkt.add(rp.USER_NAME, username)
        pkt.add(rp.NAS_IDENTIFIER, self.nas_identifier)
        if self.nas_ip:
            pkt.add(rp.NAS_IP_ADDRESS, self.nas_ip)
        if mac:
            pkt.add(rp.CALLING_STATION_ID, "-".join(f"{b:02X}" for b in mac))
        pkt.add(rp.CHAP_PASSWORD, bytes([ident & 0xFF]) + response)
        pkt.add(rp.CHAP_CHALLENGE, challenge)

        got = self._exchange(pkt, lambda s: s.auth_port)
        if got is None:
            self.stats["auth_timeout"] += 1
            return None
        resp, _ = got
        return self._auth_result(resp)

    def send_accounting(self, session_id: str, status: int, username: str = "",
                        framed_ip: int = 0, input_octets: int = 0,
                        output_octets: int = 0, input_packets: int = 0,
                        output_packets: int = 0, session_time: int = 0,
                        terminate_cause: int = 0, mac: bytes = b"") -> bool:
        """Accounting-Request (client.go:250-340)."""
        pkt = RadiusPacket(rp.ACCOUNTING_REQUEST, self._next_id())
        pkt.add(rp.ACCT_STATUS_TYPE, status)
        pkt.add(rp.ACCT_SESSION_ID, session_id)
        if username:
            pkt.add(rp.USER_NAME, username)
        pkt.add(rp.NAS_IDENTIFIER, self.nas_identifier)
        if framed_ip:
            pkt.add(rp.FRAMED_IP_ADDRESS, framed_ip)
        if mac:
            pkt.add(rp.CALLING_STATION_ID, "-".join(f"{b:02X}" for b in mac))
        if input_octets:
            pkt.add(rp.ACCT_INPUT_OCTETS, input_octets & 0xFFFFFFFF)
        if output_octets:
            pkt.add(rp.ACCT_OUTPUT_OCTETS, output_octets & 0xFFFFFFFF)
        if input_packets:
            pkt.add(rp.ACCT_INPUT_PACKETS, input_packets & 0xFFFFFFFF)
        if output_packets:
            pkt.add(rp.ACCT_OUTPUT_PACKETS, output_packets & 0xFFFFFFFF)
        if session_time:
            pkt.add(rp.ACCT_SESSION_TIME, session_time)
        if terminate_cause:
            pkt.add(rp.ACCT_TERMINATE_CAUSE, terminate_cause)
        pkt.add(rp.EVENT_TIMESTAMP, int(self.clock()))

        got = self._exchange(pkt, lambda s: s.acct_port)
        if got is None:
            self.stats["acct_timeout"] += 1
            return False
        resp, _ = got
        ok = resp.code == rp.ACCOUNTING_RESPONSE
        if ok:
            self.stats["acct_ok"] += 1
        return ok
