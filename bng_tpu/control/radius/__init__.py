from bng_tpu.control.radius.packet import RadiusPacket  # noqa: F401
from bng_tpu.control.radius.client import RadiusClient, RadiusServerConfig  # noqa: F401
from bng_tpu.control.radius.policy import PolicyManager, QoSPolicy, DEFAULT_POLICIES  # noqa: F401
from bng_tpu.control.radius.accounting import AccountingManager  # noqa: F401
from bng_tpu.control.radius.coa import CoAProcessor, CoAServer  # noqa: F401
